"""FilePV: file-backed validator key with persisted last-sign-state and
double-sign protection (reference: ``privval/file.go:75-142`` FilePVKey /
FilePVLastSignState, ``:164`` FilePV, ``:332`` signVote).

Safety argument (file.go:100 CheckHRS): the signer never signs two
different messages for the same (height, round, step).  The last sign
state — including the produced signature and the exact sign bytes — is
fsync'd to disk *before* the signature is released, so a crash between
signing and broadcasting cannot lead to equivocation after restart.  A
re-request for the identical HRS returns the stored signature; one that
differs only in timestamp returns the stored signature with the stored
timestamp; anything else is refused."""

from __future__ import annotations

import errno
import json
import os

from ..crypto.keys import (PubKey, gen_priv_key,
                           priv_key_from_type_bytes)
from ..libs import failures
from ..types.canonical import canonical_vote_sign_bytes
from ..types.priv_validator import PrivValidator
from ..types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE, Proposal, Vote

STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3

_VOTE_STEP = {PREVOTE_TYPE: STEP_PREVOTE, PRECOMMIT_TYPE: STEP_PRECOMMIT}


class DoubleSignError(Exception):
    """Refusal to sign: would conflict with the last signed state."""


class SignStateError(Exception):
    """The last-sign-state file is unreadable, incomplete, or its handle
    went dead after an IO failure.  NEVER auto-reset or delete the state
    file to clear this: the last-sign state is the only thing standing
    between a restarted validator and equivocation — resetting sign
    state is how validators double-sign.  Restore the file from a
    backup, or keep the validator offline until you can prove what this
    key last signed."""


class FilePV(PrivValidator):
    def __init__(self, priv_key, key_path: str,
                 state_path: str):
        self.priv_key = priv_key
        self.key_path = key_path
        self.state_path = state_path
        # last sign state (file.go FilePVLastSignState)
        self.height = 0
        self.round = 0
        self.step = 0
        self.signature = b""
        self.sign_bytes = b""
        self.ext_signature = b""
        # fsyncgate for the sign-state file: after one failed persist the
        # on-disk state may not reflect memory — every further sign
        # attempt must refuse (recovery is an operator restart, which
        # re-reads the file that DID land)
        self._io_failed: Exception | None = None

    # ------------------------------------------------------------- file io

    def _check_bls_backend(self) -> None:
        """Consensus-split guard (same check genesis validation runs): a
        BLS validator key may only SIGN on the non-standard bundled suite
        with the explicit closed-network opt-in.  Deliberately not in
        ``__init__``/``load`` — maintenance paths (show-validator,
        unsafe-reset-all) must keep working without the env var."""
        if self.priv_key.type() != "bls12_381":
            return
        from ..crypto import bls12381 as _bls

        err = _bls.check_validator_backend()
        if err:
            raise ValueError(err)

    @classmethod
    def generate(cls, key_path: str, state_path: str,
                 key_type: str = "ed25519") -> "FilePV":
        pv = cls(gen_priv_key(key_type), key_path, state_path)
        pv._check_bls_backend()        # refuse to CREATE an unusable key
        pv.save_key()
        pv._save_state()
        return pv

    @classmethod
    def load(cls, key_path: str, state_path: str) -> "FilePV":
        with open(key_path) as f:
            kd = json.load(f)
        pv = cls(priv_key_from_type_bytes(kd.get("type", "ed25519"),
                                          bytes.fromhex(kd["priv_key"])),
                 key_path, state_path)
        if os.path.exists(state_path):
            # a corrupt/truncated state file must be a TYPED refusal with
            # the never-auto-reset warning, not a raw JSONDecodeError an
            # operator might "fix" with unsafe-reset-all
            try:
                with open(state_path) as f:
                    sd = json.load(f)
                pv.height = int(sd["height"])
                pv.round = int(sd["round"])
                pv.step = int(sd["step"])
                pv.signature = bytes.fromhex(sd.get("signature", ""))
                pv.sign_bytes = bytes.fromhex(sd.get("signbytes", ""))
                pv.ext_signature = bytes.fromhex(sd.get("ext_signature", ""))
            except (OSError, ValueError, KeyError, TypeError) as e:
                raise SignStateError(
                    f"priv_validator state file {state_path!r} is corrupt, "
                    f"truncated, or unreadable ({e!r}).  Do NOT reset or "
                    "delete it — resetting sign state is how validators "
                    "double-sign.  Restore the file (or its permissions) "
                    "from a backup, or keep this validator offline until "
                    "you can prove what this key last signed.") from e
        return pv

    @classmethod
    def load_or_generate(cls, key_path: str, state_path: str,
                         key_type: str = "ed25519") -> "FilePV":
        if os.path.exists(key_path):
            return cls.load(key_path, state_path)
        return cls.generate(key_path, state_path, key_type)

    def save_key(self) -> None:
        pub = self.priv_key.pub_key()
        doc = {
            "address": pub.address().hex(),
            "type": pub.type(),
            "pub_key": pub.bytes().hex(),
            "priv_key": self.priv_key.bytes().hex(),
        }
        if pub.type() == "bls12_381":
            # proof of possession: the rogue-key defense the aggregate
            # fast path rests on.  Generated once at keygen, persisted
            # beside the key, published with the pubkey (genesis /
            # validator updates) and checked at admission.
            from ..crypto import bls12381 as _bls

            doc["pop"] = _bls.pop_prove(self.priv_key.bytes()).hex()
        _atomic_write_json(self.key_path, doc)

    def pop(self) -> bytes:
        """The key's proof of possession (BLS only; b"" otherwise) —
        read back from the key file when present, derived for legacy
        key files that predate the field.  An unreadable or corrupt key
        file raises: silently re-deriving would mask the same IO fault
        that load() refuses to paper over."""
        if self.priv_key.type() != "bls12_381":
            return b""
        stored = ""
        if os.path.exists(self.key_path):
            with open(self.key_path) as f:
                stored = json.load(f).get("pop", "")
        if stored:
            return bytes.fromhex(stored)
        from ..crypto import bls12381 as _bls

        return _bls.pop_prove(self.priv_key.bytes())

    def _check_alive(self) -> None:
        if self._io_failed is not None:
            raise SignStateError(
                "priv_validator sign state failed to persist earlier; "
                "refusing every further signature until restart (the "
                "on-disk state may not reflect memory)") \
                from self._io_failed

    def _save_state(self) -> None:
        """fsync'd BEFORE the signature leaves this process (file.go:332
        'signature is saved to disk before it is returned').  An IO
        failure here must NOT release the signature — the caller sees
        the raised OSError before any signature is assigned to the vote
        or proposal, and this handle goes dead (fsyncgate)."""
        self._check_alive()
        try:
            fired = failures.fire("privval.state.fsync.eio")
            if fired is not None:
                raise OSError(
                    errno.EIO, "chaos: injected privval state fsync EIO")
            _atomic_write_json(self.state_path, {
                "height": self.height,
                "round": self.round,
                "step": self.step,
                "signature": self.signature.hex(),
                "signbytes": self.sign_bytes.hex(),
                "ext_signature": self.ext_signature.hex(),
            })
        except OSError as e:
            self._io_failed = e
            raise

    # ------------------------------------------------------------- signing

    def get_pub_key(self) -> PubKey:
        return self.priv_key.pub_key()

    def _check_hrs(self, height: int, round_: int, step: int) -> bool:
        """file.go:100 CheckHRS: monotonic, returns True if same HRS."""
        if self.height > height:
            raise DoubleSignError(f"height regression {self.height}->{height}")
        if self.height == height:
            if self.round > round_:
                raise DoubleSignError(
                    f"round regression {self.round}->{round_} @ {height}")
            if self.round == round_:
                if self.step > step:
                    raise DoubleSignError(
                        f"step regression {self.step}->{step} "
                        f"@ {height}/{round_}")
                if self.step == step:
                    if not self.sign_bytes:
                        raise DoubleSignError("no sign bytes for same HRS")
                    return True
        return False

    async def sign_vote(self, chain_id: str, vote: Vote,
                        sign_extension: bool) -> None:
        self._check_alive()
        self._check_bls_backend()
        step = _VOTE_STEP[vote.type]
        same_hrs = self._check_hrs(vote.height, vote.round, step)
        # sign bytes follow the key type: a BLS validator signs the
        # zero-timestamp aggregation domain (types/vote.py
        # sign_bytes_for), so its precommits can fold into the commit's
        # aggregate.  The sign-state discipline is unchanged — the
        # stored sign_bytes are whatever was actually signed.
        sb = vote.sign_bytes_for(chain_id, self.priv_key.type())
        if same_hrs:
            if sb == self.sign_bytes:
                vote.signature = self.signature
            else:
                ts = _vote_ts_from_state(self, chain_id, vote)
                if ts is None:
                    raise DoubleSignError(
                        "conflicting vote data for same height/round/step")
                # identical modulo timestamp: reuse stored sig + timestamp
                vote.timestamp_ns = ts
                vote.signature = self.signature
            if sign_extension:
                vote.extension_signature = self.ext_signature
            return
        sig = self.priv_key.sign(sb)
        ext_sig = b""
        if sign_extension:
            ext_sig = self.priv_key.sign(vote.extension_sign_bytes(chain_id))
        self.height, self.round, self.step = vote.height, vote.round, step
        self.signature, self.sign_bytes = sig, sb
        self.ext_signature = ext_sig
        self._save_state()
        vote.signature = sig
        if sign_extension:
            vote.extension_signature = ext_sig

    async def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        self._check_alive()
        self._check_bls_backend()
        same_hrs = self._check_hrs(proposal.height, proposal.round,
                                   STEP_PROPOSE)
        sb = proposal.sign_bytes(chain_id)
        if same_hrs:
            if sb == self.sign_bytes:
                proposal.signature = self.signature
                return
            raise DoubleSignError(
                "conflicting proposal data for same height/round")
        sig = self.priv_key.sign(sb)
        self.height, self.round, self.step = (proposal.height,
                                              proposal.round, STEP_PROPOSE)
        self.signature, self.sign_bytes = sig, sb
        self.ext_signature = b""
        self._save_state()
        proposal.signature = sig


def _vote_ts_from_state(pv: FilePV, chain_id: str, vote: Vote) -> int | None:
    """If the new vote differs from the stored one ONLY by timestamp,
    return the stored timestamp (file.go checkVotesOnlyDifferByTimestamp).
    Probes by re-encoding the new vote with candidate timestamps."""
    # cheap exact check: re-encode with every plausible stored ts is not
    # possible (ts not stored separately), so compare canonical encodings
    # with the new vote's ts substituted out
    for probe_ts in _extract_ts_candidates(pv.sign_bytes):
        cand = canonical_vote_sign_bytes(
            chain_id, vote.type, vote.height, vote.round, vote.block_id,
            probe_ts)
        if cand == pv.sign_bytes:
            return probe_ts
    return None


def _extract_ts_candidates(sign_bytes: bytes):
    """Best-effort: decode the timestamp field from stored canonical vote
    bytes.  The canonical encoding is deterministic, so substituting the
    decoded ts must reproduce ``sign_bytes`` exactly for a match."""
    from ..types import canonical

    try:
        yield canonical.decode_timestamp_from_vote(sign_bytes)
    except Exception:  # bftlint: disable=EXC001 -- best-effort parse of already-persisted bytes; no candidates just means no ts-equivocation match
        return


def _atomic_write_json(path: str, obj: dict) -> None:
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
