from .file import DoubleSignError, FilePV
from .signer import RemoteSignerError, SignerClient, SignerServer

__all__ = ["FilePV", "DoubleSignError", "SignerClient", "SignerServer",
           "RemoteSignerError"]
