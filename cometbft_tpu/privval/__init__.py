from .file import DoubleSignError, FilePV, SignStateError
from .signer import (RemoteSignerError, SignerClient, SignerServer,
                     SignerTimeoutError)

__all__ = ["FilePV", "DoubleSignError", "SignStateError", "SignerClient",
           "SignerServer", "RemoteSignerError", "SignerTimeoutError"]
