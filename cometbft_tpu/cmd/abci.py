"""abci subcommand group — the reference's standalone ``abci-cli`` tool
(``abci/cmd/abci-cli/abci-cli.go``): poke any ABCI server over the socket
protocol with one-shot commands, a console REPL, or a batch script, run
the example kvstore server, and run a conformance sequence against an app.

Tx/data arguments accept the reference's literal forms: ``0xDEADBEEF`` is
hex, ``"quoted"`` is raw bytes, anything else is raw bytes too.
"""

from __future__ import annotations

import asyncio
import shlex
import sys

from ..abci import types as t


def parse_bytes(s: str) -> bytes:
    if s.startswith("0x") or s.startswith("0X"):
        return bytes.fromhex(s[2:])
    if len(s) >= 2 and s[0] == '"' and s[-1] == '"':
        return s[1:-1].encode()
    return s.encode()


def _fmt(obj) -> str:
    """Render a response dataclass compactly, hex-ing byte fields."""
    if hasattr(obj, "__dataclass_fields__"):
        parts = []
        for k in obj.__dataclass_fields__:
            v = getattr(obj, k)
            if v in (None, b"", "", 0, [], False):
                continue
            parts.append(f"{k}: {_fmt(v)}")
        return "{" + ", ".join(parts) + "}"
    if isinstance(obj, bytes):
        return "0x" + obj.hex().upper()
    if isinstance(obj, list):
        return "[" + ", ".join(_fmt(v) for v in obj) + "]"
    return str(obj)


async def run_command(client, argv: list[str]) -> str:
    """Execute one abci-cli verb against a connected client; returns the
    printable result (raises on protocol errors)."""
    cmd, *args = argv
    if cmd == "echo":
        msg = args[0] if args else ""
        res = await client.echo(msg)
        return f"-> data: {res}"
    if cmd == "info":
        res = await client.info()
        return _fmt(res)
    if cmd == "check_tx":
        res = await client.check_tx(parse_bytes(args[0]))
        return f"-> code: {res.code}" + (f" log: {res.log}" if res.log else "")
    if cmd == "commit":
        res = await client.commit()
        return f"-> retain_height: {res.retain_height}"
    if cmd == "query":
        data = parse_bytes(args[0]) if args else b""
        path = args[1] if len(args) > 1 else "/key"
        res = await client.query(path, data, 0, False)
        out = f"-> code: {res.code}"
        if res.key:
            out += f" key: {res.key.decode('utf-8', 'replace')}"
        if res.value:
            out += f" value: {res.value.decode('utf-8', 'replace')}"
        return out
    if cmd == "finalize_block":
        txs = [parse_bytes(a) for a in args]
        res = await client.finalize_block(t.FinalizeBlockRequest(
            txs=txs, height=1, time_ns=0))
        lines = [f"-> code: {r.code}" +
                 (f" log: {r.log}" if r.log else "")
                 for r in res.tx_results]
        lines.append(f"-> app_hash: 0x{res.app_hash.hex().upper()}")
        return "\n".join(lines)
    if cmd == "prepare_proposal":
        txs = [parse_bytes(a) for a in args]
        res = await client.prepare_proposal(t.PrepareProposalRequest(
            max_tx_bytes=1 << 20, txs=txs, height=1, time_ns=0))
        return "\n".join(f"-> tx: 0x{tx.hex().upper()}" for tx in res.txs) \
            or "-> (no txs)"
    if cmd == "process_proposal":
        txs = [parse_bytes(a) for a in args]
        status = await client.process_proposal(t.ProcessProposalRequest(
            txs=txs, height=1, time_ns=0))
        return ("-> status: ACCEPT"
                if status == t.PROCESS_PROPOSAL_ACCEPT
                else "-> status: REJECT")
    raise ValueError(f"unknown command {cmd!r} (try: echo info check_tx "
                     f"commit query finalize_block prepare_proposal "
                     f"process_proposal)")


async def _connect(args):
    from ..abci.client import SocketClient

    host, _, port = args.address.removeprefix("tcp://").rpartition(":")
    if not port.isdigit():
        raise ValueError(f"bad --address {args.address!r}: "
                         f"expected host:port")
    return await SocketClient.connect(host or "127.0.0.1", int(port))


def cmd_abci(args) -> int:
    sub = args.abci_command
    if sub == "kvstore":
        return _run_kvstore(args)
    if sub == "test":
        return asyncio.run(_run_test(args))
    if sub in ("console", "batch"):
        return asyncio.run(_run_repl(args, interactive=(sub == "console")))
    return asyncio.run(_run_oneshot(args))


async def _run_oneshot(args) -> int:
    client = None
    try:
        client = await _connect(args)
        print(await run_command(client, [args.abci_command] + args.args))
        return 0
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        if client is not None:
            await client.close()


async def _run_repl(args, interactive: bool) -> int:
    """console: interactive REPL; batch: same loop without prompts
    (abci-cli.go:155,178)."""
    try:
        client = await _connect(args)
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    rc = 0
    try:
        while True:
            if interactive:
                print("> ", end="", flush=True)
            line = await asyncio.get_event_loop().run_in_executor(
                None, sys.stdin.readline)
            if not line:
                break
            # posix=False keeps surrounding quotes, so parse_bytes can
            # distinguish "0xdead" (raw bytes) from 0xdead (hex)
            argv = shlex.split(line, comments=True, posix=False)
            if not argv:
                continue
            if argv[0] in ("quit", "exit"):
                break
            try:
                print(await run_command(client, argv))
            except Exception as e:
                print(f"error: {e}", file=sys.stderr)
                if not interactive:
                    rc = 1          # batch mode: first error stops the run
                    break
    finally:
        await client.close()
    return rc


def _run_kvstore(args) -> int:
    """Serve the example kvstore app over the ABCI socket protocol
    (abci-cli.go:266), or over gRPC with ``--grpc``."""
    from ..abci.kvstore import KVStoreApplication

    async def main():
        if getattr(args, "grpc", False):
            from ..abci.grpc import GRPCABCIServer

            server = GRPCABCIServer(KVStoreApplication(), port=args.port)
        else:
            from ..abci.server import ABCIServer

            server = ABCIServer(KVStoreApplication(), port=args.port)
        await server.start()
        print(f"ABCI kvstore server listening on "
              f"{server.host}:{server.port}", flush=True)
        await asyncio.Event().wait()

    try:
        asyncio.run(main())
    except KeyboardInterrupt:
        pass
    return 0


async def _run_test(args) -> int:
    """Conformance sequence against a kvstore-compatible server
    (abci-cli.go:274 runs the abci/tests suite)."""
    try:
        client = await _connect(args)
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    failures = 0

    async def check(name, got, want) -> None:
        nonlocal failures
        ok = got == want
        print(f"{'PASS' if ok else 'FAIL'} {name}: got {got!r}"
              + ("" if ok else f", want {want!r}"))
        failures += 0 if ok else 1

    try:
        await check("echo", await client.echo("hello"), "hello")
        info = await client.info()
        await check("info.last_block_height type",
                    isinstance(info.last_block_height, int), True)
        ct = await client.check_tx(b"conform=1")
        await check("check_tx valid", ct.code, 0)
        ct_bad = await client.check_tx(b"notakvtx")
        await check("check_tx invalid rejected", ct_bad.code != 0, True)
        fb = await client.finalize_block(t.FinalizeBlockRequest(
            txs=[b"conform=1"], height=info.last_block_height + 1,
            time_ns=0))
        await check("finalize_block tx code", fb.tx_results[0].code, 0)
        await check("finalize_block app_hash present",
                    len(fb.app_hash) > 0, True)
        await client.commit()
        q = await client.query("/key", b"conform", 0, False)
        await check("query committed value", q.value, b"1")
        print(f"{'OK' if failures == 0 else 'FAILED'}: "
              f"{failures} failure(s)")
        return 0 if failures == 0 else 1
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        await client.close()


def register(sub) -> None:
    """Attach the abci command group to the top-level parser."""
    sp = sub.add_parser("abci", help="poke an ABCI server "
                        "(the reference's standalone abci-cli)")
    asub = sp.add_subparsers(dest="abci_command", required=True)
    oneshots = ("echo", "info", "check_tx", "commit", "query",
                "finalize_block", "prepare_proposal", "process_proposal")
    for name in oneshots + ("console", "batch", "test"):
        ap = asub.add_parser(name)
        ap.add_argument("--address", default="127.0.0.1:26658",
                        help="ABCI server host:port")
        if name in oneshots:
            ap.add_argument("args", nargs="*")
        ap.set_defaults(fn=cmd_abci)
    ap = asub.add_parser("kvstore", help="run the example kvstore app "
                         "as an ABCI socket (or --grpc) server")
    ap.add_argument("--port", type=int, default=26658)
    ap.add_argument("--grpc", action="store_true",
                    help="serve over gRPC instead of the socket protocol")
    ap.set_defaults(fn=cmd_abci)
