"""Command-line interface (reference: ``cmd/cometbft/main.go:16-46`` and
``cmd/cometbft/commands/``): init, start, testnet, key tooling, reset and
rollback — argparse instead of cobra, same command surface.

Run as ``python -m cometbft_tpu <command>``."""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import signal
import sys

VERSION = "0.2.0"        # framework version (version/version.go analogue)


# ------------------------------------------------------------ home layout

def _cfg_path(home: str) -> str:
    return os.path.join(home, "config", "config.toml")


def _load_home(home: str):
    from ..config import Config

    cfg = Config.load(_cfg_path(home))
    cfg.base.root_dir = home
    return cfg


def _apply_overrides(cfg, options: list[str]) -> None:
    """--option section.key=value config overrides (the reference binds a
    cobra flag per config field; one generic repeatable flag covers the
    same surface).  Values coerce to the field's current type; raises
    ConfigError on unknown keys or bad values."""
    import dataclasses

    from ..config import ConfigError

    sections = {f.name for f in dataclasses.fields(cfg)}
    for opt in options:
        path, sep, raw = opt.partition("=")
        section_name, dot, key = path.strip().partition(".")
        if not sep or not dot or not key:
            raise ConfigError(f"bad --option {opt!r}: expected "
                              f"section.key=value")
        if section_name not in sections:
            raise ConfigError(f"unknown config key {path!r}")
        section = getattr(cfg, section_name)
        field_types = {f.name: f.type for f in dataclasses.fields(section)}
        if key not in field_types:
            raise ConfigError(f"unknown config key {path!r}")
        # coerce by the declared field type, not the runtime value (a
        # hand-edited TOML int in a float field must not flip the rule)
        ftype = str(field_types[key])
        try:
            if ftype == "bool":
                if raw.lower() not in ("true", "false", "1", "0"):
                    raise ValueError("expected true|false")
                value = raw.lower() in ("true", "1")
            elif ftype == "int":
                value = int(raw)
            elif ftype == "float":
                value = float(raw)
            elif ftype.startswith("list"):
                value = [s.strip() for s in raw.split(",") if s.strip()]
            else:
                value = raw
        except ValueError as e:
            raise ConfigError(f"bad value for {path!r}: {e}") from e
        setattr(section, key, value)
    if options:
        cfg.validate()


def _join(home: str, rel: str) -> str:
    return rel if os.path.isabs(rel) else os.path.join(home, rel)


def _warn_slow_bls(key_type: str) -> None:
    """bls12_381 on the bundled pure-Python backend costs seconds per
    verify — fine for tooling, ruinous on the consensus hot path."""
    if key_type != "bls12_381":
        return
    from ..crypto import bls12381 as _bls

    if type(_bls._BACKEND).__name__ == "_PurePyBackend":
        print("WARNING: bls12_381 is served by the bundled pure-Python "
              "backend (seconds per verify). A validator with this key "
              "type will blow consensus timeouts; install py_ecc or "
              "blspy for a production-grade backend.", file=sys.stderr)


# ---------------------------------------------------------------- commands

def cmd_init(args) -> int:
    """commands/init.go InitFilesCmd: config + genesis + keys."""
    from ..config import Config
    from ..p2p import NodeKey
    from ..privval import FilePV
    from ..types.genesis import GenesisDoc, GenesisValidator

    home = args.home
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)

    cfg = Config()
    cfg.base.moniker = args.moniker
    if not os.path.exists(_cfg_path(home)):
        cfg.save(_cfg_path(home))

    nk = NodeKey.load_or_gen(_join(home, cfg.base.node_key_file))
    _warn_slow_bls(getattr(args, "key_type", "ed25519"))
    pv = FilePV.load_or_generate(
        _join(home, cfg.base.priv_validator_key_file),
        _join(home, cfg.base.priv_validator_state_file),
        key_type=getattr(args, "key_type", "ed25519"))

    gen_path = _join(home, cfg.base.genesis_file)
    if not os.path.exists(gen_path):
        import time

        doc = GenesisDoc(
            chain_id=args.chain_id or f"test-chain-{nk.id[:6]}",
            genesis_time_ns=time.time_ns(),
            validators=[GenesisValidator(pv.get_pub_key(), 10,
                                         cfg.base.moniker, pop=pv.pop())])
        doc.save(gen_path)
    print(f"Initialized node in {home} (node id {nk.id})")
    return 0


def cmd_start(args) -> int:
    """commands/run_node.go: assemble and run the node."""
    return asyncio.run(_start_async(args))


async def _start_async(args) -> int:
    from ..abci.kvstore import KVStoreApplication
    from ..node import Node
    from ..p2p import NodeKey
    from ..privval import FilePV
    from ..types.genesis import GenesisDoc

    home = args.home
    cfg = _load_home(home)
    try:
        _apply_overrides(cfg, getattr(args, "option", []))
    except Exception as e:
        print(f"{e}", file=sys.stderr)
        return 1
    doc = GenesisDoc.load(_join(home, cfg.base.genesis_file))
    nk = NodeKey.load_or_gen(_join(home, cfg.base.node_key_file))
    signer_listener = None
    if cfg.base.priv_validator_laddr:
        # node listens; the remote signer process dials in
        # (privval/signer_listener_endpoint.go)
        from ..privval.signer import SignerListener

        lhost, _, lport = (cfg.base.priv_validator_laddr
                           .removeprefix("tcp://").rpartition(":"))
        if not lport.isdigit():
            print(f"bad priv_validator_laddr "
                  f"{cfg.base.priv_validator_laddr!r}: expected host:port",
                  file=sys.stderr)
            return 1
        from ..privval.signer import RemoteSignerError

        signer_listener = SignerListener(
            timeout_s=cfg.base.priv_validator_timeout_s)
        await signer_listener.listen(lhost or "127.0.0.1", int(lport))
        print(f"Waiting for remote signer on "
              f"{cfg.base.priv_validator_laddr} ...")
        try:
            await signer_listener.wait_for_signer(timeout=120.0)
        except RemoteSignerError as e:
            print(str(e), file=sys.stderr)
            return 1
        # the listener itself is the PrivValidator: it re-accepts the
        # signer's redial if the connection drops
        pv = signer_listener
    else:
        pv = FilePV.load_or_generate(
            _join(home, cfg.base.priv_validator_key_file),
            _join(home, cfg.base.priv_validator_state_file))

    app = None
    if cfg.base.abci == "builtin":
        if cfg.base.proxy_app not in ("kvstore", ""):
            print(f"unknown builtin app {cfg.base.proxy_app!r}",
                  file=sys.stderr)
            return 1
        app = KVStoreApplication()

    state_sync_provider = None
    if cfg.statesync.enable:
        # config-driven snapshot bootstrap (statesync.rpc_servers +
        # trust anchor -> light-client-verified state provider;
        # node/setup.go's stateProvider wiring)
        from ..light import Client, TrustOptions
        from ..light.rpc_provider import RPCProvider
        from ..statesync import StateProvider

        servers = [s.strip() for s in cfg.statesync.rpc_servers
                   if s.strip()]
        if (not servers or cfg.statesync.trust_height <= 0
                or not cfg.statesync.trust_hash):
            print("statesync.enable requires rpc_servers, trust_height "
                  "> 0, and trust_hash", file=sys.stderr)
            return 1
        try:
            trust_hash = bytes.fromhex(cfg.statesync.trust_hash)
        except ValueError:
            print(f"bad statesync.trust_hash "
                  f"{cfg.statesync.trust_hash!r}: expected hex",
                  file=sys.stderr)
            return 1

        providers = []
        for i, srv in enumerate(servers):
            try:
                h, pt, tls, _verify = _parse_rpc_addr(srv)
            except ValueError as e:
                print(f"statesync.rpc_servers: {e}", file=sys.stderr)
                raise SystemExit(1) from e
            providers.append(RPCProvider(h, pt, f"ss{i}", tls=tls))
        light = Client(
            doc.chain_id,
            TrustOptions(cfg.statesync.trust_period,
                         cfg.statesync.trust_height,
                         trust_hash),
            providers[0], witnesses=providers[1:],
            backend=cfg.base.signature_backend)
        state_sync_provider = StateProvider(light, doc)

    node = await Node.create(doc, app, priv_validator=pv, config=cfg,
                             node_key=nk, home=home,
                             fast_sync=cfg.blocksync.enable,
                             state_sync_provider=state_sync_provider,
                             name=cfg.base.moniker)
    await node.start()
    print(f"Node {nk.id} started: p2p {node.listen_addr}, "
          f"rpc {node.rpc_addr}", flush=True)

    async def dial_with_retry(addr: str) -> None:
        # peers boot in any order: keep trying (switch.go persistent-peer
        # reconnect semantics for the initial dial)
        delay = 0.5
        for _ in range(30):
            try:
                await node.dial_peer(addr, persistent=True)
                return
            except Exception as e:
                if "duplicate peer" in str(e):
                    return          # they dialed us first
                await asyncio.sleep(delay)
                delay = min(delay * 1.5, 5.0)
        print(f"giving up dialing {addr}", file=sys.stderr)

    dial_tasks = [asyncio.create_task(dial_with_retry(a.strip()))
                  for a in cfg.p2p.persistent_peers.split(",") if a.strip()]

    async def dial_seed(addr: str) -> None:
        # seeds bootstrap the address book; discovery continues via PEX
        # (p2p/pex reactor ensure-peers), so one successful exchange is
        # enough — no persistence
        delay = 0.5
        for _ in range(30):
            try:
                await node.dial_peer(addr, persistent=False)
                return
            except Exception as e:
                if "duplicate peer" in str(e):
                    return
                await asyncio.sleep(delay)
                delay = min(delay * 1.5, 5.0)

    dial_tasks += [asyncio.create_task(dial_seed(a.strip()))
                   for a in cfg.p2p.seeds.split(",") if a.strip()]

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)

    # goroutine-dump analogue (cmd/cometbft/commands/debug captures
    # goroutine stacks): SIGUSR1 -> native thread stacks, SIGUSR2 ->
    # asyncio task summaries, both to stderr without stopping the node
    import faulthandler

    faulthandler.register(signal.SIGUSR1, all_threads=True)

    def _dump_tasks() -> None:
        tasks = asyncio.all_tasks(loop)
        print(f"=== {len(tasks)} asyncio tasks ===", file=sys.stderr)
        for t in sorted(tasks, key=lambda t: t.get_name()):
            frames = t.get_stack()
            where = ""
            if frames:
                f = frames[-1]
                where = f" at {f.f_code.co_filename}:{f.f_lineno} " \
                        f"({f.f_code.co_name})"
            print(f"--- {t.get_name()}{where}", file=sys.stderr)
        sys.stderr.flush()

    loop.add_signal_handler(signal.SIGUSR2, _dump_tasks)
    await stop.wait()
    print("shutting down...", flush=True)
    for t in dial_tasks:
        t.cancel()
    await node.stop()
    if signer_listener is not None:
        await signer_listener.close()
    return 0


def cmd_testnet(args) -> int:
    """commands/testnet.go: N wired node homes under one directory."""
    from ..e2e.gen import HomeSpec, generate_homes

    n = args.v
    specs = [HomeSpec(name=f"node{i}",
                      p2p_port=args.base_port + 2 * i,
                      rpc_port=args.base_port + 2 * i + 1,
                      power=10)
             for i in range(n)]
    generate_homes(args.output_dir, specs,
                   args.chain_id or "testnet")
    print(f"Generated {n}-node testnet in {args.output_dir} "
          f"(ports {args.base_port}..{args.base_port + 2 * n - 1})")
    return 0


def cmd_gen_validator(args) -> int:
    from ..crypto.keys import gen_priv_key

    sk = gen_priv_key(getattr(args, "key_type", "ed25519"))
    _warn_slow_bls(getattr(args, "key_type", "ed25519"))
    print(json.dumps({
        "address": sk.pub_key().address().hex(),
        "type": sk.pub_key().type(),
        "pub_key": sk.pub_key().bytes().hex(),
        "priv_key": sk.bytes().hex()}, indent=2))
    return 0


def cmd_gen_node_key(args) -> int:
    from ..p2p import NodeKey

    path = os.path.join(args.home, "config", "node_key.json")
    nk = NodeKey.load_or_gen(path)
    print(nk.id)
    return 0


def cmd_show_node_id(args) -> int:
    cfg = _load_home(args.home)
    from ..p2p import NodeKey

    nk = NodeKey.load(_join(args.home, cfg.base.node_key_file))
    print(nk.id)
    return 0


def cmd_show_validator(args) -> int:
    cfg = _load_home(args.home)
    from ..privval import FilePV

    pv = FilePV.load(_join(args.home, cfg.base.priv_validator_key_file),
                     _join(args.home, cfg.base.priv_validator_state_file))
    pub = pv.get_pub_key()
    print(json.dumps({"type": pub.type(), "value": pub.bytes().hex()}))
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """commands/reset.go: wipe data, keep keys; reset signer state."""
    home = args.home
    data = os.path.join(home, "data")
    if os.path.isdir(data):
        _lock_data_dir(home)      # refuse to rmtree under a running node
        shutil.rmtree(data)
    os.makedirs(data, exist_ok=True)
    cfg = _load_home(home)
    state_file = _join(home, cfg.base.priv_validator_state_file)
    key_file = _join(home, cfg.base.priv_validator_key_file)
    if os.path.exists(key_file):
        from ..privval import FilePV, SignStateError

        try:
            pv = FilePV.load(key_file, state_file)
        except SignStateError:
            # the operator EXPLICITLY asked for the reset: a corrupt
            # state file must not block the one command whose job is
            # resetting it (elsewhere that error is a hard refusal)
            print(f"WARNING: discarding corrupt sign state {state_file}",
                  file=sys.stderr)
            os.unlink(state_file)
            pv = FilePV.load(key_file, state_file)
        pv.height = pv.round = pv.step = 0
        pv.signature = pv.sign_bytes = pv.ext_signature = b""
        pv._save_state()
    print(f"Reset {data} (node + validator keys kept)")
    return 0


def cmd_rollback(args) -> int:
    """commands/rollback.go: undo the latest state transition."""
    from ..storage import BlockStore, StateStore, open_db
    from ..storage.statestore import rollback_state

    home = args.home
    cfg = _load_home(home)
    _lock_data_dir(home)
    bs_db = open_db(cfg.storage.db_backend,
                    os.path.join(home, "data", "blockstore.db"))
    ss_db = open_db(cfg.storage.db_backend,
                    os.path.join(home, "data", "state.db"))
    try:
        new_state = rollback_state(StateStore(ss_db), BlockStore(bs_db),
                                   remove_block=args.hard)
    except Exception as e:
        print(f"rollback failed: {e}", file=sys.stderr)
        return 1
    print(f"Rolled back state to height {new_state.last_block_height} "
          f"app_hash {new_state.app_hash.hex()}")
    return 0


def _parse_rpc_addr(addr: str) -> tuple[str, int, bool, bool]:
    """[scheme://]host:port -> (host, port, tls, tls_verify).  Schemes:
    http / tcp / bare (plaintext), https (TLS, verified — the reference
    client's default), https+insecure (TLS, accept self-signed).  Raises
    ValueError naming the ORIGINAL string on a missing port."""
    orig = addr
    tls = verify = False
    if addr.startswith("https+insecure://"):
        tls, verify = True, False
        addr = addr.removeprefix("https+insecure://")
    elif addr.startswith("https://"):
        tls = verify = True
        addr = addr.removeprefix("https://")
    else:
        addr = addr.removeprefix("http://").removeprefix("tcp://")
    host, _, port = addr.rpartition(":")
    if not port.isdigit():
        raise ValueError(f"bad address {orig!r}: expected "
                         "[scheme://]host:port")
    return host or "127.0.0.1", int(port), tls, verify


def _rpc_client(addr: str):
    """addr per _parse_rpc_addr; https verifies certificates, the
    https+insecure scheme accepts a node's self-signed cert."""
    from ..rpc.client import HTTPClient

    host, port, tls, verify = _parse_rpc_addr(addr)
    return HTTPClient(host, port, tls=tls, tls_verify=verify)


def _lock_data_dir(home: str):
    """Exclusive lock for offline tooling — refuses while a node runs on
    this home (a live LogDB must never be reopened/compacted under it)."""
    from ..storage.db import DataDirLock

    try:
        return DataDirLock(os.path.join(home, "data"))
    except RuntimeError as e:
        print(e, file=sys.stderr)
        raise SystemExit(1) from None


def cmd_load(args) -> int:
    """test/loadtime generator: timestamped txs at a fixed rate."""
    from .. import loadtime

    async def go():
        client = _rpc_client(args.rpc)
        out = await loadtime.generate(client, args.rate, args.duration,
                                      tx_size=args.size,
                                      connections=args.connections)
        print(json.dumps(out))

    asyncio.run(go())
    return 0


def cmd_load_report(args) -> int:
    """test/loadtime/report: per-tx latency from committed chain data."""
    from .. import loadtime

    async def go():
        client = _rpc_client(args.rpc)
        out = await loadtime.report(client, run_id=args.run_id)
        print(json.dumps(out))

    asyncio.run(go())
    return 0


def cmd_reindex_event(args) -> int:
    """commands/reindex_event.go: rebuild the tx + block indexes offline
    from the block store and the saved FinalizeBlock responses."""
    from ..indexer.block import BlockIndexer
    from ..indexer.tx import TxIndexer
    from ..mempool.mempool import TxKey
    from ..sm.execution import unpack_finalize_response
    from ..storage import BlockStore, StateStore, open_db
    from ..types import events as ev

    home = args.home
    cfg = _load_home(home)
    _lock_data_dir(home)

    def data_db(name):
        return open_db(cfg.storage.db_backend,
                       os.path.join(home, "data", name))

    bs = BlockStore(data_db("blockstore.db"))
    ss = StateStore(data_db("state.db"))
    tx_ix = TxIndexer(data_db("tx_index.db"))
    blk_ix = BlockIndexer(data_db("block_index.db"))

    start = args.start_height or bs.base()
    end = args.end_height or bs.height()
    if start < bs.base() or end > bs.height() or start > end:
        print(f"height range [{start},{end}] outside stored "
              f"[{bs.base()},{bs.height()}]", file=sys.stderr)
        return 1
    done = 0
    for h in range(start, end + 1):
        block = bs.load_block(h)
        raw = ss.load_finalize_block_response(h)
        if block is None or raw is None:
            print(f"skipping height {h}: "
                  f"{'no block' if block is None else 'no ABCI response'}",
                  file=sys.stderr)
            continue
        resp = unpack_finalize_response(raw)
        blk_ix.index(h, resp.events)
        for i, tx in enumerate(block.data.txs):
            tx = bytes(tx)
            res = resp.tx_results[i] if i < len(resp.tx_results) else None
            if res is None:
                continue
            tx_ix.index(h, i, tx, res,
                        {ev.TX_HASH_KEY: TxKey(tx).hex(),
                         ev.TX_HEIGHT_KEY: str(h)})
        done += 1
    print(f"Reindexed {done} blocks [{start},{end}]")
    return 0


def cmd_compact_db(args) -> int:
    """commands/compact.go analogue: force-compact the data-dir stores
    (LogDB rewrites live records; other backends no-op)."""
    cfg = _load_home(args.home)
    _lock_data_dir(args.home)
    from ..storage import open_db

    total = 0
    for name in ("blockstore.db", "state.db", "evidence.db",
                 "tx_index.db", "block_index.db"):
        path = os.path.join(args.home, "data", name)
        if not os.path.exists(path):
            continue
        before = os.path.getsize(path) if os.path.isfile(path) else 0
        db = open_db(cfg.storage.db_backend, path)
        compact = getattr(db, "_compact", None) or getattr(
            db, "compact", None)
        if compact is not None:
            compact()
        db.close()
        after = os.path.getsize(path) if os.path.isfile(path) else 0
        total += max(0, before - after)
        print(f"{name}: {before} -> {after} bytes")
    print(f"Reclaimed {total} bytes")
    return 0


def cmd_doctor(args) -> int:
    """Offline storage integrity doctor (node/doctor.py): the boot
    cross-store consistency check plus an unconditional deep hash-chain
    scan over the data dir, report-only by default, repairing with
    ``--repair``.  Exit 0 when healthy (or fully repaired), 1 when
    problems remain."""
    from ..node.doctor import StorageDoctor
    from ..storage import BlockStore, StateStore, open_db

    home = args.home
    cfg = _load_home(home)
    lock = _lock_data_dir(home)
    bs = BlockStore(open_db(cfg.storage.db_backend,
                            os.path.join(home, "data", "blockstore.db")))
    ss = StateStore(open_db(cfg.storage.db_backend,
                            os.path.join(home, "data", "state.db")))
    try:
        doctor = StorageDoctor(
            bs, ss,
            wal_path=_join(home, cfg.consensus.wal_path),
            privval_state_path=_join(home,
                                     cfg.base.priv_validator_state_file),
            deep_scan_window=cfg.storage.doctor_deep_scan_window)
        # the offline tool always walks the chain (force_deep):
        # boot_check sequences it before the WAL-lineage check so a
        # truncating repair is immediately followed by the matching WAL
        # quarantine
        report = doctor.boot_check(repair=args.repair,
                                   raise_on_refusal=False,
                                   force_deep=True,
                                   deep_window=args.window)
        if args.repair and report.refused is None and \
                report.deep_scan is not None and report.deep_scan.get("ok"):
            bs.clear_dirty()
            fn = getattr(ss.db, "clear_dirty", None)
            if fn is not None:
                fn()
        if report.refused is None and report.deep_scan is not None and \
                not report.deep_scan.get("ok"):
            report.ok = False
    finally:
        bs.db.close()
        ss.db.close()
        lock.release()
    print(json.dumps(report.to_dict(), indent=2))
    return 0 if report.ok else 1


def cmd_e2e_gen(args) -> int:
    """test/e2e/generator analogue: emit deterministic random manifests;
    each failure reproduces from its seed alone."""
    from ..e2e.generator import generate_manifest
    from ..e2e.manifest import manifest_to_toml

    os.makedirs(args.output_dir, exist_ok=True)
    for seed in range(args.seed, args.seed + args.runs):
        m = generate_manifest(seed, compact=args.compact)
        path = _join(args.output_dir, f"gen-{seed:05d}.toml")
        with open(path, "w") as f:
            f.write(manifest_to_toml(m))
        print(f"{path}: {len(m.nodes)} nodes, final_height "
              f"{m.final_height}")
    return 0


def cmd_e2e(args) -> int:
    """test/e2e/runner analogue: run a manifest-described testnet of OS
    processes, apply its perturbation schedule, check invariants."""
    from ..e2e import Runner, RunnerError, load_manifest

    if not args.manifest:
        print("--manifest is required (generate one with e2e-gen)",
              file=sys.stderr)
        return 1
    try:
        manifest = load_manifest(args.manifest)
    except Exception as e:
        print(f"bad manifest: {e}", file=sys.stderr)
        return 1
    runner = Runner(manifest, args.dir, base_port=args.base_port)
    runner.setup()
    try:
        report = asyncio.run(runner.run(deadline_s=args.deadline))
        print(json.dumps(report, indent=2))
        return 0
    except RunnerError as e:
        print(f"e2e FAILED: {e}", file=sys.stderr)
        return 1
    finally:
        runner.stop()


def cmd_debug_wal(args) -> int:
    """scripts/wal2json analogue: dump consensus WAL records as JSON
    lines.  Strictly read-only — safe on a crashed node's torn WAL (the
    node's own open would truncate the torn tail; this never writes)."""
    import json as _json

    cfg = _load_home(args.home)
    from ..consensus.wal import WALError, iter_wal_records_readonly
    from ..rpc.json import _hexify

    n = 0
    try:
        for rec in iter_wal_records_readonly(
                _join(args.home, cfg.consensus.wal_path)):
            print(_json.dumps(_hexify(rec)))
            n += 1
    except WALError as e:
        print(f"# {n} records", file=sys.stderr)
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"# {n} records", file=sys.stderr)
    return 0


def _debug_collect(rpc: str, home: str, out_dir: str) -> None:
    """Shared capture core for ``debug dump``/``debug kill``: node
    introspection over RPC when the node is up, plus config and WAL/data
    listings from the home directory."""
    os.makedirs(out_dir, exist_ok=True)

    async def fetch_rpc():
        client = _rpc_client(rpc)
        for route in ("status", "net_info", "consensus_state",
                      "dump_consensus_state", "num_unconfirmed_txs",
                      "dump_incidents"):
            try:
                out = await asyncio.wait_for(client.call(route), 5)
                with open(os.path.join(out_dir, f"{route}.json"), "w") as f:
                    json.dump(out, f, indent=2, default=str)
            except Exception as e:
                with open(os.path.join(out_dir, f"{route}.err"), "w") as f:
                    f.write(repr(e))

    asyncio.run(fetch_rpc())

    if os.path.isdir(home):
        cfgp = _cfg_path(home)
        if os.path.exists(cfgp):
            shutil.copy(cfgp, os.path.join(out_dir, "config.toml"))
        listing = []
        for root, _dirs, files in os.walk(os.path.join(home, "data")):
            for fn in files:
                p = os.path.join(root, fn)
                listing.append(f"{os.path.getsize(p):>12} {p}")
        with open(os.path.join(out_dir, "data_listing.txt"), "w") as f:
            f.write("\n".join(listing))
        wal_dir = os.path.join(home, "data", "cs.wal")
        wal_file = wal_dir if os.path.isfile(wal_dir) else None
        if os.path.isdir(wal_dir):
            segs = sorted(os.listdir(wal_dir))
            if segs:
                wal_file = os.path.join(wal_dir, segs[-1])
        if wal_file and os.path.isfile(wal_file):
            shutil.copy(wal_file, os.path.join(out_dir, "wal_tail.bin"))


def _debug_tar(out_dir: str, tar_path: str | None = None) -> str:
    import tarfile

    tar_path = tar_path or out_dir.rstrip("/") + ".tar.gz"
    with tarfile.open(tar_path, "w:gz") as tar:
        tar.add(out_dir, arcname=os.path.basename(
            out_dir.rstrip("/")) or "debug")
    return tar_path


def cmd_debug_dump(args) -> int:
    """commands/debug: capture a post-mortem bundle — node introspection
    over RPC when the node is up, plus config and WAL/data listings."""
    import time as _time

    out_dir = args.output_dir or f"debug-dump-{int(_time.time())}"
    _debug_collect(args.rpc, args.home, out_dir)
    print(f"Debug bundle written to {_debug_tar(out_dir)}")
    return 0


def cmd_debug_kill(args) -> int:
    """commands/debug/kill.go: aggregate a RUNNING node's state — RPC
    dumps, config, WAL tail, /proc process state — trigger its in-process
    stack dumps (SIGUSR1 thread stacks + SIGUSR2 asyncio tasks, the
    goroutine-dump analogue, written to the node's stderr), terminate it,
    and package everything into one archive."""
    import signal as _signal
    import tempfile
    import time as _time

    pid = args.pid
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        print(f"no such process: {pid}", file=sys.stderr)
        return 1
    except PermissionError:
        print(f"not permitted to signal pid {pid}", file=sys.stderr)
        return 1

    out_dir = tempfile.mkdtemp(prefix="cometbft-debug-kill-")
    # 1. live node state over RPC + home files (while it still answers)
    _debug_collect(args.rpc, args.home, out_dir)

    # 2. kernel-side process state — capturable from OUTSIDE the process
    proc_info = []
    for name in ("cmdline", "status", "wchan", "io", "limits"):
        try:
            with open(f"/proc/{pid}/{name}", "rb") as f:
                data = f.read().replace(b"\x00", b" ")
            proc_info.append(f"--- /proc/{pid}/{name}\n"
                             + data.decode(errors="replace"))
        except OSError as e:
            proc_info.append(f"--- /proc/{pid}/{name}: {e!r}")
    try:
        tids = os.listdir(f"/proc/{pid}/task")
        proc_info.append(f"--- threads: {len(tids)}")
        fds = os.listdir(f"/proc/{pid}/fd")
        proc_info.append(f"--- open fds: {len(fds)}")
    except OSError:
        pass
    with open(os.path.join(out_dir, "proc_state.txt"), "w") as f:
        f.write("\n".join(proc_info))

    # 3. ask the node to dump its own stacks to ITS stderr/log, then
    #    stop it (SIGTERM is the graceful path; SIGKILL after a grace
    #    period so a wedged node still dies, like kill.go's guarantee)
    for sig in (_signal.SIGUSR1, _signal.SIGUSR2):
        try:
            os.kill(pid, sig)
        except OSError:
            pass
    _time.sleep(1.0)         # give the handlers a beat to write
    try:
        os.kill(pid, _signal.SIGTERM)
    except OSError:
        pass
    def _gone() -> bool:
        # os.kill(pid, 0) stays happy on a ZOMBIE (exited but unreaped
        # under a supervisor), which would burn the whole grace period
        # and misreport SIGKILL — read the state from /proc instead
        try:
            with open(f"/proc/{pid}/stat") as f:
                return f.read().rsplit(")", 1)[1].split()[0] == "Z"
        except OSError:
            return True

    deadline = _time.monotonic() + 10.0
    killed = False
    while _time.monotonic() < deadline:
        if _gone():
            killed = True
            break
        _time.sleep(0.2)
    if not killed:
        try:
            os.kill(pid, _signal.SIGKILL)
        except OSError:
            pass
    with open(os.path.join(out_dir, "kill.txt"), "w") as f:
        f.write(f"pid {pid} terminated "
                f"({'SIGTERM' if killed else 'SIGKILL after timeout'}); "
                "stack dumps (SIGUSR1/2) went to the node's own stderr "
                "log\n")

    tar_path = _debug_tar(out_dir, args.output_file)
    # the staging dir duplicates config/WAL/state uncompressed in /tmp:
    # never leave it behind (debug dump's out_dir is user-chosen and
    # visible; this one is not)
    shutil.rmtree(out_dir, ignore_errors=True)
    print(f"Debug bundle written to {tar_path}")
    return 0


def cmd_signer(args) -> int:
    """Remote signer daemon: load this home's FilePV and dial the node's
    priv_validator_laddr, serving sign requests over the connection
    (privval/signer_dialer_endpoint.go + signer_server.go)."""
    from ..privval import FilePV
    from ..privval.signer import serve_dialer

    cfg = _load_home(args.home)
    pv = FilePV.load_or_generate(
        _join(args.home, cfg.base.priv_validator_key_file),
        _join(args.home, cfg.base.priv_validator_state_file))
    host, _, port = args.address.removeprefix("tcp://").rpartition(":")
    if not port.isdigit():
        print(f"bad --address {args.address!r}: expected host:port",
              file=sys.stderr)
        return 1
    print(f"Serving validator {pv.get_pub_key().address().hex()} to "
          f"{args.address}", flush=True)

    async def main():
        await serve_dialer(pv, host or "127.0.0.1", int(port),
                           max_retries=args.max_retries)

    try:
        asyncio.run(main())
    except KeyboardInterrupt:
        pass
    return 0


def cmd_inspect(args) -> int:
    """commands/inspect.go: read-only RPC over a crashed node's data dir."""
    return asyncio.run(_inspect_async(args))


async def _inspect_async(args) -> int:
    from ..rpc.inspect import run_inspect
    from ..types.genesis import GenesisDoc

    home = args.home
    cfg = _load_home(home)
    _lock_data_dir(home)
    doc = GenesisDoc.load(_join(home, cfg.base.genesis_file))
    host, port = "127.0.0.1", args.port
    server, addr = await run_inspect(home, cfg, doc, host, port)
    print(f"Inspect server on {addr[0]}:{addr[1]} (read-only; ctrl-c to "
          "stop)", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await server.close()
    return 0


def cmd_light(args) -> int:
    """commands/light.go: light-client proxy daemon."""
    return asyncio.run(_light_async(args))


async def _light_async(args) -> int:
    from ..light import Client, TrustOptions
    from ..light.proxy import run_light_proxy
    from ..light.rpc_provider import RPCProvider
    from ..rpc.client import HTTPClient

    def parse_hp(s: str) -> tuple[str, int, bool]:
        try:
            host, port, tls, _verify = _parse_rpc_addr(s)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            raise SystemExit(2) from e
        return host, port, tls

    phost, pport, ptls = parse_hp(args.primary)
    primary = RPCProvider(phost, pport, "primary", tls=ptls)
    witnesses = []
    for i, w in enumerate(args.witness or []):
        wh, wp, wtls = parse_hp(w)
        witnesses.append(RPCProvider(wh, wp, f"witness{i}", tls=wtls))
    from fractions import Fraction

    from ..light.client import SEQUENTIAL, SKIPPING

    try:
        num, _, den = args.trust_level.partition("/")
        trust_level = Fraction(int(num), int(den or 1))
        if not Fraction(1, 3) <= trust_level <= 1:
            raise ValueError("must be within [1/3, 1]")
    except (ValueError, ZeroDivisionError) as e:
        print(f"bad --trust-level {args.trust_level!r}: {e}",
              file=sys.stderr)
        return 1

    client = Client(
        args.chain_id,
        TrustOptions(args.trust_period * 1_000_000_000,
                     args.trust_height, bytes.fromhex(args.trust_hash)),
        primary, witnesses=witnesses,
        mode=SEQUENTIAL if args.sequential else SKIPPING,
        trust_level=trust_level)
    server, addr = await run_light_proxy(
        client, HTTPClient(phost, pport, tls=ptls, tls_verify=False),
        "127.0.0.1", args.port)
    print(f"Light proxy on {addr[0]}:{addr[1]} "
          f"(primary {args.primary}, {len(witnesses)} witnesses)",
          flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await server.close()
    return 0


def cmd_version(args) -> int:
    print(VERSION)
    return 0


# ------------------------------------------------------------------- main

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cometbft_tpu",
        description="BFT state-machine replication with a TPU-accelerated "
                    "signature-verification hot path")
    p.add_argument("--home", default=os.environ.get(
        "CMTHOME", os.path.expanduser("~/.cometbft_tpu")),
        help="node home directory")
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("init", help="initialize config/genesis/keys")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--moniker", default="node")
    sp.add_argument("--key-type", default="ed25519",
                    choices=["ed25519", "secp256k1", "bls12_381"])
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("start", help="run the node")
    sp.add_argument("--option", "-o", action="append", default=[],
                    metavar="SECTION.KEY=VALUE",
                    help="override a config.toml entry for this run "
                         "(repeatable), e.g. -o rpc.laddr=tcp://0.0.0.0:26657"
                         " -o consensus.timeout_commit=500000000")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("testnet", help="generate wired node homes")
    sp.add_argument("--v", type=int, default=4, help="validator count")
    sp.add_argument("--output-dir", default="./mytestnet")
    sp.add_argument("--base-port", type=int, default=26656)
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_testnet)

    for name, fn in (("gen-validator", cmd_gen_validator),
                     ("gen-node-key", cmd_gen_node_key),
                     ("show-node-id", cmd_show_node_id),
                     ("show-validator", cmd_show_validator),
                     ("unsafe-reset-all", cmd_unsafe_reset_all),
                     ("version", cmd_version)):
        sp = sub.add_parser(name)
        if name == "gen-validator":
            sp.add_argument("--key-type", default="ed25519",
                            choices=["ed25519", "secp256k1", "bls12_381"])
        sp.set_defaults(fn=fn)

    sp = sub.add_parser("light", help="light-client RPC proxy daemon")
    sp.add_argument("--primary", required=True,
                    help="full node RPC addr host:port")
    sp.add_argument("--witness", action="append", default=[],
                    help="witness RPC addr (repeatable)")
    sp.add_argument("--chain-id", required=True)
    sp.add_argument("--trust-height", type=int, required=True)
    sp.add_argument("--trust-hash", required=True,
                    help="hex header hash at the trust height")
    sp.add_argument("--trust-period", type=int, default=168 * 3600,
                    help="trusting period in seconds")
    sp.add_argument("--trust-level", default="1/3",
                    help="trust level for skipping verification, "
                         "e.g. 1/3 (commands/light.go:94)")
    sp.add_argument("--sequential", action="store_true",
                    help="verify every header instead of skipping")
    sp.add_argument("--port", type=int, default=0)
    sp.set_defaults(fn=cmd_light)

    sp = sub.add_parser("signer", help="remote signer daemon: serve this "
                        "home's validator key to a node's "
                        "priv_validator_laddr")
    sp.add_argument("--address", required=True,
                    help="node's priv_validator_laddr (tcp://host:port)")
    sp.add_argument("--max-retries", type=int, default=0,
                    help="dial attempts before giving up (0 = forever)")
    sp.set_defaults(fn=cmd_signer)

    sp = sub.add_parser("inspect",
                        help="read-only RPC over the data directory")
    sp.add_argument("--port", type=int, default=26657)
    sp.set_defaults(fn=cmd_inspect)

    sp = sub.add_parser("rollback", help="undo the latest block state")
    sp.add_argument("--hard", action="store_true",
                    help="also remove the block itself")
    sp.set_defaults(fn=cmd_rollback)

    sp = sub.add_parser("load", help="drive timestamped load at a node "
                                     "(test/loadtime generator)")
    sp.add_argument("--rpc", default="127.0.0.1:26657")
    sp.add_argument("--rate", type=float, default=100.0, help="tx/s")
    sp.add_argument("--duration", type=float, default=10.0, help="seconds")
    sp.add_argument("--size", type=int, default=256, help="tx bytes")
    sp.add_argument("--connections", type=int, default=1,
                    help="concurrent sender loops splitting the rate "
                         "(loadtime's -c; one serial loop caps ~600 tx/s)")
    sp.set_defaults(fn=cmd_load)

    sp = sub.add_parser("load-report",
                        help="latency distribution of committed load txs")
    sp.add_argument("--rpc", default="127.0.0.1:26657")
    sp.add_argument("--run-id", default=None)
    sp.set_defaults(fn=cmd_load_report)

    sp = sub.add_parser("reindex-event",
                        help="rebuild tx/block indexes from stored blocks")
    sp.add_argument("--start-height", type=int, default=0)
    sp.add_argument("--end-height", type=int, default=0)
    sp.set_defaults(fn=cmd_reindex_event)

    sp = sub.add_parser("compact-db",
                        help="force-compact the data-dir stores")
    sp.set_defaults(fn=cmd_compact_db)

    sp = sub.add_parser("doctor", help="offline storage integrity check: "
                        "cross-store consistency + deep hash-chain scan "
                        "(report-only unless --repair)")
    sp.add_argument("--repair", action="store_true",
                    help="apply repairs: truncate to the last verified "
                         "height, rebuild state, quarantine a WAL that "
                         "ran ahead")
    sp.add_argument("--window", type=int, default=None,
                    help="deep-scan window in heights (default: config "
                         "storage.doctor_deep_scan_window; 0 = whole "
                         "store)")
    sp.set_defaults(fn=cmd_doctor)

    from .abci import register as register_abci

    register_abci(sub)

    sp = sub.add_parser("e2e", help="manifest-driven multi-process "
                        "testnet runner (test/e2e)")
    sp.add_argument("--manifest", help="TOML manifest path")
    sp.add_argument("--dir", default="./e2e-net")
    sp.add_argument("--base-port", type=int, default=26656)
    sp.add_argument("--deadline", type=float, default=240.0)
    sp.set_defaults(fn=cmd_e2e)

    sp = sub.add_parser("e2e-gen", help="deterministic random manifest "
                        "generator (test/e2e/generator): seed -> TOML "
                        "manifests sweeping db/abci/key/sync/perturb axes")
    sp.add_argument("--seed", type=int, default=1)
    sp.add_argument("--runs", type=int, default=1,
                    help="manifests to emit (seeds seed..seed+runs-1)")
    sp.add_argument("--output-dir", default="./e2e-gen")
    sp.add_argument("--compact", action="store_true",
                    help="CI-sized topologies (<= 4 backing nodes)")
    sp.set_defaults(fn=cmd_e2e_gen)

    sp = sub.add_parser("debug", help="post-mortem capture")
    dsub = sp.add_subparsers(dest="debug_command", required=True)
    dp = dsub.add_parser("dump", help="capture an introspection bundle")
    dp.add_argument("--rpc", default="127.0.0.1:26657")
    dp.add_argument("--output-dir", default="")
    dp.set_defaults(fn=cmd_debug_dump)
    dp = dsub.add_parser("wal", help="dump consensus WAL records as "
                         "JSON lines (scripts/wal2json)")
    dp.set_defaults(fn=cmd_debug_wal)
    dp = dsub.add_parser("kill", help="capture a RUNNING node's state "
                         "by pid, terminate it, tarball everything "
                         "(commands/debug/kill.go)")
    dp.add_argument("pid", type=int)
    dp.add_argument("output_file", nargs="?", default=None,
                    help="archive path (default <tmp>.tar.gz)")
    dp.add_argument("--rpc", default="127.0.0.1:26657")
    dp.set_defaults(fn=cmd_debug_kill)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)
