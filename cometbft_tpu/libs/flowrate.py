"""Flow-rate metering and limiting (reference: ``internal/flowrate`` —
send/recv metering for MConnection; SURVEY §2.8 small pkgs).

A Monitor tracks an exponentially-weighted transfer rate; ``limit`` returns
how many bytes may be sent now to stay under a target rate (the caller
sleeps when it gets 0).
"""

from __future__ import annotations

import time


class Monitor:
    def __init__(self, sample_period: float = 0.1, ema_alpha: float = 0.25,
                 now=time.monotonic):
        self._now = now
        self._period = sample_period
        self._alpha = ema_alpha
        self._start = now()
        self._sample_start = self._start
        self._sample_bytes = 0
        self._rate = 0.0            # bytes/sec EMA
        self.total = 0

    def update(self, n: int) -> None:
        t = self._now()
        self.total += n
        self._sample_bytes += n
        elapsed = t - self._sample_start
        if elapsed >= self._period:
            inst = self._sample_bytes / elapsed
            self._rate = (self._alpha * inst
                          + (1 - self._alpha) * self._rate)
            self._sample_start = t
            self._sample_bytes = 0

    @property
    def rate(self) -> float:
        return self._rate

    def status(self) -> dict:
        t = self._now()
        dur = max(t - self._start, 1e-9)
        return {"bytes": self.total, "duration_s": dur,
                "avg_rate": self.total / dur, "inst_rate": self._rate}

    def limit(self, want: int, max_rate: float | None) -> int:
        """How many of ``want`` bytes may transfer now under ``max_rate``
        (None = unlimited).  0 means back off."""
        if not max_rate:
            return want
        t = self._now()
        allowed = max_rate * (t - self._start) - self.total
        return max(0, min(want, int(allowed)))
