"""Flow-rate metering and limiting (reference: ``internal/flowrate`` —
send/recv metering for MConnection; SURVEY §2.8 small pkgs).

A Monitor tracks an exponentially-weighted transfer rate; ``limit`` returns
how many bytes may be sent now to stay under a target rate (the caller
sleeps when it gets 0).  ``rate`` decays while the stream is idle, so the
p2p telemetry a silent peer exports converges to zero instead of freezing
at its last burst.
"""

from __future__ import annotations

import time


class Monitor:
    def __init__(self, sample_period: float = 0.1, ema_alpha: float = 0.25,
                 now=time.monotonic):
        self._now = now
        self._period = sample_period
        self._alpha = ema_alpha
        self._start = now()
        self._sample_start = self._start
        self._sample_bytes = 0
        self._rate = 0.0            # bytes/sec EMA
        self.total = 0

    def update(self, n: int) -> None:
        t = self._now()
        self.total += n
        self._sample_bytes += n
        elapsed = t - self._sample_start
        if elapsed >= self._period:
            inst = self._sample_bytes / elapsed
            self._rate = (self._alpha * inst
                          + (1 - self._alpha) * self._rate)
            self._sample_start = t
            self._sample_bytes = 0

    @property
    def rate(self) -> float:
        """Bytes/sec EMA, read-only and idle-decaying: the pending
        partial window folds in as one sample, and every further full
        period without an ``update`` decays the estimate by
        ``(1 - alpha)`` — a connection that stops transferring reads as
        approaching zero, not as its last burst forever.  Internal EMA
        state is untouched (``update`` remains the only writer)."""
        t = self._now()
        elapsed = t - self._sample_start
        if elapsed < self._period:
            return self._rate
        inst = self._sample_bytes / elapsed
        r = self._alpha * inst + (1 - self._alpha) * self._rate
        extra = int(elapsed / self._period) - 1
        if extra > 0:
            r *= (1 - self._alpha) ** extra
        return r

    def status(self) -> dict:
        t = self._now()
        dur = max(t - self._start, 1e-9)
        return {"bytes": self.total, "duration_s": dur,
                "avg_rate": self.total / dur, "inst_rate": self.rate}

    def limit(self, want: int, max_rate: float | None) -> int:
        """How many of ``want`` bytes may transfer now under ``max_rate``
        (None = unlimited).  0 means back off.  The elapsed window is
        floored at one sample period: at ``t == start`` (a connection's
        very first write, the monotonic-clock startup edge) the budget is
        one period's allowance instead of a guaranteed-0 that would
        stall every fresh connection's first packet."""
        if not max_rate:
            return want
        t = self._now()
        allowed = max_rate * max(t - self._start, self._period) - self.total
        return max(0, min(want, int(allowed)))
