"""Deterministic node-wide fault-injection plane.

The repo grew three disjoint robustness mechanisms — crash failpoints
(``libs/fail.py``), connection fuzzing (``p2p/fuzz.py``) and e2e
perturbations — none of them seeded, none sharing a schedule, and whole
fault classes (fsync failure, torn writes, message corruption,
accelerator hangs) had no injection point at all.  This module is the
one plane they all ride:

- **Named sites.**  Each injection point in production code is a named
  site (``wal.fsync.eio``, ``p2p.send.drop``, ``device.dispatch.hang``,
  ...; dotted ``subsystem.operation.fault`` spelling, see
  ``docs/explanation/fault-injection.md``).  A site is one
  :func:`fire` call — it returns ``None`` (no fault this time) or the
  armed rule's parameter dict (inject now).
- **Seeded, deterministic schedules.**  Every site is gated by a
  :class:`FaultRule` parsed from a spec string
  (``"site:key=value:key=value"``).  Index-based triggers (``at=N``,
  ``count=N``, ``every=K``, offset ``after=N``, bound ``max=M``) depend
  only on the site's own call counter; probabilistic triggers
  (``prob=P``) draw from a per-site ``random.Random`` seeded from
  ``"{seed}:{site}"`` — so which calls fire is a pure function of the seed
  and the per-site call index, never of cross-site interleaving or
  wall-clock.  Re-running the same workload with the same seed
  reproduces the same fault schedule.
- **Bounded in-memory event log.**  Every fired fault appends one dict
  to a ``deque(maxlen=N)``; :func:`signature` projects the log onto the
  deterministic components (sorted ``(site, call-index, fire-index)``
  tuples) so a chaos test can assert that two same-seed runs injected
  the identical faults even though cross-site ordering differs.
- **Zero overhead when disabled** — the same discipline as
  ``libs/tracing.py``: a module flag, first-instruction return from
  :func:`fire`, no allocation on the hot path.  Call sites that would
  build kwargs guard with :func:`is_enabled` first.

Configuration comes from the ``[chaos]`` config section (see
``config.ChaosConfig``) wired at node start, or — for subprocess nodes
in chaos harnesses — from the ``CMT_CHAOS`` environment variable:

    CMT_CHAOS="seed=7;wal.fsync.eio:at=40;p2p.recv.corrupt:prob=0.02:max=20"

Like the flight recorder, the plane is process-wide: an in-proc
ensemble shares one schedule (events carry whatever ``detail`` the call
site passes, e.g. the channel name, to tell nodes apart).
"""

from __future__ import annotations

import functools
import random
import threading
from collections import deque

ENV_VAR = "CMT_CHAOS"

_ENABLED = False
_PLANE: "ChaosPlane | None" = None
_CONF_LOCK = threading.Lock()

# rule keys with non-float values, everything else in a spec parses as
# float (``prob=0.02``) with int-preservation (``at=40`` stays an int).
# "peer" is the scenario lab's link-spec far end (sim/transport
# apply_spec) — a plain param here, never a selector.
_STR_KEYS = ("cut", "chan", "mode", "node", "file", "peer")
# str params that act as SELECTORS when present on a rule: the site
# only counts/fires calls whose `detail` carries the same value, so
# "p2p.send.corrupt:node=bad0:every=3" arms ONE node's links in an
# in-proc ensemble, "chan=vote" one channel's packets, and
# "db.replay.corrupt:file=blockstore.db" one store's log among the
# several LogDB files a node opens.  Calls that don't match don't
# advance the call index — the schedule is a pure function of the
# MATCHING stream.
_SELECTOR_KEYS = ("chan", "node", "file")


class FaultSpecError(ValueError):
    """A fault spec string that cannot parse — raised at configure time
    (config load / node start), never from a hot-path ``fire`` call."""


@functools.cache
def _chaos_metrics():
    from . import metrics as m

    return m.counter("chaos_faults_fired_total",
                     "fault-plane injections, by site")


class FaultRule:
    """One armed site: trigger bookkeeping + pass-through params.

    Trigger precedence when several are given: ``at`` wins, then
    ``count``, then ``every``, then ``prob``.  ``after=N`` offsets any
    of them by N calls; ``max=M`` bounds total fires.
    """

    __slots__ = ("site", "at", "count", "every", "prob", "after",
                 "max_fires", "params", "selectors", "calls", "fired")

    def __init__(self, site: str, at=None, count=None, every=None,
                 prob=None, after=0, max_fires=None, params=None):
        self.site = site
        self.at = set(at) if at else None
        self.count = count
        self.every = every
        self.prob = prob
        self.after = int(after)
        self.max_fires = max_fires
        self.params = params or {}
        self.selectors = {k: v for k, v in self.params.items()
                          if k in _SELECTOR_KEYS}
        self.calls = 0              # per-site call index (1-based)
        self.fired = 0

    def decide(self, rng: random.Random) -> bool:
        """One site call: advance the counter, return fire/no-fire.
        The probabilistic draw happens on EVERY call (fired or not) so
        the set of firing call-indices is a pure function of the seed,
        independent of ``max``/``after`` bookkeeping."""
        self.calls += 1
        n = self.calls
        draw = rng.random() if self.prob is not None else 0.0
        if n <= self.after:
            return False
        if self.max_fires is not None and self.fired >= self.max_fires:
            return False
        if self.at is not None:
            hit = n in self.at
        elif self.count is not None:
            hit = (n - self.after) <= self.count
        elif self.every is not None:
            hit = (n - self.after) % self.every == 0
        elif self.prob is not None:
            hit = draw < self.prob
        else:
            hit = True              # bare site spec: always fire
        if hit:
            self.fired += 1
        return hit


def parse_fault_spec(spec: str) -> FaultRule:
    """``"site:key=value:key=value"`` -> :class:`FaultRule`.  Unknown
    keys become pass-through params the call site can read (``delay``,
    ``cut``, ...)."""
    parts = [p.strip() for p in str(spec).split(":") if p.strip()]
    if not parts or "=" in parts[0]:
        raise FaultSpecError(f"fault spec needs a leading site: {spec!r}")
    site = parts[0]
    at: list[int] = []
    kw: dict = {"params": {}}
    for part in parts[1:]:
        key, eq, raw = part.partition("=")
        if not eq:
            raise FaultSpecError(f"bad fault spec clause {part!r} "
                                 f"in {spec!r}")
        key = key.strip()
        raw = raw.strip()
        try:
            if key in _STR_KEYS:
                val: object = raw
            else:
                val = int(raw) if raw.lstrip("-").isdigit() else float(raw)
        except ValueError:
            raise FaultSpecError(
                f"bad value {raw!r} for {key!r} in {spec!r}") from None
        if key == "at":
            at.append(int(val))
        elif key == "count":
            kw["count"] = int(val)
        elif key == "every":
            kw["every"] = int(val)
        elif key == "prob":
            p = float(val)
            if not 0.0 <= p <= 1.0:
                raise FaultSpecError(f"prob must be in [0,1]: {spec!r}")
            kw["prob"] = p
        elif key == "after":
            kw["after"] = int(val)
        elif key == "max":
            kw["max_fires"] = int(val)
        else:
            kw["params"][key] = val
    if at:
        kw["at"] = at
    return FaultRule(site, **kw)


class ChaosPlane:
    """The armed schedule: rules by site, per-site seeded RNGs, and the
    bounded fault event log."""

    def __init__(self, seed: int = 0, rules: "list[FaultRule] | None" = None,
                 log_size: int = 8192):
        self.seed = int(seed)
        self.rules: dict[str, FaultRule] = {}
        for r in rules or []:
            if r.site in self.rules:
                raise FaultSpecError(f"duplicate fault site {r.site!r}")
            self.rules[r.site] = r
        self.log: deque = deque(maxlen=max(16, int(log_size)))
        self._rngs: dict[str, random.Random] = {}
        self._seq = 0

    def site_rng(self, site: str) -> random.Random:
        """Deterministic per-site RNG for payload draws (which byte to
        corrupt, how long to delay): seeded from ``seed`` + the site
        name so one site's draws never depend on another site's call
        volume.  The seed is a STRING — str/bytes seeds hash through
        sha512, stable across processes and Python versions, whereas a
        tuple seed is rejected on 3.11+ and falls back to the
        process-salted ``hash()`` on 3.10 (not reproducible)."""
        rng = self._rngs.get(site)
        if rng is None:
            rng = self._rngs[site] = random.Random(f"{self.seed}:{site}")
        return rng

    def fire(self, site: str, **detail) -> "dict | None":
        rule = self.rules.get(site)
        if rule is None:
            return None
        for k, v in rule.selectors.items():
            # selector mismatch: not part of this rule's stream at all
            # (the call index does not advance)
            if detail.get(k) != v:
                return None
        if not rule.decide(self.site_rng(site)):
            return None
        self._seq += 1
        ev = dict(rule.params)
        ev.update(detail)
        ev.update(site=site, n=rule.calls, fire=rule.fired, seq=self._seq)
        self.log.append(ev)
        _chaos_metrics().inc(site=site)
        return ev

    def events(self) -> list[dict]:
        return [dict(e) for e in self.log]

    def signature(self) -> list[tuple]:
        """Order-independent deterministic projection of the event log:
        sorted ``(site, call-index, fire-index)`` tuples.  Two same-seed
        runs of the same workload produce equal signatures even though
        cross-site interleaving (hence ``seq``) differs."""
        return sorted((e["site"], e["n"], e["fire"]) for e in self.log)

    def stats(self) -> dict:
        return {
            "seed": self.seed,
            "sites": {s: {"calls": r.calls, "fired": r.fired}
                      for s, r in self.rules.items()},
            "events": len(self.log),
        }


# ------------------------------------------------------------- module API


def is_enabled() -> bool:
    """Hot-path gate for call sites that would otherwise build detail
    dicts or bytearrays just to have :func:`fire` drop them."""
    return _ENABLED


def armed_prefix(prefix: str) -> bool:
    """True when any armed rule's site starts with ``prefix`` — the
    gate for multi-site clusters (``p2p.send.*`` is five :func:`fire`
    calls per packet; with nothing armed under the prefix the whole
    cluster is one cheap scan, and skipping it is behavior-identical
    because un-armed sites never count calls).  Scans the live rule
    table so a mid-run :func:`arm`/:func:`disarm` takes effect on the
    next packet."""
    plane = _PLANE
    if not _ENABLED or plane is None:
        return False
    return any(s.startswith(prefix) for s in plane.rules)


def fire(site: str, **detail) -> "dict | None":
    """THE injection point: ``None`` means proceed normally; a dict
    means inject (its keys are the rule's params + the caller's
    detail).  First instruction returns when chaos is disabled.  The
    plane is snapshotted locally: injection sites run on worker threads
    (device dispatch, WAL writes, the scheduler pool), so a concurrent
    ``reset()`` must degrade to a no-op, never an AttributeError."""
    if not _ENABLED:
        return None
    plane = _PLANE
    if plane is None:
        return None
    return plane.fire(site, **detail)


def site_rng(site: str) -> random.Random:
    """Per-site payload RNG; callers sit behind a :func:`fire` hit.  If
    a concurrent ``reset()`` won the race since that hit, hand back a
    throwaway RNG — the in-flight injection still completes, it just
    stops being seeded (the event was already logged or dropped)."""
    plane = _PLANE
    if plane is None:
        return random.Random(0)
    return plane.site_rng(site)


def configure(enabled: bool | None = None, seed: int | None = None,
              faults: "list[str] | None" = None,
              log_size: int | None = None) -> None:
    """Install (or clear) the process-wide plane.  ``faults`` are spec
    strings (:func:`parse_fault_spec`); passing any of ``seed`` /
    ``faults`` / ``log_size`` rebuilds the plane (fresh counters, fresh
    per-site RNGs, empty log) — re-arming the same seed+specs is
    exactly the "replay the schedule" operation."""
    global _ENABLED, _PLANE
    with _CONF_LOCK:
        if seed is not None or faults is not None or log_size is not None:
            cur = _PLANE
            _PLANE = ChaosPlane(
                seed=seed if seed is not None
                else (cur.seed if cur else 0),
                rules=[parse_fault_spec(s) for s in (faults or [])],
                log_size=log_size if log_size is not None
                else (cur.log.maxlen if cur else 8192))
        if enabled is not None:
            if enabled and _PLANE is None:
                _PLANE = ChaosPlane()
            _ENABLED = bool(enabled)


def configure_from_config(chaos_cfg) -> None:
    """Node-start wiring (``config.ChaosConfig``).  The ``CMT_CHAOS``
    environment variable, when set, wins outright — it is how chaos
    harnesses arm subprocess nodes without editing their config files.
    Process-wide and sticky like tracing: a disabled config never
    disarms a plane another in-proc node armed."""
    import os

    env = os.environ.get(ENV_VAR, "").strip()
    if env:
        seed, faults, log_size = _parse_env(env)
        configure(enabled=True, seed=seed, faults=faults,
                  log_size=log_size)
        return
    if chaos_cfg is not None and chaos_cfg.enable:
        configure(enabled=True, seed=chaos_cfg.seed,
                  faults=list(chaos_cfg.faults),
                  log_size=chaos_cfg.log_size)


def _parse_env(env: str) -> tuple[int, list[str], int]:
    """``"seed=7;log=4096;site:k=v;site2"`` -> (seed, specs, log_size).
    A clause with '=' and no ':' is a plane param; anything else is a
    fault spec."""
    seed, log_size = 0, 8192
    faults: list[str] = []
    for clause in env.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if "=" in clause and ":" not in clause:
            key, _, raw = clause.partition("=")
            key = key.strip()
            if key == "seed":
                seed = int(raw)
            elif key == "log":
                log_size = int(raw)
            else:
                raise FaultSpecError(f"unknown {ENV_VAR} param {key!r}")
        else:
            faults.append(clause)
    return seed, faults, log_size


def arm(spec: str) -> None:
    """Add one rule to the installed plane WITHOUT resetting counters or
    the event log — phased chaos scenarios arm faults as the scenario
    progresses (the new rule's call index starts at its arming point,
    which is itself deterministic when the scenario script is)."""
    with _CONF_LOCK:
        if _PLANE is None:
            raise FaultSpecError("no chaos plane installed; configure() "
                                 "first")
        rule = parse_fault_spec(spec)
        if rule.site in _PLANE.rules:
            raise FaultSpecError(f"site {rule.site!r} already armed")
        _PLANE.rules[rule.site] = rule


def disarm(site: str) -> None:
    """Remove one rule (its logged events stay in the log)."""
    with _CONF_LOCK:
        if _PLANE is not None:
            _PLANE.rules.pop(site, None)


def reset() -> None:
    """Disarm everything (tests)."""
    global _ENABLED, _PLANE
    with _CONF_LOCK:
        _ENABLED = False
        _PLANE = None


def events() -> list[dict]:
    return _PLANE.events() if _PLANE is not None else []


def signature() -> list[tuple]:
    return _PLANE.signature() if _PLANE is not None else []


def stats() -> dict:
    if _PLANE is None:
        return {"enabled": False}
    return {"enabled": _ENABLED, **_PLANE.stats()}
