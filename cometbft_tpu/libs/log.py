"""Structured key-value logger (reference: ``libs/log/tm_logger.go`` and
the JSON variant / per-module level filter in ``libs/log/filter.go``).

Usage::

    logger = log.logger("consensus", node="node0")
    logger.info("entering new round", height=5, round=0)
    logger.with_(peer="ab12").warn("send failed")

Output is one line per record: ``LVL[timestamp] message  module=consensus
height=5 ...`` (or JSON with ``log.set_format("json")``).  Levels filter
per module via ``set_level("consensus", "debug")`` / global default."""

from __future__ import annotations

import json
import sys
import threading
import time

LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40, "none": 100}

_config_lock = threading.Lock()
_default_level = LEVELS["info"]
_module_levels: dict[str, int] = {}
_format = "plain"                    # plain | json
_sink = sys.stderr


def set_level(module: str | None, level: str) -> None:
    global _default_level
    lv = LEVELS[level]
    with _config_lock:
        if module is None:
            _default_level = lv
        else:
            _module_levels[module] = lv


def set_format(fmt: str) -> None:
    global _format
    assert fmt in ("plain", "json")
    _format = fmt


def set_sink(f) -> None:
    global _sink
    _sink = f


class Logger:
    __slots__ = ("module", "ctx")

    def __init__(self, module: str, ctx: dict | None = None):
        self.module = module
        self.ctx = ctx or {}

    def with_(self, **kv) -> "Logger":
        return Logger(self.module, {**self.ctx, **kv})

    def _enabled(self, level: int) -> bool:
        return level >= _module_levels.get(self.module, _default_level)

    def _emit(self, level_name: str, msg: str, kv: dict) -> None:
        record = {**self.ctx, **kv}
        if _format == "json":
            line = json.dumps({"ts": time.time(), "level": level_name,
                               "module": self.module, "msg": msg,
                               **{k: _scalar(v) for k, v in record.items()}})
        else:
            ts = time.strftime("%H:%M:%S", time.localtime())
            kvs = " ".join(f"{k}={_scalar(v)}" for k, v in record.items())
            line = (f"{level_name[0].upper()}[{ts}] {msg:<44} "
                    f"module={self.module}" + (f" {kvs}" if kvs else ""))
        print(line, file=_sink, flush=True)

    def debug(self, msg: str, **kv) -> None:
        if self._enabled(10):
            self._emit("debug", msg, kv)

    def info(self, msg: str, **kv) -> None:
        if self._enabled(20):
            self._emit("info", msg, kv)

    def warn(self, msg: str, **kv) -> None:
        if self._enabled(30):
            self._emit("warn", msg, kv)

    def error(self, msg: str, **kv) -> None:
        if self._enabled(40):
            self._emit("error", msg, kv)


def _scalar(v):
    if isinstance(v, bytes):
        return v.hex()[:16]
    if isinstance(v, float):
        return round(v, 6)
    return v


def logger(module: str, **ctx) -> Logger:
    return Logger(module, ctx)
