"""The node-wide clock seam: every sleep/timeout/monotonic read in the
clock-managed packages (consensus, p2p, node, mempool, blocksync,
statesync) routes through this module instead of calling ``time`` /
``asyncio`` directly.

Why a seam at all: testing liveness with real wall-clock time caps nets
at ~4 nodes per test and turns every timeout into a flake budget (PR 12
had to widen a fuzz-liveness deadline from 90s to 150s because
legitimate reconnect backoff sat on the limit).  With ONE injectable
clock, the deterministic scenario lab (``cometbft_tpu.sim``) runs
hundreds of in-process nodes on a virtual clock that advances only when
every node is quiescent — a 100-node, multi-height adversarial run
finishes in seconds of real time and is replayable from a seed.

Discipline (same as ``libs/tracing`` / ``libs/failures``):

- **Real-time path costs nothing.**  With no virtual clock installed
  (every production node, every bench), each function is a
  first-instruction branch on a module global followed by the exact
  call it replaced.  The vote-gossip bench guard holds with the sim
  package never imported.
- **Virtual mode is loop-driven.**  ``asyncio.sleep`` / ``wait_for`` /
  ``call_later`` already schedule against ``loop.time()``, so under the
  sim's :class:`~cometbft_tpu.sim.vtime.VirtualTimeLoop` the async
  functions here stay thin delegates — the loop virtualizes them.  The
  functions that MUST branch are the direct time reads
  (:func:`monotonic`, :func:`walltime_ns`): a ``time.monotonic()`` call
  inside a clock-managed package reads *real* time under simulation and
  silently breaks determinism (step ages, RTTs, score decay, ban TTLs).
  bftlint's CLK001 (``scripts/analysis``, run by ``scripts/lint.sh``)
  rejects new direct calls in managed packages — including aliased
  imports and ``loop.time()``, which the old regex guard missed; the
  rare legitimate exception carries a
  ``# bftlint: disable=CLK001 -- reason`` marker (the successor of the
  retired ``clock-exempt`` grep marker).  ``time.perf_counter`` is NOT
  banned: it is the duration-METRICS clock (histograms measure real CPU
  cost even under the virtual clock), while monotonic/time/sleep order
  events and must virtualize.

``install()`` is process-wide like the chaos plane: an in-proc ensemble
shares one clock.
"""

from __future__ import annotations

import asyncio
import time as _time

_CLOCK = None      # None => real time; else an installed clock object


class Clock:
    """Interface an installable clock implements.  The sim package's
    ``VirtualClock`` is the one real implementation; production code
    never constructs a Clock (the module functions short-circuit to
    ``time`` / ``asyncio`` when none is installed)."""

    def monotonic(self) -> float:
        raise NotImplementedError

    def walltime_ns(self) -> int:
        raise NotImplementedError


def install(clk: Clock) -> None:
    """Install the process-wide clock (the sim driver calls this before
    any node is constructed, so ``__init__``-time reads land on virtual
    time too)."""
    global _CLOCK
    _CLOCK = clk


def uninstall() -> None:
    global _CLOCK
    _CLOCK = None


def installed() -> Clock | None:
    return _CLOCK


# ------------------------------------------------------------ time reads

def monotonic() -> float:
    """``time.monotonic`` through the seam — THE call that must never be
    made directly in a clock-managed package (it would measure real time
    under simulation)."""
    if _CLOCK is None:
        return _time.monotonic()
    return _CLOCK.monotonic()


def walltime_ns() -> int:
    """``time.time_ns`` through the seam.  Under the virtual clock this
    is a fixed epoch plus virtual offset, which makes block timestamps —
    hence block hashes — a pure function of the scenario seed."""
    if _CLOCK is None:
        return _time.time_ns()
    return _CLOCK.walltime_ns()


def monotonic_ns() -> int:
    """``time.monotonic_ns`` through the seam.  The flight recorder
    (``libs/tracing``) stamps records with this so a scenario-lab run's
    span timestamps — hence the per-height timeline attribution in the
    verdict — are a pure function of the scenario seed."""
    if _CLOCK is None:
        return _time.monotonic_ns()
    return int(_CLOCK.monotonic() * 1e9)


def walltime() -> float:
    """``time.time`` through the seam (ban expiries, report stamps)."""
    if _CLOCK is None:
        return _time.time()
    return _CLOCK.walltime_ns() / 1e9


# ------------------------------------------------------- async scheduling

async def sleep(delay: float, result=None):
    """``asyncio.sleep`` through the seam.  Scheduling rides
    ``loop.time()``, so the virtual loop makes this virtual without a
    branch here — the indirection exists so the lint guard has one
    spelling to allow and so a non-loop clock could intercept later."""
    return await asyncio.sleep(delay, result)


async def wait_for(awaitable, timeout: float | None):
    """``asyncio.wait_for`` through the seam (see :func:`sleep`)."""
    return await asyncio.wait_for(awaitable, timeout)
