"""Utility libraries (reference: ``libs/`` + small ``internal/`` packages):
service lifecycle, logging, pubsub, events, bit arrays, metrics."""
