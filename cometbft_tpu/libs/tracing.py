"""Node-wide flight recorder: lightweight span/event tracing into a
bounded in-memory ring buffer.

Prometheus metrics (``libs/metrics``) answer "how much / how fast on
average"; this module answers "where did THIS block's latency go".  Every
subsystem on the commit path emits spans (an interval with a duration:
a consensus step, an ABCI call, a scheduler dispatch) and events (a
point: a WAL fsync, a micro-batch flush, a kernel first-dispatch) into
one process-wide ring, and the RPC server dumps it as JSON via
``GET /dump_trace?limit=N`` — so a single trace of height H shows the
verify micro-batches the vote scheduler ran inside the prevote span.

Design constraints, in order:

- **Disabled means free.**  Tracing is off unless
  ``[instrumentation] tracing = true``.  ``event()`` returns on its
  first instruction; ``span()`` returns one shared no-op context
  manager (no per-call allocation); ``begin()`` returns None and
  ``finish(None)`` is a no-op.  Hot paths may additionally guard with
  :func:`is_enabled` to skip building attrs at all.
- **Thread/asyncio-safe without locks on the emit path.**  Records are
  single ``deque.append`` calls (atomic under the GIL) of fully-built
  tuples, and ids come from ``itertools.count`` (also atomic) — writers
  on the event loop, scheduler worker threads, and the device-owner
  thread never contend or tear.
- **Bounded memory.**  The ring is a ``deque(maxlen=N)``; old records
  fall off the back.  N is ``[instrumentation] tracing_ring_size``.

Span taxonomy (see ``docs/explanation/observability.md``): records carry
a ``sub`` (subsystem: ``consensus``, ``abci``, ``crypto.sched``,
``crypto.kernel``, ``wal``, ``mempool``), a ``name`` (one word: ``step``,
``call``, ``dispatch``, ``fsync``...), and free-form ``attrs``.  Spans
opened with the :func:`span` context manager propagate their id through
a ``ContextVar`` so lexically nested spans record a ``parent`` id;
long-lived spans that cross handler boundaries (consensus steps) use
:func:`begin`/:func:`finish` directly and correlate by time + attrs.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from contextvars import ContextVar

from . import clock as _clock

_ENABLED = False
_MAXLEN = 8192
_RING: deque = deque(maxlen=_MAXLEN)
_SEQ = itertools.count(1)
_CUR: ContextVar[int] = ContextVar("tracing_cur_span", default=0)
_CONF_LOCK = threading.Lock()

# record tuples: (kind, id, parent, sub, name, wall_ns, start_ns, end_ns,
# attrs) — built whole, appended once (no partially-visible records)


def is_enabled() -> bool:
    """Fast gate for call sites that would otherwise build attrs dicts
    or format values just to have ``event()`` drop them."""
    return _ENABLED


def configure(enabled: bool | None = None,
              ring_size: int | None = None) -> None:
    """Install the node config: flip tracing on/off and/or resize the
    ring (existing records are kept up to the new bound).  Process-wide —
    in-proc ensembles share one flight recorder, records carry a
    ``node`` attr where it matters."""
    global _ENABLED, _RING, _MAXLEN
    with _CONF_LOCK:
        if ring_size is not None:
            size = max(16, int(ring_size))
            if size != _MAXLEN:
                _MAXLEN = size
                _RING = deque(_RING, maxlen=size)
        if enabled is not None:
            _ENABLED = bool(enabled)


def clear() -> None:
    _RING.clear()


# ------------------------------------------------------------------ emit


class _Open:
    """An in-flight span: handed out by :func:`begin`, turned into a ring
    record by :func:`finish`.  Nothing is visible in the ring until the
    span closes (a mid-span ``/dump_trace`` shows completed work only)."""

    __slots__ = ("id", "parent", "sub", "name", "attrs", "t0", "wall0")


def begin(sub: str, name: str, **attrs) -> "_Open | None":
    """Open a span that outlives the current stack frame (consensus
    steps span many handler invocations).  Returns None when disabled —
    :func:`finish` accepts it."""
    if not _ENABLED:
        return None
    o = _Open.__new__(_Open)
    o.id = next(_SEQ)
    o.parent = _CUR.get()
    o.sub = sub
    o.name = name
    o.attrs = attrs
    # stamps ride the clock seam: under the sim's virtual clock the
    # ring orders by VIRTUAL time, which is what makes the scenario
    # lab's timeline verdicts a pure function of the seed.  With no
    # clock installed these are the exact raw calls they replace.
    if _clock._CLOCK is None:
        o.wall0 = time.time_ns()
        o.t0 = time.monotonic_ns()
    else:
        o.wall0 = _clock.walltime_ns()
        o.t0 = _clock.monotonic_ns()
    return o


def finish(open_: "_Open | None", **extra) -> None:
    """Close a span from :func:`begin`; ``extra`` attrs merge in (e.g.
    the verdict that was only known at the end)."""
    if open_ is None:
        return
    end = time.monotonic_ns() if _clock._CLOCK is None \
        else _clock.monotonic_ns()
    if extra:
        open_.attrs.update(extra)
    _RING.append(("span", open_.id, open_.parent, open_.sub, open_.name,
                  open_.wall0, open_.t0, end, open_.attrs))


def event(sub: str, name: str, **attrs) -> None:
    """Fire-and-forget point event."""
    if not _ENABLED:
        return
    if _clock._CLOCK is None:
        wall, t = time.time_ns(), time.monotonic_ns()
    else:
        wall, t = _clock.walltime_ns(), _clock.monotonic_ns()
    _RING.append(("event", next(_SEQ), _CUR.get(), sub, name,
                  wall, t, t, attrs))


class _SpanCM:
    """Context-manager span: sets itself as the current parent for the
    duration so nested ``span()``/``event()`` calls record ``parent``."""

    __slots__ = ("_sub", "_name", "_attrs", "_open", "_tok")

    def __init__(self, sub, name, attrs):
        self._sub = sub
        self._name = name
        self._attrs = attrs
        self._open = None
        self._tok = None

    def __enter__(self):
        self._open = begin(self._sub, self._name, **self._attrs)
        if self._open is not None:
            self._tok = _CUR.set(self._open.id)
        return self._open

    def __exit__(self, *exc):
        if self._open is not None:
            _CUR.reset(self._tok)
            finish(self._open)
        return False


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


def span(sub: str, name: str, **attrs):
    """Context manager measuring one lexical scope.  Disabled tracing
    returns a shared no-op instance — zero per-call allocation."""
    if not _ENABLED:
        return _NOOP
    return _SpanCM(sub, name, attrs)


# ------------------------------------------------------------------ dump


def _jsonable(v):
    if isinstance(v, (bytes, bytearray)):
        return v.hex()
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)


def _to_dict(rec) -> dict:
    kind, rid, parent, sub, name, wall0, t0, t1, attrs = rec
    return {
        "kind": kind, "id": rid, "parent": parent,
        "sub": sub, "name": name,
        "wall_ns": wall0,            # wall clock at start (cross-node)
        "start_ns": t0,              # monotonic: orders records
        "end_ns": t1,
        "dur_us": (t1 - t0) // 1000,
        "attrs": {k: _jsonable(v) for k, v in attrs.items()},
    }


def _rec_matches_height(attrs: dict, height: int) -> bool:
    """A record belongs to ``height`` when it stamps ``height`` exactly
    or its ``h_lo``..``h_hi`` window (batched emitters: a scheduler
    dispatch mixing heights) covers it."""
    h = attrs.get("height")
    if h is not None:
        return h == height
    lo, hi = attrs.get("h_lo"), attrs.get("h_hi")
    if lo is not None and hi is not None:
        return lo <= height <= hi
    return False


def snapshot() -> list[tuple]:
    """The raw ring as a list (newest last) — the zero-copy input for
    ``libs/timeline``; each element is the record tuple documented at
    the top of this module."""
    return list(_RING)


def dump(limit: int = 1000, sub: str | None = None,
         height: int | None = None) -> list[dict]:
    """The newest ``limit`` COMPLETED records (``limit <= 0``: the whole
    ring) as JSON-able dicts, in completion order — sort by ``start_ns``
    to reconstruct the timeline, since spans append at finish.  ``sub``
    keeps one subsystem's records; ``height`` keeps records stamped with
    that height (exactly, or inside their ``h_lo``..``h_hi`` window).
    Filters apply BEFORE the limit, so ``limit=100&height=H`` is the
    newest 100 records OF that height."""
    recs = list(_RING)               # snapshot: writers keep appending
    if sub is not None:
        recs = [r for r in recs if r[3] == sub]
    if height is not None:
        h = int(height)
        recs = [r for r in recs if _rec_matches_height(r[8], h)]
    if limit and int(limit) > 0:
        recs = recs[-int(limit):]
    return [_to_dict(r) for r in recs]


def stats() -> dict:
    return {"enabled": _ENABLED, "ring_size": _MAXLEN,
            "buffered": len(_RING)}
