"""Keep-alive task spawning.

The asyncio event loop holds only WEAK references to tasks (documented
in the asyncio API reference), so a bare ``asyncio.ensure_future(coro)``
whose return value is dropped can be garbage-collected mid-flight and
silently never complete.  Every fire-and-forget spawn in this package
goes through :func:`spawn`, which parks a strong reference until the
task finishes (ADVICE r3)."""

from __future__ import annotations

import asyncio

_BG: set[asyncio.Task] = set()       # module-level default keep-alive set


def spawn(coro, store: set | None = None) -> asyncio.Task:
    """``ensure_future`` with a strong reference held until done.

    ``store`` lets an owner track (and cancel on stop) its own tasks;
    without one the module-level set keeps the task alive."""
    t = asyncio.ensure_future(coro)
    s = _BG if store is None else store
    s.add(t)
    t.add_done_callback(s.discard)
    return t
