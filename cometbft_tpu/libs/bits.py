"""BitArray (reference: ``internal/bits/bit_array.go``) — vote/part presence
tracking gossiped between peers."""

from __future__ import annotations

import random


class BitArray:
    def __init__(self, size: int, bits: int = 0):
        if size < 0:
            raise ValueError("negative size")
        self.size = size
        self._bits = bits & ((1 << size) - 1)

    @classmethod
    def from_indices(cls, size: int, idxs) -> "BitArray":
        b = cls(size)
        for i in idxs:
            b.set_index(i, True)
        return b

    def get_index(self, i: int) -> bool:
        if not 0 <= i < self.size:
            return False
        return bool((self._bits >> i) & 1)

    def set_index(self, i: int, v: bool) -> bool:
        if not 0 <= i < self.size:
            return False
        if v:
            self._bits |= 1 << i
        else:
            self._bits &= ~(1 << i)
        return True

    def copy(self) -> "BitArray":
        return BitArray(self.size, self._bits)

    def or_(self, other: "BitArray") -> "BitArray":
        size = max(self.size, other.size)
        return BitArray(size, self._bits | other._bits)

    def and_(self, other: "BitArray") -> "BitArray":
        return BitArray(min(self.size, other.size), self._bits & other._bits)

    def not_(self) -> "BitArray":
        return BitArray(self.size, ~self._bits)

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits in self but not in other."""
        return BitArray(self.size, self._bits & ~other._bits)

    def is_empty(self) -> bool:
        return self._bits == 0

    def is_full(self) -> bool:
        return self._bits == (1 << self.size) - 1

    def pick_random(self, rng: random.Random | None = None) -> tuple[int, bool]:
        """A uniformly random set index (reference PickRandom)."""
        idxs = self.get_true_indices()
        if not idxs:
            return 0, False
        return (rng or random).choice(idxs), True

    def get_true_indices(self) -> list[int]:
        out, bits, i = [], self._bits, 0
        while bits:
            if bits & 1:
                out.append(i)
            bits >>= 1
            i += 1
        return out

    def num_true_bits(self) -> int:
        return bin(self._bits).count("1")

    def __eq__(self, other):
        return (isinstance(other, BitArray) and self.size == other.size
                and self._bits == other._bits)

    def __str__(self):
        return "".join("x" if self.get_index(i) else "_"
                       for i in range(self.size))
