"""Event-loop responsiveness watchdog — the asyncio analogue of the
reference's race/deadlock tooling (``libs/sync/deadlock.go``'s build-tag
mutexes and the ``-race`` CI target, SURVEY §5).

The single-writer asyncio design replaces Go's mutexes, so the failure
mode shifts from deadlock to *loop stall*: one synchronous call (a cold
XLA compile, a blocking probe, accidental file IO) freezes every
subsystem at once, silently.  The watchdog measures scheduling lag from a
monitor thread and turns stalls into structured log lines + a metric, so
they show up in tests and production instead of as mystery timeouts.
"""

from __future__ import annotations

import asyncio
import threading
import time

from . import metrics as _metrics
from .log import logger

_LOG = logger("loopwatch")


class LoopWatchdog:
    """Heartbeats the loop via ``call_soon_threadsafe``; if a beat takes
    more than ``stall_threshold_s`` to run, the loop was blocked that
    long by synchronous work."""

    def __init__(self, loop: asyncio.AbstractEventLoop | None = None,
                 interval_s: float = 0.5,
                 stall_threshold_s: float = 1.0,
                 name: str = "node"):
        self._loop = loop or asyncio.get_event_loop()
        self.interval_s = interval_s
        self.stall_threshold_s = stall_threshold_s
        self.name = name
        self.stalls = 0
        self.worst_stall_s = 0.0
        # the MOST RECENT beat's scheduling lag: call_soon_threadsafe
        # lands behind everything already queued, so this doubles as a
        # backlog signal — RPC admission control sheds broadcast load
        # when it climbs (the flood that starves consensus into round
        # churn announces itself here first)
        self.last_lag_s = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._gauge = _metrics.gauge(
            "loop_worst_stall_seconds",
            "longest observed event-loop stall")
        self._counter = _metrics.counter(
            "loop_stalls_total",
            "event-loop stalls above threshold")

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"loopwatch-{self.name}")
        self._thread.start()

    def stop(self) -> None:
        """Signal-only shutdown: stop() is typically called FROM the
        watched loop, and joining would block the very thread an
        in-flight heartbeat needs to land on (the daemon thread exits on
        its own after the wait)."""
        self._stop.set()
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            beat = threading.Event()
            sent = time.monotonic()
            try:
                self._loop.call_soon_threadsafe(beat.set)
            except RuntimeError:
                return                       # loop closed
            # wait generously; a stall longer than 60 s is still reported
            beat.wait(60.0)
            lag = time.monotonic() - sent
            if self._stop.is_set():
                return              # shutdown lag is not a loop stall
            self.last_lag_s = lag
            if lag >= self.stall_threshold_s:
                self.stalls += 1
                self.worst_stall_s = max(self.worst_stall_s, lag)
                self._counter.inc(node=self.name)
                self._gauge.set(self.worst_stall_s, node=self.name)
                _LOG.error("event loop stalled",
                           node=self.name, stall_s=round(lag, 3),
                           hint="synchronous work on the loop thread "
                                "(compile? blocking IO?)")
