"""Service lifecycle base (reference: ``libs/service/service.go:99,132``
``BaseService``): idempotent start/stop, an ``is_running`` flag, a
``wait()`` for termination, and overridable on_start/on_stop hooks.

Most of the framework predates this class and manages asyncio tasks
directly; new long-running components (and anything that wants uniform
lifecycle semantics) subclass this instead of re-rolling the pattern."""

from __future__ import annotations

import asyncio

from . import log as tmlog


class ServiceError(Exception):
    pass


class BaseService:
    def __init__(self, name: str | None = None):
        self.name = name or type(self).__name__
        self.log = tmlog.logger("service", name=self.name)
        self._running = False
        self._stopped_ev = asyncio.Event()

    # --------------------------------------------------------------- hooks

    async def on_start(self) -> None:
        """Subclass hook; spawn tasks here."""

    async def on_stop(self) -> None:
        """Subclass hook; cancel tasks here."""

    # ----------------------------------------------------------- lifecycle

    @property
    def is_running(self) -> bool:
        return self._running

    async def start(self) -> None:
        """service.go Start: error on double start.  The running flag
        flips BEFORE awaiting on_start (the reference's atomic CAS), so a
        concurrent second start() gets ServiceError instead of running
        on_start twice; a failed on_start resets and releases waiters."""
        if self._running:
            raise ServiceError(f"service {self.name} already running")
        self._running = True
        self._stopped_ev.clear()
        try:
            await self.on_start()
        except BaseException:
            self._running = False
            self._stopped_ev.set()
            raise
        self.log.debug("service started")

    async def stop(self) -> None:
        """service.go Stop: idempotent."""
        if not self._running:
            return
        self._running = False
        await self.on_stop()
        self._stopped_ev.set()
        self.log.debug("service stopped")

    async def wait(self) -> None:
        """Block until the service stops — or until a start attempt fails
        (service.go Wait)."""
        await self._stopped_ev.wait()
