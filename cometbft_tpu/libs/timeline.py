"""Height-timeline attribution: fold the flight-recorder ring
(``libs/tracing``) into per-height commit-latency **waterfalls**.

The ring answers "what happened"; this module answers "where did height
H's latency go, on which node".  A waterfall is one (node, height) pair
broken into the ordered consensus phases

    propose -> gossip -> prevote -> precommit -> commit

bounded by the emitter marks every commit-path subsystem stamps
(``proposal_received``, ``block_assembled``, the step-span transitions
into PRECOMMIT / COMMIT, the ``commit`` event), plus residual-time
**buckets** (``gossip_wait``, ``verify``, ``app``, ``wal``, ``idle``)
that decompose the same total exactly — buckets are clipped against the
remaining budget in a fixed order, so their sum always equals the
measured commit latency and never exceeds it.

Correlation rules by subsystem:

- ``consensus`` records REQUIRE ``node`` + ``height`` attrs (the attr
  contract pinned by ``tests/test_timeline.py``) and key the waterfall.
- ``abci`` call spans join on ``height`` (+ ``node`` when stamped — the
  sim lab shares one process ring across the fleet).
- ``wal`` fsync events join on ``height``.
- ``crypto.sched`` dispatch spans join on their ``h_lo``..``h_hi``
  window (a micro-batch mixes heights) and are clipped to the
  waterfall's interval: verification is a shared resource, so its time
  is attributed to every height it overlapped.
- ``crypto.agg`` verify spans (the BLS aggregate-commit pairing check)
  join on ``height`` and feed the same ``verify`` bucket.

Everything here is pure computation over a snapshot: no clocks are
read, so folding the virtual-time ring of a scenario-lab run yields
waterfalls that are a pure function of the scenario seed (the replay
contract ``bench.py --mode scenarios`` asserts on the ``timeline``
verdict field).
"""

from __future__ import annotations

import math

# waterfall phase taxonomy, in commit order.  Each phase starts at its
# mark and runs to the next present mark (the last runs to the commit):
#   propose    height start -> proposal received (includes commit-wait)
#   gossip     proposal received -> block parts complete
#   prevote    parts complete -> +2/3 prevotes (PRECOMMIT step entered)
#   precommit  +2/3 prevotes -> +2/3 precommits (COMMIT step entered)
#   commit     +2/3 precommits -> block applied (save/WAL/app inside)
PHASES = ("propose", "gossip", "prevote", "precommit", "commit")

# residual buckets, in clipping order (see fold()); "idle" takes the
# remainder, so the five always sum to the waterfall's total
BUCKETS = ("gossip_wait", "verify", "app", "wal", "idle")


def _r(ns: int) -> float:
    """ns -> seconds, rounded for a stable JSON surface."""
    return round(ns / 1e9, 6)


class _Acc:
    """Per-(node, height) accumulator while scanning the ring."""

    __slots__ = ("steps", "proposal_rx", "parts_done", "commit_t",
                 "commit_round", "catchup", "abci", "fsyncs", "wall0",
                 "t_min", "t_max")

    def __init__(self):
        self.steps = []          # (round, step, t0, t1)
        self.proposal_rx = None  # latest proposal_received event ns
        self.parts_done = None   # latest block_assembled event ns
        self.commit_t = None     # commit event ns
        self.commit_round = None
        self.catchup = False
        self.abci = []           # (method, t0, t1)
        self.fsyncs = []         # (t, dur_ns)
        self.wall0 = None        # wall ns of the earliest record
        self.t_min = None
        self.t_max = None

    def note(self, wall0: int, t0: int, t1: int) -> None:
        if self.t_min is None or t0 < self.t_min:
            self.t_min = t0
            self.wall0 = wall0
        if self.t_max is None or t1 > self.t_max:
            self.t_max = t1


def fold(records, *, node: str | None = None, height: int | None = None,
         limit: int = 8) -> list[dict]:
    """Fold raw ring tuples (``tracing.snapshot()``) into waterfalls,
    newest heights first, at most ``limit`` per node (``limit <= 0``:
    all).  ``node``/``height`` filter the output."""
    accs: dict[tuple, _Acc] = {}
    shared_abci = []     # abci spans with no node attr: join on height
    fsyncs = []          # (height, t, dur_ns)
    dispatches = []      # (h_lo, h_hi, t0, t1)

    for kind, _rid, _par, sub, name, wall0, t0, t1, attrs in records:
        if sub == "consensus":
            n, h = attrs.get("node"), attrs.get("height")
            if n is None or h is None:
                continue             # attr contract violated: skip
            if node is not None and n != node:
                continue
            if height is not None and h != height:
                continue
            acc = accs.get((n, h))
            if acc is None:
                acc = accs[(n, h)] = _Acc()
            acc.note(wall0, t0, t1)
            if name == "step":
                acc.steps.append((attrs.get("round", 0),
                                  attrs.get("step", ""), t0, t1))
            elif name == "proposal_received":
                acc.proposal_rx = t0
            elif name == "block_assembled":
                acc.parts_done = t0
            elif name == "commit":
                acc.commit_t = t0
                acc.commit_round = attrs.get("round", 0)
                acc.catchup = bool(attrs.get("catchup"))
        elif sub == "abci" and name == "call":
            h = attrs.get("height")
            if h is None:
                continue
            n = attrs.get("node")
            item = (attrs.get("method", ""), t0, t1)
            if n is None:
                shared_abci.append((h, item))
            else:
                acc = accs.get((n, h))
                if acc is not None:
                    acc.abci.append(item)
                else:
                    shared_abci.append((h, item))
        elif sub == "wal" and name == "fsync":
            h = attrs.get("height")
            if h is not None:
                fsyncs.append((h, t0, int(attrs.get("dur_us", 0)) * 1000))
        elif sub == "crypto.sched" and name == "dispatch":
            lo, hi = attrs.get("h_lo"), attrs.get("h_hi")
            if lo:
                dispatches.append((lo, hi or lo, t0, t1))
        elif sub == "crypto.agg" and name == "verify":
            # BLS aggregate-commit pairing check: a single-height window
            h = attrs.get("height")
            if h:
                dispatches.append((h, h, t0, t1))

    for h, item in shared_abci:
        for (n, hh), acc in accs.items():
            if hh == h:
                acc.abci.append(item)
    for h, t, dur in fsyncs:
        for (n, hh), acc in accs.items():
            if hh == h:
                acc.fsyncs.append((t, dur))

    out = []
    per_node: dict[str, int] = {}
    for (n, h) in sorted(accs, key=lambda k: (-k[1], k[0])):
        if limit and limit > 0:
            if per_node.get(n, 0) >= limit:
                continue
            per_node[n] = per_node.get(n, 0) + 1
        out.append(_waterfall(n, h, accs[(n, h)], dispatches))
    out.sort(key=lambda w: (w["height"], w["node"]))
    return out


def _waterfall(node: str, height: int, acc: _Acc, dispatches) -> dict:
    t0h = min((t0 for _r_, s, t0, _t1 in acc.steps if s == "NewHeight"),
              default=acc.t_min)
    end = acc.commit_t if acc.commit_t is not None else acc.t_max
    complete = acc.commit_t is not None
    cr = acc.commit_round

    def _step_start(step_name: str):
        cands = [(r, t0) for r, s, t0, _ in acc.steps if s == step_name]
        if not cands:
            return None
        if cr is not None:
            exact = [t0 for r, t0 in cands if r == cr]
            if exact:
                return min(exact)
        return max(t0 for _, t0 in cands)      # latest round's entry

    finalize = None
    app_ns = 0
    for method, a0, a1 in acc.abci:
        app_ns += max(0, min(a1, end) - max(a0, t0h))
        if method == "finalize_block":
            finalize = a1 if finalize is None else max(finalize, a1)
    wal_ns = sum(d for t, d in acc.fsyncs if t0h <= t <= end)
    fsync_mark = max((t for t, _ in acc.fsyncs if t0h <= t <= end),
                     default=None)
    verify_ns = 0
    for lo, hi, d0, d1 in dispatches:
        if lo <= height <= hi:
            verify_ns += max(0, min(d1, end) - max(d0, t0h))

    marks_abs = {
        "proposal_received": acc.proposal_rx,
        "parts_complete": acc.parts_done,
        "prevote_23": _step_start("Precommit"),
        "precommit_23": _step_start("Commit"),
        "commit": acc.commit_t,
        "finalize": finalize,
        "fsync": fsync_mark,
    }

    # phase boundaries: drop absent marks (evicted ring records, or a
    # catch-up commit that never saw vote phases); clamp to monotonic
    bounds = [("propose", t0h)]
    for phase, mark in (("gossip", acc.proposal_rx),
                        ("prevote", acc.parts_done),
                        ("precommit", marks_abs["prevote_23"]),
                        ("commit", marks_abs["precommit_23"])):
        if mark is not None:
            bounds.append((phase, max(mark, bounds[-1][1])))
    phases = []
    for i, (phase, t) in enumerate(bounds):
        nxt = bounds[i + 1][1] if i + 1 < len(bounds) else max(end, t)
        phases.append({"phase": phase,
                       "start_s": _r(t - t0h),
                       "dur_s": _r(max(0, min(nxt, end) - t))})

    total_ns = max(0, end - t0h)
    gossip_ns = 0
    if acc.proposal_rx is not None and acc.parts_done is not None:
        gossip_ns = max(0, acc.parts_done - acc.proposal_rx)
    # decompose total exactly: clip each bucket to the remaining budget
    rem = total_ns
    buckets = {}
    for name_, val in (("gossip_wait", gossip_ns), ("verify", verify_ns),
                       ("app", app_ns), ("wal", wal_ns)):
        val = min(max(0, val), rem)
        buckets[name_] = _r(val)
        rem -= val
    # idle takes the remainder in ROUNDED space, so the five rounded
    # values sum to the rounded total exactly
    buckets["idle"] = max(0.0, round(
        _r(total_ns) - sum(buckets.values()), 6))

    return {
        "node": node,
        "height": height,
        "rounds": max((r for r, *_ in acc.steps), default=cr or 0),
        "complete": complete,
        "catchup": acc.catchup,
        "wall0_ns": acc.wall0,
        "total_s": _r(total_ns),
        "phases": phases,
        "marks": {k: (_r(v - t0h) if v is not None else None)
                  for k, v in marks_abs.items()},
        "buckets": buckets,
    }


def _pctl(xs: list[float], q: float) -> float:
    """Nearest-rank percentile over a sorted list (deterministic — no
    interpolation, so verdict JSON is stable across platforms)."""
    i = max(0, math.ceil(q * len(xs)) - 1)
    return xs[min(i, len(xs) - 1)]


def phase_stats(waterfalls: list[dict]) -> dict:
    """Aggregate completed waterfalls into per-phase p50/p99 — the
    scenario-lab verdict surface (one sample per (node, height))."""
    samples: dict[str, list[float]] = {p: [] for p in PHASES}
    samples["total"] = []
    bsamples: dict[str, list[float]] = {b: [] for b in BUCKETS}
    n = 0
    for wf in waterfalls:
        if not wf.get("complete"):
            continue
        n += 1
        samples["total"].append(wf["total_s"])
        for seg in wf["phases"]:
            samples[seg["phase"]].append(seg["dur_s"])
        for b in BUCKETS:
            bsamples[b].append(wf["buckets"][b])
    def _stats(xs):
        xs = sorted(xs)
        return {"n": len(xs),
                "p50_s": _pctl(xs, 0.50) if xs else None,
                "p99_s": _pctl(xs, 0.99) if xs else None}
    return {
        "samples": n,
        "phases": {k: _stats(v) for k, v in samples.items()},
        "buckets": {k: _stats(v) for k, v in bsamples.items()},
    }
