"""Async pub/sub event bus (reference: ``libs/pubsub/pubsub.go`` +
``types/event_bus.go``).

Subscriptions match with the full query language of ``libs/query``
(``tm.event='Tx' AND tx.height > 5 AND app.key CONTAINS 'x'``); plain
``{attr: value}`` dicts are still accepted as the equality subset.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from .query import Query


@dataclass
class Message:
    event_type: str
    data: object
    attrs: dict[str, str] = field(default_factory=dict)

    def event_map(self) -> dict[str, list[str]]:
        """The composite-key -> values map the query language evaluates
        over (reference ``types/event_bus.go`` flattens events the same
        way before matching)."""
        m = {k: [v] for k, v in self.attrs.items()}
        m["tm.event"] = [self.event_type]
        return m


@dataclass
class Subscription:
    query: object                        # Query | dict[str, str]
    queue: asyncio.Queue = field(default_factory=lambda: asyncio.Queue(256))
    unbuffered: bool = False             # guaranteed delivery (indexer)

    def matches(self, msg: Message) -> bool:
        if isinstance(self.query, Query):
            return self.query.matches(msg.event_map())
        for k, want in self.query.items():
            if k == "tm.event":
                if msg.event_type != want:
                    return False
            elif msg.attrs.get(k) != want:
                return False
        return True


class EventBus:
    """Fire-and-forget publisher; slow subscribers drop oldest (the
    reference cancels slow subscribers — dropping oldest keeps liveness
    without killing the subscription)."""

    def __init__(self):
        self._subs: dict[str, Subscription] = {}

    def subscribe(self, subscriber: str, query,
                  unbuffered: bool = False) -> Subscription:
        """``query`` is a :class:`Query`, a query string (compiled here),
        or an equality dict.  ``unbuffered=True`` gives an unbounded queue
        with no drop — for consumers that must see every event (the
        indexer; the reference's SubscribeUnbuffered in
        types/event_bus.go)."""
        if isinstance(query, str):
            query = Query.parse(query)
        sub = Subscription(query, unbuffered=unbuffered)
        if unbuffered:
            sub.queue = asyncio.Queue()
        self._subs[subscriber] = sub
        return sub

    def unsubscribe(self, subscriber: str) -> None:
        self._subs.pop(subscriber, None)

    def publish(self, event_type: str, data: object,
                attrs: dict[str, str] | None = None) -> None:
        msg = Message(event_type, data, attrs or {})
        for sub in self._subs.values():
            if sub.matches(msg):
                if not sub.unbuffered and sub.queue.full():
                    try:
                        sub.queue.get_nowait()
                    except asyncio.QueueEmpty:
                        pass
                sub.queue.put_nowait(msg)

    def num_subscribers(self) -> int:
        return len(self._subs)
