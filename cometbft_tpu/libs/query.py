"""Event query language (reference: ``libs/pubsub/query/query.go`` +
grammar ``libs/pubsub/query/syntax/``).

The reference compiles strings like::

    tm.event = 'Tx' AND tx.height > 5 AND transfer.amount CONTAINS 'uatom'
    tm.event = 'NewBlock' AND block.height <= 100
    account.created EXISTS
    tx.time >= TIME 2023-05-03T14:45:00Z
    tx.date = DATE 2023-05-03

into a conjunction of conditions evaluated against an event attribute map
``composite key -> list of string values``.  This is a clean-room
re-implementation of that grammar with the same semantics:

- conditions are AND-joined (the grammar has no OR / parentheses);
- operators: ``=  <  <=  >  >=  CONTAINS  EXISTS``;
- operands: single-quoted strings, numbers (int/float, signed),
  ``TIME <RFC3339>`` and ``DATE <YYYY-MM-DD>``;
- a condition is satisfied when ANY value of the key matches
  (``query.go`` matchEventValues): numeric conditions parse each event
  value as a number and skip unparseable ones; CONTAINS is substring;
  EXISTS tests key presence.
"""

from __future__ import annotations

import datetime as _dt
import re
from dataclasses import dataclass

__all__ = ["Query", "Condition", "QuerySyntaxError"]


class QuerySyntaxError(ValueError):
    pass


# operator tokens, longest-first so "<=" wins over "<"
_OPS = ("<=", ">=", "=", "<", ">")

_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<op><=|>=|=|<|>)
      | (?P<str>'(?:[^'\\]|\\.)*')
      | (?P<time>\d{4}-\d{2}-\d{2}
            (?:T\d{2}:\d{2}:\d{2}(?:\.\d+)?(?:Z|[+-]\d{2}:\d{2})?)?)
      | (?P<num>-?\d+(?:\.\d+)?)
      | (?P<word>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""",
    re.VERBOSE,
)

_KEYWORDS = {"AND", "CONTAINS", "EXISTS", "TIME", "DATE"}


def _tokenize(s: str) -> list[tuple[str, str]]:
    toks, pos = [], 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m or m.end() == pos:
            rest = s[pos:].strip()
            if not rest:
                break
            raise QuerySyntaxError(f"unexpected input at {rest[:20]!r}")
        pos = m.end()
        if m.group("op"):
            toks.append(("op", m.group("op")))
        elif m.group("str"):
            raw = m.group("str")[1:-1]
            toks.append(("str", raw.replace("\\'", "'").replace("\\\\", "\\")))
        elif m.group("time"):
            toks.append(("time", m.group("time")))
        elif m.group("num"):
            toks.append(("num", m.group("num")))
        else:
            w = m.group("word")
            toks.append(("kw", w) if w.upper() in _KEYWORDS and w.isupper()
                        else ("key", w))
    return toks


def _parse_time(v: str) -> _dt.datetime:
    try:
        t = _dt.datetime.fromisoformat(v.replace("Z", "+00:00"))
    except ValueError as e:
        raise QuerySyntaxError(f"bad TIME operand {v!r}") from e
    if t.tzinfo is None:
        t = t.replace(tzinfo=_dt.timezone.utc)
    return t


def _parse_date(v: str) -> _dt.datetime:
    try:
        d = _dt.date.fromisoformat(v)
    except ValueError as e:
        raise QuerySyntaxError(f"bad DATE operand {v!r}") from e
    return _dt.datetime(d.year, d.month, d.day, tzinfo=_dt.timezone.utc)


@dataclass(frozen=True)
class Condition:
    """One ``key op operand`` clause.  ``op`` is one of
    ``= < <= > >= contains exists``; ``arg`` is ``str`` (string equality /
    CONTAINS), ``int | float`` (numeric), ``datetime`` (TIME/DATE), or
    ``None`` (EXISTS)."""

    key: str
    op: str
    arg: object = None

    # -- evaluation ------------------------------------------------------

    def matches(self, values: list[str] | None) -> bool:
        if self.op == "exists":
            return values is not None
        if not values:
            return False
        if self.op == "contains":
            return any(self.arg in v for v in values)
        if isinstance(self.arg, str):
            # string operand: only "=" reaches here (grammar restriction)
            return any(v == self.arg for v in values)
        if isinstance(self.arg, _dt.datetime):
            cast = _try_time
        else:
            cast = _try_number
        for v in values:
            got = cast(v)
            if got is None:
                continue
            if self.op == "=" and got == self.arg:
                return True
            if self.op == "<" and got < self.arg:
                return True
            if self.op == "<=" and got <= self.arg:
                return True
            if self.op == ">" and got > self.arg:
                return True
            if self.op == ">=" and got >= self.arg:
                return True
        return False

    def __str__(self) -> str:
        if self.op == "exists":
            return f"{self.key} EXISTS"
        if self.op == "contains":
            return f"{self.key} CONTAINS '{self.arg}'"
        if isinstance(self.arg, _dt.datetime):
            return f"{self.key} {self.op} TIME {self.arg.isoformat()}"
        if isinstance(self.arg, str):
            return f"{self.key} {self.op} '{self.arg}'"
        return f"{self.key} {self.op} {self.arg}"


def _try_number(v: str):
    try:
        return int(v)
    except ValueError:
        try:
            return float(v)
        except ValueError:
            return None  # "5atom" is not a number; the condition skips it


def _try_time(v: str):
    try:
        return _parse_time(v)
    except QuerySyntaxError:
        return None


class Query:
    """A compiled conjunction of :class:`Condition`."""

    def __init__(self, conditions: list[Condition], source: str = ""):
        self.conditions = conditions
        self._source = source or " AND ".join(str(c) for c in conditions)

    # -- parsing ---------------------------------------------------------

    @classmethod
    def parse(cls, s: str) -> "Query":
        toks = _tokenize(s)
        conds: list[Condition] = []
        i = 0
        while i < len(toks):
            kind, val = toks[i]
            if kind != "key":
                raise QuerySyntaxError(f"expected event key, got {val!r}")
            key = val
            i += 1
            if i >= len(toks):
                raise QuerySyntaxError(f"dangling key {key!r}")
            kind, val = toks[i]
            if kind == "kw" and val == "EXISTS":
                conds.append(Condition(key, "exists"))
                i += 1
            elif kind == "kw" and val == "CONTAINS":
                i += 1
                if i >= len(toks) or toks[i][0] != "str":
                    raise QuerySyntaxError("CONTAINS needs a string operand")
                conds.append(Condition(key, "contains", toks[i][1]))
                i += 1
            elif kind == "op":
                op = val
                i += 1
                if i >= len(toks):
                    raise QuerySyntaxError(f"missing operand after {op}")
                tkind, tval = toks[i]
                if tkind == "str":
                    if op != "=":
                        raise QuerySyntaxError(
                            f"operator {op} needs a numeric or time operand")
                    conds.append(Condition(key, op, tval))
                elif tkind == "num":
                    n = float(tval) if "." in tval else int(tval)
                    conds.append(Condition(key, op, n))
                elif tkind == "kw" and tval in ("TIME", "DATE"):
                    i += 1
                    if i >= len(toks) or toks[i][0] != "time":
                        raise QuerySyntaxError(f"missing {tval} value")
                    lit = toks[i][1]
                    arg = (_parse_time(lit) if tval == "TIME"
                           else _parse_date(lit))
                    conds.append(Condition(key, op, arg))
                else:
                    raise QuerySyntaxError(f"bad operand {tval!r}")
                i += 1
            else:
                raise QuerySyntaxError(
                    f"expected operator after {key!r}, got {val!r}")
            if i < len(toks):
                kind, val = toks[i]
                if not (kind == "kw" and val == "AND"):
                    raise QuerySyntaxError(f"expected AND, got {val!r}")
                i += 1
                if i >= len(toks):
                    raise QuerySyntaxError("dangling AND")
        if not conds:
            raise QuerySyntaxError("empty query")
        return cls(conds, s)

    # -- evaluation ------------------------------------------------------

    def matches(self, events: dict[str, list[str]]) -> bool:
        return all(c.matches(events.get(c.key)) for c in self.conditions)

    def equality_clauses(self) -> dict[str, str]:
        """The ``key -> value`` map of plain string-equality conditions —
        what posting-list indexes can answer directly; the rest of the
        query post-filters."""
        return {c.key: c.arg for c in self.conditions
                if c.op == "=" and isinstance(c.arg, str)}

    def __str__(self) -> str:
        return self._source

    def __repr__(self) -> str:
        return f"Query({self._source!r})"
