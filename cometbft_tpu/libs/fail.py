"""Fail points: deterministic crash injection for recovery testing
(reference: ``internal/fail/fail.go`` — the env var names the Nth call to
``fail_point()`` at which the process dies with a distinctive exit code).

Sites live in the commit path (consensus finalize + block executor), so a
test harness can kill a node at EVERY stage boundary and assert that WAL
+ handshake recovery reaches the same chain state (the reference's
``replay_test.go`` crash matrix)."""

from __future__ import annotations

import os
import sys

ENV_VAR = "CMT_FAIL_INDEX"
EXIT_CODE = 38              # distinctive: "killed by fail point"

_index = int(os.environ.get(ENV_VAR, "-1"))
_counter = 0
_labels: list[str] = []


def fail_point(label: str) -> None:
    """Die hard (os._exit — no cleanup, no flushing, like a real crash)
    when this is the ``CMT_FAIL_INDEX``-th call in the process.

    Unarmed (the production default) this is a near-free no-op — no
    bookkeeping accumulates on the commit path."""
    if _index < 0:
        return
    global _counter
    _labels.append(label)
    my_idx = _counter
    _counter += 1
    if my_idx == _index:
        print(f"FAIL POINT {my_idx} ({label}): crashing",
              file=sys.stderr, flush=True)
        os._exit(EXIT_CODE)


def labels_seen() -> list[str]:
    return list(_labels)
