"""Metrics: counters, gauges, histograms with labels + Prometheus text
exposition (reference: ``libs/metrics/metrics.go`` wrapping go-kit, and
the generated per-subsystem ``metrics.gen.go`` files).

A process-wide default registry keeps wiring cheap: subsystems construct
their metric sets against it, the RPC server exposes ``GET /metrics``."""

from __future__ import annotations

import threading
import time
from bisect import bisect_right


class Registry:
    def __init__(self):
        self._metrics: dict[str, "_Metric"] = {}
        self._lock = threading.Lock()

    def register(self, metric: "_Metric") -> "_Metric":
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if type(existing) is not type(metric):
                    # silently handing back a Counter to code that asked
                    # for a Gauge produces AttributeErrors (or worse,
                    # wrong series) far from the offending registration
                    raise ValueError(
                        f"metric {metric.name!r} already registered as "
                        f"{type(existing).__name__}, cannot re-register "
                        f"as {type(metric).__name__}")
                return existing
            self._metrics[metric.name] = metric
            return metric

    def collect(self) -> str:
        """Prometheus text exposition format."""
        out = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            out.append(f"# HELP {m.name} {_escape_help(m.help)}")
            out.append(f"# TYPE {m.name} {m.TYPE}")
            out.extend(m.expose())
        evicted = [(m.name, m.evicted_total) for m in metrics
                   if m.evicted_total]
        if evicted:
            # synthetic series (not a registered Counter: incrementing a
            # real metric from inside another metric's eviction path
            # would re-enter the guard) so a scrape shows WHICH metric is
            # churning label sets past its budget
            out.append("# HELP metrics_label_evictions_total label sets "
                       "evicted past a metric's cardinality cap")
            out.append("# TYPE metrics_label_evictions_total counter")
            for name, n in evicted:
                out.append("metrics_label_evictions_total"
                           f'{{metric="{_escape(name)}"}} {n}')
        return "\n".join(out) + "\n"


DEFAULT = Registry()


def _escape(v) -> str:
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _escape_help(text: str) -> str:
    """HELP-line escaping per the exposition format (backslash and
    newline only — a raw multi-line help string would otherwise corrupt
    the whole scrape)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _label_str(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


# Default ceiling on distinct label sets per metric.  Per-peer labels
# (p2p telemetry) would otherwise grow the registry without bound as
# peers churn over a long-running node's lifetime; closed label sets
# (step names, channel names...) never come near it.
DEFAULT_MAX_LABEL_SETS = 512


class _Metric:
    TYPE = "untyped"

    def __init__(self, name: str, help_: str = "",
                 max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        self.name = name
        self.help = help_
        self.max_label_sets = max(1, int(max_label_sets))
        self.evicted_total = 0        # guarded by self._lock
        self._lock = threading.Lock()

    def expose(self) -> list[str]:
        return []

    def _evict_locked(self, *value_dicts: dict) -> None:
        """Drop the oldest labeled child so a new one fits the cap
        (called with self._lock held, BEFORE inserting the new key).
        The unlabeled series ``()`` is never the victim — it is the
        metric itself, not a per-entity child.  Insertion order is the
        eviction order (dicts preserve it), which approximates
        oldest-peer-first under churn."""
        primary = value_dicts[0]
        victim = None
        for k in primary:
            if k != ():
                victim = k
                break
        if victim is None:       # only the unlabeled series exists
            return
        for d in value_dicts:
            d.pop(victim, None)
        self.evicted_total += 1


class Counter(_Metric):
    TYPE = "counter"

    def __init__(self, name, help_="",
                 max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        super().__init__(name, help_, max_label_sets)
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        self._inc_key(tuple(sorted(labels.items())), amount)

    def _inc_key(self, key: tuple, amount: float) -> None:
        with self._lock:
            try:                      # common path: key exists, no guard
                self._values[key] += amount
            except KeyError:
                if len(self._values) >= self.max_label_sets:
                    self._evict_locked(self._values)
                self._values[key] = float(amount)

    def bind(self, **labels) -> "_BoundCounter":
        """Pre-resolve a label set for hot paths: ``bind(...)`` once,
        then ``.inc()`` skips the per-call label sort (worth ~3us per
        event on the vote-gossip path)."""
        return _BoundCounter(self, tuple(sorted(labels.items())))

    def value(self, **labels) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def label_sets(self) -> int:
        """Distinct label sets currently held (cardinality introspection
        for the guard's tests and the /net_info budget surface)."""
        return len(self._values)

    def expose(self):
        with self._lock:
            return [f"{self.name}{_label_str(dict(k))} {v}"
                    for k, v in sorted(self._values.items())]


class _BoundCounter:
    """A counter pre-bound to one label set (see :meth:`Counter.bind`)."""

    __slots__ = ("_c", "_key")

    def __init__(self, counter: Counter, key: tuple):
        self._c = counter
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        self._c._inc_key(self._key, amount)


class Gauge(_Metric):
    TYPE = "gauge"

    def __init__(self, name, help_="",
                 max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        super().__init__(name, help_, max_label_sets)
        self._values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        self._set_key(tuple(sorted(labels.items())), value)

    def _set_key(self, key: tuple, value: float) -> None:
        with self._lock:
            if key not in self._values and \
                    len(self._values) >= self.max_label_sets:
                self._evict_locked(self._values)
            self._values[key] = float(value)

    def add(self, amount: float, **labels) -> None:
        self._add_key(tuple(sorted(labels.items())), amount)

    def _add_key(self, key: tuple, amount: float) -> None:
        with self._lock:
            try:
                self._values[key] += amount
            except KeyError:
                if len(self._values) >= self.max_label_sets:
                    self._evict_locked(self._values)
                self._values[key] = float(amount)

    def remove(self, **labels) -> None:
        """Drop one labeled child (a disconnected peer's gauge would
        otherwise report its last value forever)."""
        with self._lock:
            self._values.pop(tuple(sorted(labels.items())), None)

    def bind(self, **labels) -> "_BoundGauge":
        """Pre-resolve a label set for hot paths (see Counter.bind)."""
        return _BoundGauge(self, tuple(sorted(labels.items())))

    def value(self, **labels) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def label_sets(self) -> int:
        return len(self._values)

    def expose(self):
        with self._lock:
            return [f"{self.name}{_label_str(dict(k))} {v}"
                    for k, v in sorted(self._values.items())]


class _BoundGauge:
    """A gauge pre-bound to one label set (see :meth:`Gauge.bind`)."""

    __slots__ = ("_g", "_key")

    def __init__(self, gauge: Gauge, key: tuple):
        self._g = gauge
        self._key = key

    def set(self, value: float) -> None:
        self._g._set_key(self._key, value)

    def add(self, amount: float) -> None:
        self._g._add_key(self._key, amount)


DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram(_Metric):
    TYPE = "histogram"

    def __init__(self, name, help_="", buckets=DEFAULT_BUCKETS,
                 max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        super().__init__(name, help_, max_label_sets)
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        self._observe_key(tuple(sorted(labels.items())), value)

    def _observe_key(self, key: tuple, value: float) -> None:
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                if len(self._counts) >= self.max_label_sets:
                    self._evict_locked(self._counts, self._sums,
                                       self._totals)
                counts = self._counts[key] = [0] * (len(self.buckets) + 1)
            # cumulative-bucket semantics: le is inclusive
            idx = bisect_right(self.buckets, value)
            if idx > 0 and self.buckets[idx - 1] == value:
                idx -= 1
            counts[min(idx, len(self.buckets))] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def bind(self, **labels) -> "_BoundHistogram":
        """Pre-resolve a label set for hot paths (see Counter.bind)."""
        return _BoundHistogram(self, tuple(sorted(labels.items())))

    def time(self, **labels):
        """Context manager measuring seconds."""
        return _Timer(self, labels)

    def count(self, **labels) -> int:
        """Total observations for a label set (programmatic consumers:
        bench output, scheduler occupancy stats)."""
        return self._totals.get(tuple(sorted(labels.items())), 0)

    def sum(self, **labels) -> float:
        """Sum of observed values for a label set."""
        return self._sums.get(tuple(sorted(labels.items())), 0.0)

    def percentile(self, q: float, **labels) -> float:
        """Approximate percentile from bucket midpoints (tests/metrics)."""
        key = tuple(sorted(labels.items()))
        counts = self._counts.get(key)
        if not counts:
            return 0.0
        total = sum(counts)
        want = q * total
        acc = 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= want:
                return self.buckets[i] if i < len(self.buckets) \
                    else self.buckets[-1]
        return self.buckets[-1]

    def expose(self):
        out = []
        with self._lock:
            for key in sorted(self._counts):
                labels = dict(key)
                acc = 0
                for i, b in enumerate(self.buckets):
                    acc += self._counts[key][i]
                    lb = dict(labels, le=str(b))
                    out.append(f"{self.name}_bucket{_label_str(lb)} {acc}")
                lb = dict(labels, le="+Inf")
                out.append(f"{self.name}_bucket{_label_str(lb)} "
                           f"{self._totals[key]}")
                out.append(f"{self.name}_sum{_label_str(labels)} "
                           f"{self._sums[key]}")
                out.append(f"{self.name}_count{_label_str(labels)} "
                           f"{self._totals[key]}")
        return out


class _BoundHistogram:
    """A histogram pre-bound to one label set (see Histogram.bind)."""

    __slots__ = ("_h", "_key")

    def __init__(self, hist: Histogram, key: tuple):
        self._h = hist
        self._key = key

    def observe(self, value: float) -> None:
        self._h._observe_key(self._key, value)


class _Timer:
    def __init__(self, hist: Histogram, labels: dict):
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self._t0, **self.labels)


def counter(name: str, help_: str = "",
            registry: Registry | None = None,
            max_label_sets: int = DEFAULT_MAX_LABEL_SETS) -> Counter:
    return (registry or DEFAULT).register(
        Counter(name, help_, max_label_sets))


def gauge(name: str, help_: str = "",
          registry: Registry | None = None,
          max_label_sets: int = DEFAULT_MAX_LABEL_SETS) -> Gauge:
    return (registry or DEFAULT).register(Gauge(name, help_, max_label_sets))


def histogram(name: str, help_: str = "", buckets=DEFAULT_BUCKETS,
              registry: Registry | None = None,
              max_label_sets: int = DEFAULT_MAX_LABEL_SETS) -> Histogram:
    return (registry or DEFAULT).register(
        Histogram(name, help_, buckets, max_label_sets))
