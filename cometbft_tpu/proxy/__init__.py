"""AppConns: four logical ABCI connections sharing one client
(reference: ``proxy/multi_app_conn.go`` — consensus, mempool, query,
snapshot)."""

from .multi_app_conn import AppConns, ClientCreator, local_client_creator

__all__ = ["AppConns", "ClientCreator", "local_client_creator"]
