"""Multiplexed application connections (reference: ``proxy/``).

The reference maintains four logical connections (consensus, mempool,
query, snapshot — ``proxy/multi_app_conn.go``) so mempool CheckTx traffic
can't head-of-line-block consensus.  Here each logical connection is its own
client instance (its own lock / socket), produced by a ClientCreator
(``proxy/client.go:16`` analogue).
"""

from __future__ import annotations

from typing import Awaitable, Callable

from ..abci.application import Application
from ..abci.client import ABCIClient, LocalClient, SocketClient

ClientCreator = Callable[[], Awaitable[ABCIClient]]


def local_client_creator(app: Application) -> ClientCreator:
    """All four connections share the app; each gets its own lock —
    UNSYNCED local semantics per connection, serialized within one."""

    async def create() -> ABCIClient:
        return LocalClient(app)

    return create


def socket_client_creator(host: str = "127.0.0.1", port: int = 26658,
                          unix_path: str | None = None) -> ClientCreator:
    async def create() -> ABCIClient:
        return await SocketClient.connect(host, port, unix_path)

    return create


def grpc_client_creator(host: str = "127.0.0.1",
                        port: int = 26658) -> ClientCreator:
    """Remote app over gRPC (``proxy/client.go`` grpc creator)."""

    async def create() -> ABCIClient:
        from ..abci.grpc import GRPCClient

        return await GRPCClient.connect(host, port)

    return create


class AppConns:
    def __init__(self, creator: ClientCreator):
        self._creator = creator
        self.consensus: ABCIClient | None = None
        self.mempool: ABCIClient | None = None
        self.query: ABCIClient | None = None
        self.snapshot: ABCIClient | None = None

    async def start(self) -> None:
        self.consensus = await self._creator()
        self.mempool = await self._creator()
        self.query = await self._creator()
        self.snapshot = await self._creator()

    async def stop(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            if c is not None:
                await c.close()
