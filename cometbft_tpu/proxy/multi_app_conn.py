"""Multiplexed application connections (reference: ``proxy/``).

The reference maintains four logical connections (consensus, mempool,
query, snapshot — ``proxy/multi_app_conn.go``) so mempool CheckTx traffic
can't head-of-line-block consensus.  Here each logical connection is its own
client instance (its own lock / socket), produced by a ClientCreator
(``proxy/client.go:16`` analogue).
"""

from __future__ import annotations

import functools
import time
from typing import Awaitable, Callable

from ..abci.application import Application
from ..abci.client import ABCIClient, LocalClient, SocketClient
from ..libs import tracing

ClientCreator = Callable[[], Awaitable[ABCIClient]]


@functools.cache
def _abci_metrics():
    from ..libs import metrics as m

    return m.histogram(
        "abci_call_seconds",
        "application call latency by logical connection and method "
        "(a slow FinalizeBlock on the consensus connection IS commit "
        "latency; a slow CheckTx on the mempool connection stalls "
        "admission)")


class TracedAppConn(ABCIClient):
    """Per-connection latency shim around a real ABCI client: every call
    lands in ``abci_call_seconds{conn,method}`` and, when tracing is on,
    a flight-recorder span — so a height timeline shows exactly how long
    the app held the consensus connection inside the commit step."""

    def __init__(self, inner: ABCIClient, conn: str, node: str = ""):
        self._inner = inner
        self._conn = conn
        self._node = node
        self._hist = _abci_metrics()

    async def call(self, method: str, **params):
        t0 = time.perf_counter()
        sp = None
        if tracing.is_enabled():
            # height attribution for the timeline: request-object calls
            # (FinalizeBlock, PrepareProposal, ...) carry it on ``req``,
            # flat calls (query, extend_vote) pass it directly
            h = params.get("height")
            if h is None:
                h = getattr(params.get("req"), "height", None)
            sp = tracing.begin("abci", "call", conn=self._conn,
                               method=method, height=h, node=self._node)
        try:
            return await self._inner.call(method, **params)
        finally:
            self._hist.observe(time.perf_counter() - t0,
                               conn=self._conn, method=method)
            tracing.finish(sp)

    async def close(self) -> None:
        await self._inner.close()


def local_client_creator(app: Application) -> ClientCreator:
    """All four connections share the app; each gets its own lock —
    UNSYNCED local semantics per connection, serialized within one."""

    async def create() -> ABCIClient:
        return LocalClient(app)

    return create


def socket_client_creator(host: str = "127.0.0.1", port: int = 26658,
                          unix_path: str | None = None) -> ClientCreator:
    async def create() -> ABCIClient:
        return await SocketClient.connect(host, port, unix_path)

    return create


def grpc_client_creator(host: str = "127.0.0.1",
                        port: int = 26658) -> ClientCreator:
    """Remote app over gRPC (``proxy/client.go`` grpc creator)."""

    async def create() -> ABCIClient:
        from ..abci.grpc import GRPCClient

        return await GRPCClient.connect(host, port)

    return create


class AppConns:
    def __init__(self, creator: ClientCreator, node: str = ""):
        self._creator = creator
        self._node = node
        self.consensus: ABCIClient | None = None
        self.mempool: ABCIClient | None = None
        self.query: ABCIClient | None = None
        self.snapshot: ABCIClient | None = None

    async def start(self) -> None:
        self.consensus = TracedAppConn(await self._creator(), "consensus",
                                       self._node)
        self.mempool = TracedAppConn(await self._creator(), "mempool",
                                     self._node)
        self.query = TracedAppConn(await self._creator(), "query",
                                   self._node)
        self.snapshot = TracedAppConn(await self._creator(), "snapshot",
                                      self._node)

    async def stop(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            if c is not None:
                await c.close()
