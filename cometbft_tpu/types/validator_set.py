"""Validator and ValidatorSet (reference: ``types/validator.go``,
``types/validator_set.go``).

Proposer selection is the reference's weighted round-robin over
*proposer priorities*: each increment adds every validator's voting power
to its priority, picks the max (ties break to the lower address), and
charges the winner the total voting power.  Priorities are centered on
their average and rescaled so the spread stays within
``2 * total_power`` — all with Go's truncated (toward-zero) integer
division, which differs from Python's floor division on negatives and is
consensus-critical (spec/consensus/proposer-selection.md).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from ..crypto import merkle
from ..crypto.keys import PubKey
from . import wire

MAX_TOTAL_VOTING_POWER = (2**63 - 1) // 8
PRIORITY_WINDOW_SIZE_FACTOR = 2


def _go_div(a: int, b: int) -> int:
    """Integer division truncating toward zero (Go semantics)."""
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


def _pubkey_proto(pk: PubKey) -> bytes:
    """cometbft.crypto.v1.PublicKey oneof: 1=ed25519, 2=secp256k1, 3=bls."""
    fld = {"ed25519": 1, "secp256k1": 2, "bls12_381": 3}[pk.type()]
    return wire.field_bytes(fld, pk.bytes(), force=True)


@dataclass
class Validator:
    pub_key: PubKey
    voting_power: int
    proposer_priority: int = 0
    _address: bytes = field(default=b"", repr=False)

    @property
    def address(self) -> bytes:
        if not self._address:
            self._address = self.pub_key.address()
        return self._address

    def copy(self) -> "Validator":
        return replace(self)

    def compare_proposer_priority(self, other: "Validator") -> "Validator":
        """Higher priority wins; ties break to the smaller address."""
        if self.proposer_priority > other.proposer_priority:
            return self
        if self.proposer_priority < other.proposer_priority:
            return other
        return self if self.address < other.address else other

    def simple_encode(self) -> bytes:
        """SimpleValidator proto for set hashing (types/validator.go)."""
        return (wire.field_message(1, _pubkey_proto(self.pub_key), force=True)
                + wire.field_varint(2, self.voting_power))


class ValidatorSet:
    """Sorted (by address) validator list + rotating proposer."""

    def __init__(self, validators: list[Validator]):
        vals = sorted((v.copy() for v in validators),
                      key=lambda v: v.address)
        if len({v.address for v in vals}) != len(vals):
            raise ValueError("duplicate validator address")
        for v in vals:
            if v.voting_power < 0:
                raise ValueError("negative voting power")
        self.validators: list[Validator] = vals
        self._total: int | None = None
        self.proposer: Validator | None = None
        if vals:
            self.increment_proposer_priority(1)

    # ----------------------------------------------------------- accessors

    def size(self) -> int:
        return len(self.validators)

    def __len__(self) -> int:
        return len(self.validators)

    def total_voting_power(self) -> int:
        if self._total is None:
            t = sum(v.voting_power for v in self.validators)
            if t > MAX_TOTAL_VOTING_POWER:
                raise ValueError("total voting power exceeds cap")
            self._total = t
        return self._total

    def get_by_address(self, addr: bytes) -> tuple[int, Validator | None]:
        lo, hi = 0, len(self.validators)
        while lo < hi:
            mid = (lo + hi) // 2
            if self.validators[mid].address < addr:
                lo = mid + 1
            else:
                hi = mid
        if lo < len(self.validators) and self.validators[lo].address == addr:
            return lo, self.validators[lo]
        return -1, None

    def get_by_index(self, idx: int) -> Validator | None:
        if 0 <= idx < len(self.validators):
            return self.validators[idx]
        return None

    def dense(self):
        """Cached columnar view for the dense VerifyCommit fast path:
        ``(pubkeys uint8 (N,32), powers int64 (N,))`` — or None when any
        validator key isn't ed25519 (mixed sets use the per-lane loop).
        Invalidated by :meth:`update_with_change_set`; validator sets are
        otherwise immutable in membership and power."""
        d = self.__dict__.get("_dense", False)
        if d is not False:
            return d
        import numpy as np

        n = len(self.validators)
        d = None
        if n and all(v.pub_key.type() == "ed25519"
                     and len(v.pub_key.bytes()) == 32
                     for v in self.validators):
            pubs = np.frombuffer(
                b"".join(v.pub_key.bytes() for v in self.validators),
                np.uint8).reshape(n, 32)
            powers = np.fromiter((v.voting_power for v in self.validators),
                                 np.int64, n)
            d = (pubs, powers)
        self.__dict__["_dense"] = d
        return d

    def bls_cohort(self) -> tuple:
        """Cached BLS membership view for the aggregate-commit fast
        path: ``(indices tuple, pubkeys tuple)`` of validators holding
        bls12_381 keys, in validator-set index order.  Empty tuples on a
        pure-Ed25519 set.  Same invalidation discipline as
        :meth:`dense` (popped by :meth:`update_with_change_set`)."""
        c = self.__dict__.get("_bls_cohort")
        if c is None:
            idx, pks = [], []
            for i, v in enumerate(self.validators):
                if v.pub_key.type() == "bls12_381":
                    idx.append(i)
                    pks.append(v.pub_key.bytes())
            c = (tuple(idx), tuple(pks))
            self.__dict__["_bls_cohort"] = c
        return c

    def has_bls(self) -> bool:
        return bool(self.bls_cohort()[0])

    def address_index(self) -> dict:
        """Cached address -> row map for the dense trusting path (same
        invalidation discipline as :meth:`dense`)."""
        m = self.__dict__.get("_addr_idx")
        if m is None:
            m = {v.address: i for i, v in enumerate(self.validators)}
            self.__dict__["_addr_idx"] = m
        return m

    def has_address(self, addr: bytes) -> bool:
        return self.get_by_address(addr)[0] >= 0

    # ------------------------------------------------------------- hashing

    def hash(self) -> bytes:
        return merkle.hash_from_byte_slices_fast(
            [v.simple_encode() for v in self.validators])

    # ------------------------------------------------- proposer rotation

    def increment_proposer_priority(self, times: int) -> None:
        if not self.validators:
            raise ValueError("empty validator set")
        if times <= 0:
            raise ValueError("times must be positive")
        diff_max = PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        self._rescale_priorities(diff_max)
        self._shift_by_avg_proposer_priority()
        proposer = None
        for _ in range(times):
            proposer = self._increment_once()
        self.proposer = proposer

    def _increment_once(self) -> Validator:
        for v in self.validators:
            v.proposer_priority += v.voting_power
        mostest = self.validators[0]
        for v in self.validators[1:]:
            mostest = mostest.compare_proposer_priority(v)
        mostest.proposer_priority -= self.total_voting_power()
        return mostest

    def _rescale_priorities(self, diff_max: int) -> None:
        if diff_max <= 0:
            return
        prios = [v.proposer_priority for v in self.validators]
        diff = max(prios) - min(prios)
        if diff > diff_max:
            ratio = (diff + diff_max - 1) // diff_max
            for v in self.validators:
                v.proposer_priority = _go_div(v.proposer_priority, ratio)

    def _shift_by_avg_proposer_priority(self) -> None:
        avg = _go_div(sum(v.proposer_priority for v in self.validators),
                      len(self.validators))
        for v in self.validators:
            v.proposer_priority -= avg

    def get_proposer(self) -> Validator:
        if self.proposer is None:
            self.proposer = self._find_proposer()
        return self.proposer

    def _find_proposer(self) -> Validator:
        mostest = self.validators[0]
        for v in self.validators[1:]:
            mostest = mostest.compare_proposer_priority(v)
        return mostest

    def copy(self) -> "ValidatorSet":
        new = ValidatorSet.__new__(ValidatorSet)
        new.validators = [v.copy() for v in self.validators]
        new._total = self._total
        new.proposer = None
        if self.proposer is not None:
            idx, _ = self.get_by_address(self.proposer.address)
            if idx >= 0:
                new.proposer = new.validators[idx]
        return new

    def copy_increment_proposer_priority(self, times: int) -> "ValidatorSet":
        c = self.copy()
        c.increment_proposer_priority(times)
        return c

    # --------------------------------------------------------- updates

    def update_with_change_set(self, changes: list[Validator]) -> None:
        """Apply validator updates/removals (voting_power 0 = remove);
        reference: types/validator_set.go UpdateWithChangeSet."""
        if not changes:
            return
        by_addr = {}
        for c in changes:
            if c.address in by_addr:
                raise ValueError("duplicate address in changes")
            by_addr[c.address] = c
        removals = [a for a, c in by_addr.items() if c.voting_power == 0]
        updates = {a: c for a, c in by_addr.items() if c.voting_power > 0}
        for c in by_addr.values():
            if c.voting_power < 0:
                raise ValueError("negative voting power in update")
        for a in removals:
            if not self.has_address(a):
                raise ValueError("removing unknown validator")

        cur = {v.address: v for v in self.validators}
        # New-validator priorities use the total *after updates but before
        # removals* (validator_set.go:470-501 tvpAfterUpdatesBeforeRemovals) —
        # removed validators' power still counts at this stage.
        projected = sum(
            (updates[a].voting_power if a in updates else v.voting_power)
            for a, v in cur.items())
        projected += sum(c.voting_power for a, c in updates.items()
                         if a not in cur)
        if projected > MAX_TOTAL_VOTING_POWER:
            raise ValueError("total voting power would exceed cap")

        for a, c in updates.items():
            if a in cur:
                cur[a].voting_power = c.voting_power
            else:
                nv = c.copy()
                # new validators start at -1.125 * projected total
                nv.proposer_priority = -(projected + (projected >> 3))
                cur[a] = nv
        for a in removals:
            del cur[a]
        if not cur:
            raise ValueError("validator set would be empty")

        self.validators = sorted(cur.values(), key=lambda v: v.address)
        self._total = None
        self.__dict__.pop("_dense", None)     # membership/powers changed
        self.__dict__.pop("_addr_idx", None)
        self.__dict__.pop("_bls_cohort", None)
        self.__dict__.pop("_bls_agg_tbl", None)   # crypto/blsagg tables
        self.__dict__.pop("_bls_dev_tbl", None)   # blsagg device-fold points
        self.total_voting_power()
        self._rescale_priorities(
            PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power())
        self._shift_by_avg_proposer_priority()
        if self.proposer is not None:
            idx, v = self.get_by_address(self.proposer.address)
            self.proposer = v if idx >= 0 else None

    def validate_basic(self) -> str | None:
        if not self.validators:
            return "validator set is empty"
        if self.proposer is None:
            return "proposer is not set"
        return None
