"""Canonical sign-bytes encoders.

The exact bytes validators sign (reference: ``types/canonical.go:57,71``,
``types/vote.go:150``, ``proto/cometbft/types/v1/canonical.proto``): a
length-prefixed proto3 encoding of CanonicalVote / CanonicalProposal /
CanonicalVoteExtension.  Any disagreement here is a consensus failure, so
the layout is hand-rolled through ``wire`` and pinned by tests against an
independently protoc-compiled schema.

Timestamps are integer nanoseconds since the Unix epoch throughout the
framework; the canonical encoding splits them into Timestamp{seconds,nanos}.
"""

from __future__ import annotations

from . import wire
from .block_id import BlockID

# SignedMsgType (proto/cometbft/types/v1/types.proto)
SIGNED_MSG_TYPE_PREVOTE = 1
SIGNED_MSG_TYPE_PRECOMMIT = 2
SIGNED_MSG_TYPE_PROPOSAL = 32


def encode_timestamp(ns: int) -> bytes:
    """google.protobuf.Timestamp {int64 seconds=1; int32 nanos=2}."""
    seconds, nanos = divmod(ns, 1_000_000_000)
    return wire.field_varint(1, seconds) + wire.field_varint(2, nanos)


def canonical_vote_sign_bytes(chain_id: str, msg_type: int, height: int,
                              round_: int, block_id: BlockID,
                              timestamp_ns: int) -> bytes:
    """CanonicalVote, length-prefixed (types/vote.go:150 VoteSignBytes).

    Fields: type=1 varint, height=2 sfixed64, round=3 sfixed64,
    block_id=4 (omitted when nil), timestamp=5 (always emitted),
    chain_id=6.
    """
    body = (wire.field_varint(1, msg_type)
            + wire.field_sfixed64(2, height)
            + wire.field_sfixed64(3, round_)
            + wire.field_message(4, block_id.encode_canonical())
            + wire.field_message(5, encode_timestamp(timestamp_ns),
                                 force=True)
            + wire.field_string(6, chain_id))
    return wire.length_prefixed(body)


class CanonicalVoteEncoder:
    """Template encoder for one (chain_id, type, height, round, block_id):
    every field except the timestamp is precomputed, so encoding the N
    sign-bytes of a commit costs N cheap concatenations instead of N full
    proto builds (~25 us -> ~1 us each; at 10k validators this is the
    difference between 250 ms and 10 ms of host work on the VerifyCommit
    latency path)."""

    __slots__ = ("_prefix", "_suffix")

    def __init__(self, chain_id: str, msg_type: int, height: int,
                 round_: int, block_id: BlockID):
        self._prefix = (wire.field_varint(1, msg_type)
                        + wire.field_sfixed64(2, height)
                        + wire.field_sfixed64(3, round_)
                        + wire.field_message(
                            4, block_id.encode_canonical()))
        self._suffix = wire.field_string(6, chain_id)

    def sign_bytes(self, timestamp_ns: int) -> bytes:
        body = (self._prefix
                + wire.field_message(5, encode_timestamp(timestamp_ns),
                                     force=True)
                + self._suffix)
        return wire.length_prefixed(body)


def _read_varint(buf: bytes, off: int) -> tuple[int, int]:
    shift = v = 0
    while True:
        b = buf[off]
        off += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, off
        shift += 7


def decode_timestamp_from_vote(sign_bytes: bytes) -> int:
    """Extract the timestamp (ns) from canonical vote sign bytes — used by
    FilePV to decide whether a re-sign request differs only by timestamp
    (privval/file.go checkVotesOnlyDifferByTimestamp does the same via
    proto decode)."""
    ln, off = _read_varint(sign_bytes, 0)
    end = off + ln
    while off < end:
        tag, off = _read_varint(sign_bytes, off)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            val, off = _read_varint(sign_bytes, off)
        elif wt == 1:
            val = int.from_bytes(sign_bytes[off:off + 8], "little")
            off += 8
        elif wt == 2:
            ln2, off = _read_varint(sign_bytes, off)
            val = sign_bytes[off:off + ln2]
            off += ln2
        else:
            raise ValueError(f"unsupported wire type {wt}")
        if field == 5:                       # timestamp submessage
            seconds = nanos = 0
            o2 = 0
            while o2 < len(val):
                t2, o2 = _read_varint(val, o2)
                v2, o2 = _read_varint(val, o2)
                if t2 >> 3 == 1:
                    seconds = v2
                elif t2 >> 3 == 2:
                    nanos = v2
            return seconds * 1_000_000_000 + nanos
    raise ValueError("no timestamp field in sign bytes")


def canonical_proposal_sign_bytes(chain_id: str, height: int, round_: int,
                                  pol_round: int, block_id: BlockID,
                                  timestamp_ns: int) -> bytes:
    """CanonicalProposal (types/canonical.go:36, proposal sign bytes)."""
    body = (wire.field_varint(1, SIGNED_MSG_TYPE_PROPOSAL)
            + wire.field_sfixed64(2, height)
            + wire.field_sfixed64(3, round_)
            + wire.field_varint(4, pol_round)
            + wire.field_message(5, block_id.encode_canonical())
            + wire.field_message(6, encode_timestamp(timestamp_ns),
                                 force=True)
            + wire.field_string(7, chain_id))
    return wire.length_prefixed(body)


def canonical_vote_extension_sign_bytes(chain_id: str, height: int,
                                        round_: int,
                                        extension: bytes) -> bytes:
    """CanonicalVoteExtension (types/vote.go VoteExtensionSignBytes)."""
    body = (wire.field_bytes(1, extension)
            + wire.field_sfixed64(2, height)
            + wire.field_sfixed64(3, round_)
            + wire.field_string(4, chain_id))
    return wire.length_prefixed(body)
