"""Header, Data, Block (reference: ``types/block.go:1-600``).

Header.hash is the merkle root of the 14 proto-encoded header fields
(types/block.go Header.Hash); Block.hash == Header.hash.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import merkle, tmhash
from . import canonical, wire
from .block_id import BlockID
from .commit import Commit

BLOCK_PROTOCOL_VERSION = 11  # block protocol (version/version.go BlockProtocol)


def _string_value(s: str) -> bytes:
    return wire.field_string(1, s)


def _bytes_value(b: bytes) -> bytes:
    return wire.field_bytes(1, b)


def _int64_value(v: int) -> bytes:
    return wire.field_varint(1, v)


@dataclass
class Header:
    chain_id: str
    height: int
    time_ns: int
    last_block_id: BlockID = field(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""
    version_block: int = BLOCK_PROTOCOL_VERSION
    version_app: int = 0

    def version_encode(self) -> bytes:
        return (wire.field_varint(1, self.version_block)
                + wire.field_varint(2, self.version_app))

    def hash(self) -> bytes:
        """Merkle root over the proto-encoded fields (types/block.go:432).

        Returns b"" if the header is incomplete (validators_hash unset), like
        the reference's nil-return."""
        if not self.validators_hash:
            return b""
        fields = [
            self.version_encode(),
            _string_value(self.chain_id),
            _int64_value(self.height),
            canonical.encode_timestamp(self.time_ns),
            self.last_block_id.encode(),
            _bytes_value(self.last_commit_hash),
            _bytes_value(self.data_hash),
            _bytes_value(self.validators_hash),
            _bytes_value(self.next_validators_hash),
            _bytes_value(self.consensus_hash),
            _bytes_value(self.app_hash),
            _bytes_value(self.last_results_hash),
            _bytes_value(self.evidence_hash),
            _bytes_value(self.proposer_address),
        ]
        return merkle.hash_from_byte_slices_fast(fields)

    def validate_basic(self) -> str | None:
        if not self.chain_id or len(self.chain_id) > 50:
            return "chain_id empty or too long"
        if self.height < 0:
            return "negative height"
        if self.height > 1 and self.last_block_id.is_nil():
            return "nil last_block_id after height 1"
        if self.proposer_address and len(self.proposer_address) != 20:
            return "invalid proposer address size"
        return None

    def encode(self) -> bytes:
        """Wire proto of the full header (for part sets / storage)."""
        return (wire.field_message(1, self.version_encode(), force=True)
                + wire.field_string(2, self.chain_id)
                + wire.field_varint(3, self.height)
                + wire.field_message(4, canonical.encode_timestamp(
                    self.time_ns), force=True)
                + wire.field_message(5, self.last_block_id.encode(),
                                     force=True)
                + wire.field_bytes(6, self.last_commit_hash)
                + wire.field_bytes(7, self.data_hash)
                + wire.field_bytes(8, self.validators_hash)
                + wire.field_bytes(9, self.next_validators_hash)
                + wire.field_bytes(10, self.consensus_hash)
                + wire.field_bytes(11, self.app_hash)
                + wire.field_bytes(12, self.last_results_hash)
                + wire.field_bytes(13, self.evidence_hash)
                + wire.field_bytes(14, self.proposer_address))


def tx_hash(tx: bytes) -> bytes:
    return tmhash.sum_sha256(tx)


@dataclass
class Data:
    txs: list[bytes] = field(default_factory=list)

    def hash(self) -> bytes:
        return merkle.hash_from_byte_slices_fast(
            [tx_hash(t) for t in self.txs])


@dataclass
class Block:
    header: Header
    data: Data = field(default_factory=Data)
    evidence: list = field(default_factory=list)
    last_commit: Commit | None = None

    def hash(self) -> bytes:
        return self.header.hash()

    def fill_hashes(self) -> None:
        """Populate derived header hashes from contents (block construction)."""
        self.header.data_hash = self.data.hash()
        if self.last_commit is not None:
            self.header.last_commit_hash = self.last_commit.hash()
        self.header.evidence_hash = merkle.hash_from_byte_slices_fast(
            [e.hash() for e in self.evidence])

    def validate_basic(self) -> str | None:
        err = self.header.validate_basic()
        if err:
            return err
        if self.header.height > 1:
            if self.last_commit is None:
                return "nil last_commit"
            err = self.last_commit.validate_basic()
            if err:
                return f"invalid last_commit: {err}"
            if self.header.last_commit_hash != self.last_commit.hash():
                return "wrong last_commit_hash"
        if self.header.data_hash != self.data.hash():
            return "wrong data_hash"
        return None

    def encode(self) -> bytes:
        """Wire proto of the block (header=1, data=2, evidence=3, commit=4)."""
        data_enc = b"".join(wire.field_bytes(1, t, force=True)
                            for t in self.data.txs)
        ev_enc = b"".join(wire.field_message(1, e.encode(), force=True)
                          for e in self.evidence)
        out = (wire.field_message(1, self.header.encode(), force=True)
               + wire.field_message(2, data_enc, force=True)
               + wire.field_message(3, ev_enc, force=True))
        if self.last_commit is not None:
            out += wire.field_message(4, self.last_commit.encode(),
                                      force=True)
        return out
