"""Commit verification: the exact seam where the TPU backend enters.

Mirrors ``types/validation.go:13-360``:

- ``VerifyCommit``            — checks every signature (commit AND nil votes),
                                tallies only for-block power, needs > 2/3.
- ``VerifyCommitLight``       — verifies commit-flag sigs only, stops once
                                > 2/3 is tallied (blocksync/light hot path).
- ``VerifyCommitLightTrusting`` — validators looked up BY ADDRESS in a
                                (possibly different) trusted set, threshold =
                                trust-level fraction of the trusted total.
- ``...AllSignatures`` variants (evidence verification) — no early exit.

All paths route signatures through ``crypto.batch.BatchVerifier``; the
backend ("auto"/"tpu"/"cpu") comes from ``set_default_backend`` — the
reference's config.Config-driven selection point.  Where the reference
falls back to one-by-one verification for mixed key types
(``shouldBatchVerify``), our device verifier routes non-ed25519 lanes to
CPU inside the batch instead.

Commits carrying a BLS aggregate (``types/commit.py``) verify the whole
folded cohort up front — two pairings via ``crypto/blsagg``, regardless
of cohort size — and the per-lane machinery then only sees the Ed25519
cohort plus any individual BLS lanes (NIL votes sign a different
message and never fold).
"""

from __future__ import annotations

from fractions import Fraction

from ..crypto import batch as cryptobatch
from .commit import Commit
from .validator_set import ValidatorSet

_DEFAULT_BACKEND = "auto"


def set_default_backend(backend: str) -> None:
    """Select the signature backend ("auto" | "tpu" | "jax" | "cpu")."""
    global _DEFAULT_BACKEND
    _DEFAULT_BACKEND = backend


def get_default_backend() -> str:
    return _DEFAULT_BACKEND


class CommitVerificationError(Exception):
    pass


class ErrInvalidCommit(CommitVerificationError):
    pass


class ErrNotEnoughVotingPower(CommitVerificationError):
    pass


class ErrInvalidSignature(CommitVerificationError):
    def __init__(self, idx: int, msg: str = ""):
        self.idx = idx
        super().__init__(msg or f"wrong signature (#{idx})")


def _check_commit_basics(vals: ValidatorSet, commit: Commit, height: int,
                         block_id) -> None:
    if vals.size() != commit.size():
        raise ErrInvalidCommit(
            f"invalid commit: {commit.size()} sigs for {vals.size()} vals")
    if height != commit.height:
        raise ErrInvalidCommit(
            f"invalid commit height {commit.height}, want {height}")
    if block_id != commit.block_id:
        raise ErrInvalidCommit("invalid commit: wrong block ID")


def _verify_aggregate(chain_id: str, vals: ValidatorSet, commit: Commit,
                      *, lookup_by_address: bool) -> tuple[frozenset, int]:
    """Verify the commit's BLS aggregate lane block up front; the main
    loop then TALLIES the proven lanes without re-verifying them.

    Returns ``(proven aggregate lane indices, pre-tallied power)``.  The
    lane set is empty when the commit carries no aggregate, or (trusting
    path only) when the signer cohort could not be resolved in the
    trusted set, in which case the aggregate lanes simply contribute no
    power.  The power is the proven lanes' summed voting power on the
    index path — where lanes align 1:1 with the valset, so no duplicate
    bookkeeping is possible and the caller's loop can skip AGGREGATE
    lanes entirely — and 0 on the trusting path, whose loop still owns
    the by-address tally and duplicate detection.

    Index path (``lookup_by_address=False``): lanes align with the
    valset, so the structure is fully checkable — any malformation
    raises ErrInvalidCommit, a failing aggregate signature raises
    ErrInvalidSignature on the first aggregate lane.  The structural
    checks (every lane a cohort member, addresses matching the valset)
    run vectorized over numpy columns cached per commit (``_agg_np``)
    and per valset (``blsagg.valset_table``) — at 10k validators the
    per-lane object loop was costing more than the pairings.

    Trusting path: signers resolve BY ADDRESS into a possibly different
    trusted set, all-or-nothing.  If every signer resolves to a BLS
    validator there, the aggregate is verified against those pubkeys
    (a bad signature then raises — a commit carrying a provably false
    aggregate is invalid, not merely unproven).  If ANY signer is
    unknown, the cohort's power cannot be attributed and the whole
    aggregate is skipped — exactly how the trusting loop skips
    individual lanes from unknown validators.
    """
    if not commit.has_aggregate():
        return frozenset(), 0
    err = commit._validate_aggregate()
    if err:
        raise ErrInvalidCommit(f"invalid commit: {err}")
    from ..crypto import blsagg as _blsagg

    lanes = commit.aggregate_lanes()
    power = 0
    if not lookup_by_address:
        import numpy as np

        try:
            tbl = _blsagg.valset_table(vals)
        except ValueError:
            raise ErrInvalidSignature(
                lanes[0], "invalid BLS cohort pubkey in valset")
        n = len(commit.signatures)
        if tbl.cohort_mask.shape[0] != n:
            raise ErrInvalidCommit(
                f"invalid commit: {n} sigs for {vals.size()} vals")
        cached = commit.__dict__.get("_agg_np")
        if cached is None:
            mask = np.zeros((n,), np.bool_)
            lane_addrs = np.zeros((len(lanes), 20), np.uint8)
            for r, idx in enumerate(lanes):
                mask[idx] = True
                addr = commit.signatures[idx].validator_address
                if len(addr) == 20:
                    lane_addrs[r] = np.frombuffer(addr, np.uint8)
            cached = (mask, lane_addrs)
            commit.__dict__["_agg_np"] = cached
        mask, lane_addrs = cached
        stray = mask & ~tbl.cohort_mask
        if bool(stray.any()):
            raise ErrInvalidCommit(
                f"aggregate lane {int(np.nonzero(stray)[0][0])} "
                "is not a BLS validator")
        addr_bad = (tbl.addr_mat[mask] != lane_addrs).any(axis=1)
        if bool(addr_bad.any()):
            raise ErrInvalidCommit(
                f"aggregate lane {lanes[int(np.nonzero(addr_bad)[0][0])]} "
                "address does not match valset")
        power = int(tbl.powers[mask].sum())
        signers = mask
    else:
        signers = []
        for idx in lanes:
            vi, val = vals.get_by_address(
                commit.signatures[idx].validator_address)
            if vi < 0 or val.pub_key.type() != "bls12_381":
                return frozenset(), 0       # unattributable: contributes 0
            signers.append(vi)
    from ..libs import tracing

    sp = tracing.begin("crypto.agg", "verify", height=commit.height,
                       lanes=len(lanes)) if tracing.is_enabled() else None
    ok = _blsagg.verify_commit_aggregate(
        vals, signers, commit.aggregate_sign_bytes(chain_id),
        commit.agg_signature)
    tracing.finish(sp, ok=ok)
    if not ok:
        raise ErrInvalidSignature(
            lanes[0], f"wrong aggregate signature (lanes {lanes})")
    return frozenset(lanes), power


def _verify(chain_id: str, vals: ValidatorSet, commit: Commit,
            voting_power_needed: int, *, count_all: bool,
            verify_nil_sigs: bool, lookup_by_address: bool,
            backend: str | None, use_cache: bool = True) -> None:
    """Shared tally+verify core (types/validation.go verifyCommitBatch).

    count_all=False allows early exit once the tally clears the threshold
    (remaining signatures are NOT verified — VerifyCommitLight semantics).

    use_cache consults (and seeds) the verified-signature cache
    (``crypto/scheduler``): a commit signature already verified as a
    gossiped vote costs a dict hit instead of a scalar multiplication.
    The evidence-path ``*AllSignatures`` variants pass False — evidence
    verification never trusts the cache.
    """
    from ..crypto import scheduler as _vsched

    # BLS aggregate lanes verify up front (one pairing check covers the
    # whole cohort); the loop below only tallies the proven lanes.  The
    # dense paths never see aggregates: any valset with a BLS member has
    # vals.dense() None, and each dense core also guards explicitly.
    agg_proven, agg_power = _verify_aggregate(
        chain_id, vals, commit, lookup_by_address=lookup_by_address)
    if (agg_power > voting_power_needed and not count_all
            and not verify_nil_sigs):
        # VerifyCommitLight semantics: the proven aggregate alone clears
        # the threshold, remaining lanes need not be verified — the
        # O(1)-pairing fast path never enters the per-lane loop at all
        return

    if not lookup_by_address:
        if _dense_verify(chain_id, vals, commit, voting_power_needed,
                         count_all=count_all,
                         verify_nil_sigs=verify_nil_sigs,
                         backend=backend or _DEFAULT_BACKEND,
                         use_cache=use_cache):
            return
    elif not verify_nil_sigs:
        if _dense_verify_trusting(chain_id, vals, commit,
                                  voting_power_needed,
                                  count_all=count_all,
                                  backend=backend or _DEFAULT_BACKEND,
                                  use_cache=use_cache):
            return
    bv = cryptobatch.create_batch_verifier(backend or _DEFAULT_BACKEND)
    lanes: list[int] = []          # commit-sig indices added to the batch
    seeds: list[tuple] = []        # lanes to seed into the cache on success
    cache_on = use_cache and _vsched.cache_active()
    tally = agg_power
    seen: set[bytes] = set()

    for idx, cs in enumerate(commit.signatures):
        if cs.is_absent():
            continue
        if not cs.is_commit() and not verify_nil_sigs:
            # ignoreSig runs BEFORE lookup/dup bookkeeping
            # (validation.go:243-266): a NIL sig then a COMMIT sig from
            # the same address is legal on the trusting path
            continue
        if cs.is_aggregate():
            if not lookup_by_address:
                # index path: pre-tallied into agg_power (every lane is
                # proven — _verify_aggregate raises otherwise)
                if not count_all and tally > voting_power_needed:
                    break
                continue
            if idx not in agg_proven:
                continue   # trusting path, unresolved cohort: no power
        if lookup_by_address:
            vi, val = vals.get_by_address(cs.validator_address)
            if vi < 0:
                continue
            if cs.validator_address in seen:
                raise ErrInvalidCommit(
                    f"duplicate validator {cs.validator_address.hex()} in commit")
            seen.add(cs.validator_address)
        else:
            val = vals.get_by_index(idx)
        if cs.is_aggregate():
            # proven by the up-front aggregate verification: tally only
            tally += val.voting_power
            if not count_all and tally > voting_power_needed:
                break
            continue
        # BLS validators' individual lanes (NIL votes, or a cohort too
        # small to fold) sign the zero-timestamp aggregation domain
        msg = commit.vote_sign_bytes_for(chain_id, idx,
                                         val.pub_key.type())
        if cache_on and _vsched.cache_lookup(val.pub_key.bytes(), msg,
                                             cs.signature):
            pass            # verified before (gossip/scheduler): free lane
        else:
            bv.add(val.pub_key, msg, cs.signature)
            lanes.append(idx)
            if cache_on:
                seeds.append((val.pub_key.bytes(), msg, cs.signature))
        if cs.is_commit():
            tally += val.voting_power
        if not count_all and tally > voting_power_needed:
            break

    if len(bv) > 0:
        ok, oks = bv.verify()
        if not ok:
            first_bad = lanes[oks.index(False)]
            raise ErrInvalidSignature(first_bad)
        for s in seeds:
            _vsched.cache_seed(*s)
    if tally <= voting_power_needed:
        raise ErrNotEnoughVotingPower(
            f"tallied {tally} <= needed {voting_power_needed}")


def _cache_split(pubs_sel, sigs_sel, msgs, lens):
    """Per-lane verified-signature cache consult for dense rows: returns
    ``(hit mask, keys)`` where keys feed :func:`cache_seed` after a
    successful verification.  Key material matches the object path
    exactly — raw 32-byte pubkey, exact sign bytes, 64-byte signature —
    so gossip-time seeds hit commit-time lookups."""
    import numpy as np

    from ..crypto import scheduler as _vsched

    k = pubs_sel.shape[0]
    mask = np.zeros((k,), bool)
    keys: list[tuple] = []
    for i in range(k):
        key = (pubs_sel[i].tobytes(), msgs[i, :int(lens[i])].tobytes(),
               sigs_sel[i].tobytes())
        keys.append(key)
        mask[i] = _vsched.cache_lookup(*key)
    return mask, keys


def _dense_verify(chain_id: str, vals: ValidatorSet, commit: Commit,
                  needed: int, *, count_all: bool, verify_nil_sigs: bool,
                  backend: str, use_cache: bool = True) -> bool:
    """Vectorized VerifyCommit core: columnar valset/commit views + the
    native sign-bytes builder + one dense batch dispatch.  At 10k
    validators this cuts the host side from ~60 ms of per-lane Python to
    ~3 ms (the BASELINE <5 ms p50 headline needs the host share small).

    Returns True when it fully handled verification (raising on bad sigs
    or insufficient power), False when not applicable — mixed key types,
    odd signature sizes, or no native encoder — and the caller runs the
    per-lane loop.  Semantics mirror the loop exactly, including Light's
    early exit after the lane that clears the threshold."""
    import numpy as np

    from ..crypto import _native_ed25519 as nat

    if not count_all and verify_nil_sigs:
        # no caller uses this combination; the early-exit cumsum below
        # would count nil-vote power toward the threshold (the loop only
        # tallies commit lanes) — refuse rather than miscount
        return False
    if commit.has_aggregate():
        # aggregate lanes tally through the loop path (any valset with a
        # BLS member has dense() None anyway; this guards the malformed
        # all-Ed25519-commit-with-aggregate case into the strict loop)
        return False
    dense = vals.dense()
    cols = commit.dense_columns()
    if dense is None or cols is None or not nat.available():
        return False
    pubs, powers = dense
    flags, ts, sigmat = cols
    if len(flags) != len(powers):
        return False                   # size mismatch: let the loop raise
    from .commit import BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT

    commit_mask = flags == BLOCK_ID_FLAG_COMMIT
    if count_all:
        if verify_nil_sigs:
            scope = np.nonzero(flags != BLOCK_ID_FLAG_ABSENT)[0]
        else:
            scope = np.nonzero(commit_mask)[0]
        tally = int(powers[scope][commit_mask[scope]].sum()) if scope.size \
            else 0
    else:
        scope, tally = _dense_light_scope(powers, flags, needed)
    if scope.size:
        built = _dense_build_rows(chain_id, commit, ts, flags, scope)
        if built is None:
            return False
        msgs, lens = built
        pubs_sel = np.ascontiguousarray(pubs[scope])
        sigs_sel = np.ascontiguousarray(sigmat[scope])
        from ..crypto import scheduler as _vsched

        if use_cache and _vsched.dense_cache_active():
            mask, keys = _cache_split(pubs_sel, sigs_sel, msgs, lens)
            live = np.nonzero(~mask)[0]
        else:
            keys = None
            live = np.arange(scope.size)
        if live.size:
            res = cryptobatch.verify_dense(
                backend, np.ascontiguousarray(pubs_sel[live]),
                np.ascontiguousarray(sigs_sel[live]),
                np.ascontiguousarray(msgs[live]), lens[live],
                valset_pubs=pubs, scope=scope[live])
            if res is None:
                return False
            ok, oks = res
            if not ok:
                raise ErrInvalidSignature(
                    int(scope[live[np.nonzero(~oks)[0][0]]]))
            if keys is not None:
                for j in live:
                    _vsched.cache_seed(*keys[j])
    if tally <= needed:
        raise ErrNotEnoughVotingPower(
            f"tallied {tally} <= needed {needed}")
    return True


def _dense_verify_trusting(chain_id: str, vals: ValidatorSet,
                           commit: Commit, needed: int, *,
                           count_all: bool, backend: str,
                           use_cache: bool = True) -> bool:
    """Dense core of VerifyCommitLightTrusting: commit sigs resolve BY
    ADDRESS into a (possibly different) trusted set.  Lane selection
    stays a (cheap) Python loop — dict lookups, duplicate detection and
    the early exit are inherently sequential — but sign-bytes building
    and signature verification go through the same native dense
    machinery as the index-aligned paths.  Returns True when fully
    handled; False -> caller runs the object loop."""
    import numpy as np

    from ..crypto import _native_ed25519 as nat
    from .commit import BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT

    if commit.has_aggregate():
        return False                   # aggregate lanes: loop path only
    dense = vals.dense()
    cols = commit.dense_columns()
    if dense is None or cols is None or not nat.available():
        return False
    pubs, powers = dense
    flags, ts, sigmat = cols
    addrs = commit.dense_addresses()
    aidx = vals.address_index()
    seen: set[bytes] = set()
    scope: list[int] = []            # commit-sig lanes to verify
    rows: list[int] = []             # their rows in the trusted set
    tally = 0
    for i, addr in enumerate(addrs):
        fl = int(flags[i])
        # non-commit sigs are ignored BEFORE the lookup/dup bookkeeping,
        # matching the reference's ignoreSig ordering in
        # verifyCommitBatch (validation.go:243-266) — a NIL sig followed
        # by a COMMIT sig from the same address is legal there
        if fl != BLOCK_ID_FLAG_COMMIT:
            continue
        row = aidx.get(addr)
        if row is None:
            continue
        if addr in seen:
            raise ErrInvalidCommit(
                f"duplicate validator {addr.hex()} in commit")
        seen.add(addr)
        scope.append(i)
        rows.append(row)
        tally += int(powers[row])
        if not count_all and tally > needed:
            break
    if scope:
        scope_arr = np.asarray(scope)
        built = _dense_build_rows(chain_id, commit, ts, flags, scope_arr)
        if built is None:
            return False
        msgs, lens = built
        rows_arr = np.asarray(rows)
        pubs_sel = np.ascontiguousarray(pubs[rows_arr])
        sigs_sel = np.ascontiguousarray(sigmat[scope_arr])
        from ..crypto import scheduler as _vsched

        if use_cache and _vsched.dense_cache_active():
            mask, keys = _cache_split(pubs_sel, sigs_sel, msgs, lens)
            live = np.nonzero(~mask)[0]
        else:
            keys = None
            live = np.arange(scope_arr.size)
        if live.size:
            res = cryptobatch.verify_dense(
                backend, np.ascontiguousarray(pubs_sel[live]),
                np.ascontiguousarray(sigs_sel[live]),
                np.ascontiguousarray(msgs[live]), lens[live],
                valset_pubs=pubs, scope=rows_arr[live])
            if res is None:
                return False
            ok, oks = res
            if not ok:
                raise ErrInvalidSignature(
                    scope[int(live[np.nonzero(~oks)[0][0]])])
            if keys is not None:
                for j in live:
                    _vsched.cache_seed(*keys[j])
    if tally <= needed:
        raise ErrNotEnoughVotingPower(
            f"tallied {tally} <= needed {needed}")
    return True


def _dense_light_scope(powers, flags, needed):
    """VerifyCommitLight lane selection, shared by the single-commit and
    cross-block dense paths so the consensus-critical early-exit math
    lives in exactly one place: commit-flag lanes up to AND including the
    lane whose power pushes the tally past ``needed`` (the loop breaks
    after adding that lane).  Returns ``(scope indices, tally)``."""
    import numpy as np

    from .commit import BLOCK_ID_FLAG_COMMIT

    scope = np.nonzero(flags == BLOCK_ID_FLAG_COMMIT)[0]
    cum = np.cumsum(powers[scope]) if scope.size else np.zeros(0)
    over = np.nonzero(cum > needed)[0]
    if over.size:
        return scope[:int(over[0]) + 1], int(cum[int(over[0])])
    return scope, int(cum[-1]) if cum.size else 0


def _dense_build_rows(chain_id: str, commit: Commit, ts, flags, scope):
    """Native sign-bytes rows for the selected lanes of one commit, or
    None when the native builder is unavailable."""
    from ..crypto import _native_ed25519 as nat

    pre_c, pre_n, post = commit.sign_bytes_templates(chain_id)
    return nat.build_vote_sign_bytes(pre_c, pre_n, post, ts[scope],
                                     flags[scope])


def VerifyCommit(chain_id: str, vals: ValidatorSet, block_id, height: int,
                 commit: Commit, backend: str | None = None) -> None:
    """All signatures verified; > 2/3 of total power must be for block_id
    (types/validation.go:28)."""
    _check_commit_basics(vals, commit, height, block_id)
    needed = vals.total_voting_power() * 2 // 3
    _verify(chain_id, vals, commit, needed, count_all=True,
            verify_nil_sigs=True, lookup_by_address=False, backend=backend)


def VerifyCommitLight(chain_id: str, vals: ValidatorSet, block_id,
                      height: int, commit: Commit,
                      backend: str | None = None,
                      use_cache: bool = True) -> None:
    """Commit-flag signatures only, early exit at > 2/3
    (types/validation.go:63 — blocksync/light-client hot path).

    Callers verifying commits that were never gossiped to this node
    (light-client backfill, blocksync fallbacks) pass use_cache=False:
    with zero possible hits, the per-lane cache consult is pure
    overhead."""
    _check_commit_basics(vals, commit, height, block_id)
    needed = vals.total_voting_power() * 2 // 3
    _verify(chain_id, vals, commit, needed, count_all=False,
            verify_nil_sigs=False, lookup_by_address=False, backend=backend,
            use_cache=use_cache)


def VerifyCommitLightAllSignatures(chain_id: str, vals: ValidatorSet,
                                   block_id, height: int, commit: Commit,
                                   backend: str | None = None) -> None:
    """types/validation.go:96 (evidence path: no early exit, and no
    verified-signature cache — evidence rests on fresh verification)."""
    _check_commit_basics(vals, commit, height, block_id)
    needed = vals.total_voting_power() * 2 // 3
    _verify(chain_id, vals, commit, needed, count_all=True,
            verify_nil_sigs=False, lookup_by_address=False, backend=backend,
            use_cache=False)


def VerifyCommitLightTrusting(chain_id: str, vals: ValidatorSet,
                              commit: Commit,
                              trust_level: Fraction = Fraction(1, 3),
                              backend: str | None = None,
                              count_all: bool = False,
                              use_cache: bool = True) -> None:
    """Trust-level verification against a possibly different validator set,
    lookup by address (types/validation.go:127 — light-client skipping
    verification)."""
    if trust_level <= 0 or trust_level > 1:
        raise ValueError("trust level must be in (0, 1]")
    needed = (vals.total_voting_power() * trust_level.numerator
              // trust_level.denominator)
    _verify(chain_id, vals, commit, needed, count_all=count_all,
            verify_nil_sigs=False, lookup_by_address=True, backend=backend,
            use_cache=use_cache)


class ErrBatchItemInvalid(CommitVerificationError):
    """A commit inside a multi-commit batch failed; ``item`` indexes the
    offending entry so blocksync can redo exactly that height."""

    def __init__(self, item: int, height: int, cause: Exception):
        self.item = item
        self.height = height
        self.cause = cause
        super().__init__(f"commit #{item} (height {height}): {cause}")


def verify_commits_light_batched(chain_id: str, vals: ValidatorSet,
                                 items: list, backend: str | None = None,
                                 patient: bool = False,
                                 use_cache: bool = False) -> int:
    """VerifyCommitLight over MANY commits sharing one validator set in a
    single device batch — the blocksync cross-block batching seam
    (reference verifies one commit per block sequentially at
    ``internal/blocksync/reactor.go:495``; here K blocks' commits fill one
    TPU dispatch, BASELINE configs[4]).

    ``items`` is a list of ``(block_id, height, commit)``.  Returns the
    number of signatures proven (dispatched + cache-proven).  Raises
    ErrBatchItemInvalid naming the first offending item.  ``patient`` is
    the blocksync accumulator's staging mode: the device dispatch queues
    behind an in-flight window instead of host-falling-back
    (``crypto/batch._device_call``).

    ``use_cache`` consults and seeds the verified-signature dedup cache
    (``crypto/scheduler``) per lane: a commit re-verified for the second
    client (the light-serving tier's hot-anchor workload) costs dict
    hits instead of scalar multiplications.  Default False — blocksync
    and light-client callers verify commits never gossiped here, and
    evidence-grade callers must never trust a cache.

    Demux contract for callers applying per item: when the raised
    error's ``cause`` is :class:`ErrInvalidSignature`, every item BEFORE
    ``err.item`` had all its selected lanes proven valid (lane order is
    item order; the dispatch computes every verdict before raising on
    the first bad lane, and cache-proven lanes hold positive verdicts by
    construction).  Any other cause is a pre-dispatch basics/tally
    failure — earlier items were NOT signature-checked and need their
    own verification pass before being trusted.
    """
    from ..crypto import scheduler as _vsched

    n = _dense_verify_commits_batched(chain_id, vals, items,
                                      backend or _DEFAULT_BACKEND,
                                      patient=patient, use_cache=use_cache)
    if n is not None:
        return n
    bv = cryptobatch.create_batch_verifier(backend or _DEFAULT_BACKEND)
    lanes: list[tuple[int, int]] = []      # (item idx, commit-sig idx)
    seeds: list[tuple] = []
    cache_on = use_cache and _vsched.cache_active()
    n_hits = 0
    needed = vals.total_voting_power() * 2 // 3
    for k, (block_id, height, commit) in enumerate(items):
        try:
            _check_commit_basics(vals, commit, height, block_id)
            # index path: raises on any aggregate problem, so every
            # AGGREGATE lane is proven — its power is pre-tallied
            _, agg_power = _verify_aggregate(chain_id, vals, commit,
                                             lookup_by_address=False)
        except CommitVerificationError as e:
            raise ErrBatchItemInvalid(k, height, e) from e
        tally = agg_power
        if tally > needed:
            continue       # aggregate alone clears the threshold
        for idx, cs in enumerate(commit.signatures):
            if not cs.is_commit():
                continue
            if cs.is_aggregate():
                continue   # pre-tallied above
            val = vals.get_by_index(idx)
            msg = commit.vote_sign_bytes_for(chain_id, idx,
                                             val.pub_key.type())
            if cache_on and _vsched.cache_lookup(val.pub_key.bytes(), msg,
                                                 cs.signature):
                n_hits += 1            # proven before: free lane
            else:
                bv.add(val.pub_key, msg, cs.signature)
                lanes.append((k, idx))
                if cache_on:
                    seeds.append((val.pub_key.bytes(), msg, cs.signature))
            tally += val.voting_power
            if tally > needed:
                break
        if tally <= needed:
            raise ErrBatchItemInvalid(
                k, height,
                ErrNotEnoughVotingPower(f"tallied {tally} <= {needed}"))
    if len(bv) > 0:
        ok, oks = bv.verify()
        if not ok:
            k, idx = lanes[oks.index(False)]
            raise ErrBatchItemInvalid(k, items[k][1],
                                      ErrInvalidSignature(idx))
        for s in seeds:
            _vsched.cache_seed(*s)
    return len(lanes) + n_hits


def _dense_verify_commits_batched(chain_id: str, vals: ValidatorSet,
                                  items: list, backend: str,
                                  patient: bool = False,
                                  use_cache: bool = False) -> int | None:
    """Vectorized core of :func:`verify_commits_light_batched`: per-commit
    basics/tally checks in item order (matching the loop's raise order),
    then ONE dense verification over every selected lane of every commit
    (minus verified-sig-cache hits when ``use_cache``).  Returns the lane
    count, or None when not applicable (caller loops)."""
    import numpy as np

    from ..crypto import _native_ed25519 as nat
    from ..crypto import scheduler as _vsched

    dense = vals.dense()
    if dense is None or not nat.available():
        return None
    if any(item[2].has_aggregate() for item in items):
        return None                    # aggregate lanes: loop path only
    pubs, powers = dense
    needed = vals.total_voting_power() * 2 // 3
    sel_pubs, sel_sigs, sel_msgs, sel_lens = [], [], [], []
    sel_scope = []
    lanes: list[tuple[int, int]] = []
    stride = 0
    for k, (block_id, height, commit) in enumerate(items):
        try:
            _check_commit_basics(vals, commit, height, block_id)
        except CommitVerificationError as e:
            raise ErrBatchItemInvalid(k, height, e) from e
        cols = commit.dense_columns()
        if cols is None:
            return None
        flags, ts, sigmat = cols
        scope, tally = _dense_light_scope(powers, flags, needed)
        if tally <= needed:
            raise ErrBatchItemInvalid(
                k, height,
                ErrNotEnoughVotingPower(f"tallied {tally} <= {needed}"))
        built = _dense_build_rows(chain_id, commit, ts, flags, scope)
        if built is None:
            return None
        msgs, lens = built
        sel_pubs.append(pubs[scope])
        sel_sigs.append(sigmat[scope])
        sel_msgs.append(msgs)
        sel_lens.append(lens)
        sel_scope.append(scope)
        stride = max(stride, msgs.shape[1])
        lanes.extend((k, int(i)) for i in scope)
    if not lanes:
        return 0
    # strides are equal in practice (same chain_id; fixed-width height);
    # pad defensively if a template ever differs
    sel_msgs = [m if m.shape[1] == stride else np.pad(
        m, ((0, 0), (0, stride - m.shape[1]))) for m in sel_msgs]
    pubs_all = np.ascontiguousarray(np.concatenate(sel_pubs))
    sigs_all = np.ascontiguousarray(np.concatenate(sel_sigs))
    msgs_all = np.ascontiguousarray(np.concatenate(sel_msgs))
    lens_all = np.concatenate(sel_lens)
    scope_all = np.concatenate(sel_scope)
    keys = None
    if use_cache and _vsched.cache_active():
        # per-lane dedup-cache consult (same key material as the single-
        # commit dense paths): hit lanes hold positive verdicts and drop
        # out of the dispatch — a hot anchor commit re-verified for the
        # k-th light client costs k-1 dict sweeps, not k dispatches.
        # Gated on cache_active (not dense_cache_active): opt-in callers
        # are the serving tier, whose FIRST verification must seed.
        mask, keys = _cache_split(pubs_all, sigs_all, msgs_all, lens_all)
        live = np.nonzero(~mask)[0]
    else:
        live = np.arange(len(lanes))
    if live.size:
        res = cryptobatch.verify_dense(
            backend, np.ascontiguousarray(pubs_all[live]),
            np.ascontiguousarray(sigs_all[live]),
            np.ascontiguousarray(msgs_all[live]), lens_all[live],
            valset_pubs=pubs, scope=scope_all[live],
            patient=patient)
        if res is None:
            return None
        ok, oks = res
        if not ok:
            k, idx = lanes[int(live[np.nonzero(~oks)[0][0]])]
            raise ErrBatchItemInvalid(k, items[k][1],
                                      ErrInvalidSignature(idx))
        if keys is not None:
            for j in live:
                _vsched.cache_seed(*keys[int(j)])
    return len(lanes)


def VerifyCommitLightTrustingAllSignatures(chain_id: str, vals: ValidatorSet,
                                           commit: Commit,
                                           trust_level: Fraction = Fraction(1, 3),
                                           backend: str | None = None) -> None:
    """types/validation.go:182 (evidence path: no cache, see above)."""
    VerifyCommitLightTrusting(chain_id, vals, commit, trust_level,
                              backend=backend, count_all=True,
                              use_cache=False)
