"""Commit verification: the exact seam where the TPU backend enters.

Mirrors ``types/validation.go:13-360``:

- ``VerifyCommit``            — checks every signature (commit AND nil votes),
                                tallies only for-block power, needs > 2/3.
- ``VerifyCommitLight``       — verifies commit-flag sigs only, stops once
                                > 2/3 is tallied (blocksync/light hot path).
- ``VerifyCommitLightTrusting`` — validators looked up BY ADDRESS in a
                                (possibly different) trusted set, threshold =
                                trust-level fraction of the trusted total.
- ``...AllSignatures`` variants (evidence verification) — no early exit.

All paths route signatures through ``crypto.batch.BatchVerifier``; the
backend ("auto"/"tpu"/"cpu") comes from ``set_default_backend`` — the
reference's config.Config-driven selection point.  Where the reference
falls back to one-by-one verification for mixed key types
(``shouldBatchVerify``), our device verifier routes non-ed25519 lanes to
CPU inside the batch instead.
"""

from __future__ import annotations

from fractions import Fraction

from ..crypto import batch as cryptobatch
from .commit import Commit
from .validator_set import ValidatorSet

_DEFAULT_BACKEND = "auto"


def set_default_backend(backend: str) -> None:
    """Select the signature backend ("auto" | "tpu" | "jax" | "cpu")."""
    global _DEFAULT_BACKEND
    _DEFAULT_BACKEND = backend


def get_default_backend() -> str:
    return _DEFAULT_BACKEND


class CommitVerificationError(Exception):
    pass


class ErrInvalidCommit(CommitVerificationError):
    pass


class ErrNotEnoughVotingPower(CommitVerificationError):
    pass


class ErrInvalidSignature(CommitVerificationError):
    def __init__(self, idx: int, msg: str = ""):
        self.idx = idx
        super().__init__(msg or f"wrong signature (#{idx})")


def _check_commit_basics(vals: ValidatorSet, commit: Commit, height: int,
                         block_id) -> None:
    if vals.size() != commit.size():
        raise ErrInvalidCommit(
            f"invalid commit: {commit.size()} sigs for {vals.size()} vals")
    if height != commit.height:
        raise ErrInvalidCommit(
            f"invalid commit height {commit.height}, want {height}")
    if block_id != commit.block_id:
        raise ErrInvalidCommit("invalid commit: wrong block ID")


def _verify(chain_id: str, vals: ValidatorSet, commit: Commit,
            voting_power_needed: int, *, count_all: bool,
            verify_nil_sigs: bool, lookup_by_address: bool,
            backend: str | None) -> None:
    """Shared tally+verify core (types/validation.go verifyCommitBatch).

    count_all=False allows early exit once the tally clears the threshold
    (remaining signatures are NOT verified — VerifyCommitLight semantics).
    """
    bv = cryptobatch.create_batch_verifier(backend or _DEFAULT_BACKEND)
    lanes: list[int] = []          # commit-sig indices added to the batch
    tally = 0
    seen: set[bytes] = set()

    for idx, cs in enumerate(commit.signatures):
        if cs.is_absent():
            continue
        if lookup_by_address:
            vi, val = vals.get_by_address(cs.validator_address)
            if vi < 0:
                continue
            if cs.validator_address in seen:
                raise ErrInvalidCommit(
                    f"duplicate validator {cs.validator_address.hex()} in commit")
            seen.add(cs.validator_address)
        else:
            val = vals.get_by_index(idx)
        if not cs.is_commit() and not verify_nil_sigs:
            continue
        bv.add(val.pub_key, commit.vote_sign_bytes(chain_id, idx),
               cs.signature)
        lanes.append(idx)
        if cs.is_commit():
            tally += val.voting_power
        if not count_all and tally > voting_power_needed:
            break

    if len(bv) > 0:
        ok, oks = bv.verify()
        if not ok:
            first_bad = lanes[oks.index(False)]
            raise ErrInvalidSignature(first_bad)
    if tally <= voting_power_needed:
        raise ErrNotEnoughVotingPower(
            f"tallied {tally} <= needed {voting_power_needed}")


def VerifyCommit(chain_id: str, vals: ValidatorSet, block_id, height: int,
                 commit: Commit, backend: str | None = None) -> None:
    """All signatures verified; > 2/3 of total power must be for block_id
    (types/validation.go:28)."""
    _check_commit_basics(vals, commit, height, block_id)
    needed = vals.total_voting_power() * 2 // 3
    _verify(chain_id, vals, commit, needed, count_all=True,
            verify_nil_sigs=True, lookup_by_address=False, backend=backend)


def VerifyCommitLight(chain_id: str, vals: ValidatorSet, block_id,
                      height: int, commit: Commit,
                      backend: str | None = None) -> None:
    """Commit-flag signatures only, early exit at > 2/3
    (types/validation.go:63 — blocksync/light-client hot path)."""
    _check_commit_basics(vals, commit, height, block_id)
    needed = vals.total_voting_power() * 2 // 3
    _verify(chain_id, vals, commit, needed, count_all=False,
            verify_nil_sigs=False, lookup_by_address=False, backend=backend)


def VerifyCommitLightAllSignatures(chain_id: str, vals: ValidatorSet,
                                   block_id, height: int, commit: Commit,
                                   backend: str | None = None) -> None:
    """types/validation.go:96 (evidence path: no early exit)."""
    _check_commit_basics(vals, commit, height, block_id)
    needed = vals.total_voting_power() * 2 // 3
    _verify(chain_id, vals, commit, needed, count_all=True,
            verify_nil_sigs=False, lookup_by_address=False, backend=backend)


def VerifyCommitLightTrusting(chain_id: str, vals: ValidatorSet,
                              commit: Commit,
                              trust_level: Fraction = Fraction(1, 3),
                              backend: str | None = None,
                              count_all: bool = False) -> None:
    """Trust-level verification against a possibly different validator set,
    lookup by address (types/validation.go:127 — light-client skipping
    verification)."""
    if trust_level <= 0 or trust_level > 1:
        raise ValueError("trust level must be in (0, 1]")
    needed = (vals.total_voting_power() * trust_level.numerator
              // trust_level.denominator)
    _verify(chain_id, vals, commit, needed, count_all=count_all,
            verify_nil_sigs=False, lookup_by_address=True, backend=backend)


class ErrBatchItemInvalid(CommitVerificationError):
    """A commit inside a multi-commit batch failed; ``item`` indexes the
    offending entry so blocksync can redo exactly that height."""

    def __init__(self, item: int, height: int, cause: Exception):
        self.item = item
        self.height = height
        self.cause = cause
        super().__init__(f"commit #{item} (height {height}): {cause}")


def verify_commits_light_batched(chain_id: str, vals: ValidatorSet,
                                 items: list, backend: str | None = None
                                 ) -> int:
    """VerifyCommitLight over MANY commits sharing one validator set in a
    single device batch — the blocksync cross-block batching seam
    (reference verifies one commit per block sequentially at
    ``internal/blocksync/reactor.go:495``; here K blocks' commits fill one
    TPU dispatch, BASELINE configs[4]).

    ``items`` is a list of ``(block_id, height, commit)``.  Returns the
    number of signatures verified.  Raises ErrBatchItemInvalid naming the
    first offending item.
    """
    bv = cryptobatch.create_batch_verifier(backend or _DEFAULT_BACKEND)
    lanes: list[tuple[int, int]] = []      # (item idx, commit-sig idx)
    needed = vals.total_voting_power() * 2 // 3
    for k, (block_id, height, commit) in enumerate(items):
        try:
            _check_commit_basics(vals, commit, height, block_id)
        except CommitVerificationError as e:
            raise ErrBatchItemInvalid(k, height, e) from e
        tally = 0
        for idx, cs in enumerate(commit.signatures):
            if not cs.is_commit():
                continue
            val = vals.get_by_index(idx)
            bv.add(val.pub_key, commit.vote_sign_bytes(chain_id, idx),
                   cs.signature)
            lanes.append((k, idx))
            tally += val.voting_power
            if tally > needed:
                break
        if tally <= needed:
            raise ErrBatchItemInvalid(
                k, height,
                ErrNotEnoughVotingPower(f"tallied {tally} <= {needed}"))
    if len(bv) > 0:
        ok, oks = bv.verify()
        if not ok:
            k, idx = lanes[oks.index(False)]
            raise ErrBatchItemInvalid(k, items[k][1],
                                      ErrInvalidSignature(idx))
    return len(lanes)


def VerifyCommitLightTrustingAllSignatures(chain_id: str, vals: ValidatorSet,
                                           commit: Commit,
                                           trust_level: Fraction = Fraction(1, 3),
                                           backend: str | None = None) -> None:
    """types/validation.go:182 (evidence path)."""
    VerifyCommitLightTrusting(chain_id, vals, commit, trust_level,
                              backend=backend, count_all=True)
