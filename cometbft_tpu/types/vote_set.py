"""VoteSet: the 2/3-majority accumulator (reference: ``types/vote_set.go``).

One VoteSet per (height, round, type).  Tracks one canonical vote per
validator, per-block tallies, and promotes a BlockID to +2/3 majority.
Conflicting votes (same validator, different block) surface as
``ConflictingVoteError`` — the raw material of DuplicateVoteEvidence — and
are additionally tracked when a peer has claimed (SetPeerMaj23) that the
conflicting block has a majority.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..libs.bits import BitArray
from .block_id import BlockID
from .commit import (BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT,
                     BLOCK_ID_FLAG_NIL, Commit, CommitSig, ExtendedCommit,
                     ExtendedCommitSig)
from .validator_set import ValidatorSet
from .vote import PRECOMMIT_TYPE, PREVOTE_TYPE, Vote


class VoteSetError(Exception):
    pass


@dataclass
class ConflictingVoteError(Exception):
    existing: Vote
    new: Vote

    def __str__(self):
        return (f"conflicting votes from validator "
                f"{self.new.validator_address.hex()}")


class _BlockVotes:
    __slots__ = ("peer_maj23", "bit_array", "votes", "sum")

    def __init__(self, peer_maj23: bool, n: int):
        self.peer_maj23 = peer_maj23
        self.bit_array = BitArray(n)
        self.votes: list[Vote | None] = [None] * n
        self.sum = 0

    def add_verified(self, idx: int, vote: Vote, power: int):
        if self.votes[idx] is None:
            self.bit_array.set_index(idx, True)
            self.votes[idx] = vote
            self.sum += power


class VoteSet:
    def __init__(self, chain_id: str, height: int, round_: int,
                 signed_msg_type: int, val_set: ValidatorSet,
                 extensions_enabled: bool = False):
        if signed_msg_type not in (PREVOTE_TYPE, PRECOMMIT_TYPE):
            raise VoteSetError("invalid vote type")
        if extensions_enabled and signed_msg_type != PRECOMMIT_TYPE:
            raise VoteSetError("extensions on non-precommit vote set")
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.type = signed_msg_type
        self.val_set = val_set
        self.extensions_enabled = extensions_enabled
        n = val_set.size()
        self.votes_bit_array = BitArray(n)
        self.votes: list[Vote | None] = [None] * n
        self.sum = 0
        self.maj23: BlockID | None = None
        self.votes_by_block: dict[bytes, _BlockVotes] = {}
        self.peer_maj23s: dict[str, BlockID] = {}

    # ------------------------------------------------------------------ add

    def add_vote(self, vote: Vote) -> bool:
        """Returns True if the vote was added; raises on invalid/conflict
        (types/vote_set.go:158 AddVote)."""
        if vote is None:
            raise VoteSetError("nil vote")
        idx = vote.validator_index
        if (vote.height != self.height or vote.round != self.round
                or vote.type != self.type):
            raise VoteSetError(
                f"expected {self.height}/{self.round}/{self.type}, got "
                f"{vote.height}/{vote.round}/{vote.type}")
        val = self.val_set.get_by_index(idx)
        if val is None:
            raise VoteSetError(f"validator index {idx} out of range")
        if val.address != vote.validator_address:
            raise VoteSetError("validator address does not match index")

        existing = self.votes[idx]
        if existing is not None:
            if existing.block_id == vote.block_id:
                if existing.signature == vote.signature:
                    return False              # duplicate
                raise VoteSetError("same block, different signature")
            # conflicting vote — verify, maybe track, raise for evidence.
            # The verification deliberately bypasses the verified-sig
            # cache: an equivocation proof that slashes a validator must
            # rest on a fresh scalar multiplication, never a cache entry.
            if not self._verify(vote, val, use_cache=False):
                raise VoteSetError("invalid signature on conflicting vote")
            self._maybe_track_conflict(vote, val)
            raise ConflictingVoteError(existing, vote)

        if not self._verify(vote, val):
            raise VoteSetError("invalid vote signature")

        self.votes[idx] = vote
        self.votes_bit_array.set_index(idx, True)
        self.sum += val.voting_power
        bv = self._get_or_make_block_votes(vote.block_id)
        bv.add_verified(idx, vote, val.voting_power)
        self._maybe_promote_maj23(vote.block_id, bv)
        return True

    def _verify(self, vote: Vote, val, *, use_cache: bool = True) -> bool:
        """Signature check for one gossiped vote — the steady-state hot
        path.  Routed through the verified-signature cache
        (``crypto/scheduler``): the consensus reactor pre-verifies
        gossiped votes in coalesced micro-batches, so by the time the
        single-writer handler gets here the verdict is usually a cache
        hit.  With no scheduler registered this is a plain direct
        verification, byte-for-byte the old behavior."""
        from ..crypto import scheduler as _vsched

        check = _vsched.verify_cached if use_cache \
            else _vsched.verify_uncached
        # sign bytes follow the signer's key type: BLS validators sign
        # the zero-timestamp aggregation domain (Vote.sign_bytes_for)
        sb = vote.sign_bytes_for(self.chain_id, val.pub_key.type())
        if self.extensions_enabled and vote.type == PRECOMMIT_TYPE:
            if not check(val.pub_key, sb, vote.signature):
                return False
            if vote.block_id.is_nil():
                # nil precommits carry no extension to require
                # (vote.go VerifyVoteAndExtension skips the check)
                return True
            return check(val.pub_key,
                         vote.extension_sign_bytes(self.chain_id),
                         vote.extension_signature)
        if vote.extension_signature and not self.extensions_enabled:
            return False
        return check(val.pub_key, sb, vote.signature)

    def _get_or_make_block_votes(self, block_id: BlockID) -> _BlockVotes:
        key = block_id.key()
        bv = self.votes_by_block.get(key)
        if bv is None:
            bv = _BlockVotes(False, self.val_set.size())
            self.votes_by_block[key] = bv
        return bv

    def _maybe_track_conflict(self, vote: Vote, val):
        bv = self.votes_by_block.get(vote.block_id.key())
        if bv is not None and bv.peer_maj23:
            bv.add_verified(vote.validator_index, vote, val.voting_power)
            # the tracked block can cross +2/3 through conflicting votes too
            # (vote_set.go addVerifiedVote promotes on this same path)
            self._maybe_promote_maj23(vote.block_id, bv)

    def _maybe_promote_maj23(self, block_id: BlockID, bv: _BlockVotes):
        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        if bv.sum >= quorum and self.maj23 is None:
            self.maj23 = block_id
            # copy block votes into canonical slots (conflict resolution)
            for i, v in enumerate(bv.votes):
                if v is not None:
                    self.votes[i] = v

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """A peer claims +2/3 for block_id (types/vote_set.go SetPeerMaj23)."""
        if peer_id in self.peer_maj23s:
            if self.peer_maj23s[peer_id] != block_id:
                raise VoteSetError("peer already sent a different maj23")
            return
        self.peer_maj23s[peer_id] = block_id
        bv = self.votes_by_block.get(block_id.key())
        if bv is not None:
            bv.peer_maj23 = True
        else:
            nv = _BlockVotes(True, self.val_set.size())
            self.votes_by_block[block_id.key()] = nv

    # -------------------------------------------------------------- queries

    def two_thirds_majority(self) -> tuple[BlockID | None, bool]:
        return self.maj23, self.maj23 is not None

    def has_two_thirds_majority(self) -> bool:
        return self.maj23 is not None

    def has_two_thirds_any(self) -> bool:
        return self.sum > self.val_set.total_voting_power() * 2 // 3

    def has_all(self) -> bool:
        return self.sum == self.val_set.total_voting_power()

    def bit_array(self) -> BitArray:
        return self.votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID) -> BitArray | None:
        bv = self.votes_by_block.get(block_id.key())
        return bv.bit_array.copy() if bv else None

    def get_by_index(self, idx: int) -> Vote | None:
        return self.votes[idx] if 0 <= idx < len(self.votes) else None

    def get_by_address(self, addr: bytes) -> Vote | None:
        idx, _ = self.val_set.get_by_address(addr)
        return self.get_by_index(idx) if idx >= 0 else None

    # --------------------------------------------------------------- commit

    def make_commit(self) -> Commit:
        """Commit from a +2/3 precommit set (types/vote_set.go
        MakeCommit), with the BLS for-block cohort folded into one
        aggregate signature + signer bitmap (``aggregate_commit`` — the
        fold is deterministic, so replays stay byte-identical)."""
        from .commit import aggregate_commit

        return aggregate_commit(self.make_extended_commit().to_commit(),
                                self.val_set)

    def make_extended_commit(self) -> ExtendedCommit:
        if self.type != PRECOMMIT_TYPE:
            raise VoteSetError("cannot make commit from prevote set")
        if self.maj23 is None:
            raise VoteSetError("no +2/3 majority")
        sigs = []
        for i, v in enumerate(self.votes):
            if v is None:
                sigs.append(ExtendedCommitSig())
                continue
            flag = (BLOCK_ID_FLAG_COMMIT if v.block_id == self.maj23
                    else BLOCK_ID_FLAG_NIL if v.block_id.is_nil()
                    else BLOCK_ID_FLAG_ABSENT)
            if flag == BLOCK_ID_FLAG_ABSENT:
                # vote for a different block: treated as absent in the commit
                sigs.append(ExtendedCommitSig())
                continue
            cs = CommitSig(flag, v.validator_address, v.timestamp_ns,
                           v.signature)
            sigs.append(ExtendedCommitSig(cs, v.extension,
                                          v.extension_signature))
        return ExtendedCommit(self.height, self.round, self.maj23, sigs)

    def __str__(self):
        return (f"VoteSet{{h={self.height} r={self.round} t={self.type} "
                f"sum={self.sum} maj23={self.maj23}}}")
