"""GenesisDoc (reference: ``types/genesis.go``): chain bootstrap document."""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field

from ..crypto.keys import PubKey
from .params import ConsensusParams, default_consensus_params
from .validator_set import Validator, ValidatorSet

MAX_CHAIN_ID_LEN = 50


class GenesisError(Exception):
    pass


@dataclass
class GenesisValidator:
    pub_key: PubKey
    power: int
    name: str = ""
    # proof of possession, REQUIRED for bls12_381 keys (rogue-key
    # defense for aggregate commits): validate_and_complete refuses a
    # BLS genesis key whose proof is missing or fails pop_verify
    pop: bytes = b""


@dataclass
class GenesisDoc:
    chain_id: str
    genesis_time_ns: int = 0
    initial_height: int = 1
    consensus_params: ConsensusParams = field(
        default_factory=default_consensus_params)
    validators: list[GenesisValidator] = field(default_factory=list)
    app_hash: bytes = b""
    app_state: bytes = b"{}"

    def validate_and_complete(self) -> None:
        """types/genesis.go ValidateAndComplete."""
        if not self.chain_id:
            raise GenesisError("genesis doc must include chain_id")
        if len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise GenesisError("chain_id too long")
        if self.initial_height < 1:
            raise GenesisError("initial_height must be >= 1")
        err = self.consensus_params.validate()
        if err:
            raise GenesisError(err)
        for v in self.validators:
            if v.power < 0:
                raise GenesisError("validator power cannot be negative")
        if any(v.pub_key.type() == "bls12_381" for v in self.validators):
            # consensus-split guard: BLS validator keys require either a
            # standard-ciphersuite backend or an explicit closed-network
            # opt-in (see crypto/bls12381.check_validator_backend)
            from ..crypto import bls12381 as _bls

            err = _bls.check_validator_backend()
            if err:
                raise GenesisError(err)
            # rogue-key gate: basic-ciphersuite aggregation over the
            # shared zero-timestamp message is forgeable unless every
            # admitted BLS key proves possession of its secret
            for v in self.validators:
                if v.pub_key.type() != "bls12_381":
                    continue
                if not v.pop:
                    raise GenesisError(
                        f"genesis validator {v.name or v.pub_key!r} has a "
                        "bls12_381 key but no proof of possession ('pop')")
                if not _bls.pop_verify(v.pub_key.bytes(), v.pop):
                    raise GenesisError(
                        f"genesis validator {v.name or v.pub_key!r}: "
                        "bls12_381 proof of possession failed to verify")

    def validator_set(self) -> ValidatorSet:
        return ValidatorSet([Validator(v.pub_key, v.power)
                             for v in self.validators])

    # ------------------------------------------------------------- json io

    def to_json(self) -> str:
        return json.dumps({
            "chain_id": self.chain_id,
            "genesis_time_ns": self.genesis_time_ns,
            "initial_height": self.initial_height,
            "validators": [{
                "pub_key": {"type": v.pub_key.type(),
                            "value": base64.b64encode(
                                v.pub_key.bytes()).decode()},
                "power": v.power,
                "name": v.name,
                **({"pop": v.pop.hex()} if v.pop else {}),
            } for v in self.validators],
            "app_hash": self.app_hash.hex(),
            "app_state": self.app_state.decode("utf-8", "replace"),
            "consensus_params": {
                "block": {"max_bytes": self.consensus_params.block.max_bytes,
                          "max_gas": self.consensus_params.block.max_gas},
                "evidence": {
                    "max_age_num_blocks":
                        self.consensus_params.evidence.max_age_num_blocks,
                    "max_age_duration_ns":
                        self.consensus_params.evidence.max_age_duration_ns,
                    "max_bytes": self.consensus_params.evidence.max_bytes,
                },
                "validator": {
                    "pub_key_types":
                        self.consensus_params.validator.pub_key_types,
                },
                "version": {"app": self.consensus_params.version.app},
                "feature": {
                    "vote_extensions_enable_height":
                        self.consensus_params.feature
                            .vote_extensions_enable_height,
                    "pbts_enable_height":
                        self.consensus_params.feature.pbts_enable_height,
                },
                "synchrony": {
                    "precision_ns":
                        self.consensus_params.synchrony.precision_ns,
                    "message_delay_ns":
                        self.consensus_params.synchrony.message_delay_ns,
                },
            },
        }, indent=2)

    @classmethod
    def from_json(cls, s: str) -> "GenesisDoc":
        d = json.loads(s)
        params = default_consensus_params()
        cp = d.get("consensus_params", {})

        def load_into(obj, section: str):
            for k, v in cp.get(section, {}).items():
                if hasattr(obj, k):
                    setattr(obj, k, v)

        load_into(params.block, "block")
        load_into(params.evidence, "evidence")
        load_into(params.validator, "validator")
        load_into(params.version, "version")
        load_into(params.feature, "feature")
        load_into(params.synchrony, "synchrony")
        from ..crypto.keys import pub_key_from_type_bytes

        vals = []
        for v in d.get("validators", []):
            try:
                key = pub_key_from_type_bytes(
                    v["pub_key"]["type"],
                    base64.b64decode(v["pub_key"]["value"]))
            except ValueError as e:
                raise GenesisError(f"bad genesis validator key: {e}") from e
            try:
                pop = bytes.fromhex(v.get("pop", ""))
            except ValueError as e:
                raise GenesisError(f"bad genesis validator pop: {e}") from e
            vals.append(GenesisValidator(key, int(v["power"]),
                                         v.get("name", ""), pop))
        doc = cls(chain_id=d["chain_id"],
                  genesis_time_ns=d.get("genesis_time_ns", 0),
                  initial_height=d.get("initial_height", 1),
                  consensus_params=params, validators=vals,
                  app_hash=bytes.fromhex(d.get("app_hash", "")),
                  app_state=d.get("app_state", "{}").encode())
        doc.validate_and_complete()
        return doc

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "GenesisDoc":
        with open(path) as f:
            return cls.from_json(f.read())
