"""On-chain consensus parameters (reference: ``types/params.go``).

Includes the ABCI-2.0 ``FeatureParams`` height-gated activation of vote
extensions and PBTS (types/params.go:82-99), and PBTS ``SynchronyParams``
(types/params.go:121-129).
"""

from __future__ import annotations

from dataclasses import dataclass, field

BLOCK_PART_SIZE_BYTES = 65536        # types/params.go:23
MAX_BLOCK_SIZE_BYTES = 100 * 1024 * 1024


@dataclass
class BlockParams:
    max_bytes: int = 4194304           # 4 MB (types/params.go:159)
    max_gas: int = 10_000_000          # (types/params.go:160)

    def validate(self) -> str | None:
        if self.max_bytes == 0 or self.max_bytes < -1:
            return "block.max_bytes must be -1 or positive"
        if self.max_bytes > MAX_BLOCK_SIZE_BYTES:
            return "block.max_bytes too big"
        if self.max_gas < -1:
            return "block.max_gas must be >= -1"
        return None


@dataclass
class EvidenceParams:
    max_age_num_blocks: int = 100_000
    max_age_duration_ns: int = 48 * 3600 * 1_000_000_000
    max_bytes: int = 1024 * 1024

    def validate(self) -> str | None:
        if self.max_age_num_blocks <= 0:
            return "evidence.max_age_num_blocks must be positive"
        if self.max_age_duration_ns <= 0:
            return "evidence.max_age_duration must be positive"
        return None


@dataclass
class ValidatorParams:
    pub_key_types: list[str] = field(default_factory=lambda: ["ed25519"])

    def validate(self) -> str | None:
        if not self.pub_key_types:
            return "validator.pub_key_types must not be empty"
        return None


@dataclass
class VersionParams:
    app: int = 0


@dataclass
class FeatureParams:
    """Height-gated feature activation; 0 = disabled (types/params.go:82)."""

    vote_extensions_enable_height: int = 0
    pbts_enable_height: int = 0

    def vote_extensions_enabled(self, height: int) -> bool:
        h = self.vote_extensions_enable_height
        return h > 0 and height >= h

    def pbts_enabled(self, height: int) -> bool:
        h = self.pbts_enable_height
        return h > 0 and height >= h


@dataclass
class SynchronyParams:
    """PBTS bounds (types/params.go:121)."""

    precision_ns: int = 505_000_000
    message_delay_ns: int = 15_000_000_000

    def in_timely_bounds(self, proposal_time_ns: int, recv_time_ns: int,
                         round_: int) -> bool:
        """Proposal timeliness check with 10%/round message-delay back-off
        (internal/consensus/state.go:1364-1376 analogue)."""
        delay = self.message_delay_ns
        for _ in range(min(round_, 100)):
            delay = delay * 11 // 10
        lhs = proposal_time_ns - self.precision_ns
        rhs = proposal_time_ns + delay + self.precision_ns
        return lhs <= recv_time_ns <= rhs


@dataclass
class ConsensusParams:
    block: BlockParams = field(default_factory=BlockParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)
    validator: ValidatorParams = field(default_factory=ValidatorParams)
    version: VersionParams = field(default_factory=VersionParams)
    feature: FeatureParams = field(default_factory=FeatureParams)
    synchrony: SynchronyParams = field(default_factory=SynchronyParams)

    def validate(self) -> str | None:
        for part in (self.block, self.evidence, self.validator):
            err = part.validate()
            if err:
                return err
        return None

    def hash(self) -> bytes:
        """Params hash pinned into Header.consensus_hash."""
        from ..crypto import tmhash
        from . import wire

        enc = (wire.field_varint(1, self.block.max_bytes)
               + wire.field_varint(2, self.block.max_gas, force=True)
               + wire.field_varint(3, self.evidence.max_age_num_blocks)
               + wire.field_varint(4, self.version.app)
               + wire.field_varint(5, self.feature.vote_extensions_enable_height)
               + wire.field_varint(6, self.feature.pbts_enable_height))
        return tmhash.sum_sha256(enc)


def default_consensus_params() -> ConsensusParams:
    return ConsensusParams()
