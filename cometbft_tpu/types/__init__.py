"""Core domain types: the shared vocabulary of every layer above crypto.

Mirrors the reference's ``types/`` package (SURVEY.md §2.2): Block, Header,
Vote, Commit/ExtendedCommit, ValidatorSet, PartSet, canonical sign bytes,
params, evidence — with commit verification routed through the TPU-backed
``crypto.batch.BatchVerifier`` seam.
"""

from .block_id import BlockID, PartSetHeader
from .commit import (BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT,
                     BLOCK_ID_FLAG_NIL, Commit, CommitSig, ExtendedCommit,
                     ExtendedCommitSig)
from .header import Block, Data, Header
from .params import ConsensusParams, default_consensus_params
from .validator_set import Validator, ValidatorSet
from .vote import (PRECOMMIT_TYPE, PREVOTE_TYPE, PROPOSAL_TYPE, Proposal,
                   Vote)
from .validation import (VerifyCommit, VerifyCommitLight,
                         VerifyCommitLightAllSignatures,
                         VerifyCommitLightTrusting,
                         VerifyCommitLightTrustingAllSignatures)

__all__ = [
    "BlockID", "PartSetHeader", "Commit", "CommitSig", "ExtendedCommit",
    "ExtendedCommitSig", "Block", "Data", "Header", "ConsensusParams",
    "default_consensus_params", "Validator", "ValidatorSet", "Vote",
    "Proposal", "PREVOTE_TYPE", "PRECOMMIT_TYPE", "PROPOSAL_TYPE",
    "BLOCK_ID_FLAG_ABSENT", "BLOCK_ID_FLAG_COMMIT", "BLOCK_ID_FLAG_NIL",
    "VerifyCommit", "VerifyCommitLight", "VerifyCommitLightTrusting",
    "VerifyCommitLightAllSignatures",
    "VerifyCommitLightTrustingAllSignatures",
]
