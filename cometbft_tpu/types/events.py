"""Event kinds and queries (reference: ``types/events.go:19-38,151``)."""

from __future__ import annotations

EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_NEW_BLOCK_EVENTS = "NewBlockEvents"
EVENT_NEW_ROUND = "NewRound"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_TX = "Tx"
EVENT_VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_LOCK = "Lock"
EVENT_POLKA = "Polka"
EVENT_RELOCK = "Relock"
EVENT_TIMEOUT_PROPOSE = "TimeoutPropose"
EVENT_TIMEOUT_WAIT = "TimeoutWait"
EVENT_VOTE = "Vote"
EVENT_PROPOSAL_BLOCK_PART = "ProposalBlockPart"

EVENT_TYPE_KEY = "tm.event"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"
BLOCK_HEIGHT_KEY = "block.height"


def query_for_event(event_type: str) -> str:
    """Prebuilt subscription query string (types/events.go:151)."""
    return f"{EVENT_TYPE_KEY}='{event_type}'"
