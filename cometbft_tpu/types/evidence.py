"""Evidence of Byzantine behavior (reference: ``types/evidence.go``).

Two kinds, as in the reference: ``DuplicateVoteEvidence`` (equivocation —
two signed votes for the same height/round/type but different blocks,
``types/evidence.go:36``) and ``LightClientAttackEvidence`` (a conflicting
light block with validator overlap, ``types/evidence.go:210``).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass, field

from ..crypto import tmhash
from . import wire
from .validator_set import ValidatorSet
from .vote import Vote


class EvidenceError(Exception):
    pass


class EvidenceNotApplicableError(EvidenceError):
    """Evidence this node cannot currently judge (expired, from a height
    below its block base / pruned validator sets, or no state yet).  Its
    own type so the gossip reactor can DROP it without punishing the
    sender: a freshly statesync'd node lacking old blocks must not ban
    honest peers re-gossiping legitimate pending evidence."""


class Evidence(ABC):
    @abstractmethod
    def height(self) -> int: ...

    @abstractmethod
    def time_ns(self) -> int: ...

    @abstractmethod
    def hash(self) -> bytes: ...

    @abstractmethod
    def encode(self) -> bytes: ...

    @abstractmethod
    def validate_basic(self) -> str | None: ...

    @abstractmethod
    def abci_kind(self) -> str: ...


@dataclass
class DuplicateVoteEvidence(Evidence):
    vote_a: Vote
    vote_b: Vote
    total_voting_power: int = 0
    validator_power: int = 0
    timestamp_ns: int = 0

    @classmethod
    def from_votes(cls, vote1: Vote, vote2: Vote, block_time_ns: int,
                   val_set: ValidatorSet) -> "DuplicateVoteEvidence":
        """Orders votes lexically by BlockID key (types/evidence.go:66)."""
        if vote1 is None or vote2 is None or val_set is None:
            raise EvidenceError("missing vote or validator set")
        idx, val = val_set.get_by_address(vote1.validator_address)
        if idx < 0:
            raise EvidenceError("validator not in set")
        a, b = sorted((vote1, vote2), key=lambda v: v.block_id.key())
        return cls(vote_a=a, vote_b=b,
                   total_voting_power=val_set.total_voting_power(),
                   validator_power=val.voting_power,
                   timestamp_ns=block_time_ns)

    def height(self) -> int:
        return self.vote_a.height

    def time_ns(self) -> int:
        return self.timestamp_ns

    def encode(self) -> bytes:
        return (wire.field_message(1, self.vote_a.encode(), force=True)
                + wire.field_message(2, self.vote_b.encode(), force=True)
                + wire.field_varint(3, self.total_voting_power)
                + wire.field_varint(4, self.validator_power)
                + wire.field_varint(5, self.timestamp_ns))

    def hash(self) -> bytes:
        return tmhash.sum_sha256(b"duplicate-vote" + self.encode())

    def validate_basic(self) -> str | None:
        a, b = self.vote_a, self.vote_b
        if a is None or b is None:
            return "missing vote"
        if a.block_id.key() >= b.block_id.key():
            return "votes not ordered by block id"
        for v in (a, b):
            err = v.validate_basic()
            if err:
                return f"invalid vote: {err}"
        if (a.height, a.round, a.type) != (b.height, b.round, b.type):
            return "votes from different height/round/type"
        if a.validator_address != b.validator_address:
            return "votes from different validators"
        if a.block_id == b.block_id:
            return "votes for the same block"
        return None

    def abci_kind(self) -> str:
        return "DUPLICATE_VOTE"


@dataclass
class LightClientAttackEvidence(Evidence):
    """Conflicting light block seen by a light client
    (types/evidence.go:210).  ``conflicting_block`` is a (header, commit,
    validator_set) triple — typed loosely to avoid a circular import with
    the light package."""

    conflicting_header_hash: bytes
    conflicting_height: int
    common_height: int
    byzantine_validators: list = field(default_factory=list)
    total_voting_power: int = 0
    timestamp_ns: int = 0
    conflicting_block: object = None

    def height(self) -> int:
        return self.common_height

    def time_ns(self) -> int:
        return self.timestamp_ns

    def encode(self) -> bytes:
        return (wire.field_bytes(1, self.conflicting_header_hash)
                + wire.field_varint(2, self.conflicting_height)
                + wire.field_varint(3, self.common_height)
                + wire.field_varint(4, self.total_voting_power)
                + wire.field_varint(5, self.timestamp_ns))

    def hash(self) -> bytes:
        return tmhash.sum_sha256(b"light-client-attack" + self.encode())

    def validate_basic(self) -> str | None:
        if not self.conflicting_header_hash:
            return "missing conflicting header"
        if self.common_height <= 0:
            return "non-positive common height"
        if self.conflicting_height < self.common_height:
            return "conflicting height below common height"
        return None

    def abci_kind(self) -> str:
        return "LIGHT_CLIENT_ATTACK"


def evidence_list_hash(evidence: list[Evidence]) -> bytes:
    from ..crypto import merkle

    return merkle.hash_from_byte_slices_fast([e.hash() for e in evidence])
