"""Commit, CommitSig, ExtendedCommit (reference: ``types/block.go:607-1250``).

A Commit is the aggregated +2/3 precommit for a block: one CommitSig per
validator (by validator-set index), flagged absent / commit / nil.  The
ExtendedCommit additionally carries each precommit's vote extension and
extension signature (ABCI 2.0 vote extensions).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import merkle
from . import canonical, wire
from .block_id import BlockID
from .vote import PRECOMMIT_TYPE, Vote

BLOCK_ID_FLAG_ABSENT = 1
BLOCK_ID_FLAG_COMMIT = 2
BLOCK_ID_FLAG_NIL = 3


@dataclass
class CommitSig:
    block_id_flag: int = BLOCK_ID_FLAG_ABSENT
    validator_address: bytes = b""
    timestamp_ns: int = 0
    signature: bytes = b""

    @classmethod
    def absent(cls) -> "CommitSig":
        return cls()

    def is_absent(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_ABSENT

    def is_commit(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_COMMIT

    def for_block(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_COMMIT

    def block_id(self, commit_block_id: BlockID) -> BlockID:
        """The BlockID this sig actually signed (commit -> the commit's,
        nil -> nil, absent -> nil)  (types/block.go CommitSig.BlockID)."""
        if self.block_id_flag == BLOCK_ID_FLAG_COMMIT:
            return commit_block_id
        return BlockID()

    def validate_basic(self) -> str | None:
        if self.block_id_flag not in (BLOCK_ID_FLAG_ABSENT,
                                      BLOCK_ID_FLAG_COMMIT,
                                      BLOCK_ID_FLAG_NIL):
            return "unknown block ID flag"
        if self.is_absent():
            if self.validator_address or self.signature:
                return "absent sig with address/signature"
        else:
            if len(self.validator_address) != 20:
                return "invalid validator address size"
            if not self.signature or len(self.signature) > 64:
                return "signature absent or too big"
        return None

    def encode(self) -> bytes:
        return (wire.field_varint(1, self.block_id_flag)
                + wire.field_bytes(2, self.validator_address)
                + wire.field_message(3, canonical.encode_timestamp(
                    self.timestamp_ns), force=True)
                + wire.field_bytes(4, self.signature))


@dataclass
class Commit:
    height: int
    round: int
    block_id: BlockID
    signatures: list[CommitSig] = field(default_factory=list)

    def size(self) -> int:
        return len(self.signatures)

    def vote_sign_bytes(self, chain_id: str, idx: int) -> bytes:
        """Reconstructed canonical vote bytes for signature idx
        (types/block.go:902 VoteSignBytes) — the message the TPU kernel
        verifies.  Uses a per-commit template encoder (only the timestamp
        and the commit-vs-nil block id vary between a commit's sigs)."""
        cs = self.signatures[idx]
        enc = self._sb_encoder(chain_id,
                               cs.block_id_flag == BLOCK_ID_FLAG_COMMIT)
        return enc.sign_bytes(cs.timestamp_ns)

    def __deepcopy__(self, memo):
        # derived caches (_dense_cols, _sb_encoders) must not survive a
        # copy: the copy's signatures are routinely mutated (tests,
        # evidence construction) and stale columns would verify the OLD
        # bytes
        import copy as _copy

        return Commit(self.height, self.round,
                      _copy.deepcopy(self.block_id, memo),
                      _copy.deepcopy(self.signatures, memo))

    def dense_columns(self):
        """Columnar view for the dense VerifyCommit fast path: ``(flags
        uint8 (N,), timestamps int64 (N,), sigs uint8 (N,64))``, cached on
        the commit (commits are immutable once decoded).  Returns None
        when any non-absent signature isn't 64 bytes — the dense path
        doesn't apply and callers use the per-lane loop."""
        cols = self.__dict__.get("_dense_cols", False)
        if cols is not False:
            return cols
        import numpy as np

        sigs = self.signatures
        n = len(sigs)
        # peer-supplied ints can exceed uint8/int64 (the codec does not
        # bound them); the loop path handles such commits, so out-of-range
        # values mean "dense not applicable".  Flags load as int64 first —
        # Python ints beyond int64 raise OverflowError on EVERY numpy
        # major, whereas a direct uint8 conversion silently WRAPS on
        # numpy 1.x (flag 258 -> 2 == COMMIT), which would make dense
        # nodes tally lanes the loop path rejects — a validity divergence
        # between nodes on different numpy majors.  The uint8 range check
        # is then vectorized before the narrowing cast.
        try:
            flags64 = np.fromiter((cs.block_id_flag for cs in sigs),
                                  np.int64, n)
            ts = np.fromiter((cs.timestamp_ns for cs in sigs), np.int64, n)
        except (OverflowError, ValueError, TypeError):
            self.__dict__["_dense_cols"] = None
            return None
        if n and not ((flags64 >= 0) & (flags64 <= 0xFF)).all():
            self.__dict__["_dense_cols"] = None
            return None
        flags = flags64.astype(np.uint8)
        buf = bytearray(n * 64)
        cols = None
        for i, cs in enumerate(sigs):
            if cs.block_id_flag == BLOCK_ID_FLAG_ABSENT:
                continue
            if len(cs.signature) != 64:
                break
            buf[i * 64:(i + 1) * 64] = cs.signature
        else:
            sigmat = np.frombuffer(bytes(buf), np.uint8).reshape(n, 64) \
                if n else np.zeros((0, 64), np.uint8)
            cols = (flags, ts, sigmat)
        self.__dict__["_dense_cols"] = cols
        return cols

    def dense_addresses(self) -> list:
        """Cached per-lane validator addresses (the trusting path looks
        commit sigs up BY ADDRESS in a possibly different valset)."""
        addrs = self.__dict__.get("_dense_addrs")
        if addrs is None:
            addrs = [cs.validator_address for cs in self.signatures]
            self.__dict__["_dense_addrs"] = addrs
        return addrs

    def sign_bytes_templates(self, chain_id: str):
        """(pre_commit, pre_nil, post) body fragments for the native
        sign-bytes builder: everything except the timestamp field, for
        both the commit-BlockID and nil variants."""
        enc_c = self._sb_encoder(chain_id, True)
        enc_n = self._sb_encoder(chain_id, False)
        return enc_c._prefix, enc_n._prefix, enc_c._suffix

    def _sb_encoder(self, chain_id: str, is_commit: bool):
        cache = self.__dict__.setdefault("_sb_encoders", {})
        enc = cache.get((chain_id, is_commit))
        if enc is None:
            bid = self.block_id if is_commit else BlockID()
            enc = canonical.CanonicalVoteEncoder(
                chain_id, PRECOMMIT_TYPE, self.height, self.round, bid)
            cache[(chain_id, is_commit)] = enc
        return enc

    def to_vote(self, idx: int) -> Vote:
        cs = self.signatures[idx]
        return Vote(type=PRECOMMIT_TYPE, height=self.height, round=self.round,
                    block_id=cs.block_id(self.block_id),
                    timestamp_ns=cs.timestamp_ns,
                    validator_address=cs.validator_address,
                    validator_index=idx, signature=cs.signature)

    def hash(self) -> bytes:
        return merkle.hash_from_byte_slices_fast(
            [cs.encode() for cs in self.signatures])

    def validate_basic(self) -> str | None:
        if self.height < 0:
            return "negative height"
        if self.round < 0:
            return "negative round"
        if self.height >= 1:
            if self.block_id.is_nil():
                return "commit cannot be for nil block"
            if not self.signatures:
                return "no signatures in commit"
            for i, cs in enumerate(self.signatures):
                err = cs.validate_basic()
                if err:
                    return f"invalid signature {i}: {err}"
        return None

    def encode(self) -> bytes:
        body = (wire.field_varint(1, self.height)
                + wire.field_varint(2, self.round)
                + wire.field_message(3, self.block_id.encode(), force=True))
        for cs in self.signatures:
            body += wire.field_message(4, cs.encode(), force=True)
        return body


@dataclass
class ExtendedCommitSig:
    commit_sig: CommitSig = field(default_factory=CommitSig)
    extension: bytes = b""
    extension_signature: bytes = b""

    def validate_basic(self) -> str | None:
        err = self.commit_sig.validate_basic()
        if err:
            return err
        if self.commit_sig.is_commit():
            if len(self.extension_signature) > 64:
                return "extension signature too big"
        elif self.extension or self.extension_signature:
            return "extension on non-commit vote"
        return None

    def ensure_extension(self, ext_enabled: bool) -> bool:
        """types/block.go EnsureExtensions element check."""
        if not ext_enabled:
            return not self.extension and not self.extension_signature
        if self.commit_sig.is_commit():
            return len(self.extension_signature) > 0
        return True


@dataclass
class ExtendedCommit:
    """Commit + vote extensions (types/block.go:1086)."""

    height: int
    round: int
    block_id: BlockID
    extended_signatures: list[ExtendedCommitSig] = field(default_factory=list)

    def size(self) -> int:
        return len(self.extended_signatures)

    def to_commit(self) -> Commit:
        """Strip extensions (types/block.go:1165 ToCommit)."""
        return Commit(height=self.height, round=self.round,
                      block_id=self.block_id,
                      signatures=[e.commit_sig
                                  for e in self.extended_signatures])

    def ensure_extensions(self, ext_enabled: bool) -> bool:
        """types/block.go:1154 EnsureExtensions."""
        return all(e.ensure_extension(ext_enabled)
                   for e in self.extended_signatures)

    def to_extended_vote(self, idx: int) -> Vote:
        e = self.extended_signatures[idx]
        v = Commit(self.height, self.round, self.block_id,
                   [x.commit_sig for x in self.extended_signatures]
                   ).to_vote(idx)
        v.extension = e.extension
        v.extension_signature = e.extension_signature
        return v
