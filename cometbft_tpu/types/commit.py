"""Commit, CommitSig, ExtendedCommit (reference: ``types/block.go:607-1250``).

A Commit is the aggregated +2/3 precommit for a block: one CommitSig per
validator (by validator-set index), flagged absent / commit / nil.  The
ExtendedCommit additionally carries each precommit's vote extension and
extension signature (ABCI 2.0 vote extensions).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import merkle
from . import canonical, wire
from .block_id import BlockID
from .vote import PRECOMMIT_TYPE, Vote

BLOCK_ID_FLAG_ABSENT = 1
BLOCK_ID_FLAG_COMMIT = 2
BLOCK_ID_FLAG_NIL = 3
# a for-block precommit whose signature was folded into the commit's
# aggregate (Commit.agg_signature): the lane keeps address + timestamp
# but carries NO individual signature — the signer bitmap + one G2 point
# replace the whole cohort's 96-byte lanes
BLOCK_ID_FLAG_AGGREGATE = 4

# max individual signature size: 64 ed25519, 96 bls12_381 G2
MAX_SIGNATURE_SIZE = 96


def signer_bitmap(indices, n: int) -> bytes:
    """Aggregate-signer bitmap: bit i (byte i//8, bit i%8, LSB-first)
    set when validator-set index i signed into the aggregate."""
    buf = bytearray((n + 7) // 8)
    for i in indices:
        if not 0 <= i < n:
            raise ValueError(f"signer index {i} out of range for {n}")
        buf[i // 8] |= 1 << (i % 8)
    return bytes(buf)


def bitmap_indices(bitmap: bytes, n: int) -> list[int] | None:
    """Decode a signer bitmap; None when the length is wrong or a bit
    beyond n is set (a malformed commit, never a silent truncation)."""
    if len(bitmap) != (n + 7) // 8:
        return None
    out = []
    for i, byte in enumerate(bitmap):
        base = i * 8
        while byte:
            low = byte & -byte
            idx = base + low.bit_length() - 1
            if idx >= n:
                return None
            out.append(idx)
            byte ^= low
    return out


@dataclass
class CommitSig:
    block_id_flag: int = BLOCK_ID_FLAG_ABSENT
    validator_address: bytes = b""
    timestamp_ns: int = 0
    signature: bytes = b""

    @classmethod
    def absent(cls) -> "CommitSig":
        return cls()

    def is_absent(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_ABSENT

    def is_commit(self) -> bool:
        return self.block_id_flag in (BLOCK_ID_FLAG_COMMIT,
                                      BLOCK_ID_FLAG_AGGREGATE)

    def is_aggregate(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_AGGREGATE

    def for_block(self) -> bool:
        return self.is_commit()

    def block_id(self, commit_block_id: BlockID) -> BlockID:
        """The BlockID this sig actually signed (commit/aggregate -> the
        commit's, nil -> nil, absent -> nil)
        (types/block.go CommitSig.BlockID)."""
        if self.is_commit():
            return commit_block_id
        return BlockID()

    def validate_basic(self) -> str | None:
        if self.block_id_flag not in (BLOCK_ID_FLAG_ABSENT,
                                      BLOCK_ID_FLAG_COMMIT,
                                      BLOCK_ID_FLAG_NIL,
                                      BLOCK_ID_FLAG_AGGREGATE):
            return "unknown block ID flag"
        if self.is_absent():
            if self.validator_address or self.signature:
                return "absent sig with address/signature"
        elif self.is_aggregate():
            if len(self.validator_address) != 20:
                return "invalid validator address size"
            if self.signature:
                return "aggregate lane carries an individual signature"
        else:
            if len(self.validator_address) != 20:
                return "invalid validator address size"
            if not self.signature or len(self.signature) > MAX_SIGNATURE_SIZE:
                return "signature absent or too big"
        return None

    def encode(self) -> bytes:
        return (wire.field_varint(1, self.block_id_flag)
                + wire.field_bytes(2, self.validator_address)
                + wire.field_message(3, canonical.encode_timestamp(
                    self.timestamp_ns), force=True)
                + wire.field_bytes(4, self.signature))


@dataclass
class Commit:
    height: int
    round: int
    block_id: BlockID
    signatures: list[CommitSig] = field(default_factory=list)
    # BLS aggregate-commit fast path: one compressed G2 signature over the
    # zero-timestamp canonical precommit, covering exactly the lanes
    # flagged BLOCK_ID_FLAG_AGGREGATE (agg_signers is their bitmap —
    # see signer_bitmap).  Empty on pure-Ed25519 commits: wire encoding
    # and hash are then byte-identical to the pre-aggregation format.
    agg_signature: bytes = b""
    agg_signers: bytes = b""

    def size(self) -> int:
        return len(self.signatures)

    def vote_sign_bytes(self, chain_id: str, idx: int) -> bytes:
        """Reconstructed canonical vote bytes for signature idx
        (types/block.go:902 VoteSignBytes) — the message the TPU kernel
        verifies.  Uses a per-commit template encoder (only the timestamp
        and the commit-vs-nil block id vary between a commit's sigs)."""
        cs = self.signatures[idx]
        enc = self._sb_encoder(chain_id, cs.is_commit())
        return enc.sign_bytes(cs.timestamp_ns)

    def vote_sign_bytes_for(self, chain_id: str, idx: int,
                            key_type: str) -> bytes:
        """Sign bytes for lane idx as a function of the signer's key
        type: BLS validators sign the zero-timestamp aggregation domain
        (Vote.sign_bytes_for), Ed25519 the reference encoding."""
        cs = self.signatures[idx]
        enc = self._sb_encoder(chain_id, cs.is_commit())
        return enc.sign_bytes(0 if key_type == "bls12_381"
                              else cs.timestamp_ns)

    def aggregate_sign_bytes(self, chain_id: str) -> bytes:
        """THE message under the aggregate signature: every BLS for-block
        precommit in this commit signed these exact bytes (canonical
        precommit for the commit's BlockID, timestamp pinned to zero)."""
        return self._sb_encoder(chain_id, True).sign_bytes(0)

    def has_aggregate(self) -> bool:
        """True when this commit carries an aggregate signature or any
        AGGREGATE-flag lane (cached: commits are immutable once decoded)."""
        h = self.__dict__.get("_has_agg")
        if h is None:
            h = bool(self.agg_signature) or bool(self.agg_signers) or any(
                cs.block_id_flag == BLOCK_ID_FLAG_AGGREGATE
                for cs in self.signatures)
            self.__dict__["_has_agg"] = h
        return h

    def aggregate_lanes(self) -> list[int]:
        """Indices of AGGREGATE-flag lanes, in index order (cached)."""
        lanes = self.__dict__.get("_agg_lanes")
        if lanes is None:
            lanes = [i for i, cs in enumerate(self.signatures)
                     if cs.block_id_flag == BLOCK_ID_FLAG_AGGREGATE]
            self.__dict__["_agg_lanes"] = lanes
        return lanes

    def __deepcopy__(self, memo):
        # derived caches (_dense_cols, _sb_encoders) must not survive a
        # copy: the copy's signatures are routinely mutated (tests,
        # evidence construction) and stale columns would verify the OLD
        # bytes
        import copy as _copy

        return Commit(self.height, self.round,
                      _copy.deepcopy(self.block_id, memo),
                      _copy.deepcopy(self.signatures, memo),
                      self.agg_signature, self.agg_signers)

    def dense_columns(self):
        """Columnar view for the dense VerifyCommit fast path: ``(flags
        uint8 (N,), timestamps int64 (N,), sigs uint8 (N,64))``, cached on
        the commit (commits are immutable once decoded).  Returns None
        when any non-absent signature isn't 64 bytes — the dense path
        doesn't apply and callers use the per-lane loop."""
        cols = self.__dict__.get("_dense_cols", False)
        if cols is not False:
            return cols
        import numpy as np

        sigs = self.signatures
        n = len(sigs)
        # peer-supplied ints can exceed uint8/int64 (the codec does not
        # bound them); the loop path handles such commits, so out-of-range
        # values mean "dense not applicable".  Flags load as int64 first —
        # Python ints beyond int64 raise OverflowError on EVERY numpy
        # major, whereas a direct uint8 conversion silently WRAPS on
        # numpy 1.x (flag 258 -> 2 == COMMIT), which would make dense
        # nodes tally lanes the loop path rejects — a validity divergence
        # between nodes on different numpy majors.  The uint8 range check
        # is then vectorized before the narrowing cast.
        try:
            flags64 = np.fromiter((cs.block_id_flag for cs in sigs),
                                  np.int64, n)
            ts = np.fromiter((cs.timestamp_ns for cs in sigs), np.int64, n)
        except (OverflowError, ValueError, TypeError):
            self.__dict__["_dense_cols"] = None
            return None
        if n and not ((flags64 >= 0) & (flags64 <= 0xFF)).all():
            self.__dict__["_dense_cols"] = None
            return None
        flags = flags64.astype(np.uint8)
        buf = bytearray(n * 64)
        cols = None
        for i, cs in enumerate(sigs):
            # aggregate lanes carry no individual signature — their lane
            # stays zeroed like an absent one (the aggregate is verified
            # up front and dense kernels never select flag-4 lanes)
            if cs.block_id_flag in (BLOCK_ID_FLAG_ABSENT,
                                    BLOCK_ID_FLAG_AGGREGATE):
                continue
            if len(cs.signature) != 64:
                break
            buf[i * 64:(i + 1) * 64] = cs.signature
        else:
            sigmat = np.frombuffer(bytes(buf), np.uint8).reshape(n, 64) \
                if n else np.zeros((0, 64), np.uint8)
            cols = (flags, ts, sigmat)
        self.__dict__["_dense_cols"] = cols
        return cols

    def dense_addresses(self) -> list:
        """Cached per-lane validator addresses (the trusting path looks
        commit sigs up BY ADDRESS in a possibly different valset)."""
        addrs = self.__dict__.get("_dense_addrs")
        if addrs is None:
            addrs = [cs.validator_address for cs in self.signatures]
            self.__dict__["_dense_addrs"] = addrs
        return addrs

    def sign_bytes_templates(self, chain_id: str):
        """(pre_commit, pre_nil, post) body fragments for the native
        sign-bytes builder: everything except the timestamp field, for
        both the commit-BlockID and nil variants."""
        enc_c = self._sb_encoder(chain_id, True)
        enc_n = self._sb_encoder(chain_id, False)
        return enc_c._prefix, enc_n._prefix, enc_c._suffix

    def _sb_encoder(self, chain_id: str, is_commit: bool):
        cache = self.__dict__.setdefault("_sb_encoders", {})
        enc = cache.get((chain_id, is_commit))
        if enc is None:
            bid = self.block_id if is_commit else BlockID()
            enc = canonical.CanonicalVoteEncoder(
                chain_id, PRECOMMIT_TYPE, self.height, self.round, bid)
            cache[(chain_id, is_commit)] = enc
        return enc

    def to_vote(self, idx: int) -> Vote:
        cs = self.signatures[idx]
        return Vote(type=PRECOMMIT_TYPE, height=self.height, round=self.round,
                    block_id=cs.block_id(self.block_id),
                    timestamp_ns=cs.timestamp_ns,
                    validator_address=cs.validator_address,
                    validator_index=idx, signature=cs.signature)

    def hash(self) -> bytes:
        leaves = [cs.encode() for cs in self.signatures]
        if self.agg_signature or self.agg_signers:
            # one extra leaf binds the aggregate signature + bitmap into
            # the header's commit hash; pure-Ed25519 commits append
            # nothing, keeping their hashes byte-identical to the
            # pre-aggregation format
            leaves.append(wire.field_bytes(1, self.agg_signature)
                          + wire.field_bytes(2, self.agg_signers))
        return merkle.hash_from_byte_slices_fast(leaves)

    def validate_basic(self) -> str | None:
        if self.height < 0:
            return "negative height"
        if self.round < 0:
            return "negative round"
        if self.height >= 1:
            if self.block_id.is_nil():
                return "commit cannot be for nil block"
            if not self.signatures:
                return "no signatures in commit"
            for i, cs in enumerate(self.signatures):
                err = cs.validate_basic()
                if err:
                    return f"invalid signature {i}: {err}"
            err = self._validate_aggregate()
            if err:
                return err
        return None

    def _validate_aggregate(self) -> str | None:
        """Structural aggregate checks: the bitmap must name exactly the
        AGGREGATE-flag lanes, and signature/bitmap must come and go
        together.  Cryptographic verification lives in
        types/validation.py; this is pure shape."""
        lanes = self.aggregate_lanes()
        if not self.agg_signature and not self.agg_signers and not lanes:
            return None
        if len(self.agg_signature) != 96:
            return "aggregate signature must be 96 bytes"
        if not lanes:
            return "aggregate signature without aggregate lanes"
        if len(self.agg_signers) != (len(self.signatures) + 7) // 8:
            return "malformed aggregate signer bitmap"
        # one bytes compare against the re-encoded lane set (cached —
        # commits are immutable once decoded) instead of an O(N) decode
        # per call; a stray bit beyond the lanes fails the same way a
        # missing one does
        expect = self.__dict__.get("_agg_bitmap")
        if expect is None:
            expect = signer_bitmap(lanes, len(self.signatures))
            self.__dict__["_agg_bitmap"] = expect
        if self.agg_signers != expect:
            return "aggregate signer bitmap does not match aggregate lanes"
        return None

    def encode(self) -> bytes:
        body = (wire.field_varint(1, self.height)
                + wire.field_varint(2, self.round)
                + wire.field_message(3, self.block_id.encode(), force=True))
        for cs in self.signatures:
            body += wire.field_message(4, cs.encode(), force=True)
        body += (wire.field_bytes(5, self.agg_signature)
                 + wire.field_bytes(6, self.agg_signers))
        return body


def aggregate_commit(commit: Commit, val_set) -> Commit:
    """Fold the BLS for-block cohort of a freshly made commit into one
    aggregate signature + signer bitmap (the proposer-side half of the
    fast path; VoteSet.make_commit calls this).  Deterministic — lanes
    fold in validator-index order — so replays are byte-identical.
    Cohorts smaller than 2 stay as individual lanes (no wire saving);
    NIL votes always stay individual (they sign a different message).
    Ed25519 lanes are untouched."""
    if commit.has_aggregate():
        # already folded (a promoted seen commit after catch-up):
        # re-folding would overwrite the aggregate with a partial one
        return commit
    if not val_set.has_bls():
        return commit
    cohort = []
    sigs = []
    for i, cs in enumerate(commit.signatures):
        if cs.block_id_flag != BLOCK_ID_FLAG_COMMIT:
            continue
        val = val_set.get_by_index(i)
        if val is None or val.pub_key.type() != "bls12_381":
            continue
        cohort.append(i)
        sigs.append(cs.signature)
    if len(cohort) < 2:
        return commit
    from ..crypto import bls12381 as _bls

    # check=False: every input already passed individual vote
    # verification on its way into the VoteSet
    agg = _bls.aggregate_signatures(sigs, check=False)
    new_sigs = list(commit.signatures)
    for i in cohort:
        cs = commit.signatures[i]
        new_sigs[i] = CommitSig(BLOCK_ID_FLAG_AGGREGATE,
                                cs.validator_address, cs.timestamp_ns, b"")
    return Commit(commit.height, commit.round, commit.block_id, new_sigs,
                  agg, signer_bitmap(cohort, len(new_sigs)))


@dataclass
class ExtendedCommitSig:
    commit_sig: CommitSig = field(default_factory=CommitSig)
    extension: bytes = b""
    extension_signature: bytes = b""

    def validate_basic(self) -> str | None:
        err = self.commit_sig.validate_basic()
        if err:
            return err
        if self.commit_sig.is_commit():
            if len(self.extension_signature) > MAX_SIGNATURE_SIZE:
                return "extension signature too big"
        elif self.extension or self.extension_signature:
            return "extension on non-commit vote"
        return None

    def ensure_extension(self, ext_enabled: bool) -> bool:
        """types/block.go EnsureExtensions element check."""
        if not ext_enabled:
            return not self.extension and not self.extension_signature
        if self.commit_sig.is_commit():
            return len(self.extension_signature) > 0
        return True


@dataclass
class ExtendedCommit:
    """Commit + vote extensions (types/block.go:1086)."""

    height: int
    round: int
    block_id: BlockID
    extended_signatures: list[ExtendedCommitSig] = field(default_factory=list)
    # carried through when an already-aggregated commit is promoted
    # (seen-commit path after catch-up): the folded lanes have no
    # individual signatures, so dropping these would make the commit
    # unverifiable
    agg_signature: bytes = b""
    agg_signers: bytes = b""

    def size(self) -> int:
        return len(self.extended_signatures)

    def to_commit(self) -> Commit:
        """Strip extensions (types/block.go:1165 ToCommit)."""
        return Commit(height=self.height, round=self.round,
                      block_id=self.block_id,
                      signatures=[e.commit_sig
                                  for e in self.extended_signatures],
                      agg_signature=self.agg_signature,
                      agg_signers=self.agg_signers)

    def ensure_extensions(self, ext_enabled: bool) -> bool:
        """types/block.go:1154 EnsureExtensions."""
        return all(e.ensure_extension(ext_enabled)
                   for e in self.extended_signatures)

    def to_extended_vote(self, idx: int) -> Vote:
        e = self.extended_signatures[idx]
        v = Commit(self.height, self.round, self.block_id,
                   [x.commit_sig for x in self.extended_signatures]
                   ).to_vote(idx)
        v.extension = e.extension
        v.extension_signature = e.extension_signature
        return v
