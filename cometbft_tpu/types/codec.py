"""Serialization codec for storage, WAL records and p2p payloads.

The reference serializes with protobuf everywhere; this framework splits
concerns: *hash/sign* bytes use the canonical proto wire encodings
(``types/wire.py`` — consensus-critical, byte-exact), while *storage and
transport* use a msgpack dataclass codec (self-describing, fast, and — per
SURVEY.md §7.5 — only required to interop with itself, not with Go nodes).
"""

from __future__ import annotations

import msgpack

from .block_id import BlockID, PartSetHeader
from .commit import Commit, CommitSig, ExtendedCommit, ExtendedCommitSig
from .header import Block, Data, Header
from .evidence import (DuplicateVoteEvidence, Evidence,
                       LightClientAttackEvidence)
from .validator_set import Validator, ValidatorSet
from .vote import Proposal, Vote


def pack(obj) -> bytes:
    return msgpack.packb(to_dict(obj), use_bin_type=True)


def unpack(raw: bytes):
    return from_dict(msgpack.unpackb(raw, raw=False))


# --------------------------------------------------------------- dict codecs

def to_dict(obj):
    if obj is None or isinstance(obj, (int, str, bytes, bool)):
        return obj
    if isinstance(obj, (list, tuple)):
        return [to_dict(o) for o in obj]
    if isinstance(obj, dict):                 # plain containers recurse
        return {k: to_dict(v) for k, v in obj.items()}
    t = type(obj).__name__
    if isinstance(obj, PartSetHeader):
        return {"!": t, "total": obj.total, "hash": obj.hash}
    if isinstance(obj, BlockID):
        return {"!": t, "hash": obj.hash,
                "psh": to_dict(obj.part_set_header)}
    if isinstance(obj, CommitSig):
        return {"!": t, "flag": obj.block_id_flag,
                "addr": obj.validator_address, "ts": obj.timestamp_ns,
                "sig": obj.signature}
    if isinstance(obj, Commit):
        d = {"!": t, "h": obj.height, "r": obj.round,
             "bid": to_dict(obj.block_id),
             "sigs": [to_dict(s) for s in obj.signatures]}
        if obj.agg_signature or obj.agg_signers:
            # emitted only when present: pure-Ed25519 commit dicts stay
            # byte-identical to the pre-aggregation codec
            d["agg"] = obj.agg_signature
            d["asg"] = obj.agg_signers
        return d
    if isinstance(obj, ExtendedCommitSig):
        return {"!": t, "cs": to_dict(obj.commit_sig), "ext": obj.extension,
                "extsig": obj.extension_signature}
    if isinstance(obj, ExtendedCommit):
        d = {"!": t, "h": obj.height, "r": obj.round,
             "bid": to_dict(obj.block_id),
             "sigs": [to_dict(s) for s in obj.extended_signatures]}
        if obj.agg_signature or obj.agg_signers:
            d["agg"] = obj.agg_signature
            d["asg"] = obj.agg_signers
        return d
    if isinstance(obj, Vote):
        return {"!": t, "t": obj.type, "h": obj.height, "r": obj.round,
                "bid": to_dict(obj.block_id), "ts": obj.timestamp_ns,
                "addr": obj.validator_address, "idx": obj.validator_index,
                "sig": obj.signature, "ext": obj.extension,
                "extsig": obj.extension_signature}
    if isinstance(obj, Proposal):
        return {"!": t, "h": obj.height, "r": obj.round,
                "pol": obj.pol_round, "bid": to_dict(obj.block_id),
                "ts": obj.timestamp_ns, "sig": obj.signature}
    if isinstance(obj, Header):
        return {"!": t, "chain": obj.chain_id, "h": obj.height,
                "ts": obj.time_ns, "lbi": to_dict(obj.last_block_id),
                "lch": obj.last_commit_hash, "dh": obj.data_hash,
                "vh": obj.validators_hash, "nvh": obj.next_validators_hash,
                "ch": obj.consensus_hash, "ah": obj.app_hash,
                "lrh": obj.last_results_hash, "eh": obj.evidence_hash,
                "prop": obj.proposer_address, "vb": obj.version_block,
                "va": obj.version_app}
    if isinstance(obj, Data):
        return {"!": t, "txs": list(obj.txs)}
    if isinstance(obj, Block):
        return {"!": t, "hdr": to_dict(obj.header), "data": to_dict(obj.data),
                "ev": [to_dict(e) for e in obj.evidence],
                "lc": to_dict(obj.last_commit)}
    if isinstance(obj, Validator):
        return {"!": t, "pk_type": obj.pub_key.type(),
                "pk": obj.pub_key.bytes(), "power": obj.voting_power,
                "prio": obj.proposer_priority}
    if isinstance(obj, ValidatorSet):
        return {"!": t, "vals": [to_dict(v) for v in obj.validators],
                "prop": obj.proposer.address if obj.proposer else b""}
    if isinstance(obj, DuplicateVoteEvidence):
        return {"!": t, "a": to_dict(obj.vote_a), "b": to_dict(obj.vote_b),
                "tvp": obj.total_voting_power, "vp": obj.validator_power,
                "ts": obj.timestamp_ns}
    if isinstance(obj, LightClientAttackEvidence):
        return {"!": t, "chh": obj.conflicting_header_hash,
                "chht": obj.conflicting_height, "comh": obj.common_height,
                "byz": [to_dict(v) for v in obj.byzantine_validators],
                "tvp": obj.total_voting_power, "ts": obj.timestamp_ns,
                "cb": to_dict(obj.conflicting_block)}
    from ..light.types import LightBlock  # lazy: light imports types

    if isinstance(obj, LightBlock):
        return {"!": "LightBlock", "h": to_dict(obj.header),
                "c": to_dict(obj.commit), "v": to_dict(obj.validators)}
    raise TypeError(f"codec: unsupported type {t}")


def from_dict(d):
    if d is None or isinstance(d, (int, str, bytes, bool)):
        return d
    if isinstance(d, list):
        return [from_dict(x) for x in d]
    t = d.get("!")
    if t is None:                             # plain containers recurse
        return {k: from_dict(v) for k, v in d.items()}
    if t == "PartSetHeader":
        return PartSetHeader(d["total"], d["hash"])
    if t == "BlockID":
        return BlockID(d["hash"], from_dict(d["psh"]))
    if t == "CommitSig":
        return CommitSig(d["flag"], d["addr"], d["ts"], d["sig"])
    if t == "Commit":
        return Commit(d["h"], d["r"], from_dict(d["bid"]),
                      [from_dict(s) for s in d["sigs"]],
                      d.get("agg", b""), d.get("asg", b""))
    if t == "ExtendedCommitSig":
        return ExtendedCommitSig(from_dict(d["cs"]), d["ext"], d["extsig"])
    if t == "ExtendedCommit":
        return ExtendedCommit(d["h"], d["r"], from_dict(d["bid"]),
                              [from_dict(s) for s in d["sigs"]],
                              d.get("agg", b""), d.get("asg", b""))
    if t == "Vote":
        return Vote(type=d["t"], height=d["h"], round=d["r"],
                    block_id=from_dict(d["bid"]), timestamp_ns=d["ts"],
                    validator_address=d["addr"], validator_index=d["idx"],
                    signature=d["sig"], extension=d["ext"],
                    extension_signature=d["extsig"])
    if t == "Proposal":
        return Proposal(height=d["h"], round=d["r"], pol_round=d["pol"],
                        block_id=from_dict(d["bid"]), timestamp_ns=d["ts"],
                        signature=d["sig"])
    if t == "Header":
        return Header(chain_id=d["chain"], height=d["h"], time_ns=d["ts"],
                      last_block_id=from_dict(d["lbi"]),
                      last_commit_hash=d["lch"], data_hash=d["dh"],
                      validators_hash=d["vh"], next_validators_hash=d["nvh"],
                      consensus_hash=d["ch"], app_hash=d["ah"],
                      last_results_hash=d["lrh"], evidence_hash=d["eh"],
                      proposer_address=d["prop"], version_block=d["vb"],
                      version_app=d["va"])
    if t == "Data":
        return Data(txs=list(d["txs"]))
    if t == "Block":
        return Block(header=from_dict(d["hdr"]), data=from_dict(d["data"]),
                     evidence=[from_dict(e) for e in d["ev"]],
                     last_commit=from_dict(d["lc"]))
    if t == "Validator":
        from ..crypto.keys import pub_key_from_type_bytes

        return Validator(pub_key_from_type_bytes(d["pk_type"], d["pk"]),
                         d["power"], d["prio"])
    if t == "ValidatorSet":
        vs = ValidatorSet.__new__(ValidatorSet)
        vs.validators = [from_dict(v) for v in d["vals"]]
        vs._total = None
        vs.proposer = None
        if d["prop"]:
            idx, v = vs.get_by_address(d["prop"])
            vs.proposer = v
        return vs
    if t == "DuplicateVoteEvidence":
        return DuplicateVoteEvidence(from_dict(d["a"]), from_dict(d["b"]),
                                     d["tvp"], d["vp"], d["ts"])
    if t == "LightClientAttackEvidence":
        return LightClientAttackEvidence(
            d["chh"], d["chht"], d["comh"],
            byzantine_validators=[from_dict(v) for v in d.get("byz", [])],
            total_voting_power=d["tvp"], timestamp_ns=d["ts"],
            conflicting_block=from_dict(d.get("cb")))
    if t == "LightBlock":
        from ..light.types import LightBlock

        return LightBlock(header=from_dict(d["h"]), commit=from_dict(d["c"]),
                          validators=from_dict(d["v"]))
    raise TypeError(f"codec: unknown tag {t!r}")
