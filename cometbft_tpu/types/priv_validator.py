"""PrivValidator interface + in-memory MockPV (reference:
``types/priv_validator.go``).  The production FilePV with double-sign
protection and the remote signer pair live in ``cometbft_tpu.privval``.

The interface is async: a remote signer (privval/signer_client.go) does
socket round-trips, and the consensus state machine awaits signing on its
single-writer task."""

from __future__ import annotations

from abc import ABC, abstractmethod

from ..crypto.keys import Ed25519PrivKey, PubKey
from .vote import Proposal, Vote


class PrivValidator(ABC):
    @abstractmethod
    def get_pub_key(self) -> PubKey: ...

    @abstractmethod
    async def sign_vote(self, chain_id: str, vote: Vote,
                        sign_extension: bool) -> None:
        """Fills vote.signature (and extension_signature if requested)."""

    @abstractmethod
    async def sign_proposal(self, chain_id: str,
                            proposal: Proposal) -> None: ...


class MockPV(PrivValidator):
    """Unprotected signer for tests (types/priv_validator.go MockPV).
    Key-type aware: a bls12_381 key signs votes over the zero-timestamp
    aggregation domain (``Vote.sign_bytes_for``), so sim networks can
    mix BLS and Ed25519 validators in one genesis."""

    def __init__(self, priv_key=None):
        self.priv_key = priv_key or Ed25519PrivKey.generate()

    @classmethod
    def from_secret(cls, secret: bytes,
                    key_type: str = "ed25519") -> "MockPV":
        if key_type == "bls12_381":
            from ..crypto import bls12381 as _bls

            return cls(_bls.Bls12381PrivKey.from_secret(secret))
        return cls(Ed25519PrivKey.from_secret(secret))

    def get_pub_key(self) -> PubKey:
        return self.priv_key.pub_key()

    def pop(self) -> bytes:
        """Proof of possession (BLS keys only; b"" otherwise) — what a
        genesis doc or validator update publishes beside the pubkey so
        admission can run the rogue-key gate (same contract as
        ``privval.FilePV.pop``)."""
        if self.priv_key.type() != "bls12_381":
            return b""
        from ..crypto import bls12381 as _bls

        return _bls.pop_prove(self.priv_key.bytes())

    async def sign_vote(self, chain_id: str, vote: Vote,
                        sign_extension: bool) -> None:
        vote.signature = self.priv_key.sign(
            vote.sign_bytes_for(chain_id, self.priv_key.type()))
        if sign_extension:
            vote.extension_signature = self.priv_key.sign(
                vote.extension_sign_bytes(chain_id))

    async def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        proposal.signature = self.priv_key.sign(
            proposal.sign_bytes(chain_id))
