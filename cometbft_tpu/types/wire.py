"""Minimal proto3 wire-format writer.

The reference's canonical sign-bytes and hashing are defined over protobuf
encodings (``types/canonical.go``, ``types/vote.go:150``, header field
hashing in ``types/block.go``).  This module provides the deterministic
encoder primitives those layers need — hand-rolled (no generated code) so
the byte layout is explicit and auditable.  proto3 semantics: fields with
zero values are omitted unless explicitly forced.
"""

from __future__ import annotations

__all__ = [
    "varint", "zigzag", "tag", "field_varint", "field_bytes", "field_string",
    "field_fixed64", "field_sfixed64", "field_message", "length_prefixed",
    "WIRE_VARINT", "WIRE_FIXED64", "WIRE_BYTES",
]

WIRE_VARINT = 0
WIRE_FIXED64 = 1
WIRE_BYTES = 2


def varint(n: int) -> bytes:
    """Unsigned LEB128; negative int64 encodes as its 2^64 complement."""
    if n < 0:
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def tag(field: int, wire_type: int) -> bytes:
    return varint((field << 3) | wire_type)


def field_varint(field: int, value: int, force: bool = False) -> bytes:
    if value == 0 and not force:
        return b""
    return tag(field, WIRE_VARINT) + varint(value)


def field_fixed64(field: int, value: int, force: bool = False) -> bytes:
    if value == 0 and not force:
        return b""
    return tag(field, WIRE_FIXED64) + (value & ((1 << 64) - 1)).to_bytes(8, "little")


def field_sfixed64(field: int, value: int, force: bool = False) -> bytes:
    return field_fixed64(field, value & ((1 << 64) - 1) if value < 0 else value,
                         force)


def field_bytes(field: int, value: bytes, force: bool = False) -> bytes:
    if not value and not force:
        return b""
    return tag(field, WIRE_BYTES) + varint(len(value)) + bytes(value)


def field_string(field: int, value: str, force: bool = False) -> bytes:
    return field_bytes(field, value.encode("utf-8"), force)


def field_message(field: int, encoded: bytes | None,
                  force: bool = False) -> bytes:
    """Embedded message; None omits the field, b'' emits an empty message."""
    if encoded is None and not force:
        return b""
    enc = encoded or b""
    return tag(field, WIRE_BYTES) + varint(len(enc)) + enc


def length_prefixed(encoded: bytes) -> bytes:
    """Length-delimited framing (the reference's SignBytes outermost layer,
    protoio.MarshalDelimited)."""
    return varint(len(encoded)) + encoded
