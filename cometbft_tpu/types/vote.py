"""Vote and Proposal (reference: ``types/vote.go``, ``types/proposal.go``)."""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from ..crypto.keys import PubKey
from . import canonical, wire
from .block_id import BlockID

PREVOTE_TYPE = canonical.SIGNED_MSG_TYPE_PREVOTE
PRECOMMIT_TYPE = canonical.SIGNED_MSG_TYPE_PRECOMMIT
PROPOSAL_TYPE = canonical.SIGNED_MSG_TYPE_PROPOSAL

MAX_VOTE_EXTENSION_SIZE = 1024 * 1024


@dataclass
class Vote:
    """A single prevote or precommit.

    ``extension``/``extension_signature`` only appear on precommits when
    vote extensions are enabled (types/vote.go VerifyVoteAndExtension).
    """

    type: int
    height: int
    round: int
    block_id: BlockID
    timestamp_ns: int
    validator_address: bytes
    validator_index: int
    signature: bytes = b""
    extension: bytes = b""
    extension_signature: bytes = b""
    # sign-bytes memo: one vote is encoded up to three times on the hot
    # path (reactor prefetch, VoteSet._verify, evidence).  The guard
    # tuple revalidates every field the encoding reads, so mutating a
    # vote (privval timestamp adjustment, WAL decode reuse) can never
    # serve stale bytes.  Excluded from equality/repr.
    _sb_memo: tuple | None = field(default=None, compare=False, repr=False)
    # zero-timestamp variant memo (BLS aggregation domain, sign_bytes_for)
    _sbz_memo: tuple | None = field(default=None, compare=False, repr=False)

    def sign_bytes(self, chain_id: str) -> bytes:
        guard = (chain_id, self.type, self.height, self.round,
                 self.block_id, self.timestamp_ns)
        memo = self._sb_memo
        if memo is not None and memo[0] == guard:
            return memo[1]
        sb = canonical.canonical_vote_sign_bytes(
            chain_id, self.type, self.height, self.round, self.block_id,
            self.timestamp_ns)
        # plain attribute write: dataclass is not frozen
        object.__setattr__(self, "_sb_memo", (guard, sb))
        return sb

    def sign_bytes_for(self, chain_id: str, key_type: str) -> bytes:
        """Sign bytes as a function of the signer's KEY TYPE: BLS keys
        sign the canonical vote with the timestamp pinned to zero, so
        every BLS precommit for the same (chain_id, h, r, block) is a
        signature over ONE message and the cohort folds into a single
        aggregate (FastAggregateVerify, two pairings).  The CommitSig
        timestamp stays on the wire but is unauthenticated for BLS
        lanes; BFT time draws from the Ed25519 cohort.  Ed25519 keys
        keep the reference encoding unchanged."""
        if key_type != "bls12_381":
            return self.sign_bytes(chain_id)
        guard = (chain_id, self.type, self.height, self.round,
                 self.block_id, 0)
        memo = self._sbz_memo
        if memo is not None and memo[0] == guard:
            return memo[1]
        sb = canonical.canonical_vote_sign_bytes(
            chain_id, self.type, self.height, self.round, self.block_id, 0)
        object.__setattr__(self, "_sbz_memo", (guard, sb))
        return sb

    def extension_sign_bytes(self, chain_id: str) -> bytes:
        return canonical.canonical_vote_extension_sign_bytes(
            chain_id, self.height, self.round, self.extension)

    def is_nil(self) -> bool:
        return self.block_id.is_nil()

    def validate_basic(self) -> str | None:
        """Returns an error string or None (types/vote.go ValidateBasic)."""
        if self.type not in (PREVOTE_TYPE, PRECOMMIT_TYPE):
            return "invalid vote type"
        if self.height < 1:
            return "negative or zero height"
        if self.round < 0:
            return "negative round"
        if not self.block_id.is_nil() and not self.block_id.is_complete():
            return "blockID must be either empty or complete"
        if len(self.validator_address) != 20:
            return "invalid validator address size"
        if self.validator_index < 0:
            return "negative validator index"
        if not self.signature:
            return "signature is missing"
        if len(self.signature) > 96:      # 64 ed25519, 96 bls12_381 G2
            return "signature too big"
        if self.type != PRECOMMIT_TYPE and (self.extension or
                                            self.extension_signature):
            return "vote extension on non-precommit"
        return None

    def verify(self, chain_id: str, pub_key: PubKey) -> bool:
        """Single-signature verify — the per-gossiped-vote hot path
        (types/vote.go:235; consensus addVote).  Sign bytes follow the
        key type (BLS keys sign the zero-timestamp aggregation domain)."""
        return pub_key.verify_signature(
            self.sign_bytes_for(chain_id, pub_key.type()), self.signature)

    def verify_vote_and_extension(self, chain_id: str, pub_key: PubKey,
                                  require_extension: bool) -> bool:
        """types/vote.go:244 VerifyVoteAndExtension."""
        if not self.verify(chain_id, pub_key):
            return False
        if require_extension and self.type == PRECOMMIT_TYPE \
                and not self.block_id.is_nil():
            return self.verify_extension(chain_id, pub_key)
        return True

    def verify_extension(self, chain_id: str, pub_key: PubKey) -> bool:
        """types/vote.go:265 VerifyExtension."""
        return pub_key.verify_signature(self.extension_sign_bytes(chain_id),
                                        self.extension_signature)

    def encode(self) -> bytes:
        """Wire proto (types.proto Vote) for gossip/WAL."""
        return (wire.field_varint(1, self.type)
                + wire.field_varint(2, self.height)
                + wire.field_varint(3, self.round, force=False)
                + wire.field_message(4, self.block_id.encode() or b"")
                + wire.field_message(5, canonical.encode_timestamp(
                    self.timestamp_ns), force=True)
                + wire.field_bytes(6, self.validator_address)
                + wire.field_varint(7, self.validator_index, force=False)
                + wire.field_bytes(8, self.signature)
                + wire.field_bytes(9, self.extension)
                + wire.field_bytes(10, self.extension_signature))

    def copy(self) -> "Vote":
        return replace(self)


@dataclass
class Proposal:
    """Block proposal (types/proposal.go)."""

    height: int
    round: int
    pol_round: int          # -1 when no proof-of-lock
    block_id: BlockID
    timestamp_ns: int
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical.canonical_proposal_sign_bytes(
            chain_id, self.height, self.round, self.pol_round, self.block_id,
            self.timestamp_ns)

    def validate_basic(self) -> str | None:
        if self.height < 1:
            return "negative or zero height"
        if self.round < 0:
            return "negative round"
        if self.pol_round < -1 or self.pol_round >= self.round:
            return "pol_round must be -1 or in [0, round)"
        if not self.block_id.is_complete():
            return "blockID must be complete"
        if not self.signature:
            return "signature is missing"
        return None

    def verify(self, chain_id: str, pub_key: PubKey) -> bool:
        return pub_key.verify_signature(self.sign_bytes(chain_id),
                                        self.signature)
