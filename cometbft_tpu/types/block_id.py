"""BlockID and PartSetHeader (reference: ``types/block.go`` BlockID,
``types/part_set.go`` PartSetHeader)."""

from __future__ import annotations

from dataclasses import dataclass, field

from . import wire


@dataclass(frozen=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and not self.hash

    def encode(self) -> bytes:
        return wire.field_varint(1, self.total) + wire.field_bytes(2, self.hash)


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    part_set_header: PartSetHeader = field(default_factory=PartSetHeader)

    def is_nil(self) -> bool:
        return not self.hash and self.part_set_header.is_zero()

    def is_complete(self) -> bool:
        return (len(self.hash) == 32 and self.part_set_header.total > 0
                and len(self.part_set_header.hash) == 32)

    def encode(self) -> bytes:
        """BlockID proto: {bytes hash=1; PartSetHeader part_set_header=2}."""
        psh = self.part_set_header.encode()
        return (wire.field_bytes(1, self.hash)
                + (wire.field_message(2, psh) if psh else b""))

    def encode_canonical(self) -> bytes | None:
        """CanonicalBlockID, or None when nil (field omitted in sign bytes)."""
        if self.is_nil():
            return None
        return (wire.field_bytes(1, self.hash)
                + wire.field_message(2, self.part_set_header.encode(),
                                     force=True))

    def key(self) -> bytes:
        return (self.hash + self.part_set_header.hash
                + self.part_set_header.total.to_bytes(8, "big"))

    def __str__(self):
        return f"{self.hash.hex()[:12]}:{self.part_set_header.total}"
