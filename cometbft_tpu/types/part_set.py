"""PartSet: blocks split into 64 kB parts with merkle proofs for gossip
(reference: ``types/part_set.go``; part size ``types/params.go:23``)."""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto import merkle
from ..libs.bits import BitArray
from .block_id import PartSetHeader
from .params import BLOCK_PART_SIZE_BYTES


class PartSetError(Exception):
    pass


@dataclass
class Part:
    index: int
    bytes_: bytes
    proof: merkle.Proof

    def validate_basic(self) -> str | None:
        if self.index < 0:
            return "negative index"
        if len(self.bytes_) > BLOCK_PART_SIZE_BYTES:
            return "part too big"
        if self.proof.index != self.index:
            return "proof index mismatch"
        return None


class PartSet:
    """Either built complete from data (proposer side) or assembled part by
    part against a trusted header (gossip receiver side)."""

    def __init__(self, header: PartSetHeader):
        self.total = header.total
        self.hash = header.hash
        self.parts: list[Part | None] = [None] * header.total
        self.parts_bit_array = BitArray(header.total)
        self.count = 0
        self.byte_size = 0

    @classmethod
    def from_data(cls, data: bytes,
                  part_size: int = BLOCK_PART_SIZE_BYTES) -> "PartSet":
        chunks = [data[i:i + part_size]
                  for i in range(0, max(len(data), 1), part_size)]
        if not chunks:
            chunks = [b""]
        root, proofs = merkle.proofs_from_byte_slices(chunks)
        ps = cls(PartSetHeader(len(chunks), root))
        for i, (c, p) in enumerate(zip(chunks, proofs)):
            ps.parts[i] = Part(i, c, p)
            ps.parts_bit_array.set_index(i, True)
        ps.count = len(chunks)
        ps.byte_size = len(data)
        return ps

    def header(self) -> PartSetHeader:
        return PartSetHeader(self.total, self.hash)

    def add_part(self, part: Part) -> bool:
        """Verify inclusion proof and store (types/part_set.go:277 AddPart)."""
        err = part.validate_basic()
        if err:
            raise PartSetError(err)
        if part.index >= self.total:
            raise PartSetError("part index out of range")
        if self.parts[part.index] is not None:
            return False
        if not part.proof.verify(self.hash, part.bytes_):
            raise PartSetError("invalid part proof")
        self.parts[part.index] = part
        self.parts_bit_array.set_index(part.index, True)
        self.count += 1
        self.byte_size += len(part.bytes_)
        return True

    def get_part(self, i: int) -> Part | None:
        return self.parts[i] if 0 <= i < self.total else None

    def is_complete(self) -> bool:
        return self.count == self.total

    def get_data(self) -> bytes:
        if not self.is_complete():
            raise PartSetError("part set incomplete")
        return b"".join(p.bytes_ for p in self.parts)

    def bit_array(self) -> BitArray:
        return self.parts_bit_array.copy()
