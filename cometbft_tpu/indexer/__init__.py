from .block import BlockIndexer
from .service import IndexerService
from .tx import TxIndexer

__all__ = ["TxIndexer", "BlockIndexer", "IndexerService"]
