"""Transaction indexer (reference: ``state/txindex/kv/kv.go``): primary
record by tx hash plus secondary postings for every indexed app-event
attribute, so ``tx_search`` can answer ``app.key='v' AND tx.height=5``."""

from __future__ import annotations

import msgpack

from ..storage.db import KVStore, MemDB

K_TX = b"ti/"              # K_TX + hash -> record
K_ATTR = b"ta/"            # K_ATTR + key + 0 + value + 0 + height8 + hash


class TxIndexer:
    def __init__(self, db: KVStore | None = None):
        self.db = db or MemDB()

    def index(self, height: int, idx: int, tx: bytes, result,
              attrs: dict[str, str]) -> None:
        from ..mempool.mempool import TxKey

        h = TxKey(tx)
        record = {
            "height": height, "index": idx, "tx": tx,
            "code": result.code, "log": result.log, "data": result.data,
            "gas_used": result.gas_used,
            "events": [(e.type, [(a.key, a.value) for a in e.attributes])
                       for e in result.events],
        }
        batch: dict[bytes, bytes] = {K_TX + h: msgpack.packb(
            record, use_bin_type=True)}
        # one posting PER OCCURRENCE: repeated attribute keys (two
        # transfer events with different recipients) must all be findable
        postings = [(k, v) for k, v in attrs.items()]
        postings.append(("tx.height", str(height)))
        for e in result.events:
            for a in e.attributes:
                if getattr(a, "index", True):
                    postings.append((f"{e.type}.{a.key}", str(a.value)))
        for k, v in postings:
            batch[_attr_key(k, v, height, h)] = b""
        self.db.set_batch(batch)

    def get(self, tx_hash: bytes) -> dict | None:
        raw = self.db.get(K_TX + tx_hash)
        if raw is None:
            return None
        return _record(tx_hash, msgpack.unpackb(raw, raw=False))

    def search(self, query: str, page: int = 1, per_page: int = 30,
               order_by: str = "asc") -> dict:
        """Full-grammar search (``libs/query``): plain string-equality
        clauses narrow candidates via the posting index; every remaining
        condition (ranges, CONTAINS, EXISTS, numeric equality) post-filters
        against the record's reconstructed event map — same result as the
        reference kv indexer's range scans (``state/txindex/kv/kv.go``)."""
        from ..libs.query import Query

        q = Query.parse(query)
        eq = q.equality_clauses()
        eq.pop("tm.event", None)             # implied: these are all txs
        result_hashes: set[bytes] | None = None
        for k, v in eq.items():
            found = set()
            prefix = _attr_prefix(k, v)
            for key, _ in self.db.iterate(prefix, prefix + b"\xff" * 9):
                found.add(key[-32:])
            result_hashes = found if result_hashes is None \
                else result_hashes & found
        if result_hashes is None:
            result_hashes = {k[len(K_TX):]
                             for k, _ in self.db.iterate(
                                 K_TX, K_TX + b"\xff" * 33)}
        records = []
        for h in result_hashes:
            raw = self.db.get(K_TX + h)
            if raw is None:
                continue
            d = msgpack.unpackb(raw, raw=False)
            if q.matches(_event_map(h, d)):
                records.append(_record(h, d))
        records.sort(key=lambda r: (r["height"], r["index"]),
                     reverse=(order_by == "desc"))
        page, per_page = max(1, int(page)), min(100, max(1, int(per_page)))
        start = (page - 1) * per_page
        return {"txs": records[start:start + per_page],
                "total_count": len(records)}


def _record(tx_hash: bytes, d: dict) -> dict:
    """The tx endpoint/search response shape, built from a decoded
    stored record (single source of truth for both)."""
    return {
        "hash": tx_hash.hex(), "height": d["height"],
        "index": d["index"], "tx": d["tx"].hex(),
        "tx_result": {"code": d["code"], "log": d["log"],
                      "data": d["data"].hex(),
                      "gas_used": d["gas_used"]},
    }


def _event_map(tx_hash: bytes, record: dict) -> dict[str, list[str]]:
    """Composite-key -> values map for query post-filtering, mirroring the
    attributes the live event bus publishes for a Tx event."""
    m: dict[str, list[str]] = {
        "tm.event": ["Tx"],
        "tx.height": [str(record["height"])],
        # lowercase hex, matching the live event bus attr (TxKey().hex())
        "tx.hash": [tx_hash.hex()],
    }
    for etype, attrs in record["events"]:
        for k, v in attrs:
            m.setdefault(f"{etype}.{k}", []).append(str(v))
    return m


def _attr_key(key: str, value: str, height: int, tx_hash: bytes) -> bytes:
    return (K_ATTR + key.encode() + b"\x00" + value.encode() + b"\x00"
            + height.to_bytes(8, "big") + tx_hash)


def _attr_prefix(key: str, value: str) -> bytes:
    return K_ATTR + key.encode() + b"\x00" + value.encode() + b"\x00"
