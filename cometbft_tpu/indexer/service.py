"""IndexerService: pump event-bus Tx / block events into the indexers
(reference: ``state/txindex/indexer_service.go``)."""

from __future__ import annotations

import asyncio

from ..libs.service import BaseService
from ..types import events as ev
from .block import BlockIndexer
from .tx import TxIndexer


class IndexerService(BaseService):
    def __init__(self, event_bus, tx_indexer: TxIndexer,
                 block_indexer: BlockIndexer, name: str = "indexer"):
        super().__init__(name=name)
        self.event_bus = event_bus
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self._tasks: list[asyncio.Task] = []

    async def on_start(self) -> None:
        # unbuffered: the indexer must see EVERY event — the default
        # drop-oldest subscription would lose txs of large blocks
        tx_sub = self.event_bus.subscribe(
            f"{self.name}:tx", {"tm.event": ev.EVENT_TX}, unbuffered=True)
        blk_sub = self.event_bus.subscribe(
            f"{self.name}:blk", {"tm.event": ev.EVENT_NEW_BLOCK_EVENTS},
            unbuffered=True)
        self._tasks = [
            asyncio.create_task(self._pump_tx(tx_sub)),
            asyncio.create_task(self._pump_blocks(blk_sub)),
        ]

    async def on_stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        self.event_bus.unsubscribe(f"{self.name}:tx")
        self.event_bus.unsubscribe(f"{self.name}:blk")

    async def _pump_tx(self, sub) -> None:
        from ..libs import log as tmlog

        lg = tmlog.logger("indexer", name=self.name)
        while True:
            msg = await sub.queue.get()
            try:
                d = msg.data
                self.tx_indexer.index(d["height"], d["index"],
                                      bytes(d["tx"]), d["result"],
                                      dict(msg.attrs))
            except Exception as e:    # one bad event must not stop indexing
                lg.error("tx index failed", err=repr(e))

    async def _pump_blocks(self, sub) -> None:
        from ..libs import log as tmlog

        lg = tmlog.logger("indexer", name=self.name)
        while True:
            msg = await sub.queue.get()
            try:
                self.block_indexer.index(int(msg.data["height"]),
                                         list(msg.data["events"]))
            except Exception as e:
                lg.error("block index failed", err=repr(e))
