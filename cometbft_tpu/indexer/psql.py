"""External SQL event sink (reference: ``state/indexer/sink/psql/psql.go``).

Writes blocks, tx results, events and attributes to a relational
database so operators can query chain data with SQL and retain it
independently of the node.  Like the reference's psql sink it is
write-only from the node's perspective: ``tx_search``/``block_search``
are NOT served from SQL (the reference returns errors there too) — query
the database directly.

Backend: any DB-API 2.0 connection.  Production uses psycopg (a
PostgreSQL DSN in ``tx_index.psql_conn``); tests inject stdlib sqlite3,
so the SQL here is written to the common subset with per-flavor DDL.
"""

from __future__ import annotations

import json
import time


class PsqlSinkError(Exception):
    pass


_DDL = {
    "postgres": [
        """CREATE TABLE IF NOT EXISTS blocks (
             rowid BIGSERIAL PRIMARY KEY,
             height BIGINT NOT NULL,
             chain_id TEXT NOT NULL,
             created_at TIMESTAMPTZ NOT NULL DEFAULT now(),
             UNIQUE (height, chain_id))""",
        """CREATE TABLE IF NOT EXISTS tx_results (
             rowid BIGSERIAL PRIMARY KEY,
             block_id BIGINT NOT NULL REFERENCES blocks(rowid),
             index_in_block INTEGER NOT NULL,
             tx_hash TEXT NOT NULL,
             tx_result TEXT NOT NULL,
             UNIQUE (block_id, index_in_block))""",
        """CREATE TABLE IF NOT EXISTS events (
             rowid BIGSERIAL PRIMARY KEY,
             block_id BIGINT NOT NULL REFERENCES blocks(rowid),
             tx_id BIGINT REFERENCES tx_results(rowid),
             type TEXT NOT NULL)""",
        """CREATE TABLE IF NOT EXISTS attributes (
             event_id BIGINT NOT NULL REFERENCES events(rowid),
             key TEXT NOT NULL,
             composite_key TEXT NOT NULL,
             value TEXT)""",
    ],
    "sqlite": [
        """CREATE TABLE IF NOT EXISTS blocks (
             rowid INTEGER PRIMARY KEY AUTOINCREMENT,
             height INTEGER NOT NULL,
             chain_id TEXT NOT NULL,
             created_at REAL NOT NULL,
             UNIQUE (height, chain_id))""",
        """CREATE TABLE IF NOT EXISTS tx_results (
             rowid INTEGER PRIMARY KEY AUTOINCREMENT,
             block_id INTEGER NOT NULL REFERENCES blocks(rowid),
             index_in_block INTEGER NOT NULL,
             tx_hash TEXT NOT NULL,
             tx_result TEXT NOT NULL,
             UNIQUE (block_id, index_in_block))""",
        """CREATE TABLE IF NOT EXISTS events (
             rowid INTEGER PRIMARY KEY AUTOINCREMENT,
             block_id INTEGER NOT NULL REFERENCES blocks(rowid),
             tx_id INTEGER REFERENCES tx_results(rowid),
             type TEXT NOT NULL)""",
        """CREATE TABLE IF NOT EXISTS attributes (
             event_id INTEGER NOT NULL REFERENCES events(rowid),
             key TEXT NOT NULL,
             composite_key TEXT NOT NULL,
             value TEXT)""",
    ],
}


class PsqlEventSink:
    """Duck-types the TxIndexer/BlockIndexer surface the IndexerService
    pumps into, writing rows instead of kv postings."""

    def __init__(self, conn=None, dsn: str = "", chain_id: str = "",
                 flavor: str | None = None):
        if conn is None:
            try:
                import psycopg2
            except ImportError as e:
                raise PsqlSinkError(
                    "tx_index.indexer='psql' needs the psycopg2 package "
                    "(or pass a DB-API connection)") from e
            conn = psycopg2.connect(dsn)
            flavor = flavor or "postgres"
        self.conn = conn
        self.chain_id = chain_id
        self.flavor = flavor or ("sqlite" if "sqlite3" in
                                 type(conn).__module__ else "postgres")
        self._ph = "%s" if self.flavor == "postgres" else "?"
        cur = self.conn.cursor()
        for stmt in _DDL[self.flavor]:
            cur.execute(stmt)
        self.conn.commit()

    # ------------------------------------------------------------ helpers

    def _exec(self, cur, sql: str, params=()):
        cur.execute(sql.replace("?", self._ph), params)

    def _insert_returning(self, cur, sql: str, params) -> int:
        if self.flavor == "postgres":
            self._exec(cur, sql + " RETURNING rowid", params)
            return cur.fetchone()[0]
        self._exec(cur, sql, params)
        return cur.lastrowid

    def _block_rowid(self, cur, height: int) -> int:
        self._exec(cur, "SELECT rowid FROM blocks WHERE height = ? AND "
                        "chain_id = ?", (height, self.chain_id))
        row = cur.fetchone()
        if row is not None:
            return row[0]
        if self.flavor == "postgres":
            # created_at is TIMESTAMPTZ DEFAULT now() — never bind a
            # float into it
            return self._insert_returning(
                cur, "INSERT INTO blocks (height, chain_id) "
                     "VALUES (?, ?)", (height, self.chain_id))
        return self._insert_returning(
            cur, "INSERT INTO blocks (height, chain_id, created_at) "
                 "VALUES (?, ?, ?)",
            (height, self.chain_id, time.time()))

    def _insert_events(self, cur, block_id: int, tx_id, events) -> None:
        """events: iterable of (type, [(key, value), ...])."""
        for etype, attrs in events:
            eid = self._insert_returning(
                cur, "INSERT INTO events (block_id, tx_id, type) "
                     "VALUES (?, ?, ?)", (block_id, tx_id, etype))
            for key, value in attrs:
                self._exec(cur,
                           "INSERT INTO attributes (event_id, key, "
                           "composite_key, value) VALUES (?, ?, ?, ?)",
                           (eid, key, f"{etype}.{key}", str(value)))

    # ---------------------------------------------------- indexer surface

    def index_block(self, height: int, events) -> None:
        """BlockIndexer surface: block-level (FinalizeBlock) events.
        ``events`` as the event bus delivers them:
        ``[(type, [(key, value), ...]), ...]``."""
        cur = self.conn.cursor()
        try:
            bid = self._block_rowid(cur, height)
            self._insert_events(cur, bid, None, events)
            self.conn.commit()
        except Exception:
            self.conn.rollback()
            raise

    def index(self, height: int, idx: int, tx: bytes, result,
              attrs: dict) -> None:
        """TxIndexer surface: one tx result + its events."""
        from ..mempool.mempool import TxKey

        record = {
            "code": result.code, "log": result.log,
            "data": result.data.hex(), "gas_used": result.gas_used,
            "tx": tx.hex(),
        }
        cur = self.conn.cursor()
        try:
            bid = self._block_rowid(cur, height)
            tx_id = self._insert_returning(
                cur, "INSERT INTO tx_results (block_id, index_in_block, "
                     "tx_hash, tx_result) VALUES (?, ?, ?, ?)",
                (bid, idx, TxKey(tx).hex(), json.dumps(record)))
            self._insert_events(
                cur, bid, tx_id,
                [(e.type, [(a.key, a.value) for a in e.attributes])
                 for e in result.events])
            self.conn.commit()
        except Exception:
            self.conn.rollback()
            raise

    def block_indexer(self) -> "_BlockView":
        """The BlockIndexer-shaped facade the IndexerService pumps block
        events into (its ``index(height, events)`` signature differs
        from the tx ``index``)."""
        return _BlockView(self)

    # --------------------------------------------------- query surface

    def get(self, tx_hash: bytes):
        raise PsqlSinkError(
            "the psql sink is write-only from the node: query postgres "
            "directly (the reference sink equally serves no reads)")

    def search(self, query: str, page: int = 1, per_page: int = 30,
               order_by: str = "asc"):
        raise PsqlSinkError(
            "tx_search/block_search are not served by the psql sink: "
            "query postgres directly")

    def close(self) -> None:
        try:
            self.conn.close()
        except Exception:
            pass


class _BlockView:
    """Adapter matching BlockIndexer's ``index(height, events)``."""

    def __init__(self, sink: PsqlEventSink):
        self._sink = sink

    def index(self, height: int, events) -> None:
        self._sink.index_block(height, events)

    def search(self, *a, **k):
        return self._sink.search(*a, **k)
