"""Block indexer (reference: ``state/indexer/block/kv``): postings from
block-level app events to heights, for ``block_search``."""

from __future__ import annotations

from ..storage.db import KVStore, MemDB

K_HEIGHT = b"bi/"          # K_HEIGHT + height8 -> b"" (block indexed)
K_ATTR = b"ba/"            # K_ATTR + key + 0 + value + 0 + height8


class BlockIndexer:
    def __init__(self, db: KVStore | None = None):
        self.db = db or MemDB()

    def index(self, height: int, events: list) -> None:
        batch = {K_HEIGHT + height.to_bytes(8, "big"): b""}
        postings = [("block.height", str(height))]
        for e in events:
            for a in e.attributes:
                if getattr(a, "index", True):
                    postings.append((f"{e.type}.{a.key}", str(a.value)))
        for k, v in postings:
            batch[(K_ATTR + k.encode() + b"\x00" + v.encode() + b"\x00"
                   + height.to_bytes(8, "big"))] = b""
        self.db.set_batch(batch)

    def has(self, height: int) -> bool:
        return self.db.get(K_HEIGHT + height.to_bytes(8, "big")) is not None

    def search(self, query: str, page: int = 1, per_page: int = 30) -> dict:
        from ..rpc.server import parse_query

        clauses = parse_query(query)
        clauses.pop("tm.event", None)
        heights: set[int] | None = None
        for k, v in clauses.items():
            prefix = (K_ATTR + k.encode() + b"\x00" + v.encode() + b"\x00")
            found = {int.from_bytes(key[-8:], "big")
                     for key, _ in self.db.iterate(prefix,
                                                   prefix + b"\xff" * 9)}
            heights = found if heights is None else heights & found
        if heights is None:
            heights = {int.from_bytes(k[len(K_HEIGHT):], "big")
                       for k, _ in self.db.iterate(
                           K_HEIGHT, K_HEIGHT + b"\xff" * 9)}
        ordered = sorted(heights)
        page, per_page = max(1, int(page)), min(100, max(1, int(per_page)))
        start = (page - 1) * per_page
        return {"heights": ordered[start:start + per_page],
                "total_count": len(ordered)}
