"""Block indexer (reference: ``state/indexer/block/kv``): postings from
block-level app events to heights, for ``block_search``."""

from __future__ import annotations

from ..storage.db import KVStore, MemDB

K_HEIGHT = b"bi/"          # K_HEIGHT + height8 -> msgpack events
K_ATTR = b"ba/"            # K_ATTR + key + 0 + value + 0 + height8


class BlockIndexer:
    def __init__(self, db: KVStore | None = None):
        self.db = db or MemDB()

    def index(self, height: int, events: list) -> None:
        import msgpack

        stored = [(e.type, [(a.key, str(a.value)) for a in e.attributes])
                  for e in events]
        batch = {K_HEIGHT + height.to_bytes(8, "big"):
                 msgpack.packb(stored, use_bin_type=True)}
        postings = [("block.height", str(height))]
        for e in events:
            for a in e.attributes:
                if getattr(a, "index", True):
                    postings.append((f"{e.type}.{a.key}", str(a.value)))
        for k, v in postings:
            batch[(K_ATTR + k.encode() + b"\x00" + v.encode() + b"\x00"
                   + height.to_bytes(8, "big"))] = b""
        self.db.set_batch(batch)

    def has(self, height: int) -> bool:
        return self.db.get(K_HEIGHT + height.to_bytes(8, "big")) is not None

    def search(self, query: str, page: int = 1, per_page: int = 30,
               order_by: str = "asc") -> dict:
        """Full-grammar search; equality clauses use postings, the rest
        post-filters against stored events (see TxIndexer.search)."""
        import msgpack

        from ..libs.query import Query

        q = Query.parse(query)
        # tm.event is implied (every record here is a block event); strip
        # those conditions so any value the client used (NewBlock /
        # NewBlockEvents) is tolerated, matching the old posting-path pop
        conds = [c for c in q.conditions if c.key != "tm.event"]
        if len(conds) != len(q.conditions):
            q = Query(conds) if conds else None
        eq = q.equality_clauses() if q else {}
        heights: set[int] | None = None
        for k, v in eq.items():
            prefix = (K_ATTR + k.encode() + b"\x00" + v.encode() + b"\x00")
            found = {int.from_bytes(key[-8:], "big")
                     for key, _ in self.db.iterate(prefix,
                                                   prefix + b"\xff" * 9)}
            heights = found if heights is None else heights & found
        if heights is None:
            heights = {int.from_bytes(k[len(K_HEIGHT):], "big")
                       for k, _ in self.db.iterate(
                           K_HEIGHT, K_HEIGHT + b"\xff" * 9)}
        kept = []
        for h in heights:
            if q is None:
                kept.append(h)
                continue
            raw = self.db.get(K_HEIGHT + h.to_bytes(8, "big"))
            m: dict[str, list[str]] = {"block.height": [str(h)]}
            if raw:
                for etype, attrs in msgpack.unpackb(raw, raw=False):
                    for k, v in attrs:
                        m.setdefault(f"{etype}.{k}", []).append(v)
                conds = q.conditions
            else:
                # legacy row (pre-events storage, value b""): equality
                # conditions were satisfied by posting narrowing and
                # block.height is decidable, but ranges/CONTAINS/EXISTS
                # on event attributes are UNDECIDABLE — treat them as
                # non-matching rather than returning false positives
                # (reindex via `reindex-event` to make them queryable)
                undecidable = [c for c in q.conditions
                               if c.key != "block.height"
                               and not (c.op == "="
                                        and eq.get(c.key) == c.arg)]
                if undecidable:
                    continue
                conds = [c for c in q.conditions if c.key == "block.height"]
            if all(c.matches(m.get(c.key)) for c in conds):
                kept.append(h)
        ordered = sorted(kept, reverse=(order_by == "desc"))
        page, per_page = max(1, int(page)), min(100, max(1, int(per_page)))
        start = (page - 1) * per_page
        return {"heights": ordered[start:start + per_page],
                "total_count": len(ordered)}
