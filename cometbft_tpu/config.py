"""Configuration tree (reference: ``config/config.go:78-93`` — one Config
struct covering base/p2p/mempool/consensus/storage/rpc/instrumentation,
TOML-persisted, with a test variant that shrinks consensus timeouts to tens
of milliseconds for fast in-proc ensembles (``config/config.go:1210-1225``)).
"""

from __future__ import annotations

from dataclasses import dataclass, field

NS = 1_000_000_000
MS = 1_000_000


@dataclass
class ConsensusConfig:
    """Timeouts in ns (defaults: config/config.go:1189-1207)."""

    timeout_propose: int = 3 * NS
    timeout_propose_delta: int = 500 * MS
    timeout_prevote: int = 1 * NS
    timeout_prevote_delta: int = 500 * MS
    timeout_precommit: int = 1 * NS
    timeout_precommit_delta: int = 500 * MS
    timeout_commit: int = 1 * NS
    skip_timeout_commit: bool = False
    create_empty_blocks: bool = True
    create_empty_blocks_interval: int = 0
    peer_gossip_sleep_duration: int = 100 * MS
    peer_query_maj23_sleep_duration: int = 2 * NS
    wal_path: str = "data/cs.wal"

    def propose_timeout(self, round_: int) -> int:
        return self.timeout_propose + self.timeout_propose_delta * round_

    def prevote_timeout(self, round_: int) -> int:
        return self.timeout_prevote + self.timeout_prevote_delta * round_

    def precommit_timeout(self, round_: int) -> int:
        return self.timeout_precommit + self.timeout_precommit_delta * round_

    def commit_timeout(self) -> int:
        return self.timeout_commit


def test_consensus_config() -> ConsensusConfig:
    """Shrunk timeouts for in-proc multi-validator tests
    (config/config.go:1210 TestConsensusConfig pattern)."""
    return ConsensusConfig(
        timeout_propose=80 * MS, timeout_propose_delta=20 * MS,
        timeout_prevote=40 * MS, timeout_prevote_delta=10 * MS,
        timeout_precommit=40 * MS, timeout_precommit_delta=10 * MS,
        timeout_commit=20 * MS, peer_gossip_sleep_duration=5 * MS)


@dataclass
class MempoolConfig:
    size: int = 5000
    max_tx_bytes: int = 1024 * 1024
    # byte-capacity bound across the whole pool (reference
    # mempool.max_txs_bytes, default 1 GiB): capacity checks are no
    # longer tx-count-only
    max_txs_bytes: int = 1 << 30
    cache_size: int = 10000
    keep_invalid_txs_in_cache: bool = False
    broadcast: bool = True
    recheck: bool = True
    # admission shards (by tx-hash prefix): each gets its own tx map,
    # byte accounting, admission gate, and CheckTx coalescer, so
    # concurrent admissions and the post-block recheck parallelize
    shards: int = 4
    # CheckTx coalescer: how long the FIRST queued admission may wait
    # for batchmates (0 disables coalescing), and the size-flush cap
    # (snapped DOWN to a crypto/batch compile bucket)
    coalesce_ms: float = 1.0
    coalesce_max: int = 64
    # tx gossip dialect: "announce" = content-addressed (announce tx
    # hashes, fetch bodies on miss; falls back to full bodies per peer
    # for old-protocol peers), "full" = always send full bodies
    gossip_mode: str = "announce"
    # announce/fetch: how long one body fetch may be outstanding before
    # it is re-requested from another announcer
    fetch_timeout_s: float = 2.0
    # byte budget per full-body / fetch-response gossip frame (many txs
    # are packed per frame up to this)
    gossip_batch_bytes: int = 64 * 1024


@dataclass
class P2PConfig:
    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    seeds: str = ""
    persistent_peers: str = ""
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    handshake_timeout: int = 20 * NS
    dial_timeout: int = 3 * NS
    send_rate: int = 5 * 1024 * 1024
    recv_rate: int = 5 * 1024 * 1024
    pex: bool = True
    pex_interval_seconds: float = 30.0     # ensurePeersPeriod
    seed_mode: bool = False    # crawl + serve addresses, hang up after
    #   harvesting (pex_reactor.go crawlPeersRoutine)
    # one-way inter-node delay injected at the MConnection receive side;
    # the e2e runner uses it to emulate geo-distribution on one machine
    # (reference test/e2e/runner/latency_emulation.go)
    emulated_latency_ms: float = 0.0
    # cadence of the Switch's per-peer telemetry flush into the
    # peer-labeled Prometheus series (the packet hot path only touches
    # plain ints; this is how often they become scrapeable).  0 disables
    # the sampler — /net_info still reads the live counters directly.
    telemetry_flush_interval_s: float = 2.0
    addr_book_path: str = "config/addrbook.json"
    # fault injection on every peer stream (p2p/fuzz.go FuzzedConnection,
    # config.FuzzConnConfig); fuzzing starts 10s after connect like
    # p2p/transport.go:223
    test_fuzz: bool = False
    fuzz_mode: str = "drop"           # drop | delay
    fuzz_max_delay_s: float = 3.0
    fuzz_prob_drop_rw: float = 0.01
    fuzz_prob_drop_conn: float = 0.0
    fuzz_prob_sleep: float = 0.0
    fuzz_start_after_s: float = 10.0
    # seed of the fuzzer's private random.Random — connection fuzzing is
    # deterministic by default (same seed, same per-connection decision
    # stream) and composes with [chaos] schedules (libs/failures sites
    # p2p.fuzz.{drop,delay,kill} override these probabilities when armed)
    fuzz_seed: int = 0
    # --- peer quality / reputation (p2p/quality.py) -------------------
    # every layer reports typed, severity-weighted misbehavior events
    # into one decaying per-peer score; crossing quality_disconnect_score
    # drops the peer, crossing quality_ban_score issues a TIMED addrbook
    # ban (TTL doubling per repeat offense up to the max).  Persistent
    # peers are exempt from bans (scored + disconnected + re-dialed).
    quality_enable: bool = True
    quality_disconnect_score: float = 5.0
    quality_ban_score: float = 10.0
    # score half-life: an offense loses half its weight every this long
    quality_half_life_s: float = 120.0
    quality_ban_ttl_s: float = 60.0
    quality_ban_ttl_max_s: float = 3600.0


@dataclass
class RPCConfig:
    laddr: str = "tcp://127.0.0.1:26657"
    grpc_laddr: str = ""              # block/version/pruning gRPC services
    max_open_connections: int = 900
    max_subscription_clients: int = 100
    unsafe: bool = False              # dial_seeds/dial_peers/flush_mempool
    # CORS (config/config.go:353-364): origins may carry ONE wildcard
    # each; '*' alone allows every origin
    cors_allowed_origins: list[str] = field(default_factory=list)
    cors_allowed_methods: list[str] = field(
        default_factory=lambda: ["HEAD", "GET", "POST"])
    cors_allowed_headers: list[str] = field(
        default_factory=lambda: ["Origin", "Accept", "Content-Type",
                                 "X-Requested-With", "X-Server-Time"])
    # HTTPS (config/config.go:428-442): BOTH files present -> TLS server,
    # else plain HTTP.  Paths may be absolute or relative to the config
    # directory, like the reference.
    tls_cert_file: str = ""
    tls_key_file: str = ""
    # shed broadcast_tx_* with a retryable error when the event loop's
    # scheduling lag exceeds this (seconds; 0 disables) — a sustained tx
    # flood otherwise starves consensus into round churn (libs/loopwatch
    # measures the lag; the watchdog must be enabled via
    # instrumentation.loop_stall_threshold_s)
    overload_shed_lag_s: float = 2.0
    # --- admission gate (rpc/server.py) -------------------------------
    # at most this many request handlers run concurrently; up to
    # max_queued_requests more wait; past that the server sheds with
    # HTTP 503 + Retry-After (rpc_requests_shed_total counts them).
    # Diagnostic routes (/status, /net_info, /health, /dump_*) bypass
    # the gate so an overloaded node stays debuggable.
    max_concurrent_requests: int = 64
    max_queued_requests: int = 256
    shed_retry_after_s: float = 1.0


@dataclass
class BlockSyncConfig:
    enable: bool = True
    batch_size: int = 64              # deprecated (never wired); kept so
    #   configs written by older nodes still load.  Use verify_window.
    # cross-block accumulator depth: blocks whose commits fill ONE
    # device batch during catch-up (blocksync/reactor.py; the pipeline
    # double-buffers two of these).  Deeper windows amortize dispatch
    # and fill a bigger mesh; shallower ones bound memory and redo cost.
    verify_window: int = 32


@dataclass
class LightServeConfig:
    """Light-client serving tier (light/serve.py): batched proof/header
    RPC for fleet-scale bootstrap.  The tier is passive (no background
    tasks); these knobs bound its memory and per-request work."""

    enable: bool = True
    # signed header + canonical commit + validator set LRU entries;
    # entries whose header leaves the trust period are evicted on sight
    header_cache_size: int = 4096
    # approximate byte budget for the header LRU (commit JSON dominates
    # at large validator counts; 0 = count-bounded only)
    header_cache_bytes: int = 256 * 1024 * 1024
    # per-block merkle proof trees retained ((height, kind) entries —
    # a 10k-leaf tree is ~640 kB of nodes)
    proof_cache_blocks: int = 64
    # whole-commit verdict memo entries for client-supplied trust
    # anchors (positive verdicts only)
    verify_cache_size: int = 4096
    # trusting period that keys the header LRU window; defaults to the
    # statesync trust period (the same clients consume both)
    trust_period_ns: int = 168 * 3600 * NS
    # per-request bounds: heights per light_blocks / anchors per
    # light_verify, and proofs per light_proofs
    max_batch: int = 128
    max_proofs: int = 4096


@dataclass
class StateSyncConfig:
    enable: bool = False
    trust_height: int = 0
    trust_hash: str = ""
    trust_period: int = 168 * 3600 * NS
    rpc_servers: list[str] = field(default_factory=list)
    # --- snapshot fabric: fetch discipline (statesync/syncer.py) ------
    # a chunk request with no progress for this long fails the restore
    # attempt; individual requests are re-issued to another peer after
    # half of it
    chunk_timeout_s: float = 10.0
    # outstanding chunk requests per serving peer — restore bandwidth
    # scales with peer count while no single peer is ever flooded
    max_inflight_per_peer: int = 4
    # how long one discovery broadcast collects snapshot offers, and how
    # many discover-pick-restore rounds run before the sync gives up
    discovery_time_s: float = 0.5
    discovery_rounds: int = 5
    # per-chunk refetch budget before the snapshot attempt is abandoned
    chunk_retries: int = 3
    # byte budget for retained spool blobs: the window over which a
    # failed/retried restore resumes instead of re-fetching (chunks are
    # content-addressed, so identical chunks across heights/formats/
    # attempts never transfer twice)
    spool_retain_bytes: int = 64 * 1024 * 1024
    # --- snapshot fabric: serving side (statesync/reactor.py) ---------
    # byte budget of the served-chunk LRU — concurrent bootstrappers
    # hit RAM instead of costing an ABCI load each
    chunk_cache_bytes: int = 64 * 1024 * 1024
    # admission gate: concurrent serving loads / queued requests beyond
    # that; past both budgets requests are shed (fetchers re-request
    # from another peer) instead of stalling the event loop
    serve_concurrency: int = 8
    serve_queue: int = 64


@dataclass
class StorageConfig:
    db_backend: str = "logdb"         # logdb | native (C++ engine)
    discard_abci_responses: bool = False
    # --- storage integrity doctor (node/doctor.py) --------------------
    # boot-time cross-store consistency check (blockstore vs statestore
    # vs WAL lineage vs privval last-sign-state) with automatic repair:
    # ahead stores are rolled back to the max mutually-consistent height
    # and blocksync re-fetches the difference.  A salvaged (mid-log
    # corruption) store additionally triggers a deep hash-chain scan.
    doctor_enable: bool = True
    # heights the deep scan walks back from the tip verifying the block
    # hash chain and app-hash lineage (0 = the whole store).  Clamped to
    # the store base (pruned/statesync'd stores scan what they hold).
    doctor_deep_scan_window: int = 128


@dataclass
class TxIndexConfig:
    indexer: str = "kv"               # kv | psql | null
    # DSN for indexer="psql" (state/indexer/sink/psql): the node writes
    # blocks/tx_results/events/attributes rows; queries go to SQL
    psql_conn: str = ""


@dataclass
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    # event-loop stall watchdog (libs/loopwatch — the asyncio analogue of
    # the reference's deadlock-detecting mutex build); 0 disables
    loop_stall_threshold_s: float = 1.0
    # flight-recorder tracing (libs/tracing): span/event ring buffer
    # dumped via GET /dump_trace.  Off by default — disabled tracing is
    # compiled down to a no-op on every instrumented path
    tracing: bool = False
    # bounded ring capacity (records); old records fall off the back
    tracing_ring_size: int = 8192
    # --- liveness watchdog (node/watchdog.py) -------------------------
    # when consensus sits in one step (or goes without a commit, or all
    # peers fall silent) longer than this, the watchdog writes a "black
    # box" incident bundle — flight-recorder ring, per-peer telemetry
    # snapshot, consensus summary, WAL tail — to watchdog_incident_dir,
    # visible via GET /dump_incidents.  0 disables the watchdog.
    watchdog_stall_threshold_s: float = 60.0
    # how often the watchdog evaluates its stall conditions
    watchdog_check_interval_s: float = 5.0
    # rate limit: minimum seconds between two incident bundles (a stall
    # that persists re-dumps at this cadence, not per check tick)
    watchdog_min_interval_s: float = 300.0
    # newest bundles kept on disk; older ones are pruned at write time
    watchdog_max_bundles: int = 16
    # bundle directory — relative paths resolve against the node home
    # (nodes without a home dir skip bundling unless this is absolute)
    watchdog_incident_dir: str = "data/incidents"
    # newest WAL records captured into a bundle
    watchdog_wal_tail: int = 200


@dataclass
class BaseConfig:
    moniker: str = "node"
    root_dir: str = "."
    genesis_file: str = "config/genesis.json"
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    node_key_file: str = "config/node_key.json"
    # when set (tcp://host:port), the node listens here and uses the
    # remote signer that dials in instead of the file PV
    # (privval/signer_listener_endpoint.go)
    priv_validator_laddr: str = ""
    # deadline on one remote-signer round trip (seconds; 0 disables).  A
    # wedged signer process used to block consensus forever; with the
    # deadline a hang costs one missed vote, a reconnect, and a
    # privval_signer_timeouts_total tick instead
    priv_validator_timeout_s: float = 5.0
    abci: str = "builtin"             # builtin | socket
    proxy_app: str = "kvstore"
    signature_backend: str = "auto"   # auto | tpu | jax | cpu  <- TPU seam
    # batches below this verify on CPU even with a device (dispatch
    # latency dominates tiny batches); device warmup pre-compiles the
    # hot bucket shapes at node start
    min_device_lanes: int = 64
    # bound on how long one verification may wait for the accelerator
    # before host fallback (crypto/batch._device_call); 0 = library default
    device_wait_s: float = 0.0
    device_warmup: bool = True
    # leaf count before merkle tree hashing considers the batched device
    # kernel (crypto/merkle; accelerator-gated either way)
    merkle_kernel_min_leaves: int = 2048
    # AOT compile bundle (crypto/aotbundle): at start a device-backed
    # node loads the versioned bundle of pre-compiled kernel executables
    # (first dispatch runs at warm latency); a missing/stale bundle is
    # rebuilt in the background and saved for the next boot.  Stale
    # bundles (jax/plan fingerprint mismatch) are ignored with a logged
    # warning + counter, never executed.
    compile_bundle_enable: bool = True
    # bundle directory; empty = <repo>/.jax_cache/aot beside the
    # persistent XLA cache
    compile_bundle_dir: str = ""
    # coalescing vote-verification scheduler (crypto/scheduler): gossiped
    # votes micro-batch through the batched verifier and seed a
    # verified-signature dedup cache that VerifyCommit* consults
    vote_sched_enable: bool = True
    # latency bound of one coalescing window, ms (the first request of a
    # window waits at most this long before its batch dispatches)
    vote_sched_max_wait_ms: float = 2.0
    # lanes that force an immediate (size) flush; values between compile
    # buckets snap DOWN to one (a full batch never needs a new XLA
    # shape); values below the smallest bucket (16) are honored exactly,
    # since any such batch pads into the 16-lane shape anyway
    vote_sched_max_lanes: int = 256
    # verified-signature LRU entries; 0 disables caching AND the gossip
    # prefetch that feeds it (coalescing still serves async callers)
    vote_sched_cache_size: int = 65536
    # deadline on awaiting a scheduler verdict (seconds): past it the
    # caller re-verifies directly instead of hanging on a future a
    # failed dispatch can never resolve.  0 = auto: ~5x the coalescing
    # window, floored at 1 s so a cold native-verifier build can't trip
    # it on a healthy node
    vote_sched_verify_timeout_s: float = 0.0


@dataclass
class ChaosConfig:
    """Deterministic fault injection (libs/failures).  Off by default;
    when enabled, every armed site's schedule is a pure function of
    ``seed`` and the site's own call index, and fired faults land in a
    bounded in-memory event log for same-seed replay assertions.  The
    ``CMT_CHAOS`` env var overrides this section (chaos harnesses arm
    subprocess nodes without editing config files)."""

    enable: bool = False
    # master seed; per-site RNGs derive from "{seed}:{site}"
    seed: int = 0
    # fault spec strings, "site:key=value:...", e.g.
    #   "wal.fsync.eio:at=40", "p2p.recv.corrupt:prob=0.02:max=20"
    faults: list[str] = field(default_factory=list)
    # bounded fault event log capacity
    log_size: int = 8192


@dataclass
class Config:
    base: BaseConfig = field(default_factory=BaseConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    blocksync: BlockSyncConfig = field(default_factory=BlockSyncConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    lightserve: LightServeConfig = field(default_factory=LightServeConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    instrumentation: InstrumentationConfig = field(
        default_factory=InstrumentationConfig)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)

    # ------------------------------------------------------- TOML persistence
    # (reference: config/toml.go — viper-loaded config.toml; here the file
    # is plain TOML read with the stdlib tomllib and written by a minimal
    # emitter, since only flat [section] key=value forms are needed)

    def to_toml(self) -> str:
        import dataclasses

        lines = ["# cometbft_tpu node configuration", ""]
        for section_name in ("base", "consensus", "mempool", "p2p", "rpc",
                             "blocksync", "statesync", "lightserve",
                             "storage", "tx_index", "instrumentation",
                             "chaos"):
            section = getattr(self, section_name)
            lines.append(f"[{section_name}]")
            for f_ in dataclasses.fields(section):
                if (section_name, f_.name) in _DEPRECATED_KEYS:
                    continue   # load-compat only; never re-emitted
                v = getattr(section, f_.name)
                lines.append(f"{f_.name} = {_toml_value(v)}")
            lines.append("")
        return "\n".join(lines)

    def save(self, path: str) -> None:
        import os

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            f.write(self.to_toml())

    @classmethod
    def load(cls, path: str) -> "Config":
        try:
            import tomllib
        except ImportError:      # Python < 3.11: no stdlib TOML reader.
            tomllib = None       # The emitter below only writes flat
            # [section] key=value forms, so the minimal parser covers
            # every file this module can produce.

        with open(path, "rb") as f:
            if tomllib is not None:
                doc = tomllib.load(f)
            else:
                doc = _parse_flat_toml(f.read().decode())
        cfg = cls()
        for section_name, values in doc.items():
            section = getattr(cfg, section_name, None)
            if section is None:
                raise ConfigError(f"unknown config section {section_name!r}")
            for k, v in values.items():
                if not hasattr(section, k):
                    raise ConfigError(
                        f"unknown config key {section_name}.{k}")
                setattr(section, k, v)
        if "batch_size" in doc.get("blocksync", {}):
            # dead knob kept only so configs written by older nodes still
            # load — it was never wired, and silence teaches operators it
            # tunes something.  The accumulator depth they want is
            # blocksync.verify_window.
            from .libs import log as _tmlog

            _tmlog.logger("config").warn(
                "blocksync.batch_size is deprecated and has no effect; "
                "use blocksync.verify_window to size the cross-block "
                "verification window", path=path)
        cfg.validate()
        return cfg

    def validate(self) -> None:
        """Per-section sanity (config/config.go ValidateBasic)."""
        if self.base.abci not in ("builtin", "socket", "grpc"):
            raise ConfigError(f"base.abci must be builtin|socket|grpc, "
                              f"got {self.base.abci!r}")
        if self.base.signature_backend not in ("auto", "tpu", "jax", "cpu"):
            raise ConfigError(
                f"bad base.signature_backend {self.base.signature_backend!r}")
        for name in ("timeout_propose", "timeout_prevote",
                     "timeout_precommit", "timeout_commit"):
            if getattr(self.consensus, name) <= 0:
                raise ConfigError(f"consensus.{name} must be positive")
        if self.mempool.size <= 0:
            raise ConfigError("mempool.size must be positive")
        if self.mempool.max_txs_bytes <= 0:
            raise ConfigError("mempool.max_txs_bytes must be positive")
        if not 1 <= self.mempool.shards <= 256:
            raise ConfigError("mempool.shards must be in [1, 256]")
        if self.mempool.coalesce_ms < 0:
            raise ConfigError("mempool.coalesce_ms must be >= 0")
        if self.mempool.coalesce_max < 1:
            raise ConfigError("mempool.coalesce_max must be >= 1")
        if self.mempool.gossip_mode not in ("announce", "full"):
            raise ConfigError(
                f"bad mempool.gossip_mode {self.mempool.gossip_mode!r} "
                "(expected 'announce' or 'full')")
        if self.mempool.fetch_timeout_s <= 0:
            raise ConfigError("mempool.fetch_timeout_s must be positive")
        if self.mempool.gossip_batch_bytes < 1024:
            raise ConfigError(
                "mempool.gossip_batch_bytes must be >= 1024")
        if self.base.vote_sched_max_wait_ms < 0:
            raise ConfigError("base.vote_sched_max_wait_ms must be >= 0")
        if self.base.vote_sched_max_lanes < 1:
            raise ConfigError("base.vote_sched_max_lanes must be >= 1")
        if self.base.vote_sched_cache_size < 0:
            raise ConfigError("base.vote_sched_cache_size must be >= 0")
        if self.instrumentation.tracing_ring_size < 16:
            raise ConfigError(
                "instrumentation.tracing_ring_size must be >= 16")
        inst = self.instrumentation
        if inst.watchdog_stall_threshold_s < 0:
            raise ConfigError(
                "instrumentation.watchdog_stall_threshold_s must be >= 0")
        if inst.watchdog_stall_threshold_s > 0:
            if inst.watchdog_check_interval_s <= 0:
                raise ConfigError(
                    "instrumentation.watchdog_check_interval_s must be "
                    "positive when the watchdog is enabled")
            if inst.watchdog_min_interval_s < 0:
                raise ConfigError(
                    "instrumentation.watchdog_min_interval_s must be >= 0")
            if inst.watchdog_max_bundles < 1:
                raise ConfigError(
                    "instrumentation.watchdog_max_bundles must be >= 1")
            if inst.watchdog_wal_tail < 0:
                raise ConfigError(
                    "instrumentation.watchdog_wal_tail must be >= 0")
        if self.p2p.telemetry_flush_interval_s < 0:
            raise ConfigError(
                "p2p.telemetry_flush_interval_s must be >= 0")
        if self.p2p.quality_disconnect_score <= 0 or \
                self.p2p.quality_ban_score <= 0:
            raise ConfigError(
                "p2p.quality_{disconnect,ban}_score must be positive")
        if self.p2p.quality_ban_score < self.p2p.quality_disconnect_score:
            raise ConfigError(
                "p2p.quality_ban_score must be >= quality_disconnect_score")
        if self.p2p.quality_half_life_s <= 0:
            raise ConfigError("p2p.quality_half_life_s must be positive")
        if self.p2p.quality_ban_ttl_s <= 0 or \
                self.p2p.quality_ban_ttl_max_s < self.p2p.quality_ban_ttl_s:
            raise ConfigError(
                "p2p.quality_ban_ttl_s must be positive and <= "
                "quality_ban_ttl_max_s")
        if self.rpc.max_concurrent_requests < 1:
            raise ConfigError("rpc.max_concurrent_requests must be >= 1")
        if self.rpc.max_queued_requests < 0:
            raise ConfigError("rpc.max_queued_requests must be >= 0")
        if self.rpc.shed_retry_after_s < 0:
            raise ConfigError("rpc.shed_retry_after_s must be >= 0")
        if self.storage.db_backend not in ("logdb", "native", "memdb"):
            raise ConfigError(
                f"storage.db_backend must be logdb|native|memdb, "
                f"got {self.storage.db_backend!r}")
        if self.tx_index.indexer not in ("kv", "psql", "null"):
            raise ConfigError(
                f"tx_index.indexer must be kv|psql|null, "
                f"got {self.tx_index.indexer!r}")
        if self.tx_index.indexer == "psql" and not self.tx_index.psql_conn:
            raise ConfigError(
                "tx_index.indexer='psql' requires tx_index.psql_conn")
        if self.p2p.fuzz_mode not in ("drop", "delay"):
            raise ConfigError(f"p2p.fuzz_mode must be drop|delay, "
                              f"got {self.p2p.fuzz_mode!r}")
        if self.base.vote_sched_verify_timeout_s < 0:
            raise ConfigError(
                "base.vote_sched_verify_timeout_s must be >= 0")
        if self.base.priv_validator_timeout_s < 0:
            raise ConfigError(
                "base.priv_validator_timeout_s must be >= 0")
        if self.storage.doctor_deep_scan_window < 0:
            raise ConfigError(
                "storage.doctor_deep_scan_window must be >= 0")
        ls = self.lightserve
        if ls.header_cache_size < 0 or ls.proof_cache_blocks < 0 or \
                ls.verify_cache_size < 0 or ls.header_cache_bytes < 0:
            raise ConfigError(
                "lightserve cache sizes must be >= 0")
        if ls.trust_period_ns <= 0:
            raise ConfigError("lightserve.trust_period_ns must be positive")
        if ls.max_batch < 1:
            raise ConfigError("lightserve.max_batch must be >= 1")
        if ls.max_proofs < 1:
            raise ConfigError("lightserve.max_proofs must be >= 1")
        ss = self.statesync
        if ss.chunk_timeout_s <= 0:
            raise ConfigError("statesync.chunk_timeout_s must be positive")
        if not 1 <= ss.max_inflight_per_peer <= 64:
            raise ConfigError(
                "statesync.max_inflight_per_peer must be in [1, 64]")
        if ss.discovery_time_s <= 0:
            raise ConfigError(
                "statesync.discovery_time_s must be positive")
        if not 1 <= ss.discovery_rounds <= 100:
            raise ConfigError(
                "statesync.discovery_rounds must be in [1, 100]")
        if not 0 <= ss.chunk_retries <= 100:
            raise ConfigError(
                "statesync.chunk_retries must be in [0, 100]")
        if ss.spool_retain_bytes < 0 or ss.chunk_cache_bytes < 0:
            raise ConfigError(
                "statesync byte budgets must be >= 0")
        if ss.serve_concurrency < 1:
            raise ConfigError(
                "statesync.serve_concurrency must be >= 1")
        if ss.serve_queue < 0:
            raise ConfigError("statesync.serve_queue must be >= 0")
        if not 2 <= self.blocksync.verify_window <= 4096:
            # floor 2: the accumulator needs a vouching tail block;
            # cap 4096: one window's commits already fill the largest
            # lane bucket many times over — deeper windows only grow
            # memory and the redo blast radius
            raise ConfigError(
                "blocksync.verify_window must be in [2, 4096]")
        if self.chaos.log_size < 16:
            raise ConfigError("chaos.log_size must be >= 16")
        if self.chaos.enable:
            from .libs.failures import FaultSpecError, parse_fault_spec

            for spec in self.chaos.faults:
                try:
                    parse_fault_spec(spec)
                except FaultSpecError as e:
                    raise ConfigError(f"bad chaos.faults entry: {e}") \
                        from None


class ConfigError(Exception):
    pass


# keys kept on the dataclasses so configs written by older nodes still
# load, but never re-emitted and warned about when a file sets them
_DEPRECATED_KEYS = {("blocksync", "batch_size")}


def _toml_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, list):
        return "[" + ", ".join(_toml_value(x) for x in v) + "]"
    raise ConfigError(f"cannot emit TOML for {type(v).__name__}")


def _parse_flat_toml(text: str) -> dict:
    """Parser of last resort for the flat ``[section] key = value`` TOML
    this repo emits (str/bool/int/float and flat lists): the stdlib
    ``tomllib`` needs Python 3.11 and some images run 3.10.  Covers both
    emitters — :meth:`Config.to_toml` (named sections only) and the e2e
    ``manifest_to_toml`` (root-level keys first, dotted ``[node.v1]``
    tables).  Anything else is a :class:`ConfigError`, same as an
    unknown key."""
    doc: dict = {}
    section = doc               # root-level keys land in the document
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[") and line.endswith("]"):
            section = doc
            for part in line[1:-1].strip().split("."):
                section = section.setdefault(part.strip(), {})
                if not isinstance(section, dict):
                    raise ConfigError(
                        f"config line {ln}: table {line!r} collides with "
                        f"an earlier key")
            continue
        key, eq, rest = line.partition("=")
        if not eq:
            raise ConfigError(f"malformed config line {ln}: {raw!r}")
        rest = rest.strip()
        val, end = _parse_toml_scalar(rest, 0)
        tail = rest[end:].lstrip()
        if tail and not tail.startswith("#"):
            raise ConfigError(f"trailing data on config line {ln}: {raw!r}")
        section[key.strip()] = val
    return doc


def _parse_toml_scalar(s: str, i: int):
    """One value starting at ``s[i]``; returns (value, index-past-it)."""
    if s.startswith('"', i):
        out, i = [], i + 1
        while i < len(s):
            c = s[i]
            if c == "\\":
                nxt = s[i + 1] if i + 1 < len(s) else ""
                if nxt not in ('"', "\\"):
                    raise ConfigError(f"bad escape in config string: {s!r}")
                out.append(nxt)
                i += 2
            elif c == '"':
                return "".join(out), i + 1
            else:
                out.append(c)
                i += 1
        raise ConfigError(f"unterminated config string: {s!r}")
    if s.startswith("[", i):
        vals: list = []
        i += 1
        while True:
            while i < len(s) and s[i] in " \t":
                i += 1
            if i >= len(s):
                raise ConfigError(f"unterminated config list: {s!r}")
            if s[i] == "]":
                return vals, i + 1
            v, i = _parse_toml_scalar(s, i)
            vals.append(v)
            while i < len(s) and s[i] in " \t":
                i += 1
            if i < len(s) and s[i] == ",":
                i += 1
    j = i
    while j < len(s) and s[j] not in ",] \t#":
        j += 1
    tok = s[i:j]
    if tok == "true":
        return True, j
    if tok == "false":
        return False, j
    for cast in (int, float):
        try:
            return cast(tok), j
        except ValueError:
            pass
    raise ConfigError(f"bad config value {tok!r}")
