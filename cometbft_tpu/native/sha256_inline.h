// Shared SHA-256 (FIPS 180-4) for the native components — one
// implementation included by secp256k1.cpp (RFC 6979 / digests),
// bls12381.cpp (expand_message_xmd) and kvstore.cpp (merkle tree),
// which each previously carried their own copy.  All functions are
// internal-linkage: every translation unit gets its own instance, no
// symbol clashes across the separately-built .so files.
//
// The build stamp in __init__.py folds *.h sources into the digest, so
// editing this header rebuilds every dependent library.

#ifndef COMETBFT_TPU_SHA256_INLINE_H
#define COMETBFT_TPU_SHA256_INLINE_H

#include <cstdint>
#include <cstring>

#if defined(__SHA__) && defined(__SSE4_1__) && defined(__x86_64__)
#include <immintrin.h>
#define COMETBFT_TPU_SHA256_SHANI 1
#endif

namespace sha256i {

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
}

#ifdef COMETBFT_TPU_SHA256_SHANI
// SHA-NI compress (Intel SHA extensions): ~6x the portable loop per
// block.  Compiled only when -march=native reports the extension (the
// __init__.py build retries without -march=native, which drops back to
// the portable path below).  Layout per the ISA: state rides as the
// (ABEF, CDGH) pair, message words load big-endian via PSHUFB.
static inline void compress_shani(uint32_t h[8], const uint8_t blk[64]) {
    const __m128i MASK = _mm_set_epi64x(0x0c0d0e0f08090a0bULL,
                                        0x0405060700010203ULL);
    __m128i TMP = _mm_loadu_si128((const __m128i *)&h[0]);
    __m128i STATE1 = _mm_loadu_si128((const __m128i *)&h[4]);
    TMP = _mm_shuffle_epi32(TMP, 0xB1);            // CDAB
    STATE1 = _mm_shuffle_epi32(STATE1, 0x1B);      // EFGH
    __m128i STATE0 = _mm_alignr_epi8(TMP, STATE1, 8);     // ABEF
    STATE1 = _mm_blend_epi16(STATE1, TMP, 0xF0);   // CDGH
    const __m128i ABEF_SAVE = STATE0, CDGH_SAVE = STATE1;
    __m128i MSG, MSG0, MSG1, MSG2, MSG3;

#define SHA_RND(Ki_hi, Ki_lo, Wi)                                      \
    MSG = _mm_add_epi32(Wi, _mm_set_epi64x(Ki_hi, Ki_lo));             \
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);               \
    MSG = _mm_shuffle_epi32(MSG, 0x0E);                                \
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG)
#define SHA_EXT(Wa, Wb, Wc, Wd)                                        \
    TMP = _mm_alignr_epi8(Wd, Wc, 4);                                  \
    Wa = _mm_add_epi32(Wa, TMP);                                       \
    Wa = _mm_sha256msg2_epu32(Wa, Wd);                                 \
    Wb = _mm_sha256msg1_epu32(Wb, Wd)

    MSG0 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(blk + 0)),
                            MASK);
    SHA_RND(0xE9B5DBA5B5C0FBCFULL, 0x71374491428A2F98ULL, MSG0);
    MSG1 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(blk + 16)),
                            MASK);
    SHA_RND(0xAB1C5ED5923F82A4ULL, 0x59F111F13956C25BULL, MSG1);
    MSG0 = _mm_sha256msg1_epu32(MSG0, MSG1);
    MSG2 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(blk + 32)),
                            MASK);
    SHA_RND(0x550C7DC3243185BEULL, 0x12835B01D807AA98ULL, MSG2);
    MSG1 = _mm_sha256msg1_epu32(MSG1, MSG2);
    MSG3 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(blk + 48)),
                            MASK);
    SHA_RND(0xC19BF1749BDC06A7ULL, 0x80DEB1FE72BE5D74ULL, MSG3);
    SHA_EXT(MSG0, MSG2, MSG2, MSG3);   // extend W16..19, prep next msg1
    SHA_RND(0x240CA1CC0FC19DC6ULL, 0xEFBE4786E49B69C1ULL, MSG0);
    SHA_EXT(MSG1, MSG3, MSG3, MSG0);
    SHA_RND(0x76F988DA5CB0A9DCULL, 0x4A7484AA2DE92C6FULL, MSG1);
    SHA_EXT(MSG2, MSG0, MSG0, MSG1);
    SHA_RND(0xBF597FC7B00327C8ULL, 0xA831C66D983E5152ULL, MSG2);
    SHA_EXT(MSG3, MSG1, MSG1, MSG2);
    SHA_RND(0x1429296706CA6351ULL, 0xD5A79147C6E00BF3ULL, MSG3);
    SHA_EXT(MSG0, MSG2, MSG2, MSG3);
    SHA_RND(0x53380D134D2C6DFCULL, 0x2E1B213827B70A85ULL, MSG0);
    SHA_EXT(MSG1, MSG3, MSG3, MSG0);
    SHA_RND(0x92722C8581C2C92EULL, 0x766A0ABB650A7354ULL, MSG1);
    SHA_EXT(MSG2, MSG0, MSG0, MSG1);
    SHA_RND(0xC76C51A3C24B8B70ULL, 0xA81A664BA2BFE8A1ULL, MSG2);
    SHA_EXT(MSG3, MSG1, MSG1, MSG2);
    SHA_RND(0x106AA070F40E3585ULL, 0xD6990624D192E819ULL, MSG3);
    SHA_EXT(MSG0, MSG2, MSG2, MSG3);
    SHA_RND(0x34B0BCB52748774CULL, 0x1E376C0819A4C116ULL, MSG0);
    SHA_EXT(MSG1, MSG3, MSG3, MSG0);
    SHA_RND(0x682E6FF35B9CCA4FULL, 0x4ED8AA4A391C0CB3ULL, MSG1);
    // W52..55: msg2 extension only (no further msg1 needed)
    TMP = _mm_alignr_epi8(MSG1, MSG0, 4);
    MSG2 = _mm_add_epi32(MSG2, TMP);
    MSG2 = _mm_sha256msg2_epu32(MSG2, MSG1);
    SHA_RND(0x8CC7020884C87814ULL, 0x78A5636F748F82EEULL, MSG2);
    TMP = _mm_alignr_epi8(MSG2, MSG1, 4);
    MSG3 = _mm_add_epi32(MSG3, TMP);
    MSG3 = _mm_sha256msg2_epu32(MSG3, MSG2);
    SHA_RND(0xC67178F2BEF9A3F7ULL, 0xA4506CEB90BEFFFAULL, MSG3);
#undef SHA_RND
#undef SHA_EXT

    STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
    STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);
    TMP = _mm_shuffle_epi32(STATE0, 0x1B);         // FEBA
    STATE1 = _mm_shuffle_epi32(STATE1, 0xB1);      // DCHG
    STATE0 = _mm_blend_epi16(TMP, STATE1, 0xF0);   // DCBA
    STATE1 = _mm_alignr_epi8(STATE1, TMP, 8);      // HGFE
    _mm_storeu_si128((__m128i *)&h[0], STATE0);
    _mm_storeu_si128((__m128i *)&h[4], STATE1);
}
#endif  // COMETBFT_TPU_SHA256_SHANI

static inline void compress_portable(uint32_t h[8], const uint8_t blk[64]) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = (uint32_t)blk[4 * i] << 24 | (uint32_t)blk[4 * i + 1] << 16 |
               (uint32_t)blk[4 * i + 2] << 8 | blk[4 * i + 3];
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^
                      (w[i - 15] >> 3);
        uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^
                      (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = hh + S1 + ch + K[i] + w[i];
        uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
        uint32_t mj = (a & b) ^ (a & c) ^ (b & c);
        hh = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + S0 + mj;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

static inline void compress(uint32_t h[8], const uint8_t blk[64]) {
#ifdef COMETBFT_TPU_SHA256_SHANI
    compress_shani(h, blk);
#else
    compress_portable(h, blk);
#endif
}

struct ctx {
    uint32_t h[8];
    uint8_t buf[64];
    uint64_t len;
};

static inline void init(ctx &c) {
    static const uint32_t iv[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                   0xa54ff53a, 0x510e527f, 0x9b05688c,
                                   0x1f83d9ab, 0x5be0cd19};
    memcpy(c.h, iv, sizeof iv);
    c.len = 0;
}

static inline void update(ctx &c, const uint8_t *d, size_t n) {
    size_t fill = c.len % 64;
    c.len += n;
    if (fill) {
        size_t take = 64 - fill < n ? 64 - fill : n;
        memcpy(c.buf + fill, d, take);
        d += take; n -= take;
        if (fill + take == 64) compress(c.h, c.buf);
        else return;
    }
    while (n >= 64) { compress(c.h, d); d += 64; n -= 64; }
    if (n) memcpy(c.buf, d, n);
}

static inline void final(ctx &c, uint8_t out[32]) {
    uint64_t bits = c.len * 8;
    uint8_t pad[72] = {0x80};
    size_t padlen = (c.len % 64 < 56) ? 56 - c.len % 64 : 120 - c.len % 64;
    uint8_t lenb[8];
    for (int i = 0; i < 8; i++) lenb[i] = (uint8_t)(bits >> (56 - 8 * i));
    update(c, pad, padlen);
    update(c, lenb, 8);
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 4; j++)
            out[4 * i + j] = (uint8_t)(c.h[i] >> (24 - 8 * j));
}

// one-shot over up to three concatenated segments (nullptr allowed)
static inline void oneshot3(const uint8_t *d1, size_t n1, const uint8_t *d2,
                            size_t n2, const uint8_t *d3, size_t n3,
                            uint8_t out[32]) {
    ctx c;
    init(c);
    if (n1) update(c, d1, n1);
    if (n2) update(c, d2, n2);
    if (n3) update(c, d3, n3);
    final(c, out);
}

static inline void oneshot(const uint8_t *d, size_t n, uint8_t out[32]) {
    oneshot3(d, n, nullptr, 0, nullptr, 0, out);
}

}  // namespace sha256i

#endif  // COMETBFT_TPU_SHA256_INLINE_H
