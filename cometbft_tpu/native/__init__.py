"""Native (C++) components, built on demand with the image's g++.

The shared object is rebuilt whenever the source hash changes, so the
repo never carries binaries and a checkout works on any host with a
C++17 compiler."""

from __future__ import annotations

import hashlib
import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))


class NativeBuildError(Exception):
    pass


def _build(src: str, out: str) -> None:
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", src, "-o", out]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native build failed: {' '.join(cmd)}\n{proc.stderr}")


def lib_path(name: str = "kvstore") -> str:
    """Path to the built shared object, (re)building if stale."""
    src = os.path.join(_DIR, f"{name}.cpp")
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_DIR, f"_lib{name}.so")
    stamp = out + ".hash"
    if os.path.exists(out) and os.path.exists(stamp):
        with open(stamp) as f:
            if f.read().strip() == digest:
                return out
    _build(src, out)
    with open(stamp, "w") as f:
        f.write(digest)
    return out
