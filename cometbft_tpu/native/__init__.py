"""Native (C++) components, built on demand with the image's g++.

The shared object is rebuilt whenever the source hash changes, so the
repo never carries binaries and a checkout works on any host with a
C++17 compiler."""

from __future__ import annotations

import hashlib
import os
import platform
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))


def _host_id() -> str:
    """CPU identity folded into the build stamp: -march=native binaries
    must never be reused on a host with a different ISA (a stale .so
    from another machine would SIGILL, not gracefully degrade)."""
    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    flags = line
                    break
    except OSError:
        pass
    return hashlib.sha256(
        (platform.machine() + flags).encode()).hexdigest()[:12]


class NativeBuildError(Exception):
    pass


def _build(src: str, out: str) -> None:
    # built on the host it runs on, so -march=native is safe and worth
    # ~15% on the crypto hot loops; retry without it for odd toolchains
    base = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", src, "-o", out]
    for cmd in ([*base[:2], "-march=native", *base[2:]], base):
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode == 0:
            return
    raise NativeBuildError(
        f"native build failed: {' '.join(base)}\n{proc.stderr}")


def lib_path(name: str = "kvstore") -> str:
    """Path to the built shared object, (re)building if the source or the
    host CPU changed.  Concurrent callers serialize on an advisory lock
    so two processes can't interleave writes to the same .so."""
    src = os.path.join(_DIR, f"{name}.cpp")
    h = hashlib.sha256()
    with open(src, "rb") as f:
        h.update(f.read())
    # local headers are part of every unit's build input: an edit to a
    # shared .h must rebuild the libraries that include it
    for hdr in sorted(os.listdir(_DIR)):
        if hdr.endswith(".h"):
            with open(os.path.join(_DIR, hdr), "rb") as f:
                h.update(f.read())
    digest = h.hexdigest()[:16] + "-" + _host_id()
    out = os.path.join(_DIR, f"_lib{name}.so")
    stamp = out + ".hash"

    def fresh() -> bool:
        try:
            with open(stamp) as f:
                return f.read().strip() == digest
        except OSError:
            return False

    if os.path.exists(out) and fresh():
        return out
    import fcntl

    with open(out + ".lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if not (os.path.exists(out) and fresh()):   # lost the race: done
            tmp = out + f".tmp{os.getpid()}"
            _build(src, tmp)
            os.replace(tmp, out)                    # atomic swap-in
            with open(stamp, "w") as f:
                f.write(digest)
    return out
