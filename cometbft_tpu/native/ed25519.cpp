// Native ZIP-215 ed25519 verification: single and random-linear-combination
// batch (the host fallback SURVEY §2.9-1 mandates as "never a Python
// stand-in").  Design provenance (no code copied):
//   - semantics: ZIP-215 cofactored verification exactly as the repo's
//     pure-Python oracle (cometbft_tpu/crypto/_ed25519_py.py) and the
//     reference's curve25519-voi batch path (crypto/ed25519/ed25519.go:188-221)
//   - batch equation: [8]([sum z_i s_i]B - sum [z_i]R_i - sum [z_i h_i]A_i)
//     == identity with independent 128-bit z_i, evaluated as ONE Pippenger
//     multiscalar multiplication over 2n+1 points
//   - field arithmetic: radix-2^51 unsigned limbs with unsigned __int128
//     accumulation; complete twisted-Edwards addition (a=-1 square,
//     d nonsquare => unified formulas are complete, so ZIP-215's
//     small-torsion points are handled without special cases)
//   - scalars mod L: 4x64 limbs, Barrett reduction with mu = floor(2^512/L)
//
// Exported C ABI (ctypes, see crypto/_native_ed25519.py):
//   ed25519_verify(pub, sig, msg, len)            -> 1/0
//   ed25519_batch_verify(pubs, sigs, msgs, lens, n, seed32) -> 1/0

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <vector>
#include <mutex>

typedef uint64_t u64;
typedef unsigned __int128 u128;
typedef uint8_t u8;

// ------------------------------------------------------------------ sha512
// FIPS 180-4, straightforward from the spec.

static const u64 SHA_K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

struct Sha512 {
    u64 h[8];
    u8 buf[128];
    u64 buflen;          // bytes currently in buf
    u64 total;           // total message bytes so far

    void init() {
        static const u64 iv[8] = {
            0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
            0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
            0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
            0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
        memcpy(h, iv, sizeof iv);
        buflen = 0;
        total = 0;
    }

    static inline u64 rotr(u64 x, int n) { return (x >> n) | (x << (64 - n)); }

    void block(const u8* p) {
        u64 w[80];
        for (int i = 0; i < 16; i++) {
            w[i] = ((u64)p[8 * i] << 56) | ((u64)p[8 * i + 1] << 48) |
                   ((u64)p[8 * i + 2] << 40) | ((u64)p[8 * i + 3] << 32) |
                   ((u64)p[8 * i + 4] << 24) | ((u64)p[8 * i + 5] << 16) |
                   ((u64)p[8 * i + 6] << 8) | (u64)p[8 * i + 7];
        }
        for (int i = 16; i < 80; i++) {
            u64 s0 = rotr(w[i - 15], 1) ^ rotr(w[i - 15], 8) ^ (w[i - 15] >> 7);
            u64 s1 = rotr(w[i - 2], 19) ^ rotr(w[i - 2], 61) ^ (w[i - 2] >> 6);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        u64 a = h[0], b = h[1], c = h[2], d = h[3];
        u64 e = h[4], f = h[5], g = h[6], hh = h[7];
        for (int i = 0; i < 80; i++) {
            u64 S1 = rotr(e, 14) ^ rotr(e, 18) ^ rotr(e, 41);
            u64 ch = (e & f) ^ (~e & g);
            u64 t1 = hh + S1 + ch + SHA_K[i] + w[i];
            u64 S0 = rotr(a, 28) ^ rotr(a, 34) ^ rotr(a, 39);
            u64 maj = (a & b) ^ (a & c) ^ (b & c);
            u64 t2 = S0 + maj;
            hh = g; g = f; f = e; e = d + t1;
            d = c; c = b; b = a; a = t1 + t2;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }

    void update(const u8* p, u64 n) {
        total += n;
        if (buflen) {
            u64 take = 128 - buflen;
            if (take > n) take = n;
            memcpy(buf + buflen, p, take);
            buflen += take;
            p += take;
            n -= take;
            if (buflen == 128) { block(buf); buflen = 0; }
        }
        while (n >= 128) { block(p); p += 128; n -= 128; }
        if (n) { memcpy(buf, p, n); buflen = n; }
    }

    void final(u8 out[64]) {
        u64 bits_hi = total >> 61, bits_lo = total << 3;
        u8 pad = 0x80;
        update(&pad, 1);
        static const u8 zeros[128] = {0};
        u64 rem = (buflen <= 112) ? 112 - buflen : 240 - buflen;
        update(zeros, rem);
        u8 lenb[16];
        for (int i = 0; i < 8; i++) lenb[i] = (u8)(bits_hi >> (56 - 8 * i));
        for (int i = 0; i < 8; i++) lenb[8 + i] = (u8)(bits_lo >> (56 - 8 * i));
        update(lenb, 16);
        for (int i = 0; i < 8; i++)
            for (int j = 0; j < 8; j++)
                out[8 * i + j] = (u8)(h[i] >> (56 - 8 * j));
    }
};

// ------------------------------------------------------- field GF(2^255-19)
// Radix-2^51: x = v[0] + v[1]*2^51 + ... + v[4]*2^204.  add/sub carry on
// exit, mul/sq reduce on exit, so every limb stays < 2^52 and u128
// accumulation (5 products of < 2^52 * 2^52 each) can never overflow.

struct fe { u64 v[5]; };

static const u64 MASK51 = (1ULL << 51) - 1;

static const fe FE_ZERO = {{0, 0, 0, 0, 0}};
static const fe FE_ONE = {{1, 0, 0, 0, 0}};
static const fe FE_D = {{0x34dca135978a3ULL, 0x1a8283b156ebdULL,
                         0x5e7a26001c029ULL, 0x739c663a03cbbULL,
                         0x52036cee2b6ffULL}};
static const fe FE_2D = {{0x69b9426b2f159ULL, 0x35050762add7aULL,
                          0x3cf44c0038052ULL, 0x6738cc7407977ULL,
                          0x2406d9dc56dffULL}};
static const fe FE_SQRTM1 = {{0x61b274a0ea0b0ULL, 0xd5a5fc8f189dULL,
                              0x7ef5e9cbd0c60ULL, 0x78595a6804c9eULL,
                              0x2b8324804fc1dULL}};

static inline void fe_carry(fe& r) {
    // two passes: after the first, every limb < 2^51 except possibly a
    // tiny spill into the next; the second settles it.  For ARBITRARY
    // limb magnitudes (frombytes, fold residue) — the add/sub hot path
    // uses the single-pass variant below.
    for (int pass = 0; pass < 2; pass++) {
        u64 c = r.v[4] >> 51;
        r.v[4] &= MASK51;
        r.v[0] += 19 * c;
        for (int i = 0; i < 4; i++) {
            c = r.v[i] >> 51;
            r.v[i] &= MASK51;
            r.v[i + 1] += c;
        }
    }
}

static inline void fe_carry1(fe& r) {
    // ONE pass suffices on the add/sub hot path: the weakly-reduced
    // form (limb < 2^51 + 2^7) is closed under add/sub/mul:
    //   - mul/sq outputs: the final fold "o0 += 19*c" can leave a tail
    //     carry into o1 of up to ~95 < 2^7 (c <= 5*2^51-ish from the
    //     u128 accumulation), every other limb < 2^51 — weakly reduced;
    //   - add of two such values: limbs < 2^52 + 2^8, so each pass-1
    //     carry is <= 2 and the 19*carry fold into limb 0 stays < 2^7
    //     — weakly reduced again;
    //   - sub's 2p bias per limb (2^52 - 2) strictly exceeds any weakly
    //     reduced subtrahend limb, so no underflow;
    //   - mul/sq accumulate 5 products of < 2^52 * 19*2^52 < 2^111
    //     each in u128 — no overflow — and reduce on exit;
    //   - fe_tobytes (hence iszero/isodd) re-runs the full two-pass
    //     carry before canonicalizing, so no consumer reads weak limbs.
    u64 c = r.v[4] >> 51;
    r.v[4] &= MASK51;
    r.v[0] += 19 * c;
    for (int i = 0; i < 4; i++) {
        c = r.v[i] >> 51;
        r.v[i] &= MASK51;
        r.v[i + 1] += c;
    }
}

static inline void fe_add(fe& r, const fe& a, const fe& b) {
    for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + b.v[i];
    fe_carry1(r);
}

// 2p in radix 2^51 (bias so a-b can't underflow for reduced a, b)
static const u64 TWOP0 = 0xFFFFFFFFFFFDAULL;
static const u64 TWOPX = 0xFFFFFFFFFFFFEULL;

static inline void fe_sub(fe& r, const fe& a, const fe& b) {
    r.v[0] = a.v[0] + TWOP0 - b.v[0];
    for (int i = 1; i < 5; i++) r.v[i] = a.v[i] + TWOPX - b.v[i];
    fe_carry1(r);
}

static inline void fe_neg(fe& r, const fe& a) { fe_sub(r, FE_ZERO, a); }

static inline void fe_mul(fe& r, const fe& a, const fe& b) {
    u64 a0 = a.v[0], a1 = a.v[1], a2 = a.v[2], a3 = a.v[3], a4 = a.v[4];
    u64 b0 = b.v[0], b1 = b.v[1], b2 = b.v[2], b3 = b.v[3], b4 = b.v[4];
    u64 t1 = 19 * b1, t2 = 19 * b2, t3 = 19 * b3, t4 = 19 * b4;
    u128 r0 = (u128)a0 * b0 + (u128)a1 * t4 + (u128)a2 * t3 +
              (u128)a3 * t2 + (u128)a4 * t1;
    u128 r1 = (u128)a0 * b1 + (u128)a1 * b0 + (u128)a2 * t4 +
              (u128)a3 * t3 + (u128)a4 * t2;
    u128 r2 = (u128)a0 * b2 + (u128)a1 * b1 + (u128)a2 * b0 +
              (u128)a3 * t4 + (u128)a4 * t3;
    u128 r3 = (u128)a0 * b3 + (u128)a1 * b2 + (u128)a2 * b1 +
              (u128)a3 * b0 + (u128)a4 * t4;
    u128 r4 = (u128)a0 * b4 + (u128)a1 * b3 + (u128)a2 * b2 +
              (u128)a3 * b1 + (u128)a4 * b0;
    u64 c;
    u64 o0 = (u64)r0 & MASK51; c = (u64)(r0 >> 51);
    r1 += c;
    u64 o1 = (u64)r1 & MASK51; c = (u64)(r1 >> 51);
    r2 += c;
    u64 o2 = (u64)r2 & MASK51; c = (u64)(r2 >> 51);
    r3 += c;
    u64 o3 = (u64)r3 & MASK51; c = (u64)(r3 >> 51);
    r4 += c;
    u64 o4 = (u64)r4 & MASK51; c = (u64)(r4 >> 51);
    o0 += 19 * c;
    c = o0 >> 51; o0 &= MASK51; o1 += c;
    r.v[0] = o0; r.v[1] = o1; r.v[2] = o2; r.v[3] = o3; r.v[4] = o4;
}

static inline void fe_sq(fe& r, const fe& a) {
    u64 a0 = a.v[0], a1 = a.v[1], a2 = a.v[2], a3 = a.v[3], a4 = a.v[4];
    u64 a0_2 = 2 * a0, a1_2 = 2 * a1;
    u64 a3_19 = 19 * a3, a4_19 = 19 * a4, a4_38 = 38 * a4, a3_38 = 38 * a3;
    u128 r0 = (u128)a0 * a0 + (u128)a4_38 * a1 + (u128)a3_38 * a2;
    u128 r1 = (u128)a0_2 * a1 + (u128)a4_38 * a2 + (u128)a3_19 * a3;
    u128 r2 = (u128)a0_2 * a2 + (u128)a1 * a1 + (u128)a4_38 * a3;
    u128 r3 = (u128)a0_2 * a3 + (u128)a1_2 * a2 + (u128)a4_19 * a4;
    u128 r4 = (u128)a0_2 * a4 + (u128)a1_2 * a3 + (u128)a2 * a2;
    u64 c;
    u64 o0 = (u64)r0 & MASK51; c = (u64)(r0 >> 51);
    r1 += c;
    u64 o1 = (u64)r1 & MASK51; c = (u64)(r1 >> 51);
    r2 += c;
    u64 o2 = (u64)r2 & MASK51; c = (u64)(r2 >> 51);
    r3 += c;
    u64 o3 = (u64)r3 & MASK51; c = (u64)(r3 >> 51);
    r4 += c;
    u64 o4 = (u64)r4 & MASK51; c = (u64)(r4 >> 51);
    o0 += 19 * c;
    c = o0 >> 51; o0 &= MASK51; o1 += c;
    r.v[0] = o0; r.v[1] = o1; r.v[2] = o2; r.v[3] = o3; r.v[4] = o4;
}

static inline void fe_sqn(fe& r, const fe& a, int n) {
    fe_sq(r, a);
    for (int i = 1; i < n; i++) fe_sq(r, r);
}

static void fe_frombytes(fe& r, const u8 s[32]) {
    u64 w[4];
    for (int i = 0; i < 4; i++) {
        w[i] = 0;
        for (int j = 0; j < 8; j++) w[i] |= (u64)s[8 * i + j] << (8 * j);
    }
    r.v[0] = w[0] & MASK51;
    r.v[1] = ((w[0] >> 51) | (w[1] << 13)) & MASK51;
    r.v[2] = ((w[1] >> 38) | (w[2] << 26)) & MASK51;
    r.v[3] = ((w[2] >> 25) | (w[3] << 39)) & MASK51;
    r.v[4] = (w[3] >> 12) & MASK51;      // masks bit 255 (the sign bit)
}

static void fe_tobytes(u8 s[32], const fe& a) {
    fe t = a;
    fe_carry(t);
    // canonical reduction: add 19, propagate, drop bit 255, subtract 19
    // trick — compute t + 19, if it overflows 2^255 then t >= p
    u64 q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51;              // q = 1 iff t >= p
    t.v[0] += 19 * q;
    u64 c;
    c = t.v[0] >> 51; t.v[0] &= MASK51; t.v[1] += c;
    c = t.v[1] >> 51; t.v[1] &= MASK51; t.v[2] += c;
    c = t.v[2] >> 51; t.v[2] &= MASK51; t.v[3] += c;
    c = t.v[3] >> 51; t.v[3] &= MASK51; t.v[4] += c;
    t.v[4] &= MASK51;                    // drop 2^255
    u64 w0 = t.v[0] | (t.v[1] << 51);
    u64 w1 = (t.v[1] >> 13) | (t.v[2] << 38);
    u64 w2 = (t.v[2] >> 26) | (t.v[3] << 25);
    u64 w3 = (t.v[3] >> 39) | (t.v[4] << 12);
    u64 w[4] = {w0, w1, w2, w3};
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++) s[8 * i + j] = (u8)(w[i] >> (8 * j));
}

static bool fe_iszero(const fe& a) {
    u8 s[32];
    fe_tobytes(s, a);
    u8 acc = 0;
    for (int i = 0; i < 32; i++) acc |= s[i];
    return acc == 0;
}

static bool fe_isodd(const fe& a) {
    u8 s[32];
    fe_tobytes(s, a);
    return s[0] & 1;
}

// shared prefix of the 2^255-21 and 2^252-3 addition chains: returns
// z^(2^250 - 1) in r250, plus z^11 and z^(2^10-1) used by the callers
static void fe_chain250(fe& r250, fe& z11, fe& z10_0, const fe& z) {
    fe z2, t, z9, z5_0;
    fe_sq(z2, z);                        // 2
    fe_sqn(t, z2, 2);                    // 8
    fe_mul(z9, t, z);                    // 9
    fe_mul(z11, z9, z2);                 // 11
    fe_sq(t, z11);                       // 22
    fe_mul(z5_0, t, z9);                 // 2^5 - 1
    fe_sqn(t, z5_0, 5);
    fe_mul(z10_0, t, z5_0);              // 2^10 - 1
    fe_sqn(t, z10_0, 10);
    fe mid;
    fe_mul(mid, t, z10_0);               // 2^20 - 1
    fe_sqn(t, mid, 20);
    fe_mul(t, t, mid);                   // 2^40 - 1
    fe_sqn(t, t, 10);
    fe z50_0;
    fe_mul(z50_0, t, z10_0);             // 2^50 - 1
    fe_sqn(t, z50_0, 50);
    fe z100_0;
    fe_mul(z100_0, t, z50_0);            // 2^100 - 1
    fe_sqn(t, z100_0, 100);
    fe_mul(t, t, z100_0);                // 2^200 - 1
    fe_sqn(t, t, 50);
    fe_mul(r250, t, z50_0);              // 2^250 - 1
}

static void fe_invert(fe& r, const fe& a) {
    // a^(p-2) = a^(2^255 - 21)
    fe z250, z11, z10_0, t;
    fe_chain250(z250, z11, z10_0, a);
    fe_sqn(t, z250, 5);                  // 2^255 - 2^5
    fe_mul(r, t, z11);                   // 2^255 - 32 + 11 = 2^255 - 21
}

static void fe_pow2523(fe& r, const fe& a) {
    // a^((p-5)/8) = a^(2^252 - 3)
    fe z250, z11, z10_0, t;
    fe_chain250(z250, z11, z10_0, a);
    fe_sqn(t, z250, 2);                  // 2^252 - 4
    fe_mul(r, t, a);                     // 2^252 - 3
}

// ------------------------------------------------------------ scalars mod L

// L = 2^252 + 27742317777372353535851937790883648493, little-endian limbs
static const u64 SC_L[4] = {0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL,
                            0x0ULL, 0x1000000000000000ULL};
// mu = floor(2^512 / L), 260 bits (5 limbs)
static const u64 SC_MU[5] = {0xed9ce5a30a2c131bULL, 0x2106215d086329a7ULL,
                             0xffffffffffffffebULL, 0xffffffffffffffffULL,
                             0xfULL};

struct sc { u64 v[4]; };     // always < L

static inline int sc_geq(const u64 a[4], const u64 b[4]) {
    for (int i = 3; i >= 0; i--) {
        if (a[i] > b[i]) return 1;
        if (a[i] < b[i]) return 0;
    }
    return 1;
}

static inline void sc_sub4(u64 a[4], const u64 b[4]) {
    u64 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u64 bi = b[i] + borrow;
        borrow = (bi < borrow) ? 1 : (a[i] < bi ? 1 : 0);
        a[i] = a[i] - bi;
    }
}

// Barrett: reduce a 512-bit value (8 limbs LE) mod L
static void sc_reduce512(sc& r, const u64 x[8]) {
    // q = (x * mu) >> 512, keeping only the limbs we need
    u64 prod[13] = {0};
    for (int i = 0; i < 8; i++) {
        u64 carry = 0;
        for (int j = 0; j < 5; j++) {
            u128 t = (u128)x[i] * SC_MU[j] + prod[i + j] + carry;
            prod[i + j] = (u64)t;
            carry = (u64)(t >> 64);
        }
        prod[i + 5] = carry;
    }
    u64 q[5];
    for (int i = 0; i < 5; i++) q[i] = prod[8 + i];
    // r = x - q*L  (low 8 limbs; result < 3L fits in 4)
    u64 ql[8] = {0};
    for (int i = 0; i < 5; i++) {
        u64 carry = 0;
        for (int j = 0; j < 4 && i + j < 8; j++) {
            u128 t = (u128)q[i] * SC_L[j] + ql[i + j] + carry;
            ql[i + j] = (u64)t;
            carry = (u64)(t >> 64);
        }
        if (i + 4 < 8) ql[i + 4] += carry;
    }
    u64 rem[8];
    u64 borrow = 0;
    for (int i = 0; i < 8; i++) {
        u64 bi = ql[i] + borrow;
        borrow = (bi < borrow) ? 1 : (x[i] < bi ? 1 : 0);
        rem[i] = x[i] - bi;
    }
    // at most two conditional subtracts (r < 3L and L > 2^252)
    for (int k = 0; k < 2; k++)
        if (rem[4] | rem[5] | rem[6] | rem[7] || sc_geq(rem, SC_L)) {
            u64 borrow2 = 0;
            for (int i = 0; i < 8; i++) {
                u64 bi = (i < 4 ? SC_L[i] : 0) + borrow2;
                borrow2 = (bi < borrow2) ? 1 : (rem[i] < bi ? 1 : 0);
                rem[i] = rem[i] - bi;
            }
        }
    for (int i = 0; i < 4; i++) r.v[i] = rem[i];
}

static void sc_from_bytes64(sc& r, const u8 b[64]) {
    u64 x[8];
    for (int i = 0; i < 8; i++) {
        x[i] = 0;
        for (int j = 0; j < 8; j++) x[i] |= (u64)b[8 * i + j] << (8 * j);
    }
    sc_reduce512(r, x);
}

// load 32 bytes; returns false when the value is >= L (ZIP-215 rejects
// non-canonical S)
static bool sc_from_bytes32_checked(sc& r, const u8 b[32]) {
    for (int i = 0; i < 4; i++) {
        r.v[i] = 0;
        for (int j = 0; j < 8; j++) r.v[i] |= (u64)b[8 * i + j] << (8 * j);
    }
    return !sc_geq(r.v, SC_L);
}

static void sc_mul(sc& r, const sc& a, const sc& b) {
    u64 prod[8] = {0};
    for (int i = 0; i < 4; i++) {
        u64 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 t = (u128)a.v[i] * b.v[j] + prod[i + j] + carry;
            prod[i + j] = (u64)t;
            carry = (u64)(t >> 64);
        }
        prod[i + 4] = carry;
    }
    sc_reduce512(r, prod);
}

static void sc_add(sc& r, const sc& a, const sc& b) {
    u64 carry = 0;
    for (int i = 0; i < 4; i++) {
        u64 s = a.v[i] + carry;
        carry = (s < carry) ? 1 : 0;
        r.v[i] = s + b.v[i];
        if (r.v[i] < s) carry = 1;
    }
    if (carry || sc_geq(r.v, SC_L)) sc_sub4(r.v, SC_L);
}

static inline int sc_bit(const sc& a, int i) {
    return (int)((a.v[i >> 6] >> (i & 63)) & 1);
}

static inline int sc_window(const sc& a, int pos, int width) {
    // bits [pos, pos+width) of the 256-bit scalar, little-endian
    int word = pos >> 6, shift = pos & 63;
    u64 w = a.v[word] >> shift;
    if (shift + width > 64 && word + 1 < 4)
        w |= a.v[word + 1] << (64 - shift);
    return (int)(w & ((1ULL << width) - 1));
}

// ----------------------------------------------------------- group elements
// Extended coordinates (X:Y:Z:T), x = X/Z, y = Y/Z, T = XY/Z.

struct ge { fe X, Y, Z, T; };

static const ge GE_ID = {FE_ZERO, FE_ONE, FE_ONE, FE_ZERO};

// the ed25519 base point, fully constant (T = Bx*By mod p precomputed)
// so there is no runtime init and no init race across threads
static const ge BASE_POINT = {
    {{0x62d608f25d51aULL, 0x412a4b4f6592aULL, 0x75b7171a4b31dULL,
      0x1ff60527118feULL, 0x216936d3cd6e5ULL}},
    {{0x6666666666658ULL, 0x4ccccccccccccULL, 0x1999999999999ULL,
      0x3333333333333ULL, 0x6666666666666ULL}},
    FE_ONE,
    {{0x68ab3a5b7dda3ULL, 0xeea2a5eadbbULL, 0x2af8df483c27eULL,
      0x332b375274732ULL, 0x67875f0fd78b7ULL}}};

// unified addition (complete for a=-1 square, d nonsquare: every curve
// point including ZIP-215's small-torsion components)
static void ge_add(ge& r, const ge& p, const ge& q) {
    fe a, b, c, d, e, f, g, h, t;
    fe_sub(a, p.Y, p.X);
    fe_sub(t, q.Y, q.X);
    fe_mul(a, a, t);                    // A = (Y1-X1)(Y2-X2)
    fe_add(b, p.Y, p.X);
    fe_add(t, q.Y, q.X);
    fe_mul(b, b, t);                    // B = (Y1+X1)(Y2+X2)
    fe_mul(c, p.T, q.T);
    fe_mul(c, c, FE_2D);                // C = 2d T1 T2
    fe_mul(d, p.Z, q.Z);
    fe_add(d, d, d);                    // D = 2 Z1 Z2
    fe_sub(e, b, a);
    fe_sub(f, d, c);
    fe_add(g, d, c);
    fe_add(h, b, a);
    fe_mul(r.X, e, f);
    fe_mul(r.Y, g, h);
    fe_mul(r.T, e, h);
    fe_mul(r.Z, f, g);
}

static void ge_double(ge& r, const ge& p) {
    // dbl-2008-hwcd with a = -1 (D = -A folded into each expression)
    fe a, b, c, e, f, g, h, t;
    fe_sq(a, p.X);                      // A = X^2
    fe_sq(b, p.Y);                      // B = Y^2
    fe_sq(c, p.Z);
    fe_add(c, c, c);                    // C = 2 Z^2
    fe_add(t, p.X, p.Y);
    fe_sq(t, t);
    fe_sub(e, t, a);
    fe_sub(e, e, b);                    // E = (X+Y)^2 - A - B
    fe_sub(g, b, a);                    // G = D + B = B - A
    fe_sub(f, g, c);                    // F = G - C
    fe_add(h, a, b);
    fe_neg(h, h);                       // H = D - B = -(A + B)
    fe_mul(r.X, e, f);
    fe_mul(r.Y, g, h);
    fe_mul(r.T, e, h);
    fe_mul(r.Z, f, g);
}

static void ge_neg(ge& r, const ge& p) {
    fe_neg(r.X, p.X);
    r.Y = p.Y;
    r.Z = p.Z;
    fe_neg(r.T, p.T);
}

static bool ge_is_identity(const ge& p) {
    // x == 0 and y == 1  <=>  X == 0 and Y == Z
    fe d;
    fe_sub(d, p.Y, p.Z);
    return fe_iszero(p.X) && fe_iszero(d);
}

// ZIP-215 permissive decompression: non-canonical y accepted (value taken
// mod p), x=0 with sign=1 accepted.  Matches the repo's pure-Python oracle.
static bool ge_decompress_zip215(ge& r, const u8 s[32]) {
    fe y, y2, u, v, x, chk, num;
    fe_frombytes(y, s);                 // masks bit 255; y may be >= p (ok)
    int sign = s[31] >> 7;
    fe_sq(y2, y);
    fe_sub(u, y2, FE_ONE);              // u = y^2 - 1
    fe_mul(v, y2, FE_D);
    fe_add(v, v, FE_ONE);               // v = d y^2 + 1
    // x = u v^3 (u v^7)^((p-5)/8)
    fe v2, v3, v7, t;
    fe_sq(v2, v);
    fe_mul(v3, v2, v);
    fe_sq(t, v3);
    fe_mul(v7, t, v);
    fe_mul(t, u, v7);
    fe_pow2523(t, t);
    fe_mul(x, u, v3);
    fe_mul(x, x, t);
    // check v x^2 == +-u
    fe_sq(chk, x);
    fe_mul(chk, chk, v);
    fe_sub(num, chk, u);
    if (!fe_iszero(num)) {
        fe_add(num, chk, u);
        if (!fe_iszero(num)) return false;   // no square root: bad point
        fe_mul(x, x, FE_SQRTM1);
    }
    if ((int)fe_isodd(x) != sign) fe_neg(x, x);
    r.X = x;
    r.Y = y;
    r.Z = FE_ONE;
    fe_mul(r.T, x, y);
    return true;
}

// Affine Niels form of a Z=1 point: (Y+X, Y-X, 2d*T).  Mixed addition
// against it costs 7 fe_mul instead of unified ge_add's 9 — the Z2
// multiply disappears (Z2 == 1) and the 2d*T2 product is precomputed.
// Every MSM input is freshly decompressed (Z == 1 by construction), so
// Pippenger's bucket accumulation — the dominant cost at commit sizes —
// rides this form.
struct geNiels { fe ypx, ymx, t2d; };

static inline void ge_to_niels(geNiels& r, const ge& p) {
    fe_add(r.ypx, p.Y, p.X);
    fe_sub(r.ymx, p.Y, p.X);
    fe_mul(r.t2d, p.T, FE_2D);
}

static void ge_madd(ge& r, const ge& p, const geNiels& q) {
    fe a, b, c, d, e, f, g, h, t;
    fe_sub(t, p.Y, p.X);
    fe_mul(a, t, q.ymx);                // A = (Y1-X1)(Y2-X2)
    fe_add(t, p.Y, p.X);
    fe_mul(b, t, q.ypx);                // B = (Y1+X1)(Y2+X2)
    fe_mul(c, p.T, q.t2d);              // C = 2d T1 T2
    fe_add(d, p.Z, p.Z);                // D = 2 Z1 (Z2 == 1)
    fe_sub(e, b, a);
    fe_sub(f, d, c);
    fe_add(g, d, c);
    fe_add(h, b, a);
    fe_mul(r.X, e, f);
    fe_mul(r.Y, g, h);
    fe_mul(r.T, e, h);
    fe_mul(r.Z, f, g);
}

// p - q for a Niels q: negation swaps (ypx, ymx) and flips t2d's sign,
// which folds into swapped uses and C's sign in F/G
static void ge_msub(ge& r, const ge& p, const geNiels& q) {
    fe a, b, c, d, e, f, g, h, t;
    fe_sub(t, p.Y, p.X);
    fe_mul(a, t, q.ypx);
    fe_add(t, p.Y, p.X);
    fe_mul(b, t, q.ymx);
    fe_mul(c, p.T, q.t2d);
    fe_add(d, p.Z, p.Z);
    fe_sub(e, b, a);
    fe_add(f, d, c);                    // F = D + C (C negated)
    fe_sub(g, d, c);                    // G = D - C
    fe_add(h, b, a);
    fe_mul(r.X, e, f);
    fe_mul(r.Y, g, h);
    fe_mul(r.T, e, h);
    fe_mul(r.Z, f, g);
}

// fixed-window (4-bit) scalar multiplication for the single-verify path
static void ge_scalarmul(ge& r, const sc& k, const ge& p) {
    ge tab[16];
    tab[0] = GE_ID;
    tab[1] = p;
    for (int i = 2; i < 16; i++) ge_add(tab[i], tab[i - 1], p);
    ge acc = GE_ID;
    for (int w = 63; w >= 0; w--) {
        for (int i = 0; i < 4; i++) ge_double(acc, acc);
        int nib = sc_window(k, 4 * w, 4);
        if (nib) ge_add(acc, acc, tab[nib]);
    }
    r = acc;
}

// ------------------------------------------------- Pippenger multiscalar
// sum_i [scalars[i]] points[i] over 253-bit scalars.

static void ge_msm(ge& r, const std::vector<ge>& points,
                   const std::vector<sc>& scalars) {
    // Pippenger with SIGNED digits: each window digit is recoded into
    // [-2^(c-1), 2^(c-1)] with carries, so a window of width c needs
    // only 2^(c-1) buckets — for the same bucket-aggregation cost the
    // window can be one bit wider, cutting window count ~10%.
    size_t n = points.size();
    if (n == 0) { r = GE_ID; return; }
    int c;                               // window width
    if (n < 8) c = 3;
    else if (n < 32) c = 5;
    else if (n < 128) c = 6;
    else if (n < 512) c = 7;
    else if (n < 1536) c = 8;
    else if (n < 6144) c = 9;
    else if (n < 16384) c = 10;
    else c = 12;
    int nbuckets = 1 << (c - 1);         // digit magnitudes 1..2^(c-1)
    int nwindows = (254 + c - 1) / c;    // 254: room for the top carry
    // recode every scalar (LSB window first, carry into the next);
    // scalars < L < 2^253, so the top window absorbs the final carry
    std::vector<int16_t> digits(n * nwindows);
    for (size_t i = 0; i < n; i++) {
        int carry = 0;
        for (int w = 0; w < nwindows; w++) {
            int pos = w * c;
            int width = (pos + c <= 253) ? c : (pos < 253 ? 253 - pos : 0);
            int d = (width > 0 ? sc_window(scalars[i], pos, width) : 0)
                    + carry;
            if (d > nbuckets && w < nwindows - 1) {
                d -= (1 << c);
                carry = 1;
            } else {
                carry = 0;
            }
            digits[i * nwindows + w] = (int16_t)d;
        }
    }
    // bucket adds dominate (n per window vs 2*nbuckets suffix adds);
    // inputs are decompressed points with Z == 1, so they ride the 7-mul
    // Niels mixed add.  The rare general caller (Z != 1) keeps unified
    // adds.
    bool all_affine = true;
    for (size_t i = 0; i < n && all_affine; i++)
        all_affine = memcmp(&points[i].Z, &FE_ONE, sizeof(fe)) == 0;
    std::vector<geNiels> pre;
    if (all_affine) {
        pre.resize(n);
        for (size_t i = 0; i < n; i++) ge_to_niels(pre[i], points[i]);
    }
    std::vector<ge> buckets(nbuckets);
    ge acc = GE_ID;
    for (int w = nwindows - 1; w >= 0; w--) {
        for (int i = 0; i < c; i++) ge_double(acc, acc);
        for (int i = 0; i < nbuckets; i++) buckets[i] = GE_ID;
        for (size_t i = 0; i < n; i++) {
            int d = digits[i * nwindows + w];
            if (d == 0) continue;
            if (all_affine) {
                if (d > 0) ge_madd(buckets[d - 1], buckets[d - 1], pre[i]);
                else ge_msub(buckets[-d - 1], buckets[-d - 1], pre[i]);
                continue;
            }
            if (d > 0) {
                ge_add(buckets[d - 1], buckets[d - 1], points[i]);
            } else {
                ge npt;
                ge_neg(npt, points[i]);
                ge_add(buckets[-d - 1], buckets[-d - 1], npt);
            }
        }
        // sum_j j*bucket[j] via suffix sums
        ge running = GE_ID, wsum = GE_ID;
        for (int j = nbuckets - 1; j >= 0; j--) {
            ge_add(running, running, buckets[j]);
            ge_add(wsum, wsum, running);
        }
        ge_add(acc, acc, wsum);
    }
    r = acc;
}

// ------------------------------------------------------------- public API

static void hash_ram(sc& h, const u8 rbytes[32], const u8 pub[32],
                     const u8* msg, u64 msg_len) {
    Sha512 ctx;
    ctx.init();
    ctx.update(rbytes, 32);
    ctx.update(pub, 32);
    ctx.update(msg, msg_len);
    u8 out[64];
    ctx.final(out);
    sc_from_bytes64(h, out);
}

#if defined(__AVX2__)
#include <immintrin.h>

// Four independent SHA-512 streams over EQUAL-LENGTH inputs in the
// 64-bit lanes of one ymm register — the batch-verify hash_ram calls
// are embarrassingly lane-parallel, and dense VerifyCommit rows all
// share one length, so quads are the common case.  Verified against
// the scalar implementation lane-for-lane (and transitively against
// hashlib by the kernel tests).

static inline __m256i mm_rotr64(__m256i x, int n) {
    return _mm256_or_si256(_mm256_srli_epi64(x, n),
                           _mm256_slli_epi64(x, 64 - n));
}

static void sha512_x4(const u8* m[4], u64 len, u8 out[4][64]) {
    const __m256i iv[8] = {
        _mm256_set1_epi64x((long long)0x6a09e667f3bcc908ULL),
        _mm256_set1_epi64x((long long)0xbb67ae8584caa73bULL),
        _mm256_set1_epi64x((long long)0x3c6ef372fe94f82bULL),
        _mm256_set1_epi64x((long long)0xa54ff53a5f1d36f1ULL),
        _mm256_set1_epi64x((long long)0x510e527fade682d1ULL),
        _mm256_set1_epi64x((long long)0x9b05688c2b3e6c1fULL),
        _mm256_set1_epi64x((long long)0x1f83d9abfb41bd6bULL),
        _mm256_set1_epi64x((long long)0x5be0cd19137e2179ULL)};
    __m256i h[8];
    for (int i = 0; i < 8; i++) h[i] = iv[i];

    // identical lengths -> identical padding layout for all four lanes
    u64 tail_len = len % 128;
    u64 full = len - tail_len;
    u64 pad_total = (tail_len + 17 <= 128) ? 128 : 256;
    u8 tail[4][256];
    for (int l = 0; l < 4; l++) {
        memcpy(tail[l], m[l] + full, tail_len);
        tail[l][tail_len] = 0x80;
        memset(tail[l] + tail_len + 1, 0, pad_total - tail_len - 1 - 16);
        u64 bits_hi = len >> 61, bits_lo = len << 3;
        for (int i = 0; i < 8; i++) {
            tail[l][pad_total - 16 + i] = (u8)(bits_hi >> (56 - 8 * i));
            tail[l][pad_total - 8 + i] = (u8)(bits_lo >> (56 - 8 * i));
        }
    }

    u64 total_blocks = (full + pad_total) / 128;
    for (u64 blk = 0; blk < total_blocks; blk++) {
        const u8* p[4];
        for (int l = 0; l < 4; l++)
            p[l] = (blk * 128 < full) ? m[l] + blk * 128
                                      : tail[l] + (blk * 128 - full);
        __m256i w[80];
        for (int i = 0; i < 16; i++) {
            u64 w0, w1, w2, w3;
            memcpy(&w0, p[0] + 8 * i, 8);
            memcpy(&w1, p[1] + 8 * i, 8);
            memcpy(&w2, p[2] + 8 * i, 8);
            memcpy(&w3, p[3] + 8 * i, 8);
            w[i] = _mm256_set_epi64x(
                (long long)__builtin_bswap64(w3),
                (long long)__builtin_bswap64(w2),
                (long long)__builtin_bswap64(w1),
                (long long)__builtin_bswap64(w0));
        }
        for (int i = 16; i < 80; i++) {
            __m256i s0 = _mm256_xor_si256(
                _mm256_xor_si256(mm_rotr64(w[i - 15], 1),
                                 mm_rotr64(w[i - 15], 8)),
                _mm256_srli_epi64(w[i - 15], 7));
            __m256i s1 = _mm256_xor_si256(
                _mm256_xor_si256(mm_rotr64(w[i - 2], 19),
                                 mm_rotr64(w[i - 2], 61)),
                _mm256_srli_epi64(w[i - 2], 6));
            w[i] = _mm256_add_epi64(
                _mm256_add_epi64(w[i - 16], s0),
                _mm256_add_epi64(w[i - 7], s1));
        }
        __m256i a = h[0], b = h[1], c = h[2], d = h[3];
        __m256i e = h[4], f = h[5], g = h[6], hh = h[7];
        for (int i = 0; i < 80; i++) {
            __m256i S1 = _mm256_xor_si256(
                _mm256_xor_si256(mm_rotr64(e, 14), mm_rotr64(e, 18)),
                mm_rotr64(e, 41));
            __m256i ch = _mm256_xor_si256(
                _mm256_and_si256(e, f),
                _mm256_andnot_si256(e, g));
            __m256i t1 = _mm256_add_epi64(
                _mm256_add_epi64(_mm256_add_epi64(hh, S1), ch),
                _mm256_add_epi64(
                    _mm256_set1_epi64x((long long)SHA_K[i]), w[i]));
            __m256i S0 = _mm256_xor_si256(
                _mm256_xor_si256(mm_rotr64(a, 28), mm_rotr64(a, 34)),
                mm_rotr64(a, 39));
            __m256i maj = _mm256_xor_si256(
                _mm256_xor_si256(_mm256_and_si256(a, b),
                                 _mm256_and_si256(a, c)),
                _mm256_and_si256(b, c));
            __m256i t2 = _mm256_add_epi64(S0, maj);
            hh = g; g = f; f = e; e = _mm256_add_epi64(d, t1);
            d = c; c = b; b = a; a = _mm256_add_epi64(t1, t2);
        }
        h[0] = _mm256_add_epi64(h[0], a);
        h[1] = _mm256_add_epi64(h[1], b);
        h[2] = _mm256_add_epi64(h[2], c);
        h[3] = _mm256_add_epi64(h[3], d);
        h[4] = _mm256_add_epi64(h[4], e);
        h[5] = _mm256_add_epi64(h[5], f);
        h[6] = _mm256_add_epi64(h[6], g);
        h[7] = _mm256_add_epi64(h[7], hh);
    }
    for (int i = 0; i < 8; i++) {
        u64 lanes[4];
        _mm256_storeu_si256((__m256i*)lanes, h[i]);
        for (int l = 0; l < 4; l++) {
            u64 be = __builtin_bswap64(lanes[l]);
            memcpy(out[l] + 8 * i, &be, 8);
        }
    }
}

// hash_ram for four lanes sharing one message length: assembles the
// R||A||M buffers and runs the 4-way compressor
static void hash_ram_x4(sc h[4], const u8* rb[4], const u8* pb[4],
                        const u8* msgs[4], u64 msg_len) {
    static thread_local std::vector<u8> buf;
    u64 total = 64 + msg_len;
    if (buf.size() < 4 * total) buf.resize(4 * total);
    const u8* ptrs[4];
    for (int l = 0; l < 4; l++) {
        u8* b = buf.data() + l * total;
        memcpy(b, rb[l], 32);
        memcpy(b + 32, pb[l], 32);
        memcpy(b + 64, msgs[l], msg_len);
        ptrs[l] = b;
    }
    u8 out[4][64];
    sha512_x4(ptrs, total, out);
    for (int l = 0; l < 4; l++) sc_from_bytes64(h[l], out[l]);
}
#endif  // __AVX2__

// Decompressed-pubkey cache: validator sets are ~static across heights,
// so the SAME A points decompress every commit; R points are unique per
// signature and never cached.  Open-addressed, bounded, guarded by a
// mutex (ctypes releases the GIL, so concurrent batch calls are real).
// The analogue of the reference's expanded-pubkey cache
// (crypto/ed25519/ed25519.go:42-67, cacheSize 4096).
static const u64 A_CACHE_SLOTS = 32768;     // power of two; sized so a
// 10k-validator set (the headline scale) fits with ~11% collision
// probability instead of thrashing — 8192 single-slot buckets evicted
// ~37% of a 10k-key working set EVERY batch (~3 MB, allocated lazily)
struct ACacheEntry { u8 pub[32]; ge point; bool used; };
static ACacheEntry* A_CACHE = nullptr;
static std::mutex A_CACHE_MU;

static inline u64 pub_hash(const u8* pub) {
    u64 h = 1469598103934665603ULL;          // FNV-1a over the 32 bytes
    for (int i = 0; i < 32; i++) { h ^= pub[i]; h *= 1099511628211ULL; }
    return h;
}

// true + point on hit; on miss decompresses (false if invalid) and fills
// the slot (evict-on-collision: bounded memory, no tombstones).  The
// mutex guards only the lookup and the insert — the expensive
// decompression runs OUTSIDE it, so concurrent batch calls serialize on
// memcpy-sized critical sections, not on field exponentiations.
static bool a_decompress_cached(ge& out, const u8* pub) {
    u64 slot = pub_hash(pub) & (A_CACHE_SLOTS - 1);
    {
        std::lock_guard<std::mutex> lk(A_CACHE_MU);
        if (A_CACHE == nullptr)
            A_CACHE = new ACacheEntry[A_CACHE_SLOTS]();
        ACacheEntry& e = A_CACHE[slot];
        if (e.used && memcmp(e.pub, pub, 32) == 0) {
            out = e.point;
            return true;
        }
    }
    if (!ge_decompress_zip215(out, pub)) return false;
    {
        std::lock_guard<std::mutex> lk(A_CACHE_MU);
        ACacheEntry& e = A_CACHE[slot];
        memcpy(e.pub, pub, 32);
        e.point = out;
        e.used = true;
    }
    return true;
}


// compress to the wire encoding: y with sign(x) in the top bit
static void ge_compress(u8 out[32], const ge& p) {
    fe zi, x, y;
    fe_invert(zi, p.Z);
    fe_mul(x, p.X, zi);
    fe_mul(y, p.Y, zi);
    fe_tobytes(out, y);
    if (fe_isodd(x)) out[31] |= 0x80;
}

// expanded secret: a = clamp(SHA512(seed)[0:32]) mod L, prefix = [32:64].
// Reduction mod L before the ladder is sound: B has order L.
static void ed25519_expand_seed(const u8* seed, sc& a, u8 prefix[32],
                                u8 pub[32]) {
    u8 h[64];
    Sha512 sh;
    sh.init();
    sh.update(seed, 32);
    sh.final(h);
    h[0] &= 248; h[31] &= 127; h[31] |= 64;
    u8 wide[64] = {0};
    memcpy(wide, h, 32);
    sc_from_bytes64(a, wide);
    memcpy(prefix, h + 32, 32);
    ge A;
    ge_scalarmul(A, a, BASE_POINT);
    ge_compress(pub, A);
}

extern "C" {

// public key from a 32-byte seed (RFC 8032 key generation) — the host
// fallback for environments without the `cryptography` wheel
void ed25519_pubkey(const u8* seed, u8* out32) {
    sc a;
    u8 prefix[32];
    ed25519_expand_seed(seed, a, prefix, out32);
}

// RFC 8032 deterministic signature from a 32-byte seed
void ed25519_sign(const u8* seed, const u8* msg, u64 msg_len, u8* sig64) {
    sc a;
    u8 prefix[32], pub[32];
    ed25519_expand_seed(seed, a, prefix, pub);
    u8 r64[64];
    Sha512 s2;
    s2.init();
    s2.update(prefix, 32);
    s2.update(msg, msg_len);
    s2.final(r64);
    sc r;
    sc_from_bytes64(r, r64);
    ge R;
    ge_scalarmul(R, r, BASE_POINT);
    ge_compress(sig64, R);
    u8 k64[64];
    Sha512 s3;
    s3.init();
    s3.update(sig64, 32);
    s3.update(pub, 32);
    s3.update(msg, msg_len);
    s3.final(k64);
    sc k, ka, S;
    sc_from_bytes64(k, k64);
    sc_mul(ka, k, a);
    sc_add(S, r, ka);
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            sig64[32 + 8 * i + j] = (u8)(S.v[i] >> (8 * j));
}

// single ZIP-215 verification; returns 1 (valid) / 0 (invalid)
int ed25519_verify(const u8* pub, const u8* sig, const u8* msg,
                   u64 msg_len) {
    sc s;
    if (!sc_from_bytes32_checked(s, sig + 32)) return 0;
    ge A, R;
    if (!ge_decompress_zip215(A, pub)) return 0;
    if (!ge_decompress_zip215(R, sig)) return 0;
    sc h;
    hash_ram(h, sig, pub, msg, msg_len);
    // [8]([s]B - [h]A - R) == identity
    ge sB, hA, T, nhA, nR;
    ge_scalarmul(sB, s, BASE_POINT);
    ge_scalarmul(hA, h, A);
    ge_neg(nhA, hA);
    ge_neg(nR, R);
    ge_add(T, sB, nhA);
    ge_add(T, T, nR);
    ge_double(T, T);
    ge_double(T, T);
    ge_double(T, T);
    return ge_is_identity(T) ? 1 : 0;
}

// RLC batch verification: 1 iff EVERY signature is ZIP-215-valid (with
// probability 1 - 2^-127 over the z_i; callers fall back to per-signature
// verification on 0 to localize failures, like the reference's voi path).
// msgs holds all messages: packed back-to-back when msg_stride == 0, or
// as fixed-stride rows (the dense fast path hands its row matrix
// directly, no repacking) otherwise; msg_lens[i] are the true lengths.
int ed25519_batch_verify(const u8* pubs, const u8* sigs, const u8* msgs,
                         const u64* msg_lens, u64 n, const u8* seed32,
                         u64 msg_stride) {
    if (n == 0) return 0;
    std::vector<ge> points;
    std::vector<sc> scalars;
    points.reserve(2 * n + 1);
    scalars.reserve(2 * n + 1);
    sc s_total = {{0, 0, 0, 0}};
    // cheap structural checks FIRST (canonical s, decompressible A):
    // a bad lane must fail before the whole batch is hashed, not after
    // (the A results warm the cache for the main loop; R decompression
    // stays in the main loop — its cost is symmetric with the hash)
    for (u64 i = 0; i < n; i++) {
        sc s;
        if (!sc_from_bytes32_checked(s, sigs + 64 * i + 32)) return 0;
        ge A;
        if (!a_decompress_cached(A, pubs + 32 * i)) return 0;
    }
    // hash phase: h_i = SHA-512(R_i || A_i || M_i) mod L, four lanes
    // per AVX2 pass when consecutive lanes share a message length
    // (dense VerifyCommit rows always do); scalar for the remainder
    std::vector<sc> hs(n);
    {
        std::vector<u64> offs;
        if (!msg_stride) {               // packed mode only: stride mode
            offs.resize(n);              // never reads the prefix sums
            u64 off = 0;
            for (u64 i = 0; i < n; i++) { offs[i] = off; off += msg_lens[i]; }
        }
        auto mptr = [&](u64 i) {
            return msg_stride ? msgs + i * msg_stride : msgs + offs[i];
        };
        u64 i = 0;
        while (i < n) {
#if defined(__AVX2__)
            if (i + 4 <= n && msg_lens[i] == msg_lens[i + 1]
                && msg_lens[i] == msg_lens[i + 2]
                && msg_lens[i] == msg_lens[i + 3]) {
                const u8 *rb[4], *pb[4], *mp[4];
                for (int l = 0; l < 4; l++) {
                    rb[l] = sigs + 64 * (i + l);
                    pb[l] = pubs + 32 * (i + l);
                    mp[l] = mptr(i + l);
                }
                hash_ram_x4(&hs[i], rb, pb, mp, msg_lens[i]);
                i += 4;
                continue;
            }
#endif
            hash_ram(hs[i], sigs + 64 * i, pubs + 32 * i, mptr(i),
                     msg_lens[i]);
            i++;
        }
    }
    // z_i: 128 independent bits each, four lanes per SHA-512(seed ||
    // blockidx) call (the 64-byte digest yields 4x16 bytes) — the
    // values only need to be unpredictable per batch, and one hash per
    // four lanes quarters the derivation cost
    u8 zblock[64];
    for (u64 i = 0; i < n; i++) {
        const u8* pub = pubs + 32 * i;
        const u8* sig = sigs + 64 * i;
        sc s;
        if (!sc_from_bytes32_checked(s, sig + 32)) return 0;
        ge A, R;
        if (!a_decompress_cached(A, pub)) return 0;
        if (!ge_decompress_zip215(R, sig)) return 0;
        const sc& h = hs[i];
        if (i % 4 == 0) {
            Sha512 zc;
            zc.init();
            zc.update(seed32, 32);
            u64 blk = i / 4;
            u8 ib[8];
            for (int j = 0; j < 8; j++) ib[j] = (u8)(blk >> (8 * j));
            zc.update(ib, 8);
            zc.final(zblock);
        }
        const u8* zb = zblock + 16 * (i % 4);
        sc z = {{0, 0, 0, 0}};
        for (int j = 0; j < 8; j++) z.v[0] |= (u64)zb[j] << (8 * j);
        for (int j = 0; j < 8; j++) z.v[1] |= (u64)zb[8 + j] << (8 * j);
        z.v[0] |= 1;
        // s_total += z*s ; points += { -R with z, -A with z*h }
        sc zs, zh;
        sc_mul(zs, z, s);
        sc_add(s_total, s_total, zs);
        sc_mul(zh, z, h);
        ge nR, nA;
        ge_neg(nR, R);
        ge_neg(nA, A);
        points.push_back(nR);
        scalars.push_back(z);
        points.push_back(nA);
        scalars.push_back(zh);
    }
    points.push_back(BASE_POINT);
    scalars.push_back(s_total);
    ge T;
    ge_msm(T, points, scalars);
    ge_double(T, T);
    ge_double(T, T);
    ge_double(T, T);
    return ge_is_identity(T) ? 1 : 0;
}

}  // extern "C"

// --------------------------------------------- canonical vote sign bytes
// The native encoder SURVEY §2.9-4 mandates for the VerifyCommit latency
// path: assembles the N sign-bytes rows of one commit (they differ only
// in timestamp and commit-vs-nil prefix) into a dense (n, row_stride)
// matrix the batch verifier and the TPU kernel consume directly.
// Byte-exact with cometbft_tpu/types/canonical.py (tested against it).

static inline u64 put_varint(u8* out, u64 v) {
    u64 i = 0;
    while (v >= 0x80) { out[i++] = (u8)(v | 0x80); v >>= 7; }
    out[i++] = (u8)v;
    return i;
}

extern "C" {

// flags[i] == 2 (commit) selects pre_commit, anything else pre_nil.
// Each row = varint(body_len) || pre || ts_field || post, zero-padded to
// row_stride; lens[i] receives the true length.  Returns 0 on success or
// the required stride when row_stride is too small (nothing written).
u64 build_vote_sign_bytes(const u8* pre_commit, u64 pre_commit_len,
                          const u8* pre_nil, u64 pre_nil_len,
                          const u8* post, u64 post_len,
                          const int64_t* ts_ns, const u8* flags, u64 n,
                          u8* out, u64 row_stride, u64* lens) {
    // worst-case timestamp field: tag(1) + len(1) + [tag+varint(10)] +
    // [tag+varint(5)] = 19 bytes; worst-case body-length prefix: 5
    u64 maxpre = pre_commit_len > pre_nil_len ? pre_commit_len : pre_nil_len;
    u64 need = 5 + maxpre + 19 + post_len;
    if (need > row_stride) return need;
    for (u64 i = 0; i < n; i++) {
        // Timestamp{seconds, nanos} with floor division (python divmod)
        int64_t ns = ts_ns[i];
        int64_t secs = ns / 1000000000;
        int64_t nanos = ns % 1000000000;
        if (nanos < 0) { nanos += 1000000000; secs -= 1; }
        u8 tsf[19];
        u64 tl = 0;
        if (secs != 0) {               // field 1 varint, omitted when 0
            tsf[tl++] = 0x08;
            tl += put_varint(tsf + tl, (u64)secs);
        }
        if (nanos != 0) {              // field 2 varint, omitted when 0
            tsf[tl++] = 0x10;
            tl += put_varint(tsf + tl, (u64)nanos);
        }
        const u8* pre = (flags[i] == 2) ? pre_commit : pre_nil;
        u64 pre_len = (flags[i] == 2) ? pre_commit_len : pre_nil_len;
        u64 body_len = pre_len + 2 + tl + post_len;
        u8* row = out + i * row_stride;
        u64 off = put_varint(row, body_len);
        memcpy(row + off, pre, pre_len);
        off += pre_len;
        row[off++] = 0x2a;             // field 5, wire type 2 (always emitted)
        row[off++] = (u8)tl;           // ts submessage length (<= 17)
        memcpy(row + off, tsf, tl);
        off += tl;
        memcpy(row + off, post, post_len);
        off += post_len;
        memset(row + off, 0, row_stride - off);
        lens[i] = off;
    }
    return 0;
}

}  // extern "C"
