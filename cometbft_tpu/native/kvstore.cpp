// Native embedded KV store: append-only log + ordered in-memory index,
// crash-safe via CRC'd records and torn-tail truncation, background-free
// compaction on garbage-ratio threshold.
//
// This is the framework's C++ storage backend (SURVEY §2.9-3: the
// reference links RocksDB through grocksdb for its heavy-duty DB backend;
// here one solid embedded native engine suffices).  Same record layout as
// the Python LogDB ([crc32][klen][vlen|TOMBSTONE][key][value]) so the two
// backends can read each other's files.
//
// Exposed through a minimal C ABI consumed via ctypes
// (cometbft_tpu/storage/nativedb.py) — no pybind11 in this image.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kTombstone = 0xFFFFFFFFu;
constexpr double kCompactGarbageRatio = 0.5;
constexpr uint64_t kCompactMinBytes = 1u << 20;

// CRC-32 (IEEE, zlib-compatible) — table-driven, no external deps.
uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t crc32_ieee(const uint8_t* buf, size_t len) {
  crc_init();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Store {
  std::string path;
  int fd = -1;
  std::map<std::string, std::string> data;  // ordered: range scans
  uint64_t live_bytes = 0;
  uint64_t log_bytes = 0;

  bool open(const char* p) {
    path = p;
    replay();
    fd = ::open(path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
    return fd >= 0;
  }

  void replay() {
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) return;
    fseek(f, 0, SEEK_END);
    long size = ftell(f);
    fseek(f, 0, SEEK_SET);
    std::vector<uint8_t> raw((size_t)size);
    if (size > 0 && fread(raw.data(), 1, (size_t)size, f) != (size_t)size) {
      fclose(f);
      return;
    }
    fclose(f);
    size_t off = 0, good = 0;
    while (off + 12 <= raw.size()) {
      uint32_t crc, klen, vlen;
      memcpy(&crc, &raw[off], 4);
      memcpy(&klen, &raw[off + 4], 4);
      memcpy(&vlen, &raw[off + 8], 4);
      uint64_t vl = (vlen == kTombstone) ? 0 : vlen;
      uint64_t end = off + 12 + (uint64_t)klen + vl;
      if (end > raw.size()) break;
      if (crc32_ieee(&raw[off + 12], (size_t)(klen + vl)) != crc) break;
      std::string key((char*)&raw[off + 12], klen);
      if (vlen == kTombstone) {
        data.erase(key);
      } else {
        data[key] = std::string((char*)&raw[off + 12 + klen], vl);
      }
      off = good = (size_t)end;
    }
    if (good < raw.size()) {
      if (truncate(path.c_str(), (off_t)good) != 0) { /* best effort */ }
    }
    log_bytes = good;
    live_bytes = 0;
    for (auto& kv : data) live_bytes += kv.first.size() + kv.second.size();
  }

  void append_record(const std::string& key, const std::string* value,
                     std::string& out) {
    uint32_t klen = (uint32_t)key.size();
    uint32_t vlen = value ? (uint32_t)value->size() : kTombstone;
    std::string body = key;
    if (value) body += *value;
    uint32_t crc = crc32_ieee((const uint8_t*)body.data(), body.size());
    out.append((char*)&crc, 4);
    out.append((char*)&klen, 4);
    out.append((char*)&vlen, 4);
    out += body;
  }

  bool write_and_sync(const std::string& buf) {
    size_t off = 0;
    while (off < buf.size()) {
      ssize_t n = ::write(fd, buf.data() + off, buf.size() - off);
      if (n <= 0) return false;
      off += (size_t)n;
    }
    log_bytes += buf.size();
    return fsync(fd) == 0;
  }

  void apply(const std::string& key, const std::string* value) {
    auto it = data.find(key);
    if (it != data.end())
      live_bytes -= it->first.size() + it->second.size();
    if (value) {
      data[key] = *value;
      live_bytes += key.size() + value->size();
    } else if (it != data.end()) {
      data.erase(it);
    }
  }

  void maybe_compact() {
    if (log_bytes < kCompactMinBytes) return;
    if ((double)live_bytes / (double)log_bytes > 1.0 - kCompactGarbageRatio)
      return;
    std::string tmp = path + ".compact";
    int tfd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (tfd < 0) return;
    std::string buf;
    for (auto& kv : data) append_record(kv.first, &kv.second, buf);
    size_t off = 0;
    while (off < buf.size()) {
      ssize_t n = ::write(tfd, buf.data() + off, buf.size() - off);
      if (n <= 0) { close(tfd); unlink(tmp.c_str()); return; }
      off += (size_t)n;
    }
    if (fsync(tfd) != 0) { close(tfd); unlink(tmp.c_str()); return; }
    close(tfd);
    if (rename(tmp.c_str(), path.c_str()) != 0) return;
    close(fd);
    fd = ::open(path.c_str(), O_WRONLY | O_APPEND, 0644);
    log_bytes = buf.size();
  }
};

struct Iter {
  std::vector<std::pair<std::string, std::string>> items;  // snapshot
  size_t pos = 0;
};

}  // namespace

extern "C" {

void* kv_open(const char* path) {
  Store* s = new Store();
  if (!s->open(path)) {
    delete s;
    return nullptr;
  }
  return s;
}

void kv_close(void* h) {
  Store* s = (Store*)h;
  if (s->fd >= 0) close(s->fd);
  delete s;
}

// returns 1 + malloc'd value when found, 0 when absent
int kv_get(void* h, const uint8_t* key, uint32_t klen, uint8_t** val,
           uint32_t* vlen) {
  Store* s = (Store*)h;
  auto it = s->data.find(std::string((const char*)key, klen));
  if (it == s->data.end()) return 0;
  *vlen = (uint32_t)it->second.size();
  *val = (uint8_t*)malloc(it->second.size() ? it->second.size() : 1);
  memcpy(*val, it->second.data(), it->second.size());
  return 1;
}

void kv_free(uint8_t* p) { free(p); }

int kv_set(void* h, const uint8_t* key, uint32_t klen, const uint8_t* val,
           uint32_t vlen) {
  Store* s = (Store*)h;
  std::string k((const char*)key, klen), v((const char*)val, vlen);
  std::string buf;
  s->append_record(k, &v, buf);
  if (!s->write_and_sync(buf)) return -1;
  s->apply(k, &v);
  s->maybe_compact();
  return 0;
}

int kv_delete(void* h, const uint8_t* key, uint32_t klen) {
  Store* s = (Store*)h;
  std::string k((const char*)key, klen);
  std::string buf;
  s->append_record(k, nullptr, buf);
  if (!s->write_and_sync(buf)) return -1;
  s->apply(k, nullptr);
  return 0;
}

// batch wire: repeated [u32 klen][u32 vlen|TOMBSTONE][key][value]
// one append + ONE fsync for the whole group (atomic grouped save)
int kv_batch(void* h, const uint8_t* wire, uint64_t len) {
  Store* s = (Store*)h;
  std::string buf;
  uint64_t off = 0;
  // first pass: parse + build the log buffer
  std::vector<std::pair<std::string, bool>> parsed;  // key, has_value
  std::vector<std::string> parsed_vals;
  while (off + 8 <= len) {
    uint32_t klen, vlen;
    memcpy(&klen, wire + off, 4);
    memcpy(&vlen, wire + off + 4, 4);
    uint64_t vl = (vlen == kTombstone) ? 0 : vlen;
    if (off + 8 + klen + vl > len) return -2;
    std::string k((const char*)wire + off + 8, klen);
    if (vlen == kTombstone) {
      s->append_record(k, nullptr, buf);
      parsed.push_back({k, false});
      parsed_vals.push_back(std::string());
    } else {
      std::string v((const char*)wire + off + 8 + klen, vl);
      parsed_vals.push_back(v);
      s->append_record(k, &parsed_vals.back(), buf);
      parsed.push_back({k, true});
    }
    off += 8 + klen + vl;
  }
  if (off != len) return -2;
  if (!s->write_and_sync(buf)) return -1;
  for (size_t i = 0; i < parsed.size(); i++)
    s->apply(parsed[i].first, parsed[i].second ? &parsed_vals[i] : nullptr);
  s->maybe_compact();
  return 0;
}

void* kv_iter_new(void* h, const uint8_t* start, uint32_t slen,
                  const uint8_t* end, uint32_t elen) {
  Store* s = (Store*)h;
  Iter* it = new Iter();
  std::string sk((const char*)start, slen);
  auto lo = s->data.lower_bound(sk);
  auto hi = elen ? s->data.lower_bound(std::string((const char*)end, elen))
                 : s->data.end();
  for (auto i = lo; i != hi; ++i) it->items.push_back(*i);
  return it;
}

int kv_iter_next(void* h, uint8_t** key, uint32_t* klen, uint8_t** val,
                 uint32_t* vlen) {
  Iter* it = (Iter*)h;
  if (it->pos >= it->items.size()) return 0;
  auto& kv = it->items[it->pos++];
  *klen = (uint32_t)kv.first.size();
  *key = (uint8_t*)malloc(kv.first.size() ? kv.first.size() : 1);
  memcpy(*key, kv.first.data(), kv.first.size());
  *vlen = (uint32_t)kv.second.size();
  *val = (uint8_t*)malloc(kv.second.size() ? kv.second.size() : 1);
  memcpy(*val, kv.second.data(), kv.second.size());
  return 1;
}

void kv_iter_free(void* h) { delete (Iter*)h; }

uint64_t kv_size(void* h) { return (uint64_t)((Store*)h)->data.size(); }

}  // extern "C"

// ----------------------------------------------------------------------
// RFC-6962 merkle root (crypto/merkle hash_from_byte_slices semantics:
// 0x00/0x01 domain prefixes, split at the largest power of two strictly
// below n, empty tree = SHA-256("")).  The builtin kvstore app calls
// this per block for its app hash — the pure-Python recursion was the
// single hottest function in the end-to-end throughput profile.

typedef unsigned long long u64k;

#include "sha256_inline.h"

static u64k split_point(u64k n) {
    u64k k = 1;
    while (k * 2 < n) k *= 2;
    return k;
}

static void merkle_node(const uint8_t *buf, const u64k *offs, u64k lo,
                        u64k hi, uint8_t out[32]) {
    static const uint8_t LEAF = 0x00, INNER = 0x01;
    u64k n = hi - lo;
    if (n == 1) {
        sha256i::oneshot3(&LEAF, 1, buf + offs[lo],
                          offs[lo + 1] - offs[lo], nullptr, 0, out);
        return;
    }
    u64k k = split_point(n);
    uint8_t l[32], r[32];
    merkle_node(buf, offs, lo, lo + k, l);
    merkle_node(buf, offs, lo + k, hi, r);
    sha256i::oneshot3(&INNER, 1, l, 32, r, 32, out);
}

extern "C" {

// leaves concatenated in `buf`; offs has n+1 entries (prefix offsets).
// n == 0 -> SHA-256 of the empty string, matching the Python tree.
void kv_merkle_root(const uint8_t *buf, const u64k *offs, u64k n,
                    uint8_t *out32) {
    if (n == 0) {
        sha256i::oneshot(nullptr, 0, out32);
        return;
    }
    merkle_node(buf, offs, 0, n, out32);
}

// Level-order tree build (crypto/merkle.py batched-proof path): every
// tree level is written to `out` as 32-byte nodes, leaf hashes first,
// root last.  Pairing is adjacent-left-to-right with an odd tail node
// PROMOTED unchanged to the next level — bit-identical to the recursive
// largest-power-of-two split above (same invariant the Python level
// builder relies on; pinned by the golden-vector tests).  `out` must
// hold sum over levels of ceil-halved widths (n + ceil(n/2) + ... + 1)
// nodes.  Returns the node count written.
u64k kv_merkle_levels(const uint8_t *buf, const u64k *offs, u64k n,
                      uint8_t *out) {
    static const uint8_t LEAF = 0x00, INNER = 0x01;
    if (n == 0) return 0;
    for (u64k i = 0; i < n; i++)
        sha256i::oneshot3(&LEAF, 1, buf + offs[i], offs[i + 1] - offs[i],
                          nullptr, 0, out + 32 * i);
    uint8_t *prev = out;
    u64k w = n, total = n;
    while (w > 1) {
        uint8_t *cur = out + 32 * total;
        u64k m = w / 2;
        for (u64k i = 0; i < m; i++)
            sha256i::oneshot3(&INNER, 1, prev + 64 * i, 32,
                              prev + 64 * i + 32, 32, cur + 32 * i);
        if (w & 1) memcpy(cur + 32 * m, prev + 32 * (w - 1), 32);
        w = m + (w & 1);
        prev = cur;
        total += w;
    }
    return total;
}

}  // extern "C"
