// ChaCha20-Poly1305 AEAD (RFC 8439) — the native engine behind the
// p2p SecretConnection on images without the `cryptography` wheel.
// The pure-Python stand-in (`crypto/_sc_fallback.py`) moves ~1 MB/s,
// which starves a multi-node in-proc net: every frame of every peer
// connection rides this cipher, so the fallback must be C-speed.  The
// Python class keeps its own implementation as the last resort when
// the on-demand g++ build is unavailable; verdicts are pinned against
// RFC 8439 vectors and cross-checked Python-vs-native in tests.

#include <cstdint>
#include <cstring>

namespace {

inline uint32_t le32(const uint8_t *p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16)
         | ((uint32_t)p[3] << 24);
}

inline void store32(uint8_t *p, uint32_t v) {
    p[0] = (uint8_t)v; p[1] = (uint8_t)(v >> 8);
    p[2] = (uint8_t)(v >> 16); p[3] = (uint8_t)(v >> 24);
}

inline uint32_t rotl(uint32_t x, int n) {
    return (x << n) | (x >> (32 - n));
}

void chacha_block(const uint32_t st[16], uint8_t out[64]) {
    uint32_t s[16];
    memcpy(s, st, sizeof s);
#define QR(a, b, c, d)                                  \
    s[a] += s[b]; s[d] = rotl(s[d] ^ s[a], 16);         \
    s[c] += s[d]; s[b] = rotl(s[b] ^ s[c], 12);         \
    s[a] += s[b]; s[d] = rotl(s[d] ^ s[a], 8);          \
    s[c] += s[d]; s[b] = rotl(s[b] ^ s[c], 7)
    for (int i = 0; i < 10; i++) {
        QR(0, 4, 8, 12); QR(1, 5, 9, 13);
        QR(2, 6, 10, 14); QR(3, 7, 11, 15);
        QR(0, 5, 10, 15); QR(1, 6, 11, 12);
        QR(2, 7, 8, 13); QR(3, 4, 9, 14);
    }
#undef QR
    for (int i = 0; i < 16; i++)
        store32(out + 4 * i, s[i] + st[i]);
}

void chacha_init(uint32_t st[16], const uint8_t key[32], uint32_t counter,
                 const uint8_t nonce[12]) {
    st[0] = 0x61707865; st[1] = 0x3320646E;
    st[2] = 0x79622D32; st[3] = 0x6B206574;
    for (int i = 0; i < 8; i++) st[4 + i] = le32(key + 4 * i);
    st[12] = counter;
    for (int i = 0; i < 3; i++) st[13 + i] = le32(nonce + 4 * i);
}

void chacha_xor(const uint8_t key[32], uint32_t counter,
                const uint8_t nonce[12], const uint8_t *in, uint64_t len,
                uint8_t *out) {
    uint32_t st[16];
    chacha_init(st, key, counter, nonce);
    uint8_t ks[64];
    while (len >= 64) {
        chacha_block(st, ks);
        st[12]++;
        for (int i = 0; i < 64; i++) out[i] = in[i] ^ ks[i];
        in += 64; out += 64; len -= 64;
    }
    if (len) {
        chacha_block(st, ks);
        for (uint64_t i = 0; i < len; i++) out[i] = in[i] ^ ks[i];
    }
}

// poly1305-donna, 32-bit limbs (5 x 26-bit; 64-bit products)
struct Poly {
    uint32_t r[5], h[5], pad[4];

    void init(const uint8_t key[32]) {
        r[0] = (le32(key + 0)) & 0x3ffffff;
        r[1] = (le32(key + 3) >> 2) & 0x3ffff03;
        r[2] = (le32(key + 6) >> 4) & 0x3ffc0ff;
        r[3] = (le32(key + 9) >> 6) & 0x3f03fff;
        r[4] = (le32(key + 12) >> 8) & 0x00fffff;
        for (int i = 0; i < 5; i++) h[i] = 0;
        for (int i = 0; i < 4; i++) pad[i] = le32(key + 16 + 4 * i);
    }

    void blocks(const uint8_t *m, uint64_t len, uint32_t hibit) {
        const uint32_t s1 = r[1] * 5, s2 = r[2] * 5, s3 = r[3] * 5,
                       s4 = r[4] * 5;
        uint32_t h0 = h[0], h1 = h[1], h2 = h[2], h3 = h[3], h4 = h[4];
        while (len >= 16) {
            h0 += (le32(m + 0)) & 0x3ffffff;
            h1 += (le32(m + 3) >> 2) & 0x3ffffff;
            h2 += (le32(m + 6) >> 4) & 0x3ffffff;
            h3 += (le32(m + 9) >> 6) & 0x3ffffff;
            h4 += (le32(m + 12) >> 8) | hibit;
            uint64_t d0 = (uint64_t)h0 * r[0] + (uint64_t)h1 * s4
                        + (uint64_t)h2 * s3 + (uint64_t)h3 * s2
                        + (uint64_t)h4 * s1;
            uint64_t d1 = (uint64_t)h0 * r[1] + (uint64_t)h1 * r[0]
                        + (uint64_t)h2 * s4 + (uint64_t)h3 * s3
                        + (uint64_t)h4 * s2;
            uint64_t d2 = (uint64_t)h0 * r[2] + (uint64_t)h1 * r[1]
                        + (uint64_t)h2 * r[0] + (uint64_t)h3 * s4
                        + (uint64_t)h4 * s3;
            uint64_t d3 = (uint64_t)h0 * r[3] + (uint64_t)h1 * r[2]
                        + (uint64_t)h2 * r[1] + (uint64_t)h3 * r[0]
                        + (uint64_t)h4 * s4;
            uint64_t d4 = (uint64_t)h0 * r[4] + (uint64_t)h1 * r[3]
                        + (uint64_t)h2 * r[2] + (uint64_t)h3 * r[1]
                        + (uint64_t)h4 * r[0];
            uint64_t c = d0 >> 26; h0 = (uint32_t)d0 & 0x3ffffff;
            d1 += c; c = d1 >> 26; h1 = (uint32_t)d1 & 0x3ffffff;
            d2 += c; c = d2 >> 26; h2 = (uint32_t)d2 & 0x3ffffff;
            d3 += c; c = d3 >> 26; h3 = (uint32_t)d3 & 0x3ffffff;
            d4 += c; c = d4 >> 26; h4 = (uint32_t)d4 & 0x3ffffff;
            h0 += (uint32_t)c * 5; h1 += h0 >> 26; h0 &= 0x3ffffff;
            m += 16; len -= 16;
        }
        h[0] = h0; h[1] = h1; h[2] = h2; h[3] = h3; h[4] = h4;
    }

    void tag(uint8_t out[16]) {
        uint32_t h0 = h[0], h1 = h[1], h2 = h[2], h3 = h[3], h4 = h[4];
        uint32_t c = h1 >> 26; h1 &= 0x3ffffff;
        h2 += c; c = h2 >> 26; h2 &= 0x3ffffff;
        h3 += c; c = h3 >> 26; h3 &= 0x3ffffff;
        h4 += c; c = h4 >> 26; h4 &= 0x3ffffff;
        h0 += c * 5; c = h0 >> 26; h0 &= 0x3ffffff;
        h1 += c;
        // h + 5 - 2^130; select it when h >= p
        uint32_t g0 = h0 + 5; c = g0 >> 26; g0 &= 0x3ffffff;
        uint32_t g1 = h1 + c; c = g1 >> 26; g1 &= 0x3ffffff;
        uint32_t g2 = h2 + c; c = g2 >> 26; g2 &= 0x3ffffff;
        uint32_t g3 = h3 + c; c = g3 >> 26; g3 &= 0x3ffffff;
        uint32_t g4 = h4 + c - (1u << 26);
        uint32_t mask = (g4 >> 31) - 1;     // all-ones when h >= p
        h0 = (h0 & ~mask) | (g0 & mask);
        h1 = (h1 & ~mask) | (g1 & mask);
        h2 = (h2 & ~mask) | (g2 & mask);
        h3 = (h3 & ~mask) | (g3 & mask);
        h4 = (h4 & ~mask) | (g4 & mask);
        // little-endian 128-bit h + pad
        uint32_t f0 = (h0 | (h1 << 26));
        uint32_t f1 = ((h1 >> 6) | (h2 << 20));
        uint32_t f2 = ((h2 >> 12) | (h3 << 14));
        uint32_t f3 = ((h3 >> 18) | (h4 << 8));
        uint64_t t = (uint64_t)f0 + pad[0];
        store32(out + 0, (uint32_t)t);
        t = (uint64_t)f1 + pad[1] + (t >> 32);
        store32(out + 4, (uint32_t)t);
        t = (uint64_t)f2 + pad[2] + (t >> 32);
        store32(out + 8, (uint32_t)t);
        t = (uint64_t)f3 + pad[3] + (t >> 32);
        store32(out + 12, (uint32_t)t);
    }
};

// RFC 8439 §2.8 MAC input: aad || pad16 || ct || pad16 || le64(len(aad))
// || le64(len(ct)).  Every poly1305 block here is a full 16 bytes with
// the 2^128 bit set (hibit) — the zero padding is part of the message,
// not the poly1305 0x01-terminator scheme.
void aead_mac(const uint8_t key[32], const uint8_t nonce[12],
              const uint8_t *aad, uint64_t aad_len, const uint8_t *ct,
              uint64_t ct_len, uint8_t tag[16]) {
    uint32_t st[16];
    chacha_init(st, key, 0, nonce);
    uint8_t otk[64];
    chacha_block(st, otk);
    Poly p;
    p.init(otk);
    uint8_t pad[16] = {0};
    uint64_t full = aad_len & ~(uint64_t)15;
    if (full) p.blocks(aad, full, 1u << 24);
    if (aad_len & 15) {
        memcpy(pad, aad + full, aad_len & 15);
        memset(pad + (aad_len & 15), 0, 16 - (aad_len & 15));
        p.blocks(pad, 16, 1u << 24);
    }
    full = ct_len & ~(uint64_t)15;
    if (full) p.blocks(ct, full, 1u << 24);
    if (ct_len & 15) {
        memcpy(pad, ct + full, ct_len & 15);
        memset(pad + (ct_len & 15), 0, 16 - (ct_len & 15));
        p.blocks(pad, 16, 1u << 24);
    }
    uint8_t lens[16];
    for (int i = 0; i < 8; i++) {
        lens[i] = (uint8_t)(aad_len >> (8 * i));
        lens[8 + i] = (uint8_t)(ct_len >> (8 * i));
    }
    p.blocks(lens, 16, 1u << 24);
    p.tag(tag);
}

}  // namespace

extern "C" {

// out must hold pt_len + 16 bytes (ciphertext || tag).
void aead_seal(const uint8_t *key, const uint8_t *nonce, const uint8_t *aad,
               uint64_t aad_len, const uint8_t *pt, uint64_t pt_len,
               uint8_t *out) {
    chacha_xor(key, 1, nonce, pt, pt_len, out);
    aead_mac(key, nonce, aad, aad_len, out, pt_len, out + pt_len);
}

// ct_len INCLUDES the 16-byte tag; out holds ct_len - 16 bytes.
// Returns 1 on tag match, 0 on mismatch (out untouched on mismatch).
int aead_open(const uint8_t *key, const uint8_t *nonce, const uint8_t *aad,
              uint64_t aad_len, const uint8_t *ct, uint64_t ct_len,
              uint8_t *out) {
    if (ct_len < 16) return 0;
    uint64_t pt_len = ct_len - 16;
    uint8_t tag[16];
    aead_mac(key, nonce, aad, aad_len, ct, pt_len, tag);
    uint8_t diff = 0;
    for (int i = 0; i < 16; i++) diff |= (uint8_t)(tag[i] ^ ct[pt_len + i]);
    if (diff) return 0;
    chacha_xor(key, 1, nonce, ct, pt_len, out);
    return 1;
}

}  // extern "C"
