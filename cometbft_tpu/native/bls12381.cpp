// Native BLS12-381 minimal-pubkey signatures (BLS_SIG_BLS12381G2_XMD:
// SHA-256_SSWU_RO_NUL_), the C++ backend behind crypto/bls12381.py.
//
// Reference seam: the optional blst-backed build of the reference's
// crypto/bls12381 key type (key_bls12381.go).  This file is an original
// implementation, structured after this repo's own pure-Python
// cometbft_tpu/crypto/_bls12381_py.py (same tower, same RFC 9380
// SSWU+3-isogeny hash-to-curve, same zcash serialization), rebuilt on a
// 6x64-bit Montgomery base field:
//
//   fp     = GF(p), p 381 bits, CIOS Montgomery multiplication
//   fp2    = fp[u]/(u^2+1);  fp6 = fp2[v]/(v^3 - (1+u));  fp12 = fp6[w]/(w^2 - v)
//   G1     = E(fp):  y^2 = x^3 + 4        (pk, 48-byte compressed)
//   G2     = E'(fp2): y^2 = x^3 + 4(1+u)  (sig, 96-byte compressed, M-twist)
//   e      = optimal ate pairing: inversion-free Jacobian Miller loop with
//            sparse line multiplication (affine fallback for degenerate
//            inputs); final exp = easy part + Hayashida-Hayasaka-Teruya
//            cubed hard part over Granger-Scott cyclotomic squarings
//            (returns e(..)^3 — callers only test against one)
//   G2 aux = psi-endomorphism subgroup check (Scott) and RFC 9380 App. G.3
//            fast cofactor clearing
//
// Shared material is limited to forced constants: the curve parameters,
// RFC 9380 Appendix E.3 isogeny coefficients, and the suite's h_eff.
//
// C ABI (ctypes): bls_sk_to_pk, bls_sign, bls_verify, bls_selftest.

#include <cstdint>
#include <cstring>

typedef unsigned __int128 u128;
typedef uint64_t u64;
typedef uint8_t u8;

// ------------------------------------------------------------------ fp
// little-endian 64-bit limbs, Montgomery form (R = 2^384)

struct fp { u64 l[6]; };

static const fp P = {{0xb9feffffffffaaabull, 0x1eabfffeb153ffffull,
                      0x6730d2a0f6b0f624ull, 0x64774b84f38512bfull,
                      0x4b1ba7b6434bacd7ull, 0x1a0111ea397fe69aull}};
static const u64 N0 = 0x89f3fffcfffcfffdull;          // -p^-1 mod 2^64
static const fp R2 = {{0xf4df1f341c341746ull, 0x0a76e6a609d104f1ull,
                       0x8de5476c4c95b6d5ull, 0x67eb88a9939d83c0ull,
                       0x9a793e85b519952dull, 0x11988fe592cae3aaull}};
static const fp FP_ONE_M = {{0x760900000002fffdull, 0xebf4000bc40c0002ull,
                             0x5f48985753c758baull, 0x77ce585370525745ull,
                             0x5c071a97a256ec6dull, 0x15f65ec3fa80e493ull}};
static const fp FP_ZERO = {{0, 0, 0, 0, 0, 0}};

static inline int fp_cmp(const fp &a, const fp &b) {
    for (int i = 5; i >= 0; i--) {
        if (a.l[i] < b.l[i]) return -1;
        if (a.l[i] > b.l[i]) return 1;
    }
    return 0;
}

static inline bool fp_is_zero(const fp &a) {
    u64 t = 0;
    for (int i = 0; i < 6; i++) t |= a.l[i];
    return t == 0;
}

static inline void fp_cond_sub_p(fp &a) {
    // branchless: compute a - p, keep it unless the subtract borrowed
    u64 d[6];
    u128 bw = 0;
    for (int i = 0; i < 6; i++) {
        u128 t = (u128)a.l[i] - P.l[i] - bw;
        d[i] = (u64)t;
        bw = (t >> 64) & 1;
    }
    u64 keep = (u64)0 - (u64)(1 - (u64)bw);   // all-ones when a >= p
    for (int i = 0; i < 6; i++)
        a.l[i] = (a.l[i] & ~keep) | (d[i] & keep);
}

static inline fp fp_add(const fp &a, const fp &b) {
    fp r;
    u128 c = 0;
    for (int i = 0; i < 6; i++) {
        c += (u128)a.l[i] + b.l[i];
        r.l[i] = (u64)c;
        c >>= 64;
    }
    fp_cond_sub_p(r);          // a,b < p so sum < 2p: one subtract settles it
    return r;
}

static inline fp fp_sub(const fp &a, const fp &b) {
    fp r;
    u128 bw = 0;
    for (int i = 0; i < 6; i++) {
        u128 t = (u128)a.l[i] - b.l[i] - bw;
        r.l[i] = (u64)t;
        bw = (t >> 64) & 1;
    }
    if (bw) {
        u128 c = 0;
        for (int i = 0; i < 6; i++) {
            c += (u128)r.l[i] + P.l[i];
            r.l[i] = (u64)c;
            c >>= 64;
        }
    }
    return r;
}

static inline fp fp_neg(const fp &a) {
    return fp_is_zero(a) ? a : fp_sub(FP_ZERO, a);
}

static inline fp fp_dbl(const fp &a) { return fp_add(a, a); }

// CIOS Montgomery multiplication: r = a*b*R^-1 mod p
static fp fp_mul(const fp &a, const fp &b) {
    u64 t[8] = {0};
    for (int i = 0; i < 6; i++) {
        u128 c = 0;
        for (int j = 0; j < 6; j++) {
            c += (u128)t[j] + (u128)a.l[i] * b.l[j];
            t[j] = (u64)c;
            c >>= 64;
        }
        c += t[6];
        t[6] = (u64)c;
        t[7] = (u64)(c >> 64);
        u64 m = t[0] * N0;
        c = (u128)t[0] + (u128)m * P.l[0];
        c >>= 64;
        for (int j = 1; j < 6; j++) {
            c += (u128)t[j] + (u128)m * P.l[j];
            t[j - 1] = (u64)c;
            c >>= 64;
        }
        c += t[6];
        t[5] = (u64)c;
        t[6] = t[7] + (u64)(c >> 64);
        t[7] = 0;
    }
    fp r;
    memcpy(r.l, t, sizeof r.l);
    // result < 2p (t[6] can only be set transiently); settle to [0,p)
    fp_cond_sub_p(r);
    return r;
}

static inline fp fp_sqr(const fp &a) { return fp_mul(a, a); }

// generic pow over an exponent given as little-endian limbs
static fp fp_pow(const fp &a, const u64 *e, int nbits) {
    fp out = FP_ONE_M, base = a;
    for (int i = 0; i < nbits; i++) {
        if ((e[i >> 6] >> (i & 63)) & 1) out = fp_mul(out, base);
        base = fp_sqr(base);
    }
    return out;
}

// derived exponents, built at init from P's limbs
static u64 E_P_M2[6];      // p - 2         (inversion)
static u64 E_P_P1_D4[6];   // (p + 1) / 4   (fp sqrt; p = 3 mod 4)
static u64 E_P_M3_D4[6];   // (p - 3) / 4   (fp2 sqrt)
static u64 E_P_M1_D2[6];   // (p - 1) / 2   (fp2 sqrt correction)
static fp HALF_P;          // (p - 1) / 2 as a canonical value for sign tests

static void big_sub_small(u64 *r, const u64 *a, u64 k) {
    u128 bw = k;
    for (int i = 0; i < 6; i++) {
        u128 t = (u128)a[i] - bw;
        r[i] = (u64)t;
        bw = (t >> 64) & 1;
    }
}

static void big_add_small(u64 *r, const u64 *a, u64 k) {
    u128 c = k;
    for (int i = 0; i < 6; i++) {
        c += a[i];
        r[i] = (u64)c;
        c >>= 64;
    }
}

static void big_div_small(u64 *r, const u64 *a, u64 d) {
    u128 rem = 0;
    for (int i = 5; i >= 0; i--) {
        u128 cur = (rem << 64) | a[i];
        r[i] = (u64)(cur / d);
        rem = cur % d;
    }
}

static void big_shr(u64 *r, const u64 *a, int k) {
    for (int i = 0; i < 6; i++) {
        u64 lo = a[i] >> k;
        u64 hi = (i + 1 < 6) ? (a[i + 1] << (64 - k)) : 0;
        r[i] = lo | hi;
    }
}

static inline fp fp_inv(const fp &a) { return fp_pow(a, E_P_M2, 381); }

static bool fp_sqrt(fp &out, const fp &a) {
    fp r = fp_pow(a, E_P_P1_D4, 379);
    if (fp_cmp(fp_sqr(r), a) != 0) return false;
    out = r;
    return true;
}

static fp fp_from_mont(const fp &a) {
    fp one = {{1, 0, 0, 0, 0, 0}};
    return fp_mul(a, one);
}

static fp fp_to_mont(const fp &a) { return fp_mul(a, R2); }

static void fp_to_bytes_be(u8 out[48], const fp &a_mont) {
    fp a = fp_from_mont(a_mont);
    for (int i = 0; i < 6; i++)
        for (int j = 0; j < 8; j++)
            out[47 - 8 * i - j] = (u8)(a.l[i] >> (8 * j));
}

// returns false when the 48 bytes encode a value >= p
static bool fp_from_bytes_be(fp &out, const u8 in[48]) {
    fp a = FP_ZERO;
    for (int i = 0; i < 6; i++)
        for (int j = 0; j < 8; j++)
            a.l[i] |= (u64)in[47 - 8 * i - j] << (8 * j);
    if (fp_cmp(a, P) >= 0) return false;
    out = fp_to_mont(a);
    return true;
}

// canonical comparison against (p-1)/2 (the "larger" lexicographic sign)
static bool fp_is_larger(const fp &a_mont) {
    fp a = fp_from_mont(a_mont);
    return fp_cmp(a, HALF_P) > 0;
}

static bool fp_is_odd(const fp &a_mont) {
    return fp_from_mont(a_mont).l[0] & 1;
}

// ----------------------------------------------------------------- fp2

struct fp2 { fp c0, c1; };

static const fp2 F2_ZERO = {FP_ZERO, FP_ZERO};

static inline fp2 f2_add(const fp2 &a, const fp2 &b) {
    return {fp_add(a.c0, b.c0), fp_add(a.c1, b.c1)};
}
static inline fp2 f2_sub(const fp2 &a, const fp2 &b) {
    return {fp_sub(a.c0, b.c0), fp_sub(a.c1, b.c1)};
}
static inline fp2 f2_neg(const fp2 &a) {
    return {fp_neg(a.c0), fp_neg(a.c1)};
}
static inline bool f2_is_zero(const fp2 &a) {
    return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}
static inline bool f2_eq(const fp2 &a, const fp2 &b) {
    return fp_cmp(a.c0, b.c0) == 0 && fp_cmp(a.c1, b.c1) == 0;
}

static fp2 f2_mul(const fp2 &a, const fp2 &b) {
    // Karatsuba over u^2 = -1
    fp t0 = fp_mul(a.c0, b.c0);
    fp t1 = fp_mul(a.c1, b.c1);
    fp s = fp_mul(fp_add(a.c0, a.c1), fp_add(b.c0, b.c1));
    return {fp_sub(t0, t1), fp_sub(s, fp_add(t0, t1))};
}

static fp2 f2_sqr(const fp2 &a) {
    // (a0+a1)(a0-a1) + 2 a0 a1 u
    fp s = fp_mul(fp_add(a.c0, a.c1), fp_sub(a.c0, a.c1));
    fp t = fp_mul(a.c0, a.c1);
    return {s, fp_dbl(t)};
}

static fp2 f2_scalar_fp(const fp2 &a, const fp &k) {
    return {fp_mul(a.c0, k), fp_mul(a.c1, k)};
}

static fp2 f2_inv(const fp2 &a) {
    fp t = fp_add(fp_sqr(a.c0), fp_sqr(a.c1));
    fp ti = fp_inv(t);
    return {fp_mul(a.c0, ti), fp_neg(fp_mul(a.c1, ti))};
}

static fp2 f2_conj(const fp2 &a) { return {a.c0, fp_neg(a.c1)}; }

static fp2 f2_pow(const fp2 &a, const u64 *e, int nbits) {
    fp2 out = {FP_ONE_M, FP_ZERO}, base = a;
    for (int i = 0; i < nbits; i++) {
        if ((e[i >> 6] >> (i & 63)) & 1) out = f2_mul(out, base);
        base = f2_sqr(base);
    }
    return out;
}

// sqrt in fp2 (p = 3 mod 4, Adj–Rodríguez-Henríquez complex method),
// mirroring _bls12381_py.f2_sqrt
static bool f2_sqrt(fp2 &out, const fp2 &a) {
    if (f2_is_zero(a)) { out = F2_ZERO; return true; }
    fp2 a1 = f2_pow(a, E_P_M3_D4, 379);
    fp2 alpha = f2_mul(f2_sqr(a1), a);
    fp2 x0 = f2_mul(a1, a);
    fp2 minus_one = {fp_neg(FP_ONE_M), FP_ZERO};
    if (f2_eq(alpha, minus_one)) {
        out = {fp_neg(x0.c1), x0.c0};                // i * x0
        return true;
    }
    fp2 one = {FP_ONE_M, FP_ZERO};
    fp2 b = f2_pow(f2_add(one, alpha), E_P_M1_D2, 381);
    fp2 x = f2_mul(b, x0);
    if (!f2_eq(f2_sqr(x), a)) return false;
    out = x;
    return true;
}

// sgn0 for m=2 (RFC 9380 section 4.1)
static int f2_sgn0(const fp2 &x) {
    bool z0 = fp_is_zero(x.c0);
    int s0 = fp_is_odd(x.c0) ? 1 : 0;
    int s1 = fp_is_odd(x.c1) ? 1 : 0;
    return s0 | (z0 ? s1 : 0);
}

// lexicographic "larger" (compare c1 first) for G2 compression sign
static bool f2_is_larger(const fp2 &y) {
    if (!fp_is_zero(y.c1)) return fp_is_larger(y.c1);
    return fp_is_larger(y.c0);
}

// ----------------------------------------------------------------- fp6
// fp6 = fp2[v]/(v^3 - XI), XI = 1 + u

static inline fp2 mul_xi(const fp2 &a) {
    // (c0 + c1 u)(1 + u) = (c0 - c1) + (c0 + c1) u
    return {fp_sub(a.c0, a.c1), fp_add(a.c0, a.c1)};
}

struct fp6 { fp2 c0, c1, c2; };

static inline fp6 f6_add(const fp6 &a, const fp6 &b) {
    return {f2_add(a.c0, b.c0), f2_add(a.c1, b.c1), f2_add(a.c2, b.c2)};
}
static inline fp6 f6_sub(const fp6 &a, const fp6 &b) {
    return {f2_sub(a.c0, b.c0), f2_sub(a.c1, b.c1), f2_sub(a.c2, b.c2)};
}
static inline fp6 f6_neg(const fp6 &a) {
    return {f2_neg(a.c0), f2_neg(a.c1), f2_neg(a.c2)};
}

static fp6 f6_mul(const fp6 &a, const fp6 &b) {
    fp2 t0 = f2_mul(a.c0, b.c0);
    fp2 t1 = f2_mul(a.c1, b.c1);
    fp2 t2 = f2_mul(a.c2, b.c2);
    fp2 c0 = f2_add(t0, mul_xi(f2_sub(
        f2_mul(f2_add(a.c1, a.c2), f2_add(b.c1, b.c2)), f2_add(t1, t2))));
    fp2 c1 = f2_add(f2_sub(f2_mul(f2_add(a.c0, a.c1), f2_add(b.c0, b.c1)),
                           f2_add(t0, t1)), mul_xi(t2));
    fp2 c2 = f2_add(f2_sub(f2_mul(f2_add(a.c0, a.c2), f2_add(b.c0, b.c2)),
                           f2_add(t0, t2)), t1);
    return {c0, c1, c2};
}

static inline fp6 f6_sqr(const fp6 &a) { return f6_mul(a, a); }

static fp6 f6_inv(const fp6 &a) {
    fp2 c0 = f2_sub(f2_sqr(a.c0), mul_xi(f2_mul(a.c1, a.c2)));
    fp2 c1 = f2_sub(mul_xi(f2_sqr(a.c2)), f2_mul(a.c0, a.c1));
    fp2 c2 = f2_sub(f2_sqr(a.c1), f2_mul(a.c0, a.c2));
    fp2 t = f2_add(mul_xi(f2_add(f2_mul(a.c2, c1), f2_mul(a.c1, c2))),
                   f2_mul(a.c0, c0));
    fp2 ti = f2_inv(t);
    return {f2_mul(c0, ti), f2_mul(c1, ti), f2_mul(c2, ti)};
}

// (c0 + c1 v + c2 v^2) * v = XI c2 + c0 v + c1 v^2
static inline fp6 f6_mul_v(const fp6 &a) {
    return {mul_xi(a.c2), a.c0, a.c1};
}

// ---------------------------------------------------------------- fp12
// fp12 = fp6[w]/(w^2 - v)

struct fp12 { fp6 c0, c1; };

static fp12 F12_ONE;       // set at init

static fp12 f12_mul(const fp12 &a, const fp12 &b) {
    fp6 t0 = f6_mul(a.c0, b.c0);
    fp6 t1 = f6_mul(a.c1, b.c1);
    fp6 c0 = f6_add(t0, f6_mul_v(t1));
    fp6 c1 = f6_sub(f6_mul(f6_add(a.c0, a.c1), f6_add(b.c0, b.c1)),
                    f6_add(t0, t1));
    return {c0, c1};
}

static inline fp12 f12_sqr(const fp12 &a) { return f12_mul(a, a); }

static fp12 f12_inv(const fp12 &a) {
    fp6 t = f6_sub(f6_mul(a.c0, a.c0), f6_mul_v(f6_mul(a.c1, a.c1)));
    fp6 ti = f6_inv(t);
    return {f6_mul(a.c0, ti), f6_neg(f6_mul(a.c1, ti))};
}

static inline fp12 f12_conj(const fp12 &a) { return {a.c0, f6_neg(a.c1)}; }

static inline fp12 f12_sub(const fp12 &a, const fp12 &b) {
    return {f6_sub(a.c0, b.c0), f6_sub(a.c1, b.c1)};
}

static bool f12_is_one(const fp12 &a) {
    return f2_eq(a.c0.c0, {FP_ONE_M, FP_ZERO}) &&
           f2_is_zero(a.c0.c1) && f2_is_zero(a.c0.c2) &&
           f2_is_zero(a.c1.c0) && f2_is_zero(a.c1.c1) &&
           f2_is_zero(a.c1.c2);
}

// Frobenius^2: multiplies the w^i v^j coefficient (basis power
// k = 2j + i) by gamma_k = XI^(k (p^2-1)/6); all six gammas lie in fp.
static fp G2GAMMA[6];      // Montgomery, set at init (canonical below)
static const fp G2GAMMA_CANON[6] = {
    {{1, 0, 0, 0, 0, 0}},
    {{0x2e01fffffffeffffull, 0xde17d813620a0002ull, 0xddb3a93be6f89688ull,
      0xba69c6076a0f77eaull, 0x5f19672fdf76ce51ull, 0}},
    {{0x2e01fffffffefffeull, 0xde17d813620a0002ull, 0xddb3a93be6f89688ull,
      0xba69c6076a0f77eaull, 0x5f19672fdf76ce51ull, 0}},
    {{0xb9feffffffffaaaaull, 0x1eabfffeb153ffffull, 0x6730d2a0f6b0f624ull,
      0x64774b84f38512bfull, 0x4b1ba7b6434bacd7ull, 0x1a0111ea397fe69aull}},
    {{0x8bfd00000000aaacull, 0x409427eb4f49fffdull, 0x897d29650fb85f9bull,
      0xaa0d857d89759ad4ull, 0xec02408663d4de85ull, 0x1a0111ea397fe699ull}},
    {{0x8bfd00000000aaadull, 0x409427eb4f49fffdull, 0x897d29650fb85f9bull,
      0xaa0d857d89759ad4ull, 0xec02408663d4de85ull, 0x1a0111ea397fe699ull}},
};

static fp12 f12_frob2(const fp12 &a) {
    return {{f2_scalar_fp(a.c0.c0, G2GAMMA[0]),
             f2_scalar_fp(a.c0.c1, G2GAMMA[2]),
             f2_scalar_fp(a.c0.c2, G2GAMMA[4])},
            {f2_scalar_fp(a.c1.c0, G2GAMMA[1]),
             f2_scalar_fp(a.c1.c1, G2GAMMA[3]),
             f2_scalar_fp(a.c1.c2, G2GAMMA[5])}};
}

// Frobenius^1: w^p = w * XI^((p-1)/6), and x^p = conj(x) on fp2, so the
// coefficient at basis power k (w-degree + 2*v-degree ordering as in
// frob2 above) maps to conj(c_k) * GAMMA1^k.  GAMMA1 = XI^((p-1)/6) is
// computed at init (it is a full fp2 element, unlike the frob2 gammas).
static fp2 GAMMA1_POW[6];

static fp12 f12_frob1(const fp12 &a) {
    return {{f2_mul(f2_conj(a.c0.c0), GAMMA1_POW[0]),
             f2_mul(f2_conj(a.c0.c1), GAMMA1_POW[2]),
             f2_mul(f2_conj(a.c0.c2), GAMMA1_POW[4])},
            {f2_mul(f2_conj(a.c1.c0), GAMMA1_POW[1]),
             f2_mul(f2_conj(a.c1.c1), GAMMA1_POW[3]),
             f2_mul(f2_conj(a.c1.c2), GAMMA1_POW[5])}};
}

// Granger-Scott cyclotomic squaring: after the easy part the element
// lies in the cyclotomic subgroup, where w-basis coefficients (g0..g5,
// fp4 pairs (g0,g3),(g1,g4),(g2,g5) over s = w^3, s^2 = XI) square as
//   h0 = 3 A0 - 2 g0   h3 = 3 A1 + 2 g3      (A = (g0+g3 s)^2)
//   h2 = 3 B0 - 2 g2   h5 = 3 B1 + 2 g5      (B = (g1+g4 s)^2)
//   h4 = 3 C0 - 2 g4   h1 = 3 XI C1 + 2 g1   (C = (g2+g5 s)^2)
// — 3 fp4 squarings instead of a full f12 multiply (~2.6x cheaper).
// The coefficient pattern was solved and uniquely pinned against this
// file's own tower by exhaustive check on random cyclotomic elements
// (and every verify exercises it end to end against the Python oracle).
static inline void fp4_sq(const fp2 &a, const fp2 &b, fp2 &r0, fp2 &r1) {
    r0 = f2_add(f2_sqr(a), mul_xi(f2_sqr(b)));
    fp2 ab = f2_mul(a, b);
    r1 = f2_add(ab, ab);
}

static fp12 f12_cyclo_sqr(const fp12 &g) {
    // w-basis: g0=c0.c0 g1=c1.c0 g2=c0.c1 g3=c1.c1 g4=c0.c2 g5=c1.c2
    const fp2 &g0 = g.c0.c0, &g1 = g.c1.c0, &g2 = g.c0.c1,
              &g3 = g.c1.c1, &g4 = g.c0.c2, &g5 = g.c1.c2;
    fp2 A0, A1, B0, B1, C0, C1;
    fp4_sq(g0, g3, A0, A1);
    fp4_sq(g1, g4, B0, B1);
    fp4_sq(g2, g5, C0, C1);
    auto three = [](const fp2 &x) { return f2_add(f2_add(x, x), x); };
    auto two = [](const fp2 &x) { return f2_add(x, x); };
    fp12 h;
    h.c0.c0 = f2_sub(three(A0), two(g0));
    h.c1.c1 = f2_add(three(A1), two(g3));
    h.c0.c1 = f2_sub(three(B0), two(g2));
    h.c1.c2 = f2_add(three(B1), two(g5));
    h.c0.c2 = f2_sub(three(C0), two(g4));
    h.c1.c0 = f2_add(three(mul_xi(C1)), two(g1));
    return h;
}

// f^|x| for the curve parameter x = -0xd201000000010000, inside the
// cyclotomic subgroup (63 cyclotomic squarings + 5 multiplies; the
// caller conjugates — the cyclotomic inverse — for x's sign).
static fp12 f12_cyclo_pow_xabs(const fp12 &f) {
    static const u64 XABS = 0xd201000000010000ull;
    fp12 acc = f;
    for (int i = 62; i >= 0; i--) {
        acc = f12_cyclo_sqr(acc);
        if ((XABS >> i) & 1) acc = f12_mul(acc, f);
    }
    return acc;
}

static inline fp12 f12_cyclo_pow_x(const fp12 &f) {   // f^x, x < 0
    return f12_conj(f12_cyclo_pow_xabs(f));
}

// Final exponentiation, CUBED: returns e(..)^3 rather than e(..).
// Every caller only compares the result against one, and gcd(3, r) = 1
// (f after the easy part has order dividing r-smooth p^4-p^2+1), so
// f^(3h) == 1 iff f^h == 1.  The cubed hard part factors as the
// Hayashida-Hayasaka-Teruya chain
//   3 (p^4 - p^2 + 1)/r = (x-1)^2 (x+p) (x^2 + p^2 - 1) + 3
// — five 64-bit pow-by-x ladders (~315 cyclotomic squarings + ~35 f12
// multiplies) instead of the 381-bit 4-way Shamir ladder this replaced
// (381 squarings + ~357 multiplies): ~2.6x less fp work.
static fp12 final_exponentiation(const fp12 &f) {
    fp12 g = f12_mul(f12_conj(f), f12_inv(f));     // f^(p^6 - 1)
    g = f12_mul(f12_frob2(g), g);                  // ^(p^2 + 1): easy part
    // a = g^((x-1)^2) — in the cyclotomic subgroup conj IS inversion
    fp12 a = f12_mul(f12_cyclo_pow_x(g), f12_conj(g));
    a = f12_mul(f12_cyclo_pow_x(a), f12_conj(a));
    // b = a^(x+p)
    fp12 b = f12_mul(f12_cyclo_pow_x(a), f12_frob1(a));
    // c = b^(x^2 + p^2 - 1); b^(x^2) via two pow-x (the signs cancel)
    fp12 bx2 = f12_cyclo_pow_xabs(f12_cyclo_pow_xabs(b));
    fp12 c = f12_mul(f12_mul(bx2, f12_frob2(b)), f12_conj(b));
    // result = c * g^3
    return f12_mul(c, f12_mul(f12_cyclo_sqr(g), g));
}

// ------------------------------------------------------------ G1 points

struct g1a { fp x, y; bool inf; };
struct g1j { fp X, Y, Z; };        // Z == 0 -> infinity

static const fp G1X_CANON = {{0xfb3af00adb22c6bbull, 0x6c55e83ff97a1aefull,
                              0xa14e3a3f171bac58ull, 0xc3688c4f9774b905ull,
                              0x2695638c4fa9ac0full, 0x17f1d3a73197d794ull}};
static const fp G1Y_CANON = {{0x0caa232946c5e7e1ull, 0xd03cc744a2888ae4ull,
                              0x00db18cb2c04b3edull, 0xfcf5e095d5d00af6ull,
                              0xa09e30ed741d8ae4ull, 0x08b3f481e3aaa0f1ull}};
static g1a G1_GEN;                 // Montgomery, set at init
static fp FP_B;                    // curve b = 4, Montgomery

// group order r (255 bits), big-endian byte form built at init
static const u64 ORDER_R[4] = {0xffffffff00000001ull, 0x53bda402fffe5bfeull,
                               0x3339d80809a1d805ull, 0x73eda753299d7d48ull};

static g1j g1_dbl(const g1j &p) {
    if (fp_is_zero(p.Z)) return p;
    // standard a=0 Jacobian doubling
    fp A = fp_sqr(p.X), B = fp_sqr(p.Y), C = fp_sqr(B);
    fp D = fp_dbl(fp_sub(fp_sub(fp_sqr(fp_add(p.X, B)), A), C));
    fp E = fp_add(fp_dbl(A), A);
    fp F = fp_sqr(E);
    g1j r;
    r.X = fp_sub(F, fp_dbl(D));
    r.Y = fp_sub(fp_mul(E, fp_sub(D, r.X)),
                 fp_dbl(fp_dbl(fp_dbl(C))));
    r.Z = fp_mul(fp_dbl(p.Y), p.Z);
    return r;
}

static g1j g1_add_mixed(const g1j &p, const g1a &q) {
    if (q.inf) return p;
    if (fp_is_zero(p.Z)) {
        g1j r = {q.x, q.y, FP_ONE_M};
        return r;
    }
    fp Z2 = fp_sqr(p.Z);
    fp U2 = fp_mul(q.x, Z2);
    fp S2 = fp_mul(fp_mul(q.y, Z2), p.Z);
    if (fp_cmp(U2, p.X) == 0) {
        if (fp_cmp(S2, p.Y) != 0) return {FP_ZERO, FP_ONE_M, FP_ZERO};
        return g1_dbl(p);
    }
    fp H = fp_sub(U2, p.X), Rr = fp_sub(S2, p.Y);
    fp H2 = fp_sqr(H), H3 = fp_mul(H2, H);
    fp V = fp_mul(p.X, H2);
    g1j r;
    r.X = fp_sub(fp_sub(fp_sqr(Rr), H3), fp_dbl(V));
    r.Y = fp_sub(fp_mul(Rr, fp_sub(V, r.X)), fp_mul(p.Y, H3));
    r.Z = fp_mul(p.Z, H);
    return r;
}

// scalar multiply by a big-endian byte string
static g1j g1_mul_be(const g1a &p, const u8 *e, int elen) {
    g1j acc = {FP_ZERO, FP_ONE_M, FP_ZERO};
    for (int i = 0; i < elen; i++)
        for (int b = 7; b >= 0; b--) {
            acc = g1_dbl(acc);
            if ((e[i] >> b) & 1) acc = g1_add_mixed(acc, p);
        }
    return acc;
}

static bool g1_to_affine(g1a &out, const g1j &p) {
    if (fp_is_zero(p.Z)) { out.inf = true; return true; }
    fp zi = fp_inv(p.Z), zi2 = fp_sqr(zi);
    out.x = fp_mul(p.X, zi2);
    out.y = fp_mul(p.Y, fp_mul(zi2, zi));
    out.inf = false;
    return true;
}

static bool g1_on_curve(const g1a &p) {
    if (p.inf) return true;
    fp y2 = fp_sqr(p.y);
    fp x3 = fp_mul(fp_sqr(p.x), p.x);
    return fp_cmp(y2, fp_add(x3, FP_B)) == 0;
}

static void order_be_bytes(u8 out[32]) {
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            out[31 - 8 * i - j] = (u8)(ORDER_R[i] >> (8 * j));
}

static bool g1_in_subgroup(const g1a &p) {
    if (!g1_on_curve(p)) return false;
    if (p.inf) return true;
    u8 rb[32];
    order_be_bytes(rb);
    return fp_is_zero(g1_mul_be(p, rb, 32).Z);
}

// ------------------------------------------------------------ G2 points

struct g2a { fp2 x, y; bool inf; };
struct g2j { fp2 X, Y, Z; };

static fp2 F2_B2;                  // 4(1+u), Montgomery, set at init

static g2j g2_dbl(const g2j &p) {
    if (f2_is_zero(p.Z)) return p;
    fp2 A = f2_sqr(p.X), B = f2_sqr(p.Y), C = f2_sqr(B);
    fp2 D = f2_add(f2_sub(f2_sub(f2_sqr(f2_add(p.X, B)), A), C),
                   f2_sub(f2_sub(f2_sqr(f2_add(p.X, B)), A), C));
    fp2 E = f2_add(f2_add(A, A), A);
    fp2 F = f2_sqr(E);
    g2j r;
    r.X = f2_sub(F, f2_add(D, D));
    fp2 C8 = f2_add(C, C); C8 = f2_add(C8, C8); C8 = f2_add(C8, C8);
    r.Y = f2_sub(f2_mul(E, f2_sub(D, r.X)), C8);
    r.Z = f2_mul(f2_add(p.Y, p.Y), p.Z);
    return r;
}

static g2j g2_add_mixed(const g2j &p, const g2a &q) {
    if (q.inf) return p;
    if (f2_is_zero(p.Z)) {
        fp2 one = {FP_ONE_M, FP_ZERO};
        g2j r = {q.x, q.y, one};
        return r;
    }
    fp2 Z2 = f2_sqr(p.Z);
    fp2 U2 = f2_mul(q.x, Z2);
    fp2 S2 = f2_mul(f2_mul(q.y, Z2), p.Z);
    if (f2_eq(U2, p.X)) {
        if (!f2_eq(S2, p.Y)) {
            fp2 one = {FP_ONE_M, FP_ZERO};
            return {F2_ZERO, one, F2_ZERO};
        }
        return g2_dbl(p);
    }
    fp2 H = f2_sub(U2, p.X), Rr = f2_sub(S2, p.Y);
    fp2 H2 = f2_sqr(H), H3 = f2_mul(H2, H);
    fp2 V = f2_mul(p.X, H2);
    g2j r;
    r.X = f2_sub(f2_sub(f2_sqr(Rr), H3), f2_add(V, V));
    r.Y = f2_sub(f2_mul(Rr, f2_sub(V, r.X)), f2_mul(p.Y, H3));
    r.Z = f2_mul(p.Z, H);
    return r;
}

static g2j g2_mul_be(const g2a &p, const u8 *e, int elen) {
    fp2 one = {FP_ONE_M, FP_ZERO};
    g2j acc = {F2_ZERO, one, F2_ZERO};
    for (int i = 0; i < elen; i++)
        for (int b = 7; b >= 0; b--) {
            acc = g2_dbl(acc);
            if ((e[i] >> b) & 1) acc = g2_add_mixed(acc, p);
        }
    return acc;
}

static bool g2_to_affine(g2a &out, const g2j &p) {
    if (f2_is_zero(p.Z)) { out.inf = true; return true; }
    fp2 zi = f2_inv(p.Z), zi2 = f2_sqr(zi);
    out.x = f2_mul(p.X, zi2);
    out.y = f2_mul(p.Y, f2_mul(zi2, zi));
    out.inf = false;
    return true;
}

// affine addition (used by the Miller loop's point ladder and hash map)
static g2a g2_add_affine(const g2a &p, const g2a &q) {
    if (p.inf) return q;
    if (q.inf) return p;
    fp2 lam;
    if (f2_eq(p.x, q.x)) {
        if (!f2_eq(p.y, q.y) || f2_is_zero(p.y))
            return {F2_ZERO, F2_ZERO, true};
        fp2 x2 = f2_sqr(p.x);
        fp2 num = f2_add(f2_add(x2, x2), x2);
        lam = f2_mul(num, f2_inv(f2_add(p.y, p.y)));
    } else {
        lam = f2_mul(f2_sub(q.y, p.y), f2_inv(f2_sub(q.x, p.x)));
    }
    fp2 x3 = f2_sub(f2_sub(f2_sqr(lam), p.x), q.x);
    fp2 y3 = f2_sub(f2_mul(lam, f2_sub(p.x, x3)), p.y);
    return {x3, y3, false};
}

static bool g2_on_curve(const g2a &p) {
    if (p.inf) return true;
    fp2 y2 = f2_sqr(p.y);
    fp2 x3 = f2_mul(f2_sqr(p.x), p.x);
    return f2_eq(y2, f2_add(x3, F2_B2));
}

// psi = twist o frobenius o untwist on E'(fp2): with this file's
// untwist (x'/w^2, y'/w^3) and w^p = w GAMMA1,
//   psi(x, y) = (conj(x) GAMMA1^-2, conj(y) GAMMA1^-3).
static fp2 PSI_CX, PSI_CY;         // set at init

static g2a g2_psi(const g2a &p) {
    if (p.inf) return p;
    return {f2_mul(f2_conj(p.x), PSI_CX),
            f2_mul(f2_conj(p.y), PSI_CY), false};
}

// psi on Jacobian coordinates: x = X/Z^2, y = Y/Z^3, and conj is
// multiplicative, so conj each coordinate and scale X, Y only.
static g2j g2j_psi(const g2j &p) {
    return {f2_mul(f2_conj(p.X), PSI_CX),
            f2_mul(f2_conj(p.Y), PSI_CY), f2_conj(p.Z)};
}

static g2j g2j_neg(const g2j &p) { return {p.X, f2_neg(p.Y), p.Z}; }

// general Jacobian-Jacobian addition
static g2j g2j_add(const g2j &p, const g2j &q) {
    if (f2_is_zero(p.Z)) return q;
    if (f2_is_zero(q.Z)) return p;
    fp2 Z1Z1 = f2_sqr(p.Z), Z2Z2 = f2_sqr(q.Z);
    fp2 U1 = f2_mul(p.X, Z2Z2), U2 = f2_mul(q.X, Z1Z1);
    fp2 S1 = f2_mul(f2_mul(p.Y, q.Z), Z2Z2);
    fp2 S2 = f2_mul(f2_mul(q.Y, p.Z), Z1Z1);
    if (f2_eq(U1, U2)) {
        if (!f2_eq(S1, S2)) {
            fp2 one = {FP_ONE_M, FP_ZERO};
            return {F2_ZERO, one, F2_ZERO};
        }
        return g2_dbl(p);
    }
    fp2 H = f2_sub(U2, U1), Rr = f2_sub(S2, S1);
    fp2 H2 = f2_sqr(H), H3 = f2_mul(H2, H);
    fp2 V = f2_mul(U1, H2);
    g2j r;
    r.X = f2_sub(f2_sub(f2_sqr(Rr), H3), f2_add(V, V));
    r.Y = f2_sub(f2_mul(Rr, f2_sub(V, r.X)), f2_mul(S1, H3));
    r.Z = f2_mul(f2_mul(p.Z, q.Z), H);
    return r;
}

// |x| = 0xd201000000010000 big-endian (the BLS parameter magnitude)
static const u8 ABS_X_BE[8] = {0xd2, 0x01, 0, 0, 0, 0x01, 0, 0};

// [x]P over a Jacobian base, x = -|x| (no inversion: stays Jacobian)
static g2j g2j_mul_by_x(const g2j &p) {
    fp2 one = {FP_ONE_M, FP_ZERO};
    g2j acc = {F2_ZERO, one, F2_ZERO};
    for (int i = 0; i < 8; i++)
        for (int b = 7; b >= 0; b--) {
            acc = g2_dbl(acc);
            if ((ABS_X_BE[i] >> b) & 1) acc = g2j_add(acc, p);
        }
    return g2j_neg(acc);
}

static bool g2_in_subgroup(const g2a &p) {
    // psi acts on G2 as multiplication by t-1 = x (Scott's criterion:
    // P is in G2 iff psi(P) == [x]P); a 64-bit ladder instead of the
    // generic 255-bit order multiplication, compared cross-multiplied
    // so no inversion is spent normalizing [x]P
    if (!g2_on_curve(p)) return false;
    if (p.inf) return true;
    g2a lhs = g2_psi(p);                 // p != inf so psi(p) != inf
    fp2 one = {FP_ONE_M, FP_ZERO};
    g2j rhs = g2j_mul_by_x({p.x, p.y, one});
    if (f2_is_zero(rhs.Z)) return false;
    fp2 Z2 = f2_sqr(rhs.Z);
    return f2_eq(f2_mul(lhs.x, Z2), rhs.X) &&
           f2_eq(f2_mul(f2_mul(lhs.y, Z2), rhs.Z), rhs.Y);
}

// -------------------------------------------------------------- pairing
// Optimal ate, affine Miller loop over |x| = 0xd201000000010000, lines
// evaluated generically in fp12 through the same untwist embeddings the
// Python implementation uses (x'/w^2, y'/w^3, lam/w, each times XI^-1).

static fp2 XI_INV_M;       // (1+u)^-1, set at init

// fp12 element layout: ((c00,c01,c02),(c10,c11,c12)) =
//   c00 + c01 v + c02 v^2 + w (c10 + c11 v + c12 v^2), v = w^2
static fp12 embed_fq(const fp &c) {
    fp12 r = {};
    r.c0.c0 = {c, FP_ZERO};
    return r;
}
static fp12 embed_g2_x(const fp2 &x) {
    fp12 r = {};
    r.c0.c2 = f2_mul(x, XI_INV_M);         // x' v^2 / XI
    return r;
}
static fp12 embed_g2_y(const fp2 &y) {
    fp12 r = {};
    r.c1.c1 = f2_mul(y, XI_INV_M);         // y' v w / XI
    return r;
}
static fp12 embed_g2_lambda(const fp2 &lam) {
    fp12 r = {};
    r.c1.c2 = f2_mul(lam, XI_INV_M);       // lam w v^2 / XI
    return r;
}

// line through t and q (tangent when equal) evaluated at p, as fp12;
// *vertical set when x_t == x_q but the points are not doubleable
static fp12 line_eval(const g2a &t, const g2a &q, const g1a &p,
                      bool *vertical) {
    *vertical = false;
    fp2 lam;
    if (f2_eq(t.x, q.x) && f2_eq(t.y, q.y)) {
        if (f2_is_zero(t.y)) { *vertical = true; }
        else {
            fp2 x2 = f2_sqr(t.x);
            lam = f2_mul(f2_add(f2_add(x2, x2), x2),
                         f2_inv(f2_add(t.y, t.y)));
        }
    } else if (f2_eq(t.x, q.x)) {
        *vertical = true;
    } else {
        lam = f2_mul(f2_sub(q.y, t.y), f2_inv(f2_sub(q.x, t.x)));
    }
    if (*vertical) {
        // x - x_t at untwisted coordinates: xp - x_t/w^2
        return f12_sub(embed_fq(p.x), embed_g2_x(t.x));
    }
    // (y_p - y_t) - lam (x_p - x_t), all embedded
    fp12 yp = embed_fq(p.y), xp = embed_fq(p.x);
    fp12 xt = embed_g2_x(t.x), yt = embed_g2_y(t.y);
    fp12 l = embed_g2_lambda(lam);
    return f12_sub(f12_sub(yp, yt), f12_mul(l, f12_sub(xp, xt)));
}

// |x| = 0xd201000000010000, all 64 bits MSB-first (the loop skips the
// leading 1, mirroring the Python bin(n)[3:] iteration)
static const char *ATE_BITS =
    "1101001000000001" "0000000000000000"
    "0000000000000001" "0000000000000000";

static fp12 miller_loop_affine(const g2a &q, const g1a &p) {
    if (q.inf || p.inf) return F12_ONE;
    g2a t = q;
    fp12 f = F12_ONE;
    bool vert;
    for (const char *b = ATE_BITS + 1; *b; b++) {
        fp12 val = line_eval(t, t, p, &vert);
        f = f12_mul(f12_sqr(f), val);
        t = vert ? g2a{F2_ZERO, F2_ZERO, true} : g2_add_affine(t, t);
        if (*b == '1') {
            val = line_eval(t, q, p, &vert);
            f = f12_mul(f, val);
            t = g2_add_affine(t, q);
        }
    }
    return f12_conj(f);        // x < 0
}

// --- inversion-free fast path -------------------------------------------
// Lines are tracked in the sparse form  a + b (v w) + c (v^2 w)  (fp2
// coefficients; exactly the slots the affine embedding populates), and
// the running T stays Jacobian so no per-step field inversion is needed.
// Each line is scaled by a nonzero fp2 constant (the cleared
// denominator), which the final exponentiation's easy part kills:
// fp2* elements are roots of unity under (p^6-1).

// f *= a + b(vw) + c(v^2 w)
static fp12 f12_mul_sparse(const fp12 &f, const fp2 &a, const fp2 &b,
                           const fp2 &c) {
    // A6 = (a,0,0), B6 = (0,b,c):  r0 = f0 A6 + v (f1 B6);
    // r1 = f0 B6 + f1 A6
    fp6 f0a = {f2_mul(f.c0.c0, a), f2_mul(f.c0.c1, a), f2_mul(f.c0.c2, a)};
    fp6 f1a = {f2_mul(f.c1.c0, a), f2_mul(f.c1.c1, a), f2_mul(f.c1.c2, a)};
    // f6 * (0,b,c): 5-mul sparse product (f6_mul with b0 = 0)
    auto mul_sp = [](const fp6 &x, const fp2 &b, const fp2 &c) -> fp6 {
        fp2 t1 = f2_mul(x.c1, b);
        fp2 t2 = f2_mul(x.c2, c);
        fp2 c0 = mul_xi(f2_sub(
            f2_mul(f2_add(x.c1, x.c2), f2_add(b, c)), f2_add(t1, t2)));
        fp2 c1 = f2_add(f2_sub(f2_mul(f2_add(x.c0, x.c1), b), t1),
                        mul_xi(t2));
        fp2 c2 = f2_add(f2_sub(f2_mul(f2_add(x.c0, x.c2), c), t2), t1);
        return {c0, c1, c2};
    };
    fp6 f0b = mul_sp(f.c0, b, c);
    fp6 f1b = mul_sp(f.c1, b, c);
    return {f6_add(f0a, f6_mul_v(f1b)), f6_add(f0b, f1a)};
}

// doubling step: line through T (Jacobian), scaled by 2 Y Z^4; the
// point doubling is inlined so the X^2/Y^2/Z^2 squarings are shared
// with the line coefficients instead of recomputed by g2_dbl
static void dbl_step(g2j &t, const g1a &p, fp2 &a, fp2 &b, fp2 &c,
                     bool *bad) {
    if (f2_is_zero(t.Z) || f2_is_zero(t.Y)) { *bad = true; return; }
    fp2 X2 = f2_sqr(t.X);
    fp2 X3 = f2_mul(X2, t.X);
    fp2 Y2 = f2_sqr(t.Y);
    fp2 Z2 = f2_sqr(t.Z);
    fp2 Z3 = f2_mul(Z2, t.Z);
    fp2 Z4 = f2_sqr(Z2);
    // lambda = 3X^2 / (2YZ); value * 2YZ^4:
    //   a = 2 Y Z^4 yp;  b = Z (3X^3 - 2Y^2) / XI;  c = -3 X^2 Z^3 xp / XI
    fp2 yz4 = f2_mul(t.Y, Z4);
    a = f2_scalar_fp(f2_add(yz4, yz4), p.y);
    fp2 x3_3 = f2_add(f2_add(X3, X3), X3);
    b = f2_mul(f2_mul(t.Z, f2_sub(x3_3, f2_add(Y2, Y2))), XI_INV_M);
    fp2 x2_3 = f2_add(f2_add(X2, X2), X2);
    c = f2_scalar_fp(f2_neg(f2_mul(f2_mul(x2_3, Z3), XI_INV_M)), p.x);
    // doubling with the squares above: C = (Y^2)^2, D = 2((X+Y^2)^2 -
    // X^2 - C), E = 3X^2, F = E^2 (a=0 Jacobian, as g2_dbl)
    fp2 C = f2_sqr(Y2);
    fp2 D = f2_sub(f2_sub(f2_sqr(f2_add(t.X, Y2)), X2), C);
    D = f2_add(D, D);
    fp2 F = f2_sqr(x2_3);
    g2j r;
    r.X = f2_sub(F, f2_add(D, D));
    fp2 C8 = f2_add(C, C); C8 = f2_add(C8, C8); C8 = f2_add(C8, C8);
    r.Y = f2_sub(f2_mul(x2_3, f2_sub(D, r.X)), C8);
    r.Z = f2_mul(f2_add(t.Y, t.Y), t.Z);
    t = r;
}

// addition step: line through T and affine Q, scaled by H Z
static void add_step(g2j &t, const g2a &q, const g1a &p, fp2 &a, fp2 &b,
                     fp2 &c, bool *bad) {
    if (f2_is_zero(t.Z)) { *bad = true; return; }
    fp2 Z2 = f2_sqr(t.Z);
    fp2 Z3 = f2_mul(Z2, t.Z);
    fp2 H = f2_sub(f2_mul(q.x, Z2), t.X);       // xq Z^2 - X
    fp2 M = f2_sub(f2_mul(q.y, Z3), t.Y);       // yq Z^3 - Y
    if (f2_is_zero(H)) { *bad = true; return; }
    // lambda = M / (H Z); value * H Z:
    //   a = H Z yp;  b = (M xq - H Z yq) / XI;  c = -M xp / XI
    fp2 hz = f2_mul(H, t.Z);
    a = f2_scalar_fp(hz, p.y);
    b = f2_mul(f2_sub(f2_mul(M, q.x), f2_mul(hz, q.y)), XI_INV_M);
    c = f2_scalar_fp(f2_neg(f2_mul(M, XI_INV_M)), p.x);
    t = g2_add_mixed(t, q);
}

static fp12 miller_loop(const g2a &q, const g1a &p) {
    if (q.inf || p.inf) return F12_ONE;
    fp2 one2 = {FP_ONE_M, FP_ZERO};
    g2j t = {q.x, q.y, one2};
    fp12 f = F12_ONE;
    fp2 a, b, c;
    bool bad = false;
    for (const char *bit = ATE_BITS + 1; *bit; bit++) {
        dbl_step(t, p, a, b, c, &bad);
        if (bad) return miller_loop_affine(q, p);   // degenerate input
        f = f12_mul_sparse(f12_sqr(f), a, b, c);
        if (*bit == '1') {
            add_step(t, q, p, a, b, c, &bad);
            if (bad) return miller_loop_affine(q, p);
            f = f12_mul_sparse(f, a, b, c);
        }
    }
    return f12_conj(f);        // x < 0
}

#include "sha256_inline.h"

// --------------------------------------------------- hash to G2 (RFC 9380)

static const char DST[] = "BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_";
#define DST_LEN 43
// proof-of-possession domain (draft-irtf-cfrg-bls-signature section 4.2.3):
// PoPs sign the pubkey bytes under this tag so a vote signature can never
// double as a possession proof (same length as the signing DST)
static const char DSTP[] = "BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_";

// expand_message_xmd for length <= 255*32; here always 256 bytes
static void expand_xmd(u8 *out, int outlen, const u8 *msg, size_t msglen,
                       const u8 *dst = (const u8 *)DST,
                       size_t dstlen = DST_LEN) {
    int ell = (outlen + 31) / 32;
    u8 b0[32], bi[32];
    u8 dst_prime[256];
    memcpy(dst_prime, dst, dstlen);
    dst_prime[dstlen] = (u8)dstlen;
    sha256i::ctx c;
    sha256i::init(c);
    u8 zpad[64] = {0};
    sha256i::update(c, zpad, 64);
    sha256i::update(c, msg, msglen);
    u8 lib[3] = {(u8)(outlen >> 8), (u8)outlen, 0};
    sha256i::update(c, lib, 3);
    sha256i::update(c, dst_prime, DST_LEN + 1);
    sha256i::final(c, b0);
    sha256i::init(c);
    sha256i::update(c, b0, 32);
    u8 one = 1;
    sha256i::update(c, &one, 1);
    sha256i::update(c, dst_prime, DST_LEN + 1);
    sha256i::final(c, bi);
    int off = 0;
    for (int i = 2;; i++) {
        int take = outlen - off < 32 ? outlen - off : 32;
        memcpy(out + off, bi, take);
        off += take;
        if (off >= outlen) break;
        u8 x[32];
        for (int j = 0; j < 32; j++) x[j] = b0[j] ^ bi[j];
        sha256i::init(c);
        sha256i::update(c, x, 32);
        u8 ib = (u8)i;
        sha256i::update(c, &ib, 1);
        sha256i::update(c, dst_prime, DST_LEN + 1);
        sha256i::final(c, bi);
    }
}

// 64 big-endian bytes -> fp (mod p), Montgomery
static fp fp_from_wide_be(const u8 in[64]) {
    fp acc = FP_ZERO;
    fp c256 = fp_to_mont({{256, 0, 0, 0, 0, 0}});
    for (int i = 0; i < 64; i++) {
        acc = fp_mul(acc, c256);
        fp b = fp_to_mont({{in[i], 0, 0, 0, 0, 0}});
        acc = fp_add(acc, b);
    }
    return acc;
}

// SSWU constants on the isogenous curve E'' (RFC 9380 section 8.8.2)
static fp2 SSWU_A, SSWU_B, SSWU_Z;     // set at init

// 3-isogeny coefficients (RFC 9380 Appendix E.3), canonical hex pairs;
// converted to Montgomery fp2 at init.  Layout: low->high degree.
struct k2 { const char *c0, *c1; };
static const k2 ISO_XNUM_H[4] = {
    {"5c759507e8e333ebb5b7a9a47d7ed8532c52d39fd3a042a88b58423c50ae15d5c2638e343d9c71c6238aaaaaaaa97d6",
     "5c759507e8e333ebb5b7a9a47d7ed8532c52d39fd3a042a88b58423c50ae15d5c2638e343d9c71c6238aaaaaaaa97d6"},
    {"0",
     "11560bf17baa99bc32126fced787c88f984f87adf7ae0c7f9a208c6b4f20a4181472aaa9cb8d555526a9ffffffffc71a"},
    {"11560bf17baa99bc32126fced787c88f984f87adf7ae0c7f9a208c6b4f20a4181472aaa9cb8d555526a9ffffffffc71e",
     "8ab05f8bdd54cde190937e76bc3e447cc27c3d6fbd7063fcd104635a790520c0a395554e5c6aaaa9354ffffffffe38d"},
    {"171d6541fa38ccfaed6dea691f5fb614cb14b4e7f4e810aa22d6108f142b85757098e38d0f671c7188e2aaaaaaaa5ed1",
     "0"},
};
static const k2 ISO_XDEN_H[3] = {
    {"0",
     "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffaa63"},
    {"c",
     "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffaa9f"},
    {"1", "0"},
};
static const k2 ISO_YNUM_H[4] = {
    {"1530477c7ab4113b59a4c18b076d11930f7da5d4a07f649bf54439d87d27e500fc8c25ebf8c92f6812cfc71c71c6d706",
     "1530477c7ab4113b59a4c18b076d11930f7da5d4a07f649bf54439d87d27e500fc8c25ebf8c92f6812cfc71c71c6d706"},
    {"0",
     "5c759507e8e333ebb5b7a9a47d7ed8532c52d39fd3a042a88b58423c50ae15d5c2638e343d9c71c6238aaaaaaaa97be"},
    {"11560bf17baa99bc32126fced787c88f984f87adf7ae0c7f9a208c6b4f20a4181472aaa9cb8d555526a9ffffffffc71c",
     "8ab05f8bdd54cde190937e76bc3e447cc27c3d6fbd7063fcd104635a790520c0a395554e5c6aaaa9354ffffffffe38f"},
    {"124c9ad43b6cf79bfbf7043de3811ad0761b0f37a1e26286b0e977c69aa274524e79097a56dc4bd9e1b371c71c718b10",
     "0"},
};
static const k2 ISO_YDEN_H[4] = {
    {"1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffa8fb",
     "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffa8fb"},
    {"0",
     "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffa9d3"},
    {"12",
     "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffaa99"},
    {"1", "0"},
};
static fp2 ISO_XNUM[4], ISO_XDEN[3], ISO_YNUM[4], ISO_YDEN[4];

static int hexval(char c) {
    if (c >= '0' && c <= '9') return c - '0';
    if (c >= 'a' && c <= 'f') return c - 'a' + 10;
    if (c >= 'A' && c <= 'F') return c - 'A' + 10;
    return -1;
}

// canonical hex string -> Montgomery fp
static fp fp_from_hex(const char *h) {
    fp a = FP_ZERO;
    for (const char *p = h; *p; p++) {
        int v = hexval(*p);
        // a = a*16 + v over the raw limbs (values stay < p by input)
        u128 c = v;
        for (int i = 0; i < 6; i++) {
            u128 t = ((u128)a.l[i] << 4) + (u64)c;
            a.l[i] = (u64)t;
            c = t >> 64;
        }
    }
    return fp_to_mont(a);
}

static fp2 f2_from_hex(const k2 &k) {
    return {fp_from_hex(k.c0), fp_from_hex(k.c1)};
}

static fp2 horner(const fp2 *k, int n, const fp2 &x) {
    fp2 acc = k[n - 1];
    for (int i = n - 2; i >= 0; i--) acc = f2_add(f2_mul(acc, x), k[i]);
    return acc;
}

// simple SWU map on E'' (RFC 9380 section 6.6.2)
static g2a map_to_curve_sswu(const fp2 &u) {
    fp2 one = {FP_ONE_M, FP_ZERO};
    fp2 u2 = f2_sqr(u);
    fp2 zu2 = f2_mul(SSWU_Z, u2);
    fp2 tv = f2_add(f2_sqr(zu2), zu2);
    fp2 x1;
    if (f2_is_zero(tv)) {
        x1 = f2_mul(SSWU_B, f2_inv(f2_mul(SSWU_Z, SSWU_A)));
    } else {
        x1 = f2_mul(f2_mul(f2_neg(SSWU_B), f2_inv(SSWU_A)),
                    f2_add(one, f2_inv(tv)));
    }
    fp2 gx1 = f2_add(f2_add(f2_mul(f2_sqr(x1), x1), f2_mul(SSWU_A, x1)),
                     SSWU_B);
    fp2 x, y;
    if (f2_sqrt(y, gx1)) {
        x = x1;
    } else {
        fp2 x2 = f2_mul(zu2, x1);
        fp2 gx2 = f2_add(f2_add(f2_mul(f2_sqr(x2), x2), f2_mul(SSWU_A, x2)),
                         SSWU_B);
        if (!f2_sqrt(y, gx2)) { return {F2_ZERO, F2_ZERO, true}; }
        x = x2;
    }
    if (f2_sgn0(u) != f2_sgn0(y)) y = f2_neg(y);
    return {x, y, false};
}

// 3-isogeny E'' -> E' (Appendix E.3 rational maps)
static g2a iso3_map(const g2a &p) {
    if (p.inf) return p;
    fp2 xn = horner(ISO_XNUM, 4, p.x);
    fp2 xd = horner(ISO_XDEN, 3, p.x);
    fp2 yn = horner(ISO_YNUM, 4, p.x);
    fp2 yd = horner(ISO_YDEN, 4, p.x);
    if (f2_is_zero(xd) || f2_is_zero(yd)) return {F2_ZERO, F2_ZERO, true};
    g2a r;
    r.x = f2_mul(xn, f2_inv(xd));
    r.y = f2_mul(p.y, f2_mul(yn, f2_inv(yd)));
    r.inf = false;
    return r;
}

// fast cofactor clearing (RFC 9380 Appendix G.3): equivalent to the
// h_eff multiplication, via Q = [x^2-x-1]P + [x-1]psi(P) + psi^2(2P),
// with two 64-bit parameter ladders instead of one 636-bit ladder.
// The whole chain stays Jacobian (one inversion at the very end).
// Byte-parity with the pure-Python h_eff path is pinned by the tests.
static g2a g2_clear_cofactor(const g2a &p) {
    if (p.inf) return p;
    fp2 one = {FP_ONE_M, FP_ZERO};
    g2j pj = {p.x, p.y, one};
    g2j t1 = g2j_mul_by_x(pj);                   // [x]P
    g2j t2 = g2j_psi(pj);                        // psi(P)
    g2j t3 = g2j_psi(g2j_psi(g2_dbl(pj)));       // psi^2(2P)
    t3 = g2j_add(t3, g2j_neg(t2));               // - psi(P)
    t2 = g2j_add(t1, t2);                        // [x]P + psi(P)
    t2 = g2j_mul_by_x(t2);                       // [x]([x]P + psi(P))
    t3 = g2j_add(t3, t2);
    t3 = g2j_add(t3, g2j_neg(t1));               // - [x]P
    t3 = g2j_add(t3, g2j_neg(pj));               // - P
    g2a out;
    g2_to_affine(out, t3);
    return out;
}

static g2a hash_to_g2(const u8 *msg, size_t msglen,
                      const u8 *dst = (const u8 *)DST,
                      size_t dstlen = DST_LEN) {
    u8 uniform[256];
    expand_xmd(uniform, 256, msg, msglen, dst, dstlen);
    fp2 u0 = {fp_from_wide_be(uniform), fp_from_wide_be(uniform + 64)};
    fp2 u1 = {fp_from_wide_be(uniform + 128), fp_from_wide_be(uniform + 192)};
    g2a q0 = iso3_map(map_to_curve_sswu(u0));
    g2a q1 = iso3_map(map_to_curve_sswu(u1));
    return g2_clear_cofactor(g2_add_affine(q0, q1));
}

// --------------------------------------------------- serialization (zcash)

static void g1_compress(u8 out[48], const g1a &p) {
    if (p.inf) {
        memset(out, 0, 48);
        out[0] = 0xC0;
        return;
    }
    fp_to_bytes_be(out, p.x);
    out[0] |= 0x80 | (fp_is_larger(p.y) ? 0x20 : 0);
}

static bool g1_decompress(g1a &out, const u8 in[48]) {
    if (!(in[0] & 0x80)) return false;
    if (in[0] & 0x40) {
        if (in[0] != 0xC0) return false;
        for (int i = 1; i < 48; i++) if (in[i]) return false;
        out = {FP_ZERO, FP_ZERO, true};
        return true;
    }
    bool sign = in[0] & 0x20;
    u8 xb[48];
    memcpy(xb, in, 48);
    xb[0] &= 0x1F;
    fp x;
    if (!fp_from_bytes_be(x, xb)) return false;
    fp y2 = fp_add(fp_mul(fp_sqr(x), x), FP_B);
    fp y;
    if (!fp_sqrt(y, y2)) return false;
    if (fp_is_larger(y) != sign) y = fp_neg(y);
    out = {x, y, false};
    return true;
}

static void g2_compress(u8 out[96], const g2a &p) {
    if (p.inf) {
        memset(out, 0, 96);
        out[0] = 0xC0;
        return;
    }
    fp_to_bytes_be(out, p.x.c1);
    fp_to_bytes_be(out + 48, p.x.c0);
    out[0] |= 0x80 | (f2_is_larger(p.y) ? 0x20 : 0);
}

static bool g2_decompress(g2a &out, const u8 in[96]) {
    if (!(in[0] & 0x80)) return false;
    if (in[0] & 0x40) {
        if (in[0] != 0xC0) return false;
        for (int i = 1; i < 96; i++) if (in[i]) return false;
        out = {F2_ZERO, F2_ZERO, true};
        return true;
    }
    bool sign = in[0] & 0x20;
    u8 xb[48];
    memcpy(xb, in, 48);
    xb[0] &= 0x1F;
    fp x1, x0;
    if (!fp_from_bytes_be(x1, xb)) return false;
    if (!fp_from_bytes_be(x0, in + 48)) return false;
    fp2 x = {x0, x1};
    fp2 y2 = f2_add(f2_mul(f2_sqr(x), x), F2_B2);
    fp2 y;
    if (!f2_sqrt(y, y2)) return false;
    if (f2_is_larger(y) != sign) y = f2_neg(y);
    out = {x, y, false};
    return true;
}

// ----------------------------------------------------------------- init

static bool INIT_DONE = false;

static void bls_init() {
    if (INIT_DONE) return;
    // derived exponents from P
    big_sub_small(E_P_M2, P.l, 2);
    u64 t[6];
    big_add_small(t, P.l, 1);
    big_shr(E_P_P1_D4, t, 2);
    big_sub_small(t, P.l, 3);
    big_shr(E_P_M3_D4, t, 2);
    big_sub_small(t, P.l, 1);
    big_shr(E_P_M1_D2, t, 1);
    memcpy(HALF_P.l, E_P_M1_D2, sizeof HALF_P.l);
    // towers & constants
    fp four = fp_to_mont({{4, 0, 0, 0, 0, 0}});
    FP_B = four;
    F2_B2 = {four, four};
    fp2 xi = {FP_ONE_M, FP_ONE_M};
    XI_INV_M = f2_inv(xi);
    F12_ONE = {};
    F12_ONE.c0.c0 = {FP_ONE_M, FP_ZERO};
    for (int k = 0; k < 6; k++) G2GAMMA[k] = fp_to_mont(G2GAMMA_CANON[k]);
    // GAMMA1 = XI^((p-1)/6) for the Frobenius^1 coefficient map
    u64 e16[6];
    big_sub_small(t, P.l, 1);
    big_div_small(e16, t, 6);
    GAMMA1_POW[0] = {FP_ONE_M, FP_ZERO};
    GAMMA1_POW[1] = f2_pow(xi, e16, 381);
    for (int k = 2; k < 6; k++)
        GAMMA1_POW[k] = f2_mul(GAMMA1_POW[k - 1], GAMMA1_POW[1]);
    PSI_CX = f2_inv(GAMMA1_POW[2]);
    PSI_CY = f2_inv(GAMMA1_POW[3]);
    G1_GEN = {fp_to_mont(G1X_CANON), fp_to_mont(G1Y_CANON), false};
    // SSWU constants: A' = 240 u, B' = 1012(1+u), Z = -(2+u)
    fp c240 = fp_to_mont({{240, 0, 0, 0, 0, 0}});
    fp c1012 = fp_to_mont({{1012, 0, 0, 0, 0, 0}});
    fp c2 = fp_to_mont({{2, 0, 0, 0, 0, 0}});
    SSWU_A = {FP_ZERO, c240};
    SSWU_B = {c1012, c1012};
    SSWU_Z = {fp_neg(c2), fp_neg(FP_ONE_M)};
    for (int i = 0; i < 4; i++) ISO_XNUM[i] = f2_from_hex(ISO_XNUM_H[i]);
    for (int i = 0; i < 3; i++) ISO_XDEN[i] = f2_from_hex(ISO_XDEN_H[i]);
    for (int i = 0; i < 4; i++) ISO_YNUM[i] = f2_from_hex(ISO_YNUM_H[i]);
    for (int i = 0; i < 4; i++) ISO_YDEN[i] = f2_from_hex(ISO_YDEN_H[i]);
    INIT_DONE = true;
}

// ------------------------------------------------------------------ API

extern "C" {

// sk: 32 bytes big-endian (already reduced mod r by the caller)
int bls_sk_to_pk(const u8 *sk, u8 *out48) {
    bls_init();
    g1a pk;
    g1_to_affine(pk, g1_mul_be(G1_GEN, sk, 32));
    g1_compress(out48, pk);
    return 1;
}

int bls_sign(const u8 *sk, const u8 *msg, size_t msglen, u8 *out96) {
    bls_init();
    g2a h = hash_to_g2(msg, msglen);
    g2a sig;
    g2_to_affine(sig, g2_mul_be(h, sk, 32));
    g2_compress(out96, sig);
    return 1;
}

int bls_verify(const u8 *pk48, const u8 *msg, size_t msglen,
               const u8 *sig96) {
    bls_init();
    g1a pk;
    g2a sig;
    if (!g1_decompress(pk, pk48)) return 0;
    if (!g2_decompress(sig, sig96)) return 0;
    if (pk.inf || sig.inf) return 0;
    if (!g1_in_subgroup(pk)) return 0;
    if (!g2_in_subgroup(sig)) return 0;
    g2a h = hash_to_g2(msg, msglen);
    // e(pk, H(m)) == e(g1, sig)  <=>  e(pk, H(m)) e(-g1, sig) == 1
    g1a neg_g1 = {G1_GEN.x, fp_neg(G1_GEN.y), false};
    fp12 f = f12_mul(miller_loop(h, pk), miller_loop(sig, neg_g1));
    return f12_is_one(final_exponentiation(f)) ? 1 : 0;
}

// --------------------------------------------- aggregation (same-message)

// Fold n compressed G2 signatures into one. `check` toggles the per-input
// subgroup check — callers that already validated inputs (e.g. sigs that
// passed individual vote verification) pass 0 and skip the scalar mults.
int bls_agg_sigs(const u8 *sigs, size_t n, int check, u8 *out96) {
    bls_init();
    if (n == 0) return 0;
    fp2 one = {FP_ONE_M, FP_ZERO};
    g2j acc = {F2_ZERO, one, F2_ZERO};
    for (size_t i = 0; i < n; i++) {
        g2a s;
        if (!g2_decompress(s, sigs + 96 * i)) return 0;
        if (s.inf) return 0;
        if (check && !g2_in_subgroup(s)) return 0;
        acc = g2_add_mixed(acc, s);
    }
    g2a out;
    g2_to_affine(out, acc);
    g2_compress(out96, out);
    return 1;
}

int bls_agg_pks(const u8 *pks, size_t n, int check, u8 *out48) {
    bls_init();
    if (n == 0) return 0;
    g1j acc = {FP_ZERO, FP_ONE_M, FP_ZERO};
    for (size_t i = 0; i < n; i++) {
        g1a p;
        if (!g1_decompress(p, pks + 48 * i)) return 0;
        if (p.inf) return 0;
        if (check && !g1_in_subgroup(p)) return 0;
        acc = g1_add_mixed(acc, p);
    }
    g1a out;
    g1_to_affine(out, acc);
    g1_compress(out48, out);
    return 1;
}

// FastAggregateVerify: all signers signed the same message. Full input
// validation (decompress + subgroup on every pk and the sig); the commit
// hot path goes through the affine-table variants below instead.
int bls_fagg_verify(const u8 *pks, size_t n, const u8 *msg, size_t msglen,
                    const u8 *sig96) {
    bls_init();
    if (n == 0) return 0;
    g1j acc = {FP_ZERO, FP_ONE_M, FP_ZERO};
    for (size_t i = 0; i < n; i++) {
        g1a p;
        if (!g1_decompress(p, pks + 48 * i)) return 0;
        if (p.inf) return 0;
        if (!g1_in_subgroup(p)) return 0;
        acc = g1_add_mixed(acc, p);
    }
    g1a apk;
    g1_to_affine(apk, acc);
    if (apk.inf) return 0;
    g2a sig;
    if (!g2_decompress(sig, sig96)) return 0;
    if (sig.inf) return 0;
    if (!g2_in_subgroup(sig)) return 0;
    g2a h = hash_to_g2(msg, msglen);
    g1a neg_g1 = {G1_GEN.x, fp_neg(G1_GEN.y), false};
    fp12 f = f12_mul(miller_loop(h, apk), miller_loop(sig, neg_g1));
    return f12_is_one(final_exponentiation(f)) ? 1 : 0;
}

// ------------------------------------- affine pubkey tables (hot path)
// The per-valset cache decompresses + subgroup-checks each pubkey ONCE
// via bls_pk_to_affine, then per-commit work is pure affine adds.
// Affine form: x||y, each 48 bytes canonical big-endian.

int bls_pk_to_affine(const u8 *pk48, u8 *out96) {
    bls_init();
    g1a pk;
    if (!g1_decompress(pk, pk48)) return 0;
    if (pk.inf) return 0;
    if (!g1_in_subgroup(pk)) return 0;
    fp_to_bytes_be(out96, pk.x);
    fp_to_bytes_be(out96 + 48, pk.y);
    return 1;
}

// Sum n affine points (0 = malformed input, 1 = ok, 2 = sum is infinity).
// Inputs are on-curve-checked only; subgroup membership was vouched for
// by bls_pk_to_affine when the table was built.
int bls_agg_affine(const u8 *pts96, size_t n, u8 *out96) {
    bls_init();
    if (n == 0) return 0;
    g1j acc = {FP_ZERO, FP_ONE_M, FP_ZERO};
    for (size_t i = 0; i < n; i++) {
        fp x, y;
        if (!fp_from_bytes_be(x, pts96 + 96 * i)) return 0;
        if (!fp_from_bytes_be(y, pts96 + 96 * i + 48)) return 0;
        g1a p = {x, y, false};
        if (!g1_on_curve(p)) return 0;
        acc = g1_add_mixed(acc, p);
    }
    g1a out;
    g1_to_affine(out, acc);
    if (out.inf) { memset(out96, 0, 96); return 2; }
    fp_to_bytes_be(out96, out.x);
    fp_to_bytes_be(out96 + 48, out.y);
    return 1;
}

// Verify an aggregate signature against a pre-aggregated affine pubkey:
// exactly two Miller loops + one final exponentiation.
int bls_verify_agg_affine(const u8 *xy96, const u8 *msg, size_t msglen,
                          const u8 *sig96) {
    bls_init();
    fp x, y;
    if (!fp_from_bytes_be(x, xy96)) return 0;
    if (!fp_from_bytes_be(y, xy96 + 48)) return 0;
    g1a apk = {x, y, false};
    if (!g1_on_curve(apk)) return 0;
    g2a sig;
    if (!g2_decompress(sig, sig96)) return 0;
    if (sig.inf) return 0;
    if (!g2_in_subgroup(sig)) return 0;
    g2a h = hash_to_g2(msg, msglen);
    g1a neg_g1 = {G1_GEN.x, fp_neg(G1_GEN.y), false};
    fp12 f = f12_mul(miller_loop(h, apk), miller_loop(sig, neg_g1));
    return f12_is_one(final_exponentiation(f)) ? 1 : 0;
}

// ------------------------------------------------- proof of possession

int bls_pop_prove(const u8 *sk, u8 *out96) {
    bls_init();
    u8 pk[48];
    bls_sk_to_pk(sk, pk);
    g2a h = hash_to_g2(pk, 48, (const u8 *)DSTP, sizeof DSTP - 1);
    g2a pop;
    g2_to_affine(pop, g2_mul_be(h, sk, 32));
    g2_compress(out96, pop);
    return 1;
}

int bls_pop_verify(const u8 *pk48, const u8 *pop96) {
    bls_init();
    g1a pk;
    g2a pop;
    if (!g1_decompress(pk, pk48)) return 0;
    if (!g2_decompress(pop, pop96)) return 0;
    if (pk.inf || pop.inf) return 0;
    if (!g1_in_subgroup(pk)) return 0;
    if (!g2_in_subgroup(pop)) return 0;
    g2a h = hash_to_g2(pk48, 48, (const u8 *)DSTP, sizeof DSTP - 1);
    g1a neg_g1 = {G1_GEN.x, fp_neg(G1_GEN.y), false};
    fp12 f = f12_mul(miller_loop(h, pk), miller_loop(pop, neg_g1));
    return f12_is_one(final_exponentiation(f)) ? 1 : 0;
}

// sanity pipeline: key -> pk -> sign -> verify (+ tamper reject)
int bls_selftest(void) {
    bls_init();
    if (!g1_on_curve(G1_GEN)) return 0;
    u8 sk[32] = {0};
    sk[31] = 7;
    u8 pk[48], sig[96];
    bls_sk_to_pk(sk, pk);
    const u8 msg[] = "bls-selftest";
    bls_sign(sk, msg, sizeof msg - 1, sig);
    if (!bls_verify(pk, msg, sizeof msg - 1, sig)) return 0;
    u8 bad[96];
    memcpy(bad, sig, 96);
    bad[95] ^= 1;
    if (bls_verify(pk, msg, sizeof msg - 1, bad)) return 0;
    const u8 msg2[] = "bls-selftest2";
    if (bls_verify(pk, msg2, sizeof msg2 - 1, sig)) return 0;
    return 1;
}

}  // extern "C"
