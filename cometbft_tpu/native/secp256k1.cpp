// Native secp256k1 ECDSA verification (reference seam:
// crypto/secp256k1/secp256k1.go, backed there by dcrd's C-accelerated
// library).  Original implementation, same design discipline as
// native/ed25519.cpp: radix-2^52 field limbs with unsigned __int128
// accumulation, Jacobian point arithmetic (a=0 short Weierstrass),
// Barrett scalar arithmetic mod n, and one Shamir joint ladder for
// u1*G + u2*Q.  Semantics match cometbft_tpu/crypto/secp256k1.py
// exactly: 33-byte compressed pubkeys, 64-byte r||s big-endian
// signatures, 1 <= r,s < n, LOW-S ONLY, e = SHA-256(msg) mod n,
// valid iff R != inf and R.x mod n == r.
//
// Exported C ABI (ctypes):
//   secp256k1_verify(pub33, sig64, msg, msg_len) -> 1/0

#include <cstdint>
#include <cstring>

typedef uint64_t u64;
typedef unsigned __int128 u128;
typedef uint8_t u8;

// ------------------------------------------------------------------ sha256
// FIPS 180-4.

static const uint32_t SHA256_K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
    0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
    0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
    0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
    0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
    0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
    0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
    0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
    0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t ror32(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
}

static void sha256(const u8* msg, u64 len, u8 out[32]) {
    uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                     0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    u64 total = len;
    const u8* p = msg;
    // process: full blocks, then padding block(s)
    u8 tail[128];
    u64 tail_len = len % 64;
    u64 full = len - tail_len;
    memcpy(tail, p + full, tail_len);
    tail[tail_len] = 0x80;
    u64 pad_total = (tail_len + 9 <= 64) ? 64 : 128;
    memset(tail + tail_len + 1, 0, pad_total - tail_len - 1 - 8);
    u64 bits = total * 8;
    for (int i = 0; i < 8; i++)
        tail[pad_total - 8 + i] = (u8)(bits >> (56 - 8 * i));
    for (u64 off = 0; off <= full + pad_total - 64; off += 64) {
        const u8* b = (off < full) ? p + off : tail + (off - full);
        uint32_t w[64];
        for (int i = 0; i < 16; i++)
            w[i] = ((uint32_t)b[4 * i] << 24) | ((uint32_t)b[4 * i + 1] << 16)
                 | ((uint32_t)b[4 * i + 2] << 8) | b[4 * i + 3];
        for (int i = 16; i < 64; i++) {
            uint32_t s0 = ror32(w[i - 15], 7) ^ ror32(w[i - 15], 18)
                        ^ (w[i - 15] >> 3);
            uint32_t s1 = ror32(w[i - 2], 17) ^ ror32(w[i - 2], 19)
                        ^ (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        uint32_t a = h[0], bb = h[1], c = h[2], d = h[3];
        uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
        for (int i = 0; i < 64; i++) {
            uint32_t S1 = ror32(e, 6) ^ ror32(e, 11) ^ ror32(e, 25);
            uint32_t ch = (e & f) ^ (~e & g);
            uint32_t t1 = hh + S1 + ch + SHA256_K[i] + w[i];
            uint32_t S0 = ror32(a, 2) ^ ror32(a, 13) ^ ror32(a, 22);
            uint32_t maj = (a & bb) ^ (a & c) ^ (bb & c);
            uint32_t t2 = S0 + maj;
            hh = g; g = f; f = e; e = d + t1;
            d = c; c = bb; bb = a; a = t1 + t2;
        }
        h[0] += a; h[1] += bb; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 4; j++)
            out[4 * i + j] = (u8)(h[i] >> (24 - 8 * j));
}

// ----------------------------------------------- field GF(2^256 - 2^32 - 977)
// Radix-2^52, 5 limbs.  2^260 == 16*C (mod p) with C = 2^32 + 977, so
// overflow limbs fold back with a single small multiply.

struct fe { u64 v[5]; };

static const u64 M52 = (1ULL << 52) - 1;
static const u64 C16 = 0x1000003D10ULL;     // 16 * (2^32 + 977)

static const fe FE_SEVEN = {{7, 0, 0, 0, 0}};
static const fe GX = {{0x2815b16f81798ULL, 0xdb2dce28d959fULL,
                       0xe870b07029bfcULL, 0xbbac55a06295cULL,
                       0x79be667ef9dcULL}};
static const fe GY = {{0x7d08ffb10d4b8ULL, 0x48a68554199c4ULL,
                       0xe1108a8fd17b4ULL, 0xc4655da4fbfc0ULL,
                       0x483ada7726a3ULL}};

static inline void fe_carry_weak(fe& r) {
    // bring limbs under ~2^52 (top limb may hold up to 2^48+eps after a
    // fold; 2^260 overflow recycles through C16/16 at limb 0)
    u64 c;
    c = r.v[4] >> 48;            // keep top limb at 48 bits so products
    r.v[4] &= (1ULL << 48) - 1;  // never reach the fold limit
    // c * 2^(4*52+48) = c * 2^256 == c * (2^32+977) = c * (C16 >> 4)
    u128 t = (u128)c * (C16 >> 4) + r.v[0];
    r.v[0] = (u64)t & M52;
    r.v[1] += (u64)(t >> 52);
    for (int i = 1; i < 4; i++) {
        c = r.v[i] >> 52;
        r.v[i] &= M52;
        r.v[i + 1] += c;
    }
}

static inline void fe_add(fe& r, const fe& a, const fe& b) {
    for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + b.v[i];
    fe_carry_weak(r);
}

// 4p as per-limb multiples (NOT normalized) for the subtraction bias:
// every limb is >= 2^53, strictly above the largest weakly-reduced
// operand limb (~2^52 + 2^41), so a.v[i] + BIAS4P[i] - b.v[i] can never
// underflow u64 (a 2p bias with a normalized low limb CAN underflow —
// it sat below 2^52 — and silently corrupted ~2^-19 of decompressions)
static const u64 BIAS4P[5] = {0x3ffffbfffff0bcULL, 0x3ffffffffffffcULL,
                              0x3ffffffffffffcULL, 0x3ffffffffffffcULL,
                              0x3fffffffffffcULL};

static inline void fe_sub(fe& r, const fe& a, const fe& b) {
    for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + BIAS4P[i] - b.v[i];
    fe_carry_weak(r);
}

static inline void _fold10(const u128 acc[10], fe& r) {
    // carry into 10 exact 52-bit limbs (a*b < 2^512 < 2^520, so the
    // chain terminates with no residue past limb 9), then fold: limb
    // (5+k) has weight 2^(260+52k) == 16C * 2^(52k).  lo[5+k]*C16 <=
    // 2^52 * 2^41 = 2^93, so t fits u128; the fold carry stays <= 2^41.
    u64 lo[10];
    u128 carry = 0;
    for (int i = 0; i < 10; i++) {
        carry += acc[i];
        lo[i] = (u64)carry & M52;
        carry >>= 52;
    }
    u64 res[6] = {lo[0], lo[1], lo[2], lo[3], lo[4], 0};
    u64 cc = 0;
    for (int k = 0; k < 5; k++) {
        u128 t = (u128)lo[5 + k] * C16 + res[k] + cc;
        res[k] = (u64)t & M52;
        cc = (u64)(t >> 52);
    }
    res[5] = cc;                          // weight 2^260 again, <= 2^41
    u128 t2 = (u128)res[5] * C16 + res[0];
    res[0] = (u64)t2 & M52;
    res[1] += (u64)(t2 >> 52);            // <= 2^30 extra: no overflow
    fe out = {{res[0], res[1], res[2], res[3], res[4]}};
    fe_carry_weak(out);
    r = out;
}

static void fe_mul(fe& r, const fe& a, const fe& b) {
    u128 acc[10] = {0};
    for (int i = 0; i < 5; i++)
        for (int j = 0; j < 5; j++)
            acc[i + j] += (u128)a.v[i] * b.v[j];
    _fold10(acc, r);
}

static inline void fe_sq(fe& r, const fe& a) {
    u64 a0 = a.v[0], a1 = a.v[1], a2 = a.v[2], a3 = a.v[3], a4 = a.v[4];
    u128 acc[10];
    acc[0] = (u128)a0 * a0;
    acc[1] = (u128)(2 * a0) * a1;
    acc[2] = (u128)(2 * a0) * a2 + (u128)a1 * a1;
    acc[3] = (u128)(2 * a0) * a3 + (u128)(2 * a1) * a2;
    acc[4] = (u128)(2 * a0) * a4 + (u128)(2 * a1) * a3 + (u128)a2 * a2;
    acc[5] = (u128)(2 * a1) * a4 + (u128)(2 * a2) * a3;
    acc[6] = (u128)(2 * a2) * a4 + (u128)a3 * a3;
    acc[7] = (u128)(2 * a3) * a4;
    acc[8] = (u128)a4 * a4;
    acc[9] = 0;
    _fold10(acc, r);
}

static void fe_frombytes(fe& r, const u8 s[32]) {
    // big-endian 32 bytes -> 5x52 limbs
    u64 w[4];
    for (int i = 0; i < 4; i++) {
        w[i] = 0;
        for (int j = 0; j < 8; j++)
            w[i] = (w[i] << 8) | s[8 * i + j];   // w[0] = most significant
    }
    u64 w0 = w[3], w1 = w[2], w2 = w[1], w3 = w[0];   // little-endian now
    r.v[0] = w0 & M52;
    r.v[1] = ((w0 >> 52) | (w1 << 12)) & M52;
    r.v[2] = ((w1 >> 40) | (w2 << 24)) & M52;
    r.v[3] = ((w2 >> 28) | (w3 << 36)) & M52;
    r.v[4] = w3 >> 16;
    fe_carry_weak(r);
}

static void fe_tobytes(u8 s[32], const fe& a) {
    fe t = a;
    fe_carry_weak(t);
    fe_carry_weak(t);
    // canonical: add C and check overflow of 2^256 (t >= p iff t + C
    // carries past bit 256, with C = 2^32 + 977)
    u64 c0 = C16 >> 4;
    u64 q = (t.v[0] + c0) >> 52;
    q = (t.v[1] + q) >> 52;
    q = (t.v[2] + q) >> 52;
    q = (t.v[3] + q) >> 52;
    q = (t.v[4] + q) >> 48;              // top limb holds 48 bits
    // if q: t -= p  (equivalently t = t + C, dropping bit 256)
    if (q) {
        u128 tt = (u128)t.v[0] + c0;
        t.v[0] = (u64)tt & M52;
        u64 cc = (u64)(tt >> 52);
        for (int i = 1; i < 5; i++) {
            u64 s2 = t.v[i] + cc;
            cc = s2 >> 52;
            t.v[i] = s2 & M52;
        }
        t.v[4] &= (1ULL << 48) - 1;      // drop 2^256
    }
    u64 w0 = t.v[0] | (t.v[1] << 52);
    u64 w1 = (t.v[1] >> 12) | (t.v[2] << 40);
    u64 w2 = (t.v[2] >> 24) | (t.v[3] << 28);
    u64 w3 = (t.v[3] >> 36) | (t.v[4] << 16);
    u64 w[4] = {w3, w2, w1, w0};         // big-endian order
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            s[8 * i + j] = (u8)(w[i] >> (56 - 8 * j));
}

static bool fe_iszero(const fe& a) {
    u8 b[32];
    fe_tobytes(b, a);
    u8 acc = 0;
    for (int i = 0; i < 32; i++) acc |= b[i];
    return acc == 0;
}

static bool fe_equal(const fe& a, const fe& b) {
    fe d;
    fe_sub(d, a, b);
    return fe_iszero(d);
}

static bool fe_isodd(const fe& a) {
    u8 b[32];
    fe_tobytes(b, a);
    return b[31] & 1;
}

// generic pow over a big-endian 32-byte exponent (fixed public exponents)
static void fe_pow(fe& r, const fe& a, const u8 exp[32]) {
    fe acc;
    bool started = false;
    for (int byte = 0; byte < 32; byte++) {
        for (int bit = 7; bit >= 0; bit--) {
            if (started) fe_sq(acc, acc);
            if ((exp[byte] >> bit) & 1) {
                if (started) fe_mul(acc, acc, a);
                else { acc = a; started = true; }
            }
        }
    }
    r = acc;
}

static void fe_invert(fe& r, const fe& a) {
    // p - 2, big-endian
    static const u8 e[32] = {
        0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
        0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
        0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
        0xff, 0xff, 0xff, 0xfe, 0xff, 0xff, 0xfc, 0x2d};
    fe_pow(r, a, e);
}

static bool fe_sqrt(fe& r, const fe& a) {
    // p == 3 (mod 4): candidate = a^((p+1)/4); verify square
    static const u8 e[32] = {
        0x3f, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
        0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
        0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
        0xff, 0xff, 0xff, 0xff, 0xbf, 0xff, 0xff, 0x0c};
    fe cand, chk;
    fe_pow(cand, a, e);
    fe_sq(chk, cand);
    if (!fe_equal(chk, a)) return false;
    r = cand;
    return true;
}

// -------------------------------------------------------------- scalars mod n

static const u64 SC_N[4] = {0xbfd25e8cd0364141ULL, 0xbaaedce6af48a03bULL,
                            0xfffffffffffffffeULL, 0xffffffffffffffffULL};
static const u64 SC_HALF_N[4] = {0xdfe92f46681b20a0ULL,
                                 0x5d576e7357a4501dULL,
                                 0xffffffffffffffffULL,
                                 0x7fffffffffffffffULL};
static const u64 SC_MU[5] = {0x402da1732fc9bec0ULL, 0x4551231950b75fc4ULL,
                             0x1ULL, 0x0ULL, 0x1ULL};

struct sc { u64 v[4]; };

static inline int sc_geq(const u64 a[4], const u64 b[4]) {
    for (int i = 3; i >= 0; i--) {
        if (a[i] > b[i]) return 1;
        if (a[i] < b[i]) return 0;
    }
    return 1;
}

static inline bool sc_iszero(const sc& a) {
    return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

static void sc_reduce512(sc& r, const u64 x[8]) {
    u64 prod[13] = {0};
    for (int i = 0; i < 8; i++) {
        u64 carry = 0;
        for (int j = 0; j < 5; j++) {
            u128 t = (u128)x[i] * SC_MU[j] + prod[i + j] + carry;
            prod[i + j] = (u64)t;
            carry = (u64)(t >> 64);
        }
        prod[i + 5] += carry;
    }
    u64 q[5];
    for (int i = 0; i < 5; i++) q[i] = prod[8 + i];
    u64 ql[8] = {0};
    for (int i = 0; i < 5; i++) {
        u64 carry = 0;
        for (int j = 0; j < 4 && i + j < 8; j++) {
            u128 t = (u128)q[i] * SC_N[j] + ql[i + j] + carry;
            ql[i + j] = (u64)t;
            carry = (u64)(t >> 64);
        }
        if (i + 4 < 8) ql[i + 4] += carry;
    }
    u64 rem[8];
    u64 borrow = 0;
    for (int i = 0; i < 8; i++) {
        u64 bi = ql[i] + borrow;
        borrow = (bi < borrow) ? 1 : (x[i] < bi ? 1 : 0);
        rem[i] = x[i] - bi;
    }
    for (int k = 0; k < 3; k++)
        if (rem[4] | rem[5] | rem[6] | rem[7] || sc_geq(rem, SC_N)) {
            u64 borrow2 = 0;
            for (int i = 0; i < 8; i++) {
                u64 bi = (i < 4 ? SC_N[i] : 0) + borrow2;
                borrow2 = (bi < borrow2) ? 1 : (rem[i] < bi ? 1 : 0);
                rem[i] = rem[i] - bi;
            }
        }
    for (int i = 0; i < 4; i++) r.v[i] = rem[i];
}

static void sc_mul(sc& r, const sc& a, const sc& b) {
    u64 prod[8] = {0};
    for (int i = 0; i < 4; i++) {
        u64 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 t = (u128)a.v[i] * b.v[j] + prod[i + j] + carry;
            prod[i + j] = (u64)t;
            carry = (u64)(t >> 64);
        }
        prod[i + 4] = carry;
    }
    sc_reduce512(r, prod);
}

// load 32 big-endian bytes; true if the value is in [1, n)
static bool sc_from_bytes_checked(sc& r, const u8 b[32]) {
    for (int i = 0; i < 4; i++) {
        r.v[i] = 0;
        for (int j = 0; j < 8; j++)
            r.v[i] = (r.v[i] << 8) | b[(3 - i) * 8 + j];
    }
    return !sc_iszero(r) && !sc_geq(r.v, SC_N);
}

static void sc_from_hash(sc& r, const u8 b[32]) {
    u64 x[8] = {0};
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            x[i] = (x[i] << 8) | b[(3 - i) * 8 + j];
    sc_reduce512(r, x);
}

// 256-bit helpers for the inversion (variable-time is fine: ECDSA
// verification handles only public values)
static inline bool u256_iszero(const u64 a[4]) {
    return (a[0] | a[1] | a[2] | a[3]) == 0;
}

static inline bool u256_iseven(const u64 a[4]) { return !(a[0] & 1); }

static inline void u256_rshift1(u64 a[4]) {
    a[0] = (a[0] >> 1) | (a[1] << 63);
    a[1] = (a[1] >> 1) | (a[2] << 63);
    a[2] = (a[2] >> 1) | (a[3] << 63);
    a[3] >>= 1;
}

static inline u64 u256_add(u64 r[4], const u64 a[4], const u64 b[4]) {
    u128 c = 0;
    for (int i = 0; i < 4; i++) {
        c += (u128)a[i] + b[i];
        r[i] = (u64)c;
        c >>= 64;
    }
    return (u64)c;
}

static inline void u256_sub(u64 r[4], const u64 a[4], const u64 b[4]) {
    u64 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u64 bi = b[i] + borrow;
        borrow = (bi < borrow) ? 1 : (a[i] < bi ? 1 : 0);
        r[i] = a[i] - bi;
    }
}

static void sc_invert(sc& r, const sc& a) {
    // binary extended gcd mod n (~15x faster than the Fermat ladder of
    // Barrett multiplications; gcd(a, n) == 1 since n is prime)
    u64 u[4] = {a.v[0], a.v[1], a.v[2], a.v[3]};
    u64 v[4] = {SC_N[0], SC_N[1], SC_N[2], SC_N[3]};
    u64 x1[4] = {1, 0, 0, 0};
    u64 x2[4] = {0, 0, 0, 0};
    while (!u256_iszero(u) && !u256_iszero(v)) {
        while (u256_iseven(u)) {
            u256_rshift1(u);
            if (u256_iseven(x1)) {
                u256_rshift1(x1);
            } else {
                u64 carry = u256_add(x1, x1, SC_N);
                u256_rshift1(x1);
                x1[3] |= carry << 63;
            }
        }
        while (u256_iseven(v)) {
            u256_rshift1(v);
            if (u256_iseven(x2)) {
                u256_rshift1(x2);
            } else {
                u64 carry = u256_add(x2, x2, SC_N);
                u256_rshift1(x2);
                x2[3] |= carry << 63;
            }
        }
        if (sc_geq(u, v)) {
            u256_sub(u, u, v);
            // x1 = (x1 - x2) mod n
            if (sc_geq(x1, x2)) {
                u256_sub(x1, x1, x2);
            } else {
                u64 t[4];
                u256_sub(t, x2, x1);
                u256_sub(x1, SC_N, t);
            }
        } else {
            u256_sub(v, v, u);
            if (sc_geq(x2, x1)) {
                u256_sub(x2, x2, x1);
            } else {
                u64 t[4];
                u256_sub(t, x1, x2);
                u256_sub(x2, SC_N, t);
            }
        }
    }
    const u64* out = u256_iszero(u) ? x2 : x1;
    for (int i = 0; i < 4; i++) r.v[i] = out[i];
}

static inline int sc_window(const sc& a, int pos, int width) {
    int word = pos >> 6, shift = pos & 63;
    u64 w = a.v[word] >> shift;
    if (shift + width > 64 && word + 1 < 4)
        w |= a.v[word + 1] << (64 - shift);
    return (int)(w & ((1ULL << width) - 1));
}

// ---------------------------------------------------- points (Jacobian, a=0)

struct ge { fe X, Y, Z; bool inf; };

static const ge GE_INF = {{{0}}, {{0}}, {{0}}, true};

static void ge_double(ge& r, const ge& p) {
    if (p.inf) { r = p; return; }
    // y = 0 cannot happen on y^2 = x^3 + 7 (would need x^3 = -7, and
    // such points have y=0 only if on curve; handle defensively)
    if (fe_iszero(p.Y)) { r = GE_INF; return; }
    fe A, B, Cc, D, X3, Y3, Z3, t;
    fe_sq(A, p.X);                       // A = X^2
    fe_sq(B, p.Y);                       // B = Y^2
    fe_sq(Cc, B);                        // C = B^2
    fe_add(t, p.X, B);
    fe_sq(t, t);
    fe_sub(t, t, A);
    fe_sub(t, t, Cc);
    fe_add(D, t, t);                     // D = 2((X+B)^2 - A - C)
    fe M;
    fe_add(M, A, A);
    fe_add(M, M, A);                     // M = 3A (a = 0)
    fe_sq(X3, M);
    fe_sub(X3, X3, D);
    fe_sub(X3, X3, D);                   // X3 = M^2 - 2D
    fe c8;
    fe_add(c8, Cc, Cc);
    fe_add(c8, c8, c8);
    fe_add(c8, c8, c8);                  // 8C
    fe_sub(Y3, D, X3);
    fe_mul(Y3, M, Y3);
    fe_sub(Y3, Y3, c8);                  // Y3 = M(D - X3) - 8C
    fe_mul(Z3, p.Y, p.Z);
    fe_add(Z3, Z3, Z3);                  // Z3 = 2YZ
    r.X = X3; r.Y = Y3; r.Z = Z3; r.inf = false;
}

static void ge_add(ge& r, const ge& p, const ge& q) {
    if (p.inf) { r = q; return; }
    if (q.inf) { r = p; return; }
    fe Z1Z1, Z2Z2, U1, U2, S1, S2, H, Rr, t;
    fe_sq(Z1Z1, p.Z);
    fe_sq(Z2Z2, q.Z);
    fe_mul(U1, p.X, Z2Z2);
    fe_mul(U2, q.X, Z1Z1);
    fe_mul(S1, p.Y, q.Z);
    fe_mul(S1, S1, Z2Z2);
    fe_mul(S2, q.Y, p.Z);
    fe_mul(S2, S2, Z1Z1);
    fe_sub(H, U2, U1);
    fe_sub(Rr, S2, S1);
    if (fe_iszero(H)) {
        if (fe_iszero(Rr)) { ge_double(r, p); return; }
        r = GE_INF;                      // P + (-P)
        return;
    }
    fe HH, HHH, V, X3, Y3, Z3;
    fe_sq(HH, H);
    fe_mul(HHH, HH, H);
    fe_mul(V, U1, HH);
    fe_sq(X3, Rr);
    fe_sub(X3, X3, HHH);
    fe_sub(X3, X3, V);
    fe_sub(X3, X3, V);                   // X3 = R^2 - HHH - 2V
    fe_sub(t, V, X3);
    fe_mul(Y3, Rr, t);
    fe_mul(t, S1, HHH);
    fe_sub(Y3, Y3, t);                   // Y3 = R(V - X3) - S1*HHH
    fe_mul(Z3, p.Z, q.Z);
    fe_mul(Z3, Z3, H);                   // Z3 = Z1 Z2 H
    r.X = X3; r.Y = Y3; r.Z = Z3; r.inf = false;
}

static bool ge_decompress(ge& r, const u8 pub[33]) {
    if (pub[0] != 0x02 && pub[0] != 0x03) return false;
    fe x, y2, y;
    // reject non-canonical x (>= p): round-trip the bytes
    fe_frombytes(x, pub + 1);
    u8 chk[32];
    fe_tobytes(chk, x);
    if (memcmp(chk, pub + 1, 32) != 0) return false;
    fe_sq(y2, x);
    fe_mul(y2, y2, x);
    fe_add(y2, y2, FE_SEVEN);            // y^2 = x^3 + 7
    if (!fe_sqrt(y, y2)) return false;
    if (fe_isodd(y) != (pub[0] == 0x03)) {
        fe zero = {{0, 0, 0, 0, 0}};
        fe_sub(y, zero, y);
    }
    r.X = x; r.Y = y;
    r.Z.v[0] = 1; r.Z.v[1] = r.Z.v[2] = r.Z.v[3] = r.Z.v[4] = 0;
    r.inf = false;
    return true;
}

// ------------------------------------------------------------- verification

// 4-bit base-point window, built once at library load (dlopen runs
// initializers single-threaded, so no init race across ctypes calls)
static ge G_TAB[16];
static const bool _gtab_ready = []() {
    G_TAB[0] = GE_INF;
    G_TAB[1].X = GX;
    G_TAB[1].Y = GY;
    G_TAB[1].Z = {{1, 0, 0, 0, 0}};
    G_TAB[1].inf = false;
    for (int i = 2; i < 16; i++) ge_add(G_TAB[i], G_TAB[i - 1], G_TAB[1]);
    return true;
}();

extern "C" {

// 1 = valid, 0 = invalid.  pub: 33-byte compressed SEC1; sig: r||s
// big-endian, low-s enforced; e = SHA-256(msg) mod n.
int secp256k1_verify(const u8* pub, const u8* sig, const u8* msg,
                     u64 msg_len) {
    sc r_s, s_s;
    if (!sc_from_bytes_checked(r_s, sig)) return 0;
    if (!sc_from_bytes_checked(s_s, sig + 32)) return 0;
    if (sc_geq(s_s.v, SC_HALF_N) && !(s_s.v[0] == SC_HALF_N[0]
        && s_s.v[1] == SC_HALF_N[1] && s_s.v[2] == SC_HALF_N[2]
        && s_s.v[3] == SC_HALF_N[3])) {
        // s > n/2: reject malleable signatures (matches the Python
        // seam's low-s rule; s == n/2 itself is allowed)
        return 0;
    }
    ge Q;
    if (!ge_decompress(Q, pub)) return 0;

    u8 h[32];
    sha256(msg, msg_len, h);
    sc e, w, u1, u2;
    sc_from_hash(e, h);
    sc_invert(w, s_s);
    sc_mul(u1, e, w);
    sc_mul(u2, r_s, w);

    // Shamir joint ladder: 4-bit windows over u1 (static G table) and
    // u2 (per-verify Q table)
    ge qt[16];
    qt[0] = GE_INF;
    qt[1] = Q;
    for (int i = 2; i < 16; i++) ge_add(qt[i], qt[i - 1], Q);

    ge acc = GE_INF;
    for (int wdx = 63; wdx >= 0; wdx--) {
        for (int k = 0; k < 4; k++) ge_double(acc, acc);
        int d1 = sc_window(u1, 4 * wdx, 4);
        if (d1) ge_add(acc, acc, G_TAB[d1]);
        int d2 = sc_window(u2, 4 * wdx, 4);
        if (d2) ge_add(acc, acc, qt[d2]);
    }
    if (acc.inf) return 0;

    // R.x mod n == r  (affine x = X / Z^2)
    fe zinv, zinv2, xa;
    fe_invert(zinv, acc.Z);
    fe_sq(zinv2, zinv);
    fe_mul(xa, acc.X, zinv2);
    u8 xb[32];
    fe_tobytes(xb, xa);
    sc xs;
    u64 xw[8] = {0};
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            xw[i] = (xw[i] << 8) | xb[(3 - i) * 8 + j];
    sc_reduce512(xs, xw);
    return (xs.v[0] == r_s.v[0] && xs.v[1] == r_s.v[1]
            && xs.v[2] == r_s.v[2] && xs.v[3] == r_s.v[3]) ? 1 : 0;
}

}  // extern "C"
