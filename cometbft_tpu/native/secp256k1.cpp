// Native secp256k1 ECDSA verification (reference seam:
// crypto/secp256k1/secp256k1.go, backed there by dcrd's C-accelerated
// library).  Original implementation, same design discipline as
// native/ed25519.cpp: radix-2^52 field limbs with unsigned __int128
// accumulation, Jacobian point arithmetic (a=0 short Weierstrass),
// Barrett scalar arithmetic mod n, and one Shamir joint ladder for
// u1*G + u2*Q.  Semantics match cometbft_tpu/crypto/secp256k1.py
// exactly: 33-byte compressed pubkeys, 64-byte r||s big-endian
// signatures, 1 <= r,s < n, LOW-S ONLY, e = SHA-256(msg) mod n,
// valid iff R != inf and R.x mod n == r.
//
// Exported C ABI (ctypes):
//   secp256k1_verify(pub33, sig64, msg, msg_len) -> 1/0

#include <cstdint>
#include <cstring>

typedef uint64_t u64;
typedef unsigned __int128 u128;
typedef uint8_t u8;

// ------------------------------------------------------------------ sha256
// Shared implementation (sha256_inline.h); thin shim keeps this file's
// call sites unchanged.

#include "sha256_inline.h"

static void sha256(const u8* msg, u64 len, u8 out[32]) {
    sha256i::oneshot(msg, len, out);
}

// ----------------------------------------------- field GF(2^256 - 2^32 - 977)
// Radix-2^52, 5 limbs.  2^260 == 16*C (mod p) with C = 2^32 + 977, so
// overflow limbs fold back with a single small multiply.

struct fe { u64 v[5]; };

static const u64 M52 = (1ULL << 52) - 1;
static const u64 C16 = 0x1000003D10ULL;     // 16 * (2^32 + 977)

static const fe FE_SEVEN = {{7, 0, 0, 0, 0}};
static const fe GX = {{0x2815b16f81798ULL, 0xdb2dce28d959fULL,
                       0xe870b07029bfcULL, 0xbbac55a06295cULL,
                       0x79be667ef9dcULL}};
static const fe GY = {{0x7d08ffb10d4b8ULL, 0x48a68554199c4ULL,
                       0xe1108a8fd17b4ULL, 0xc4655da4fbfc0ULL,
                       0x483ada7726a3ULL}};

static inline void fe_carry_weak(fe& r) {
    // bring limbs under ~2^52 (top limb may hold up to 2^48+eps after a
    // fold; 2^260 overflow recycles through C16/16 at limb 0)
    u64 c;
    c = r.v[4] >> 48;            // keep top limb at 48 bits so products
    r.v[4] &= (1ULL << 48) - 1;  // never reach the fold limit
    // c * 2^(4*52+48) = c * 2^256 == c * (2^32+977) = c * (C16 >> 4)
    u128 t = (u128)c * (C16 >> 4) + r.v[0];
    r.v[0] = (u64)t & M52;
    r.v[1] += (u64)(t >> 52);
    for (int i = 1; i < 4; i++) {
        c = r.v[i] >> 52;
        r.v[i] &= M52;
        r.v[i + 1] += c;
    }
}

static inline void fe_add(fe& r, const fe& a, const fe& b) {
    for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + b.v[i];
    fe_carry_weak(r);
}

// 4p as per-limb multiples (NOT normalized) for the subtraction bias:
// every limb is >= 2^53, strictly above the largest weakly-reduced
// operand limb (~2^52 + 2^41), so a.v[i] + BIAS4P[i] - b.v[i] can never
// underflow u64 (a 2p bias with a normalized low limb CAN underflow —
// it sat below 2^52 — and silently corrupted ~2^-19 of decompressions)
static const u64 BIAS4P[5] = {0x3ffffbfffff0bcULL, 0x3ffffffffffffcULL,
                              0x3ffffffffffffcULL, 0x3ffffffffffffcULL,
                              0x3fffffffffffcULL};

static inline void fe_sub(fe& r, const fe& a, const fe& b) {
    for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + BIAS4P[i] - b.v[i];
    fe_carry_weak(r);
}

static inline void _fold10(const u128 acc[10], fe& r) {
    // carry into 10 exact 52-bit limbs (a*b < 2^512 < 2^520, so the
    // chain terminates with no residue past limb 9), then fold: limb
    // (5+k) has weight 2^(260+52k) == 16C * 2^(52k).  lo[5+k]*C16 <=
    // 2^52 * 2^41 = 2^93, so t fits u128; the fold carry stays <= 2^41.
    u64 lo[10];
    u128 carry = 0;
    for (int i = 0; i < 10; i++) {
        carry += acc[i];
        lo[i] = (u64)carry & M52;
        carry >>= 52;
    }
    u64 res[6] = {lo[0], lo[1], lo[2], lo[3], lo[4], 0};
    u64 cc = 0;
    for (int k = 0; k < 5; k++) {
        u128 t = (u128)lo[5 + k] * C16 + res[k] + cc;
        res[k] = (u64)t & M52;
        cc = (u64)(t >> 52);
    }
    res[5] = cc;                          // weight 2^260 again, <= 2^41
    u128 t2 = (u128)res[5] * C16 + res[0];
    res[0] = (u64)t2 & M52;
    res[1] += (u64)(t2 >> 52);            // <= 2^30 extra: no overflow
    fe out = {{res[0], res[1], res[2], res[3], res[4]}};
    fe_carry_weak(out);
    r = out;
}

static void fe_mul(fe& r, const fe& a, const fe& b) {
    u128 acc[10] = {0};
    for (int i = 0; i < 5; i++)
        for (int j = 0; j < 5; j++)
            acc[i + j] += (u128)a.v[i] * b.v[j];
    _fold10(acc, r);
}

static inline void fe_sq(fe& r, const fe& a) {
    u64 a0 = a.v[0], a1 = a.v[1], a2 = a.v[2], a3 = a.v[3], a4 = a.v[4];
    u128 acc[10];
    acc[0] = (u128)a0 * a0;
    acc[1] = (u128)(2 * a0) * a1;
    acc[2] = (u128)(2 * a0) * a2 + (u128)a1 * a1;
    acc[3] = (u128)(2 * a0) * a3 + (u128)(2 * a1) * a2;
    acc[4] = (u128)(2 * a0) * a4 + (u128)(2 * a1) * a3 + (u128)a2 * a2;
    acc[5] = (u128)(2 * a1) * a4 + (u128)(2 * a2) * a3;
    acc[6] = (u128)(2 * a2) * a4 + (u128)a3 * a3;
    acc[7] = (u128)(2 * a3) * a4;
    acc[8] = (u128)a4 * a4;
    acc[9] = 0;
    _fold10(acc, r);
}

static void fe_frombytes(fe& r, const u8 s[32]) {
    // big-endian 32 bytes -> 5x52 limbs
    u64 w[4];
    for (int i = 0; i < 4; i++) {
        w[i] = 0;
        for (int j = 0; j < 8; j++)
            w[i] = (w[i] << 8) | s[8 * i + j];   // w[0] = most significant
    }
    u64 w0 = w[3], w1 = w[2], w2 = w[1], w3 = w[0];   // little-endian now
    r.v[0] = w0 & M52;
    r.v[1] = ((w0 >> 52) | (w1 << 12)) & M52;
    r.v[2] = ((w1 >> 40) | (w2 << 24)) & M52;
    r.v[3] = ((w2 >> 28) | (w3 << 36)) & M52;
    r.v[4] = w3 >> 16;
    fe_carry_weak(r);
}

static void fe_tobytes(u8 s[32], const fe& a) {
    fe t = a;
    fe_carry_weak(t);
    fe_carry_weak(t);
    // canonical: add C and check overflow of 2^256 (t >= p iff t + C
    // carries past bit 256, with C = 2^32 + 977)
    u64 c0 = C16 >> 4;
    u64 q = (t.v[0] + c0) >> 52;
    q = (t.v[1] + q) >> 52;
    q = (t.v[2] + q) >> 52;
    q = (t.v[3] + q) >> 52;
    q = (t.v[4] + q) >> 48;              // top limb holds 48 bits
    // if q: t -= p  (equivalently t = t + C, dropping bit 256)
    if (q) {
        u128 tt = (u128)t.v[0] + c0;
        t.v[0] = (u64)tt & M52;
        u64 cc = (u64)(tt >> 52);
        for (int i = 1; i < 5; i++) {
            u64 s2 = t.v[i] + cc;
            cc = s2 >> 52;
            t.v[i] = s2 & M52;
        }
        t.v[4] &= (1ULL << 48) - 1;      // drop 2^256
    }
    u64 w0 = t.v[0] | (t.v[1] << 52);
    u64 w1 = (t.v[1] >> 12) | (t.v[2] << 40);
    u64 w2 = (t.v[2] >> 24) | (t.v[3] << 28);
    u64 w3 = (t.v[3] >> 36) | (t.v[4] << 16);
    u64 w[4] = {w3, w2, w1, w0};         // big-endian order
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            s[8 * i + j] = (u8)(w[i] >> (56 - 8 * j));
}

static bool fe_iszero(const fe& a) {
    u8 b[32];
    fe_tobytes(b, a);
    u8 acc = 0;
    for (int i = 0; i < 32; i++) acc |= b[i];
    return acc == 0;
}

static bool fe_equal(const fe& a, const fe& b) {
    fe d;
    fe_sub(d, a, b);
    return fe_iszero(d);
}

static bool fe_isodd(const fe& a) {
    u8 b[32];
    fe_tobytes(b, a);
    return b[31] & 1;
}

static inline void fe_sqn(fe& r, const fe& a, int n) {
    fe_sq(r, a);
    for (int i = 1; i < n; i++) fe_sq(r, r);
}

// x^(2^223 - 1): the shared prefix of both fixed exponents —
// p - 2      = (2^223 - 1)*2^33 + 0xFFFFFC2D   (33-bit tail)   and
// (p + 1)/4  = (2^223 - 1)*2^31 + 0x3FFFFF0C   (31-bit tail)
// (both identities follow from p = 2^256 - 2^32 - 977: the tails are
// 2^33 - 2^32 - 979 and 2^31 - 2^30 - 244).  The 2^k-1 ladder costs
// ~222 sq + 12 mul vs the generic bit-scan's ~250 mul.
static void fe_chain223(fe& r, const fe& x) {
    fe x2, x4, x8, x16, x32, x64, x128, t;
    fe_sq(t, x);
    fe_mul(x2, t, x);                    // 2^2 - 1
    fe_sqn(t, x2, 2);
    fe_mul(x4, t, x2);                   // 2^4 - 1
    fe_sqn(t, x4, 4);
    fe_mul(x8, t, x4);                   // 2^8 - 1
    fe_sqn(t, x8, 8);
    fe_mul(x16, t, x8);                  // 2^16 - 1
    fe_sqn(t, x16, 16);
    fe_mul(x32, t, x16);                 // 2^32 - 1
    fe_sqn(t, x32, 32);
    fe_mul(x64, t, x32);                 // 2^64 - 1
    fe_sqn(t, x64, 64);
    fe_mul(x128, t, x64);                // 2^128 - 1
    fe_sqn(t, x128, 64);
    fe_mul(t, t, x64);                   // 2^192 - 1
    fe_sqn(t, t, 16);
    fe_mul(t, t, x16);                   // 2^208 - 1
    fe_sqn(t, t, 8);
    fe_mul(t, t, x8);                    // 2^216 - 1
    fe_sqn(t, t, 4);
    fe_mul(t, t, x4);                    // 2^220 - 1
    fe_sqn(t, t, 2);
    fe_mul(t, t, x2);                    // 2^222 - 1
    fe_sq(t, t);
    fe_mul(r, t, x);                     // 2^223 - 1
}

// square-and-multiply over a short tail (the low 33/31 bits of the
// fixed exponents after the shared 2^223-1 prefix)
static void fe_pow_tail(fe& r, const fe& prefix, const fe& x,
                        u64 tail, int bits) {
    // u64 tail: bits can be 33, and (u32 >> 32) is undefined behavior
    // (x86 shifts count mod 32 — exactly the bug this signature avoids)
    fe acc = prefix;
    for (int i = bits - 1; i >= 0; i--) {
        fe_sq(acc, acc);
        if ((tail >> i) & 1) fe_mul(acc, acc, x);
    }
    r = acc;
}

static void fe_invert(fe& r, const fe& a) {
    // a^(p-2) = a^((2^223-1)*2^33 + 0xFFFFFC2D)
    fe pre;
    fe_chain223(pre, a);
    fe_pow_tail(r, pre, a, 0xFFFFFC2Du, 33);
}

static bool fe_sqrt(fe& r, const fe& a) {
    // p == 3 (mod 4): candidate = a^((p+1)/4) =
    // a^((2^223-1)*2^31 + 0x3FFFFF0C); verify square
    fe pre, cand, chk;
    fe_chain223(pre, a);
    fe_pow_tail(cand, pre, a, 0x3FFFFF0Cu, 31);
    fe_sq(chk, cand);
    if (!fe_equal(chk, a)) return false;
    r = cand;
    return true;
}

// -------------------------------------------------------------- scalars mod n

static const u64 SC_N[4] = {0xbfd25e8cd0364141ULL, 0xbaaedce6af48a03bULL,
                            0xfffffffffffffffeULL, 0xffffffffffffffffULL};
static const u64 SC_HALF_N[4] = {0xdfe92f46681b20a0ULL,
                                 0x5d576e7357a4501dULL,
                                 0xffffffffffffffffULL,
                                 0x7fffffffffffffffULL};
static const u64 SC_MU[5] = {0x402da1732fc9bec0ULL, 0x4551231950b75fc4ULL,
                             0x1ULL, 0x0ULL, 0x1ULL};

struct sc { u64 v[4]; };

static inline int sc_geq(const u64 a[4], const u64 b[4]) {
    for (int i = 3; i >= 0; i--) {
        if (a[i] > b[i]) return 1;
        if (a[i] < b[i]) return 0;
    }
    return 1;
}

static inline bool sc_iszero(const sc& a) {
    return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

static void sc_reduce512(sc& r, const u64 x[8]) {
    u64 prod[13] = {0};
    for (int i = 0; i < 8; i++) {
        u64 carry = 0;
        for (int j = 0; j < 5; j++) {
            u128 t = (u128)x[i] * SC_MU[j] + prod[i + j] + carry;
            prod[i + j] = (u64)t;
            carry = (u64)(t >> 64);
        }
        prod[i + 5] += carry;
    }
    u64 q[5];
    for (int i = 0; i < 5; i++) q[i] = prod[8 + i];
    u64 ql[8] = {0};
    for (int i = 0; i < 5; i++) {
        u64 carry = 0;
        for (int j = 0; j < 4 && i + j < 8; j++) {
            u128 t = (u128)q[i] * SC_N[j] + ql[i + j] + carry;
            ql[i + j] = (u64)t;
            carry = (u64)(t >> 64);
        }
        if (i + 4 < 8) ql[i + 4] += carry;
    }
    u64 rem[8];
    u64 borrow = 0;
    for (int i = 0; i < 8; i++) {
        u64 bi = ql[i] + borrow;
        borrow = (bi < borrow) ? 1 : (x[i] < bi ? 1 : 0);
        rem[i] = x[i] - bi;
    }
    for (int k = 0; k < 3; k++)
        if (rem[4] | rem[5] | rem[6] | rem[7] || sc_geq(rem, SC_N)) {
            u64 borrow2 = 0;
            for (int i = 0; i < 8; i++) {
                u64 bi = (i < 4 ? SC_N[i] : 0) + borrow2;
                borrow2 = (bi < borrow2) ? 1 : (rem[i] < bi ? 1 : 0);
                rem[i] = rem[i] - bi;
            }
        }
    for (int i = 0; i < 4; i++) r.v[i] = rem[i];
}

static void sc_mul(sc& r, const sc& a, const sc& b) {
    u64 prod[8] = {0};
    for (int i = 0; i < 4; i++) {
        u64 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 t = (u128)a.v[i] * b.v[j] + prod[i + j] + carry;
            prod[i + j] = (u64)t;
            carry = (u64)(t >> 64);
        }
        prod[i + 4] = carry;
    }
    sc_reduce512(r, prod);
}

// load 32 big-endian bytes; true if the value is in [1, n)
static bool sc_from_bytes_checked(sc& r, const u8 b[32]) {
    for (int i = 0; i < 4; i++) {
        r.v[i] = 0;
        for (int j = 0; j < 8; j++)
            r.v[i] = (r.v[i] << 8) | b[(3 - i) * 8 + j];
    }
    return !sc_iszero(r) && !sc_geq(r.v, SC_N);
}

static void sc_from_hash(sc& r, const u8 b[32]) {
    u64 x[8] = {0};
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            x[i] = (x[i] << 8) | b[(3 - i) * 8 + j];
    sc_reduce512(r, x);
}

// 256-bit helpers for the inversion (variable-time is fine: ECDSA
// verification handles only public values)
static inline bool u256_iszero(const u64 a[4]) {
    return (a[0] | a[1] | a[2] | a[3]) == 0;
}

static inline bool u256_iseven(const u64 a[4]) { return !(a[0] & 1); }

static inline void u256_rshift1(u64 a[4]) {
    a[0] = (a[0] >> 1) | (a[1] << 63);
    a[1] = (a[1] >> 1) | (a[2] << 63);
    a[2] = (a[2] >> 1) | (a[3] << 63);
    a[3] >>= 1;
}

static inline u64 u256_add(u64 r[4], const u64 a[4], const u64 b[4]) {
    u128 c = 0;
    for (int i = 0; i < 4; i++) {
        c += (u128)a[i] + b[i];
        r[i] = (u64)c;
        c >>= 64;
    }
    return (u64)c;
}

static inline void u256_sub(u64 r[4], const u64 a[4], const u64 b[4]) {
    u64 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u64 bi = b[i] + borrow;
        borrow = (bi < borrow) ? 1 : (a[i] < bi ? 1 : 0);
        r[i] = a[i] - bi;
    }
}

static void sc_invert(sc& r, const sc& a) {
    // binary extended gcd mod n (~15x faster than the Fermat ladder of
    // Barrett multiplications; gcd(a, n) == 1 since n is prime)
    u64 u[4] = {a.v[0], a.v[1], a.v[2], a.v[3]};
    u64 v[4] = {SC_N[0], SC_N[1], SC_N[2], SC_N[3]};
    u64 x1[4] = {1, 0, 0, 0};
    u64 x2[4] = {0, 0, 0, 0};
    while (!u256_iszero(u) && !u256_iszero(v)) {
        while (u256_iseven(u)) {
            u256_rshift1(u);
            if (u256_iseven(x1)) {
                u256_rshift1(x1);
            } else {
                u64 carry = u256_add(x1, x1, SC_N);
                u256_rshift1(x1);
                x1[3] |= carry << 63;
            }
        }
        while (u256_iseven(v)) {
            u256_rshift1(v);
            if (u256_iseven(x2)) {
                u256_rshift1(x2);
            } else {
                u64 carry = u256_add(x2, x2, SC_N);
                u256_rshift1(x2);
                x2[3] |= carry << 63;
            }
        }
        if (sc_geq(u, v)) {
            u256_sub(u, u, v);
            // x1 = (x1 - x2) mod n
            if (sc_geq(x1, x2)) {
                u256_sub(x1, x1, x2);
            } else {
                u64 t[4];
                u256_sub(t, x2, x1);
                u256_sub(x1, SC_N, t);
            }
        } else {
            u256_sub(v, v, u);
            if (sc_geq(x2, x1)) {
                u256_sub(x2, x2, x1);
            } else {
                u64 t[4];
                u256_sub(t, x1, x2);
                u256_sub(x2, SC_N, t);
            }
        }
    }
    const u64* out = u256_iszero(u) ? x2 : x1;
    for (int i = 0; i < 4; i++) r.v[i] = out[i];
}

static inline int sc_window(const sc& a, int pos, int width) {
    int word = pos >> 6, shift = pos & 63;
    u64 w = a.v[word] >> shift;
    if (shift + width > 64 && word + 1 < 4)
        w |= a.v[word + 1] << (64 - shift);
    return (int)(w & ((1ULL << width) - 1));
}

// ------------------------------------------------- GLV endomorphism split
// secp256k1 has the cube-root endomorphism psi(x, y) = (beta*x, y) with
// psi(P) = [lambda]P, so k*P = k1*P + k2*psi(P) with |k1|, |k2| ~ 2^128
// — the joint ladder then needs HALF the doublings.  Every constant is
// VERIFIED at library init (beta^2+beta+1 = 0 mod p, lambda^2+lambda+1
// = 0 mod n, the lattice relations, and psi(G) == lambda*G against the
// plain ladder), and every per-call decomposition is re-verified
// algebraically (k1 + lambda*k2 == k mod n, magnitudes < 2^130); any
// mismatch falls back to the plain 2-table ladder, so a wrong constant
// can only cost speed, never correctness.

static const u8 GLV_BETA_BYTES[32] = {
    0x7a, 0xe9, 0x6a, 0x2b, 0x65, 0x7c, 0x07, 0x10,
    0x6e, 0x64, 0x47, 0x9e, 0xac, 0x34, 0x34, 0xe9,
    0x9c, 0xf0, 0x49, 0x75, 0x12, 0xf5, 0x89, 0x95,
    0xc1, 0x39, 0x6c, 0x28, 0x71, 0x95, 0x01, 0xee};
static const u8 GLV_LAMBDA_BYTES[32] = {
    0x53, 0x63, 0xad, 0x4c, 0xc0, 0x5c, 0x30, 0xe0,
    0xa5, 0x26, 0x1c, 0x02, 0x88, 0x12, 0x64, 0x5a,
    0x12, 0x2e, 0x22, 0xea, 0x20, 0x81, 0x66, 0x78,
    0xdf, 0x02, 0x96, 0x7c, 0x1b, 0x23, 0xbd, 0x72};
// lattice basis (a1 + b1*lambda == 0, a2 + b2*lambda == 0 mod n), with
// b1 stored negated: b1 = -B1N, b2 = a1
static const u64 GLV_A1[2] = {0xe86c90e49284eb15ULL, 0x3086d221a7d46bcdULL};
static const u64 GLV_B1N[2] = {0x6f547fa90abfe4c3ULL, 0xe4437ed6010e8828ULL};
static const u64 GLV_A2[3] = {0x57c1108d9d44cfd8ULL, 0x14ca50f7a8e2f3f6ULL,
                              0x1ULL};

static fe GLV_BETA;
static sc GLV_LAMBDA;
static u64 GLV_G1[4], GLV_G2[4];     // round(2^384 * b2 / n), ... * (-b1)
static bool GLV_OK = false;

// 512-bit / 256-bit long division (init-only; bitwise, trivially right)
static void u512_divmod_n(const u64 num[8], u64 quot[8]) {
    u64 rem[4] = {0, 0, 0, 0};
    for (int i = 0; i < 8; i++) quot[i] = 0;
    for (int bit = 511; bit >= 0; bit--) {
        // rem = rem*2 + bit_i  (rem < n < 2^256 so the shift can't drop)
        u64 carry = 0;
        for (int i = 0; i < 4; i++) {
            u64 nx = (rem[i] << 1) | carry;
            carry = rem[i] >> 63;
            rem[i] = nx;
        }
        rem[0] |= (num[bit >> 6] >> (bit & 63)) & 1;
        if (carry || sc_geq(rem, SC_N)) {
            u64 borrow = 0;
            for (int i = 0; i < 4; i++) {
                u64 bi = SC_N[i] + borrow;
                borrow = (bi < borrow) ? 1 : (rem[i] < bi ? 1 : 0);
                rem[i] = rem[i] - bi;
            }
        } else {
            continue;
        }
        quot[bit >> 6] |= 1ULL << (bit & 63);
    }
}

// (a[na] * b[nb]) into out[na+nb] (schoolbook, u128 carries)
static void limb_mul(const u64* a, int na, const u64* b, int nb, u64* out) {
    for (int i = 0; i < na + nb; i++) out[i] = 0;
    for (int i = 0; i < na; i++) {
        u64 carry = 0;
        for (int j = 0; j < nb; j++) {
            u128 t = (u128)a[i] * b[j] + out[i + j] + carry;
            out[i + j] = (u64)t;
            carry = (u64)(t >> 64);
        }
        out[i + nb] += carry;
    }
}

// c = (k * g + 2^383) >> 384 — the rounded GLV quotient.  c fits 2
// limbs because c ~ m*k/n with m <= max(|b1|, b2) < 2^128 and k < n,
// so c < 2^128 (g itself is ~2^253 for b2 and ~2^255.8 for -b1)
static void glv_round_mul(const sc& k, const u64 g[4], u64 c[2]) {
    u64 prod[8];
    limb_mul(k.v, 4, g, 4, prod);
    // add the rounding bit at position 383
    u128 t = (u128)prod[5] + (1ULL << 63);
    prod[5] = (u64)t;
    u64 carry = (u64)(t >> 64);
    for (int i = 6; i < 8 && carry; i++) {
        t = (u128)prod[i] + carry;
        prod[i] = (u64)t;
        carry = (u64)(t >> 64);
    }
    c[0] = prod[6];
    c[1] = prod[7];
}

// signed small scalar: magnitude (3 limbs, < 2^130) + sign
struct glv_half { u64 mag[3]; bool neg; };

// d (mod n, canonical) -> small signed form; false if |d| >= 2^130
static bool glv_small(const u64 d[4], glv_half& out) {
    if ((d[3] | (d[2] >> 2)) == 0) {            // d < 2^130
        out.mag[0] = d[0]; out.mag[1] = d[1]; out.mag[2] = d[2];
        out.neg = false;
        return true;
    }
    u64 nd[4];
    u256_sub(nd, SC_N, d);                      // n - d
    if ((nd[3] | (nd[2] >> 2)) == 0) {
        out.mag[0] = nd[0]; out.mag[1] = nd[1]; out.mag[2] = nd[2];
        out.neg = true;
        return true;
    }
    return false;
}

static inline int glv_window(const glv_half& h, int pos) {
    // pos is always a multiple of 4 (the ladder steps whole windows),
    // so a 4-bit window can never straddle a 64-bit limb boundary
    return (int)((h.mag[pos >> 6] >> (pos & 63)) & 0xF);
}

// k -> k1 + lambda*k2 (mod n), both halves small; false -> caller uses
// the plain ladder.  Includes the full algebraic re-verification.
static bool glv_decompose(const sc& k, glv_half& k1, glv_half& k2) {
    u64 c1[2], c2[2];
    glv_round_mul(k, GLV_G1, c1);
    glv_round_mul(k, GLV_G2, c2);
    // s = c1*a1 + c2*a2  (< 2^255 < n: no reduction needed)
    u64 s1[4], s2[5], s[5] = {0};
    limb_mul(c1, 2, GLV_A1, 2, s1);
    limb_mul(c2, 2, GLV_A2, 3, s2);
    u64 carry = 0;
    for (int i = 0; i < 4; i++) {
        u128 t = (u128)s1[i] + s2[i] + carry;
        s[i] = (u64)t;
        carry = (u64)(t >> 64);
    }
    if (carry + s2[4] != 0) return false;       // defensive: can't happen
    // d1 = (k - s) mod n
    u64 d1[4];
    if (sc_geq(k.v, s)) {
        u256_sub(d1, k.v, s);
    } else {
        u64 t[4];
        u256_sub(t, s, k.v);
        u256_sub(d1, SC_N, t);
    }
    if (!glv_small(d1, k1)) return false;
    // d2 = (c1*b1n - c2*b2) mod n   (k2 = -(c1*b1 + c2*b2) = c1*b1n - c2*b2)
    u64 t1[4], t2[4];
    limb_mul(c1, 2, GLV_B1N, 2, t1);
    limb_mul(c2, 2, GLV_A1, 2, t2);             // b2 == a1
    u64 d2[4];
    if (sc_geq(t1, t2)) {
        u256_sub(d2, t1, t2);
        if (sc_geq(d2, SC_N)) u256_sub(d2, d2, SC_N);
    } else {
        u64 t[4];
        u256_sub(t, t2, t1);
        if (sc_geq(t, SC_N)) u256_sub(t, t, SC_N);
        u256_sub(d2, SC_N, t);
    }
    if (!glv_small(d2, k2)) return false;
    // re-verify: k1 + lambda*k2 == k (mod n)
    sc m2 = {{k2.mag[0], k2.mag[1], k2.mag[2], 0}};
    sc lk2;
    sc_mul(lk2, GLV_LAMBDA, m2);
    u64 acc[4] = {k1.mag[0], k1.mag[1], k1.mag[2], 0};
    if (k1.neg) {
        u64 t[4];
        u256_sub(t, SC_N, acc);
        for (int i = 0; i < 4; i++) acc[i] = t[i];
    }
    u64 l[4] = {lk2.v[0], lk2.v[1], lk2.v[2], lk2.v[3]};
    if (k2.neg) {
        u64 t[4];
        u256_sub(t, SC_N, l);
        for (int i = 0; i < 4; i++) l[i] = t[i];
    }
    u64 sum[4];
    u64 cadd = u256_add(sum, acc, l);
    if (cadd || sc_geq(sum, SC_N)) u256_sub(sum, sum, SC_N);
    return sum[0] == k.v[0] && sum[1] == k.v[1] &&
           sum[2] == k.v[2] && sum[3] == k.v[3];
}

// ---------------------------------------------------- points (Jacobian, a=0)

struct ge { fe X, Y, Z; bool inf; };

static const ge GE_INF = {{{0}}, {{0}}, {{0}}, true};

static void ge_double(ge& r, const ge& p) {
    if (p.inf) { r = p; return; }
    // no y == 0 check: a y = 0 point would have order 2, and
    // secp256k1's group order n is an odd prime (cofactor 1) — no
    // 2-torsion exists, so on-curve inputs can never hit it (and every
    // ladder input is decompression-validated on-curve)
    fe A, B, Cc, D, X3, Y3, Z3, t;
    fe_sq(A, p.X);                       // A = X^2
    fe_sq(B, p.Y);                       // B = Y^2
    fe_sq(Cc, B);                        // C = B^2
    fe_add(t, p.X, B);
    fe_sq(t, t);
    fe_sub(t, t, A);
    fe_sub(t, t, Cc);
    fe_add(D, t, t);                     // D = 2((X+B)^2 - A - C)
    fe M;
    fe_add(M, A, A);
    fe_add(M, M, A);                     // M = 3A (a = 0)
    fe_sq(X3, M);
    fe_sub(X3, X3, D);
    fe_sub(X3, X3, D);                   // X3 = M^2 - 2D
    fe c8;
    fe_add(c8, Cc, Cc);
    fe_add(c8, c8, c8);
    fe_add(c8, c8, c8);                  // 8C
    fe_sub(Y3, D, X3);
    fe_mul(Y3, M, Y3);
    fe_sub(Y3, Y3, c8);                  // Y3 = M(D - X3) - 8C
    fe_mul(Z3, p.Y, p.Z);
    fe_add(Z3, Z3, Z3);                  // Z3 = 2YZ
    r.X = X3; r.Y = Y3; r.Z = Z3; r.inf = false;
}

static void ge_add(ge& r, const ge& p, const ge& q) {
    if (p.inf) { r = q; return; }
    if (q.inf) { r = p; return; }
    fe Z1Z1, Z2Z2, U1, U2, S1, S2, H, Rr, t;
    fe_sq(Z1Z1, p.Z);
    fe_sq(Z2Z2, q.Z);
    fe_mul(U1, p.X, Z2Z2);
    fe_mul(U2, q.X, Z1Z1);
    fe_mul(S1, p.Y, q.Z);
    fe_mul(S1, S1, Z2Z2);
    fe_mul(S2, q.Y, p.Z);
    fe_mul(S2, S2, Z1Z1);
    fe_sub(H, U2, U1);
    fe_sub(Rr, S2, S1);
    if (fe_iszero(H)) {
        if (fe_iszero(Rr)) { ge_double(r, p); return; }
        r = GE_INF;                      // P + (-P)
        return;
    }
    fe HH, HHH, V, X3, Y3, Z3;
    fe_sq(HH, H);
    fe_mul(HHH, HH, H);
    fe_mul(V, U1, HH);
    fe_sq(X3, Rr);
    fe_sub(X3, X3, HHH);
    fe_sub(X3, X3, V);
    fe_sub(X3, X3, V);                   // X3 = R^2 - HHH - 2V
    fe_sub(t, V, X3);
    fe_mul(Y3, Rr, t);
    fe_mul(t, S1, HHH);
    fe_sub(Y3, Y3, t);                   // Y3 = R(V - X3) - S1*HHH
    fe_mul(Z3, p.Z, q.Z);
    fe_mul(Z3, Z3, H);                   // Z3 = Z1 Z2 H
    r.X = X3; r.Y = Y3; r.Z = Z3; r.inf = false;
}

static bool ge_decompress(ge& r, const u8 pub[33]) {
    if (pub[0] != 0x02 && pub[0] != 0x03) return false;
    fe x, y2, y;
    // reject non-canonical x (>= p): round-trip the bytes
    fe_frombytes(x, pub + 1);
    u8 chk[32];
    fe_tobytes(chk, x);
    if (memcmp(chk, pub + 1, 32) != 0) return false;
    fe_sq(y2, x);
    fe_mul(y2, y2, x);
    fe_add(y2, y2, FE_SEVEN);            // y^2 = x^3 + 7
    if (!fe_sqrt(y, y2)) return false;
    if (fe_isodd(y) != (pub[0] == 0x03)) {
        fe zero = {{0, 0, 0, 0, 0}};
        fe_sub(y, zero, y);
    }
    r.X = x; r.Y = y;
    r.Z.v[0] = 1; r.Z.v[1] = r.Z.v[2] = r.Z.v[3] = r.Z.v[4] = 0;
    r.inf = false;
    return true;
}

// mixed addition r = p + q with AFFINE q (madd-2007-bl shape): 8 fe_mul
// + 3 fe_sq vs general ge_add's 12 + 4 — the ladder's table entries are
// pre-normalized to affine exactly so every window add is mixed
struct geaff { fe x, y; bool inf; };

static void ge_madd(ge& r, const ge& p, const geaff& q) {
    if (q.inf) { r = p; return; }
    if (p.inf) {
        r.X = q.x; r.Y = q.y;
        r.Z = {{1, 0, 0, 0, 0}};
        r.inf = false;
        return;
    }
    fe Z1Z1, U2, S2, H, Rr, t;
    fe_sq(Z1Z1, p.Z);
    fe_mul(U2, q.x, Z1Z1);
    fe_mul(S2, q.y, p.Z);
    fe_mul(S2, S2, Z1Z1);
    fe_sub(H, U2, p.X);
    fe_sub(Rr, S2, p.Y);
    if (fe_iszero(H)) {
        if (fe_iszero(Rr)) { ge_double(r, p); return; }
        r = GE_INF;
        return;
    }
    fe HH, HHH, V, X3, Y3, Z3;
    fe_sq(HH, H);
    fe_mul(HHH, HH, H);
    fe_mul(V, p.X, HH);
    fe_sq(X3, Rr);
    fe_sub(X3, X3, HHH);
    fe_sub(X3, X3, V);
    fe_sub(X3, X3, V);
    fe_sub(t, V, X3);
    fe_mul(Y3, Rr, t);
    fe_mul(t, p.Y, HHH);
    fe_sub(Y3, Y3, t);
    fe_mul(Z3, p.Z, H);
    r.X = X3; r.Y = Y3; r.Z = Z3; r.inf = false;
}

// batch-normalize Jacobian points to affine: ONE field inversion for the
// whole table (Montgomery trick), then x = X/Z^2, y = Y/Z^3 per entry
static void ge_batch_to_affine(geaff* out, const ge* in, int n) {
    // n <= 16 at every call site (window tables); stack storage keeps
    // the per-verification hot path allocation-free
    fe partial[16];
    fe acc = {{1, 0, 0, 0, 0}};
    for (int i = 0; i < n; i++) {
        partial[i] = acc;
        if (!in[i].inf) fe_mul(acc, acc, in[i].Z);
    }
    fe inv;
    fe_invert(inv, acc);
    for (int i = n - 1; i >= 0; i--) {
        out[i].inf = in[i].inf;
        if (in[i].inf) continue;
        fe zi, zi2;
        fe_mul(zi, inv, partial[i]);         // 1 / Z_i
        fe_mul(inv, inv, in[i].Z);           // drop Z_i from the running inv
        fe_sq(zi2, zi);
        fe_mul(out[i].x, in[i].X, zi2);
        fe_mul(out[i].y, in[i].Y, zi2);
        fe_mul(out[i].y, out[i].y, zi);
    }
}

// ------------------------------------------------------------- verification

// 4-bit base-point window in AFFINE form, built once at library load
// (dlopen runs initializers single-threaded, so no init race across
// ctypes calls); affine entries make every ladder add a mixed add.
// PSI_G_TAB is the endomorphism image (beta*x, y) of each entry —
// psi(i*G) = i*psi(G), so it needs only one field mul per entry.
static geaff G_TAB[16];
static geaff PSI_G_TAB[16];

static void ge_scalarmul_plain(ge& r, const sc& k, const ge& p) {
    // simple 4-bit ladder (init-time psi(G) check only)
    ge tab[16];
    tab[0] = GE_INF;
    tab[1] = p;
    for (int i = 2; i < 16; i++) ge_add(tab[i], tab[i - 1], p);
    ge acc = GE_INF;
    for (int w = 63; w >= 0; w--) {
        for (int i = 0; i < 4; i++) ge_double(acc, acc);
        int d = sc_window(k, 4 * w, 4);
        if (d) ge_add(acc, acc, tab[d]);
    }
    r = acc;
}

static void psi_table(geaff* out, const geaff* in) {
    for (int i = 0; i < 16; i++) {
        out[i].inf = in[i].inf;
        if (in[i].inf) continue;
        fe_mul(out[i].x, GLV_BETA, in[i].x);
        out[i].y = in[i].y;
    }
}

static bool glv_init() {
    fe_frombytes(GLV_BETA, GLV_BETA_BYTES);
    for (int i = 0; i < 4; i++) {
        GLV_LAMBDA.v[i] = 0;
        for (int j = 0; j < 8; j++)
            GLV_LAMBDA.v[i] = (GLV_LAMBDA.v[i] << 8)
                | GLV_LAMBDA_BYTES[(3 - i) * 8 + j];
    }
    // beta^2 + beta + 1 == 0 (mod p)
    fe t, one = {{1, 0, 0, 0, 0}};
    fe_sq(t, GLV_BETA);
    fe_add(t, t, GLV_BETA);
    fe_add(t, t, one);
    if (!fe_iszero(t)) return false;
    // lambda^2 + lambda + 1 == 0 (mod n)
    sc lt;
    sc_mul(lt, GLV_LAMBDA, GLV_LAMBDA);
    u64 acc[4];
    u64 c = u256_add(acc, lt.v, GLV_LAMBDA.v);
    if (c || sc_geq(acc, SC_N)) u256_sub(acc, acc, SC_N);
    u64 onev[4] = {1, 0, 0, 0};
    c = u256_add(acc, acc, onev);
    if (c || sc_geq(acc, SC_N)) u256_sub(acc, acc, SC_N);
    if ((acc[0] | acc[1] | acc[2] | acc[3]) != 0) return false;
    // lattice relations: a1 == b1n * lambda, a2 == n - (a1 * lambda)
    // (a1 + b1*lambda == 0 with b1 = -b1n;  a2 + b2*lambda == 0, b2 = a1)
    sc b1n = {{GLV_B1N[0], GLV_B1N[1], 0, 0}};
    sc a1 = {{GLV_A1[0], GLV_A1[1], 0, 0}};
    sc chk;
    sc_mul(chk, b1n, GLV_LAMBDA);
    if (chk.v[0] != GLV_A1[0] || chk.v[1] != GLV_A1[1] ||
        chk.v[2] | chk.v[3]) return false;
    sc_mul(chk, a1, GLV_LAMBDA);
    u64 na2[4];
    u256_sub(na2, SC_N, chk.v);                  // -a1*lambda mod n
    if (na2[0] != GLV_A2[0] || na2[1] != GLV_A2[1] ||
        na2[2] != GLV_A2[2] || na2[3]) return false;
    // rounded quotients: G1 = round(2^384*b2/n) with b2 == a1, and
    // G2 = round(2^384*b1n/n) — computed as ((m << 384) + n/2) / n
    for (int which = 0; which < 2; which++) {
        const u64* m = which == 0 ? GLV_A1 : GLV_B1N;
        u64 nm[8] = {0};
        nm[6] = m[0];
        nm[7] = m[1];
        // += floor(n/2): 4-limb value (n odd -> n>>1)
        u64 half[4] = {(SC_N[0] >> 1) | (SC_N[1] << 63),
                       (SC_N[1] >> 1) | (SC_N[2] << 63),
                       (SC_N[2] >> 1) | (SC_N[3] << 63),
                       SC_N[3] >> 1};
        u64 carry = 0;
        for (int i = 0; i < 8; i++) {
            u128 tt = (u128)nm[i] + (i < 4 ? half[i] : 0) + carry;
            nm[i] = (u64)tt;
            carry = (u64)(tt >> 64);
        }
        u64 q[8];
        u512_divmod_n(nm, q);
        if (q[4] | q[5] | q[6] | q[7]) return false;     // g must fit 4 limbs
        for (int i = 0; i < 4; i++)
            (which == 0 ? GLV_G1 : GLV_G2)[i] = q[i];
    }
    // psi(G) == lambda * G — the one check the per-call verification
    // cannot cover (it would pass equally for lambda^2)
    ge lg;
    ge jg;
    jg.X = GX; jg.Y = GY; jg.Z = {{1, 0, 0, 0, 0}}; jg.inf = false;
    ge_scalarmul_plain(lg, GLV_LAMBDA, jg);
    fe zi, zi2, lx;
    fe_invert(zi, lg.Z);
    fe_sq(zi2, zi);
    fe_mul(lx, lg.X, zi2);
    fe px;
    fe_mul(px, GLV_BETA, GX);
    if (!fe_equal(lx, px)) return false;
    return true;
}

static const bool _gtab_ready = []() {
    ge jac[16];
    jac[0] = GE_INF;
    jac[1].X = GX;
    jac[1].Y = GY;
    jac[1].Z = {{1, 0, 0, 0, 0}};
    jac[1].inf = false;
    for (int i = 2; i < 16; i++) ge_add(jac[i], jac[i - 1], jac[1]);
    ge_batch_to_affine(G_TAB, jac, 16);
    GLV_OK = glv_init();
    if (GLV_OK) psi_table(PSI_G_TAB, G_TAB);
    return true;
}();

extern "C" {

// 1 = valid, 0 = invalid.  pub: 33-byte compressed SEC1; sig: r||s
// big-endian, low-s enforced; e = SHA-256(msg) mod n.
int secp256k1_verify(const u8* pub, const u8* sig, const u8* msg,
                     u64 msg_len) {
    sc r_s, s_s;
    if (!sc_from_bytes_checked(r_s, sig)) return 0;
    if (!sc_from_bytes_checked(s_s, sig + 32)) return 0;
    if (sc_geq(s_s.v, SC_HALF_N) && !(s_s.v[0] == SC_HALF_N[0]
        && s_s.v[1] == SC_HALF_N[1] && s_s.v[2] == SC_HALF_N[2]
        && s_s.v[3] == SC_HALF_N[3])) {
        // s > n/2: reject malleable signatures (matches the Python
        // seam's low-s rule; s == n/2 itself is allowed)
        return 0;
    }
    ge Q;
    if (!ge_decompress(Q, pub)) return 0;

    u8 h[32];
    sha256(msg, msg_len, h);
    sc e, w, u1, u2;
    sc_from_hash(e, h);
    sc_invert(w, s_s);
    sc_mul(u1, e, w);
    sc_mul(u2, r_s, w);

    // Shamir joint ladder over affine tables (every window add is a
    // mixed add, 8M+3S vs the general 12M+4S).  With a VERIFIED GLV
    // split the four ~130-bit halves share 33 window positions (132
    // doublings); otherwise the plain 2-table 64-window ladder runs.
    ge qtj[16];
    qtj[0] = GE_INF;
    qtj[1] = Q;
    for (int i = 2; i < 16; i++) ge_add(qtj[i], qtj[i - 1], Q);
    geaff qt[16];
    ge_batch_to_affine(qt, qtj, 16);

    ge acc = GE_INF;
    glv_half h1a, h1b, h2a, h2b;
    if (GLV_OK && glv_decompose(u1, h1a, h1b)
        && glv_decompose(u2, h2a, h2b)) {
        geaff psi_qt[16];
        psi_table(psi_qt, qt);
        const geaff* tabs[4] = {G_TAB, PSI_G_TAB, qt, psi_qt};
        const glv_half* halves[4] = {&h1a, &h1b, &h2a, &h2b};
        for (int wdx = 32; wdx >= 0; wdx--) {
            for (int k = 0; k < 4; k++) ge_double(acc, acc);
            for (int t = 0; t < 4; t++) {
                int d = glv_window(*halves[t], 4 * wdx);
                if (!d) continue;
                geaff e = tabs[t][d];
                if (halves[t]->neg) {
                    fe zero = {{0, 0, 0, 0, 0}};
                    fe_sub(e.y, zero, e.y);
                }
                ge_madd(acc, acc, e);
            }
        }
    } else {
        for (int wdx = 63; wdx >= 0; wdx--) {
            for (int k = 0; k < 4; k++) ge_double(acc, acc);
            int d1 = sc_window(u1, 4 * wdx, 4);
            if (d1) ge_madd(acc, acc, G_TAB[d1]);
            int d2 = sc_window(u2, 4 * wdx, 4);
            if (d2) ge_madd(acc, acc, qt[d2]);
        }
    }
    if (acc.inf) return 0;

    // R.x mod n == r, checked PROJECTIVELY (no field inversion):
    // x = X/Z^2 == r (mod n) iff X == c*Z^2 (mod p) for c in {r, r+n}
    // — x < p and r < n, so x ≡ r (mod n) only via x == r or x == r+n,
    // the latter possible only when r < p - n (~2^128.3)
    fe z2, cand, rx;
    fe_sq(z2, acc.Z);
    fe_frombytes(rx, sig);                  // r as a field element (r < n < p)
    fe_mul(cand, rx, z2);
    if (fe_equal(cand, acc.X)) return 1;
    // second candidate r + n (as a 256-bit integer; fits iff no carry)
    u64 rn[4];
    if (u256_add(rn, r_s.v, SC_N) == 0) {
        // only meaningful when r + n < p; if r + n >= p the candidate
        // wraps and cannot equal x (x < p) -- fe_frombytes would reduce
        // mod p and produce a WRONG acceptance, so check the bound:
        // p - n fits in 129 bits, so r + n < p iff rn < p, tested via
        // canonical bytes round-trip
        u8 rb[32];
        for (int i = 0; i < 4; i++)
            for (int j = 0; j < 8; j++)
                rb[8 * i + j] = (u8)(rn[3 - i] >> (56 - 8 * j));
        fe rnf;
        fe_frombytes(rnf, rb);
        u8 chkb[32];
        fe_tobytes(chkb, rnf);
        if (memcmp(chkb, rb, 32) == 0) {    // rn < p: candidate valid
            fe_mul(cand, rnf, z2);
            if (fe_equal(cand, acc.X)) return 1;
        }
    }
    return 0;
}

}  // extern "C"
