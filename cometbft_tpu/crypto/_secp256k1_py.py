"""Pure-Python secp256k1 ECDSA (RFC 6979 deterministic nonces).

The host fallback for images without the ``cryptography`` wheel: key
derivation, signing and verification byte-compatible with the
OpenSSL-backed path in ``crypto/secp256k1.py`` (low-S normalized,
compressed SEC1 public keys).  Hot-path verification still rides the
native C++ verifier (``native/secp256k1.cpp``); this module mostly signs
— test fixtures and small valsets — where big-int Python is adequate
(~1 ms/op).
"""

from __future__ import annotations

import hashlib
import hmac

P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

_INF = None                      # point at infinity sentinel


def _add(p1, p2):
    if p1 is _INF:
        return p2
    if p2 is _INF:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return _INF
        lam = (3 * x1 * x1) * pow(2 * y1, -1, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return (x3, (lam * (x1 - x3) - y1) % P)


def _mul(k: int, pt):
    acc, add = _INF, pt
    while k:
        if k & 1:
            acc = _add(acc, add)
        add = _add(add, add)
        k >>= 1
    return acc


def pubkey_from_scalar(d: int) -> bytes:
    """Compressed SEC1 encoding of d*G."""
    x, y = _mul(d, (GX, GY))
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def decompress(raw: bytes):
    """(x, y) from a 33-byte compressed SEC1 point; raises ValueError."""
    if len(raw) != 33 or raw[0] not in (2, 3):
        raise ValueError("not a compressed secp256k1 point")
    x = int.from_bytes(raw[1:], "big")
    if x >= P:
        raise ValueError("x out of range")
    y2 = (x * x * x + 7) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise ValueError("point not on curve")
    if (y & 1) != (raw[0] & 1):
        y = P - y
    return (x, y)


def rfc6979_k(d: int, h1: bytes) -> int:
    """Deterministic nonce (RFC 6979 §3.2) for SHA-256, curve order N."""
    holen = 32
    x = d.to_bytes(32, "big")
    # bits2octets: h1 as int (qlen == hlen == 256, no shift), reduced mod N
    z = int.from_bytes(h1, "big") % N
    bo = z.to_bytes(32, "big")
    v = b"\x01" * holen
    k = b"\x00" * holen
    k = hmac.new(k, v + b"\x00" + x + bo, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + bo, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def sign(d: int, msg: bytes) -> tuple[int, int]:
    """(r, s) over SHA-256(msg), low-S normalized."""
    h1 = hashlib.sha256(msg).digest()
    z = int.from_bytes(h1, "big") % N
    k = rfc6979_k(d, h1)
    while True:
        x, _y = _mul(k, (GX, GY))
        r = x % N
        if r != 0:
            s = pow(k, -1, N) * (z + r * d) % N
            if s != 0:
                break
        # astronomically unlikely; RFC 6979 retries with an updated K
        k = (k + 1) % N or 1
    if s > N // 2:
        s = N - s
    return r, s


def verify(pub_raw: bytes, msg: bytes, r: int, s: int) -> bool:
    if not (1 <= r < N and 1 <= s < N):
        return False
    try:
        q = decompress(pub_raw)
    except ValueError:
        return False
    z = int.from_bytes(hashlib.sha256(msg).digest(), "big") % N
    w = pow(s, -1, N)
    pt = _add(_mul(z * w % N, (GX, GY)), _mul(r * w % N, q))
    if pt is _INF:
        return False
    return pt[0] % N == r
