"""Key interfaces and the Ed25519 implementation.

Mirrors the reference's ``crypto.PubKey``/``crypto.PrivKey`` interfaces
(``crypto/crypto.go:22-43``) and its ed25519 key semantics
(``crypto/ed25519/ed25519.go``): 32-byte public keys, 64-byte private keys
(seed || pubkey), addresses = first 20 bytes of SHA-256 of the pubkey,
and ZIP-215 single-signature verification.

Signing and the single-verify fast path use the ``cryptography`` library's
native (OpenSSL) Ed25519 — the host-side analogue of the reference's
curve25519-voi.  OpenSSL's strict verifier accepts a *subset* of ZIP-215
(cofactorless equation + canonical-encoding checks), so an OpenSSL "reject"
falls back to the exact pure-Python ZIP-215 check; an OpenSSL "accept" is
always correct to accept.  Batch verification lives in ``crypto.batch``.
"""

from __future__ import annotations

import functools
import hashlib
import os
from abc import ABC, abstractmethod

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric import ed25519 as _ossl
except ImportError:              # no `cryptography` wheel on this image:
    # sign/derive/verify fall back to the native C++ implementation
    # (ed25519_sign/ed25519_pubkey/ed25519_verify), then the pure-Python
    # oracle.  Never reintroduce these as unconditional imports.
    # CAVEAT: unlike OpenSSL, the fallback scalar ladders are NOT
    # constant-time (secret-indexed table lookups / data-dependent
    # branches), so secret keys leak through timing/cache side channels.
    # Fine for tests and development images; a production validator must
    # run with the `cryptography` wheel installed.
    InvalidSignature = None
    _ossl = None

from . import _ed25519_py as _ref

ED25519_KEY_TYPE = "ed25519"
SECP256K1_KEY_TYPE = "secp256k1"
BLS12381_KEY_TYPE = "bls12_381"


def _key_classes(key_type: str):
    """The (PubKey, PrivKey) classes for a key type — the single registry
    behind every dispatch site (internal/keytypes/keytypes.go:14-33 +
    crypto/encoding/codec.go)."""
    if key_type == ED25519_KEY_TYPE:
        return Ed25519PubKey, Ed25519PrivKey
    if key_type == SECP256K1_KEY_TYPE:
        from .secp256k1 import Secp256k1PrivKey, Secp256k1PubKey

        return Secp256k1PubKey, Secp256k1PrivKey
    if key_type == BLS12381_KEY_TYPE:
        from .bls12381 import Bls12381PrivKey, Bls12381PubKey

        return Bls12381PubKey, Bls12381PrivKey
    raise ValueError(f"unsupported pubkey type {key_type!r}")


def pub_key_from_type_bytes(key_type: str, raw: bytes) -> "PubKey":
    return _key_classes(key_type)[0](raw)


def priv_key_from_type_bytes(key_type: str, raw: bytes) -> "PrivKey":
    return _key_classes(key_type)[1](raw)


def gen_priv_key(key_type: str = ED25519_KEY_TYPE) -> "PrivKey":
    """Generate a validator key of the given registered type."""
    return _key_classes(key_type)[1].generate()

ADDRESS_SIZE = 20


def address_hash(b: bytes) -> bytes:
    """Address = first 20 bytes of SHA-256 (crypto/crypto.go:18)."""
    return hashlib.sha256(b).digest()[:ADDRESS_SIZE]


class PubKey(ABC):
    @abstractmethod
    def bytes(self) -> bytes: ...

    @abstractmethod
    def verify_signature(self, msg: bytes, sig: bytes) -> bool: ...

    @abstractmethod
    def type(self) -> str: ...

    def address(self) -> bytes:
        return address_hash(self.bytes())

    def __eq__(self, other):
        return (isinstance(other, PubKey) and self.type() == other.type()
                and self.bytes() == other.bytes())

    def __hash__(self):
        return hash((self.type(), self.bytes()))

    def __repr__(self):
        return f"PubKey{{{self.type()}:{self.bytes().hex()[:16]}…}}"


class PrivKey(ABC):
    @abstractmethod
    def bytes(self) -> bytes: ...

    @abstractmethod
    def sign(self, msg: bytes) -> bytes: ...

    @abstractmethod
    def pub_key(self) -> PubKey: ...

    @abstractmethod
    def type(self) -> str: ...


def _ed25519_pubkey_from_seed(seed: bytes) -> bytes:
    """RFC 8032 public key derivation: OpenSSL when the ``cryptography``
    wheel exists, else native C++, else the pure-Python oracle."""
    if _ossl is not None:
        return (_ossl.Ed25519PrivateKey.from_private_bytes(seed)
                .public_key().public_bytes_raw())
    from . import _native_ed25519 as _nat

    pub = _nat.public_key(seed)
    return pub if pub is not None else _ref.public_key_from_seed(seed)


@functools.lru_cache(maxsize=4096)
def _parsed_pubkey(pub: bytes):
    """Parsed OpenSSL key objects, cached per raw pubkey: validator sets
    are ~static across heights, so repeat verifies skip the parse (the
    reference's cacheSize-4096 expanded-pubkey cache,
    ``crypto/ed25519/ed25519.go:42-67``).  Raises on malformed keys."""
    return _ossl.Ed25519PublicKey.from_public_bytes(pub)


def verify_ed25519_zip215(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Single ZIP-215 verification on host.

    OpenSSL fast path: its accepts are a subset of ZIP-215's, so a pass is
    final; only its (rare, adversarial-input) rejects re-check with the exact
    ZIP-215 verifier (native C++ when built, pure-Python otherwise).
    Without the ``cryptography`` wheel the exact verifier IS the path.
    """
    if len(sig) != 64 or len(pub) != 32:
        return False
    if _ossl is not None:
        try:
            _parsed_pubkey(pub).verify(sig, msg)
            return True
        except (InvalidSignature, ValueError):
            pass
    from . import _native_ed25519 as _nat

    exact = _nat.verify(pub, msg, sig)
    if exact is not None:
        return exact
    return _ref.verify_zip215(pub, msg, sig)


class Ed25519PubKey(PubKey):
    SIZE = 32

    def __init__(self, raw: bytes):
        if len(raw) != self.SIZE:
            raise ValueError(f"ed25519 pubkey must be {self.SIZE} bytes")
        self._raw = bytes(raw)

    def bytes(self) -> bytes:
        return self._raw

    def type(self) -> str:
        return ED25519_KEY_TYPE

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        return verify_ed25519_zip215(self._raw, msg, sig)


class Ed25519PrivKey(PrivKey):
    """64-byte private key: seed || pubkey (matching the reference layout)."""

    SIZE = 64

    def __init__(self, raw: bytes):
        if len(raw) == 32:           # accept bare seeds
            raw = raw + _ed25519_pubkey_from_seed(raw)
        if len(raw) != self.SIZE:
            raise ValueError(f"ed25519 privkey must be {self.SIZE} bytes")
        self._raw = bytes(raw)
        self._sk = (_ossl.Ed25519PrivateKey.from_private_bytes(raw[:32])
                    if _ossl is not None else None)

    @classmethod
    def generate(cls) -> "Ed25519PrivKey":
        return cls(os.urandom(32))

    @classmethod
    def from_secret(cls, secret: bytes) -> "Ed25519PrivKey":
        """Deterministic key from a secret (test helper, like GenPrivKeyFromSecret)."""
        return cls(hashlib.sha256(secret).digest())

    def bytes(self) -> bytes:
        return self._raw

    def type(self) -> str:
        return ED25519_KEY_TYPE

    def sign(self, msg: bytes) -> bytes:
        if self._sk is not None:
            return self._sk.sign(msg)
        from . import _native_ed25519 as _nat

        sig = _nat.sign(self._raw[:32], msg)
        return sig if sig is not None else _ref.sign(self._raw[:32], msg)

    def pub_key(self) -> Ed25519PubKey:
        return Ed25519PubKey(self._raw[32:])
