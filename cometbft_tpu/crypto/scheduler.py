"""VerificationScheduler: node-wide micro-batching front end for single
signature verifications, plus the verified-signature dedup cache.

The batched verifier (``crypto/batch.py``) is the paper's engine, but it
is only reachable from call sites that already HOLD a batch — commits,
blocksync windows, light-client traces.  Live consensus gossip arrives as
single votes on concurrent per-peer tasks and used to verify one scalar
multiplication at a time through ``types/vote_set.py``.  This module
closes that gap with the classic dynamic-batching move from
committee-based consensus and inference serving alike:

- ``verify()`` (async) parks each ``(pub, msg, sig)`` request behind a
  future; requests coalesce until either the oldest has waited
  ``max_wait_ms`` (window flush) or ``max_lanes`` lanes are pending
  (size flush — the cap is snapped DOWN to a ``crypto/batch`` compile
  bucket so a full batch pads to a shape XLA has already compiled).
- one dispatch runs the whole micro-batch through the routed
  ``BatchVerifier`` (native SHA-NI RLC on host, device kernel when the
  ``_ThroughputRouter`` prefers it) on a single worker thread, then
  demultiplexes per-item verdicts back to the awaiting callers.  The
  backends already localize failures (a refused batch re-verifies per
  item), so one bad signature can never poison or reject its batchmates.
- a bounded LRU **verified-signature cache** keyed by
  ``(pubkey bytes, sha256(msg), sig)`` remembers POSITIVE verdicts only.
  It is consulted and seeded by this scheduler, by ``VoteSet._verify``
  (sync, via :func:`verify_cached`) and by the ``VerifyCommit*`` family —
  so a vote re-gossiped by k peers and then re-checked inside the commit
  costs one scalar multiplication instead of k+1.  Failed verdicts are
  NEVER cached: a signature that fails verification cannot be served
  from the cache as valid.  Requests for a key already in flight attach
  to the pending future instead of occupying another lane.

Trust boundaries: the equivocation/evidence paths
(``VoteSet.add_vote``'s conflicting-vote branch, the
``VerifyCommit*AllSignatures`` variants) bypass the cache entirely via
:func:`verify_uncached` — evidence that slashes a validator must rest on
a fresh verification, not a cache entry.

Lifecycle: one process-wide scheduler shared by every in-proc node
(verdicts are universal; cross-node batching is free concurrency),
refcounted through :func:`acquire_scheduler`/:func:`release_scheduler`
from node start/stop.  With no scheduler registered every helper
degrades to a direct ``pub.verify_signature`` call with zero overhead —
no hashing, no locks on the common path.

Since r13 the scheduler and the batched verifier are driven by ONE
declarative device plan (``crypto/plan.py``): the lane-cap snapping
below reads the plan's bucket tables (the same tables the dispatch pads
to and the AOT compile bundle enumerates), so a reconfigured plan steers
coalescing, padding, and pre-compilation together.
"""

from __future__ import annotations

import asyncio
import hashlib
import time

from ..libs import failures
from ..libs import metrics
from ..libs import tracing
from ..libs.service import BaseService
from .keys import PubKey

# ---------------------------------------------------------------- metrics


def _sched_metrics():
    """Registered once (libs.metrics dedups by name); grouped so the hot
    path pays one tuple unpack."""
    return (
        metrics.histogram(
            "crypto_sched_batch_lanes",
            "micro-batch occupancy at dispatch (lanes per flush)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)),
        metrics.histogram(
            "crypto_sched_wait_seconds",
            "time a request waited in the coalescing window"),
        metrics.histogram(
            "crypto_sched_latency_seconds",
            "end-to-end single-verification latency through the scheduler"),
        metrics.counter(
            "crypto_sched_cache_hits_total",
            "verified-signature cache hits, by consulting subsystem"),
        metrics.counter(
            "crypto_sched_cache_misses_total",
            "verified-signature cache misses, by consulting subsystem"),
        metrics.counter(
            "crypto_sched_dedup_inflight_total",
            "requests coalesced onto an identical in-flight verification"),
        metrics.counter(
            "crypto_sched_flush_total",
            "micro-batch flushes, by trigger (window/size/stop/sync)"),
        metrics.counter(
            "crypto_sched_lanes_total",
            "scheduler-verified lanes, by verdict"),
    )


# ------------------------------------------------------------------ cache


def cache_key(pub_bytes: bytes, msg: bytes, sig: bytes) -> tuple:
    """Cache key for one verification: the message is folded through
    sha256 so keys stay bounded regardless of message size (vote sign
    bytes are ~120 B, but evidence/commit messages need not be)."""
    return (pub_bytes, hashlib.sha256(msg).digest(), sig)


class VerifiedSigCache:
    """Bounded LRU of POSITIVELY verified signatures.

    Thread-safe: consulted from the event loop (scheduler, vote sets)
    and from executor threads (dispatch seeding, bench drivers).  Only
    ``True`` verdicts are ever stored — there is deliberately no API to
    record a failure, so a bug cannot turn this into a
    forged-signature oracle.  Eviction is plain LRU via dict ordering.
    """

    def __init__(self, max_size: int = 65536):
        self.max_size = max(0, int(max_size))
        self._entries: dict[tuple, None] = {}
        import threading

        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._entries)

    def hit(self, key: tuple) -> bool:
        """True iff ``key`` was verified before; refreshes recency."""
        if self.max_size == 0:
            return False
        with self._lock:
            if key not in self._entries:
                return False
            # move-to-end: dicts preserve insertion order
            del self._entries[key]
            self._entries[key] = None
            return True

    def seed(self, key: tuple) -> None:
        if self.max_size == 0:
            return
        with self._lock:
            if key in self._entries:
                del self._entries[key]
            self._entries[key] = None
            while len(self._entries) > self.max_size:
                del self._entries[next(iter(self._entries))]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


# -------------------------------------------------------------- scheduler


class _OverdueSentinel:
    """Resolved into a request's future when its deadline timer fires
    before the verdict demuxed: awaiting callers re-verify directly."""


_OVERDUE = _OverdueSentinel()


class _Request:
    __slots__ = ("key", "pub", "msg", "sig", "future", "callbacks",
                 "t_enqueue", "timer", "height")

    def __init__(self, key, pub, msg, sig, height=0):
        self.key = key
        self.pub = pub
        self.msg = msg
        self.sig = sig
        # block height the signature belongs to (0 = unknown): dispatch
        # spans stamp the batch's h_lo..h_hi window so the height
        # timeline (libs/timeline) can attribute verify time
        self.height = height
        # ONE shared future for every awaiting caller (asyncio futures
        # support multiple awaiters) plus plain callbacks for the
        # fire-and-forget path — a 384-arrival gossip burst must not pay
        # a future per arrival
        self.future: asyncio.Future | None = None
        self.callbacks: list = []
        self.t_enqueue = time.perf_counter()
        # ONE deadline timer per request (r16): the per-CALLER
        # ``wait_for(shield(...))`` it replaces built a timer task per
        # awaiter — at mempool-admission rates that machinery cost more
        # than the submission itself (measured ~2x submit_nowait)
        self.timer: asyncio.TimerHandle | None = None


class VerificationScheduler(BaseService):
    """Latency-bounded micro-batching over the routed BatchVerifier.

    ``max_lanes`` is snapped down to a ``crypto/batch`` lane bucket so a
    size-flushed batch exactly fills a compiled shape; ``max_wait_ms``
    bounds how long the FIRST request of a window can wait (the paper's
    latency/throughput knob).  Dispatch runs on a single worker thread:
    the native RLC batch is CPU-bound and the device path serializes in
    ``crypto/batch`` anyway, so one thread avoids oversubscribing the
    host while keeping the event loop free.
    """

    def __init__(self, backend: str = "auto", max_wait_ms: float = 2.0,
                 max_lanes: int = 256, cache_size: int = 65536,
                 verify_timeout_s: float = 0.0,
                 name: str = "vote-sched"):
        super().__init__(name=name)
        self.backend = backend
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self.max_lanes = snap_lane_cap(max_lanes)
        # deadline on awaiting a verdict future: a fault between flush
        # and demux must never hang a caller forever.  Default ~5x the
        # coalescing window, floored at 1 s (a cold native-verifier
        # build or a loaded box must not trip it); past the deadline the
        # caller re-verifies directly — a correct verdict, minus the
        # batching win.
        self.verify_timeout_s = (float(verify_timeout_s)
                                 if verify_timeout_s and verify_timeout_s > 0
                                 else max(1.0, 5.0 * self.max_wait_s))
        self.cache = VerifiedSigCache(cache_size)
        self._pending: dict[tuple, _Request] = {}
        # dispatched but not yet demuxed: identical requests arriving
        # while a batch is on the worker attach here instead of buying
        # another lane (the "never verify the same signature twice"
        # guarantee covers the dispatch window too)
        self._inflight: dict[tuple, _Request] = {}
        self._timer: asyncio.TimerHandle | None = None
        self._dispatches: set[asyncio.Task] = set()
        self._pool = None            # lazy ThreadPoolExecutor(1)
        self._loop: asyncio.AbstractEventLoop | None = None
        self._m = _sched_metrics()
        # hot-path counters pre-bound to their label sets (per-event
        # label sorting costs real time in a gossip storm)
        (_, _, _, hits, misses, dedup, _, lanes) = self._m
        self._bound: dict[str, tuple] = {
            s: (hits.bind(source=s), misses.bind(source=s))
            for s in ("scheduler", "votes", "commit", "sync")}
        self._dedup_b = dedup.bind()
        self._lanes_ok = lanes.bind(verdict="ok")
        self._lanes_bad = lanes.bind(verdict="bad")
        # hot-path histograms pre-bound to the empty label set: _flush
        # observes once per REQUEST (wait time), not once per batch
        self._occ_b = self._m[0].bind()
        self._wait_b = self._m[1].bind()
        self._lat_b = self._m[2].bind()
        # per-INSTANCE tallies for stats(): the libs.metrics registry is
        # process-global (a restarted node's fresh scheduler would report
        # its predecessor's totals), so the operator/bench surface reads
        # these and only Prometheus reads the global counters
        self._t_hits = 0
        self._t_misses = 0
        self._t_dedup = 0
        self._t_ok = 0
        self._t_bad = 0
        self._t_batches = 0
        self._t_lanes_sum = 0

    # ----------------------------------------------------------- lifecycle

    async def on_start(self) -> None:
        self._loop = asyncio.get_running_loop()

    def _abandon(self) -> None:
        """Synchronous teardown for an instance whose event loop is gone
        (a crashed node that never released): the async stop() path can
        never run, but the worker thread and timer must not leak.  Parked
        requests are dropped — their futures/callbacks belong to the dead
        loop and nothing can consume them."""
        self._running = False
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self._pending.clear()
        self._inflight.clear()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    async def on_stop(self) -> None:
        """Flush everything still pending so no caller is left hanging,
        then wait for in-flight dispatches to demux their verdicts."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self._pending:
            self._flush("stop")
        # snapshot: dispatch tasks remove themselves on completion
        for t in list(self._dispatches):
            try:
                await t
            except Exception:       # demux already logged; don't wedge stop
                pass
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    # -------------------------------------------------------------- verify

    def _enqueue(self, pub, msg, sig, key,
                 height: int = 0) -> "_Request | None":
        """Shared enqueue core: returns the (possibly pre-existing)
        request to attach to, or None when the verdict was served
        directly (cache hit handled by callers)."""
        req = self._pending.get(key) or self._inflight.get(key)
        if req is not None:
            self._dedup_b.inc()
            self._t_dedup += 1
            return req
        req = _Request(key, pub, bytes(msg), bytes(sig), height)
        self._pending[key] = req
        if len(self._pending) >= self.max_lanes:
            self._flush("size")
        elif self._timer is None:
            self._timer = (self._loop or asyncio.get_event_loop()) \
                .call_later(self.max_wait_s, self._flush, "window")
        return req

    async def verify(self, pub: PubKey, msg: bytes, sig: bytes) -> bool:
        """Coalescing single-verification entry point (async callers:
        RPC, tests, tooling).  Falls back to a direct check when the
        service is not running."""
        t0 = time.perf_counter()
        key = cache_key(pub.bytes(), msg, sig)
        lat_h = self._lat_b
        hit_b, miss_b = self._bound["scheduler"]
        if self.cache.hit(key):
            hit_b.inc()
            self._t_hits += 1
            return True
        miss_b.inc()
        self._t_misses += 1
        if not self.is_running:
            ok = bool(pub.verify_signature(msg, sig))
            if ok:
                self.cache.seed(key)
            lat_h.observe(time.perf_counter() - t0)
            return ok
        req = self._enqueue(pub, msg, sig, key)
        if req.future is None:
            loop = asyncio.get_running_loop()
            req.future = loop.create_future()
            # a fault between flush and demux must never hang a caller
            # forever: one timer per REQUEST resolves the shared future
            # with the overdue sentinel past the deadline (covers a
            # request a stubbed/wedged flush never dispatches, too)
            req.timer = loop.call_later(self.verify_timeout_s,
                                        self._overdue, req)
        res = _OVERDUE
        poisoned = False
        try:
            # shield: one caller's cancellation must not cancel the
            # future its batchmates (and the demux loop) still share
            res = await asyncio.shield(req.future)
        except asyncio.CancelledError:
            lat_h.observe(time.perf_counter() - t0)
            raise
        except Exception as e:       # a poisoned future
            poisoned = True
            self.log.error("scheduler verdict failed; verifying "
                           "directly", err=repr(e))
        if res is _OVERDUE:
            # fall back OFF the event loop, and NOT on self._pool: the
            # deadline usually means that single worker is wedged, and
            # queueing behind it would just hang a second time
            if not poisoned:         # don't double-log a demux fault
                self.log.error("scheduler verdict overdue; verifying "
                               "directly")
            ok = bool(await asyncio.to_thread(
                pub.verify_signature, msg, sig))
            if ok:
                self.cache.seed(key)
        else:
            ok = bool(res)
        lat_h.observe(time.perf_counter() - t0)
        return ok

    def _overdue(self, req: "_Request") -> None:
        req.timer = None
        if req.future is not None and not req.future.done():
            req.future.set_result(_OVERDUE)

    def submit_nowait(self, pub: PubKey, msg: bytes, sig: bytes,
                      on_done=None, height: int = 0) -> None:
        """Fire-and-forget coalescing submission — the consensus reactor's
        entry point: no future, no task, no await.  ``on_done(ok)`` (if
        given) runs on the event loop after the verdict lands; cache hits
        and the not-running fallback invoke it synchronously.  Exceptions
        from ``on_done`` are swallowed after logging: a broken callback
        must not poison its batchmates' demux."""
        key = cache_key(pub.bytes(), msg, sig)
        hit_b, miss_b = self._bound["scheduler"]
        if self.cache.hit(key):
            hit_b.inc()
            self._t_hits += 1
            if on_done is not None:
                on_done(True)
            return
        miss_b.inc()
        self._t_misses += 1
        if not self.is_running:
            ok = bool(pub.verify_signature(msg, sig))
            if ok:
                self.cache.seed(key)
            if on_done is not None:
                on_done(ok)
            return
        req = self._enqueue(pub, msg, sig, key, height)
        if on_done is not None:
            req.callbacks.append(on_done)

    def verify_sync(self, pub: PubKey, msg: bytes, sig: bytes,
                    source: str = "sync") -> bool:
        """Synchronous cached verification: the fallback for callers that
        cannot await (``VoteSet._verify`` runs inside the single-writer
        consensus handler; tooling may have no loop at all).  Cache hit
        or one direct verification; positive verdicts seed the cache."""
        key = cache_key(pub.bytes(), msg, sig)
        bound = self._bound.get(source)
        if bound is None:
            bound = (self._m[3].bind(source=source),
                     self._m[4].bind(source=source))
            self._bound[source] = bound
        if self.cache.hit(key):
            bound[0].inc()
            self._t_hits += 1
            return True
        bound[1].inc()
        self._t_misses += 1
        ok = bool(pub.verify_signature(msg, sig))
        if ok:
            self.cache.seed(key)
        return ok

    # ------------------------------------------------------------ dispatch

    def _flush(self, reason: str) -> None:
        """Move the pending window into one dispatch task.  Runs on the
        event loop (call_later callback or inline from verify/stop)."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if not self._pending:
            return
        batch = list(self._pending.values())
        self._pending.clear()
        for req in batch:
            self._inflight[req.key] = req
        self._m[6].inc(reason=reason)                       # flushes
        self._t_batches += 1
        self._t_lanes_sum += len(batch)
        now = time.perf_counter()
        self._occ_b.observe(len(batch))                     # occupancy
        for req in batch:
            self._wait_b.observe(now - req.t_enqueue)       # wait time
        if tracing.is_enabled():
            hs = [r.height for r in batch if r.height]
            tracing.event("crypto.sched", "flush", reason=reason,
                          lanes=len(batch), h_lo=min(hs, default=0),
                          h_hi=max(hs, default=0))
        loop = self._loop or asyncio.get_running_loop()
        task = loop.create_task(self._dispatch(batch))
        self._dispatches.add(task)
        task.add_done_callback(self._dispatches.discard)

    async def _dispatch(self, batch: list[_Request]) -> None:
        loop = asyncio.get_running_loop()
        if self._pool is None:
            import concurrent.futures as cf

            self._pool = cf.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="vote-sched")
        sp = None
        if tracing.is_enabled():
            hs = [r.height for r in batch if r.height]
            sp = tracing.begin("crypto.sched", "dispatch",
                               lanes=len(batch), backend=self.backend,
                               h_lo=min(hs, default=0),
                               h_hi=max(hs, default=0))
        try:
            oks = await loop.run_in_executor(
                self._pool, self._verify_batch, batch)
        except Exception as e:
            # infra failure, not a signature verdict: every batchmate
            # still deserves a REAL answer, so re-verify per item on the
            # worker (no batch machinery, no chaos site on the recovery
            # path).  Only if even that fails does the batch fail
            # closed — False, never an unresolved future.
            self.log.error("batch dispatch failed; re-verifying items "
                           "directly", err=repr(e))
            try:
                oks = await loop.run_in_executor(
                    self._pool, self._verify_items_direct, batch)
            except Exception as e2:
                self.log.error("per-item recovery failed; failing batch "
                               "closed", err=repr(e2))
                oks = [False] * len(batch)
        tracing.finish(sp, ok=sum(map(bool, oks)))
        for req, ok in zip(batch, oks):
            ok = bool(ok)
            self._inflight.pop(req.key, None)
            if ok:
                self.cache.seed(req.key)
            (self._lanes_ok if ok else self._lanes_bad).inc()
            if ok:
                self._t_ok += 1
            else:
                self._t_bad += 1
            if req.timer is not None:
                req.timer.cancel()
                req.timer = None
            if req.future is not None and not req.future.done():
                req.future.set_result(ok)
            for cb in req.callbacks:
                try:
                    cb(ok)
                except Exception as e:
                    self.log.error("submit_nowait callback failed",
                                   err=repr(e))

    def _verify_batch(self, batch: list[_Request]) -> list[bool]:
        """Worker-thread body: one routed BatchVerifier pass.  The
        backends localize failures internally (native RLC and the device
        RLC both fall back to per-item verification on a refused batch),
        so the returned verdicts are per-item safe.  A batch of one skips
        the batch machinery — there is nothing to amortize."""
        from . import batch as cryptobatch

        f = failures.fire("sched.dispatch.raise")
        if f is not None:
            raise RuntimeError("chaos: injected scheduler dispatch "
                               "failure")
        if len(batch) == 1:
            r = batch[0]
            return [bool(r.pub.verify_signature(r.msg, r.sig))]
        bv = cryptobatch.create_batch_verifier(self.backend)
        for r in batch:
            bv.add(r.pub, r.msg, r.sig)
        _, oks = bv.verify()
        return oks

    @staticmethod
    def _verify_items_direct(batch: list[_Request]) -> list[bool]:
        """Recovery path for a failed batch dispatch: one direct
        verification per item, no batching, no injection sites."""
        return [bool(r.pub.verify_signature(r.msg, r.sig))
                for r in batch]

    # ------------------------------------------------------------- surface

    def stats(self) -> dict:
        """Operator/bench surface: THIS instance's cache + coalescing
        tallies (the global Prometheus counters outlive instances; these
        reset with every scheduler)."""
        lookups = self._t_hits + self._t_misses
        return {
            "cache_size": len(self.cache),
            "cache_hits": self._t_hits,
            "cache_misses": self._t_misses,
            "cache_hit_rate": (self._t_hits / lookups) if lookups else 0.0,
            "dedup_inflight": self._t_dedup,
            "batches": self._t_batches,
            "mean_batch_lanes": (self._t_lanes_sum / self._t_batches)
            if self._t_batches else 0.0,
            "lanes_ok": self._t_ok,
            "lanes_bad": self._t_bad,
        }


# snap_lane_cap moved into the declarative device plan (crypto/plan.py,
# r13): the scheduler and the batched verifier now read ONE copy of the
# bucket tables.  Re-exported here for existing importers.
from .plan import snap_lane_cap  # noqa: E402  (re-export)


# ------------------------------------------------- process-wide registry

_GLOBAL: VerificationScheduler | None = None
_REFS = 0


def get_scheduler() -> VerificationScheduler | None:
    return _GLOBAL


def set_scheduler(sched: VerificationScheduler | None) -> None:
    """Test/tooling hook: install (or clear) the process-wide scheduler
    directly, bypassing the refcount."""
    global _GLOBAL, _REFS
    _GLOBAL = sched
    _REFS = 0 if sched is None else max(_REFS, 1)


async def acquire_scheduler(backend: str = "auto", max_wait_ms: float = 2.0,
                            max_lanes: int = 256, cache_size: int = 65536,
                            verify_timeout_s: float = 0.0
                            ) -> VerificationScheduler:
    """Start (or share) the process-wide scheduler.  In-proc ensembles
    call this once per node: the first caller's knobs win — verdicts are
    universal, so sharing one cache and one coalescing window across
    nodes only improves occupancy.  A scheduler left over from a
    different (dead) event loop is discarded, not reused: its timer and
    dispatch tasks are bound to that loop."""
    global _GLOBAL, _REFS
    loop = asyncio.get_running_loop()
    if _GLOBAL is not None and (_GLOBAL._loop is not loop
                                or not _GLOBAL.is_running):
        _GLOBAL._abandon()          # reclaim the worker thread + timer
        _GLOBAL = None
        _REFS = 0
    if _GLOBAL is None:
        sched = VerificationScheduler(
            backend=backend, max_wait_ms=max_wait_ms, max_lanes=max_lanes,
            cache_size=cache_size, verify_timeout_s=verify_timeout_s)
        await sched.start()
        _GLOBAL = sched
    _REFS += 1
    return _GLOBAL


async def release_scheduler() -> None:
    """Drop one node's reference; the last release stops the service."""
    global _GLOBAL, _REFS
    if _GLOBAL is None:
        return
    _REFS -= 1
    if _REFS <= 0:
        sched, _GLOBAL, _REFS = _GLOBAL, None, 0
        await sched.stop()


# ----------------------------------------------- sync helpers (hot path)


def cache_active() -> bool:
    """True when a scheduler (hence a cache) is registered.  Callers use
    this to skip key hashing entirely when there is nothing to consult —
    the no-scheduler configuration must cost zero."""
    return _GLOBAL is not None


def dense_cache_active() -> bool:
    """Gate for the DENSE commit paths: a cache that exists but is EMPTY
    cannot hit, and the per-lane key build (tobytes + sha256 + lock) is
    ~45 ms at 10k lanes — pure overhead on a node whose gossip never
    seeded anything (cold start, catch-up).  Live nodes always have
    scheduler-seeded entries, so this gate only spares the cold case."""
    return _GLOBAL is not None and len(_GLOBAL.cache) > 0


def verify_cached(pub: PubKey, msg: bytes, sig: bytes,
                  source: str = "votes") -> bool:
    """Cached single verification for sync call sites
    (``VoteSet._verify``): cache hit, else direct verify + seed.  With no
    scheduler registered this is exactly ``pub.verify_signature``."""
    sched = _GLOBAL
    if sched is None:
        return bool(pub.verify_signature(msg, sig))
    return sched.verify_sync(pub, msg, sig, source=source)


def verify_uncached(pub: PubKey, msg: bytes, sig: bytes) -> bool:
    """Evidence-grade verification: never reads OR seeds the cache.  The
    conflicting-vote branch of ``VoteSet.add_vote`` and the
    ``VerifyCommit*AllSignatures`` evidence paths use this — an
    equivocation proof must rest on a fresh scalar multiplication."""
    return bool(pub.verify_signature(msg, sig))


def cache_lookup(pub_bytes: bytes, msg: bytes, sig: bytes,
                 source: str = "commit") -> bool:
    """Dense-path cache consult (``types/validation.py``): True iff this
    exact (pub, msg, sig) was positively verified before."""
    sched = _GLOBAL
    if sched is None:
        return False
    key = cache_key(pub_bytes, msg, sig)
    bound = sched._bound.get(source)
    if bound is None:
        bound = (sched._m[3].bind(source=source),
                 sched._m[4].bind(source=source))
        sched._bound[source] = bound
    if sched.cache.hit(key):
        bound[0].inc()
        return True
    bound[1].inc()
    return False


def cache_seed(pub_bytes: bytes, msg: bytes, sig: bytes) -> None:
    """Record a POSITIVE verdict obtained outside the scheduler (a
    successful ``VerifyCommit*`` batch seeds its lanes so later
    re-checks of the same votes are free)."""
    sched = _GLOBAL
    if sched is None:
        return
    sched.cache.seed(cache_key(pub_bytes, msg, sig))
