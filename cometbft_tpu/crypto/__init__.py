"""Cryptographic primitives and key interfaces.

Mirrors the reference's ``crypto/`` layer (``crypto/crypto.go:22-52``):
PubKey/PrivKey interfaces, the BatchVerifier seam the TPU backend plugs
into (``crypto/batch/batch.go``), merkle trees, and hashes.
"""
