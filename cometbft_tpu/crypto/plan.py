"""The declarative device plan: one layer that says HOW verification
work maps onto the machine.

Before r13 the mapping was smeared across two modules: ``crypto/batch.py``
owned the lane/block/table bucket tables, the device set, and the
RLC/min-lane routing thresholds, while ``crypto/scheduler.py`` kept its
own copy of the bucket-snapping math (``snap_lane_cap``).  This module
collapses that into a single declarative :class:`DevicePlan` — the mesh
(device set), the compile-bucket tables (verify lanes x hash blocks,
valset table rows, merkle level widths), and the routing thresholds —
that both the batched verifier and the coalescing scheduler read, and
that the AOT compile-bundle cache (``crypto/aotbundle.py``) enumerates:

- ``active()`` is the live plan; ``configure()``/``set_plan()`` replace
  it (node startup wires ``config.base``/``config.blocksync`` through
  here; the legacy ``crypto/batch`` ``set_*`` hooks now delegate).
- ``bucket``/``bucket_for_lanes``/``buckets_for_batch``/``chunk_bucket``/
  ``snap_lane_cap`` are the ONE copy of the bucket math (``batch.py``
  and ``scheduler.py`` re-export them for their callers).
- :func:`enumerate_buckets` lists every compiled shape the plan implies
  — the warm set a node AOT-lowers into its on-disk bundle, and the
  per-bucket cold/warm status surfaced in ``/status``.
- :func:`plan_hash` fingerprints the declarative fields; the bundle
  loader combines it with the jax/jaxlib/platform fingerprint so a
  stale bundle is ignored, never silently executed
  (``aotbundle.bundle_version``).

Mutable runtime registers deliberately stay where tests and tooling
already poke them: ``TpuBatchVerifier.MIN_DEVICE_LANES`` (the class
attribute IS the live value; ``configure(min_device_lanes=...)`` writes
it) and the device set (moved here from ``batch._DEVICES``;
``batch.set_devices`` delegates).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field, replace

# Default bucket tables (moved verbatim from crypto/batch.py r12).
# Lane buckets cap at 4096: measured on TPU v5e, verify throughput peaks
# at 2048-4096 lanes and HALVES by 10240 (docs/bench/r04-notes.md);
# oversized batches chunk at the cap.  Valset TABLE rows bucket
# separately and keep growing past the cap: a cached per-valset table
# must hold every validator (the gather indexes into it, it cannot
# chunk).  Hash-block buckets: a vote sign-bytes message is ~120 B ->
# 2 SHA-512 blocks.  Merkle level widths mirror crypto/merkle.py.
LANE_BUCKETS = (16, 64, 256, 1024, 2048, 4096)
TABLE_BUCKETS = LANE_BUCKETS + (8192, 16384, 32768, 65536)
BLOCK_BUCKETS = (2, 3, 4, 8, 16)
MERKLE_BUCKETS = (256, 1024, 4096)
# BLS cohort-table row buckets (ops/blsg1 masked G1 fold): powers of two
# only — the kernel's tree reduction pads to one anyway, so intermediate
# sizes would compile distinct shapes for identical work
BLS_BUCKETS = (16, 64, 256, 1024, 4096, 16384)


@dataclass(frozen=True)
class DevicePlan:
    """Declarative description of the verification pipeline's device
    mapping.  Frozen: mutate via :func:`configure` (which installs a
    replaced copy), so a plan captured by the AOT bundle or /status can
    never drift under its reader."""

    lane_buckets: tuple = LANE_BUCKETS
    block_buckets: tuple = BLOCK_BUCKETS
    table_buckets: tuple = TABLE_BUCKETS
    merkle_buckets: tuple = MERKLE_BUCKETS
    bls_buckets: tuple = BLS_BUCKETS
    # routing thresholds (crypto/batch dispatch):
    rlc_min_lanes: int = 128        # lanes before the one-shot RLC verdict
    min_device_lanes: int = 1       # below: host crypto even with a device
    # the warm set: the (kind x lanes x blocks) compile buckets a node
    # AOT-lowers into its on-disk bundle.  Deliberately a subset of the
    # full bucket cross-product — every shape costs a multi-minute XLA
    # compile at build time and megabytes in the bundle, so the plan
    # names the shapes the workload actually hits (the same hot shapes
    # node warmup compiled before r13, plus the lane cap the blocksync
    # accumulator fills).
    warm_lanes: tuple = (256, 1024, 4096)
    warm_blocks: tuple = (2,)
    warm_kinds: tuple = ("verify", "rlc")
    warm_merkle: tuple = ()         # merkle level widths to bundle
    # valset TABLE row buckets to bundle: each adds the table-build
    # kernel plus the cached-gather verify/RLC shapes — the route every
    # real commit takes (the node wires the bucket its CURRENT valset
    # lands in, so "first real commit" really is warm)
    warm_tables: tuple = ()
    # BLS aggregation row buckets to bundle (``bls_agg:<rows>`` — the
    # ops/blsg1 masked cohort fold).  Default EMPTY: the host complement
    # fold is already sub-millisecond, and each bls_agg shape is a
    # multi-minute XLA compile; a BLS-heavy deployment opts in with the
    # bucket its valset cohort lands in.
    warm_bls: tuple = ()
    mesh_axis: str = "batch"
    # explicit device-mesh dims for true SPMD dispatch: () = single-device
    # (the pre-r19 behavior), (D,) = one sharded program over the first D
    # visible devices.  Kept OUT of plan_hash so a mesh-shape mismatch is
    # its own bundle-staleness reason (aotbundle reason="mesh"), distinct
    # from a plan change.
    mesh_shape: tuple = ()


@dataclass(frozen=True)
class CompileBucket:
    """One compiled shape the plan implies.  ``key`` is the bundle/
    status identity: ``"<kind>:<lanes>x<blocks>"`` for the plain verify
    kernels, ``"<kind>:<rows>:<lanes>x<blocks>"`` for the cached-table
    gather kernels, ``"tables:<rows>"`` for the table build,
    ``"bls_agg:<rows>"`` for the BLS cohort fold, and
    ``"merkle_level:<lanes>"`` for the tree kernel."""

    kind: str
    lanes: int
    blocks: int = 0
    table_rows: int = 0
    key: str = field(default="")

    def __post_init__(self):
        if not self.key:
            if self.kind == "tables":
                k = f"tables:{self.table_rows}"
            elif self.kind == "bls_agg":
                k = f"bls_agg:{self.table_rows}"
            elif self.table_rows:
                k = (f"{self.kind}:{self.table_rows}:"
                     f"{self.lanes}x{self.blocks}")
            elif self.blocks:
                k = f"{self.kind}:{self.lanes}x{self.blocks}"
            else:
                k = f"{self.kind}:{self.lanes}"
            object.__setattr__(self, "key", k)


def mesh_size(plan: "DevicePlan | None" = None) -> int:
    """Devices the plan's mesh spans (1 when no mesh is declared)."""
    plan = plan or _ACTIVE
    n = 1
    for d in plan.mesh_shape:
        n *= max(1, int(d))
    return n


# Per-kernel sharding labels: which positional argument is lane-sharded
# over the mesh axis and which is replicated to every device.  This
# table is the ONE place the argument layout of the sharded programs is
# declared — parallel/mesh.py turns the labels into NamedShardings and
# crypto/aotbundle.py compiles from the same source, so a bundle's
# executable and the live dispatch can never disagree about layout.
# ``donate`` lists the lane-sharded operands: they are staging copies of
# host arrays (dispatch always re-transfers from numpy), so the runtime
# may reuse their device memory for outputs.
KERNEL_SHARDINGS = {
    # verify_padded(pub, r, s, msgs, active) -> ok[lane]
    "verify": {"in": ("lane",) * 5, "out": "lane",
               "donate": (0, 1, 2, 3, 4)},
    # rlc(pub, r, s, msgs, active, z10) -> scalar verdict
    "rlc": {"in": ("lane",) * 6, "out": "repl", "donate": (0, 1, 2, 3, 4)},
    # gather(tables..., ok_active, idx, r, s, msgs, active) -> ok[lane]
    # (the Cached table tuple + precomputed ok row are replicated; the
    # per-lane operands shard)
    "gather": {"in": ("repl", "repl") + ("lane",) * 5, "out": "lane",
               "donate": (2, 3, 4, 5, 6)},
    # rlc_gather(tables..., ok_active, idx, r, s, msgs, active, z10)
    "rlc_gather": {"in": ("repl", "repl") + ("lane",) * 6, "out": "repl",
                   "donate": (2, 3, 4, 5, 6)},
    # merkle_inner_level(left, right) -> parents[lane]
    "merkle_level": {"in": ("lane", "lane"), "out": "lane",
                     "donate": (0, 1)},
}


def lane_sharding(mesh):
    """NamedSharding splitting the leading (lane) axis over the mesh."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(mesh.axis_names[0]))


def replicated_sharding(mesh):
    """NamedSharding replicating an operand to every mesh device."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def kernel_shardings(kind: str, mesh):
    """(in_shardings, out_shardings, donate_argnums) for ``kind`` on
    ``mesh``, realized from :data:`KERNEL_SHARDINGS`.  Single-entry
    labels expand per positional argument; jit broadcasts a sharding
    over pytree leaves (the Cached table tuple) by prefix matching."""
    spec = KERNEL_SHARDINGS[kind]
    lane, repl = lane_sharding(mesh), replicated_sharding(mesh)
    pick = {"lane": lane, "repl": repl}
    ins = tuple(pick[label] for label in spec["in"])
    out = pick[spec["out"]]
    return ins, out, spec["donate"]


_ACTIVE = DevicePlan()
_DEVICES: tuple | None = None    # explicit device set (config/test hook)


def active() -> DevicePlan:
    return _ACTIVE


def set_plan(plan: DevicePlan, push_min_lanes: bool = True) -> None:
    """Install ``plan`` as the live plan; when ``push_min_lanes``, also
    write the batch verifier's class-level min-lane threshold (the live
    register tests and the legacy ``set_min_device_lanes`` hook poke
    directly — a configure() that did not touch that field leaves the
    register alone so a direct poke stays authoritative)."""
    global _ACTIVE
    _ACTIVE = plan
    if push_min_lanes:
        from . import batch as _b

        _b.TpuBatchVerifier.MIN_DEVICE_LANES = \
            max(1, int(plan.min_device_lanes))


def configure(**overrides) -> DevicePlan:
    """Replace fields of the active plan (node startup / legacy hooks).
    Unknown fields raise — a typo'd knob must not silently no-op."""
    plan = replace(_ACTIVE, **overrides)
    set_plan(plan, push_min_lanes="min_device_lanes" in overrides)
    return plan


def reset() -> None:
    """Test hook: restore the default plan and clear the device set."""
    global _DEVICES
    _DEVICES = None
    set_plan(DevicePlan())


# ------------------------------------------------------------ device set


def set_devices(devices) -> None:
    """Shard every device batch over these devices (None or a single
    device restores single-chip dispatch).  The node wires this from
    config; ``dryrun_multichip`` uses it so the driver artifact
    exercises the production sharded path."""
    global _DEVICES
    _DEVICES = tuple(devices) if devices else None


def resolve_devices(device) -> tuple:
    """Devices a batch should run on: an explicit single device wins,
    then the configured set, then the plan's declared mesh shape (the
    first ``mesh_size`` visible devices — CPU host-device emulation
    included, which is how CI exercises the sharded path), else all
    visible accelerator chips (so a multi-chip host shards
    automatically).  Empty tuple = jit default."""
    if device is not None:
        return (device,)
    if _DEVICES is not None:
        return _DEVICES
    try:
        import jax

        n = mesh_size(_ACTIVE)
        if n > 1:
            devs = tuple(jax.devices())
            if len(devs) >= n:
                return devs[:n]
        accels = tuple(d for d in jax.devices() if d.platform != "cpu")
        return accels if len(accels) > 1 else ()
    except Exception:
        return ()


# ----------------------------------------------------------- bucket math


def bucket(n: int, buckets) -> int:
    """Next bucket >= n; beyond the largest, the exact size (a fresh
    compile for the rare oversized case beats crashing or silent
    truncation)."""
    for b in buckets:
        if n <= b:
            return b
    return n


def bucket_for_lanes(n: int) -> int:
    """The lane bucket a batch of ``n`` signatures compiles into,
    clamped to the cap (bigger batches chunk, so no larger shape is
    ever compiled)."""
    lanes = _ACTIVE.lane_buckets
    return min(bucket(max(1, n), lanes), lanes[-1])


def buckets_for_batch(n: int) -> tuple:
    """EVERY lane bucket a batch of ``n`` signatures will dispatch: the
    dispatch splits past the largest bucket into cap-sized chunks plus a
    remainder, so n=10000 runs the cap shape AND the remainder's bucket
    — warmup/bundling must cover both."""
    lanes = _ACTIVE.lane_buckets
    cap = lanes[-1]
    if n <= cap:
        return (bucket_for_lanes(n),)
    out = {cap}
    rem = n % cap
    if rem:
        out.add(bucket(rem, lanes))
    return tuple(sorted(out))


def chunk_bucket(b: int, devices: tuple) -> int:
    """Lane bucket for a dispatch chunk: next size bucket, rounded up so
    each chip of a mesh takes an equal contiguous slab (power-of-two
    buckets already divide power-of-two meshes).  Past the single-device
    lane cap — a multi-device dispatch chunks at ``cap x mesh`` — the
    global shape is the per-device bucket times the mesh, so every shard
    is itself a compiled bucket shape."""
    lanes = _ACTIVE.lane_buckets
    nd = len(devices)
    if nd > 1 and b > lanes[-1]:
        per = bucket((b + nd - 1) // nd, lanes)
        return per * nd
    bb = bucket(b, lanes)
    if nd > 1:
        bb += (-bb) % nd
    return bb


def snap_lane_cap(n: int) -> int:
    """Largest lane bucket <= n (cap at the largest bucket): a
    size-flushed scheduler batch must exactly fill a shape the kernel
    already compiles, never force a new one.  Values BELOW the smallest
    bucket are honored exactly — any batch that small pads into the
    smallest shape regardless, so the operator's latency intent wins."""
    lanes = _ACTIVE.lane_buckets
    n = max(1, int(n))
    if n <= lanes[0]:
        return n
    snapped = lanes[0]
    for b in lanes:
        if b <= n:
            snapped = b
    return snapped


def mesh_occupancy(n_lanes: int, n_devices: int = 1) -> float:
    """Fraction of the padded compiled shape(s) a batch of ``n_lanes``
    actually fills — the bench's mesh-occupancy figure.  The dispatch
    chunks at the lane cap; each chunk pads up to its bucket (rounded to
    the mesh size), so occupancy = real lanes / padded lanes."""
    if n_lanes <= 0:
        return 0.0
    n_devices = max(1, int(n_devices))
    devices = tuple(range(n_devices))
    # a mesh widens the chunk cap: one sharded dispatch carries a
    # cap-sized slab PER DEVICE, and occupancy is judged against the
    # full-mesh padded shape (not per device)
    cap = _ACTIVE.lane_buckets[-1] * n_devices
    padded = 0
    for start in range(0, n_lanes, cap):
        c = min(start + cap, n_lanes) - start
        padded += chunk_bucket(c, devices if n_devices > 1 else ())
    return n_lanes / padded if padded else 0.0


def window_blocks(base_blocks: int, lanes_per_block: int) -> int:
    """Blocks the blocksync accumulator should stage per verify window
    so ONE sharded dispatch fills the whole mesh.  Without a mesh the
    configured window stands.  With one, the window's lane count snaps
    up to ``mesh_size x lane_bucket``: the per-device share of the base
    window rounds to its bucket, and the window grows (never shrinks) to
    the block count whose lanes fill that full-mesh shape."""
    base_blocks = max(1, int(base_blocks))
    nd = mesh_size(_ACTIVE)
    if nd <= 1 or lanes_per_block <= 0:
        return base_blocks
    lanes = base_blocks * lanes_per_block
    per = bucket_for_lanes((lanes + nd - 1) // nd)
    full = per * nd
    # snap from BELOW: one block past the full-mesh shape would spill
    # into a second padded dispatch and halve occupancy
    return max(base_blocks, full // lanes_per_block)


# --------------------------------------------- compile-bucket enumeration


def enumerate_buckets(plan: DevicePlan | None = None,
                      kinds: tuple | None = None) -> list[CompileBucket]:
    """Every compiled shape the plan's warm set implies — the bundle
    build list and the /status per-bucket ledger.  ``kinds`` restricts
    (the CI smoke bundles only the cheap merkle kernel; a production
    node bundles the verify/RLC shapes too)."""
    plan = plan or _ACTIVE
    want = kinds if kinds is not None else (
        tuple(plan.warm_kinds)
        + (("merkle_level",) if plan.warm_merkle else ())
        + (("tables", "gather", "rlc_gather") if plan.warm_tables
           else ())
        + (("bls_agg",) if plan.warm_bls else ()))
    out: list[CompileBucket] = []
    for kind in plan.warm_kinds:
        if kind not in want:
            continue
        for lanes in plan.warm_lanes:
            for nb in plan.warm_blocks:
                out.append(CompileBucket(kind, lanes, nb))
    # the cached-valset route (the real commit hot path): one table
    # build per row bucket plus every gather shape it feeds
    for rows in plan.warm_tables:
        if "tables" in want:
            out.append(CompileBucket("tables", 0, table_rows=rows))
        for kind in ("gather", "rlc_gather"):
            if kind not in want:
                continue
            for lanes in plan.warm_lanes:
                for nb in plan.warm_blocks:
                    out.append(CompileBucket(kind, lanes, nb,
                                             table_rows=rows))
    if "bls_agg" in want:
        for rows in plan.warm_bls:
            out.append(CompileBucket("bls_agg", 0, table_rows=rows))
    if "merkle_level" in want:
        for lanes in (plan.warm_merkle or plan.merkle_buckets):
            out.append(CompileBucket("merkle_level", lanes))
    return out


def plan_hash(plan: DevicePlan | None = None) -> str:
    """Stable fingerprint of the DECLARATIVE plan fields (no device or
    jax state — ``aotbundle.bundle_version`` folds those in).  Changing
    any bucket table, threshold, or the warm set changes the hash, so a
    bundle built under a different plan can never be loaded."""
    plan = plan or _ACTIVE
    doc = {
        "lane_buckets": list(plan.lane_buckets),
        "block_buckets": list(plan.block_buckets),
        "table_buckets": list(plan.table_buckets),
        "merkle_buckets": list(plan.merkle_buckets),
        "bls_buckets": list(plan.bls_buckets),
        "rlc_min_lanes": plan.rlc_min_lanes,
        "warm": [b.key for b in enumerate_buckets(plan)],
        "mesh_axis": plan.mesh_axis,
    }
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()[:16]


def describe(plan: DevicePlan | None = None) -> dict:
    """Operator surface (/status, bundle header): the plan's shape plus
    the live runtime registers it drives."""
    plan = plan or _ACTIVE
    from . import batch as _b

    return {
        "hash": plan_hash(plan),
        "lane_buckets": list(plan.lane_buckets),
        "block_buckets": list(plan.block_buckets),
        "table_buckets": list(plan.table_buckets),
        "merkle_buckets": list(plan.merkle_buckets),
        "bls_buckets": list(plan.bls_buckets),
        "rlc_min_lanes": plan.rlc_min_lanes,
        "min_device_lanes": _b.TpuBatchVerifier.MIN_DEVICE_LANES,
        "mesh_devices": len(_DEVICES) if _DEVICES is not None else None,
        "mesh_axis": plan.mesh_axis,
        "mesh_shape": list(plan.mesh_shape),
        "mesh_size": mesh_size(plan),
        "warm_buckets": [b.key for b in enumerate_buckets(plan)],
    }
