"""BLS aggregate-commit verification: the orchestrator between
``types/validation.py`` and the BLS backends/kernels.

The fast path this module owns (ISSUE 18 tentpole): a commit's BLS
for-block cohort arrives as ONE aggregate G2 signature plus a signer
bitmap (``types/commit.py``), and verifying it costs two pairings plus a
G1 pubkey fold — instead of one signature verification per validator.
The fold is where the time goes at 10k validators, so it is engineered
like the Ed25519 dense path:

- **Per-valset table** (:func:`valset_table`): every cohort pubkey is
  decompressed + subgroup-checked ONCE (``bls12381.pk_to_affine``) and
  cached on the validator set itself (``vals.__dict__['_bls_agg_tbl']``
  — popped by ``update_with_change_set`` exactly like the dense
  columns), together with the full-cohort affine sum.
- **Complement fold**: a healthy commit carries most of the cohort, so
  the aggregate pubkey is computed as ``full_sum - sum(absentees)``
  (affine negation is one field subtraction) — O(missing) point
  additions instead of O(signers).
- **Device route**: when the plan declares ``bls_agg`` compile buckets
  (``plan.warm_bls`` / ``plan.bls_buckets``), the masked fold dispatches
  the ``ops/blsg1`` kernel through the same AOT-bundle lookup and
  wedge-protected device call as the Ed25519 kernels; any failure or
  timeout falls back to the host fold.  Default plans declare none —
  the host fold is already sub-millisecond and the kernel is a
  multi-minute XLA compile.

Observability: ``crypto_bls_*`` metrics (documented in
docs/explanation/observability.md) — verify wall time by route, call
results, lanes folded, table builds.
"""

from __future__ import annotations

import functools
import time
from typing import NamedTuple

from . import bls12381 as _bls


class AggTable(NamedTuple):
    """Per-valset aggregation table (all derived once, cached on the
    set): ``affine`` maps cohort valset index -> 96-byte affine pubkey;
    ``full`` is the whole-cohort affine sum (None when the cohort sums
    to infinity or is empty); the numpy columns back the vectorized
    commit checks in types/validation.py — ``cohort_mask`` bool (N,),
    ``addr_mat`` uint8 (N, 20) with cohort rows filled, ``powers``
    int64 (N,).  ``neg`` memoizes negated cohort points for the
    complement fold (filled lazily — absentee churn is tiny between
    commits, so steady state re-negates almost nothing)."""

    affine: dict
    full: bytes | None
    cohort_mask: object
    addr_mat: object
    powers: object
    neg: dict


@functools.cache
def _metrics():
    from ..libs import metrics as m

    return (
        m.histogram("crypto_bls_verify_seconds",
                    "wall time of one aggregate-commit verification "
                    "(pubkey fold + two pairings), labeled by fold route"),
        m.counter("crypto_bls_verify_total",
                  "aggregate-commit verifications by result"),
        m.counter("crypto_bls_lanes_total",
                  "commit lanes proven via the aggregate (signatures "
                  "that never became individual verify lanes)"),
        m.counter("crypto_bls_table_builds_total",
                  "per-valset cohort table builds (pk decompress + "
                  "subgroup check, full-cohort sum)"),
    )


def valset_table(vals) -> AggTable:
    """The per-valset :class:`AggTable`, built once and cached on the
    set.  Raises ValueError if a cohort pubkey fails decompression or
    the subgroup check — such a validator could never have entered a
    correct valset."""
    tbl = vals.__dict__.get("_bls_agg_tbl")
    if tbl is None:
        import numpy as np

        idx, pks = vals.bls_cohort()
        affine = {i: _bls.pk_to_affine(pk) for i, pk in zip(idx, pks)}
        full = None
        if affine:
            try:
                full = _bls.aggregate_affine(list(affine.values()))
            except ValueError:
                # a cohort summing to infinity is a (contrived) valid
                # set; the complement fold just stays unavailable
                full = None
        n = vals.size()
        cohort_mask = np.zeros((n,), np.bool_)
        addr_mat = np.zeros((n, 20), np.uint8)
        powers = np.zeros((n,), np.int64)
        for i, val in enumerate(vals.validators):
            powers[i] = val.voting_power
            if i in affine:
                cohort_mask[i] = True
                addr_mat[i] = np.frombuffer(val.address, np.uint8)
        tbl = AggTable(affine, full, cohort_mask, addr_mat, powers, {})
        vals.__dict__["_bls_agg_tbl"] = tbl
        _metrics()[3].inc(lanes=str(_lanes_bucket(len(affine))))
    return tbl


def _lanes_bucket(n: int) -> int:
    from . import plan as _plan

    return _plan.bucket(max(1, n), _plan.active().bls_buckets)


def _device_fold(vals, tbl, signer_rows) -> bytes | None:
    """Masked fold on the accelerator: one ``bls_agg:<rows>`` dispatch
    over the valset's padded cohort table.  Returns the affine aggregate
    pubkey, None when the route is unavailable (no bucket declared, no
    kernel warm, device busy/wedged) or the sum is infinity — callers
    fall back to the host fold / reject."""
    from . import aotbundle, batch as _b, plan as _plan

    affine = tbl.affine
    if not _plan.active().warm_bls:
        return None
    rows = _lanes_bucket(len(affine))
    key = f"bls_agg:{rows}"
    fn = aotbundle.lookup(key)
    if fn is None:
        return None
    import numpy as np

    # keyed off the AggTable identity, not just the bucket size: a valset
    # change rebuilds the AggTable (update_with_change_set pops both
    # caches), and a stale point table must never survive it — folding
    # rotated-out pubkeys yields a wrong aggregate pubkey
    cached = vals.__dict__.get("_bls_dev_tbl")
    if cached is None or cached[0] is not tbl or cached[1] != rows:
        from ..ops import blsg1

        pts = np.zeros((rows, 2, blsg1.NLIMB), np.int32)
        order = sorted(affine)        # valset index -> table row
        for r, i in enumerate(order):
            pts[r] = blsg1.limbs_from_xy(affine[i])
        cached = (tbl, rows, order, pts)
        vals.__dict__["_bls_dev_tbl"] = cached
    _, _, order, pts = cached
    row_of = {i: r for r, i in enumerate(order)}
    mask = np.zeros((rows,), np.int32)
    for i in signer_rows:
        r = row_of.get(i)
        if r is None:
            return None     # table out of sync: fall back to the host fold
        mask[r] = 1

    t0 = time.perf_counter()
    out = _b._device_call(lambda: np.asarray(fn(pts, mask)))
    if out is None:
        return None
    _b._note_dispatch("bls_agg", rows, time.perf_counter() - t0)
    from ..ops import blsg1

    return blsg1.xy_from_projective(out)


def verify_commit_aggregate(vals, signer_indices, msg: bytes,
                            agg_sig: bytes) -> bool:
    """Verify one commit's aggregate lane block: ``signer_indices`` are
    valset indices (the decoded bitmap) — either an iterable of ints or
    a numpy bool mask of shape (valset size,) (the vectorized path in
    types/validation.py hands the mask straight through, so the hot
    path never materializes a per-signer Python list).  ``msg`` is the
    shared zero-timestamp sign bytes, ``agg_sig`` the 96-byte
    aggregate.  Returns False — never raises — on any crypto failure,
    including a signer outside the valset's BLS cohort."""
    import numpy as np

    hist, calls, lanes, _ = _metrics()
    t0 = time.perf_counter()
    route = "host"
    try:
        tbl = valset_table(vals)
    except ValueError:
        calls.inc(result="bad_table")
        return False
    affine, full = tbl.affine, tbl.full
    if isinstance(signer_indices, np.ndarray):
        mask = signer_indices
        n_signers = int(mask.sum())
        if (not n_signers or mask.shape != tbl.cohort_mask.shape
                or bool((mask & ~tbl.cohort_mask).any())):
            calls.inc(result="bad_signer")
            return False
        signers = None          # materialized lazily, off the hot path
        missing = [int(i) for i in np.nonzero(tbl.cohort_mask & ~mask)[0]]
    else:
        signers = list(signer_indices)
        n_signers = len(signers)
        if not signers or any(i not in affine for i in signers):
            calls.inc(result="bad_signer")
            return False
        missing = sorted(set(affine) - set(signers))
    try:
        from . import plan as _plan

        if signers is None and _plan.active().warm_bls:
            signers = [int(i) for i in np.nonzero(mask)[0]]
        agg_pk = (_device_fold(vals, tbl, signers)
                  if signers is not None else None)
        if agg_pk is not None:
            route = "device"
        else:
            if full is not None and len(missing) < n_signers:
                # complement fold: full-cohort sum minus the absentees
                neg = tbl.neg
                for i in missing:
                    if i not in neg:
                        neg[i] = _bls.negate_affine(affine[i])
                pts = [full] + [neg[i] for i in missing]
            else:
                if signers is None:
                    signers = [int(i) for i in np.nonzero(mask)[0]]
                pts = [affine[i] for i in signers]
            agg_pk = _bls.aggregate_affine(pts) if len(pts) > 1 else pts[0]
        ok = _bls.verify_aggregate_affine(agg_pk, msg, agg_sig)
    except ValueError:
        # aggregate pubkey is the point at infinity (cancelling cohort)
        # or a malformed signature: reject, never crash the verify path
        ok = False
    hist.observe(time.perf_counter() - t0, route=route)
    calls.inc(result="ok" if ok else "bad_signature")
    if ok:
        lanes.inc(n_signers)
    return ok
