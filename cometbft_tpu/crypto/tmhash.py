"""SHA-256 hash helpers (reference: ``crypto/tmhash/hash.go``)."""

from __future__ import annotations

import hashlib

SIZE = 32
TRUNCATED_SIZE = 20


def sum_sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def sum_truncated(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()[:TRUNCATED_SIZE]
