"""ASCII armor for keys and other binary payloads (reference:
``crypto/armor/armor.go`` — OpenPGP-style blocks with headers and a
CRC-24 integrity trailer)."""

from __future__ import annotations

import base64
import textwrap

_CRC24_INIT = 0xB704CE
_CRC24_POLY = 0x1864CFB


def _crc24(data: bytes) -> int:
    crc = _CRC24_INIT
    for b in data:
        crc ^= b << 16
        for _ in range(8):
            crc <<= 1
            if crc & 0x1000000:
                crc ^= _CRC24_POLY
    return crc & 0xFFFFFF


class ArmorError(Exception):
    pass


def encode_armor(block_type: str, headers: dict[str, str],
                 data: bytes) -> str:
    """armor.go EncodeArmor."""
    lines = [f"-----BEGIN {block_type}-----"]
    for k, v in sorted(headers.items()):
        lines.append(f"{k}: {v}")
    lines.append("")
    body = base64.b64encode(data).decode()
    lines.extend(textwrap.wrap(body, 64) or [""])
    crc = base64.b64encode(_crc24(data).to_bytes(3, "big")).decode()
    lines.append(f"={crc}")
    lines.append(f"-----END {block_type}-----")
    return "\n".join(lines) + "\n"


def decode_armor(text: str) -> tuple[str, dict[str, str], bytes]:
    """armor.go DecodeArmor -> (block_type, headers, data)."""
    lines = [ln.rstrip("\r") for ln in text.strip().splitlines()]
    if not lines or not lines[0].startswith("-----BEGIN ") or \
            not lines[0].endswith("-----"):
        raise ArmorError("missing armor begin line")
    block_type = lines[0][len("-----BEGIN "):-len("-----")]
    if lines[-1] != f"-----END {block_type}-----":
        raise ArmorError("missing or mismatched armor end line")
    headers: dict[str, str] = {}
    i = 1
    while i < len(lines) - 1 and lines[i]:
        if ":" not in lines[i]:
            break
        k, _, v = lines[i].partition(":")
        headers[k.strip()] = v.strip()
        i += 1
    if i < len(lines) - 1 and not lines[i]:
        i += 1
    body_lines = []
    crc_line = None
    for ln in lines[i:-1]:
        if ln.startswith("="):
            crc_line = ln[1:]
        else:
            body_lines.append(ln)
    try:
        data = base64.b64decode("".join(body_lines), validate=True)
    except Exception as e:
        raise ArmorError(f"bad armor body: {e}")
    if crc_line is not None:
        try:
            want = int.from_bytes(base64.b64decode(crc_line,
                                                   validate=True), "big")
        except Exception as e:
            raise ArmorError(f"bad armor CRC trailer: {e}")
        if _crc24(data) != want:
            raise ArmorError("armor CRC mismatch")
    return block_type, headers, data


def armor_priv_key(key_bytes: bytes, key_type: str) -> str:
    """Keyfile armor (the reference pairs this with bcrypt+xsalsa20
    encryption in the keyring; plaintext armor is the crypto/armor layer)."""
    return encode_armor("TENDERMINT PRIVATE KEY",
                        {"type": key_type, "kdf": "none"}, key_bytes)


def unarmor_priv_key(text: str) -> tuple[bytes, str]:
    block_type, headers, data = decode_armor(text)
    if block_type != "TENDERMINT PRIVATE KEY":
        raise ArmorError(f"unexpected block type {block_type!r}")
    return data, headers.get("type", "")
