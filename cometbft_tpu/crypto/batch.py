"""Batch signature verification: the TPU execution backend's seam.

Mirrors ``crypto.BatchVerifier`` (``crypto/crypto.go:44-52``) and the
dispatch in ``crypto/batch/batch.go:10-32``, with the 'tpu' backend the
reference lacks (the north-star of BASELINE.json): signatures accumulate
into dense numpy arrays, pad into (batch, hash-block) *buckets* so XLA
compiles a handful of shapes once, and verify on-device via the vmap'd
ZIP-215 kernel (``ops/ed25519.py``).  Lanes padded to fill a bucket repeat
lane 0 and are sliced away on return.

Unlike the reference — whose batch path refuses mixed key types
(``types/validation.go:18``) — the dispatcher here routes ed25519 lanes to
the device and anything else to per-signature CPU verification, merging
results positionally.

Backend selection: ``create_batch_verifier(backend=...)`` with "auto"
choosing the device backend iff an accelerator is present (the
``config.Config``-driven selection point; falls back to CPU like the
reference's pure-Go path).

Since r13 the bucket tables, device set and routing thresholds are
owned by the declarative device plan (``crypto/plan.py``) — shared with
the coalescing scheduler — and every unpinned single-device dispatch
consults the AOT compile bundle (``crypto/aotbundle.py``) before the
jit caches, so a node booted from a prewarmed bundle runs its first
dispatch at warm latency.
"""

from __future__ import annotations

import functools
import threading
import time
from abc import ABC, abstractmethod

import numpy as np

from . import plan as _plan
from .keys import ED25519_KEY_TYPE, PubKey, verify_ed25519_zip215

# Bucket tables live in the declarative device plan (crypto/plan.py)
# since r13 — one layer owns the lane/block/table bucketing, the device
# set, and the routing thresholds for BOTH this module and the
# coalescing scheduler.  The names below are READ-ONLY aliases of the
# plan DEFAULTS for existing readers (bench, tests); dispatch reads the
# ACTIVE plan, so assigning to these is a no-op — install a plan via
# plan.set_plan/configure to change bucketing.
_LANE_BUCKETS = _plan.LANE_BUCKETS
_TABLE_BUCKETS = _plan.TABLE_BUCKETS
_BLOCK_BUCKETS = _plan.BLOCK_BUCKETS


class BatchVerifier(ABC):
    """Accumulate (pubkey, msg, sig) triples; verify all at once.

    ``verify()`` returns ``(all_ok, per_sig)`` like the reference's
    ``BatchVerifier.Verify`` (crypto/crypto.go:50-51).
    """

    @abstractmethod
    def add(self, pub: PubKey, msg: bytes, sig: bytes) -> None: ...

    @abstractmethod
    def verify(self) -> tuple[bool, list[bool]]: ...

    def __len__(self) -> int:
        return getattr(self, "_count", 0)


class CpuBatchVerifier(BatchVerifier):
    """Host fallback, used when no accelerator is present.

    ed25519 lanes verify through the native (C++) RLC batch verifier —
    one Pippenger multiscalar multiplication over the whole batch, ~5x a
    single-verify loop, matching the reference's curve25519-voi batch
    path (``crypto/ed25519/ed25519.go:188-221``).  On batch failure (or
    when the native lib is unavailable) lanes verify one by one; other
    key types always verify per-signature."""

    def __init__(self):
        self._items: list[tuple[PubKey, bytes, bytes]] = []

    def add(self, pub, msg, sig):
        self._items.append((pub, bytes(msg), bytes(sig)))

    @property
    def _count(self):
        return len(self._items)

    def verify(self):
        import time as _time

        hist, lanes, calls = _metrics()
        t0 = _time.perf_counter()
        try:
            return self._verify()
        finally:
            hist.observe(_time.perf_counter() - t0, backend="cpu")
            calls.inc(backend="cpu")

    def _verify(self):
        _, lanes, _ = _metrics()
        n = len(self._items)
        oks = [False] * n
        ed_idx = [i for i, (p, _, s) in enumerate(self._items)
                  if p.type() == ED25519_KEY_TYPE and len(s) == 64]
        ed_set = set(ed_idx)
        for i, (p, m, s) in enumerate(self._items):
            if i not in ed_set:
                oks[i] = p.verify_signature(m, s)
        ed_oks = _host_verify_ed25519(
            [self._items[i] for i in ed_idx], lanes, route="cpu")
        for j, i in enumerate(ed_idx):
            oks[i] = ed_oks[j]
        lanes.inc(n - len(ed_idx), route="cpu")
        return all(oks) and n > 0, oks


def _host_verify_ed25519(items, lanes_metric, route: str) -> list[bool]:
    """Host verification of ed25519 lanes (32-byte pubs, 64-byte sigs
    pre-filtered): one native C++ RLC batch when the whole batch is valid
    (the common case), falling back to per-signature verification to
    localize failures — or when the native lib is unavailable.  Shared by
    the CPU backend and every TpuBatchVerifier host-fallback path.
    Successful batches feed the throughput router's host estimate."""
    import time as _time

    from . import _native_ed25519 as _nat

    # >= 2 lanes: one RLC multiscalar beats OpenSSL's asm single verify
    if len(items) >= 2:
        t0 = _time.perf_counter()
        batched = _nat.batch_verify([p.bytes() for p, _, _ in items],
                                    [m for _, m, _ in items],
                                    [s for _, _, s in items])
        if batched:
            _ROUTER.observe("host", len(items), _time.perf_counter() - t0)
            lanes_metric.inc(len(items), route=route + "_batch")
            return [True] * len(items)
    lanes_metric.inc(len(items), route=route)
    return [p.verify_signature(m, s) for p, m, s in items]


# one copy of the bucket math, in the plan layer
_bucket = _plan.bucket


@functools.cache
def _compiled_verify():
    """The jitted kernel; jax.jit's own cache handles per-(batch, nb) shapes.

    The persistent on-disk XLA cache is enabled here — in the LIBRARY, not
    just the test conftest — so a node's first verification at a new
    bucket shape pays the multi-minute compile exactly once per machine,
    not once per process (VERDICT r1 weak-point 5)."""
    import jax

    from ..ops import ed25519 as _kernel

    _jit_env()
    return jax.jit(_kernel.verify_padded)


@functools.cache
def _compiled_verify_sharded(devices: tuple):
    """ONE sharded program of the verify kernel over a 1-D mesh of
    ``devices`` (SURVEY §2.10: verification is data-parallel over lanes,
    so the step is collective-free and scales linearly over ICI).
    Shardings + donation come from the plan's labels via the mesh
    authority.  Cached per device tuple; jit's cache handles shapes."""
    from ..parallel.mesh import sharded_kernel

    _jit_env()
    return sharded_kernel("verify", list(devices))


def _jit_env():
    """Every jit entry point must harden a CPU-pinned process against
    the wedgeable accelerator factory AND enable the persistent XLA
    cache (VERDICT r1 weak-point 5) before first backend init."""
    from ..jaxenv import enable_compile_cache, harden_cpu_pinned_env

    harden_cpu_pinned_env()
    try:
        enable_compile_cache()
    except Exception:
        pass                 # cache dir unwritable: compile-only


@functools.cache
def _compiled_prepare_tables():
    import jax

    from ..ops import ed25519 as _kernel

    _jit_env()
    return jax.jit(_kernel.prepare_pubkey_tables)


@functools.cache
def _compiled_rlc():
    """jit of the one-shot RLC batch verdict (ops/rlc.py)."""
    import jax

    from ..ops import rlc as _r

    _jit_env()
    return jax.jit(_r.verify_batch_rlc)


@functools.cache
def _compiled_rlc_gather():
    """jit of the RLC verdict through a cached whole-valset table."""
    import jax

    from ..ops import rlc as _r

    _jit_env()
    return jax.jit(_r.verify_batch_rlc_gather)


@functools.cache
def _compiled_rlc_sharded(devices: tuple):
    """jit of the lane-sharded RLC verdict over a device mesh: each chip
    reduces its own lane shard to per-window partial sums, a replicated
    add_cc tree folds the D partials, one chip-replicated ladder
    finishes — O(windows) cross-chip points per verdict (the reduction
    the old single-device gate forbade)."""
    from ..parallel.mesh import sharded_kernel

    _jit_env()
    return sharded_kernel("rlc", list(devices))


@functools.cache
def _compiled_rlc_gather_sharded(devices: tuple):
    """Sharded RLC through a replicated cached valset table."""
    from ..parallel.mesh import sharded_kernel

    _jit_env()
    return sharded_kernel("rlc_gather", list(devices))


# RLC dispatch threshold: batches with at least this many ed25519 lanes
# try the one-shot random-linear-combination kernel first (~3x less
# group-op work than the per-lane ladder; all-or-nothing verdict) and
# fall back to the per-lane kernel only to localize a rejection —
# mirroring the native CPU path's batch->single fallback.  Below the
# threshold the per-lane kernel runs directly: tiny batches don't
# amortize the extra compiled shape, and tests keep their compile
# budget.  Multi-device meshes use the lane-sharded RLC variant
# (device-local partial sums + a replicated fold of O(windows) points
# per verdict — ``ops/rlc.py make_verify_batch_rlc_sharded``), so a
# multi-chip host no longer falls back to the ~3x-slower per-lane
# kernel for large all-valid batches.  The threshold lives in the
# device plan since r13.


def _rlc_min_lanes() -> int:
    return _plan.active().rlc_min_lanes


def set_rlc_min_lanes(n: int) -> None:
    """Config hook: minimum ed25519 lanes before the RLC fast path
    (delegates to the device plan — the one routing layer)."""
    _plan.configure(rlc_min_lanes=max(1, int(n)))


def _rlc_args(bb: int, b: int):
    """Coefficient limbs for a padded chunk: fresh CSPRNG draws on the
    ``b`` active lanes, z = 0 on the padding."""
    from ..ops import rlc as _r

    return _r.host_rlc_coeffs(bb, active_mask=np.arange(bb) < b)


@functools.cache
def _compiled_verify_gather(devices: tuple):
    """jit of the cached-table verify: the whole-valset table is
    replicated (every chip gathers its own lanes' rows), the per-lane
    args shard on the lane axis.  devices=() compiles for the default
    single device."""
    import jax

    from ..ops import ed25519 as _kernel

    _jit_env()
    if len(devices) <= 1:
        return jax.jit(_kernel.verify_padded_gather)
    from ..parallel.mesh import sharded_kernel

    return sharded_kernel("gather", list(devices))


# Whole-validator-set device tables, keyed by the identity of the
# valset's cached pubkey matrix (regenerated on membership changes, so
# identity == valset version).  Entries hold a strong ref to the matrix,
# making id() reuse impossible while cached.
_VALSET_TABLES: "dict" = {}
_VALSET_TABLES_MAX = 4
_WARMUP_ACTIVE = False           # warmup_device in progress (executor)
_WARMUP_ARRAYS: list = []        # pubkey matrices owned by warmup


def _valset_tables(pubs_full, devices: tuple):
    """Device [j](-A) tables + ok mask for a full validator set, padded
    to the lane bucket; cached so consecutive commits from the same set
    skip decompression and table building on device."""
    key = (id(pubs_full), devices)
    ent = _VALSET_TABLES.get(key)
    if ent is not None and ent[0] is pubs_full:
        return ent[1], ent[2], ent[3]
    n = pubs_full.shape[0]
    nb = _bucket(n, _plan.active().table_buckets)
    if len(devices) > 1:
        nb += (-nb) % len(devices)
    padded = np.zeros((nb, 32), np.int32)
    padded[:n] = pubs_full
    padded[n:] = pubs_full[0] if n else 0
    if len(devices) == 1:
        # pinned single chip: build the table THERE, not on the default
        padded = _timed_put(padded, devices[0])
    fn = None
    if not devices:
        # unpinned default-device build: a bundled table kernel skips
        # the trace+compile on the first valset of a warm-booted node
        from . import aotbundle as _aot

        fn = _aot.lookup(f"tables:{nb}")
    if fn is None:
        fn = _compiled_prepare_tables()
    t0 = time.perf_counter()
    tab, ok = fn(padded)
    try:
        # force completion so the timing covers the table-build kernel,
        # not just its enqueue (runs once per valset, not per batch)
        import jax

        jax.block_until_ready((tab, ok))
    except Exception:
        pass
    _note_dispatch("tables", nb, time.perf_counter() - t0)
    while len(_VALSET_TABLES) >= _VALSET_TABLES_MAX:
        # evict warmup-owned entries first; while warmup itself is
        # running, a real commit's concurrently-inserted table must
        # never be evicted to make room (the cache may exceed its cap
        # until warmup's cleanup drops the fake matrices)
        victim = next(
            (k for k, ent in _VALSET_TABLES.items()
             if any(ent[0] is a for a in _WARMUP_ARRAYS)), None)
        if victim is None:
            if _WARMUP_ACTIVE:
                break
            victim = next(iter(_VALSET_TABLES))
        _VALSET_TABLES.pop(victim)
    _VALSET_TABLES[key] = (pubs_full, tab, ok, nb)
    return tab, ok, nb


def device_verify_ed25519_cached(valset_pubs, scope, pubs_rows, rs, ss,
                                 msgs, msg_lens, device=None) -> np.ndarray:
    """Dense verify through the per-valset table cache: like
    :func:`device_verify_ed25519` but A decompression + table building
    happen once per validator set, not once per batch.  ``scope`` (B,)
    are validator indices into ``valset_pubs``; ``pubs_rows`` (B,32) are
    the gathered pubkey bytes (still needed for the R||A||M hash)."""
    b = pubs_rows.shape[0]
    if b == 0:
        return np.zeros((0,), bool)
    devices = _resolve_devices(device)
    tab, ok, n_pad = _valset_tables(valset_pubs, devices)
    place = _single_device_place(device, devices)
    results = np.zeros((b,), bool)
    # a mesh multiplies the chunk cap: one sharded dispatch carries a
    # cap-sized lane slab per device
    cap = _plan.active().lane_buckets[-1] * max(1, len(devices))
    for start in range(0, b, cap):
        end = min(start + cap, b)
        c = end - start
        sl = slice(start, end)
        bb = _chunk_bucket(c, devices)
        _, r32, s32, blocks, active = _padded_lane_args(
            pubs_rows[sl], rs[sl], ss[sl], msgs[sl], msg_lens[sl], bb)
        idx = np.zeros((bb,), np.int32)
        idx[:c] = np.asarray(scope[sl], np.int32)
        idx[c:] = idx[0]
        nb_blocks = blocks.shape[1]
        _note_mesh(devices, c, bb)
        if c >= _rlc_min_lanes():
            # steady-state fast path: one RLC verdict over the cached
            # tables (lane-sharded over a multi-chip mesh); a reject
            # falls through to per-lane localization
            rl_args = (idx, r32, s32, blocks, active, _rlc_args(bb, c))
            if len(devices) > 1:
                rfn = _aot_fn_mesh(f"rlc_gather:{n_pad}", bb, nb_blocks,
                                   devices)
                if rfn is None:
                    rfn = _compiled_rlc_gather_sharded(devices)
                rkind = "rlc_gather_sharded"
            else:
                rkind = "rlc_gather"
                rfn = _aot_fn(f"rlc_gather:{n_pad}", bb, nb_blocks, place)
                if rfn is None:
                    rfn = _compiled_rlc_gather()
                    if place is not None:
                        rl_args = _timed_put(rl_args, place)
            t0 = time.perf_counter()
            verdict = bool(np.asarray(rfn(tab, ok, *rl_args)))
            _note_dispatch(rkind, bb, time.perf_counter() - t0)
            if verdict:
                _metrics()[1].inc(c, route="device_rlc" if len(devices) <= 1
                                  else "device_rlc_sharded")
                results[start:end] = True
                continue
        lane_args = (idx, r32, s32, blocks, active)
        if len(devices) > 1:
            fn = _aot_fn_mesh(f"gather:{n_pad}", bb, nb_blocks, devices)
            if fn is None:
                fn = _compiled_verify_gather(devices)
        else:
            fn = _aot_fn(f"gather:{n_pad}", bb, nb_blocks, place)
            if fn is None:
                fn = _compiled_verify_gather(devices)
                if place is not None:
                    lane_args = _timed_put(lane_args, place)
        t0 = time.perf_counter()
        out = np.asarray(fn(tab, ok, *lane_args))
        _note_dispatch("gather_sharded" if len(devices) > 1 else "gather",
                       bb, time.perf_counter() - t0)
        results[start:end] = out[:c]
    return results


# The device set and the bucket-selection math moved into the plan
# layer (r13); these names stay as the public seam callers already use
# (node wiring, dryrun_multichip, tests).
set_devices = _plan.set_devices
_resolve_devices = _plan.resolve_devices
bucket_for_lanes = _plan.bucket_for_lanes
buckets_for_batch = _plan.buckets_for_batch


def warmup_device(lane_buckets=(256, 1024), block_buckets=(2,),
                  device=None, valset_sizes=()) -> int:
    """Pre-compile BOTH verify kernels (plain and cached-table gather —
    the dense VerifyCommit path uses the latter) for the hot bucket
    shapes so the first real commit verification doesn't stall consensus
    for an XLA compile (node startup warmup; shapes beyond these hit the
    persistent cache or compile on demand).  ``valset_sizes`` warms the
    cached-gather route at REAL validator-set scale: the per-valset
    table pads to ``_TABLE_BUCKETS`` (which keeps growing past the lane
    cap), so a 10k-validator commit needs the (16384-row table,
    4096-lane chunk) gather shape — not covered by the square
    lane-bucket warmups below.  Returns the number of shapes compiled."""
    import numpy as np

    global _WARMUP_ACTIVE
    done = 0
    # Cleanup must drop only the tables built from warmup's OWN fake
    # valset matrices: a REAL commit can populate the cache concurrently
    # (warmup runs in an executor while the node syncs) and must not
    # lose its tables.  Entries are matched by the identity of the pubs
    # array they were built from — warmup keeps every matrix it passed,
    # and _valset_tables' eviction prefers warmup-owned victims (never
    # evicting a real entry while _WARMUP_ACTIVE).
    warm_arrays = _WARMUP_ARRAYS
    warm_arrays.clear()
    _WARMUP_ACTIVE = True
    try:
        for lanes in lane_buckets:
            for nb in block_buckets:
                pubs = np.zeros((lanes, 32), np.uint8)
                rs = ss = pubs
                # longest message that still fits nb SHA-512 blocks after
                # the 64-byte R||A prefix and 17 bytes of padding
                msg_len = nb * 128 - 64 - 17
                msgs = np.zeros((lanes, msg_len), np.uint8)
                lens = np.full((lanes,), msg_len, np.int64)
                scope = np.zeros((lanes,), np.int64)
                warm_arrays.append(pubs)
                try:
                    _device_verify_chunk(pubs, rs, ss, msgs, lens, device)
                    device_verify_ed25519_cached(pubs, scope, pubs, rs, ss,
                                                 msgs, lens, device)
                    done += 1
                except Exception:
                    return done
        for n_vals in valset_sizes:
            for nb in block_buckets:
                valset = np.zeros((n_vals, 32), np.uint8)
                rows = np.zeros((n_vals, 32), np.uint8)
                msg_len = nb * 128 - 64 - 17
                msgs = np.zeros((n_vals, msg_len), np.uint8)
                lens = np.full((n_vals,), msg_len, np.int64)
                scope = np.zeros((n_vals,), np.int64)
                warm_arrays.append(valset)
                try:
                    # drives the real dispatch: one table build at the
                    # n_vals TABLE bucket + every chunked gather shape
                    device_verify_ed25519_cached(valset, scope, rows,
                                                 rows, rows, msgs, lens,
                                                 device)
                    done += 1
                except Exception:
                    return done
    finally:
        _WARMUP_ACTIVE = False
        for k in list(_VALSET_TABLES):    # snapshot: concurrent inserts
            ent = _VALSET_TABLES.get(k)
            if ent is not None and any(ent[0] is a for a in warm_arrays):
                _VALSET_TABLES.pop(k, None)   # warmup matrices aren't real
        warm_arrays.clear()
    return done


def device_verify_ed25519(pubs: np.ndarray, rs: np.ndarray, ss: np.ndarray,
                          msgs: np.ndarray, msg_lens: np.ndarray,
                          device=None) -> np.ndarray:
    """Dense-array entry: verify B ed25519 signatures on device.

    pubs (B,32) u8; rs/ss (B,32) u8 (signature halves); msgs (B,L) u8 padded;
    msg_lens (B,).  Returns (B,) bool.  Pads lanes/blocks to bucket shapes.
    """
    b = pubs.shape[0]
    if b == 0:
        return np.zeros((0,), bool)
    results = np.zeros((b,), bool)
    # chunk anything beyond the largest bucket; a mesh multiplies the
    # cap — one sharded dispatch carries a cap-sized slab per device
    cap = _plan.active().lane_buckets[-1] * \
        max(1, len(_resolve_devices(device)))
    for start in range(0, b, cap):
        end = min(start + cap, b)
        results[start:end] = _device_verify_chunk(
            pubs[start:end], rs[start:end], ss[start:end],
            msgs[start:end], msg_lens[start:end], device)
    return results


_chunk_bucket = _plan.chunk_bucket


def _padded_lane_args(pubs, rs, ss, msgs, msg_lens, bb):
    """The lane/block padding protocol shared by the cached and uncached
    device routes: R||A||M hash-input assembly, lens padding, block
    bucketing, repeat-lane-0 fill, int32 byte matrices.  Returns
    ``(pub32, r32, s32, blocks, active)``."""
    from ..ops import sha512 as _sha

    b = pubs.shape[0]
    # hash input is R || A || M
    hin = np.zeros((bb, 64 + msgs.shape[1]), np.uint8)
    hin[:b, :32] = rs
    hin[:b, 32:64] = pubs
    hin[:b, 64:] = msgs
    lens = np.full((bb,), 64, np.int64)
    lens[:b] = 64 + np.asarray(msg_lens, np.int64)
    hin[b:] = hin[0]
    lens[b:] = lens[0]
    nb = _bucket(int(_sha.max_blocks_for_len(int(lens.max()))),
                 _plan.active().block_buckets)
    blocks, active = _sha.host_pad(hin, lens, nb)

    def pad(a):
        out = np.zeros((bb, 32), np.int32)
        out[:b] = a
        out[b:] = a[0] if b else 0          # repeat lane 0 into padding
        return out

    return pad(pubs), pad(rs), pad(ss), blocks, active


def _single_device_place(device, devices: tuple):
    """The chip a non-sharded dispatch must pin its arrays to: the
    caller's pin wins, else a configured 1-device set (set_devices must
    actually pin THAT chip), else None for the jit default."""
    if device is not None:
        return device
    return devices[0] if len(devices) == 1 else None


def _aot_fn(kind: str, bb: int, nb: int, place):
    """AOT compile-bundle consult for an unpinned single-device
    dispatch: a bucket loaded from the versioned on-disk bundle skips
    tracing, lowering AND compiling — the warm-boot path.  Pinned
    placements and meshes keep their sharded jits (the serialized
    executable is bound to the default device layout)."""
    if place is not None:
        return None
    from . import aotbundle as _aot

    return _aot.lookup(f"{kind}:{bb}x{nb}")


def _aot_fn_mesh(kind: str, bb: int, nb: int, devices: tuple):
    """AOT compile-bundle consult for a SHARDED dispatch: bundle keys
    carry an ``@m<D>`` mesh tag (and the bundle header records the mesh
    shape), so a serialized 4-device executable can never run on 8."""
    from . import aotbundle as _aot

    return _aot.lookup(f"{kind}:{bb}x{nb}@m{len(devices)}")


def _device_verify_chunk(pubs, rs, ss, msgs, msg_lens, device):
    b = pubs.shape[0]
    devices = _resolve_devices(device)
    bb = _chunk_bucket(b, devices)
    args = _padded_lane_args(pubs, rs, ss, msgs, msg_lens, bb)
    nb = args[3].shape[1]           # hash-block bucket of this dispatch
    _note_mesh(devices, b, bb)
    if len(devices) > 1:
        # production multi-chip path: ONE lane-sharded dispatch over the
        # mesh (no per-device fan-out) — RLC verdict first (device-local
        # partial sums, O(windows) cross-chip points), per-lane sharded
        # program to localize a rejection
        if b >= _rlc_min_lanes():
            rargs = args + (_rlc_args(bb, b),)
            rfn = _aot_fn_mesh("rlc", bb, nb, devices)
            if rfn is None:
                rfn = _compiled_rlc_sharded(devices)
            t0 = time.perf_counter()
            verdict = bool(np.asarray(rfn(*rargs)))
            _note_dispatch("rlc_sharded", bb, time.perf_counter() - t0)
            if verdict:
                _metrics()[1].inc(b, route="device_rlc_sharded")
                return np.ones((b,), bool)
        fn = _aot_fn_mesh("verify", bb, nb, devices)
        if fn is None:
            fn = _compiled_verify_sharded(devices)
        t0 = time.perf_counter()
        out = np.asarray(fn(*args))
        _note_dispatch("verify_sharded", bb, time.perf_counter() - t0)
        return out[:b]
    place = _single_device_place(device, devices)
    if b >= _rlc_min_lanes():
        # one-shot RLC verdict first (the all-valid common case); a
        # reject falls through to the per-lane ladder for localization
        rargs = args + (_rlc_args(bb, b),)
        rfn = _aot_fn("rlc", bb, nb, place)
        if rfn is None:
            rfn = _compiled_rlc()
            if place is not None:
                rargs = _timed_put(rargs, place)
        t0 = time.perf_counter()
        verdict = bool(np.asarray(rfn(*rargs)))
        _note_dispatch("rlc", bb, time.perf_counter() - t0)
        if verdict:
            _metrics()[1].inc(b, route="device_rlc")
            return np.ones((b,), bool)
    fn = _aot_fn("verify", bb, nb, place)
    if fn is None:
        fn = _compiled_verify()
        if place is not None:
            args = _timed_put(args, place)
    t0 = time.perf_counter()
    out = np.asarray(fn(*args))
    _note_dispatch("verify", bb, time.perf_counter() - t0)
    return out[:b]


@functools.cache
def _metrics():
    """Registered once; cached so the hot verify path pays a dict hit."""
    from ..libs import metrics as m

    return (
        m.histogram("crypto_batch_verify_seconds",
                    "wall time of one BatchVerifier.verify() call"),
        m.counter("crypto_batch_lanes_total",
                  "signature lanes verified, by route (device/cpu)"),
        m.counter("crypto_batch_calls_total", "BatchVerifier.verify calls"),
    )


@functools.cache
def _mesh_metrics():
    """crypto_mesh_*: the sharded-dispatch observability surface — mesh
    width, how full each sharded dispatch runs, and how often dispatch
    takes the sharded vs the single-device program."""
    from ..libs import metrics as m

    return (
        m.gauge("crypto_mesh_devices",
                "devices the verify dispatch spans (1 = single-device)"),
        m.histogram(
            "crypto_mesh_dispatch_occupancy",
            "real lanes / padded full-mesh lanes, per sharded dispatch",
            buckets=(0.25, 0.5, 0.75, 0.85, 0.9, 0.95, 1.0)),
        m.counter("crypto_mesh_dispatch_total",
                  "verify dispatches by route (sharded vs single)"),
    )


def _note_mesh(devices: tuple, b: int, bb: int) -> None:
    """Record one dispatch chunk against the mesh series."""
    gauge, occ, total = _mesh_metrics()
    gauge.set(max(1, len(devices)))
    if len(devices) > 1:
        total.inc(1, route="sharded")
        if bb:
            occ.observe(b / bb)
    else:
        total.inc(1, route="single")


# -------------------------------------------------- kernel profiling hooks

@functools.cache
def _kprof():
    """Kernel-profiling series (tentpole: per-bucket compile visibility).

    ``crypto_kernel_first_dispatch_seconds{kind,lanes}`` records the wall
    time of the FIRST in-process dispatch of each compiled shape: a
    multi-second/minute value is a cold XLA compile, a value near the
    dispatch p50 means the persistent compile cache served it.  Later
    dispatches of a seen shape land in
    ``crypto_kernel_dispatch_seconds{kind}``; explicit host->device
    placements land in ``crypto_device_transfer_seconds``."""
    from ..libs import metrics as m

    return (
        m.gauge("crypto_kernel_first_dispatch_seconds",
                "first dispatch wall time per compiled shape "
                "(compile when cold, cache-hit when warm)"),
        m.counter("crypto_kernel_first_dispatch_total",
                  "compiled shapes first-dispatched in this process"),
        m.histogram("crypto_kernel_dispatch_seconds",
                    "device kernel dispatch latency (warm shapes)",
                    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                             0.05, 0.1, 0.25, 0.5, 1, 2.5)),
        m.histogram("crypto_device_transfer_seconds",
                    "host->device transfer latency (explicit device_put)",
                    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                             0.005, 0.01, 0.05, 0.1)),
    )


_SEEN_SHAPES: set = set()


def _note_dispatch(kind: str, lanes_bucket: int, seconds: float) -> None:
    """Record one compiled-kernel execution: the first (kind, bucket)
    sighting is the compile-or-cache gauge + a flight-recorder event,
    repeats are the warm dispatch histogram."""
    gauge, first, hist, _ = _kprof()
    key = (kind, lanes_bucket)
    if key not in _SEEN_SHAPES:
        _SEEN_SHAPES.add(key)
        gauge.set(seconds, kind=kind, lanes=str(lanes_bucket))
        first.inc(kind=kind)
        from ..libs import tracing

        tracing.event("crypto.kernel", "first_dispatch", kind=kind,
                      lanes=lanes_bucket, dur_us=int(seconds * 1e6))
    else:
        hist.observe(seconds, kind=kind)


def _timed_put(tree, place):
    """``jax.device_put`` with transfer timing.  With the flight
    recorder ON (deep-profiling opt-in) it blocks until the copy lands
    so the histogram measures the real transfer; with tracing off (the
    production default) it times only the enqueue — forcing a host sync
    on every hot-path placement would forfeit the transfer/dispatch
    overlap just to make a histogram prettier."""
    import jax

    from ..libs import tracing

    t0 = time.perf_counter()
    out = jax.device_put(tree, place)
    if tracing.is_enabled():
        try:
            jax.block_until_ready(out)
        except Exception:
            pass
    _kprof()[3].observe(time.perf_counter() - t0)
    return out


_DEVICE_WAIT_S = 2.0             # max time a verify waits on the device:
#   below the p2p pong timeout (5 s), so even the FIRST wedged dispatch
#   cannot make peers drop the node; a compile that outlasts the wait
#   finishes on the worker thread and the device resumes on a later batch
_DEVICE_POOL = None              # single dispatch thread owning the chip
_DEVICE_INFLIGHT = None          # last submitted future (may be stuck)
_DEVICE_SUBMIT_LOCK = threading.Lock()    # pool creation + submit order


def set_device_wait(seconds: float) -> None:
    """Config hook: bound on how long a verification waits for the
    accelerator before falling back to host crypto."""
    global _DEVICE_WAIT_S
    _DEVICE_WAIT_S = max(0.1, float(seconds))


@functools.cache
def _device_health():
    """Operator-facing device-health surface (VERDICT r3 weak 6): a
    gauge that flips 0 when verification is riding the device and 1
    while dispatches are being abandoned to host fallback, plus a
    counter of abandonments.  Cached like _metrics."""
    from ..libs import metrics as m

    return (
        m.gauge("crypto_device_degraded",
                "1 while device dispatches are abandoned (host fallback)"),
        m.counter("crypto_device_abandoned_total",
                  "device dispatches abandoned after the bounded wait"),
    )


_DEGRADED_LOGGED = False         # one-shot transition log, not per-batch
_PATIENT_PREV_LANES = 0          # lanes of the last patient dispatch: the
#   window the NEXT patient caller queues behind (double-buffer depth 2)
_DEVICE_INFLIGHT_DEADLINE = 0.0  # when the in-flight dispatch is overdue


def patient_wait_s(lanes: int) -> float:
    """How long a patient (catch-up) dispatch of ``lanes`` signatures
    may wait on the device before host fallback: the fail-fast bound
    plus the compute of its OWN window AND the window it queues behind
    (the previous patient submission — adjacent windows can be wildly
    asymmetric, so a small tail window must still wait out the deep one
    ahead of it), at a deliberately pessimistic throughput floor.  The
    timeout exists to catch a WEDGED device, not a busy one, so a deep
    accumulated window must never outrun it; the work term is capped so
    a real wedge during catch-up still falls back within a bounded
    delay on top of the configured fail-fast wait."""
    global _PATIENT_PREV_LANES
    floor_sigs_per_s = 1000.0
    total = lanes + _PATIENT_PREV_LANES
    _PATIENT_PREV_LANES = lanes
    return _DEVICE_WAIT_S * 2 + min(56.0, 2.0 * total / floor_sigs_per_s)


def _device_call(fn, patient: float = 0.0):
    """Run ``fn`` (a device dispatch) on the single device-owner thread,
    waiting at most ``_DEVICE_WAIT_S``.  Returns ``fn()``'s result, or
    None when the device is unavailable: a previous call is still running
    (possibly wedged in native code — it cannot be killed, only
    abandoned) or the bounded wait expired.  Callers fall back to host
    verification; if the abandoned call eventually completes, the device
    resumes on a later batch.  This keeps the consensus event loop from
    ever blocking on the accelerator — the TPU is a compute sidecar, not
    a liveness dependency.  Every abandonment increments
    ``crypto_device_abandoned_total`` and holds ``crypto_device_degraded``
    at 1 (with a one-shot log line on the transition) so a node that
    quietly became a CPU node is visible to operators.

    ``patient`` (seconds, 0 = off) is the blocksync accumulator's
    double-buffered staging mode: the caller is a catch-up worker
    thread, not the consensus loop, and WANTS to queue behind the
    window currently verifying on the device (that queuing is the
    transfer/compute overlap).  It skips the in-flight fast-fail and
    waits up to the given bound — sized by the CALLER to the work it
    submitted (:func:`patient_wait_s`), because a deep accumulated
    window legitimately needs many seconds of device compute and must
    not be misread as a wedge.  A genuinely wedged device still
    degrades to host when the bound expires."""
    global _DEVICE_POOL, _DEVICE_INFLIGHT, _DEGRADED_LOGGED, \
        _DEVICE_INFLIGHT_DEADLINE
    import concurrent.futures as cf

    from ..libs import failures

    gauge, abandoned = _device_health()
    with _DEVICE_SUBMIT_LOCK:
        # concurrent staging threads (the double-buffered accumulator)
        # must agree on ONE device-owner executor — two would defeat the
        # queue-behind-the-previous-window serialization
        if _DEVICE_POOL is None:
            _DEVICE_POOL = cf.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tpu-verify")
    if _DEVICE_INFLIGHT is not None and not _DEVICE_INFLIGHT.done() \
            and not patient:
        # fail-fast callers never wait on a busy device; only flag it
        # DEGRADED when the in-flight dispatch is past its own allowed
        # window (a healthy patient catch-up dispatch legitimately holds
        # the device for many seconds — that is busy, not wedged)
        if time.perf_counter() > _DEVICE_INFLIGHT_DEADLINE:
            gauge.set(1)
        return None
    if failures.is_enabled():
        # chaos sites wrap the dispatch ON the device-owner thread so
        # the hang/raise exercises the real bounded-wait + host-fallback
        # machinery (the only way to rehearse a wedged or dying
        # accelerator on a CPU-only box)
        f_hang = failures.fire("device.dispatch.hang")
        f_raise = failures.fire("device.dispatch.raise")
        if f_hang is not None or f_raise is not None:
            inner = fn

            def fn():
                if f_hang is not None:
                    time.sleep(float(f_hang.get("delay",
                                                _DEVICE_WAIT_S + 1.0)))
                if f_raise is not None:
                    raise RuntimeError(
                        "chaos: injected device dispatch failure")
                return inner()
    timeout = patient or _DEVICE_WAIT_S
    with _DEVICE_SUBMIT_LOCK:
        fut = _DEVICE_POOL.submit(fn)
        _DEVICE_INFLIGHT = fut
        _DEVICE_INFLIGHT_DEADLINE = time.perf_counter() + timeout
    try:
        result = fut.result(timeout=timeout)
    except cf.TimeoutError:
        abandoned.inc()
        gauge.set(1)
        if not _DEGRADED_LOGGED:
            _DEGRADED_LOGGED = True
            from ..libs import log as _tmlog

            _tmlog.logger("crypto").error(
                "device dispatch abandoned after bounded wait; "
                "verification falling back to host until the device "
                "answers again", wait_s=_DEVICE_WAIT_S)
        return None
    except Exception as e:
        # a dispatch that RAISES (driver crash, runtime error mid-kernel)
        # degrades exactly like one that hangs: host fallback, visible
        # on the same gauge/counter — never an exception on the
        # consensus path
        abandoned.inc()
        gauge.set(1)
        if not _DEGRADED_LOGGED:
            _DEGRADED_LOGGED = True
            from ..libs import log as _tmlog

            _tmlog.logger("crypto").error(
                "device dispatch raised; verification falling back to "
                "host until the device answers again", err=repr(e))
        return None
    gauge.set(0)
    if _DEGRADED_LOGGED:
        _DEGRADED_LOGGED = False
        from ..libs import log as _tmlog

        _tmlog.logger("crypto").info("device dispatch recovered")
    return result


class TpuBatchVerifier(BatchVerifier):
    """Device-backed batch verifier behind the ``crypto.BatchVerifier`` seam.

    Ed25519 lanes go to the device kernel; other key types verify on CPU
    (an improvement over the reference, which refuses mixed batches —
    ``types/validation.go:13-19``).
    """

    # batches below this go one-by-one on CPU even with a device present:
    # dispatch overhead dominates tiny batches (config-driven via
    # set_min_device_lanes; the reference's batchVerifyThreshold analogue)
    MIN_DEVICE_LANES = 1

    def __init__(self, device=None, routed: bool = False):
        self._items: list[tuple[PubKey, bytes, bytes]] = []
        self._device = device
        # created under backend="auto": consult the measured router per
        # batch (explicit "tpu"/"jax" pins the device unconditionally)
        self._routed = routed

    def add(self, pub, msg, sig):
        if not isinstance(msg, (bytes, bytearray)):
            raise TypeError("msg must be bytes")
        self._items.append((pub, bytes(msg), bytes(sig)))

    @property
    def _count(self):
        return len(self._items)

    def verify(self):
        import time as _time

        hist, lanes, calls = _metrics()
        t0 = _time.perf_counter()
        try:
            return self._verify()
        finally:
            hist.observe(_time.perf_counter() - t0, backend="device")
            calls.inc(backend="device")

    def _verify(self):
        import time as _time

        n = len(self._items)
        if n == 0:
            return False, []
        _, lanes, _ = _metrics()
        ed_idx = [i for i, (p, _, s) in enumerate(self._items)
                  if p.type() == ED25519_KEY_TYPE and len(s) == 64]
        ed_set = set(ed_idx)
        oks = [False] * n
        for i, (p, m, s) in enumerate(self._items):
            if i not in ed_set:
                oks[i] = p.verify_signature(m, s)
        if n < TpuBatchVerifier.MIN_DEVICE_LANES or (
                self._routed and ed_idx
                and not _ROUTER.prefer_device(len(ed_idx))):
            # tiny batch — or the router measured the host faster at
            # this bucket: host verification (still through the native
            # RLC batch when >= 2 ed lanes, which feeds the router's
            # host estimate)
            ed_oks = _host_verify_ed25519(
                [self._items[i] for i in ed_idx], lanes, route="cpu")
            for j, i in enumerate(ed_idx):
                oks[i] = ed_oks[j]
            lanes.inc(n - len(ed_idx), route="cpu")
            return all(oks) and n > 0, oks
        lanes.inc(len(ed_idx), route="device")
        lanes.inc(n - len(ed_idx), route="cpu")
        if ed_idx:
            # vectorized packing: one frombuffer per FIELD, not per lane
            # (a per-lane loop costs ~100 ms at 10k sigs — on the p50
            # VerifyCommit latency path that dwarfs the device dispatch)
            ed_items = [self._items[i] for i in ed_idx]
            maxlen = max(max(len(m) for _, m, _ in ed_items), 1)
            bsz = len(ed_idx)
            pubs = np.frombuffer(
                b"".join(p.bytes() for p, _, _ in ed_items),
                np.uint8).reshape(bsz, 32)
            sigs = np.frombuffer(
                b"".join(s for _, _, s in ed_items),
                np.uint8).reshape(bsz, 64)
            rs, ss = sigs[:, :32], sigs[:, 32:]
            buf = bytearray(bsz * maxlen)
            lens = np.empty((bsz,), np.int64)
            for j, (_, m, _) in enumerate(ed_items):
                buf[j * maxlen:j * maxlen + len(m)] = m
                lens[j] = len(m)
            msgs = np.frombuffer(bytes(buf), np.uint8).reshape(bsz, maxlen)
            t0 = _time.perf_counter()
            dev = _device_call(lambda: device_verify_ed25519(
                pubs, rs, ss, msgs, lens, self._device))
            if dev is not None:
                _ROUTER.observe("device", bsz, _time.perf_counter() - t0)
            else:
                _ROUTER.observe("device", bsz,
                                max(_DEVICE_WAIT_S,
                                    _time.perf_counter() - t0))
            if dev is None:
                # device busy/stuck/slow: verify these lanes on host (via
                # the native RLC batch) so consensus never waits on the
                # accelerator
                ed_oks = _host_verify_ed25519(
                    [self._items[i] for i in ed_idx], lanes,
                    route="host_fallback")
                for j, i in enumerate(ed_idx):
                    oks[i] = ed_oks[j]
            else:
                for j, i in enumerate(ed_idx):
                    oks[i] = bool(dev[j])
        return all(oks), oks


class _ThroughputRouter:
    """Measured device-vs-host routing (VERDICT r4 weak 3: a node must
    never verify slower because a device is merely *present*).  Keeps a
    per-lane-bucket EWMA of observed throughput for each backend and
    prefers the faster one; every 64th decision per bucket deliberately
    explores the non-preferred backend so a backend that got faster
    (device un-wedged, host freed up) is re-measured instead of starved.
    Optimistic start: with no device sample yet, the device is tried
    (its first batches both measure and serve), matching the r4
    behavior until evidence says otherwise."""

    EXPLORE_EVERY = 64
    ALPHA = 0.25                # EWMA weight of the newest sample
    HYSTERESIS = 0.9            # device must be >=90% of host to keep

    def __init__(self):
        self._ewma: dict = {}   # (backend, bucket) -> sigs/s
        self._decisions: dict = {}   # bucket -> decision count

    def observe(self, backend: str, lanes: int, seconds: float) -> None:
        if lanes <= 0 or seconds <= 0:
            return
        key = (backend, bucket_for_lanes(lanes))
        tp = lanes / seconds
        prev = self._ewma.get(key)
        self._ewma[key] = tp if prev is None else (
            (1 - self.ALPHA) * prev + self.ALPHA * tp)

    def prefer_device(self, lanes: int) -> bool:
        bucket = bucket_for_lanes(lanes)
        n = self._decisions.get(bucket, 0)
        self._decisions[bucket] = n + 1
        dev = self._ewma.get(("device", bucket))
        host = self._ewma.get(("host", bucket))
        if dev is None:
            preferred = True           # optimism: measure by serving
        elif host is None:
            preferred = True
        else:
            preferred = dev >= self.HYSTERESIS * host
        if n and n % self.EXPLORE_EVERY == 0 and dev is not None \
                and host is not None:
            return not preferred       # periodic re-measure of the loser
        return preferred

    def snapshot(self) -> dict:
        """Operator surface: observed sigs/s by (backend, bucket)."""
        return {f"{b}:{bk}": v for (b, bk), v in self._ewma.items()}

    def reset(self) -> None:
        self._ewma.clear()
        self._decisions.clear()


_ROUTER = _ThroughputRouter()


def _backend_wants_device(backend: str, device, lanes: int | None = None
                          ) -> bool:
    """Shared backend dispatch for the object and dense paths: should
    this batch attempt the device route?  Under "auto" with no probe
    verdict yet, kicks off the background probe and answers False (the
    batch serves from host so consensus never blocks on discovery);
    once a device exists, "auto" additionally consults the measured
    throughput router (``lanes`` given) so a device that is SLOWER than
    the native host path never captures the hot path — "tpu"/"jax" are
    explicit operator overrides and skip the router.  Raises ValueError
    on unknown backend names — misconfigurations must surface
    identically on every path."""
    if backend in ("tpu", "jax"):
        return True
    if backend == "cpu":
        return False
    if backend != "auto":
        raise ValueError(f"unknown batch-verifier backend {backend!r}")
    if device is None and _PROBE_RESULT is None:
        _start_probe_background()
        return False
    dev = device if device is not None else _accelerator_device()
    if dev is None or getattr(dev, "platform", "cpu") == "cpu":
        return False
    return _ROUTER.prefer_device(lanes) if lanes is not None else True


def verify_dense(backend: str, pubs, sigs, msgs, lens, device=None,
                 valset_pubs=None, scope=None, patient: bool = False):
    """Dense-array verification behind the same backend dispatch as
    :func:`create_batch_verifier`: ``pubs`` (k,32) u8, ``sigs`` (k,64) u8,
    ``msgs`` (k,L) u8 zero-padded rows, ``lens`` (k,) int — the matrices
    the native sign-bytes builder emits.  All lanes must be ed25519.

    ``valset_pubs``/``scope`` (optional): the FULL validator-set pubkey
    matrix plus this batch's validator indices — lets the device route
    reuse per-valset decompressed-point tables across commits.

    Returns ``(all_ok, oks ndarray)``, or None when no dense-capable
    backend exists (no native lib on a CPU box) — the caller falls back
    to the per-lane object path.  Device wedging degrades to the native
    CPU batch under the same bounded wait as TpuBatchVerifier.
    ``patient`` queues behind an in-flight device dispatch instead of
    host-falling-back (the blocksync accumulator's staging mode; see
    :func:`_device_call`)."""
    import numpy as np

    from . import _native_ed25519 as _nat

    k = pubs.shape[0]
    if k == 0:
        return True, np.zeros((0,), bool)
    import time as _time

    _, lanes, _ = _metrics()
    if _backend_wants_device(backend, device, lanes=k) \
            and k >= TpuBatchVerifier.MIN_DEVICE_LANES:
        rs = np.ascontiguousarray(sigs[:, :32])
        ss = np.ascontiguousarray(sigs[:, 32:])
        t0 = _time.perf_counter()
        wait = patient_wait_s(k) if patient else 0.0
        if valset_pubs is not None and scope is not None:
            out = _device_call(lambda: device_verify_ed25519_cached(
                valset_pubs, scope, pubs, rs, ss, msgs, lens, device),
                patient=wait)
        else:
            out = _device_call(lambda: device_verify_ed25519(
                pubs, rs, ss, msgs, lens, device), patient=wait)
        if out is not None:
            _ROUTER.observe("device", k, _time.perf_counter() - t0)
            lanes.inc(k, route="device")
            return bool(out.all()), out
        # device busy/wedged: bounded fallback to the native host batch.
        # Charge the router the full bounded wait so "auto" prefers the
        # host until the device measurably answers again.
        _ROUTER.observe("device", k, max(_DEVICE_WAIT_S,
                                         _time.perf_counter() - t0))
    t0 = _time.perf_counter()
    res = _nat.batch_verify_dense(pubs, sigs, msgs, lens)
    if res is None:
        return None
    if res:
        _ROUTER.observe("host", k, _time.perf_counter() - t0)
        lanes.inc(k, route="cpu_batch")
        return True, np.ones((k,), bool)
    # refuted: localize per lane with the exact native single verify
    oks = np.fromiter(
        (_nat.verify(pubs[i].tobytes(), msgs[i, :int(lens[i])].tobytes(),
                     sigs[i].tobytes()) for i in range(k)), bool, k)
    lanes.inc(k, route="cpu")
    return bool(oks.all()), oks


_PROBE_RESULT: list | None = None    # [bool] once probed: accel usable?
_PROBE_LOCK = None                   # created lazily (threading.Lock)


def _probe_accelerator_subprocess(timeout_s: float = 15.0) -> bool:
    """Backend discovery in a THROWAWAY subprocess with a hard timeout.

    ``jax.devices()`` hangs forever in native code when the accelerator
    relay is wedged (observed repeatedly on this image) — a hung thread
    can't be killed, so the only safe first touch is a process we can.
    Returns True only if the child reports a live non-CPU platform."""
    import subprocess
    import sys

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(any(d.platform != 'cpu' "
             "for d in jax.devices()))"],
            capture_output=True, timeout=timeout_s, text=True)
        return out.returncode == 0 and "True" in out.stdout
    except Exception:            # timeout, OOM, missing interpreter...
        return False


_PROBE_THREAD = None


def _start_probe_background() -> None:
    """Kick off :func:`_accelerator_device` on a daemon thread so the
    caller can fall back to host crypto immediately; once the probe
    caches its verdict, later auto-selections use the device."""
    global _PROBE_THREAD, _PROBE_RESULT
    import os
    import threading

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        _PROBE_RESULT = [False]
        return
    if _PROBE_THREAD is None:
        _PROBE_THREAD = threading.Thread(
            target=_accelerator_device, daemon=True,
            name="tpu-backend-probe")
        _PROBE_THREAD.start()


def _accelerator_device():
    """First non-CPU jax device, or None (config-free auto-detection).

    When the environment pins CPU (``JAX_PLATFORMS=cpu``), return None
    WITHOUT touching jax.  Otherwise the first call probes the backend in
    a subprocess (see :func:`_probe_accelerator_subprocess`) so a wedged
    relay degrades a node to the CPU verifier instead of hanging its
    consensus hot path; the verdict is cached for the process."""
    global _PROBE_RESULT, _PROBE_LOCK
    import os

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return None
    if _PROBE_LOCK is None:
        import threading

        _PROBE_LOCK = threading.Lock()
    with _PROBE_LOCK:       # one probe; concurrent callers share verdict
        if _PROBE_RESULT is None:
            import sys

            if "jax" in sys.modules and getattr(
                    sys.modules.get("jax._src.xla_bridge"),
                    "_backends", None):
                # a backend already initialized in-process without
                # hanging — trust it, skip the subprocess round-trip
                _PROBE_RESULT = [True]
            else:
                _PROBE_RESULT = [_probe_accelerator_subprocess()]
                if not _PROBE_RESULT[0]:
                    # pin + harden so later jax imports can't wedge
                    os.environ["JAX_PLATFORMS"] = "cpu"
                    from ..jaxenv import harden_cpu_pinned_env

                    harden_cpu_pinned_env()
    if not _PROBE_RESULT[0]:
        return None
    try:
        import jax

        for d in jax.devices():
            if d.platform != "cpu":
                return d
        return jax.devices()[0]
    except Exception:
        return None


def supports_batch_verifier(pub: PubKey) -> bool:
    """Only ed25519 batches on device (crypto/batch/batch.go:21-31 analogue;
    other key types still *work* in TpuBatchVerifier via the CPU route)."""
    return pub.type() == ED25519_KEY_TYPE


def set_min_device_lanes(n: int) -> None:
    """Config hook: batches smaller than ``n`` verify on CPU even when a
    device is present (latency vs throughput crossover, BASELINE's
    'fallback-to-CPU threshold must be config-driven')."""
    TpuBatchVerifier.MIN_DEVICE_LANES = max(1, int(n))


def create_batch_verifier(backend: str = "auto",
                          device=None) -> BatchVerifier:
    """Backend dispatch (the reference's config.Config selection point).

    backend: "auto" | "tpu" | "jax" | "cpu".  The small-batch CPU
    threshold is process-wide via :func:`set_min_device_lanes`.
    """
    # device=None on the device backends lets the dispatch shard over
    # ALL visible chips (SURVEY §2.10 — multi-chip in the production hot
    # path); a caller-pinned device restores single-chip dispatch
    if _backend_wants_device(backend, device):
        return TpuBatchVerifier(device, routed=(backend == "auto"))
    return CpuBatchVerifier()
