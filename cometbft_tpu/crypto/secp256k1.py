"""secp256k1 ECDSA keys (reference: ``crypto/secp256k1/secp256k1.go``).

Semantics mirror the reference's dcrec-backed implementation:
- address  = RIPEMD160(SHA256(33-byte compressed pubkey))
  (``secp256k1.go:147-166``)
- signature = 64-byte big-endian R || S over SHA256(msg), S normalized to
  the lower half order on signing; verification REJECTS malleable (high-S)
  signatures (``secp256k1.go Sign/VerifySignature``).

The curve math rides on OpenSSL via the ``cryptography`` package — the
same native-backend stance as the ed25519 CPU path (SURVEY §2.9: native
where the reference is native).  secp256k1 never batches on device; in a
mixed-key commit the TpuBatchVerifier routes these lanes to CPU while
ed25519 lanes fill the device batch (BASELINE configs[5])."""

from __future__ import annotations

import functools as _functools
import hashlib
import os

try:
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature, encode_dss_signature)
    from cryptography.hazmat.primitives.serialization import (Encoding,
                                                              PublicFormat)
    from cryptography.exceptions import InvalidSignature
except ImportError:              # no `cryptography` wheel on this image:
    # signing/derivation fall back to the pure-Python RFC 6979 path
    # (crypto/_secp256k1_py.py) — byte-identical output; verification
    # keeps the native C++ fast path either way.  CAVEAT: the fallback
    # scalar arithmetic is NOT constant-time (bit-branching multiply),
    # so secret keys leak through timing side channels — tests and
    # development only; production signing requires the wheel
    ec = None

from . import _secp256k1_py as _py
from .keys import SECP256K1_KEY_TYPE, PrivKey, PubKey

# curve order (SEC2 v2)
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_HALF_N = _N // 2

PUB_KEY_SIZE = 33          # compressed
PRIV_KEY_SIZE = 32
SIG_SIZE = 64


def _native_verify(pub: bytes, msg: bytes, sig: bytes) -> bool | None:
    """Native C++ ECDSA verify (native/secp256k1.cpp) — ~1.7x the
    OpenSSL-via-`cryptography` path, which pays per-call DER encoding
    and object overhead.  None when the lib is unavailable (caller
    falls back)."""
    lib = _native_lib()
    if lib is None:
        return None
    return bool(lib.secp256k1_verify(pub, sig, msg, len(msg)))


@_functools.cache
def _native_lib():
    """CDLL for native/secp256k1.cpp, or None when the on-demand build
    fails (same lazy-load shape as crypto/_native_ed25519)."""
    import ctypes

    try:
        from ..native import lib_path

        lib = ctypes.CDLL(lib_path("secp256k1"))
        lib.secp256k1_verify.restype = ctypes.c_int
        lib.secp256k1_verify.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint64]
        return lib
    except Exception:
        return None


class Secp256k1PubKey(PubKey):
    SIZE = PUB_KEY_SIZE

    def __init__(self, raw: bytes):
        if len(raw) != self.SIZE:
            raise ValueError(f"secp256k1 pubkey must be {self.SIZE} bytes")
        self._raw = bytes(raw)
        if ec is not None:
            self._pk = ec.EllipticCurvePublicKey.from_encoded_point(
                ec.SECP256K1(), self._raw)
        else:
            self._pk = None
            _py.decompress(self._raw)    # same reject-on-construction

    def bytes(self) -> bytes:
        return self._raw

    def type(self) -> str:
        return SECP256K1_KEY_TYPE

    def address(self) -> bytes:
        """RIPEMD160(SHA256(pubkey)) — bitcoin-style, unlike ed25519's
        truncated SHA256 (secp256k1.go:147-166)."""
        sha = hashlib.sha256(self._raw).digest()
        return hashlib.new("ripemd160", sha).digest()

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIG_SIZE:
            return False
        native = _native_verify(self._raw, msg, sig)
        if native is not None:
            return native
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not (1 <= r < _N and 1 <= s < _N):
            return False
        if s > _HALF_N:
            return False            # reject malleable signatures
        if self._pk is None:
            return _py.verify(self._raw, msg, r, s)
        try:
            self._pk.verify(encode_dss_signature(r, s), msg,
                            ec.ECDSA(hashes.SHA256()))
            return True
        except InvalidSignature:
            return False


class Secp256k1PrivKey(PrivKey):
    SIZE = PRIV_KEY_SIZE

    def __init__(self, raw: bytes):
        if len(raw) != self.SIZE:
            raise ValueError(f"secp256k1 privkey must be {self.SIZE} bytes")
        self._raw = bytes(raw)
        self._d = int.from_bytes(raw, "big")
        if not 1 <= self._d < _N:
            raise ValueError("secp256k1 scalar out of range")
        self._sk = (ec.derive_private_key(self._d, ec.SECP256K1())
                    if ec is not None else None)

    @classmethod
    def generate(cls) -> "Secp256k1PrivKey":
        while True:
            cand = os.urandom(32)
            v = int.from_bytes(cand, "big")
            if 1 <= v < _N:
                return cls(cand)

    @classmethod
    def from_secret(cls, secret: bytes) -> "Secp256k1PrivKey":
        """One-way derivation like GenPrivKeySecp256k1 (secp256k1.go:95):
        sha256(secret), reduced into [1, n-1]."""
        v = int.from_bytes(hashlib.sha256(secret).digest(), "big")
        v = v % (_N - 1) + 1
        return cls(v.to_bytes(32, "big"))

    def bytes(self) -> bytes:
        return self._raw

    def type(self) -> str:
        return SECP256K1_KEY_TYPE

    def sign(self, msg: bytes) -> bytes:
        """RFC 6979 deterministic ECDSA over SHA-256(msg), low-S
        normalized — byte-for-byte a function of (key, msg), like the
        reference's dcrec SignCompact (secp256k1.go:121-125).  Nonce
        derivation and the scalar ladder run in OpenSSL's constant-time
        code; pinned to the published RFC 6979 secp256k1 vectors in
        tests/test_secp256k1.py."""
        if self._sk is None:
            r, s = _py.sign(self._d, msg)
            return r.to_bytes(32, "big") + s.to_bytes(32, "big")
        from cryptography.exceptions import UnsupportedAlgorithm

        try:
            der = self._sk.sign(
                msg, ec.ECDSA(hashes.SHA256(), deterministic_signing=True))
        except UnsupportedAlgorithm as exc:  # OpenSSL < 3.2
            raise RuntimeError(
                "deterministic ECDSA (RFC 6979) needs an OpenSSL 3.2+ "
                "backend; this cryptography build does not support it"
            ) from exc
        r, s = decode_dss_signature(der)
        if s > _HALF_N:
            s = _N - s              # low-S normalization
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def pub_key(self) -> Secp256k1PubKey:
        if self._sk is None:
            return Secp256k1PubKey(_py.pubkey_from_scalar(self._d))
        return Secp256k1PubKey(self._sk.public_key().public_bytes(
            Encoding.X962, PublicFormat.CompressedPoint))
