"""Pure-Python X25519 + ChaCha20-Poly1305 (RFC 7748 / RFC 8439).

Drop-in stand-ins for the ``cryptography`` classes the p2p secret
connection uses, for images without that wheel.  API-compatible with the
subset ``p2p/secret_connection.py`` touches: ``X25519PrivateKey.generate/
public_key/exchange``, ``X25519PublicKey.from_public_bytes/
public_bytes_raw``, ``ChaCha20Poly1305(key).encrypt/decrypt``.

The AEAD routes through the native C engine (``native/aead.cpp``,
on-demand g++ build, ~600x the pure-Python seal) whenever available;
the pure-Python cipher is the last resort, and the X25519 handshake
(once per connection) stays Python either way.  A production
deployment installs the wheel and never loads this module.  Pinned
against RFC 8439/7748 vectors and native-vs-Python parity in tests.
"""

from __future__ import annotations

import os
import struct
from hmac import compare_digest

_P = 2**255 - 19
_A24 = 121665


class InvalidTag(Exception):
    pass


def x25519(k: bytes, u: bytes) -> bytes:
    """RFC 7748 §5 scalar multiplication (montgomery ladder)."""
    kb = bytearray(k)
    kb[0] &= 248
    kb[31] &= 127
    kb[31] |= 64
    ki = int.from_bytes(kb, "little")
    x1 = int.from_bytes(u, "little") & ((1 << 255) - 1)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in reversed(range(255)):
        kt = (ki >> t) & 1
        if swap ^ kt:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = x1 * z3 * z3 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + _A24 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return (x2 * pow(z2, _P - 2, _P) % _P).to_bytes(32, "little")


_BASE_U = (9).to_bytes(32, "little")


class X25519PublicKey:
    def __init__(self, raw: bytes):
        self._raw = bytes(raw)

    @classmethod
    def from_public_bytes(cls, raw: bytes) -> "X25519PublicKey":
        if len(raw) != 32:
            raise ValueError("X25519 public key must be 32 bytes")
        return cls(raw)

    def public_bytes_raw(self) -> bytes:
        return self._raw


class X25519PrivateKey:
    def __init__(self, raw: bytes):
        self._raw = bytes(raw)

    @classmethod
    def generate(cls) -> "X25519PrivateKey":
        return cls(os.urandom(32))

    def public_key(self) -> X25519PublicKey:
        return X25519PublicKey(x25519(self._raw, _BASE_U))

    def exchange(self, peer: X25519PublicKey) -> bytes:
        shared = x25519(self._raw, peer.public_bytes_raw())
        if shared == b"\x00" * 32:
            raise ValueError("X25519 exchange produced the zero point")
        return shared


# ------------------------------------------------------ ChaCha20-Poly1305

def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF


def _chacha_block(key_words, counter: int, nonce_words) -> bytes:
    init = [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574,
            *key_words, counter & 0xFFFFFFFF, *nonce_words]
    s = list(init)

    def qr(a, b, c, d):
        s[a] = (s[a] + s[b]) & 0xFFFFFFFF
        s[d] = _rotl(s[d] ^ s[a], 16)
        s[c] = (s[c] + s[d]) & 0xFFFFFFFF
        s[b] = _rotl(s[b] ^ s[c], 12)
        s[a] = (s[a] + s[b]) & 0xFFFFFFFF
        s[d] = _rotl(s[d] ^ s[a], 8)
        s[c] = (s[c] + s[d]) & 0xFFFFFFFF
        s[b] = _rotl(s[b] ^ s[c], 7)

    for _ in range(10):
        qr(0, 4, 8, 12)
        qr(1, 5, 9, 13)
        qr(2, 6, 10, 14)
        qr(3, 7, 11, 15)
        qr(0, 5, 10, 15)
        qr(1, 6, 11, 12)
        qr(2, 7, 8, 13)
        qr(3, 4, 9, 14)
    return struct.pack("<16I",
                       *((s[i] + init[i]) & 0xFFFFFFFF for i in range(16)))


def _chacha_stream(key: bytes, counter: int, nonce: bytes,
                   data: bytes) -> bytes:
    kw = struct.unpack("<8I", key)
    nw = struct.unpack("<3I", nonce)
    out = bytearray(len(data))
    for i in range(0, len(data), 64):
        ks = _chacha_block(kw, counter + i // 64, nw)
        chunk = data[i:i + 64]
        out[i:i + len(chunk)] = bytes(a ^ b for a, b in zip(chunk, ks))
    return bytes(out)


def _poly1305(key32: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key32[:16], "little") \
        & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key32[16:32], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        blk = msg[i:i + 16]
        acc = (acc + int.from_bytes(blk, "little")
               + (1 << (8 * len(blk)))) * r % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(b: bytes) -> bytes:
    return b"\x00" * (-len(b) % 16)


def _native_aead():
    """ctypes handle to the C AEAD (``native/aead.cpp``), or None when
    the on-demand g++ build is unavailable.  The pure-Python cipher
    below moves ~1 MB/s — every p2p frame of every peer connection pays
    it, which starves a multi-node in-proc net — while the native seal
    is ~600x faster; parity is pinned in tests."""
    global _NATIVE_AEAD
    if _NATIVE_AEAD is None:
        import ctypes

        try:
            from ..native import lib_path

            lib = ctypes.CDLL(lib_path("aead"))
            lib.aead_seal.restype = None
            lib.aead_seal.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
                ctypes.c_char_p]
            lib.aead_open.restype = ctypes.c_int
            lib.aead_open.argtypes = list(lib.aead_seal.argtypes)
            _NATIVE_AEAD = (lib,)
        except Exception:
            _NATIVE_AEAD = ()
    return _NATIVE_AEAD[0] if _NATIVE_AEAD else None


_NATIVE_AEAD = None


class ChaCha20Poly1305:
    """RFC 8439 AEAD: 32-byte key, 12-byte nonces, 16-byte tag.
    Routes through the native C engine when the build is available; the
    pure-Python methods below are the last-resort path."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)
        self._lib = _native_aead()

    def _otk(self, nonce: bytes) -> bytes:
        return _chacha_block(struct.unpack("<8I", self._key), 0,
                             struct.unpack("<3I", nonce))[:32]

    def _mac(self, nonce: bytes, aad: bytes, ct: bytes) -> bytes:
        data = (aad + _pad16(aad) + ct + _pad16(ct)
                + struct.pack("<QQ", len(aad), len(ct)))
        return _poly1305(self._otk(nonce), data)

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        aad = aad or b""
        if self._lib is not None:
            import ctypes

            out = ctypes.create_string_buffer(len(data) + 16)
            self._lib.aead_seal(self._key, nonce, aad, len(aad), data,
                                len(data), out)
            return out.raw
        ct = _chacha_stream(self._key, 1, nonce, data)
        return ct + self._mac(nonce, aad, ct)

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        if len(data) < 16:
            raise InvalidTag("ciphertext shorter than the tag")
        aad = aad or b""
        if self._lib is not None:
            import ctypes

            out = ctypes.create_string_buffer(max(1, len(data) - 16))
            if not self._lib.aead_open(self._key, nonce, aad, len(aad),
                                       data, len(data), out):
                raise InvalidTag("poly1305 tag mismatch")
            return out.raw[:len(data) - 16]
        ct, tag = data[:-16], data[-16:]
        if not compare_digest(self._mac(nonce, aad, ct), tag):
            raise InvalidTag("poly1305 tag mismatch")
        return _chacha_stream(self._key, 1, nonce, ct)
