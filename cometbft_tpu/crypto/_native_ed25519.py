"""ctypes binding for the native (C++) ZIP-215 ed25519 verifier.

``native/ed25519.cpp`` implements single and random-linear-combination
batch verification — the host CPU analogue of the reference's
curve25519-voi batch path (``crypto/ed25519/ed25519.go:188-221``), which
SURVEY §2.9-1 requires to be native, never a Python stand-in.  The batch
entry verifies n signatures as ONE Pippenger multiscalar multiplication,
~5x a single-verify loop at commit scale.

Degrades gracefully: if the on-demand g++ build fails, every function
returns None and callers keep their pure-host path.
"""

from __future__ import annotations

import ctypes
import functools
import os


@functools.cache
def _lib():
    try:
        from ..native import lib_path

        lib = ctypes.CDLL(lib_path("ed25519"))
        lib.ed25519_verify.restype = ctypes.c_int
        lib.ed25519_verify.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint64]
        lib.ed25519_batch_verify.restype = ctypes.c_int
        lib.ed25519_batch_verify.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
            ctypes.c_char_p]
        return lib
    except Exception:
        return None


def available() -> bool:
    return _lib() is not None


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool | None:
    """Exact single ZIP-215 verification; None if the lib is unavailable."""
    lib = _lib()
    if lib is None:
        return None
    if len(pub) != 32 or len(sig) != 64:
        return False
    return bool(lib.ed25519_verify(pub, sig, msg, len(msg)))


def batch_verify(pubs: list[bytes], msgs: list[bytes],
                 sigs: list[bytes]) -> bool | None:
    """One RLC batch check over the whole list: True means EVERY signature
    is valid; False means at least one is not (caller localizes with
    single verifies); None when the native lib is unavailable.

    Inputs must be pre-validated: 32-byte pubs, 64-byte sigs.
    """
    lib = _lib()
    if lib is None:
        return None
    n = len(pubs)
    if n == 0:
        return False
    lens = (ctypes.c_uint64 * n)(*[len(m) for m in msgs])
    return bool(lib.ed25519_batch_verify(
        b"".join(pubs), b"".join(sigs), b"".join(msgs), lens, n,
        os.urandom(32)))
