"""ctypes binding for the native (C++) ZIP-215 ed25519 verifier.

``native/ed25519.cpp`` implements single and random-linear-combination
batch verification — the host CPU analogue of the reference's
curve25519-voi batch path (``crypto/ed25519/ed25519.go:188-221``), which
SURVEY §2.9-1 requires to be native, never a Python stand-in.  The batch
entry verifies n signatures as ONE Pippenger multiscalar multiplication,
~5x a single-verify loop at commit scale.  It also hosts the native
canonical vote sign-bytes builder (SURVEY §2.9-4) used by the dense
VerifyCommit fast path.

Degrades gracefully: if the on-demand g++ build fails, every function
returns None and callers keep their pure-host path.
"""

from __future__ import annotations

import ctypes
import functools
import os

_U64P = ctypes.POINTER(ctypes.c_uint64)
_I64P = ctypes.POINTER(ctypes.c_int64)
_U8P = ctypes.POINTER(ctypes.c_uint8)


@functools.cache
def _lib():
    try:
        from ..native import lib_path

        lib = ctypes.CDLL(lib_path("ed25519"))
        lib.ed25519_verify.restype = ctypes.c_int
        lib.ed25519_verify.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint64]
        lib.ed25519_batch_verify.restype = ctypes.c_int
        lib.ed25519_batch_verify.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            _U64P, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64]
        lib.build_vote_sign_bytes.restype = ctypes.c_uint64
        lib.build_vote_sign_bytes.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64,      # pre_commit
            ctypes.c_char_p, ctypes.c_uint64,      # pre_nil
            ctypes.c_char_p, ctypes.c_uint64,      # post
            _I64P, ctypes.c_char_p, ctypes.c_uint64,   # ts, flags, n
            _U8P, ctypes.c_uint64, _U64P]          # out, stride, lens
        lib.ed25519_pubkey.restype = None
        lib.ed25519_pubkey.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.ed25519_sign.restype = None
        lib.ed25519_sign.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p]
        return lib
    except Exception:
        return None


def available() -> bool:
    return _lib() is not None


def public_key(seed: bytes) -> bytes | None:
    """RFC 8032 public key from a 32-byte seed; None without the lib.
    The host fallback for images without the ``cryptography`` wheel
    (the pure-Python ladder is ~10 ms per key — unusable at valset
    scale)."""
    lib = _lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(32)
    lib.ed25519_pubkey(seed, out)
    return out.raw


def sign(seed: bytes, msg: bytes) -> bytes | None:
    """RFC 8032 deterministic signature from a 32-byte seed; None
    without the lib."""
    lib = _lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(64)
    lib.ed25519_sign(seed, msg, len(msg), out)
    return out.raw


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool | None:
    """Exact single ZIP-215 verification; None if the lib is unavailable."""
    lib = _lib()
    if lib is None:
        return None
    if len(pub) != 32 or len(sig) != 64:
        return False
    return bool(lib.ed25519_verify(pub, sig, msg, len(msg)))


def batch_verify(pubs: list[bytes], msgs: list[bytes],
                 sigs: list[bytes]) -> bool | None:
    """One RLC batch check over the whole list: True means EVERY signature
    is valid; False means at least one is not (caller localizes with
    single verifies); None when the native lib is unavailable.

    Inputs must be pre-validated: 32-byte pubs, 64-byte sigs.
    """
    lib = _lib()
    if lib is None:
        return None
    n = len(pubs)
    if n == 0:
        return False
    lens = (ctypes.c_uint64 * n)(*[len(m) for m in msgs])
    return bool(lib.ed25519_batch_verify(
        b"".join(pubs), b"".join(sigs), b"".join(msgs), lens, n,
        os.urandom(32), 0))


def batch_verify_dense(pubs, sigs, msgs, lens) -> bool | None:
    """Dense-array RLC batch: ``pubs`` (n,32) u8, ``sigs`` (n,64) u8,
    ``msgs`` (n,stride) u8 zero-padded rows, ``lens`` (n,) — the exact
    matrices the TPU packing path builds, verified without any repacking.
    Arrays must be C-contiguous numpy uint8 (lens any int dtype)."""
    import numpy as np

    lib = _lib()
    if lib is None:
        return None
    n = pubs.shape[0]
    if n == 0:
        return False
    lens64 = np.ascontiguousarray(lens, np.uint64)
    return bool(lib.ed25519_batch_verify(
        pubs.ctypes.data_as(ctypes.c_char_p),
        sigs.ctypes.data_as(ctypes.c_char_p),
        msgs.ctypes.data_as(ctypes.c_char_p),
        lens64.ctypes.data_as(_U64P), n, os.urandom(32), msgs.shape[1]))


def build_vote_sign_bytes(pre_commit: bytes, pre_nil: bytes, post: bytes,
                          ts_ns, flags):
    """Assemble one commit's canonical vote sign-bytes rows natively.

    ``ts_ns`` int64 array (n,), ``flags`` uint8 array (n,) with 2 =
    commit-variant prefix, else nil-variant.  Returns ``(msgs, lens)`` —
    (n, stride) uint8 rows + true lengths — or None when unavailable.
    """
    import numpy as np

    lib = _lib()
    if lib is None:
        return None
    n = len(ts_ns)
    stride = 5 + max(len(pre_commit), len(pre_nil)) + 19 + len(post)
    out = np.zeros((n, stride), np.uint8)
    lens = np.zeros((n,), np.uint64)
    ts64 = np.ascontiguousarray(ts_ns, np.int64)
    fl8 = np.ascontiguousarray(flags, np.uint8)
    rc = lib.build_vote_sign_bytes(
        pre_commit, len(pre_commit), pre_nil, len(pre_nil),
        post, len(post),
        ts64.ctypes.data_as(_I64P),
        fl8.ctypes.data_as(ctypes.c_char_p), n,
        out.ctypes.data_as(_U8P), stride,
        lens.ctypes.data_as(_U64P))
    if rc != 0:                      # stride undersized (can't happen with
        raise RuntimeError("sign-bytes stride miscomputed")  # our formula)
    return out, lens.astype(np.int64)
