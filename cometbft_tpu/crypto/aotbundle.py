"""The AOT compile-bundle cache: serialized XLA executables for the
device plan's warm compile buckets, loaded at node start.

Why it exists: PR 5 measured ~110 s of cold XLA compile per verify
bucket on this image vs 0.14 s warm — and even with the persistent
HLO-level compile cache a fresh process still pays multi-second tracing
and lowering on its first dispatch of every shape.  Spinning up a verify
node per traffic spike is only plausible if the node boots WARM: this
module enumerates the compile buckets from the declarative device plan
(``crypto/plan.py``), AOT-lowers and compiles each one
(``jax.jit(fn).lower(args).compile()``), serializes the executables
(``jax.experimental.serialize_executable``) into one versioned on-disk
bundle, and on later boots deserializes them straight into the dispatch
table — the first real dispatch then runs at warm-dispatch latency, with
no tracing, no lowering, no compile.

Versioning/staleness (the hard safety requirement): serialized
executables embed jaxlib internals, so a bundle is only valid for the
exact (bundle format, jax, jaxlib, platform, device count, plan hash)
that built it.  The fingerprint is checked BEFORE any payload is
deserialized; a mismatched or undecodable bundle is ignored with a
logged warning and a ``crypto_compile_bundle_stale_total`` tick — never
a crash, never a silently wrong executable.  The bundle file is trusted
local state (same trust level as the XLA persistent cache it extends):
the outer container is msgpack, and the pickled pytree metadata inside
is only touched after the fingerprint matches.

Surfaces: ``crypto_compile_bundle_info`` (gauge: warm-bucket count,
labeled by bundle version + status) and the ``compile_bundle`` block in
``/status`` (version, plan shape, per-bucket cold/warm).  The dispatch
integration lives in ``crypto/batch.py``/``crypto/merkle.py``:
``lookup(key)`` is a plain dict hit consulted before the jit caches.
"""

from __future__ import annotations

import functools
import os
import pickle
import time

from . import plan as _plan

_MAGIC = "cmt-aot"
_FORMAT = 1
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_LOADED: dict[str, object] = {}      # bucket key -> loaded executable
_INFO: dict = {"status": "absent", "buckets": {}}


@functools.cache
def _metrics():
    from ..libs import metrics as m

    return (
        m.gauge("crypto_compile_bundle_info",
                "AOT compile-bundle state: value = warm (loaded) bucket "
                "count, labeled by bundle version and load status"),
        m.counter("crypto_compile_bundle_stale_total",
                  "bundles (or bundle buckets) ignored, by reason"),
    )


def _log():
    from ..libs import log as tmlog

    return tmlog.logger("aotbundle")


# -------------------------------------------------------------- identity


def bundle_version(plan=None) -> str:
    """The full environment+plan fingerprint a bundle is keyed by.
    Anything that could change the compiled artifact's meaning is folded
    in: bundle format, jax + jaxlib versions, backend platform and
    device count, and the declarative plan hash."""
    import hashlib

    import jax

    try:
        import jaxlib

        jl = getattr(jaxlib, "__version__", "?")
    except Exception:
        jl = "?"
    devs = jax.devices()
    doc = "|".join([
        str(_FORMAT), jax.__version__, jl,
        devs[0].platform if devs else "?", str(len(devs)),
        _plan.plan_hash(plan),
    ])
    return hashlib.sha256(doc.encode()).hexdigest()[:16]


def default_path(dir_: str | None = None, plan=None) -> str:
    """Bundle location: ``<dir>/bundle-<version>[-m<D>].aot`` (one file
    per fingerprint, so a jax upgrade builds beside the old bundle
    instead of clobbering it; a mesh tag keeps sharded bundles beside
    the single-device one — the plan hash deliberately excludes the
    mesh shape).  Default dir sits next to the persistent XLA cache."""
    base = dir_ or os.path.join(_REPO, ".jax_cache", "aot")
    plan = plan or _plan.active()
    nd = _plan.mesh_size(plan)
    tag = f"-m{nd}" if nd > 1 else ""
    return os.path.join(base, f"bundle-{bundle_version(plan)}{tag}.aot")


# --------------------------------------------------------------- samples


def _kernel_fn(kind: str):
    if kind == "verify":
        from ..ops import ed25519 as k

        return k.verify_padded
    if kind == "rlc":
        from ..ops import rlc as k

        return k.verify_batch_rlc
    if kind == "gather":
        from ..ops import ed25519 as k

        return k.verify_padded_gather
    if kind == "rlc_gather":
        from ..ops import rlc as k

        return k.verify_batch_rlc_gather
    if kind == "tables":
        from ..ops import ed25519 as k

        return k.prepare_pubkey_tables
    if kind == "bls_agg":
        from ..ops import blsg1 as k

        return k.aggregate_g1_masked
    if kind == "merkle_level":
        from ..ops import sha256 as k

        return k.merkle_inner_level
    raise ValueError(f"unknown compile-bucket kind {kind!r}")


def sample_args(bucket: "_plan.CompileBucket") -> tuple:
    """Arrays of EXACTLY the shapes/dtypes the production dispatch
    builds for this bucket — assembled through the same host packers
    (``batch._padded_lane_args`` / ``_rlc_args``), so the AOT-compiled
    executable and the runtime call can never disagree on a shape."""
    import numpy as np

    if bucket.kind == "merkle_level":
        row = np.zeros((bucket.lanes, 8), np.uint32)
        return (row, row)
    if bucket.kind == "tables":
        return (np.zeros((bucket.table_rows, 32), np.int32),)
    if bucket.kind == "bls_agg":
        from ..ops import blsg1

        return (np.zeros((bucket.table_rows, 2, blsg1.NLIMB), np.int32),
                np.zeros((bucket.table_rows,), np.int32))
    from . import batch as _b

    bb, nb = bucket.lanes, bucket.blocks
    # longest message that still fits nb SHA-512 blocks after the
    # 64-byte R||A prefix and 17 bytes of padding (same as warmup)
    msg_len = nb * 128 - 64 - 17
    zeros32 = np.zeros((bb, 32), np.uint8)
    msgs = np.zeros((bb, msg_len), np.uint8)
    lens = np.full((bb,), msg_len, np.int64)
    args = _b._padded_lane_args(zeros32, zeros32, zeros32, msgs, lens, bb)
    if bucket.kind == "rlc":
        return args + (_b._rlc_args(bb, bb),)
    if bucket.kind in ("gather", "rlc_gather"):
        # cached-valset route: (tab, ok, idx, r32, s32, blocks, active
        # [, z10]) — the table/ok avals come from the table-build kernel
        # itself so they can never drift from what _valset_tables feeds
        import jax

        from ..ops import ed25519 as _ked

        # the table is a custom pytree (ops.group Cached) — zero-fill
        # every leaf of the exact structure the table kernel emits
        tab, ok = jax.tree_util.tree_map(
            lambda s: np.zeros(s.shape, s.dtype),
            jax.eval_shape(
                _ked.prepare_pubkey_tables,
                jax.ShapeDtypeStruct((bucket.table_rows, 32), np.int32)))
        idx = np.zeros((bb,), np.int32)
        out = (tab, ok, idx) + args[1:]
        if bucket.kind == "rlc_gather":
            out = out + (_b._rlc_args(bb, bb),)
        return out
    return args


# ------------------------------------------------------------ build/save


def build(plan=None, kinds: tuple | None = None, path: str | None = None,
          save: bool = True) -> dict:
    """AOT-lower + compile every warm bucket of the plan, register the
    executables in the live dispatch table, and (by default) serialize
    them into the versioned bundle file.  Returns the info dict also
    surfaced at ``/status``."""
    from jax.experimental import serialize_executable as se
    import jax

    from . import batch as _b

    plan = plan or _plan.active()
    _b._jit_env()
    nd = _plan.mesh_size(plan)
    mesh_devices = None
    if nd > 1:
        devs = jax.devices()
        if len(devs) >= nd:
            mesh_devices = list(devs[:nd])
        else:
            _log().warn("plan mesh wider than visible devices; building "
                        "a single-device bundle", mesh=nd,
                        devices=len(devs))
            nd = 1
    buckets = _plan.enumerate_buckets(plan, kinds=kinds)
    entries: dict[str, dict] = {}
    statuses: dict[str, str] = {}
    for bucket in buckets:
        key = bucket.key
        t0 = time.perf_counter()
        try:
            if mesh_devices is not None and bucket.kind not in (
                    "tables", "bls_agg"):
                # sharded program over the plan's mesh; the @m<D> key tag
                # and the header's mesh dims keep it off any other mesh.
                # ("tables" builds once and replicates, so it stays a
                # single-device program.)
                if bucket.lanes % nd:
                    statuses[key] = "degraded:mesh_divides"
                    _log().warn("bucket lanes do not divide the mesh; "
                                "not bundling", bucket=key, mesh=nd)
                    continue
                from ..parallel.mesh import sharded_kernel

                key = f"{bucket.key}@m{nd}"
                jfn = sharded_kernel(bucket.kind, mesh_devices)
            else:
                jfn = jax.jit(_kernel_fn(bucket.kind))
            args = sample_args(bucket)
            compiled = jfn.lower(*args).compile()
            payload, in_tree, out_tree = se.serialize(compiled)
        except Exception as e:
            _log().error("AOT build failed for bucket; skipping",
                         bucket=key, err=repr(e))
            statuses[key] = "degraded:compile"
            continue
        secs = time.perf_counter() - t0
        _LOADED[key] = compiled
        entries[key] = {
            "payload": payload,
            "trees": pickle.dumps((in_tree, out_tree)),
            "compile_s": round(secs, 3),
        }
        statuses[key] = "warm"
        _log().info("AOT-compiled bucket", bucket=key,
                    secs=round(secs, 2))
    version = bundle_version(plan)
    out_path = path or default_path(plan=plan)
    if save and entries:
        _save_file(out_path, version, plan, entries)
    return _set_info({
        "status": "built" if entries else "build_failed",
        "version": version,
        "path": out_path if save else None,
        "plan": _plan.describe(plan),
        "buckets": statuses,
    })


def _save_file(path: str, version: str, plan, entries: dict) -> None:
    import msgpack

    doc = {
        "magic": _MAGIC,
        "format": _FORMAT,
        "version": version,
        # mesh dims ride OUTSIDE the version hash: a mesh mismatch is
        # its own staleness reason (a 4-chip executable on an 8-chip
        # mesh would be silently wrong, not just stale)
        "mesh": [int(d) for d in plan.mesh_shape],
        "plan": _plan.describe(plan),
        "buckets": entries,
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(msgpack.packb(doc, use_bin_type=True))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _log().info("compile bundle written", path=path,
                buckets=len(entries),
                bytes=os.path.getsize(path))


# ------------------------------------------------------------------ load


def load(path: str | None = None, plan=None) -> dict:
    """Load a bundle into the live dispatch table.  The staleness guard
    runs BEFORE any pickled payload is touched: magic/format/version
    mismatches are ignored with a warning + counter, never a crash and
    never a wrong executable."""
    import msgpack

    plan = plan or _plan.active()
    gauge, stale = _metrics()
    want = bundle_version(plan)
    path = path or default_path(plan=plan)
    if not os.path.exists(path):
        return _set_info({"status": "absent", "version": want,
                          "path": path, "plan": _plan.describe(plan),
                          "buckets": {}})
    try:
        with open(path, "rb") as f:
            doc = msgpack.unpackb(f.read(), raw=False)
    except Exception as e:
        stale.inc(reason="corrupt")
        _log().warn("compile bundle undecodable; ignoring",
                    path=path, err=repr(e))
        return _set_info({"status": "corrupt", "version": want,
                          "path": path, "plan": _plan.describe(plan),
                          "buckets": {}})
    if not isinstance(doc, dict) or doc.get("magic") != _MAGIC \
            or doc.get("format") != _FORMAT or doc.get("version") != want:
        stale.inc(reason="version")
        _log().warn(
            "compile bundle version mismatch; ignoring (rebuild will "
            "replace it)", path=path,
            bundle_version=str((doc or {}).get("version"))
            if isinstance(doc, dict) else "?", want=want)
        return _set_info({"status": "stale", "version": want,
                          "path": path, "plan": _plan.describe(plan),
                          "buckets": {}})
    want_mesh = [int(d) for d in plan.mesh_shape]
    got_mesh = [int(d) for d in (doc.get("mesh") or [])]
    if got_mesh != want_mesh:
        # version matches (mesh is deliberately outside the plan hash)
        # but the executables were sharded for a different mesh: running
        # them would be WRONG, not slow — degrade to jit compiles
        stale.inc(reason="mesh")
        _log().warn("compile bundle mesh mismatch; ignoring",
                    path=path, bundle_mesh=got_mesh, want=want_mesh)
        return _set_info({"status": "stale", "version": want,
                          "path": path, "plan": _plan.describe(plan),
                          "buckets": {}})
    from jax.experimental import serialize_executable as se

    from . import batch as _b

    _b._jit_env()
    statuses: dict[str, str] = {}
    nd = _plan.mesh_size(plan)
    for bucket in _plan.enumerate_buckets(plan):
        k = bucket.key
        if nd > 1 and bucket.kind not in ("tables", "bls_agg"):
            k = f"{k}@m{nd}"
        statuses.setdefault(k, "cold")
    for key, ent in (doc.get("buckets") or {}).items():
        try:
            in_tree, out_tree = pickle.loads(ent["trees"])
            _LOADED[key] = se.deserialize_and_load(
                ent["payload"], in_tree, out_tree)
            statuses[key] = "warm"
        except Exception as e:
            # per-bucket degrade with a REASON in /status (the r13 CPU
            # quirk: executables referencing runtime symbols — "Symbols
            # not found" on the tables kernel — fail cross-process
            # deserialization while the rest of the bundle is fine)
            stale.inc(reason="bucket")
            _log().warn("bundle bucket failed to deserialize; that "
                        "bucket degrades to jit", bucket=key, err=repr(e))
            statuses[key] = "degraded:deserialize"
    return _set_info({
        "status": "loaded",
        "version": want,
        "path": path,
        "plan": _plan.describe(plan),
        "buckets": statuses,
    })


def _set_info(info: dict) -> dict:
    global _INFO
    _INFO = info
    gauge, _ = _metrics()
    warm = sum(1 for s in (info.get("buckets") or {}).values()
               if s == "warm")
    gauge.set(warm, version=str(info.get("version")),
              status=str(info.get("status")))
    return info


def info() -> dict:
    """The current bundle state (the /status ``compile_bundle`` block)."""
    return _INFO


# -------------------------------------------------------------- dispatch


def lookup(key: str):
    """The hot-path consult: the loaded executable for a bucket key, or
    None.  A plain dict hit — callers fall through to their jit cache."""
    return _LOADED.get(key)


def timed_call(key: str, *args):
    """Execute a loaded bucket with first-dispatch instrumentation (the
    PR 5 ``crypto_kernel_first_dispatch_seconds`` gauge — how the bundle
    smoke proves a prewarmed process dispatches at warm latency)."""
    fn = _LOADED[key]
    t0 = time.perf_counter()
    out = fn(*args)
    try:
        import jax

        jax.block_until_ready(out)
    except Exception:
        pass
    dt = time.perf_counter() - t0
    base = key.split("@", 1)[0]          # drop any @m<D> mesh tag
    kind = base.split(":")[0]
    lanes = int(base.split(":")[-1].split("x")[0])
    from .batch import _note_dispatch

    _note_dispatch(kind, lanes, dt)
    return out


def reset() -> None:
    """Test hook: drop loaded executables and state."""
    global _INFO
    _LOADED.clear()
    _INFO = {"status": "absent", "buckets": {}}
