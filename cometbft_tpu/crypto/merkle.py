"""RFC-6962-style merkle trees and proofs.

Reference: ``crypto/merkle/`` — leaf/inner domain separation (0x00/0x01
prefixes), split at the largest power of two strictly less than n, empty
tree hashes to SHA-256 of the empty string.  Used for block-part sets, tx
hashes, header field hashing, validator-set hashing and evidence.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def _sha(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def leaf_hash(leaf: bytes) -> bytes:
    return _sha(LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha(INNER_PREFIX + left + right)


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n (n >= 2)."""
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def hash_from_byte_slices(items: list[bytes]) -> bytes:
    n = len(items)
    if n == 0:
        return _sha(b"")
    if n == 1:
        return leaf_hash(items[0])
    k = _split_point(n)
    return inner_hash(hash_from_byte_slices(items[:k]),
                      hash_from_byte_slices(items[k:]))


@dataclass
class Proof:
    """Merkle inclusion proof (crypto/merkle/proof.go semantics)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: list[bytes] = field(default_factory=list)

    def compute_root(self) -> bytes:
        return _compute_from_aunts(self.index, self.total, self.leaf_hash,
                                   self.aunts)

    def verify(self, root: bytes, leaf: bytes) -> bool:
        if self.total < 0 or self.index < 0 or self.index >= self.total:
            return False
        if leaf_hash(leaf) != self.leaf_hash:
            return False
        computed = self.compute_root()
        return computed is not None and computed == root


def _compute_from_aunts(index: int, total: int, leaf: bytes,
                        aunts: list[bytes]) -> bytes | None:
    if total == 0 or index >= total:
        return None
    if total == 1:
        return leaf if not aunts else None
    if not aunts:
        return None
    k = _split_point(total)
    if index < k:
        left = _compute_from_aunts(index, k, leaf, aunts[:-1])
        return None if left is None else inner_hash(left, aunts[-1])
    right = _compute_from_aunts(index - k, total - k, leaf, aunts[:-1])
    return None if right is None else inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: list[bytes]) -> tuple[bytes, list[Proof]]:
    """Root hash + one inclusion proof per item."""
    total = len(items)
    leaves = [leaf_hash(it) for it in items]

    def build(lo: int, hi: int) -> tuple[bytes, dict[int, list[bytes]]]:
        n = hi - lo
        if n == 0:
            return _sha(b""), {}
        if n == 1:
            return leaves[lo], {lo: []}
        k = _split_point(n)
        lroot, lpaths = build(lo, lo + k)
        rroot, rpaths = build(lo + k, hi)
        paths = {}
        for i, p in lpaths.items():
            paths[i] = p + [rroot]
        for i, p in rpaths.items():
            paths[i] = p + [lroot]
        return inner_hash(lroot, rroot), paths

    root, paths = build(0, total)
    # paths accumulate bottom-up (deepest sibling first), which is exactly
    # the order _compute_from_aunts consumes (aunts[-1] = topmost).
    proofs = [Proof(total=total, index=i, leaf_hash=leaves[i],
                    aunts=paths[i]) for i in range(total)]
    return root, proofs
