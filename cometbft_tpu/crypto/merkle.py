"""RFC-6962-style merkle trees and proofs.

Reference: ``crypto/merkle/`` — leaf/inner domain separation (0x00/0x01
prefixes), split at the largest power of two strictly less than n, empty
tree hashes to SHA-256 of the empty string.  Used for block-part sets, tx
hashes, header field hashing, validator-set hashing and evidence.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def _sha(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def leaf_hash(leaf: bytes) -> bytes:
    return _sha(LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha(INNER_PREFIX + left + right)


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n (n >= 2)."""
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def hash_from_byte_slices(items: list[bytes]) -> bytes:
    n = len(items)
    if n == 0:
        return _sha(b"")
    if n == 1:
        return leaf_hash(items[0])
    k = _split_point(n)
    return inner_hash(hash_from_byte_slices(items[:k]),
                      hash_from_byte_slices(items[k:]))


_NATIVE_ROOT = None


def _native_root_fn():
    """ctypes binding for the C++ RFC-6962 root (native/kvstore.cpp), or
    None when the native build is unavailable."""
    global _NATIVE_ROOT
    if _NATIVE_ROOT is None:
        import ctypes

        try:
            from ..native import lib_path

            lib = ctypes.CDLL(lib_path("kvstore"))
            lib.kv_merkle_root.restype = None
            lib.kv_merkle_root.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64, ctypes.c_char_p]
            _NATIVE_ROOT = (lib,)
        except Exception:
            _NATIVE_ROOT = ()
    return _NATIVE_ROOT[0] if _NATIVE_ROOT else None


def hash_from_byte_slices_fast(items: list[bytes]) -> bytes:
    """Root-only merkle hash through the native tree when available —
    identical output to :func:`hash_from_byte_slices` (pinned by tests),
    ~30x faster on big leaf sets (the builtin kvstore's per-block app
    hash was the hottest function in the e2e throughput profile)."""
    if len(items) < 64:        # BEFORE lib resolution: small callers must
        # not pay the one-time native build/load on first use
        return hash_from_byte_slices(items)
    lib = _native_root_fn()
    if lib is None:
        return hash_from_byte_slices(items)
    import ctypes

    import numpy as np

    buf = b"".join(items)
    # prefix offsets via numpy: a Python accumulation loop here was
    # ~5x the native tree's own cost at 20k leaves
    offs = np.zeros(len(items) + 1, np.uint64)
    np.cumsum(np.fromiter(map(len, items), np.uint64, len(items)),
              out=offs[1:])
    out = ctypes.create_string_buffer(32)
    lib.kv_merkle_root(buf,
                       offs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                       len(items), out)
    return out.raw


@dataclass
class Proof:
    """Merkle inclusion proof (crypto/merkle/proof.go semantics)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: list[bytes] = field(default_factory=list)

    def compute_root(self) -> bytes:
        return _compute_from_aunts(self.index, self.total, self.leaf_hash,
                                   self.aunts)

    def verify(self, root: bytes, leaf: bytes) -> bool:
        if self.total < 0 or self.index < 0 or self.index >= self.total:
            return False
        if leaf_hash(leaf) != self.leaf_hash:
            return False
        computed = self.compute_root()
        return computed is not None and computed == root


def _compute_from_aunts(index: int, total: int, leaf: bytes,
                        aunts: list[bytes]) -> bytes | None:
    if total == 0 or index >= total:
        return None
    if total == 1:
        return leaf if not aunts else None
    if not aunts:
        return None
    k = _split_point(total)
    if index < k:
        left = _compute_from_aunts(index, k, leaf, aunts[:-1])
        return None if left is None else inner_hash(left, aunts[-1])
    right = _compute_from_aunts(index - k, total - k, leaf, aunts[:-1])
    return None if right is None else inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: list[bytes]) -> tuple[bytes, list[Proof]]:
    """Root hash + one inclusion proof per item."""
    total = len(items)
    leaves = [leaf_hash(it) for it in items]

    def build(lo: int, hi: int) -> tuple[bytes, dict[int, list[bytes]]]:
        n = hi - lo
        if n == 0:
            return _sha(b""), {}
        if n == 1:
            return leaves[lo], {lo: []}
        k = _split_point(n)
        lroot, lpaths = build(lo, lo + k)
        rroot, rpaths = build(lo + k, hi)
        paths = {}
        for i, p in lpaths.items():
            paths[i] = p + [rroot]
        for i, p in rpaths.items():
            paths[i] = p + [lroot]
        return inner_hash(lroot, rroot), paths

    root, paths = build(0, total)
    # paths accumulate bottom-up (deepest sibling first), which is exactly
    # the order _compute_from_aunts consumes (aunts[-1] = topmost).
    proofs = [Proof(total=total, index=i, leaf_hash=leaves[i],
                    aunts=paths[i]) for i in range(total)]
    return root, proofs


# ------------------------------------------------------------- proof ops
# (crypto/merkle/proof_op.go + proof_value.go: composable proof chains for
# multi-store queries — ProofOperators.Verify walks ops leaf-to-root,
# each op transforming its input into the next layer's expected value)

@dataclass
class ProofOp:
    """Serialized proof step (type tag + key + opaque payload)."""

    type: str
    key: bytes
    data: bytes


class ProofOpError(Exception):
    pass


def kv_leaf(key: bytes, value: bytes) -> bytes:
    """Leaf encoding for provable KV stores: the KEY is bound into the
    leaf alongside the value hash (proof_value.go does the same via
    proto KVPair) — otherwise a prover could relabel any proven value
    under any key."""
    return (len(key).to_bytes(4, "big") + key
            + hashlib.sha256(value).digest())


class ValueOp:
    """Proves (key, value) -> store root: leaf = hash(kv_leaf(key,
    sha256(value))), then the merkle path in ``proof``
    (crypto/merkle/proof_value.go)."""

    TYPE = "simple:v"

    def __init__(self, key: bytes, proof: Proof):
        self.key = key
        self.proof = proof

    def run(self, args: list[bytes]) -> list[bytes]:
        if len(args) != 1:
            raise ProofOpError(f"ValueOp wants 1 arg, got {len(args)}")
        if leaf_hash(kv_leaf(self.key, args[0])) != self.proof.leaf_hash:
            raise ProofOpError("key/value does not match proof leaf")
        root = self.proof.compute_root()
        if root is None:
            raise ProofOpError("invalid merkle path")
        return [root]

    def proof_op(self) -> ProofOp:
        import msgpack

        return ProofOp(self.TYPE, self.key, msgpack.packb(
            {"t": self.proof.total, "i": self.proof.index,
             "l": self.proof.leaf_hash, "a": self.proof.aunts},
            use_bin_type=True))

    @classmethod
    def decode(cls, op: ProofOp) -> "ValueOp":
        import msgpack

        d = msgpack.unpackb(op.data, raw=False)
        return cls(op.key, Proof(d["t"], d["i"], d["l"], list(d["a"])))


_OP_DECODERS = {ValueOp.TYPE: ValueOp.decode}


def register_proof_op(type_: str, decoder) -> None:
    """proof_op.go ProofRuntime.RegisterOpDecoder."""
    _OP_DECODERS[type_] = decoder


class ProofOperators:
    """Ordered op chain: Verify(root, keypath, value) runs each op over
    the previous op's output, consuming keypath segments right-to-left
    (proof_op.go ProofOperators.Verify)."""

    def __init__(self, ops: list):
        self.ops = ops

    @classmethod
    def decode(cls, ops: list[ProofOp]) -> "ProofOperators":
        decoded = []
        for op in ops:
            dec = _OP_DECODERS.get(op.type)
            if dec is None:
                raise ProofOpError(f"unregistered proof op {op.type!r}")
            decoded.append(dec(op))
        return cls(decoded)

    def verify(self, root: bytes, keypath: list[bytes],
               value: bytes) -> None:
        """Raises ProofOpError unless the chain proves value@keypath
        under root."""
        if not self.ops:
            raise ProofOpError("empty proof op chain")
        args = [value]
        keys = list(keypath)
        for op in self.ops:
            if getattr(op, "key", b""):
                if not keys:
                    raise ProofOpError("keypath exhausted")
                if keys[-1] != op.key:
                    raise ProofOpError(
                        f"key mismatch: {keys[-1]!r} != {op.key!r}")
                keys.pop()
            args = op.run(args)
        if keys:
            raise ProofOpError(f"keypath not fully consumed: {keys!r}")
        if args != [root]:
            raise ProofOpError("computed root does not match")
