"""RFC-6962-style merkle trees and proofs.

Reference: ``crypto/merkle/`` — leaf/inner domain separation (0x00/0x01
prefixes), split at the largest power of two strictly less than n, empty
tree hashes to SHA-256 of the empty string.  Used for block-part sets, tx
hashes, header field hashing, validator-set hashing and evidence.

Construction is LEVEL-ORDER for anything beyond tiny trees: pair adjacent
nodes left to right, promote an odd tail node unchanged — provably the
same tree as the recursive largest-power-of-two split (pinned by golden
tests), but buildable one whole level at a time.  That shape admits three
interchangeable level engines behind a size-based dispatch:

- hashlib loop           — tiny trees, and the no-dependency fallback;
- native C++ (ctypes)    — ``kv_merkle_levels``/``kv_merkle_root`` in
  ``native/kvstore.cpp``: the host fast path (one C call for the whole
  tree);
- batched JAX kernel     — ``ops/sha256.py``: one jitted dispatch hashes
  an entire level, engaged for large trees when an accelerator is live
  (measured ~7x SLOWER than the hashlib loop on host CPU, so a
  ``JAX_PLATFORMS=cpu`` box falls back to the native/hashlib engines).

Every engine retains the per-level node cache, so
:func:`proofs_from_byte_slices` assembles ALL aunt paths by indexing into
the cached levels — zero re-hashing, and the gather is vectorized
(numpy sibling indices + one ``itemgetter`` sweep per level) instead of
the old recursive per-node dict merging.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass
from itertools import count, repeat
from operator import itemgetter
from typing import NamedTuple

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def _sha(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def leaf_hash(leaf: bytes) -> bytes:
    return _sha(LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha(INNER_PREFIX + left + right)


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n (n >= 2)."""
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def hash_from_byte_slices(items: list[bytes]) -> bytes:
    n = len(items)
    if n == 0:
        return _sha(b"")
    if n == 1:
        return leaf_hash(items[0])
    k = _split_point(n)
    return inner_hash(hash_from_byte_slices(items[:k]),
                      hash_from_byte_slices(items[k:]))


_NATIVE_ROOT = None


def _native_root_fn():
    """ctypes binding for the C++ RFC-6962 tree (native/kvstore.cpp), or
    None when the native build is unavailable.  Binds both the root-only
    entry and the level-cache builder the proof path uses."""
    global _NATIVE_ROOT
    if _NATIVE_ROOT is None:
        import ctypes

        try:
            from ..native import lib_path

            lib = ctypes.CDLL(lib_path("kvstore"))
            lib.kv_merkle_root.restype = None
            lib.kv_merkle_root.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64, ctypes.c_char_p]
            lib.kv_merkle_levels.restype = ctypes.c_uint64
            lib.kv_merkle_levels.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64, ctypes.c_char_p]
            _NATIVE_ROOT = (lib,)
        except Exception:
            _NATIVE_ROOT = ()
    return _NATIVE_ROOT[0] if _NATIVE_ROOT else None


def _native_args(items: list[bytes]):
    """(buf, offs) for the native tree calls: leaves concatenated plus
    numpy prefix offsets (a Python accumulation loop here was ~5x the
    native tree's own cost at 20k leaves)."""
    import numpy as np

    buf = b"".join(items)
    offs = np.zeros(len(items) + 1, np.uint64)
    np.cumsum(np.fromiter(map(len, items), np.uint64, len(items)),
              out=offs[1:])
    return buf, offs


def hash_from_byte_slices_fast(items: list[bytes]) -> bytes:
    """Root-only merkle hash through the fastest available engine —
    identical output to :func:`hash_from_byte_slices` (pinned by tests).

    Dispatch: tiny trees stay on hashlib (callers must not pay the
    one-time native build/load), large trees ride the batched device
    kernel when an accelerator is live, everything else goes through the
    native C++ tree (~30x the recursion on big leaf sets — the builtin
    kvstore's per-block app hash was the hottest function in the e2e
    throughput profile)."""
    n = len(items)
    if n < 64:                 # BEFORE lib resolution
        return hash_from_byte_slices(items)
    if _kernel_wanted(n):
        root = _root_kernel(items)
        if root is not None:
            return root
    lib = _native_root_fn()
    if lib is None:
        return _levels_hashlib(items)[-1][0]
    import ctypes

    buf, offs = _native_args(items)
    out = ctypes.create_string_buffer(32)
    lib.kv_merkle_root(buf,
                       offs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                       n, out)
    return out.raw


# ------------------------------------------------------- level-order core
# Pair adjacent nodes left to right; an odd tail node is promoted
# unchanged.  The ancestor of leaf i at level l is node i >> l in every
# level (promotion preserves floor-halving indices), so aunt paths are
# pure index arithmetic over the cached levels: sibling (i >> l) ^ 1,
# absent exactly when it falls off the level's width.

_KERNEL_MIN_LEAVES = 2048   # leaves before the device kernel is considered
_PROOF_LEVEL_MIN = 64       # below: the tiny recursive reference path
# padded kernel dispatch widths are owned by the declarative device
# plan (crypto/plan.py merkle_buckets) since r13; _bucket_width reads
# the ACTIVE plan so the AOT compile bundle and this dispatch agree
_LEAF_KERNEL_MAX_LEN = 118  # 0x00 + item + 9B padding fits two SHA-256 blocks


def set_merkle_kernel_min(n: int) -> None:
    """Config hook: minimum leaf count before the batched device kernel
    is considered for tree hashing (accelerator-gated either way)."""
    global _KERNEL_MIN_LEAVES
    _KERNEL_MIN_LEAVES = max(2, int(n))


def _level_widths(n: int) -> list[int]:
    widths = [n]
    while n > 1:
        n = (n + 1) // 2
        widths.append(n)
    return widths


def _levels_hashlib(items: list[bytes]) -> list[list[bytes]]:
    """Pure-Python level cache: every tree level, leaves first."""
    lv = [_sha(LEAF_PREFIX + it) for it in items]
    levels = [lv]
    while len(lv) > 1:
        m = len(lv) // 2
        nxt = [_sha(INNER_PREFIX + lv[2 * i] + lv[2 * i + 1])
               for i in range(m)]
        if len(lv) & 1:
            nxt.append(lv[-1])
        levels.append(nxt)
        lv = nxt
    return levels


def _levels_native(items: list[bytes]) -> list[list[bytes]] | None:
    """Whole level cache in one native call, or None without the lib."""
    lib = _native_root_fn()
    if lib is None:
        return None
    import ctypes

    import numpy as np

    n = len(items)
    widths = _level_widths(n)
    buf, offs = _native_args(items)
    out = ctypes.create_string_buffer(32 * sum(widths))
    wrote = lib.kv_merkle_levels(
        buf, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n, out)
    if wrote != sum(widths):
        return None
    raw = out.raw
    levels, pos = [], 0
    for w in widths:
        end = pos + 32 * w
        levels.append([raw[i:i + 32] for i in range(pos, end, 32)])
        pos = end
    return levels


_ACCEL_LIVE: bool | None = None      # cached accelerator verdict


def _kernel_wanted(n: int) -> bool:
    """Should this tree try the batched device kernel?  Accelerator-gated:
    on host CPU the per-level kernel measured ~7x slower than the hashlib
    loop, so a ``JAX_PLATFORMS=cpu`` box must keep the native/hashlib
    engines.  ``TPU_BFT_MERKLE_KERNEL=1/0`` forces/disables (tests and
    bench exercise the kernel path on the CPU backend with =1).  The
    probe verdict is cached: re-resolving it (and retrying a failing
    crypto-backend import) per tree was ~30% of a 10k root+proofs
    build."""
    global _ACCEL_LIVE
    if n < _KERNEL_MIN_LEAVES:
        return False
    force = os.environ.get("TPU_BFT_MERKLE_KERNEL", "").strip()
    if force == "0":
        return False
    if force == "1":
        return True
    if _ACCEL_LIVE is None:
        try:
            from .batch import _accelerator_device

            _ACCEL_LIVE = _accelerator_device() is not None
        except Exception:
            _ACCEL_LIVE = False
    return _ACCEL_LIVE


def _kernel_jits():
    """(jit(merkle_inner_level), jit(sha256_blocks)) after the shared
    hardening (CPU-pin defense + persistent compile cache), or None when
    jax is unusable.  Import stays lazy: merkle is on many non-JAX
    paths."""
    global _KERNEL_JITS
    if _KERNEL_JITS is None:
        try:
            import jax

            from ..jaxenv import enable_compile_cache, harden_cpu_pinned_env
            from ..ops import sha256 as _s

            harden_cpu_pinned_env()
            try:
                enable_compile_cache()
            except Exception:
                pass             # cache dir unwritable: compile-only
            _KERNEL_JITS = (jax.jit(_s.merkle_inner_level),
                            jax.jit(_s.sha256_blocks), _s)
        except Exception:
            _KERNEL_JITS = ()
    return _KERNEL_JITS if _KERNEL_JITS else None


_KERNEL_JITS = None


def _bucket_width(n: int) -> int:
    from . import plan as _plan

    buckets = _plan.active().merkle_buckets
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def _kernel_leaf_words(items: list[bytes], jits):
    """Leaf hashes as (n, 8) uint32 digest words.  Small items batch
    through the generic block kernel; big items (e.g. 64 kB block parts)
    hash through hashlib — leaf hashing there is data-bound, where C
    wins, while the kernel's edge is the per-node dispatch overhead."""
    import numpy as np

    jit_level, jit_blocks, _s = jits
    n = len(items)
    maxlen = max(map(len, items), default=0)
    if maxlen > _LEAF_KERNEL_MAX_LEN:
        leaves = b"".join(_sha(LEAF_PREFIX + it) for it in items)
        return _s.bytes_to_words(
            np.frombuffer(leaves, np.uint8).reshape(n, 32))
    nb = _s.max_blocks_for_len(maxlen + 1)
    lens = np.fromiter(map(len, items), np.int64, n) + 1
    msgs = np.zeros((n, maxlen + 1), np.uint8)
    for i, it in enumerate(items):       # rows start with the 0x00 prefix
        msgs[i, 1:1 + len(it)] = np.frombuffer(it, np.uint8)
    out = np.empty((n, 32), np.uint8)
    cap = _bucket_width(1 << 30)           # plan's largest level width
    for start in range(0, n, cap):
        end = min(start + cap, n)
        c = end - start
        bb = _bucket_width(c)
        mp = np.zeros((bb, maxlen + 1), np.uint8)
        mp[:c] = msgs[start:end]
        lp = np.ones((bb,), np.int64)
        lp[:c] = lens[start:end]
        blocks, active = _s.host_pad(mp, lp, nb)
        out[start:end] = np.asarray(
            jit_blocks(blocks, active), np.uint8)[:c]
    return _s.bytes_to_words(out)


def _kernel_levels_from_words(words, jits, keep_levels: bool):
    """Run the level kernel to the root.  Returns the level list (word
    arrays, leaves first) when ``keep_levels``, else just the root row."""
    import numpy as np

    from . import aotbundle as _aot

    jit_level, _, _s = jits
    cap = _bucket_width(1 << 30)           # plan's largest level width
    lv = words
    levels = [lv]
    while len(lv) > 1:
        m = len(lv) // 2
        left, right = lv[0:2 * m:2], lv[1:2 * m:2]
        out = np.empty((m, 8), np.uint32)
        for start in range(0, m, cap):
            end = min(start + cap, m)
            c = end - start
            bb = _bucket_width(c)
            lpad = np.zeros((bb, 8), np.uint32)
            rpad = np.zeros((bb, 8), np.uint32)
            lpad[:c], rpad[:c] = left[start:end], right[start:end]
            # AOT compile-bundle consult: a bundled level width skips
            # tracing/compiling on the first dispatch (warm boot)
            fn = _aot.lookup(f"merkle_level:{bb}") or jit_level
            out[start:end] = np.asarray(fn(lpad, rpad))[:c]
        if len(lv) & 1:
            out = np.concatenate([out, lv[-1:]])
        lv = out
        levels.append(lv)
    if not keep_levels:
        return lv
    _sdw = jits[2].words_to_bytes
    return [[row.tobytes() for row in _sdw(l_)] for l_ in levels]


def _root_kernel(items: list[bytes]) -> bytes | None:
    jits = _kernel_jits()
    if jits is None:
        return None
    words = _kernel_leaf_words(items, jits)
    root = _kernel_levels_from_words(words, jits, keep_levels=False)
    return jits[2].words_to_bytes(root)[0].tobytes()


def _levels_kernel(items: list[bytes]) -> list[list[bytes]] | None:
    jits = _kernel_jits()
    if jits is None:
        return None
    words = _kernel_leaf_words(items, jits)
    return _kernel_levels_from_words(words, jits, keep_levels=True)


def _build_levels(items: list[bytes]) -> list[list[bytes]]:
    """The dispatch ladder shared by the proof builders."""
    if _kernel_wanted(len(items)):
        levels = _levels_kernel(items)
        if levels is not None:
            return levels
    return _levels_native(items) or _levels_hashlib(items)


class Proof(NamedTuple):
    """Merkle inclusion proof (crypto/merkle/proof.go semantics).

    A NamedTuple rather than a dataclass: proofs are built in bulk (one
    per part / per tx) and never mutated, and tuple construction is
    C-speed — the dataclass ``__init__`` was ~40% of a 10k-leaf
    root+proofs build."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: tuple[bytes, ...] = ()

    def compute_root(self) -> bytes:
        return _compute_from_aunts(self.index, self.total, self.leaf_hash,
                                   self.aunts)

    def verify(self, root: bytes, leaf: bytes) -> bool:
        if self.total < 0 or self.index < 0 or self.index >= self.total:
            return False
        if leaf_hash(leaf) != self.leaf_hash:
            return False
        computed = self.compute_root()
        return computed is not None and computed == root


def _compute_from_aunts(index: int, total: int, leaf: bytes,
                        aunts: list[bytes]) -> bytes | None:
    if total == 0 or index >= total:
        return None
    if total == 1:
        return leaf if not aunts else None
    if not aunts:
        return None
    k = _split_point(total)
    if index < k:
        left = _compute_from_aunts(index, k, leaf, aunts[:-1])
        return None if left is None else inner_hash(left, aunts[-1])
    right = _compute_from_aunts(index - k, total - k, leaf, aunts[:-1])
    return None if right is None else inner_hash(aunts[-1], right)


def proofs_from_byte_slices_reference(items: list[bytes]
                                      ) -> tuple[bytes, list[Proof]]:
    """Recursive reference builder (crypto/merkle/proof.go shape): root
    hash + one inclusion proof per item.  Kept as the oracle the batched
    level-order path is pinned against, and as the tiny-tree fast path —
    a handful of leaves don't amortize the vectorized assembly."""
    total = len(items)
    leaves = [leaf_hash(it) for it in items]

    def build(lo: int, hi: int) -> tuple[bytes, dict[int, list[bytes]]]:
        n = hi - lo
        if n == 0:
            return _sha(b""), {}
        if n == 1:
            return leaves[lo], {lo: []}
        k = _split_point(n)
        lroot, lpaths = build(lo, lo + k)
        rroot, rpaths = build(lo + k, hi)
        paths = {}
        for i, p in lpaths.items():
            paths[i] = p + [rroot]
        for i, p in rpaths.items():
            paths[i] = p + [lroot]
        return inner_hash(lroot, rroot), paths

    root, paths = build(0, total)
    # paths accumulate bottom-up (deepest sibling first), which is exactly
    # the order _compute_from_aunts consumes (aunts[-1] = topmost).  Aunts
    # are tuples on EVERY construction path (here, the level-order
    # builder, and the wire decoders) so Proof equality is reliable.
    proofs = [Proof(total=total, index=i, leaf_hash=leaves[i],
                    aunts=tuple(paths[i])) for i in range(total)]
    return root, proofs


def _proofs_from_levels(levels: list[list[bytes]], total: int
                        ) -> tuple[bytes, list[Proof]]:
    """All aunt paths from the cached levels with zero re-hashing.

    Per level one vectorized sibling-index computation plus one
    ``itemgetter`` gather (both C-speed over all leaves at once);
    the per-leaf Python work is a single zip/list pass.  Aunts come out
    bottom-up (deepest first), matching ``_compute_from_aunts``."""
    import numpy as np

    root = levels[-1][0]
    if total == 1:
        return root, [Proof(1, 0, levels[0][0], ())]
    idx = np.arange(total)
    cols = []           # per level: sequence of that level's aunt per leaf
    starts = []         # per level: first leaf whose sibling is promoted
    for lvl_i in range(len(levels) - 1):
        nodes = levels[lvl_i]
        w = len(nodes)
        run = 1 << lvl_i
        # the only possible invalid sibling is the promoted odd tail:
        # ancestor w-1 with (w-1)^1 == w — a contiguous tail of leaves
        start = ((w - 1) << lvl_i) if ((w - 1) ^ 1) >= w else total
        if run >= 32:
            # deep levels: the aunt is constant over runs of 2^l leaves,
            # so sequence-multiply beats a per-leaf gather (None fills
            # the promoted tail; `start` keeps it out of every proof)
            col = []
            for j in range(w):
                sib = j ^ 1
                col.extend((nodes[sib] if sib < w else None,) * run)
            cols.append(col[:total])
        else:
            sib = (idx >> lvl_i) ^ 1
            np.minimum(sib, w - 1, out=sib)
            cols.append(itemgetter(*sib.tolist())(nodes))
        starts.append(start)
    min_start = min(starts, default=total)
    leaves = levels[0]
    nlv = len(cols)
    # bulk assembly, C-speed end to end: one zip builds each proof's
    # field tuple, Proof._make (tuple.__new__) materializes it.  Aunt
    # paths are tuples here — never mutated, and list() per proof would
    # be ~15% of the whole build.
    proofs = list(map(Proof._make,
                      zip(repeat(total, min_start), count(), leaves,
                          zip(*cols))))
    for i in range(min_start, total):    # promoted-tail leaves: filter
        aunts = tuple(cols[k][i] for k in range(nlv) if i < starts[k])
        proofs.append(Proof(total, i, leaves[i], aunts))
    return root, proofs


def proofs_from_byte_slices(items: list[bytes]) -> tuple[bytes, list[Proof]]:
    """Root hash + one inclusion proof per item, through the size-based
    engine dispatch (see the module docstring).  Bit-identical to
    :func:`proofs_from_byte_slices_reference` on every path."""
    total = len(items)
    if total < _PROOF_LEVEL_MIN:
        return proofs_from_byte_slices_reference(items)
    return _proofs_from_levels(_build_levels(items), total)


class TreeCache:
    """Retained per-level node cache of one tree: build the levels ONCE
    (through the same engine dispatch as :func:`proofs_from_byte_slices`),
    then emit inclusion proofs for ARBITRARY leaf indexes by pure index
    arithmetic over the cached levels — zero re-hashing per proof.

    This is the light-serving seam: a block's tx/validator tree is built
    on the first proof request and every later request (any subset of
    indexes, any order, any number of clients) is a gather.  Unlike
    :func:`proofs_from_byte_slices` it does not materialize all N proofs
    up front, so a 10k-leaf block whose clients only ever ask for a few
    hundred leaves never pays the full assembly.

    Proofs are bit-identical to the reference builder (aunts bottom-up,
    promoted odd-tail nodes skipped), pinned by tests."""

    __slots__ = ("levels", "total")

    def __init__(self, levels: list[list[bytes]], total: int):
        self.levels = levels
        self.total = total

    @classmethod
    def build(cls, items: list[bytes]) -> "TreeCache":
        n = len(items)
        if n == 0:
            return cls([[_sha(b"")]], 0)
        if n < _PROOF_LEVEL_MIN:
            return cls(_levels_hashlib(items), n)
        return cls(_build_levels(items), n)

    @property
    def root(self) -> bytes:
        return self.levels[-1][0]

    def nbytes(self) -> int:
        """Approximate retained size (cache accounting): 32 bytes per
        node across every level."""
        return 32 * sum(len(lv) for lv in self.levels)

    def proof(self, index: int) -> Proof:
        """Inclusion proof for leaf ``index`` (raises IndexError when out
        of range).  The ancestor of leaf i at level l is node i >> l, its
        sibling (i >> l) ^ 1 — absent exactly when the sibling index
        falls off the level's width (promoted odd tail)."""
        total = self.total
        if not 0 <= index < total:
            raise IndexError(f"leaf {index} out of range (total {total})")
        if total == 1:
            return Proof(1, 0, self.levels[0][0], ())
        aunts = []
        for lvl_i in range(len(self.levels) - 1):
            nodes = self.levels[lvl_i]
            sib = (index >> lvl_i) ^ 1
            if sib < len(nodes):
                aunts.append(nodes[sib])
        return Proof(total, index, self.levels[0][index], tuple(aunts))

    def proofs(self, indexes) -> list[Proof]:
        return [self.proof(i) for i in indexes]


# ------------------------------------------------------------- proof ops
# (crypto/merkle/proof_op.go + proof_value.go: composable proof chains for
# multi-store queries — ProofOperators.Verify walks ops leaf-to-root,
# each op transforming its input into the next layer's expected value)

@dataclass
class ProofOp:
    """Serialized proof step (type tag + key + opaque payload)."""

    type: str
    key: bytes
    data: bytes


class ProofOpError(Exception):
    pass


def kv_leaf(key: bytes, value: bytes) -> bytes:
    """Leaf encoding for provable KV stores: the KEY is bound into the
    leaf alongside the value hash (proof_value.go does the same via
    proto KVPair) — otherwise a prover could relabel any proven value
    under any key."""
    return (len(key).to_bytes(4, "big") + key
            + hashlib.sha256(value).digest())


class ValueOp:
    """Proves (key, value) -> store root: leaf = hash(kv_leaf(key,
    sha256(value))), then the merkle path in ``proof``
    (crypto/merkle/proof_value.go)."""

    TYPE = "simple:v"

    def __init__(self, key: bytes, proof: Proof):
        self.key = key
        self.proof = proof

    def run(self, args: list[bytes]) -> list[bytes]:
        if len(args) != 1:
            raise ProofOpError(f"ValueOp wants 1 arg, got {len(args)}")
        if leaf_hash(kv_leaf(self.key, args[0])) != self.proof.leaf_hash:
            raise ProofOpError("key/value does not match proof leaf")
        root = self.proof.compute_root()
        if root is None:
            raise ProofOpError("invalid merkle path")
        return [root]

    def proof_op(self) -> ProofOp:
        import msgpack

        return ProofOp(self.TYPE, self.key, msgpack.packb(
            {"t": self.proof.total, "i": self.proof.index,
             "l": self.proof.leaf_hash, "a": self.proof.aunts},
            use_bin_type=True))

    @classmethod
    def decode(cls, op: ProofOp) -> "ValueOp":
        import msgpack

        d = msgpack.unpackb(op.data, raw=False)
        return cls(op.key, Proof(d["t"], d["i"], d["l"], tuple(d["a"])))


_OP_DECODERS = {ValueOp.TYPE: ValueOp.decode}


def register_proof_op(type_: str, decoder) -> None:
    """proof_op.go ProofRuntime.RegisterOpDecoder."""
    _OP_DECODERS[type_] = decoder


class ProofOperators:
    """Ordered op chain: Verify(root, keypath, value) runs each op over
    the previous op's output, consuming keypath segments right-to-left
    (proof_op.go ProofOperators.Verify)."""

    def __init__(self, ops: list):
        self.ops = ops

    @classmethod
    def decode(cls, ops: list[ProofOp]) -> "ProofOperators":
        decoded = []
        for op in ops:
            dec = _OP_DECODERS.get(op.type)
            if dec is None:
                raise ProofOpError(f"unregistered proof op {op.type!r}")
            decoded.append(dec(op))
        return cls(decoded)

    def verify(self, root: bytes, keypath: list[bytes],
               value: bytes) -> None:
        """Raises ProofOpError unless the chain proves value@keypath
        under root."""
        if not self.ops:
            raise ProofOpError("empty proof op chain")
        args = [value]
        keys = list(keypath)
        for op in self.ops:
            if getattr(op, "key", b""):
                if not keys:
                    raise ProofOpError("keypath exhausted")
                if keys[-1] != op.key:
                    raise ProofOpError(
                        f"key mismatch: {keys[-1]!r} != {op.key!r}")
                keys.pop()
            args = op.run(args)
        if keys:
            raise ProofOpError(f"keypath not fully consumed: {keys!r}")
        if args != [root]:
            raise ProofOpError("computed root does not match")
