"""Pure-Python BLS12-381: fields, curves, pairing, signatures.

A dependency-free host implementation behind ``crypto/bls12381.py``'s
backend seam, so BLS keys WORK out of the box — the reference's default
build ships only an error stub (``crypto/bls12381/key.go``) and demands a
cgo+blst rebuild for functionality.

Scope and honesty notes:

- Field towers Fq2/Fq6/Fq12, optimal-ate Miller loop, factored final
  exponentiation ((p^6-1)(p^2+1) easy part via conjugation + a computed
  Frobenius^2, then the (p^4-p^2+1)/r hard exponent), and Jacobian
  scalar multiplication in G1/G2 (one inversion per mult, not per add).
- Point (de)serialization follows the zcash/blst compressed format
  (48-byte G1 / 96-byte G2, flag bits, lexicographic y-sign).
- Hash-to-curve is the STANDARD G2 suite,
  BLS12381G2_XMD:SHA-256_SSWU_RO (RFC 9380 §8.8.2): simple SWU on the
  isogenous curve E', the 3-isogeny of App. E.3, h_eff cofactor
  clearing — pinned byte-exactly to the RFC's QUUX test vectors in
  tests/test_bls12381.py, so signatures interoperate with blst-class
  implementations.
- Performance: a verify costs two pairings — ~0.3 s in CPython (was
  ~1.3 s before the factored final exp + Jacobian mults).  A usable
  fallback; still not a production signer (variable-time).

Sanity is enforced by tests: generator/curve/subgroup relations,
pairing bilinearity e(aP, bQ) == e(P, Q)^(ab), serialization
round-trips, and sign/verify semantics.
"""

from __future__ import annotations

import hashlib
import hmac

# ---------------------------------------------------------------- params

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x (the curve family seed); negative.
X = -0xD201000000010000

G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
G2_X0 = 0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8
G2_X1 = 0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E
G2_Y0 = 0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801
G2_Y1 = 0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE

DST = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_"


# ------------------------------------------------------------------- Fq

def _inv(a: int, m: int = P) -> int:
    return pow(a, m - 2, m)


# ------------------------------------------------------------------ Fq2
# Fq2 = Fq[u] / (u^2 + 1); elements (c0, c1) = c0 + c1*u

def f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_neg(a):
    return (-a[0] % P, -a[1] % P)


def f2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = a0 * b0
    t1 = a1 * b1
    t2 = (a0 + a1) * (b0 + b1)
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def f2_sqr(a):
    a0, a1 = a
    t = a0 * a1
    return ((a0 + a1) * (a0 - a1) % P, (t + t) % P)


def f2_scalar(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


def f2_inv(a):
    a0, a1 = a
    d = _inv((a0 * a0 + a1 * a1) % P)
    return (a0 * d % P, -a1 * d % P)


def f2_conj(a):
    return (a[0], -a[1] % P)


def f2_pow(a, e: int):
    out = F2_ONE
    base = a
    while e:
        if e & 1:
            out = f2_mul(out, base)
        base = f2_sqr(base)
        e >>= 1
    return out


F2_ZERO = (0, 0)
F2_ONE = (1, 0)
F2_U = (0, 1)
XI = (1, 1)                 # the Fq6 non-residue 1 + u


def f2_is_zero(a):
    return a[0] == 0 and a[1] == 0


def f2_legendre(a):
    """1 if QR, -1 if non-QR, 0 if zero (via a^((p^2-1)/2))."""
    if f2_is_zero(a):
        return 0
    r = f2_pow(a, (P * P - 1) // 2)
    return 1 if r == F2_ONE else -1


def f2_sqrt(a):
    """Square root in Fq2, or None.  p ≡ 3 (mod 4) enables the
    complex-method shortcut (Adj–Rodríguez-Henríquez)."""
    if f2_is_zero(a):
        return F2_ZERO
    a1 = f2_pow(a, (P - 3) // 4)
    alpha = f2_mul(f2_sqr(a1), a)
    x0 = f2_mul(a1, a)
    if alpha == (P - 1, 0):
        # sqrt = i * x0
        return (-x0[1] % P, x0[0])
    b = f2_pow(f2_add(F2_ONE, alpha), (P - 1) // 2)
    x = f2_mul(b, x0)
    return x if f2_sqr(x) == a else None


XI_INV = f2_inv(XI)         # hoisted: the line embeddings use it per step


# ------------------------------------------------------------------ Fq6
# Fq6 = Fq2[v] / (v^3 - XI); elements (c0, c1, c2)

def f6_add(a, b):
    return tuple(f2_add(x, y) for x, y in zip(a, b))


def f6_sub(a, b):
    return tuple(f2_sub(x, y) for x, y in zip(a, b))


def f6_neg(a):
    return tuple(f2_neg(x) for x in a)


def _mul_xi(a):
    # (c0 + c1 u)(1 + u) = (c0 - c1) + (c0 + c1) u
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def f6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = f2_mul(a0, b0)
    t1 = f2_mul(a1, b1)
    t2 = f2_mul(a2, b2)
    c0 = f2_add(t0, _mul_xi(f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)),
                                   f2_add(t1, t2))))
    c1 = f2_add(f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)),
                       f2_add(t0, t1)), _mul_xi(t2))
    c2 = f2_add(f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)),
                       f2_add(t0, t2)), t1)
    return (c0, c1, c2)


def f6_sqr(a):
    return f6_mul(a, a)


def f6_inv(a):
    a0, a1, a2 = a
    c0 = f2_sub(f2_sqr(a0), _mul_xi(f2_mul(a1, a2)))
    c1 = f2_sub(_mul_xi(f2_sqr(a2)), f2_mul(a0, a1))
    c2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    t = f2_add(_mul_xi(f2_add(f2_mul(a2, c1), f2_mul(a1, c2))),
               f2_mul(a0, c0))
    ti = f2_inv(t)
    return (f2_mul(c0, ti), f2_mul(c1, ti), f2_mul(c2, ti))


F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


# ----------------------------------------------------------------- Fq12
# Fq12 = Fq6[w] / (w^2 - v); elements (c0, c1)

def f12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    # v * t1
    vt1 = (_f6_mul_v(t1))
    c0 = f6_add(t0, vt1)
    c1 = f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)), f6_add(t0, t1))
    return (c0, c1)


def _f6_mul_v(a):
    # (c0 + c1 v + c2 v^2) * v = XI*c2 + c0 v + c1 v^2
    return (_mul_xi(a[2]), a[0], a[1])


def f12_sqr(a):
    return f12_mul(a, a)


def f12_inv(a):
    a0, a1 = a
    t = f6_sub(f6_mul(a0, a0), _f6_mul_v(f6_mul(a1, a1)))
    ti = f6_inv(t)
    return (f6_mul(a0, ti), f6_neg(f6_mul(a1, ti)))


def f12_conj(a):
    """Conjugation = Frobenius^6: c0 - c1 w."""
    return (a[0], f6_neg(a[1]))


def f12_pow(a, e: int):
    if e < 0:
        return f12_pow(f12_inv(a), -e)
    out = F12_ONE
    base = a
    while e:
        if e & 1:
            out = f12_mul(out, base)
        base = f12_sqr(base)
        e >>= 1
    return out


F12_ONE = (F6_ONE, F6_ZERO)


# ------------------------------------------------------------ G1 points
# Affine (x, y) with None = infinity.  y^2 = x^3 + 4.

def g1_is_on_curve(pt):
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - 4) % P == 0


def g1_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1) * _inv(2 * y1) % P
    else:
        lam = (y2 - y1) * _inv(x2 - x1) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def g1_neg(p1):
    if p1 is None:
        return None
    return (p1[0], -p1[1] % P)


def g1_mul(p1, k: int):
    """Scalar multiplication in JACOBIAN coordinates: the affine
    double-and-add it replaces paid one field inversion per point op
    (~0.35 ms each); here one inversion converts back at the end."""
    if p1 is None or k == 0:
        return None
    if k < 0:
        return g1_neg(g1_mul(p1, -k))
    ax, ay = p1
    X = Y = Z = None                       # Jacobian accumulator (inf)

    def dbl(X, Y, Z):
        # dbl-2009-l (a = 0)
        A = X * X % P
        B = Y * Y % P
        C = B * B % P
        D = 2 * ((X + B) * (X + B) - A - C) % P
        M = 3 * A % P
        X3 = (M * M - 2 * D) % P
        Y3 = (M * (D - X3) - 8 * C) % P
        Z3 = 2 * Y * Z % P
        return X3, Y3, Z3

    for bit in bin(k)[2:]:
        if X is not None:
            X, Y, Z = dbl(X, Y, Z)
        if bit == "1":
            if X is None or Z == 0:
                X, Y, Z = ax, ay, 1
                continue
            # mixed add (affine q): madd-2007-bl
            Z1Z1 = Z * Z % P
            U2 = ax * Z1Z1 % P
            S2 = ay * Z % P * Z1Z1 % P
            H = (U2 - X) % P
            Rr = (S2 - Y) % P
            if H == 0:
                if Rr != 0:
                    X, Y, Z = 0, 1, 0          # P + (-P) = inf
                    continue
                X, Y, Z = dbl(X, Y, Z)         # equal points: double
                continue
            HH = H * H % P
            HHH = HH * H % P
            V = X * HH % P
            X3 = (Rr * Rr - HHH - 2 * V) % P
            Y3 = (Rr * (V - X3) - Y * HHH) % P
            Z3 = Z * H % P
            X, Y, Z = X3, Y3, Z3
    if X is None or Z == 0:
        return None
    zi = _inv(Z)
    zi2 = zi * zi % P
    return (X * zi2 % P, Y * zi2 % P * zi % P)


G1 = (G1_X, G1_Y)


# ------------------------------------------------------------ G2 points
# Affine ((x0,x1), (y0,y1)) over Fq2; y^2 = x^3 + 4(1+u).

B2 = f2_scalar(XI, 4)


def g2_is_on_curve(pt):
    if pt is None:
        return True
    x, y = pt
    return f2_sub(f2_sqr(y), f2_add(f2_mul(f2_sqr(x), x), B2)) == F2_ZERO


def g2_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if f2_is_zero(f2_add(y1, y2)):
            return None
        lam = f2_mul(f2_scalar(f2_sqr(x1), 3),
                     f2_inv(f2_scalar(y1, 2)))
    else:
        lam = f2_mul(f2_sub(y2, y1), f2_inv(f2_sub(x2, x1)))
    x3 = f2_sub(f2_sub(f2_sqr(lam), x1), x2)
    y3 = f2_sub(f2_mul(lam, f2_sub(x1, x3)), y1)
    return (x3, y3)


def g2_neg(p):
    if p is None:
        return None
    return (p[0], f2_neg(p[1]))


def g2_mul(p, k: int):
    """Jacobian scalar multiplication over Fq2 (see g1_mul: the affine
    chain paid one f2_inv per point op; one inversion remains)."""
    if p is None or k == 0:
        return None
    if k < 0:
        return g2_neg(g2_mul(p, -k))
    ax, ay = p
    X = Y = Z = None

    def dbl(X, Y, Z):
        A = f2_sqr(X)
        B = f2_sqr(Y)
        C = f2_sqr(B)
        D = f2_scalar(f2_sub(f2_sqr(f2_add(X, B)), f2_add(A, C)), 2)
        M = f2_scalar(A, 3)
        X3 = f2_sub(f2_sqr(M), f2_scalar(D, 2))
        Y3 = f2_sub(f2_mul(M, f2_sub(D, X3)), f2_scalar(C, 8))
        Z3 = f2_scalar(f2_mul(Y, Z), 2)
        return X3, Y3, Z3

    for bit in bin(k)[2:]:
        if X is not None:
            X, Y, Z = dbl(X, Y, Z)
        if bit == "1":
            if X is None or f2_is_zero(Z):
                X, Y, Z = ax, ay, F2_ONE
                continue
            Z1Z1 = f2_sqr(Z)
            U2 = f2_mul(ax, Z1Z1)
            S2 = f2_mul(f2_mul(ay, Z), Z1Z1)
            H = f2_sub(U2, X)
            Rr = f2_sub(S2, Y)
            if f2_is_zero(H):
                if not f2_is_zero(Rr):
                    X, Y, Z = F2_ZERO, F2_ONE, F2_ZERO     # inf
                    continue
                X, Y, Z = dbl(X, Y, Z)
                continue
            HH = f2_sqr(H)
            HHH = f2_mul(HH, H)
            V = f2_mul(X, HH)
            X3 = f2_sub(f2_sub(f2_sqr(Rr), HHH), f2_scalar(V, 2))
            Y3 = f2_sub(f2_mul(Rr, f2_sub(V, X3)), f2_mul(Y, HHH))
            Z3 = f2_mul(Z, H)
            X, Y, Z = X3, Y3, Z3
    if X is None or f2_is_zero(Z):
        return None
    zi = f2_inv(Z)
    zi2 = f2_sqr(zi)
    return (f2_mul(X, zi2), f2_mul(f2_mul(Y, zi2), zi))


G2 = ((G2_X0, G2_X1), (G2_Y0, G2_Y1))


def g2_in_subgroup(pt) -> bool:
    return g2_is_on_curve(pt) and g2_mul(pt, R) is None


def g1_in_subgroup(pt) -> bool:
    return g1_is_on_curve(pt) and g1_mul(pt, R) is None


# -------------------------------------------------------------- pairing
# Optimal ate: f = f_{|X|,Q}(P) over the twist, conjugated for X < 0,
# then the full final exponentiation (p^12 - 1)/r.
#
# Line evaluations embed G2 (on the twist) and G1 coordinates into Fq12
# directly: with the tower above, an Fq2 point (x', y') on the twist maps
# to (x' / w^2, y' / w^3) on E(Fq12).  We track lines symbolically in the
# sparse form l = a + b*w + c*w^3 with Fq2 coefficients.

def _sparse_line(a, b, c):
    """a + b*w^2... represented as a full Fq12 element.

    Coefficient positions: Fq12 element ((c0,c1,c2),(c3,c4,c5)) equals
    c0 + c1 v + c2 v^2 + w (c3 + c4 v + c5 v^2), with v = w^2.
    """
    return ((a, F2_ZERO, F2_ZERO), (b, c, F2_ZERO))


def _line(q1, q2, p1):
    """The line through twist points q1, q2 (or tangent if equal),
    evaluated at the G1 point p1, embedded in Fq12."""
    x1, y1 = q1
    x2, y2 = q2
    xp, yp = p1
    if x1 == x2 and y1 == y2:
        lam = f2_mul(f2_scalar(f2_sqr(x1), 3), f2_inv(f2_scalar(y1, 2)))
    elif x1 == x2:
        # vertical: x - x1 evaluated at untwisted coordinates
        return _sparse_line(f2_scalar(F2_ONE, xp), f2_neg(x1), F2_ZERO), \
            None
    else:
        lam = f2_mul(f2_sub(y2, y1), f2_inv(f2_sub(x2, x1)))
    # l(P) = yp - y1 - lam (xp - x1): embed with the twist untwisting.
    # Using the untwist x = x'/w^2, y = y'/w^3 and clearing w^3:
    #   l = yp * w^3 ... constant-free sparse form:
    #   l = (yp) * 1  - (lam * xp) * w^... — use the standard D-twist form:
    # l = lam*xp - y1*w ... To sidestep per-term bookkeeping errors we
    # evaluate the line GENERICALLY in Fq12 (slower, but transparently
    # correct): L(P) = (y_P - y_1) - lam * (x_P - x_1) with all values
    # embedded in Fq12.
    y_p = _embed_fq(yp)
    x_p = _embed_fq(xp)
    x_1 = _embed_g2_x(x1)
    y_1 = _embed_g2_y(y1)
    lam12 = _embed_g2_lambda(lam)
    val = f12_sub(f12_sub(y_p, y_1), f12_mul(lam12, f12_sub(x_p, x_1)))
    return val, lam


def f12_sub(a, b):
    return (f6_sub(a[0], b[0]), f6_sub(a[1], b[1]))


def _embed_fq(c: int):
    """Fq scalar into Fq12."""
    return (((c % P, 0), F2_ZERO, F2_ZERO), F6_ZERO)


def _embed_g2_x(x):
    """Twist x-coordinate x' -> x'/w^2: w^2 = v, and v^-1 = v^2/XI
    (since v^3 = XI), so the element is x' * v^2 / XI."""
    return ((F2_ZERO, F2_ZERO, f2_mul(x, XI_INV)), F6_ZERO)


def _embed_g2_y(y):
    """y'/w^3: w^3 = v*w and (v w)^-1 = v w / XI, so the element is
    y' * v w / XI."""
    return (F6_ZERO, (F2_ZERO, f2_mul(y, XI_INV), F2_ZERO))


def _embed_g2_lambda(lam):
    """lam is dy'/dx' on the twist; untwisted slope = lam / w, and
    w^-1 = w v^2 / XI (since w * w v^2 = v^3 = XI)."""
    return (F6_ZERO, (F2_ZERO, F2_ZERO, f2_mul(lam, XI_INV)))


def miller_loop(q, p1):
    """f_{|X|, q}(p1) with q in G2 (twist affine), p1 in G1 affine."""
    if q is None or p1 is None:
        return F12_ONE
    t = q
    f = F12_ONE
    n = -X                          # positive loop count
    for bit in bin(n)[3:]:
        val, lam = _line(t, t, p1)
        if lam is None:
            f = f12_mul(f12_sqr(f), val)
            t = None
        else:
            f = f12_mul(f12_sqr(f), val)
            t = g2_add(t, t)
        if bit == "1":
            val, lam = _line(t, q, p1)
            f = f12_mul(f, val)
            t = g2_add(t, q)
    # X < 0: conjugate (f^(p^6) = 1/f after the easy part)
    return f12_conj(f)


# Final exponentiation, factored (p^12-1)/r = (p^6-1)(p^2+1) * hard
# with hard = (p^4 - p^2 + 1)/r.  The easy part costs one conjugation,
# one Fq12 inversion, and one Frobenius^2; the hard part is a ~1550-bit
# exponent — ~3x less work than the previous monolithic
# f^((p^12-1)/r) over a ~4600-bit exponent, with identical output
# (it is the same group exponent, just factored).
_HARD_EXP = (P ** 4 - P ** 2 + 1) // R
assert _HARD_EXP * R == P ** 4 - P ** 2 + 1

# Frobenius^2 on the tower: Fq2 is FIXED by x -> x^(p^2) (|Fq2| = p^2),
# so phi2 multiplies each w^i v^j coefficient by the CONSTANT
# (XI^((p^2-1)/6))^k for its basis power k in {0..5} — computed here,
# not transcribed.
_FROB2_GAMMA = [f2_pow(XI, k * (P * P - 1) // 6) for k in range(6)]
# basis powers k for ((c00, c01, c02), (c10, c11, c12)):
# c0j has w-degree 0, v-degree j -> k = 2j; c1j -> w v^j -> k = 2j + 1


def _f12_frob2(a):
    (c00, c01, c02), (c10, c11, c12) = a
    g = _FROB2_GAMMA
    return ((f2_mul(c00, g[0]), f2_mul(c01, g[2]), f2_mul(c02, g[4])),
            (f2_mul(c10, g[1]), f2_mul(c11, g[3]), f2_mul(c12, g[5])))


def final_exponentiation(f):
    g = f12_mul(f12_conj(f), f12_inv(f))       # f^(p^6 - 1)
    g = f12_mul(_f12_frob2(g), g)              # ^(p^2 + 1)
    return f12_pow(g, _HARD_EXP)


def pairing(p1, q) -> tuple:
    """e(P, Q) with P in G1, Q in G2 — full final exponentiation."""
    if p1 is None or q is None:
        return F12_ONE
    return final_exponentiation(miller_loop(q, p1))


# ------------------------------------------- serialization (zcash/blst)

_HALF = (P - 1) // 2


def _fq2_larger(y) -> bool:
    """Lexicographic sign: compare c1 first, then c0."""
    y0, y1 = y
    if y1 != 0:
        return y1 > _HALF
    return y0 > _HALF


def g1_compress(pt) -> bytes:
    if pt is None:
        return bytes([0xC0] + [0] * 47)
    x, y = pt
    flags = 0x80 | (0x20 if y > _HALF else 0)
    raw = bytearray(x.to_bytes(48, "big"))
    raw[0] |= flags
    return bytes(raw)


def g1_decompress(raw: bytes):
    if len(raw) != 48 or not raw[0] & 0x80:
        raise ValueError("bad G1 compressed encoding")
    if raw[0] & 0x40:
        if any(raw[1:]) or raw[0] != 0xC0:
            raise ValueError("bad G1 infinity encoding")
        return None
    sign = bool(raw[0] & 0x20)
    x = int.from_bytes(bytes([raw[0] & 0x1F]) + raw[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y2 = (x * x * x + 4) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise ValueError("G1 x not on curve")
    if (y > _HALF) != sign:
        y = P - y
    return (x, y)


def g2_compress(pt) -> bytes:
    if pt is None:
        return bytes([0xC0] + [0] * 95)
    x, y = pt
    flags = 0x80 | (0x20 if _fq2_larger(y) else 0)
    raw = bytearray(x[1].to_bytes(48, "big") + x[0].to_bytes(48, "big"))
    raw[0] |= flags
    return bytes(raw)


def g2_decompress(raw: bytes):
    if len(raw) != 96 or not raw[0] & 0x80:
        raise ValueError("bad G2 compressed encoding")
    if raw[0] & 0x40:
        if any(raw[1:]) or raw[0] != 0xC0:
            raise ValueError("bad G2 infinity encoding")
        return None
    sign = bool(raw[0] & 0x20)
    x1 = int.from_bytes(bytes([raw[0] & 0x1F]) + raw[1:48], "big")
    x0 = int.from_bytes(raw[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    y2 = f2_add(f2_mul(f2_sqr(x), x), B2)
    y = f2_sqrt(y2)
    if y is None:
        raise ValueError("G2 x not on curve")
    if _fq2_larger(y) != sign:
        y = f2_neg(y)
    return (x, y)


# --------------------------------------------------------- hash to G2
# RFC 9380: hash_to_field via expand_message_xmd(SHA-256), then the
# generic Shallue–van de Woestijne map (§6.6.1) + cofactor clearing.
# (See module docstring: the standard G2 suite uses SSWU+isogeny and
# yields different points; this choice is self-interop.)

def _expand_message_xmd(msg: bytes, dst: bytes, length: int) -> bytes:
    ell = (length + 31) // 32
    if ell > 255 or len(dst) > 255:
        raise ValueError("expand_message_xmd bounds")
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(64)
    l_i_b = length.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    bi = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = bi
    for i in range(2, ell + 1):
        bi = hashlib.sha256(
            bytes(a ^ b for a, b in zip(b0, bi))
            + bytes([i]) + dst_prime).digest()
        out += bi
    return out[:length]


def _hash_to_field_fq2(msg: bytes, count: int, dst: bytes):
    length = count * 2 * 64
    uniform = _expand_message_xmd(msg, dst, length)
    out = []
    for i in range(count):
        c0 = int.from_bytes(uniform[i * 128:i * 128 + 64], "big") % P
        c1 = int.from_bytes(uniform[i * 128 + 64:i * 128 + 128], "big") % P
        out.append((c0, c1))
    return out


# -------------------------- standard-suite SSWU + 3-isogeny (RFC 9380)
# BLS12381G2_XMD:SHA-256_SSWU_RO: simple SWU on the isogenous curve
# E': y^2 = x^3 + A'x + B' over Fq2 (§8.8.2), then the 3-isogeny to E
# (App. E.3), then h_eff cofactor clearing.  This REPLACES the previous
# SVDW map: SVDW was uniform but self-interop only; SSWU makes
# hash_to_g2 byte-compatible with blst and every other standard-suite
# implementation (pinned by the RFC's own QUUX test vectors in
# tests/test_bls12381.py).

_SSWU_A = (0, 240)                          # A' = 240 I
_SSWU_B = (1012, 1012)                      # B' = 1012 (1 + I)
_SSWU_Z = (P - 2, P - 1)                    # Z  = -(2 + I)

# 3-isogeny coefficients (RFC 9380 App. E.3), Horner order low->high
_K = 0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6
_ISO3_XNUM = [
    (_K, _K),
    (0, 0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A),
    (0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
     0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D),
    (0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1, 0),
]
_ISO3_XDEN = [
    (0, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63),
    (0xC, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F),
    (1, 0),                                  # monic x^2 term
]
_ISO3_YNUM = [
    (0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
     0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706),
    (0, 0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE),
    (0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
     0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F),
    (0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10, 0),
]
_ISO3_YDEN = [
    (0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB),
    (0, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3),
    (0x12, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99),
    (1, 0),                                  # monic x^3 term
]

# h_eff for the G2 suite (RFC 9380 §8.8.2) — NOT the plain cofactor h2:
# the standard suite's vectors and every interop implementation clear
# with this value (h_eff ≡ c * h2 with c coprime to r, so both land in
# G2, but on DIFFERENT points of it)
H_EFF = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551


def _sgn0_fq2(x) -> int:
    """RFC 9380 §4.1 sgn0 for m = 2."""
    return (x[0] & 1) | ((x[0] == 0) & (x[1] & 1))


def _horner(coeffs, x):
    acc = coeffs[-1]
    for c in reversed(coeffs[:-1]):
        acc = f2_add(f2_mul(acc, x), c)
    return acc


def _map_to_curve_sswu(u):
    """Simple SWU on E' (RFC 9380 §6.6.2), returning an E' point."""
    A, B, Z = _SSWU_A, _SSWU_B, _SSWU_Z
    u2 = f2_sqr(u)
    zu2 = f2_mul(Z, u2)
    tv = f2_add(f2_sqr(zu2), zu2)            # Z^2 u^4 + Z u^2
    if f2_is_zero(tv):
        # exceptional case: x1 = B / (Z A)
        x1 = f2_mul(B, f2_inv(f2_mul(Z, A)))
    else:
        x1 = f2_mul(f2_mul(f2_neg(B), f2_inv(A)),
                    f2_add(F2_ONE, f2_inv(tv)))
    gx1 = f2_add(f2_add(f2_mul(f2_sqr(x1), x1), f2_mul(A, x1)), B)
    y1 = f2_sqrt(gx1)
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = f2_mul(zu2, x1)
        gx2 = f2_add(f2_add(f2_mul(f2_sqr(x2), x2), f2_mul(A, x2)), B)
        y2 = f2_sqrt(gx2)
        if y2 is None:                       # impossible by SWU theory
            raise RuntimeError("SSWU: neither candidate square")
        x, y = x2, y2
    if _sgn0_fq2(u) != _sgn0_fq2(y):
        y = f2_neg(y)
    return (x, y)


def _iso3_map(pt):
    """The 3-isogeny E' -> E (App. E.3 rational maps)."""
    x, y = pt
    xn = _horner(_ISO3_XNUM, x)
    xd = _horner(_ISO3_XDEN, x)
    yn = _horner(_ISO3_YNUM, x)
    yd = _horner(_ISO3_YDEN, x)
    if f2_is_zero(xd) or f2_is_zero(yd):
        return None                          # exceptional: infinity
    X = f2_mul(xn, f2_inv(xd))
    Y = f2_mul(y, f2_mul(yn, f2_inv(yd)))
    return (X, Y)


def hash_to_g2(msg: bytes, dst: bytes = DST):
    """BLS12381G2_XMD:SHA-256_SSWU_RO hash_to_curve (RFC 9380 §8.8.2):
    standard-suite, byte-compatible with blst (QUUX vectors pinned in
    tests/test_bls12381.py)."""
    u0, u1 = _hash_to_field_fq2(msg, 2, dst)
    q0 = _iso3_map(_map_to_curve_sswu(u0))
    q1 = _iso3_map(_map_to_curve_sswu(u1))
    return g2_mul(g2_add(q0, q1), H_EFF)


# ------------------------------------------------------------ signatures

def keygen(ikm: bytes, key_info: bytes = b"") -> int:
    """RFC-style HKDF keygen (draft-irtf-cfrg-bls-signature KeyGen)."""
    if len(ikm) < 32:
        raise ValueError("ikm must be >= 32 bytes")
    salt = b"BLS-SIG-KEYGEN-SALT-"
    sk = 0
    while sk == 0:
        prk = hmac.new(hashlib.sha256(salt).digest(),
                       ikm + b"\x00", hashlib.sha256).digest()
        okm = b""
        t = b""
        info = key_info + (48).to_bytes(2, "big")
        for i in range(1, 3):
            t = hmac.new(prk, t + info + bytes([i]),
                         hashlib.sha256).digest()
            okm += t
        sk = int.from_bytes(okm[:48], "big") % R
        salt = hashlib.sha256(salt).digest()
    return sk


def sk_to_pk(sk: int) -> bytes:
    return g1_compress(g1_mul(G1, sk))


def sign(sk: int, msg: bytes) -> bytes:
    return g2_compress(g2_mul(hash_to_g2(msg), sk))


def verify(pk_raw: bytes, msg: bytes, sig_raw: bytes) -> bool:
    try:
        pk = g1_decompress(pk_raw)
        sig = g2_decompress(sig_raw)
    except ValueError:
        return False
    if pk is None or sig is None:
        return False
    if not g1_in_subgroup(pk) or not g2_in_subgroup(sig):
        return False
    h = hash_to_g2(msg)
    # e(pk, H(m)) == e(g1, sig)  <=>  e(pk, H(m)) * e(-g1, sig) == 1
    f = f12_mul(miller_loop(h, pk), miller_loop(sig, g1_neg(G1)))
    return final_exponentiation(f) == F12_ONE


# ----------------------------------------------------------- aggregation
# Same-message aggregation (draft-irtf-cfrg-bls-signature §2.8/§3.3.4):
# signatures add in G2, pubkeys add in G1, and FastAggregateVerify is one
# ordinary verification of the aggregate pair.  The Basic (NUL_) suite is
# rogue-key-UNSAFE for same-message aggregation on its own; the commit
# layer requires a proof of possession per BLS validator key (the POP_
# DST below), which restores safety without changing the vote
# ciphersuite — see docs/explanation/bls-aggregation.md.

DST_POP = b"BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"


def aggregate_signatures(sigs: list) -> bytes:
    """Sum of G2 signatures, compressed.  Every input must decode to a
    non-infinity subgroup point; raises ValueError otherwise (an
    aggregate built from an unchecked signature would pin rejection on
    the whole cohort instead of the bad lane)."""
    if not sigs:
        raise ValueError("cannot aggregate an empty signature set")
    acc = None
    for raw in sigs:
        pt = g2_decompress(bytes(raw))
        if pt is None or not g2_in_subgroup(pt):
            raise ValueError("aggregate input not a valid G2 signature")
        acc = pt if acc is None else g2_add(acc, pt)
    return g2_compress(acc)


def aggregate_pubkeys(pks: list) -> bytes:
    """Sum of G1 pubkeys, compressed; same strictness as signatures."""
    if not pks:
        raise ValueError("cannot aggregate an empty pubkey set")
    acc = None
    for raw in pks:
        pt = g1_decompress(bytes(raw))
        if pt is None or not g1_in_subgroup(pt):
            raise ValueError("aggregate input not a valid G1 pubkey")
        acc = pt if acc is None else g1_add(acc, pt)
    return g1_compress(acc)


def fast_aggregate_verify(pks: list, msg: bytes, sig_raw: bytes) -> bool:
    """FastAggregateVerify: all signers signed the SAME msg."""
    if not pks:
        return False
    try:
        agg_pk = aggregate_pubkeys(pks)
    except ValueError:
        return False
    return verify(agg_pk, msg, sig_raw)


def pop_prove(sk: int) -> bytes:
    """Proof of possession: sign the pubkey bytes under the POP_ DST
    (draft-irtf-cfrg-bls-signature §3.3.2, blst/blspy-compatible)."""
    pk_raw = sk_to_pk(sk)
    return g2_compress(g2_mul(hash_to_g2(pk_raw, DST_POP), sk))


def pop_verify(pk_raw: bytes, pop_raw: bytes) -> bool:
    """PopVerify (§3.3.3): the rogue-key gate every BLS validator key
    must pass before its votes may fold into an aggregate."""
    try:
        pk = g1_decompress(bytes(pk_raw))
        pop = g2_decompress(bytes(pop_raw))
    except ValueError:
        return False
    if pk is None or pop is None:
        return False
    if not g1_in_subgroup(pk) or not g2_in_subgroup(pop):
        return False
    h = hash_to_g2(bytes(pk_raw), DST_POP)
    f = f12_mul(miller_loop(h, pk), miller_loop(pop, g1_neg(G1)))
    return final_exponentiation(f) == F12_ONE


# Affine pubkey tables: the per-valset cache decompresses and
# subgroup-checks each key ONCE (pk_to_affine); per-commit aggregation is
# then pure affine adds over x||y big-endian coordinates, and the
# verifier pays exactly two Miller loops (verify_agg_affine).

def _affine_parse(raw: bytes):
    raw = bytes(raw)
    if len(raw) != 96:
        raise ValueError("affine G1 point must be 96 bytes (x||y)")
    x = int.from_bytes(raw[:48], "big")
    y = int.from_bytes(raw[48:], "big")
    if x >= P or y >= P or not g1_is_on_curve((x, y)):
        raise ValueError("affine input not on the G1 curve")
    return (x, y)


def pk_to_affine(pk_raw: bytes) -> bytes:
    """Decompress + subgroup-check a pubkey into x||y affine bytes."""
    pt = g1_decompress(bytes(pk_raw))
    if pt is None or not g1_in_subgroup(pt):
        raise ValueError("not a valid G1 pubkey")
    x, y = pt
    return x.to_bytes(48, "big") + y.to_bytes(48, "big")


def aggregate_affine(pts: list) -> bytes:
    """Sum of affine points, as affine bytes; subgroup membership was
    vouched for by pk_to_affine when the table was built."""
    if not pts:
        raise ValueError("cannot aggregate an empty point set")
    acc = None
    for raw in pts:
        acc = g1_add(acc, _affine_parse(raw))
    if acc is None:
        raise ValueError("aggregate is the point at infinity")
    x, y = acc
    return x.to_bytes(48, "big") + y.to_bytes(48, "big")


def verify_agg_affine(xy: bytes, msg: bytes, sig_raw: bytes) -> bool:
    """Verify an aggregate signature against a pre-aggregated affine
    pubkey: two Miller loops + one final exponentiation."""
    try:
        apk = _affine_parse(xy)
        sig = g2_decompress(bytes(sig_raw))
    except ValueError:
        return False
    if sig is None or not g2_in_subgroup(sig):
        return False
    h = hash_to_g2(msg)
    f = f12_mul(miller_loop(h, apk), miller_loop(sig, g1_neg(G1)))
    return final_exponentiation(f) == F12_ONE
