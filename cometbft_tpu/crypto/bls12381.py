"""BLS12-381 keys (reference: ``crypto/bls12381/``).

The reference gates its real implementation behind the ``bls12381`` build
tag (cgo -> supranational/blst, ``crypto/bls12381/key_bls12381.go:1-30``);
default builds ship an error-returning stub with ``Enabled = false``
(``crypto/bls12381/key.go``).  This module goes further: a bundled
pure-Python implementation (``_bls12381_py``) makes BLS keys functional
with no extra dependencies, and the backend seam automatically upgrades
to a standard-ciphersuite host library (``py_ecc`` or ``blspy``) when one
is importable.  ``ENABLED`` and :class:`ErrDisabled` are retained for
surface parity with the reference; with the bundled fallback they are
always True / never raised.

Sizes follow the min-pubkey-size scheme the reference uses (blst minimal
public keys): 32-byte private keys, 48-byte compressed G1 public keys,
96-byte compressed G2 signatures.
"""

from __future__ import annotations

from .keys import BLS12381_KEY_TYPE, PrivKey, PubKey, address_hash

PRIV_KEY_SIZE = 32
PUB_KEY_SIZE = 48
SIGNATURE_LENGTH = 96


class ErrDisabled(NotImplementedError):
    """bls12_381 is disabled (no host BLS backend in this build) —
    the reference's ``bls12381.ErrDisabled``."""

    def __init__(self):
        super().__init__(
            "bls12_381 is disabled: no host BLS backend available "
            "(the reference equally requires the `bls12381` build tag + "
            "blst; install py_ecc or blspy to enable)")


class _PyEccBackend:
    """Adapter over py_ecc's basic ciphersuite (G2Basic =
    BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_, minimal-pubkey-size:
    48-byte G1 pubkeys / 96-byte G2 signatures, the reference's blst
    layout)."""

    def __init__(self, impl):
        self._impl = impl

    def key_gen(self, ikm: bytes) -> int:
        return int(self._impl.KeyGen(ikm))

    def sk_to_pk(self, sk: int) -> bytes:
        return bytes(self._impl.SkToPk(sk))

    def sign(self, sk: int, msg: bytes) -> bytes:
        return bytes(self._impl.Sign(sk, msg))

    def verify(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        return bool(self._impl.Verify(pk, msg, sig))


class _BlspyBackend:
    """Adapter over blspy's BasicSchemeMPL (same ciphersuite)."""

    def __init__(self, mod):
        self._mod = mod

    def key_gen(self, ikm: bytes) -> int:
        sk = self._mod.BasicSchemeMPL.key_gen(ikm)
        return int.from_bytes(bytes(sk), "big")

    def _sk(self, sk: int):
        return self._mod.PrivateKey.from_bytes(
            sk.to_bytes(PRIV_KEY_SIZE, "big"))

    def sk_to_pk(self, sk: int) -> bytes:
        return bytes(self._sk(sk).get_g1())

    def sign(self, sk: int, msg: bytes) -> bytes:
        return bytes(self._mod.BasicSchemeMPL.sign(self._sk(sk), msg))

    def verify(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        m = self._mod
        return bool(m.BasicSchemeMPL.verify(
            m.G1Element.from_bytes(pk), msg, m.G2Element.from_bytes(sig)))


class _NativeBackend:
    """The bundled C++ implementation (``native/bls12381.cpp``), built on
    demand like the other native components — the tpu-native equivalent
    of the reference's blst binding (``crypto/bls12381/key_bls12381.go``,
    cgo + supranational/blst behind the ``bls12381`` build tag).  Same
    standard G2Basic ciphersuite as the pure-Python backend, pinned
    byte-identical to it (and so to the RFC 9380 QUUX vectors) by
    ``tests/test_bls12381.py``.  Verification is ~300x the pure-Python
    speed; signing uses a plain double-and-add ladder, which is NOT
    constant-time — the signing warning below applies to it too."""

    def __init__(self):
        import ctypes

        from ..native import lib_path

        lib = ctypes.CDLL(lib_path("bls12381"))
        lib.bls_verify.restype = ctypes.c_int
        lib.bls_verify.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                   ctypes.c_size_t, ctypes.c_char_p]
        lib.bls_sign.restype = ctypes.c_int
        lib.bls_sign.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                 ctypes.c_size_t, ctypes.c_char_p]
        lib.bls_sk_to_pk.restype = ctypes.c_int
        lib.bls_sk_to_pk.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.bls_selftest.restype = ctypes.c_int
        if lib.bls_selftest() != 1:
            raise RuntimeError("native bls12381 selftest failed")
        self._lib = lib
        self._ctypes = ctypes

    def key_gen(self, ikm: bytes) -> int:
        # RFC-style HKDF keygen is pure hashing — not a hot path; reuse
        # the bundled implementation rather than duplicating HKDF in C++
        from . import _bls12381_py as impl

        return impl.keygen(ikm)

    def sk_to_pk(self, sk: int) -> bytes:
        out = self._ctypes.create_string_buffer(PUB_KEY_SIZE)
        self._lib.bls_sk_to_pk(sk.to_bytes(PRIV_KEY_SIZE, "big"), out)
        return out.raw

    def sign(self, sk: int, msg: bytes) -> bytes:
        out = self._ctypes.create_string_buffer(SIGNATURE_LENGTH)
        self._lib.bls_sign(sk.to_bytes(PRIV_KEY_SIZE, "big"),
                           msg, len(msg), out)
        return out.raw

    def verify(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        return self._lib.bls_verify(pk, msg, len(msg), sig) == 1


class _PurePyBackend:
    """The bundled pure-Python implementation (``_bls12381_py``):
    dependency-free and always available, so BLS keys WORK out of the
    box where the reference's default build only errors.  Since r4 its
    hash-to-curve is the STANDARD G2 suite (RFC 9380 SSWU + 3-isogeny +
    h_eff, pinned to the RFC's own QUUX vectors), so signatures are
    byte-interoperable with blst/py_ecc/blspy.  Still slow (seconds per
    verify — two pairings in CPython); the seam prefers a native host
    library when one is importable."""

    def __init__(self):
        from . import _bls12381_py as impl

        self._impl = impl

    def key_gen(self, ikm: bytes) -> int:
        return self._impl.keygen(ikm)

    def sk_to_pk(self, sk: int) -> bytes:
        return self._impl.sk_to_pk(sk)

    def sign(self, sk: int, msg: bytes) -> bytes:
        return self._impl.sign(sk, msg)

    def verify(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        return self._impl.verify(pk, msg, sig)


def _try_blspy():
    import blspy

    return _BlspyBackend(blspy)


def _try_pyecc():
    from py_ecc.bls import G2Basic

    return _PyEccBackend(G2Basic)


def _backend():
    """Best available host implementation; never None — the bundled
    pure-Python fallback closes the gap.

    Preference order: blspy first (supranational/blst underneath — the
    reference's own backend, and the only CONSTANT-TIME signer here, so
    installing it actually fixes what the signing warning flags), then
    the bundled native C++ build, then py_ecc, then pure Python.
    ``COMETBFT_TPU_BLS_BACKEND`` (blspy|native|pyecc|purepy) pins one
    explicitly — the pin never falls through to a different backend."""
    import os

    forced = os.environ.get("COMETBFT_TPU_BLS_BACKEND", "").strip().lower()
    if forced:
        maker = {"blspy": _try_blspy, "native": _NativeBackend,
                 "pyecc": _try_pyecc, "purepy": _PurePyBackend}.get(forced)
        if maker is None:
            raise ValueError(
                f"COMETBFT_TPU_BLS_BACKEND={forced!r}: expected "
                "blspy|native|pyecc|purepy")
        return maker()
    for maker in (_try_blspy, _NativeBackend, _try_pyecc):
        try:
            return maker()
        except Exception:
            pass
    return _PurePyBackend()


_BACKEND = _backend()                # resolved once at import
ENABLED = _BACKEND is not None

# The IETF ciphersuite each backend implements.  Every backend —
# including the bundled pure-Python fallback since its r4 SSWU
# conversion — speaks the standard G2Basic suite, so there is no
# consensus-split hazard left; the guard machinery below stays as a
# safety net should a future backend deviate.
STANDARD_CIPHERSUITE = "BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_"
_PUREPY_CIPHERSUITE = STANDARD_CIPHERSUITE


def backend_ciphersuite() -> str:
    """The hash-to-curve ciphersuite of the active backend — recorded so
    mismatched networks fail fast instead of forking (a hazard the
    reference avoids only by having a single blst backend)."""
    if isinstance(_BACKEND, _PurePyBackend):
        return _PUREPY_CIPHERSUITE
    return STANDARD_CIPHERSUITE


def is_standard_backend() -> bool:
    return backend_ciphersuite() == STANDARD_CIPHERSUITE


def nonstandard_backend_allowed() -> bool:
    """Opt-in gate for running BLS *validator* keys on the non-standard
    bundled backend (``COMETBFT_TPU_ALLOW_NONSTANDARD_BLS=1``): without
    it, a network mixing backend suites would silently disagree on BLS
    signature validity."""
    import os

    return os.environ.get("COMETBFT_TPU_ALLOW_NONSTANDARD_BLS",
                          "").strip().lower() in ("1", "true", "yes")


def check_validator_backend() -> str | None:
    """Return an error string when BLS validator keys would run on the
    non-standard pure-Python suite without the explicit opt-in; None when
    safe.  Called from genesis validation and privval key loading."""
    if is_standard_backend() or nonstandard_backend_allowed():
        return None
    return (
        "bls12_381 validator keys are in use but this node's BLS "
        f"backend speaks the non-standard bundled suite "
        f"({_PUREPY_CIPHERSUITE}); a network with standard-suite nodes "
        "(py_ecc/blspy) would disagree on signature validity and fork. "
        "Install py_ecc or blspy, or — for a closed testnet where EVERY "
        "node runs the bundled backend — set "
        "COMETBFT_TPU_ALLOW_NONSTANDARD_BLS=1")


_SIGN_WARNED = False


def _warn_purepy_signing() -> None:
    """One-time runtime warning: pure-Python big-int scalar multiplication
    is variable-time — a secret-key timing side channel.  Production BLS
    validators must install blspy or py_ecc."""
    global _SIGN_WARNED
    if _SIGN_WARNED:
        return
    _SIGN_WARNED = True
    import sys

    print("WARNING: signing with a bls12_381 key on a bundled backend "
          "(native C++ or pure Python) — signatures are standard-suite "
          "(RFC 9380 SSWU) and interoperable, but the variable-time "
          "scalar multiplication leaks key bits through timing. Install "
          "blspy (constant-time blst) for production validators.",
          file=sys.stderr)


class Bls12381PubKey(PubKey):
    def __init__(self, raw: bytes):
        if len(raw) != PUB_KEY_SIZE:
            raise ValueError(f"bls12_381 pubkey must be {PUB_KEY_SIZE} "
                             f"bytes, got {len(raw)}")
        self._raw = bytes(raw)

    def bytes(self) -> bytes:
        return self._raw

    def type(self) -> str:
        return BLS12381_KEY_TYPE

    def address(self) -> bytes:
        return address_hash(self._raw)

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        impl = _BACKEND
        if impl is None:
            raise ErrDisabled()
        if len(sig) != SIGNATURE_LENGTH:
            return False
        try:
            return impl.verify(self._raw, msg, sig)
        except Exception:
            return False


class Bls12381PrivKey(PrivKey):
    def __init__(self, raw: bytes):
        if len(raw) != PRIV_KEY_SIZE:
            raise ValueError(f"bls12_381 privkey must be {PRIV_KEY_SIZE} "
                             f"bytes, got {len(raw)}")
        self._raw = bytes(raw)

    @classmethod
    def generate(cls) -> "Bls12381PrivKey":
        impl = _BACKEND
        if impl is None:
            raise ErrDisabled()
        import os as _os

        sk = impl.key_gen(_os.urandom(48))
        return cls(sk.to_bytes(PRIV_KEY_SIZE, "big"))

    def bytes(self) -> bytes:
        return self._raw

    def type(self) -> str:
        return BLS12381_KEY_TYPE

    def sign(self, msg: bytes) -> bytes:
        impl = _BACKEND
        if impl is None:
            raise ErrDisabled()
        if isinstance(impl, (_PurePyBackend, _NativeBackend)):
            _warn_purepy_signing()
        return impl.sign(int.from_bytes(self._raw, "big"), msg)

    def pub_key(self) -> Bls12381PubKey:
        impl = _BACKEND
        if impl is None:
            raise ErrDisabled()
        return Bls12381PubKey(
            impl.sk_to_pk(int.from_bytes(self._raw, "big")))
