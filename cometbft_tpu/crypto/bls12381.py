"""BLS12-381 keys (reference: ``crypto/bls12381/``).

The reference gates its real implementation behind the ``bls12381`` build
tag (cgo -> supranational/blst, ``crypto/bls12381/key_bls12381.go:1-30``);
default builds ship an error-returning stub with ``Enabled = false``
(``crypto/bls12381/key.go``).  This module goes further: a bundled
pure-Python implementation (``_bls12381_py``) makes BLS keys functional
with no extra dependencies, and the backend seam automatically upgrades
to a standard-ciphersuite host library (``py_ecc`` or ``blspy``) when one
is importable.  ``ENABLED`` and :class:`ErrDisabled` are retained for
surface parity with the reference; with the bundled fallback they are
always True / never raised.

Sizes follow the min-pubkey-size scheme the reference uses (blst minimal
public keys): 32-byte private keys, 48-byte compressed G1 public keys,
96-byte compressed G2 signatures.
"""

from __future__ import annotations

from .keys import BLS12381_KEY_TYPE, PrivKey, PubKey, address_hash

PRIV_KEY_SIZE = 32
PUB_KEY_SIZE = 48
SIGNATURE_LENGTH = 96


class ErrDisabled(NotImplementedError):
    """bls12_381 is disabled (no host BLS backend in this build) —
    the reference's ``bls12381.ErrDisabled``."""

    def __init__(self):
        super().__init__(
            "bls12_381 is disabled: no host BLS backend available "
            "(the reference equally requires the `bls12381` build tag + "
            "blst; install py_ecc or blspy to enable)")


class _PyEccBackend:
    """Adapter over py_ecc's basic ciphersuite (G2Basic =
    BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_, minimal-pubkey-size:
    48-byte G1 pubkeys / 96-byte G2 signatures, the reference's blst
    layout)."""

    def __init__(self, impl):
        self._impl = impl

    def key_gen(self, ikm: bytes) -> int:
        return int(self._impl.KeyGen(ikm))

    def sk_to_pk(self, sk: int) -> bytes:
        return bytes(self._impl.SkToPk(sk))

    def sign(self, sk: int, msg: bytes) -> bytes:
        return bytes(self._impl.Sign(sk, msg))

    def verify(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        return bool(self._impl.Verify(pk, msg, sig))

    # Aggregation is plain group addition — backend-independent math on
    # standard-suite bytes — so rather than depending on which py_ecc
    # flavour exposes which Aggregate/_AggregatePKs helper, route it
    # through the bundled implementation (byte-identical results).
    def aggregate_signatures(self, sigs, check=True) -> bytes:
        from . import _bls12381_py as impl

        return impl.aggregate_signatures(list(sigs))

    def aggregate_pubkeys(self, pks, check=True) -> bytes:
        from . import _bls12381_py as impl

        return impl.aggregate_pubkeys(list(pks))

    def fast_aggregate_verify(self, pks, msg: bytes, sig: bytes) -> bool:
        try:
            agg_pk = self.aggregate_pubkeys(pks)
        except ValueError:
            return False
        return self.verify(agg_pk, msg, sig)

    def pop_prove(self, sk: int) -> bytes:
        from . import _bls12381_py as impl

        return impl.pop_prove(sk)

    def pop_verify(self, pk: bytes, pop: bytes) -> bool:
        from . import _bls12381_py as impl

        return impl.pop_verify(pk, pop)


class _BlspyBackend:
    """Adapter over blspy's BasicSchemeMPL (same ciphersuite)."""

    def __init__(self, mod):
        self._mod = mod

    def key_gen(self, ikm: bytes) -> int:
        sk = self._mod.BasicSchemeMPL.key_gen(ikm)
        return int.from_bytes(bytes(sk), "big")

    def _sk(self, sk: int):
        return self._mod.PrivateKey.from_bytes(
            sk.to_bytes(PRIV_KEY_SIZE, "big"))

    def sk_to_pk(self, sk: int) -> bytes:
        return bytes(self._sk(sk).get_g1())

    def sign(self, sk: int, msg: bytes) -> bytes:
        return bytes(self._mod.BasicSchemeMPL.sign(self._sk(sk), msg))

    def verify(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        m = self._mod
        return bool(m.BasicSchemeMPL.verify(
            m.G1Element.from_bytes(pk), msg, m.G2Element.from_bytes(sig)))

    # from_bytes does full validation (decompress + subgroup) in blst, so
    # the `check` knob is honored implicitly; element `+` is the group op.
    def aggregate_signatures(self, sigs, check=True) -> bytes:
        m = self._mod
        return bytes(m.BasicSchemeMPL.aggregate(
            [m.G2Element.from_bytes(bytes(s)) for s in sigs]))

    def aggregate_pubkeys(self, pks, check=True) -> bytes:
        m = self._mod
        acc = m.G1Element()                      # identity
        for raw in pks:
            acc = acc + m.G1Element.from_bytes(bytes(raw))
        return bytes(acc)

    def fast_aggregate_verify(self, pks, msg: bytes, sig: bytes) -> bool:
        # NOT PopSchemeMPL.fast_aggregate_verify — that hashes under the
        # POP_ DST; the repo signs votes under the Basic (NUL_) suite, so
        # aggregate the pubkeys and verify with BasicSchemeMPL.
        try:
            agg_pk = self.aggregate_pubkeys(pks)
        except Exception:
            return False
        return self.verify(agg_pk, msg, sig)

    def pop_prove(self, sk: int) -> bytes:
        # PopSchemeMPL's possession proof IS the draft's §3.3.2: sign the
        # pubkey bytes under the POP_ DST — byte-compatible with ours.
        return bytes(self._mod.PopSchemeMPL.pop_prove(self._sk(sk)))

    def pop_verify(self, pk: bytes, pop: bytes) -> bool:
        m = self._mod
        try:
            return bool(m.PopSchemeMPL.pop_verify(
                m.G1Element.from_bytes(bytes(pk)),
                m.G2Element.from_bytes(bytes(pop))))
        except Exception:
            return False


class _NativeBackend:
    """The bundled C++ implementation (``native/bls12381.cpp``), built on
    demand like the other native components — the tpu-native equivalent
    of the reference's blst binding (``crypto/bls12381/key_bls12381.go``,
    cgo + supranational/blst behind the ``bls12381`` build tag).  Same
    standard G2Basic ciphersuite as the pure-Python backend, pinned
    byte-identical to it (and so to the RFC 9380 QUUX vectors) by
    ``tests/test_bls12381.py``.  Verification is ~300x the pure-Python
    speed; signing uses a plain double-and-add ladder, which is NOT
    constant-time — the signing warning below applies to it too."""

    def __init__(self):
        import ctypes

        from ..native import lib_path

        lib = ctypes.CDLL(lib_path("bls12381"))
        lib.bls_verify.restype = ctypes.c_int
        lib.bls_verify.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                   ctypes.c_size_t, ctypes.c_char_p]
        lib.bls_sign.restype = ctypes.c_int
        lib.bls_sign.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                 ctypes.c_size_t, ctypes.c_char_p]
        lib.bls_sk_to_pk.restype = ctypes.c_int
        lib.bls_sk_to_pk.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        for name, argtypes in (
            ("bls_agg_sigs", [ctypes.c_char_p, ctypes.c_size_t,
                              ctypes.c_int, ctypes.c_char_p]),
            ("bls_agg_pks", [ctypes.c_char_p, ctypes.c_size_t,
                             ctypes.c_int, ctypes.c_char_p]),
            ("bls_fagg_verify", [ctypes.c_char_p, ctypes.c_size_t,
                                 ctypes.c_char_p, ctypes.c_size_t,
                                 ctypes.c_char_p]),
            ("bls_pk_to_affine", [ctypes.c_char_p, ctypes.c_char_p]),
            ("bls_agg_affine", [ctypes.c_char_p, ctypes.c_size_t,
                                ctypes.c_char_p]),
            ("bls_verify_agg_affine", [ctypes.c_char_p, ctypes.c_char_p,
                                       ctypes.c_size_t, ctypes.c_char_p]),
            ("bls_pop_prove", [ctypes.c_char_p, ctypes.c_char_p]),
            ("bls_pop_verify", [ctypes.c_char_p, ctypes.c_char_p]),
        ):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = argtypes
        lib.bls_selftest.restype = ctypes.c_int
        if lib.bls_selftest() != 1:
            raise RuntimeError("native bls12381 selftest failed")
        self._lib = lib
        self._ctypes = ctypes

    def key_gen(self, ikm: bytes) -> int:
        # RFC-style HKDF keygen is pure hashing — not a hot path; reuse
        # the bundled implementation rather than duplicating HKDF in C++
        from . import _bls12381_py as impl

        return impl.keygen(ikm)

    def sk_to_pk(self, sk: int) -> bytes:
        out = self._ctypes.create_string_buffer(PUB_KEY_SIZE)
        self._lib.bls_sk_to_pk(sk.to_bytes(PRIV_KEY_SIZE, "big"), out)
        return out.raw

    def sign(self, sk: int, msg: bytes) -> bytes:
        out = self._ctypes.create_string_buffer(SIGNATURE_LENGTH)
        self._lib.bls_sign(sk.to_bytes(PRIV_KEY_SIZE, "big"),
                           msg, len(msg), out)
        return out.raw

    def verify(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        return self._lib.bls_verify(pk, msg, len(msg), sig) == 1

    def aggregate_signatures(self, sigs, check=True) -> bytes:
        out = self._ctypes.create_string_buffer(SIGNATURE_LENGTH)
        if self._lib.bls_agg_sigs(b"".join(sigs), len(sigs),
                                  1 if check else 0, out) != 1:
            raise ValueError("aggregate input not a valid G2 signature")
        return out.raw

    def aggregate_pubkeys(self, pks, check=True) -> bytes:
        out = self._ctypes.create_string_buffer(PUB_KEY_SIZE)
        if self._lib.bls_agg_pks(b"".join(pks), len(pks),
                                 1 if check else 0, out) != 1:
            raise ValueError("aggregate input not a valid G1 pubkey")
        return out.raw

    def fast_aggregate_verify(self, pks, msg: bytes, sig: bytes) -> bool:
        return self._lib.bls_fagg_verify(
            b"".join(pks), len(pks), msg, len(msg), sig) == 1

    def pop_prove(self, sk: int) -> bytes:
        out = self._ctypes.create_string_buffer(SIGNATURE_LENGTH)
        self._lib.bls_pop_prove(sk.to_bytes(PRIV_KEY_SIZE, "big"), out)
        return out.raw

    def pop_verify(self, pk: bytes, pop: bytes) -> bool:
        return self._lib.bls_pop_verify(pk, pop) == 1

    # affine pubkey-table fast path (see the module-level helpers)

    def pk_to_affine(self, pk: bytes) -> bytes:
        out = self._ctypes.create_string_buffer(96)
        if self._lib.bls_pk_to_affine(pk, out) != 1:
            raise ValueError("not a valid G1 pubkey")
        return out.raw

    def aggregate_affine(self, pts) -> bytes:
        out = self._ctypes.create_string_buffer(96)
        rc = self._lib.bls_agg_affine(b"".join(pts), len(pts), out)
        if rc == 2:
            raise ValueError("aggregate is the point at infinity")
        if rc != 1:
            raise ValueError("affine input not on the G1 curve"
                             if pts else
                             "cannot aggregate an empty point set")
        return out.raw

    def verify_agg_affine(self, xy: bytes, msg: bytes, sig: bytes) -> bool:
        return self._lib.bls_verify_agg_affine(xy, msg, len(msg), sig) == 1


class _PurePyBackend:
    """The bundled pure-Python implementation (``_bls12381_py``):
    dependency-free and always available, so BLS keys WORK out of the
    box where the reference's default build only errors.  Since r4 its
    hash-to-curve is the STANDARD G2 suite (RFC 9380 SSWU + 3-isogeny +
    h_eff, pinned to the RFC's own QUUX vectors), so signatures are
    byte-interoperable with blst/py_ecc/blspy.  Still slow (seconds per
    verify — two pairings in CPython); the seam prefers a native host
    library when one is importable."""

    def __init__(self):
        from . import _bls12381_py as impl

        self._impl = impl

    def key_gen(self, ikm: bytes) -> int:
        return self._impl.keygen(ikm)

    def sk_to_pk(self, sk: int) -> bytes:
        return self._impl.sk_to_pk(sk)

    def sign(self, sk: int, msg: bytes) -> bytes:
        return self._impl.sign(sk, msg)

    def verify(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        return self._impl.verify(pk, msg, sig)

    def aggregate_signatures(self, sigs, check=True) -> bytes:
        return self._impl.aggregate_signatures(list(sigs))

    def aggregate_pubkeys(self, pks, check=True) -> bytes:
        return self._impl.aggregate_pubkeys(list(pks))

    def fast_aggregate_verify(self, pks, msg: bytes, sig: bytes) -> bool:
        return self._impl.fast_aggregate_verify(list(pks), msg, sig)

    def pop_prove(self, sk: int) -> bytes:
        return self._impl.pop_prove(sk)

    def pop_verify(self, pk: bytes, pop: bytes) -> bool:
        return self._impl.pop_verify(pk, pop)

    def pk_to_affine(self, pk: bytes) -> bytes:
        return self._impl.pk_to_affine(pk)

    def aggregate_affine(self, pts) -> bytes:
        return self._impl.aggregate_affine(list(pts))

    def verify_agg_affine(self, xy: bytes, msg: bytes, sig: bytes) -> bool:
        return self._impl.verify_agg_affine(xy, msg, sig)


def _try_blspy():
    import blspy

    return _BlspyBackend(blspy)


def _try_pyecc():
    from py_ecc.bls import G2Basic

    return _PyEccBackend(G2Basic)


def _backend():
    """Best available host implementation; never None — the bundled
    pure-Python fallback closes the gap.

    Preference order: blspy first (supranational/blst underneath — the
    reference's own backend, and the only CONSTANT-TIME signer here, so
    installing it actually fixes what the signing warning flags), then
    the bundled native C++ build, then py_ecc, then pure Python.
    ``COMETBFT_TPU_BLS_BACKEND`` (blspy|native|pyecc|purepy) pins one
    explicitly — the pin never falls through to a different backend."""
    import os

    forced = os.environ.get("COMETBFT_TPU_BLS_BACKEND", "").strip().lower()
    if forced:
        maker = {"blspy": _try_blspy, "native": _NativeBackend,
                 "pyecc": _try_pyecc, "purepy": _PurePyBackend}.get(forced)
        if maker is None:
            raise ValueError(
                f"COMETBFT_TPU_BLS_BACKEND={forced!r}: expected "
                "blspy|native|pyecc|purepy")
        return maker()
    for maker in (_try_blspy, _NativeBackend, _try_pyecc):
        try:
            return maker()
        except Exception:
            pass
    return _PurePyBackend()


_BACKEND = _backend()                # resolved once at import
ENABLED = _BACKEND is not None

# The IETF ciphersuite each backend implements.  Every backend —
# including the bundled pure-Python fallback since its r4 SSWU
# conversion — speaks the standard G2Basic suite, so there is no
# consensus-split hazard left; the guard machinery below stays as a
# safety net should a future backend deviate.
STANDARD_CIPHERSUITE = "BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_"
_PUREPY_CIPHERSUITE = STANDARD_CIPHERSUITE


def backend_ciphersuite() -> str:
    """The hash-to-curve ciphersuite of the active backend — recorded so
    mismatched networks fail fast instead of forking (a hazard the
    reference avoids only by having a single blst backend)."""
    if isinstance(_BACKEND, _PurePyBackend):
        return _PUREPY_CIPHERSUITE
    return STANDARD_CIPHERSUITE


def is_standard_backend() -> bool:
    return backend_ciphersuite() == STANDARD_CIPHERSUITE


def nonstandard_backend_allowed() -> bool:
    """Opt-in gate for running BLS *validator* keys on the non-standard
    bundled backend (``COMETBFT_TPU_ALLOW_NONSTANDARD_BLS=1``): without
    it, a network mixing backend suites would silently disagree on BLS
    signature validity."""
    import os

    return os.environ.get("COMETBFT_TPU_ALLOW_NONSTANDARD_BLS",
                          "").strip().lower() in ("1", "true", "yes")


def check_validator_backend() -> str | None:
    """Return an error string when BLS validator keys would run on the
    non-standard pure-Python suite without the explicit opt-in; None when
    safe.  Called from genesis validation and privval key loading."""
    if is_standard_backend() or nonstandard_backend_allowed():
        return None
    return (
        "bls12_381 validator keys are in use but this node's BLS "
        f"backend speaks the non-standard bundled suite "
        f"({_PUREPY_CIPHERSUITE}); a network with standard-suite nodes "
        "(py_ecc/blspy) would disagree on signature validity and fork. "
        "Install py_ecc or blspy, or — for a closed testnet where EVERY "
        "node runs the bundled backend — set "
        "COMETBFT_TPU_ALLOW_NONSTANDARD_BLS=1")


_SIGN_WARNED = False


def _warn_purepy_signing() -> None:
    """One-time runtime warning: pure-Python big-int scalar multiplication
    is variable-time — a secret-key timing side channel.  Production BLS
    validators must install blspy or py_ecc."""
    global _SIGN_WARNED
    if _SIGN_WARNED:
        return
    _SIGN_WARNED = True
    import sys

    print("WARNING: signing with a bls12_381 key on a bundled backend "
          "(native C++ or pure Python) — signatures are standard-suite "
          "(RFC 9380 SSWU) and interoperable, but the variable-time "
          "scalar multiplication leaks key bits through timing. Install "
          "blspy (constant-time blst) for production validators.",
          file=sys.stderr)


# --------------------------------------------------------- aggregation
# Same-message (FastAggregateVerify) aggregation for the commit fast
# path: N BLS precommits over identical sign-bytes fold into one G2
# point, and verification costs two pairings plus a G1 pubkey sum
# regardless of N.  The Basic suite is rogue-key-UNSAFE under same-
# message aggregation, so every BLS validator key must carry a proof of
# possession (pop_prove/pop_verify, POP_ DST) checked at key admission —
# see docs/explanation/bls-aggregation.md.  Policy (empty-set and
# duplicate-signer rejection) lives HERE at the module seam; the
# backends underneath stay purely mathematical.

DST_POP = b"BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"


def aggregate_signatures(sigs, check: bool = True) -> bytes:
    """Fold compressed G2 signatures into one.  ``check=False`` skips
    per-input subgroup checks for inputs that already passed individual
    verification (e.g. precommits entering a commit)."""
    sigs = [bytes(s) for s in sigs]
    if not sigs:
        raise ValueError("cannot aggregate an empty signature set")
    for s in sigs:
        if len(s) != SIGNATURE_LENGTH:
            raise ValueError(
                f"signature must be {SIGNATURE_LENGTH} bytes, got {len(s)}")
    return _BACKEND.aggregate_signatures(sigs, check=check)


def aggregate_pubkeys(pks) -> bytes:
    """Sum compressed G1 pubkeys.  Duplicates are rejected: in the
    commit path the signer bitmap guarantees distinct validators, so a
    repeated key can only mean a caller bug or a forged commit."""
    pks = [bytes(p) for p in pks]
    if not pks:
        raise ValueError("cannot aggregate an empty pubkey set")
    seen = set()
    for p in pks:
        if len(p) != PUB_KEY_SIZE:
            raise ValueError(
                f"pubkey must be {PUB_KEY_SIZE} bytes, got {len(p)}")
        if p in seen:
            raise ValueError("duplicate pubkey in aggregate")
        seen.add(p)
    return _BACKEND.aggregate_pubkeys(pks)


def fast_aggregate_verify(pks, msg: bytes, sig: bytes) -> bool:
    """Verify that every pk's holder signed the SAME msg.  Returns False
    (never raises) on empty sets, duplicate signers, or malformed input."""
    pks = [bytes(p) for p in pks]
    if not pks or len(bytes(sig)) != SIGNATURE_LENGTH:
        return False
    if any(len(p) != PUB_KEY_SIZE for p in pks):
        return False
    if len(set(pks)) != len(pks):
        return False
    try:
        return _BACKEND.fast_aggregate_verify(pks, msg, bytes(sig))
    except Exception:
        return False


def pop_prove(priv: bytes) -> bytes:
    """Proof of possession for a raw 32-byte secret key: sign the pubkey
    bytes under the POP_ DST (draft-irtf-cfrg-bls-signature §3.3.2)."""
    priv = bytes(priv)
    if len(priv) != PRIV_KEY_SIZE:
        raise ValueError(f"privkey must be {PRIV_KEY_SIZE} bytes")
    return _BACKEND.pop_prove(int.from_bytes(priv, "big"))


def pop_verify(pk: bytes, pop: bytes) -> bool:
    """The rogue-key gate: every BLS validator key must pass this before
    its votes may fold into an aggregate."""
    try:
        return bool(_BACKEND.pop_verify(bytes(pk), bytes(pop)))
    except Exception:
        return False


def _affine_impl():
    """Affine-table helpers are internal cache plumbing (not consensus-
    visible backend behavior), so backends without them borrow the
    bundled math — byte-identical by construction."""
    if hasattr(_BACKEND, "pk_to_affine"):
        return _BACKEND
    from . import _bls12381_py as impl

    return impl


def pk_to_affine(pk: bytes) -> bytes:
    """Decompress + subgroup-check a pubkey ONCE into 96 x||y bytes; the
    per-valset cache stores these so per-commit work is pure adds."""
    return _affine_impl().pk_to_affine(bytes(pk))


def aggregate_affine(pts) -> bytes:
    """Sum affine G1 points (x||y each).  Raises ValueError on malformed
    input or an infinity sum."""
    return _affine_impl().aggregate_affine([bytes(p) for p in pts])


def negate_affine(xy: bytes) -> bytes:
    """-P for an affine point: y -> p - y.  Host-side big-int — lets the
    cached full-cohort sum serve near-full commits as sum - missing."""
    xy = bytes(xy)
    if len(xy) != 96:
        raise ValueError("affine G1 point must be 96 bytes (x||y)")
    from ._bls12381_py import P as _P

    y = int.from_bytes(xy[48:], "big")
    return xy[:48] + ((_P - y) % _P).to_bytes(48, "big")


def verify_aggregate_affine(xy: bytes, msg: bytes, sig: bytes) -> bool:
    """Verify an aggregate signature against a pre-aggregated affine
    pubkey: exactly two pairings."""
    try:
        return bool(_affine_impl().verify_agg_affine(
            bytes(xy), msg, bytes(sig)))
    except Exception:
        return False


class Bls12381PubKey(PubKey):
    def __init__(self, raw: bytes):
        if len(raw) != PUB_KEY_SIZE:
            raise ValueError(f"bls12_381 pubkey must be {PUB_KEY_SIZE} "
                             f"bytes, got {len(raw)}")
        self._raw = bytes(raw)

    def bytes(self) -> bytes:
        return self._raw

    def type(self) -> str:
        return BLS12381_KEY_TYPE

    def address(self) -> bytes:
        return address_hash(self._raw)

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        impl = _BACKEND
        if impl is None:
            raise ErrDisabled()
        if len(sig) != SIGNATURE_LENGTH:
            return False
        try:
            return impl.verify(self._raw, msg, sig)
        except Exception:
            return False


class Bls12381PrivKey(PrivKey):
    def __init__(self, raw: bytes):
        if len(raw) != PRIV_KEY_SIZE:
            raise ValueError(f"bls12_381 privkey must be {PRIV_KEY_SIZE} "
                             f"bytes, got {len(raw)}")
        self._raw = bytes(raw)

    @classmethod
    def generate(cls) -> "Bls12381PrivKey":
        impl = _BACKEND
        if impl is None:
            raise ErrDisabled()
        import os as _os

        sk = impl.key_gen(_os.urandom(48))
        return cls(sk.to_bytes(PRIV_KEY_SIZE, "big"))

    @classmethod
    def from_secret(cls, secret: bytes) -> "Bls12381PrivKey":
        """Deterministic test key from a short secret (the BLS analogue
        of ``Ed25519PrivKey.from_secret``): the secret is padded to the
        32 bytes of KeyGen IKM entropy RFC 9380's HKDF requires.  Tests
        and sim genesis only — real keys come from :meth:`generate`."""
        impl = _BACKEND
        if impl is None:
            raise ErrDisabled()
        sk = impl.key_gen(secret.ljust(48, b"\x9b"))
        return cls(sk.to_bytes(PRIV_KEY_SIZE, "big"))

    def bytes(self) -> bytes:
        return self._raw

    def type(self) -> str:
        return BLS12381_KEY_TYPE

    def sign(self, msg: bytes) -> bytes:
        impl = _BACKEND
        if impl is None:
            raise ErrDisabled()
        if isinstance(impl, (_PurePyBackend, _NativeBackend)):
            _warn_purepy_signing()
        return impl.sign(int.from_bytes(self._raw, "big"), msg)

    def pub_key(self) -> Bls12381PubKey:
        impl = _BACKEND
        if impl is None:
            raise ErrDisabled()
        return Bls12381PubKey(
            impl.sk_to_pk(int.from_bytes(self._raw, "big")))
