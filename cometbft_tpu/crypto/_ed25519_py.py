"""Pure-Python Ed25519 with ZIP-215 verification semantics.

This is the framework's *reference* implementation: the correctness oracle for
the JAX/TPU kernel (``ops/ed25519.py``) and the slow path of the CPU fallback
verifier.  Verification is **cofactored** with **permissive point decoding**
(ZIP-215), matching the semantics CometBFT inherits from curve25519-voi
(reference: ``crypto/ed25519/ed25519.go:169-221`` — `VerifyOptions` there are
ZIP-215 / batch-compatible).  Concretely:

- ``S`` must be canonical (``S < L``); otherwise reject.
- ``A`` and ``R`` encodings may be non-canonical (``y >= p`` accepted) and may
  be small-order / mixed-order points; the ``x = 0`` with sign-bit-1 encodings
  are accepted.
- The verification equation is cofactored: ``[8][S]B == [8]R + [8][h]A``.

Signing is standard RFC 8032.  Everything uses Python big ints — slow, but
exact; the hot path lives on TPU.
"""

from __future__ import annotations

import hashlib

__all__ = [
    "P", "L", "D", "BX", "BY",
    "sign", "verify_zip215", "public_key_from_seed",
    "pt_decompress_zip215", "pt_compress", "pt_add", "pt_mul", "pt_equal",
    "IDENTITY", "sc_reduce64",
]

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1) mod p

BY = (4 * pow(5, P - 2, P)) % P
# Recover base-point x with even parity (RFC 8032: x is the "positive" root).
def _xrecover(y: int) -> int | None:
    xx = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    x = pow(xx, (P + 3) // 8, P)
    if (x * x - xx) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - xx) % P != 0:
        return None
    return x

BX = _xrecover(BY)
assert BX is not None
if BX % 2 == 1:
    BX = P - BX

# Points are extended homogeneous coordinates (X, Y, Z, T) with x=X/Z, y=Y/Z,
# T = XY/Z.  IDENTITY = (0, 1).
IDENTITY = (0, 1, 1, 0)
BASE = (BX, BY, 1, BX * BY % P)


def pt_add(p1, p2):
    # add-2008-hwcd-3 for a=-1 twisted Edwards (the ed25519 curve form).
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2 = p2
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * D * t1 % P * t2 % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def pt_double(p1):
    x1, y1, z1, _ = p1
    a = x1 * x1 % P
    b = y1 * y1 % P
    c = 2 * z1 * z1 % P
    h = (a + b) % P
    e = (h - (x1 + y1) * (x1 + y1)) % P
    g = (a - b) % P
    f = (c + g) % P
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def pt_neg(p1):
    x1, y1, z1, t1 = p1
    return ((-x1) % P, y1, z1, (-t1) % P)


def pt_mul(k: int, pt):
    q = IDENTITY
    while k > 0:
        if k & 1:
            q = pt_add(q, pt)
        pt = pt_double(pt)
        k >>= 1
    return q


def pt_equal(p1, p2) -> bool:
    x1, y1, z1, _ = p1
    x2, y2, z2, _ = p2
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def pt_compress(p1) -> bytes:
    x1, y1, z1, _ = p1
    zi = pow(z1, P - 2, P)
    x, y = x1 * zi % P, y1 * zi % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def pt_decompress_zip215(s: bytes):
    """Permissive (ZIP-215) decoding: non-canonical y accepted; x=0/sign=1
    accepted.  Returns an extended point or None if x^2 has no root."""
    if len(s) != 32:
        return None
    enc = int.from_bytes(s, "little")
    sign = enc >> 255
    y = (enc & ((1 << 255) - 1)) % P
    x = _xrecover(y)
    if x is None:
        return None
    if x & 1 != sign:
        x = (-x) % P
    return (x, y, 1, x * y % P)


def sc_reduce64(b: bytes) -> int:
    return int.from_bytes(b, "little") % L


def _clamp(a: bytes) -> int:
    k = bytearray(a)
    k[0] &= 248
    k[31] &= 127
    k[31] |= 64
    return int.from_bytes(bytes(k), "little")


def public_key_from_seed(seed: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = _clamp(h[:32])
    return pt_compress(pt_mul(a, BASE))


def sign(seed: bytes, msg: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = _clamp(h[:32])
    prefix = h[32:]
    pub = pt_compress(pt_mul(a, BASE))
    r = sc_reduce64(hashlib.sha512(prefix + msg).digest())
    rb = pt_compress(pt_mul(r, BASE))
    k = sc_reduce64(hashlib.sha512(rb + pub + msg).digest())
    s = (r + k * a) % L
    return rb + int.to_bytes(s, 32, "little")


def verify_zip215(pub: bytes, msg: bytes, sig: bytes) -> bool:
    if len(sig) != 64 or len(pub) != 32:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    a = pt_decompress_zip215(pub)
    r = pt_decompress_zip215(sig[:32])
    if a is None or r is None:
        return False
    h = sc_reduce64(hashlib.sha512(sig[:32] + pub + msg).digest())
    # [8]([S]B - [h]A - R) == identity
    q = pt_add(pt_mul(s, BASE), pt_neg(pt_add(pt_mul(h, a), r)))
    q = pt_double(pt_double(pt_double(q)))
    return pt_equal(q, IDENTITY)
