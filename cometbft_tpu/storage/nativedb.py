"""NativeDB: the C++ embedded KV engine behind the KVStore interface
(SURVEY §2.9-3 — native where the reference's heavy-duty backend is
native; the engine lives in ``cometbft_tpu/native/kvstore.cpp``).

Same on-disk record format as LogDB, so the two backends are
file-compatible; the native engine owns the index, the log, fsync
batching and compaction, and Python talks to it over a ctypes C ABI."""

from __future__ import annotations

import ctypes
import struct

from ..native import lib_path
from .db import KVStore

_TOMBSTONE = 0xFFFFFFFF
_U32 = struct.Struct("<I")

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(lib_path("kvstore"))
    lib.kv_open.restype = ctypes.c_void_p
    lib.kv_open.argtypes = [ctypes.c_char_p]
    lib.kv_close.argtypes = [ctypes.c_void_p]
    lib.kv_get.restype = ctypes.c_int
    lib.kv_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                           ctypes.c_uint32,
                           ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                           ctypes.POINTER(ctypes.c_uint32)]
    lib.kv_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.kv_set.restype = ctypes.c_int
    lib.kv_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                           ctypes.c_uint32, ctypes.c_char_p,
                           ctypes.c_uint32]
    lib.kv_delete.restype = ctypes.c_int
    lib.kv_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_uint32]
    lib.kv_batch.restype = ctypes.c_int
    lib.kv_batch.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.c_uint64]
    lib.kv_iter_new.restype = ctypes.c_void_p
    lib.kv_iter_new.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint32, ctypes.c_char_p,
                                ctypes.c_uint32]
    lib.kv_iter_next.restype = ctypes.c_int
    lib.kv_iter_next.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint32)]
    lib.kv_iter_free.argtypes = [ctypes.c_void_p]
    lib.kv_size.restype = ctypes.c_uint64
    lib.kv_size.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def _take(lib, ptr, ln) -> bytes:
    try:
        return ctypes.string_at(ptr, ln)
    finally:
        lib.kv_free(ptr)


class NativeDBError(Exception):
    pass


class NativeDB(KVStore):
    def __init__(self, path: str):
        import os

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lib = _load()
        self._h = self._lib.kv_open(path.encode())
        if not self._h:
            raise NativeDBError(f"cannot open native kv store at {path}")

    def get(self, key: bytes) -> bytes | None:
        val = ctypes.POINTER(ctypes.c_uint8)()
        vlen = ctypes.c_uint32()
        if self._lib.kv_get(self._h, key, len(key),
                            ctypes.byref(val), ctypes.byref(vlen)) == 0:
            return None
        return _take(self._lib, val, vlen.value)

    def set(self, key: bytes, value: bytes) -> None:
        if self._lib.kv_set(self._h, key, len(key), value,
                            len(value)) != 0:
            raise NativeDBError("set failed")

    def delete(self, key: bytes) -> None:
        if self._lib.kv_delete(self._h, key, len(key)) != 0:
            raise NativeDBError("delete failed")

    def set_batch(self, items: dict[bytes, bytes | None]) -> None:
        parts = []
        for k, v in items.items():
            vlen = _TOMBSTONE if v is None else len(v)
            parts.append(_U32.pack(len(k)) + _U32.pack(vlen) + k
                         + (v or b""))
        wire = b"".join(parts)
        if self._lib.kv_batch(self._h, wire, len(wire)) != 0:
            raise NativeDBError("batch failed")

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        it = self._lib.kv_iter_new(self._h, start, len(start),
                                   end or b"", len(end or b""))
        try:
            while True:
                kp = ctypes.POINTER(ctypes.c_uint8)()
                vp = ctypes.POINTER(ctypes.c_uint8)()
                kl = ctypes.c_uint32()
                vl = ctypes.c_uint32()
                if self._lib.kv_iter_next(it, ctypes.byref(kp),
                                          ctypes.byref(kl),
                                          ctypes.byref(vp),
                                          ctypes.byref(vl)) == 0:
                    return
                yield (_take(self._lib, kp, kl.value),
                       _take(self._lib, vp, vl.value))
        finally:
            self._lib.kv_iter_free(it)

    def size(self) -> int:
        return int(self._lib.kv_size(self._h))

    def close(self) -> None:
        if self._h:
            self._lib.kv_close(self._h)
            self._h = None
