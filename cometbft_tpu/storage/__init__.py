"""Storage layer (reference: ``store/``, ``state/store.go``, cometbft-db).

KV abstraction with an in-memory backend and a crash-safe append-only log
backend; BlockStore and StateStore above it.  A C++ KV engine slots in
behind the same ``KVStore`` interface (SURVEY.md §2.9 item 3).
"""

from .db import KVStore, MemDB, LogDB, open_db
from .blockstore import BlockStore, BlockMeta
from .statestore import State, StateStore

__all__ = ["KVStore", "MemDB", "LogDB", "open_db", "BlockStore", "BlockMeta",
           "State", "StateStore"]
