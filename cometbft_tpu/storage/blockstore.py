"""BlockStore (reference: ``store/store.go:46``): persisted blocks, part
sets, commits and seen-commits, keyed by height with a height-ordered key
layout (the reference's storage study found height-ordered keys keep
throughput under pruning, ``docs/references/storage/README.md:202``)."""

from __future__ import annotations

from dataclasses import dataclass

import msgpack

from ..types import codec
from ..types.block_id import BlockID
from ..types.commit import Commit, ExtendedCommit
from ..types.header import Block
from ..types.part_set import PartSet
from .db import KVStore, height_key as _hkey


K_BLOCK = b"B/"
K_COMMIT = b"C/"          # canonical commit for height (from block H+1 or seen)
K_SEEN_COMMIT = b"SC"     # latest seen commit (one record)
K_EXT_COMMIT = b"EC/"
K_META = b"M/"
K_STATE = b"BSJ"          # base/height bookkeeping


@dataclass
class BlockMeta:
    block_id: BlockID
    block_size: int
    num_txs: int
    header_height: int


class BlockStore:
    def __init__(self, db: KVStore):
        self.db = db
        raw = db.get(K_STATE)
        if raw:
            d = msgpack.unpackb(raw, raw=False)
            self._base, self._height = d["base"], d["height"]
        else:
            self._base = self._height = 0

    def base(self) -> int:
        return self._base

    def height(self) -> int:
        return self._height

    def size(self) -> int:
        return self._height - self._base + 1 if self._height else 0

    def _save_bookkeeping(self):
        self.db.set(K_STATE, msgpack.packb(
            {"base": self._base, "height": self._height}))

    def save_block(self, block: Block, parts: PartSet,
                   seen_commit: Commit) -> None:
        h = block.header.height
        if h != self._height + 1 and self._height != 0:
            raise ValueError(
                f"non-contiguous block save: {h} after {self._height}")
        bid = BlockID(block.hash(), parts.header())
        if self._base == 0:
            self._base = h
        self._height = h
        batch: dict[bytes, bytes] = {
            _hkey(K_BLOCK, h): codec.pack(block),
            _hkey(K_META, h): msgpack.packb({
                "bid": codec.to_dict(bid), "size": parts.byte_size,
                "ntxs": len(block.data.txs), "h": h}),
            K_SEEN_COMMIT: codec.pack(seen_commit),
            K_STATE: msgpack.packb({"base": self._base,
                                    "height": self._height}),
        }
        if block.last_commit is not None:
            batch[_hkey(K_COMMIT, h - 1)] = codec.pack(block.last_commit)
        # single grouped write: one fsync on LogDB, no torn bookkeeping
        self.db.set_batch(batch)

    def save_block_with_extended_commit(self, block: Block, parts: PartSet,
                                        seen_ext: ExtendedCommit) -> None:
        self.save_block(block, parts, seen_ext.to_commit())
        self.db.set(_hkey(K_EXT_COMMIT, block.header.height),
                    codec.pack(seen_ext))

    def load_block(self, height: int) -> Block | None:
        raw = self.db.get(_hkey(K_BLOCK, height))
        return codec.unpack(raw) if raw else None

    def load_block_parts(self, height: int) -> PartSet | None:
        """Rebuild the block's PartSet for gossip catch-up.  Parts are a
        deterministic function of the block bytes (PartSet.from_data over
        the codec encoding), so they need not be stored separately."""
        block = self.load_block(height)
        if block is None:
            return None
        return PartSet.from_data(codec.pack(block))

    def load_block_meta(self, height: int) -> BlockMeta | None:
        raw = self.db.get(_hkey(K_META, height))
        if not raw:
            return None
        d = msgpack.unpackb(raw, raw=False)
        return BlockMeta(codec.from_dict(d["bid"]), d["size"], d["ntxs"],
                         d["h"])

    def load_block_commit(self, height: int) -> Commit | None:
        """The canonical commit for ``height`` (stored from block h+1's
        LastCommit)."""
        raw = self.db.get(_hkey(K_COMMIT, height))
        return codec.unpack(raw) if raw else None

    def load_seen_commit(self) -> Commit | None:
        raw = self.db.get(K_SEEN_COMMIT)
        return codec.unpack(raw) if raw else None

    def load_block_extended_commit(self, height: int) -> ExtendedCommit | None:
        raw = self.db.get(_hkey(K_EXT_COMMIT, height))
        return codec.unpack(raw) if raw else None

    def prune_blocks(self, retain_height: int) -> int:
        """Remove blocks below retain_height (store/store.go PruneBlocks);
        returns number pruned.  Errors past the store height like the
        reference (cannot prune what was never stored)."""
        if retain_height <= self._base:
            return 0
        if retain_height > self._height + 1:
            raise ValueError(
                f"retain height {retain_height} beyond store height "
                f"{self._height}")
        pruned = 0
        for h in range(self._base, retain_height):
            for prefix in (K_BLOCK, K_META, K_COMMIT, K_EXT_COMMIT):
                self.db.delete(_hkey(prefix, h))
            pruned += 1
        self._base = retain_height
        self._save_bookkeeping()
        return pruned

    def truncate_above(self, height: int) -> int:
        """Remove every block above ``height`` (storage-doctor repair:
        the tip region failed verification, blocksync re-fetches it).
        Missing per-height records are tolerated — a salvaged store may
        have lost exactly the records being truncated.  Returns the
        number of heights removed."""
        if height < 0 or (self._height and height > self._height):
            raise ValueError(
                f"cannot truncate to {height}: store at {self._height}")
        removed = 0
        while self._height > height:
            h = self._height
            for prefix in (K_BLOCK, K_META, K_COMMIT, K_EXT_COMMIT):
                self.db.delete(_hkey(prefix, h))
            self._height = h - 1
            removed += 1
        if self._height == 0:
            self._base = 0
        elif self._base > self._height:
            self._base = self._height
        if removed:
            self._save_bookkeeping()
        return removed

    def is_dirty(self) -> bool:
        """True when the backing store was salvaged after mid-log
        corruption and the doctor's deep verification has not yet passed
        — a dirty store must not serve blocks (salvage can resurrect
        stale records)."""
        fn = getattr(self.db, "is_dirty", None)
        return bool(fn is not None and fn())

    def clear_dirty(self) -> None:
        fn = getattr(self.db, "clear_dirty", None)
        if fn is not None:
            fn()

    def remove_tip(self) -> None:
        """Delete the highest block (rollback --hard support; the
        reference pairs state/rollback.go with store.DeleteLatestBlock)."""
        if self._height == 0:
            raise ValueError("empty block store")
        h = self._height
        for prefix in (K_BLOCK, K_META, K_COMMIT, K_EXT_COMMIT):
            self.db.delete(_hkey(prefix, h))
        self._height = h - 1
        if self._height < self._base:
            self._base = self._height
        self._save_bookkeeping()

    def bootstrap_statesync(self, height: int, seen_commit: Commit) -> None:
        """Install statesync bookkeeping: the store holds no blocks below
        ``height`` but knows the trusted commit for it, so consensus can
        propose at height+1 and blocksync serves nothing older
        (store/store.go SaveSeenCommit + base/height bootstrap used by
        statesync)."""
        if self._height != 0:
            raise ValueError("cannot bootstrap a non-empty block store")
        self._base = height
        self._height = height
        self.db.set_batch({
            K_SEEN_COMMIT: codec.pack(seen_commit),
            K_STATE: msgpack.packb({"base": self._base,
                                    "height": self._height}),
        })
