"""State and StateStore (reference: ``state/state.go``, ``state/store.go``).

``State`` is the deterministic snapshot consensus carries between heights
(validator sets, params, last results); ``StateStore`` persists it plus
per-height validator sets / params and FinalizeBlock responses, with
pruning honoring retain heights (``state/store.go:112-152``, pruner
``state/pruner.go``)."""

from __future__ import annotations

from dataclasses import dataclass, field, replace

import msgpack

from ..types import codec
from ..types.block_id import BlockID
from ..types.genesis import GenesisDoc
from ..types.params import ConsensusParams, default_consensus_params
from ..types.validator_set import ValidatorSet
from .db import KVStore, height_key as _hkey

K_STATE = b"S/state"
K_VALS = b"S/v/"
K_PARAMS = b"S/p/"
K_ABCI = b"S/r/"
K_RETAIN = b"S/retain"
K_PRUNED_TO = b"S/prunedto"
K_OFFLINE_SS = b"S/offliness"


@dataclass
class State:
    chain_id: str
    initial_height: int
    last_block_height: int
    last_block_id: BlockID
    last_block_time_ns: int
    validators: ValidatorSet
    next_validators: ValidatorSet
    last_validators: ValidatorSet | None
    last_height_validators_changed: int
    consensus_params: ConsensusParams
    last_height_params_changed: int
    last_results_hash: bytes
    app_hash: bytes

    @classmethod
    def from_genesis(cls, doc: GenesisDoc) -> "State":
        vals = doc.validator_set()
        return cls(
            chain_id=doc.chain_id,
            initial_height=doc.initial_height,
            last_block_height=0,
            last_block_id=BlockID(),
            last_block_time_ns=doc.genesis_time_ns,
            validators=vals,
            next_validators=vals.copy_increment_proposer_priority(1),
            last_validators=None,
            last_height_validators_changed=doc.initial_height,
            consensus_params=doc.consensus_params,
            last_height_params_changed=doc.initial_height,
            last_results_hash=b"",
            app_hash=doc.app_hash,
        )

    def copy(self) -> "State":
        return replace(
            self,
            validators=self.validators.copy(),
            next_validators=self.next_validators.copy(),
            last_validators=(self.last_validators.copy()
                             if self.last_validators else None),
        )

    def is_empty(self) -> bool:
        return self.last_block_height == 0 and not self.chain_id


class StateStore:
    def __init__(self, db: KVStore):
        self.db = db

    # ----------------------------------------------------------- state

    def save(self, state: State) -> None:
        self.db.set(K_STATE, msgpack.packb({
            "chain": state.chain_id,
            "ih": state.initial_height,
            "h": state.last_block_height,
            "bid": codec.to_dict(state.last_block_id),
            "ts": state.last_block_time_ns,
            "vals": codec.to_dict(state.validators),
            "nvals": codec.to_dict(state.next_validators),
            "lvals": codec.to_dict(state.last_validators),
            "lhvc": state.last_height_validators_changed,
            "params": _params_to_dict(state.consensus_params),
            "lhpc": state.last_height_params_changed,
            "lrh": state.last_results_hash,
            "ah": state.app_hash,
        }, use_bin_type=True))
        # per-height validator sets for light client / evidence lookups
        self.save_validators(state.last_block_height + 1, state.validators)
        self.save_validators(state.last_block_height + 2,
                             state.next_validators)
        self.db.set(_hkey(K_PARAMS, state.last_block_height + 1),
                    msgpack.packb(_params_to_dict(state.consensus_params)))

    def load(self) -> State | None:
        raw = self.db.get(K_STATE)
        if not raw:
            return None
        d = msgpack.unpackb(raw, raw=False)
        return State(
            chain_id=d["chain"], initial_height=d["ih"],
            last_block_height=d["h"],
            last_block_id=codec.from_dict(d["bid"]),
            last_block_time_ns=d["ts"],
            validators=codec.from_dict(d["vals"]),
            next_validators=codec.from_dict(d["nvals"]),
            last_validators=codec.from_dict(d["lvals"]),
            last_height_validators_changed=d["lhvc"],
            consensus_params=_params_from_dict(d["params"]),
            last_height_params_changed=d["lhpc"],
            last_results_hash=d["lrh"], app_hash=d["ah"])

    def bootstrap(self, state: State) -> None:
        """Direct state install (statesync; state/store.go Bootstrap)."""
        self.save(state)
        if state.last_validators is not None:
            self.save_validators(state.last_block_height,
                                 state.last_validators)

    def clear_state(self) -> None:
        """Drop the latest-state snapshot (storage-doctor last resort:
        no verified height remained, so the node restarts from genesis
        and resyncs).  Per-height records are left in place — they are
        overwritten as heights are re-applied."""
        self.db.delete(K_STATE)

    # ----------------------------------------- validators/params by height

    def save_validators(self, height: int, vals: ValidatorSet) -> None:
        self.db.set(_hkey(K_VALS, height), codec.pack(vals))

    def load_validators(self, height: int) -> ValidatorSet | None:
        raw = self.db.get(_hkey(K_VALS, height))
        return codec.unpack(raw) if raw else None

    def load_params(self, height: int) -> ConsensusParams | None:
        raw = self.db.get(_hkey(K_PARAMS, height))
        if not raw:
            return None
        return _params_from_dict(msgpack.unpackb(raw, raw=False))

    # ------------------------------------------------- abci responses

    def save_finalize_block_response(self, height: int, resp_raw: bytes):
        self.db.set(_hkey(K_ABCI, height), resp_raw)

    def load_finalize_block_response(self, height: int) -> bytes | None:
        return self.db.get(_hkey(K_ABCI, height))

    # ------------------------------------------------------- pruning

    def set_retain_heights(self, app: int, companion: int = 0) -> None:
        self.db.set(K_RETAIN, msgpack.packb({"app": app, "dc": companion}))

    def get_retain_height(self) -> int:
        raw = self.db.get(K_RETAIN)
        if not raw:
            return 0
        d = msgpack.unpackb(raw, raw=False)
        vals = [v for v in (d["app"], d["dc"]) if v > 0]
        return min(vals) if vals else 0

    def prune_states(self, retain_height: int) -> int:
        """Delete per-height records below retain_height, resuming from a
        persisted low-water mark (state/store.go PruneStates pattern) so no
        height is ever skipped regardless of how far retain jumps."""
        raw = self.db.get(K_PRUNED_TO)
        start = msgpack.unpackb(raw) if raw else 1
        pruned = 0
        for h in range(start, retain_height):
            for prefix in (K_VALS, K_PARAMS, K_ABCI):
                if self.db.has(_hkey(prefix, h)):
                    self.db.delete(_hkey(prefix, h))
                    pruned += 1
        if retain_height > start:
            self.db.set(K_PRUNED_TO, msgpack.packb(retain_height))
        return pruned

    def set_offline_state_sync_height(self, height: int) -> None:
        self.db.set(K_OFFLINE_SS, msgpack.packb(height))

    def get_offline_state_sync_height(self) -> int:
        raw = self.db.get(K_OFFLINE_SS)
        return msgpack.unpackb(raw) if raw else 0


def _params_to_dict(p: ConsensusParams) -> dict:
    return {
        "block": [p.block.max_bytes, p.block.max_gas],
        "evidence": [p.evidence.max_age_num_blocks,
                     p.evidence.max_age_duration_ns, p.evidence.max_bytes],
        "validator": p.validator.pub_key_types,
        "version": p.version.app,
        "feature": [p.feature.vote_extensions_enable_height,
                    p.feature.pbts_enable_height],
        "synchrony": [p.synchrony.precision_ns,
                      p.synchrony.message_delay_ns],
    }


def _params_from_dict(d: dict) -> ConsensusParams:
    p = default_consensus_params()
    p.block.max_bytes, p.block.max_gas = d["block"]
    (p.evidence.max_age_num_blocks, p.evidence.max_age_duration_ns,
     p.evidence.max_bytes) = d["evidence"]
    p.validator.pub_key_types = list(d["validator"])
    p.version.app = d["version"]
    (p.feature.vote_extensions_enable_height,
     p.feature.pbts_enable_height) = d["feature"]
    p.synchrony.precision_ns, p.synchrony.message_delay_ns = d["synchrony"]
    return p


def rollback_state(state_store: "StateStore", block_store,
                   remove_block: bool = False):
    """Undo the latest state transition (reference: ``state/rollback.go``):
    reconstruct the post-(h-1) state from the stores — the block at h
    carries app_hash/last_results_hash as of h-1, and the per-height
    validator/params records supply the rotated sets — then persist it.
    The ABCI application must be rolled back to the same height separately
    (same caveat as the reference's rollback command)."""
    state = state_store.load()
    if state is None:
        raise ValueError("no state to roll back")
    h = state.last_block_height
    if h <= 0:
        raise ValueError("state is at genesis; nothing to roll back")
    if block_store.height() != h:
        raise ValueError(
            f"block store height {block_store.height()} != state height {h}"
            " (cannot roll back)")

    block = block_store.load_block(h)
    prev_meta = block_store.load_block_meta(h - 1)
    vals_h = state_store.load_validators(h)
    vals_h1 = state_store.load_validators(h + 1)
    vals_prev = state_store.load_validators(h - 1)
    params = state_store.load_params(h)
    if block is None or vals_h is None or vals_h1 is None:
        raise ValueError(f"missing records to roll back height {h}")

    from dataclasses import replace as _replace

    prev_block = block_store.load_block(h - 1)
    rolled = _replace(
        state,
        last_block_height=h - 1,
        last_block_id=prev_meta.block_id if prev_meta is not None
        else type(state.last_block_id)(),
        last_block_time_ns=prev_block.header.time_ns
        if prev_block is not None else state.last_block_time_ns,
        validators=vals_h,
        next_validators=vals_h1,
        last_validators=vals_prev if vals_prev is not None else None,
        # clamp to h+1, not h: the rolled-back state still carries the
        # next_validators that take effect at h+1 (state/rollback.go)
        last_height_validators_changed=min(
            state.last_height_validators_changed, h + 1),
        consensus_params=params if params is not None
        else state.consensus_params,
        last_height_params_changed=min(state.last_height_params_changed,
                                       h + 1),
        app_hash=block.header.app_hash,
        last_results_hash=block.header.last_results_hash,
    )
    state_store.save(rolled)
    if remove_block:
        block_store.remove_tip()
    return rolled
