"""Key-value store abstraction (reference: the cometbft-db interface —
Get/Set/Delete/Iterator/Batch over pluggable backends, ``go.mod:10``).

Backends: ``MemDB`` (tests, light stores) and ``LogDB`` — a crash-safe
append-only record log with an in-memory index and size-triggered
compaction (the pure-host analogue of goleveldb for round 1; the C++
engine replaces it behind this same interface).
"""

from __future__ import annotations

import errno
import functools
import os
import struct
import zlib
from abc import ABC, abstractmethod

from ..libs import failures


@functools.cache
def _salvage_metrics():
    """Mid-log corruption accounting (registered once): every salvage is
    a data-loss event an operator must hear about — the doctor's deep
    verification is what makes the survivor trustworthy."""
    from ..libs import metrics as m

    return (
        m.counter("db_corrupt_records_total",
                  "LogDB record parses that failed mid-log (one per "
                  "quarantined span; torn tails are truncated, not "
                  "counted here)"),
        m.counter("db_salvaged_spans_total",
                  "corrupt LogDB byte spans skipped and quarantined to "
                  "the .quarantine sidecar on open"),
    )


class KVStore(ABC):
    @abstractmethod
    def get(self, key: bytes) -> bytes | None: ...

    @abstractmethod
    def set(self, key: bytes, value: bytes) -> None: ...

    @abstractmethod
    def delete(self, key: bytes) -> None: ...

    @abstractmethod
    def iterate(self, start: bytes = b"", end: bytes | None = None):
        """Yield (key, value) sorted ascending, key in [start, end)."""

    @abstractmethod
    def close(self) -> None: ...

    def set_batch(self, items: dict[bytes, bytes | None]) -> None:
        """Grouped write: None value = delete.  Backends may override to
        make this a single durable append (LogDB: one fsync)."""
        for k, v in items.items():
            if v is None:
                self.delete(k)
            else:
                self.set(k, v)

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None


class DataDirLock:
    """Exclusive advisory lock on a node home's data dir, held for the
    process lifetime (the role of the reference DBs' file locks: offline
    tooling must refuse to touch a live node's stores).  flock releases
    automatically on process death, so a crashed node never wedges its
    home."""

    def __init__(self, data_dir: str):
        import os as _os

        _os.makedirs(data_dir, exist_ok=True)
        self.path = _os.path.join(data_dir, "LOCK")
        self._fd = _os.open(self.path, _os.O_CREAT | _os.O_RDWR, 0o644)
        import fcntl

        try:
            fcntl.flock(self._fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            _os.close(self._fd)
            raise RuntimeError(
                f"data dir {data_dir} is locked by a running node — "
                "stop it before running offline tooling") from None
        _os.write(self._fd, str(_os.getpid()).encode())

    def release(self) -> None:
        import os as _os

        if self._fd is not None:
            import fcntl

            fcntl.flock(self._fd, fcntl.LOCK_UN)
            _os.close(self._fd)
            self._fd = None


def height_key(prefix: bytes, height: int) -> bytes:
    """Height-ordered key layout shared by block/state stores (the layout
    the reference's storage study found keeps pruning cheap)."""
    return prefix + height.to_bytes(8, "big")


class MemDB(KVStore):
    def __init__(self):
        self._data: dict[bytes, bytes] = {}

    def get(self, key):
        return self._data.get(key)

    def set(self, key, value):
        self._data[bytes(key)] = bytes(value)

    def delete(self, key):
        self._data.pop(key, None)

    def iterate(self, start=b"", end=None):
        for k in sorted(self._data):
            if k < start:
                continue
            if end is not None and k >= end:
                break
            yield k, self._data[k]

    def close(self):
        pass


# LogDB record: u32 crc | u32 klen | u32 vlen(or 0xFFFFFFFF tombstone) | k | v
_HDR = struct.Struct("<III")
_TOMBSTONE = 0xFFFFFFFF


class LogDB(KVStore):
    """Append-only log + in-memory index.  Two distinct corruption
    classes are handled on open:

    - **torn tail** (a crash mid-append): no valid record follows the bad
      bytes — truncate to the last good record, exactly the crash-safety
      contract of the reference's WAL-substrate autofile;
    - **mid-log bit-rot**: valid records FOLLOW the bad bytes — replay
      forward-scans to the next valid ``crc|klen|vlen`` boundary,
      quarantines the corrupt span to a ``<path>.quarantine`` sidecar,
      rewrites the log clean, and marks the store **dirty**
      (``<path>.dirty``).  Salvage alone is not trustworthy — a skipped
      span can resurrect a stale value or lose a tombstone — so the
      dirty marker gates serving until the storage doctor's deep
      verification (node/doctor.py) clears it.
    """

    def __init__(self, path: str):
        self.path = path
        self._base = os.path.basename(path)
        self._data: dict[bytes, bytes] = {}
        self._live_bytes = 0
        self._log_bytes = 0
        # salvage report for this open (the doctor reads these)
        self.salvaged = False
        self.salvage_spans: list[tuple[int, int]] = []
        # same fsyncgate discipline as consensus/wal.py: after one
        # write/fsync failure the handle is dead — the in-memory index
        # may already be ahead of what durably landed, and a retried
        # fsync on the same fd proves nothing.  Every further write
        # raises; recovery is a restart replaying the intact log prefix.
        self._io_failed: Exception | None = None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._replay()
        self._f = open(path, "ab")

    # ------------------------------------------------------ replay/salvage

    @staticmethod
    def _parse_at(raw: bytes, off: int):
        """One record at ``off`` -> (key, value|None, end) or None if the
        bytes there do not decode to a CRC-valid record."""
        if off + _HDR.size > len(raw):
            return None
        crc, klen, vlen = _HDR.unpack_from(raw, off)
        vl = 0 if vlen == _TOMBSTONE else vlen
        end = off + _HDR.size + klen + vl
        if end > len(raw):
            return None
        body = raw[off + _HDR.size:end]
        if zlib.crc32(body) != crc:
            return None
        key = body[:klen]
        return key, (None if vlen == _TOMBSTONE else body[klen:]), end

    @classmethod
    def _scan_next_record(cls, raw: bytes, start: int) -> int | None:
        """Forward-scan for the next offset where a CRC-valid record
        parses (a 32-bit CRC over the candidate body makes a false
        boundary astronomically unlikely; implausible lengths reject
        candidates before any CRC is computed)."""
        n = len(raw)
        for off in range(start, n - _HDR.size + 1):
            if cls._parse_at(raw, off) is not None:
                return off
        return None

    def _replay(self):
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            raw = f.read()
        fired = failures.fire("db.replay.corrupt", file=self._base)
        if fired is not None and len(raw) > _HDR.size:
            # seeded bit-flip on open: the chaos analogue of at-rest
            # bit-rot.  frac= pins the flip position (fraction of the
            # file); otherwise the per-site RNG draws it.
            rng = failures.site_rng("db.replay.corrupt")
            frac = fired.get("frac")
            pos = int(float(frac) * (len(raw) - 1)) if frac is not None \
                else rng.randrange(len(raw))
            mut = bytearray(raw)
            mut[pos] ^= 1 << rng.randrange(8)
            raw = bytes(mut)
        off = 0
        good_end = 0
        spans: list[tuple[int, int]] = []
        while off + _HDR.size <= len(raw):
            parsed = self._parse_at(raw, off)
            if parsed is None:
                resume = self._scan_next_record(raw, off + 1)
                if resume is None:
                    break                 # torn tail: truncate below
                spans.append((off, resume))
                off = resume
                continue
            key, value, end = parsed
            if value is None:
                self._data.pop(key, None)
            else:
                self._data[key] = value
            off = good_end = end
        self._live_bytes = sum(len(k) + len(v)
                               for k, v in self._data.items())
        if spans:
            self._salvage(raw, spans)
            return
        if good_end < len(raw):
            with open(self.path, "r+b") as f:
                f.truncate(good_end)
        self._log_bytes = good_end

    def _salvage(self, raw: bytes, spans: list[tuple[int, int]]) -> None:
        """Mid-log corruption found: quarantine every corrupt span to the
        sidecar, rewrite the log from the surviving index, and mark the
        store dirty until deep verification clears it."""
        import msgpack

        with open(self.path + ".quarantine", "ab") as f:
            for lo, hi in spans:
                f.write(msgpack.packb(
                    {"off": lo, "len": hi - lo, "data": raw[lo:hi]},
                    use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        corrupt, salvaged = _salvage_metrics()
        for _ in spans:
            corrupt.inc(file=self._base)
            salvaged.inc(file=self._base)
        self.salvaged = True
        self.salvage_spans = list(spans)
        self.mark_dirty({"spans": [[lo, hi] for lo, hi in spans],
                         "file": self._base})
        # rewrite the log clean so the next open replays without
        # re-salvaging (and the torn tail past the last span is dropped)
        tmp = self.path + ".salvage"
        total = 0
        with open(tmp, "wb") as f:
            for k, v in self._data.items():
                rec = self._record(k, v)
                f.write(rec)
                total += len(rec)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._log_bytes = total

    # ------------------------------------------------------- dirty marker

    def _dirty_path(self) -> str:
        return self.path + ".dirty"

    def mark_dirty(self, info: dict | None = None) -> None:
        """Persist the needs-deep-verification flag (survives restarts: a
        crash between salvage and verification must not lose it)."""
        import json

        with open(self._dirty_path(), "w") as f:
            json.dump(info or {}, f)
            f.flush()
            os.fsync(f.fileno())

    def clear_dirty(self) -> None:
        try:
            os.unlink(self._dirty_path())
        except FileNotFoundError:
            pass

    def is_dirty(self) -> bool:
        return os.path.exists(self._dirty_path())

    def dirty_info(self) -> dict | None:
        import json

        try:
            with open(self._dirty_path()) as f:
                return json.load(f)
        except (OSError, ValueError):  # bftlint: disable=EXC001 -- read-only marker probe; the dirty GATE keys off exists(), this only loses detail
            return None

    @staticmethod
    def _record(key: bytes, value: bytes | None) -> bytes:
        vlen = _TOMBSTONE if value is None else len(value)
        body = key + (value or b"")
        return _HDR.pack(zlib.crc32(body), len(key), vlen) + body

    def _append(self, key: bytes, value: bytes | None):
        self._append_raw(self._record(key, value))

    def _append_raw(self, rec: bytes):
        if self._io_failed is not None:
            raise OSError(
                errno.EIO,
                "LogDB is dead after an earlier IO failure (never retry "
                "on the same fd)") from self._io_failed
        try:
            f = failures.fire("db.append.enospc", file=self._base)
            if f is not None:
                raise OSError(errno.ENOSPC,
                              "chaos: injected ENOSPC on append")
            self._f.write(rec)
            self._f.flush()
            f = failures.fire("db.fsync.eio", file=self._base)
            if f is not None:
                raise OSError(errno.EIO, "chaos: injected fsync EIO")
            os.fsync(self._f.fileno())
        except OSError as e:
            self._io_failed = e
            raise
        self._log_bytes += len(rec)
        if (self._log_bytes > 1 << 20
                and self._log_bytes > 4 * max(self._live_bytes, 1)):
            self._compact()

    def set_batch(self, items):
        """All records in one append + one fsync (block-save hot path)."""
        recs = []
        for k, v in items.items():
            k = bytes(k)
            old = self._data.get(k)
            if v is None:
                if old is None:
                    continue
                del self._data[k]
                self._live_bytes -= len(k) + len(old)
            else:
                v = bytes(v)
                self._data[k] = v
                self._live_bytes += len(k) + len(v) - (
                    len(k) + len(old) if old is not None else 0)
            recs.append(self._record(k, v))
        if recs:
            self._append_raw(b"".join(recs))

    def _compact(self):
        # any IO failure here is fsyncgate-fatal for the handle: an
        # exception between the close and the reopen used to leave later
        # appends dying on a closed-file ValueError instead of the
        # dead-handle OSError discipline — route every failure through
        # _io_failed so the caller sees one consistent contract
        tmp = self.path + ".compact"
        try:
            with open(tmp, "wb") as f:
                total = 0
                for k, v in self._data.items():
                    body = k + v
                    rec = _HDR.pack(zlib.crc32(body), len(k), len(v)) + body
                    f.write(rec)
                    total += len(rec)
                f.flush()
                os.fsync(f.fileno())
            self._f.close()
            fired = failures.fire("db.compact.eio", file=self._base)
            if fired is not None:
                raise OSError(errno.EIO,
                              "chaos: injected EIO mid-compaction")
            os.replace(tmp, self.path)
            self._f = open(self.path, "ab")
        except OSError as e:
            self._io_failed = e
            raise
        self._log_bytes = total

    def get(self, key):
        return self._data.get(key)

    def set(self, key, value):
        key, value = bytes(key), bytes(value)
        old = self._data.get(key)
        self._data[key] = value
        self._live_bytes += len(key) + len(value) - (
            len(key) + len(old) if old is not None else 0)
        self._append(key, value)

    def delete(self, key):
        if key in self._data:
            old = self._data.pop(key)
            self._live_bytes -= len(key) + len(old)
            self._append(key, None)

    def iterate(self, start=b"", end=None):
        for k in sorted(self._data):
            if k < start:
                continue
            if end is not None and k >= end:
                break
            yield k, self._data[k]

    def close(self):
        self._f.close()


def open_db(backend: str, path: str | None = None) -> KVStore:
    """Backend factory — the one dispatch point (config storage.db_backend).

    "native" is the C++ embedded engine (cometbft_tpu/native/kvstore.cpp),
    file-compatible with "logdb"."""
    if backend == "memdb":
        return MemDB()
    if backend == "logdb":
        if not path:
            raise ValueError("logdb requires a path")
        return LogDB(path)
    if backend == "native":
        from .nativedb import NativeDB

        if not path:
            raise ValueError("native requires a path")
        return NativeDB(path)
    raise ValueError(f"unknown db backend {backend!r}")
