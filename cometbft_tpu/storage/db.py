"""Key-value store abstraction (reference: the cometbft-db interface —
Get/Set/Delete/Iterator/Batch over pluggable backends, ``go.mod:10``).

Backends: ``MemDB`` (tests, light stores) and ``LogDB`` — a crash-safe
append-only record log with an in-memory index and size-triggered
compaction (the pure-host analogue of goleveldb for round 1; the C++
engine replaces it behind this same interface).
"""

from __future__ import annotations

import errno
import os
import struct
import zlib
from abc import ABC, abstractmethod

from ..libs import failures


class KVStore(ABC):
    @abstractmethod
    def get(self, key: bytes) -> bytes | None: ...

    @abstractmethod
    def set(self, key: bytes, value: bytes) -> None: ...

    @abstractmethod
    def delete(self, key: bytes) -> None: ...

    @abstractmethod
    def iterate(self, start: bytes = b"", end: bytes | None = None):
        """Yield (key, value) sorted ascending, key in [start, end)."""

    @abstractmethod
    def close(self) -> None: ...

    def set_batch(self, items: dict[bytes, bytes | None]) -> None:
        """Grouped write: None value = delete.  Backends may override to
        make this a single durable append (LogDB: one fsync)."""
        for k, v in items.items():
            if v is None:
                self.delete(k)
            else:
                self.set(k, v)

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None


class DataDirLock:
    """Exclusive advisory lock on a node home's data dir, held for the
    process lifetime (the role of the reference DBs' file locks: offline
    tooling must refuse to touch a live node's stores).  flock releases
    automatically on process death, so a crashed node never wedges its
    home."""

    def __init__(self, data_dir: str):
        import os as _os

        _os.makedirs(data_dir, exist_ok=True)
        self.path = _os.path.join(data_dir, "LOCK")
        self._fd = _os.open(self.path, _os.O_CREAT | _os.O_RDWR, 0o644)
        import fcntl

        try:
            fcntl.flock(self._fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            _os.close(self._fd)
            raise RuntimeError(
                f"data dir {data_dir} is locked by a running node — "
                "stop it before running offline tooling") from None
        _os.write(self._fd, str(_os.getpid()).encode())

    def release(self) -> None:
        import os as _os

        if self._fd is not None:
            import fcntl

            fcntl.flock(self._fd, fcntl.LOCK_UN)
            _os.close(self._fd)
            self._fd = None


def height_key(prefix: bytes, height: int) -> bytes:
    """Height-ordered key layout shared by block/state stores (the layout
    the reference's storage study found keeps pruning cheap)."""
    return prefix + height.to_bytes(8, "big")


class MemDB(KVStore):
    def __init__(self):
        self._data: dict[bytes, bytes] = {}

    def get(self, key):
        return self._data.get(key)

    def set(self, key, value):
        self._data[bytes(key)] = bytes(value)

    def delete(self, key):
        self._data.pop(key, None)

    def iterate(self, start=b"", end=None):
        for k in sorted(self._data):
            if k < start:
                continue
            if end is not None and k >= end:
                break
            yield k, self._data[k]

    def close(self):
        pass


# LogDB record: u32 crc | u32 klen | u32 vlen(or 0xFFFFFFFF tombstone) | k | v
_HDR = struct.Struct("<III")
_TOMBSTONE = 0xFFFFFFFF


class LogDB(KVStore):
    """Append-only log + in-memory index; corrupt/torn tails are truncated
    on open (crash safety like the reference's WAL-substrate autofile)."""

    def __init__(self, path: str):
        self.path = path
        self._data: dict[bytes, bytes] = {}
        self._live_bytes = 0
        self._log_bytes = 0
        # same fsyncgate discipline as consensus/wal.py: after one
        # write/fsync failure the handle is dead — the in-memory index
        # may already be ahead of what durably landed, and a retried
        # fsync on the same fd proves nothing.  Every further write
        # raises; recovery is a restart replaying the intact log prefix.
        self._io_failed: Exception | None = None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._replay()
        self._f = open(path, "ab")

    def _replay(self):
        if not os.path.exists(self.path):
            return
        good_end = 0
        with open(self.path, "rb") as f:
            raw = f.read()
        off = 0
        while off + _HDR.size <= len(raw):
            crc, klen, vlen = _HDR.unpack_from(raw, off)
            vl = 0 if vlen == _TOMBSTONE else vlen
            end = off + _HDR.size + klen + vl
            if end > len(raw):
                break
            body = raw[off + _HDR.size:end]
            if zlib.crc32(body) != crc:
                break
            key = body[:klen]
            if vlen == _TOMBSTONE:
                self._data.pop(key, None)
            else:
                self._data[key] = body[klen:]
            off = good_end = end
        if good_end < len(raw):
            with open(self.path, "r+b") as f:
                f.truncate(good_end)
        self._live_bytes = sum(len(k) + len(v)
                               for k, v in self._data.items())
        self._log_bytes = good_end

    @staticmethod
    def _record(key: bytes, value: bytes | None) -> bytes:
        vlen = _TOMBSTONE if value is None else len(value)
        body = key + (value or b"")
        return _HDR.pack(zlib.crc32(body), len(key), vlen) + body

    def _append(self, key: bytes, value: bytes | None):
        self._append_raw(self._record(key, value))

    def _append_raw(self, rec: bytes):
        if self._io_failed is not None:
            raise OSError(
                errno.EIO,
                "LogDB is dead after an earlier IO failure (never retry "
                "on the same fd)") from self._io_failed
        try:
            f = failures.fire("db.append.enospc")
            if f is not None:
                raise OSError(errno.ENOSPC,
                              "chaos: injected ENOSPC on append")
            self._f.write(rec)
            self._f.flush()
            f = failures.fire("db.fsync.eio")
            if f is not None:
                raise OSError(errno.EIO, "chaos: injected fsync EIO")
            os.fsync(self._f.fileno())
        except OSError as e:
            self._io_failed = e
            raise
        self._log_bytes += len(rec)
        if (self._log_bytes > 1 << 20
                and self._log_bytes > 4 * max(self._live_bytes, 1)):
            self._compact()

    def set_batch(self, items):
        """All records in one append + one fsync (block-save hot path)."""
        recs = []
        for k, v in items.items():
            k = bytes(k)
            old = self._data.get(k)
            if v is None:
                if old is None:
                    continue
                del self._data[k]
                self._live_bytes -= len(k) + len(old)
            else:
                v = bytes(v)
                self._data[k] = v
                self._live_bytes += len(k) + len(v) - (
                    len(k) + len(old) if old is not None else 0)
            recs.append(self._record(k, v))
        if recs:
            self._append_raw(b"".join(recs))

    def _compact(self):
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            total = 0
            for k, v in self._data.items():
                body = k + v
                rec = _HDR.pack(zlib.crc32(body), len(k), len(v)) + body
                f.write(rec)
                total += len(rec)
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")
        self._log_bytes = total

    def get(self, key):
        return self._data.get(key)

    def set(self, key, value):
        key, value = bytes(key), bytes(value)
        old = self._data.get(key)
        self._data[key] = value
        self._live_bytes += len(key) + len(value) - (
            len(key) + len(old) if old is not None else 0)
        self._append(key, value)

    def delete(self, key):
        if key in self._data:
            old = self._data.pop(key)
            self._live_bytes -= len(key) + len(old)
            self._append(key, None)

    def iterate(self, start=b"", end=None):
        for k in sorted(self._data):
            if k < start:
                continue
            if end is not None and k >= end:
                break
            yield k, self._data[k]

    def close(self):
        self._f.close()


def open_db(backend: str, path: str | None = None) -> KVStore:
    """Backend factory — the one dispatch point (config storage.db_backend).

    "native" is the C++ embedded engine (cometbft_tpu/native/kvstore.cpp),
    file-compatible with "logdb"."""
    if backend == "memdb":
        return MemDB()
    if backend == "logdb":
        if not path:
            raise ValueError("logdb requires a path")
        return LogDB(path)
    if backend == "native":
        from .nativedb import NativeDB

        if not path:
            raise ValueError("native requires a path")
        return NativeDB(path)
    raise ValueError(f"unknown db backend {backend!r}")
