"""Tendermint consensus state machine (reference: ``internal/consensus/``):
round state, height vote sets, timeout ticker, WAL, the single-writer
receive loop, and crash-recovery replay/handshake."""

from .round_state import (STEP_COMMIT, STEP_NEW_HEIGHT, STEP_NEW_ROUND,
                          STEP_PRECOMMIT, STEP_PRECOMMIT_WAIT, STEP_PREVOTE,
                          STEP_PREVOTE_WAIT, STEP_PROPOSE, RoundState)
from .height_vote_set import HeightVoteSet
from .state import ConsensusState
from .ticker import TimeoutInfo, TimeoutTicker

__all__ = ["ConsensusState", "RoundState", "HeightVoteSet", "TimeoutTicker",
           "TimeoutInfo", "STEP_NEW_HEIGHT", "STEP_NEW_ROUND", "STEP_PROPOSE",
           "STEP_PREVOTE", "STEP_PREVOTE_WAIT", "STEP_PRECOMMIT",
           "STEP_PRECOMMIT_WAIT", "STEP_COMMIT"]
