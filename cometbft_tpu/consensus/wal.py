"""Consensus write-ahead log (reference: ``internal/consensus/wal.go`` on
top of ``internal/autofile/group.go`` rotating file groups).

Every message (peer msg, own msg, timeout) is logged *before* processing;
own votes/proposals are fsync'd before they can be sent (the double-sign
safety argument, ``internal/consensus/state.go:843``).  Records are
``crc32(body) | len | body`` with msgpack bodies; a height sentinel
(``EndHeightMessage``, wal.go:43) marks each committed height so replay
starts after the last one.

Like the reference's autofile group, the log rotates into fixed-size
segments (``<path>``, ``<path>.001``, ``<path>.002`` ...) so one
long-running validator never grows a single unbounded file, and segments
wholly behind the latest EndHeight sentinel are pruned (group head
checkpointing).  Torn tails are truncated on open."""

from __future__ import annotations

import errno
import functools
import os
import struct
import time
import zlib

import msgpack

from ..libs import failures, tracing

_HDR = struct.Struct("<II")
MAX_BODY = 1 << 20            # 1 MB cap, like the reference's maxMsgSizeBytes
DEFAULT_SEGMENT_BYTES = 4 << 20


class WALError(Exception):
    pass


@functools.cache
def _wal_metrics():
    """WAL latency series (registered once): fsync stalls on a loaded
    disk are a classic hidden consensus-latency source — every own vote
    is fsync'd before it may be broadcast."""
    from ..libs import metrics as m

    return (
        m.histogram("consensus_wal_write_seconds",
                    "WAL record append latency (buffered write)",
                    buckets=(0.00005, 0.0001, 0.00025, 0.0005, 0.001,
                             0.0025, 0.005, 0.01, 0.05, 0.1)),
        m.histogram("consensus_wal_fsync_seconds",
                    "WAL flush+fsync latency",
                    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                             0.005, 0.01, 0.025, 0.05, 0.1, 0.5, 1)),
    )


def wal_segments(path: str) -> list[str]:
    """Existing segment paths in write order (directory scan: pruning may
    leave index gaps)."""
    d = os.path.dirname(path) or "."
    base = os.path.basename(path)
    found = []          # (index, path); the bare path is index 0
    try:
        names = os.listdir(d)
    except OSError:  # bftlint: disable=EXC001 -- read-only discovery scan; an unreadable dir reads as no segments and the boot doctor cross-checks WAL lineage
        names = []
    for name in names:
        if name == base:
            found.append((0, path))
        elif name.startswith(base + "."):
            suffix = name[len(base) + 1:]
            if suffix.isdigit():
                found.append((int(suffix), os.path.join(d, name)))
    return [p for _, p in sorted(found)]


def _iter_segment_file(path: str):
    """Yields records; final item is the sentinel True when the whole
    segment decoded cleanly, False when it ended in corruption."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:  # bftlint: disable=EXC001 -- the False sentinel IS the routing: callers treat an unreadable segment exactly like a corrupt one
        yield False
        return
    off = 0
    while off + _HDR.size <= len(raw):
        crc, ln = _HDR.unpack_from(raw, off)
        end = off + _HDR.size + ln
        if ln > MAX_BODY or end > len(raw) or \
                zlib.crc32(raw[off + _HDR.size:end]) != crc:
            yield off == len(raw)
            return
        yield msgpack.unpackb(raw[off + _HDR.size:end], raw=False)
        off = end
    yield True


def iter_wal_records_readonly(path: str):
    """Strictly read-only record stream across segments for tooling
    (scripts/wal2json): no truncation, no append handle, no fsync, no
    directory creation.  Raises WALError if the WAL does not exist;
    raises WALError at a corrupt record (after yielding everything intact
    before it) so callers can report instead of silently stopping."""
    segs = wal_segments(path)
    if not segs:
        raise WALError(f"no WAL at {path}")
    for seg in segs:
        clean = False
        for item in _iter_segment_file(seg):
            if isinstance(item, bool):
                clean = item
                break
            yield item
        if not clean:
            raise WALError(f"corrupt record in {seg}; later segments "
                           f"not decoded")


def last_end_height(path: str) -> int | None:
    """Read-only: the last EndHeight sentinel across all segments (the
    storage doctor's WAL-lineage anchor).  Stops at the first corruption
    like replay does — records past a corrupt span are unreachable by
    any replay, so their sentinels must not anchor anything."""
    last = None
    for seg in wal_segments(path):
        clean = False
        for item in _iter_segment_file(seg):
            if isinstance(item, bool):
                clean = item
                break
            if item.get("#") == "endheight":
                last = item["h"]
        if not clean:
            break
    return last


def quarantine_wal(path: str) -> list[str]:
    """Move every WAL segment aside (``<seg>.quarantine``), returning
    the new paths.  Used by the storage doctor when the WAL's lineage
    runs AHEAD of the (repaired) stores: replaying records for heights
    the stores no longer hold would feed consensus a stream from a
    discarded timeline.  Double-sign safety does not depend on the WAL —
    the privval last-sign-state survives untouched."""
    moved = []
    for seg in wal_segments(path):
        dst = seg + ".quarantine"
        i = 0
        while os.path.exists(dst):
            i += 1
            dst = f"{seg}.quarantine.{i}"
        os.replace(seg, dst)
        moved.append(dst)
    return moved


class WAL:
    def __init__(self, path: str,
                 max_segment_bytes: int = DEFAULT_SEGMENT_BYTES):
        self.path = path
        self.max_segment_bytes = max_segment_bytes
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        segs = self._segments()
        if not segs:
            segs = [path]
        self._truncate_torn_tail(segs[-1])
        self._cur_path = segs[-1]
        self._f = open(self._cur_path, "ab")
        # segment holding the PREVIOUS EndHeight sentinel: the safe prune
        # boundary (see prune note below).  Unknown after reopen -> prune
        # nothing until two sentinels have been written in this process.
        self._prev_sentinel_seg: str | None = None
        # fsyncgate: once ANY write/fsync on this handle failed, the
        # kernel may have dropped the dirty pages — a later fsync that
        # "succeeds" on the same fd proves nothing.  The WAL goes dead
        # (every further write/sync raises); recovery is a process
        # restart reopening the file, which truncates the torn tail.
        self._io_failed: Exception | None = None
        # height attribution for fsync tracing events: EndHeight(h)
        # stamps h on its own fsync, then advances the hint — every
        # later fsync (own votes, timeouts) belongs to height h+1
        self._height_hint = 0

    # ------------------------------------------------------------ segments

    def _segments(self) -> list[str]:
        return wal_segments(self.path)

    def _next_segment_path(self) -> str:
        segs = self._segments()
        if not segs or segs[-1] == self.path:
            return f"{self.path}.001"
        idx = int(segs[-1].rsplit(".", 1)[1])
        return f"{self.path}.{idx + 1:03d}"

    def _maybe_rotate(self) -> None:
        if self._f.tell() < self.max_segment_bytes:
            return
        self.flush_and_sync()
        self._f.close()
        self._cur_path = self._next_segment_path()
        self._f = open(self._cur_path, "ab")

    def _truncate_torn_tail(self, path: str) -> None:
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            raw = f.read()
        off = 0
        good = 0
        while off + _HDR.size <= len(raw):
            crc, ln = _HDR.unpack_from(raw, off)
            if ln > MAX_BODY:
                break
            end = off + _HDR.size + ln
            if end > len(raw) or zlib.crc32(raw[off + _HDR.size:end]) != crc:
                break
            off = good = end
        if good < len(raw):
            with open(path, "r+b") as f:
                f.truncate(good)

    def prune_completed_segments(self) -> int:
        """Drop whole segments strictly older than the segment holding the
        PREVIOUS EndHeight sentinel (autofile group head checkpointing).

        The previous sentinel — not the latest — is the safe boundary:
        the latest EndHeight(h) is written BEFORE the state for h is
        persisted (state.go:1899 ordering), so a crash right after it
        still replays from EndHeight(h-1).  Everything strictly before
        EndHeight(h-1)'s segment is unreachable by any replay.  Tracked
        in memory at sentinel-write time, so pruning never re-reads the
        log (no file scans on the commit path); after a reopen the
        boundary is unknown and nothing is pruned until two sentinels
        have been written.  Returns segments removed."""
        boundary = self._prev_sentinel_seg
        if boundary is None:
            return 0
        segs = self._segments()
        if boundary not in segs:
            return 0
        removed = 0
        for path in segs:
            if path == boundary or path == self._cur_path:
                break
            os.unlink(path)
            removed += 1
        return removed

    # -------------------------------------------------------------- write

    def _check_alive(self) -> None:
        if self._io_failed is not None:
            raise WALError(
                "WAL is dead after an earlier IO failure (fsyncgate: "
                "never retry on the same fd)") from self._io_failed

    def write(self, record: dict) -> None:
        t0 = time.perf_counter()
        self._check_alive()
        body = msgpack.packb(record, use_bin_type=True)
        if len(body) > MAX_BODY:
            raise WALError(f"record too big: {len(body)}")
        rec = _HDR.pack(zlib.crc32(body), len(body)) + body
        f = failures.fire("wal.write.torn")
        if f is not None:
            # a torn write IS a crash from the record's point of view:
            # persist a seeded prefix (mid-header or mid-body per the
            # rule's cut= param), then fail the handle like the outage
            # that tore it
            self._io_failed = self._torn_write(rec, f)
            raise WALError("chaos: torn WAL write") from self._io_failed
        try:
            self._f.write(rec)
        except OSError as e:
            self._io_failed = e
            raise
        self._maybe_rotate()
        _wal_metrics()[0].observe(time.perf_counter() - t0)

    def _torn_write(self, rec: bytes, rule: dict) -> Exception:
        """Persist a strict prefix of ``rec`` (the chaos analogue of
        power loss mid-append).  ``cut=header`` tears inside the 8-byte
        crc|len header, ``cut=body`` after a whole header; default draws
        anywhere in the record."""
        rng = failures.site_rng("wal.write.torn")
        cut = rule.get("cut")
        if cut == "header":
            keep = rng.randrange(1, _HDR.size)
        elif cut == "body":
            keep = _HDR.size + rng.randrange(0, max(len(rec) - _HDR.size, 1))
        else:
            keep = rng.randrange(1, len(rec))
        self._f.write(rec[:keep])
        self._f.flush()
        return OSError(errno.EIO, "chaos: write torn mid-record")

    def write_sync(self, record: dict) -> None:
        self.write(record)
        self.flush_and_sync()

    def write_end_height(self, height: int) -> None:
        """fsync'd height sentinel (wal.go:202 EndHeightMessage)."""
        sentinel_seg = self._cur_path
        self._height_hint = height
        self.write_sync({"#": "endheight", "h": height})
        self._height_hint = height + 1
        try:
            self.prune_completed_segments()
        except OSError:  # bftlint: disable=EXC001 -- prune is best-effort cleanup AFTER the fsync'd sentinel; failure leaves extra segments, never loses records
            pass
        self._prev_sentinel_seg = sentinel_seg

    def flush_and_sync(self) -> None:
        t0 = time.perf_counter()
        self._check_alive()
        try:
            self._f.flush()
            f = failures.fire("wal.fsync.eio")
            if f is not None:
                raise OSError(errno.EIO, "chaos: injected fsync EIO")
            os.fsync(self._f.fileno())
        except OSError as e:
            # fsyncgate semantics: an fsync failure is FATAL for this
            # handle.  Linux drops the dirty pages after reporting the
            # error, so retrying fsync on the same fd can "succeed"
            # while the data never hit the platter — mark the WAL dead
            # and let the caller halt consensus.
            self._io_failed = e
            raise
        dt = time.perf_counter() - t0
        _wal_metrics()[1].observe(dt)
        tracing.event("wal", "fsync", path=self._cur_path,
                      height=self._height_hint, dur_us=int(dt * 1e6))

    # --------------------------------------------------------------- read

    def _iter_segment(self, path: str):
        return _iter_segment_file(path)

    def iter_records(self):
        """All intact records across segments, oldest first.  Stops at the
        first corruption: continuing into later segments would hand replay
        a record stream with a silent hole (the single-file WAL's
        truncate-at-corruption semantics, generalized)."""
        self.flush_and_sync()
        for path in self._segments():
            clean = False
            for item in self._iter_segment(path):
                if isinstance(item, bool):
                    clean = item
                    break
                yield item
            if not clean:
                return

    def _segment_first_endheight(self, path: str):
        """First EndHeight sentinel value in a segment, or None (no
        sentinel / unreadable).  Decodes only up to the first sentinel —
        the binary-search probe cost."""
        for item in self._iter_segment(path):
            if isinstance(item, bool):
                return None
            if item.get("#") == "endheight":
                return item["h"]
        return None

    def _search_start_segment(self, segs: list[str], height: int) -> int:
        """Binary search for the last segment that can contain the
        EndHeight(height) sentinel (reference: autofile group binary
        search, ``internal/autofile/group.go:34-54`` via
        ``internal/consensus/wal.go:232`` SearchForEndHeight): sentinel
        heights increase monotonically across segments, so the segment
        whose FIRST sentinel is <= height is a safe scan start — a
        restarting validator reads O(log n) segment heads plus the tail
        instead of every record of every segment.  Segments without any
        sentinel probe their nearest keyed predecessor."""
        if height == 0 or len(segs) <= 1:
            return 0
        probed: dict = {}            # memo: a keyless segment decodes once

        def first_eh(i):
            if i not in probed:
                probed[i] = self._segment_first_endheight(segs[i])
            return probed[i]

        best = 0
        lo, hi = 0, len(segs) - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            j, key = mid, None
            while j >= lo:           # nearest keyed segment at/below mid
                key = first_eh(j)
                if key is not None:
                    break
                j -= 1
            if key is None:          # no sentinel anywhere in [lo, mid]
                lo = mid + 1
                continue
            if key <= height:
                best = j
                lo = mid + 1
            else:
                hi = j - 1
        return best

    def records_after_height(self, height: int) -> list[dict]:
        """Records following the EndHeight(h) sentinel for h == height
        (replay input: catchupReplay, replay.go:95).  If the sentinel is
        missing, returns records from the start (fresh WAL).  Scans only
        from the binary-searched start segment — corruption in the
        unreachable earlier segments is not re-verified (their records
        cannot be replay input)."""
        self.flush_and_sync()
        segs = self._segments()
        out: list[dict] = []
        found = height == 0
        for path in segs[self._search_start_segment(segs, height):]:
            clean = False
            for item in self._iter_segment(path):
                if isinstance(item, bool):
                    clean = item
                    break
                rec = item
                if rec.get("#") == "endheight":
                    if rec["h"] == height:
                        found = True
                        out = []
                    elif rec["h"] > height and not found:
                        raise WALError(
                            f"WAL jumped past height {height} "
                            f"(saw {rec['h']})")
                    continue
                if found or height == 0:
                    out.append(rec)
            if not clean:
                break                 # same stop-at-corruption semantics
        return out

    def close(self) -> None:
        self._f.close()
