"""Consensus write-ahead log (reference: ``internal/consensus/wal.go``).

Every message (peer msg, own msg, timeout) is logged *before* processing;
own votes/proposals are fsync'd before they can be sent (the double-sign
safety argument, ``internal/consensus/state.go:843``).  Records are
``crc32(body) | len | body`` with msgpack bodies; a height sentinel
(``EndHeightMessage``, wal.go:43) marks each committed height so replay
starts after the last one.  Torn tails are truncated on open."""

from __future__ import annotations

import os
import struct
import zlib

import msgpack

_HDR = struct.Struct("<II")
MAX_BODY = 1 << 20          # 1 MB cap, like the reference's maxMsgSizeBytes


class WALError(Exception):
    pass


class WAL:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._truncate_torn_tail()
        self._f = open(path, "ab")

    def _truncate_torn_tail(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            raw = f.read()
        off = 0
        good = 0
        while off + _HDR.size <= len(raw):
            crc, ln = _HDR.unpack_from(raw, off)
            if ln > MAX_BODY:
                break
            end = off + _HDR.size + ln
            if end > len(raw) or zlib.crc32(raw[off + _HDR.size:end]) != crc:
                break
            off = good = end
        if good < len(raw):
            with open(self.path, "r+b") as f:
                f.truncate(good)

    def write(self, record: dict) -> None:
        body = msgpack.packb(record, use_bin_type=True)
        if len(body) > MAX_BODY:
            raise WALError(f"record too big: {len(body)}")
        self._f.write(_HDR.pack(zlib.crc32(body), len(body)) + body)

    def write_sync(self, record: dict) -> None:
        self.write(record)
        self.flush_and_sync()

    def write_end_height(self, height: int) -> None:
        """fsync'd height sentinel (wal.go:202 EndHeightMessage)."""
        self.write_sync({"#": "endheight", "h": height})

    def flush_and_sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def iter_records(self):
        """All intact records from the start (corruption already truncated)."""
        self.flush_and_sync()
        with open(self.path, "rb") as f:
            raw = f.read()
        off = 0
        while off + _HDR.size <= len(raw):
            crc, ln = _HDR.unpack_from(raw, off)
            end = off + _HDR.size + ln
            if end > len(raw) or zlib.crc32(raw[off + _HDR.size:end]) != crc:
                return
            yield msgpack.unpackb(raw[off + _HDR.size:end], raw=False)
            off = end

    def records_after_height(self, height: int) -> list[dict]:
        """Records following the EndHeight(h) sentinel for h == height
        (replay input: catchupReplay, replay.go:95).  If the sentinel is
        missing, returns records from the start (fresh WAL)."""
        out: list[dict] = []
        found = height == 0
        for rec in self.iter_records():
            if rec.get("#") == "endheight":
                if rec["h"] == height:
                    found = True
                    out = []
                elif rec["h"] > height and not found:
                    raise WALError(
                        f"WAL jumped past height {height} (saw {rec['h']})")
                continue
            if found or height == 0:
                out.append(rec)
        return out

    def close(self) -> None:
        self._f.close()
