"""Timeout ticker (reference: ``internal/consensus/ticker.go``): one pending
timeout at a time; scheduling overrides the previous.  Mockable for
deterministic tests (tests drive ``fire`` directly).

Implementation note: a ``loop.call_later`` handle, not a task —
consensus re-schedules on every step transition, and at scenario-lab
scale (hundreds of nodes) the old task-per-schedule pattern was one of
the two dominant allocators in the whole run (a Task + CancelledError
per step vs a heap entry).  ``call_later`` rides ``loop.time()``, so
the virtual clock drives it like any other timer."""

from __future__ import annotations

import asyncio
from dataclasses import dataclass


@dataclass(frozen=True)
class TimeoutInfo:
    duration_ns: int
    height: int
    round: int
    step: int


class TimeoutTicker:
    def __init__(self, deliver):
        """``deliver(TimeoutInfo)`` is called on the event loop when a
        timeout fires (posts into the consensus queue)."""
        self._deliver = deliver
        self._handle: asyncio.TimerHandle | None = None

    def schedule(self, ti: TimeoutInfo) -> None:
        if self._handle is not None:
            self._handle.cancel()
        self._handle = asyncio.get_running_loop().call_later(
            ti.duration_ns / 1e9, self._fire, ti)

    def _fire(self, ti: TimeoutInfo) -> None:
        self._handle = None
        self._deliver(ti)

    def stop(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
