"""Timeout ticker (reference: ``internal/consensus/ticker.go``): one pending
timeout at a time; scheduling overrides the previous.  Mockable for
deterministic tests (tests drive ``fire`` directly)."""

from __future__ import annotations

import asyncio
from dataclasses import dataclass


@dataclass(frozen=True)
class TimeoutInfo:
    duration_ns: int
    height: int
    round: int
    step: int


class TimeoutTicker:
    def __init__(self, deliver):
        """``deliver(TimeoutInfo)`` is called on the event loop when a
        timeout fires (posts into the consensus queue)."""
        self._deliver = deliver
        self._task: asyncio.Task | None = None

    def schedule(self, ti: TimeoutInfo) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
        self._task = asyncio.get_running_loop().create_task(self._run(ti))

    async def _run(self, ti: TimeoutInfo) -> None:
        try:
            await asyncio.sleep(ti.duration_ns / 1e9)
            self._deliver(ti)
        except asyncio.CancelledError:
            pass

    def stop(self) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
        self._task = None
