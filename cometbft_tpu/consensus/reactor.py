"""Consensus reactor: gossips proposals, block parts and votes over the
p2p switch (reference: ``internal/consensus/reactor.go:41,590,646,708`` and
``PeerState`` at ``:1079``).

Four channels, same ids as the reference (``reactor.go:27-30``):
STATE (0x20) round-step/has-vote/maj23 announcements, DATA (0x21)
proposals + block parts, VOTE (0x22) votes, VOTE_SET_BITS (0x23) vote-set
bit-array replies.  Per-peer gossip tasks mirror gossipDataRoutine /
gossipVotesRoutine / queryMaj23Routine; all state access happens on the one
event loop, so PeerState needs no locks (single-writer discipline).
"""

from __future__ import annotations

import asyncio
import functools
import random

import msgpack

from ..libs import clock
from ..libs.bits import BitArray
from ..types import codec
from ..types.block_id import BlockID
from ..types.commit import Commit
from ..types.part_set import Part
from ..types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE, Vote
from ..p2p.reactor import ChannelDescriptor, Reactor
from .round_state import (STEP_COMMIT, STEP_NEW_HEIGHT, STEP_PRECOMMIT,
                          STEP_PREVOTE)
from .state import ConsensusState

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23

GOSSIP_SLEEP = 0.01                 # config PeerGossipSleepDuration analog
QUERY_MAJ23_SLEEP = 2.0


@functools.cache
def _dup_votes_metric():
    from ..libs import metrics as _m
    from ..p2p.metrics import PEER_LABEL_BUDGET

    # per-peer children (Counter.bind at add_peer); the cardinality
    # guard caps them at the peer-label budget under churn
    return _m.counter(
        "consensus_gossip_duplicate_votes_total",
        "re-gossiped votes dropped at the reactor (already in a vote "
        "set), by sending peer",
        max_label_sets=PEER_LABEL_BUDGET)


@functools.cache
def _useful_votes_metric():
    from ..libs import metrics as _m
    from ..p2p.metrics import PEER_LABEL_BUDGET

    return _m.counter(
        "consensus_gossip_useful_votes_total",
        "gossiped votes accepted into processing (not already held), by "
        "sending peer — useful/(useful+duplicate) is that peer's gossip "
        "efficiency",
        max_label_sets=PEER_LABEL_BUDGET)


@functools.cache
def _msg_type_metric():
    from ..libs import metrics as _m

    return _m.counter(
        "consensus_reactor_msgs_total",
        "consensus reactor messages received, by wire tag (nrs, hv, nvb, "
        "maj23, prop, pol, part, vote, vsb)")


# ------------------------------------------------------------- wire helpers

def _ba_to_wire(ba: BitArray | None):
    if ba is None:
        return None
    return {"n": ba.size, "b": ba._bits.to_bytes((ba.size + 7) // 8 or 1,
                                                 "little")}


def _ba_from_wire(d) -> BitArray | None:
    if d is None:
        return None
    return BitArray(d["n"], int.from_bytes(d["b"], "little"))


def _pack(tag: str, **fields) -> bytes:
    fields["@"] = tag
    return msgpack.packb(fields, use_bin_type=True)


def _unpack(raw: bytes) -> dict:
    return msgpack.unpackb(raw, raw=False)


def votes_from_commit(commit: Commit) -> list[Vote]:
    """Reconstruct precommit Votes from a stored commit so lagging peers
    can be caught up vote-by-vote (reactor.go:646 gossip for earlier
    heights; Commit.ToVoteSet types/block.go:1134).

    AGGREGATE lanes are skipped: their individual signatures were folded
    into ``commit.agg_signature`` and no longer exist — a reconstructed
    empty-signature vote would only earn the sender a misbehavior report
    at the receiver.  Aggregated commits catch peers up whole
    (:meth:`ConsensusReactor._send_catchup_commit`)."""
    out = []
    for i, cs in enumerate(commit.signatures):
        if cs.is_absent() or cs.is_aggregate():
            continue
        out.append(Vote(
            type=PRECOMMIT_TYPE, height=commit.height, round=commit.round,
            block_id=commit.block_id if cs.is_commit() else BlockID(),
            timestamp_ns=cs.timestamp_ns, validator_address=cs.validator_address,
            validator_index=i, signature=cs.signature))
    return out


# ----------------------------------------------------------------- PeerState

class PeerState:
    """What we know about one peer's consensus view (reactor.go:1079)."""

    def __init__(self, rng: random.Random | None = None):
        # per-peer seeded RNG for gossip picks/jitter (DET001): drawing
        # from the GLOBAL rng makes the sequence a function of coroutine
        # interleaving across every peer and node in the process, which
        # breaks the scenario lab's replay-identity contract.  Keyed per
        # (node, peer) the sequence is a pure function of identity —
        # decorrelated between peers, byte-stable across replays.
        self.rng = rng if rng is not None else random.Random()
        self.height = 0
        self.round = -1
        self.step = 0
        self.proposal = False
        self.proposal_block_parts_header = None
        self.proposal_block_parts: BitArray | None = None
        self.proposal_pol_round = -1
        self.proposal_pol: BitArray | None = None
        self.prevotes: dict[int, BitArray] = {}
        self.precommits: dict[int, BitArray] = {}
        self.last_commit_round = -1
        self.last_commit: BitArray | None = None
        # height of the last whole catch-up commit shipped to this peer
        # (aggregate catch-up; see _send_catchup_commit)
        self.commit_sent_height = 0

    def apply_new_round_step(self, h: int, r: int, step: int,
                             last_commit_round: int) -> None:
        prev_h, prev_r = self.height, self.round
        self.height, self.round, self.step = h, r, step
        if prev_h != h or prev_r != r:
            self.proposal = False
            self.proposal_block_parts_header = None
            self.proposal_block_parts = None
            self.proposal_pol_round = -1
            self.proposal_pol = None
        if prev_h != h:
            if prev_h + 1 == h and prev_r != -1:
                # peer's round precommits became its last commit
                self.last_commit = self.precommits.get(prev_r)
                self.last_commit_round = prev_r
            else:
                self.last_commit = None
                self.last_commit_round = last_commit_round
            self.prevotes.clear()
            self.precommits.clear()

    def vote_bits(self, height: int, round_: int, typ: int,
                  n_validators: int) -> BitArray | None:
        if height == self.height:
            table = self.prevotes if typ == PREVOTE_TYPE else self.precommits
            if round_ not in table:
                table[round_] = BitArray(n_validators)
            return table[round_]
        if height == self.height - 1 and typ == PRECOMMIT_TYPE and \
                round_ == self.last_commit_round:
            if self.last_commit is None:
                self.last_commit = BitArray(n_validators)
            return self.last_commit
        return None

    def set_has_vote(self, height: int, round_: int, typ: int, index: int,
                     n_validators: int) -> None:
        ba = self.vote_bits(height, round_, typ, n_validators)
        if ba is not None:
            ba.set_index(index, True)

    def apply_vote_set_bits(self, height: int, round_: int, typ: int,
                            bits: BitArray) -> None:
        ours = self.vote_bits(height, round_, typ, bits.size)
        if ours is not None:
            merged = ours.or_(bits)
            if typ == PREVOTE_TYPE and height == self.height:
                self.prevotes[round_] = merged
            elif typ == PRECOMMIT_TYPE and height == self.height:
                self.precommits[round_] = merged
            else:
                self.last_commit = merged


# ------------------------------------------------------------------ reactor

_KNOWN_TAGS = ("nrs", "hv", "nvb", "maj23", "prop", "pol", "part",
               "vote", "vsb", "commit")


class ConsensusReactor(Reactor):
    def __init__(self, cs: ConsensusState,
                 gossip_sleep: float = GOSSIP_SLEEP):
        super().__init__()
        self.cs = cs
        self.gossip_sleep = gossip_sleep
        self.wait_sync = False      # True while blocksync owns the chain
        self._peer_tasks: dict[str, list[asyncio.Task]] = {}
        self._last_nrs = None
        # per-tag message counters, pre-bound (the tag comes off the
        # wire, so only the closed protocol set gets a label — anything
        # else lands in "other" rather than minting attacker-chosen
        # label values)
        mt = _msg_type_metric()
        self._m_msgs = {tag: mt.bind(type=tag, node=cs.name)
                        for tag in _KNOWN_TAGS}
        self._m_msgs_other = mt.bind(type="other", node=cs.name)
        cs.broadcast_proposal = self._broadcast_proposal
        cs.broadcast_block_part = self._broadcast_block_part
        cs.broadcast_vote = self._broadcast_vote
        cs.on_round_step = self._broadcast_new_round_step
        cs.on_vote_added = self._broadcast_has_vote
        cs.on_valid_block = self._broadcast_new_valid_block
        cs.on_peer_misbehavior = self._on_peer_misbehavior

    def get_channels(self):
        return [
            ChannelDescriptor(STATE_CHANNEL, priority=6,
                              send_queue_capacity=100, name="state"),
            ChannelDescriptor(DATA_CHANNEL, priority=10,
                              send_queue_capacity=100, name="data"),
            ChannelDescriptor(VOTE_CHANNEL, priority=7,
                              send_queue_capacity=200, name="vote"),
            ChannelDescriptor(VOTE_SET_BITS_CHANNEL, priority=1,
                              send_queue_capacity=20, name="votesetbits"),
        ]

    # ------------------------------------------------------ peer lifecycle

    def add_peer(self, peer) -> None:
        peer.set("cons_peer_state", PeerState(
            rng=random.Random(f"gossip:{self.cs.name}:{peer.id}")))
        # gossip-efficiency children, pre-bound per peer (the label is
        # the same 12-char prefix the p2p telemetry uses)
        from ..p2p.metrics import peer_label

        pl = peer_label(peer.id)
        peer.set("m_dup_votes",
                 _dup_votes_metric().bind(peer=pl, node=self.cs.name))
        peer.set("m_useful_votes",
                 _useful_votes_metric().bind(peer=pl, node=self.cs.name))
        if not self.wait_sync:
            peer.send(STATE_CHANNEL, self._nrs_msg())
            nvb = self._nvb_msg()
            if nvb is not None:
                peer.send(STATE_CHANNEL, nvb)
        self._peer_tasks[peer.id] = [
            asyncio.create_task(self._gossip_data_routine(peer)),
            asyncio.create_task(self._gossip_votes_routine(peer)),
            asyncio.create_task(self._query_maj23_routine(peer)),
        ]

    def remove_peer(self, peer, reason=None) -> None:
        for task in self._peer_tasks.pop(peer.id, []):
            task.cancel()

    _MISBEHAVIOR_EVENTS = {"vote": "invalid_vote", "part": "invalid_part",
                           "proposal": "invalid_proposal"}

    def _on_peer_misbehavior(self, peer_id: str, kind: str,
                             exc: Exception) -> None:
        """A peer-fed consensus message made its handler raise.  Only
        VALIDATION failures (bad vote/proposal signature, part with a
        bad merkle proof) are the sender's fault — a quorum-completing
        vote runs commit + ABCI inline, and a flapping app's
        ConnectionResetError must not blame whichever honest peer's
        vote happened to land last."""
        sw = self.switch
        if sw is None or not hasattr(sw, "report_peer"):
            return
        from ..types.part_set import PartSetError
        from ..types.vote_set import VoteSetError

        if not isinstance(exc, (VoteSetError, PartSetError)):
            return
        event = self._MISBEHAVIOR_EVENTS.get(kind, "protocol_error")
        sw.report_peer(peer_id, event, detail=f"{kind}: {exc!r}"[:160])

    async def stop(self) -> None:
        for tasks in self._peer_tasks.values():
            for t in tasks:
                t.cancel()
        self._peer_tasks.clear()

    # -------------------------------------------------- outbound broadcasts

    def _nrs_msg(self) -> bytes:
        rs = self.cs.rs
        lcr = rs.last_commit.round if rs.last_commit is not None else -1
        return _pack("nrs", h=rs.height, r=rs.round, s=rs.step, lcr=lcr)

    def switch_to_consensus(self) -> None:
        """Blocksync handed the chain over: resume gossip and announce our
        (freshly synced) round state (reference SwitchToConsensus)."""
        self.wait_sync = False
        self._last_nrs = None
        self._broadcast_new_round_step()

    def _nvb_msg(self) -> bytes | None:
        """NewValidBlockMessage analogue (reactor.go
        broadcastNewValidBlockMessage): advertise which parts of the
        to-be-committed block we actually hold, so peers whose bookkeeping
        drifted (parts sent before we had the part-set header were dropped)
        re-send the gap.  Without this a catch-up node that enters COMMIT
        after the parts went by deadlocks waiting for a block nobody will
        re-send."""
        rs = self.cs.rs
        if rs.proposal_block_parts is None:
            return None
        return _pack(
            "nvb", h=rs.height, r=rs.round,
            psh=codec.to_dict(rs.proposal_block_parts.header()),
            bits=_ba_to_wire(rs.proposal_block_parts.bit_array()))

    def _broadcast_new_round_step(self) -> None:
        if self.switch is None or self.wait_sync:
            return
        nrs = self._nrs_msg()
        if nrs == self._last_nrs:
            return
        self._last_nrs = nrs
        self.switch.broadcast(STATE_CHANNEL, nrs)

    def _broadcast_new_valid_block(self) -> None:
        if self.switch is None or self.wait_sync:
            return
        nvb = self._nvb_msg()
        if nvb is not None:
            # peers track us against our announced round state: make sure
            # it precedes the nvb even if the step transition was deduped
            self.switch.broadcast(STATE_CHANNEL, self._nrs_msg())
            self.switch.broadcast(STATE_CHANNEL, nvb)

    def _broadcast_has_vote(self, vote: Vote) -> None:
        if self.switch is None:
            return
        self.switch.broadcast(STATE_CHANNEL, _pack(
            "hv", h=vote.height, r=vote.round, t=vote.type,
            i=vote.validator_index))

    def _broadcast_proposal(self, proposal) -> None:
        if self.switch is None:
            return
        self.switch.broadcast(DATA_CHANNEL,
                              _pack("prop", p=codec.to_dict(proposal)))

    def _broadcast_block_part(self, height: int, round_: int,
                              part: Part) -> None:
        if self.switch is None:
            return
        self.switch.broadcast(DATA_CHANNEL, _pack(
            "part", h=height, r=round_, p=_part_to_wire(part)))

    def _broadcast_vote(self, vote: Vote) -> None:
        if self.switch is None:
            return
        self.switch.broadcast(VOTE_CHANNEL,
                              _pack("vote", v=codec.to_dict(vote)))

    # -------------------------------------------------------------- receive

    def receive(self, channel_id: int, peer, msg: bytes) -> None:
        ps: PeerState = peer.get("cons_peer_state")
        if ps is None:
            return
        if self.wait_sync:
            # blocksync owns the chain: consensus traffic would pile up in
            # the unstarted state machine's queue (reference Reactor.Receive
            # drops messages while WaitSync)
            return
        d = _unpack(msg)
        tag = d.get("@")
        # wire-supplied tag may be any msgpack value: an unhashable one
        # must count as "other", not raise out of receive() and tear
        # down the connection
        ((self._m_msgs.get(tag) if isinstance(tag, str) else None)
         or self._m_msgs_other).inc()
        n_vals = self.cs.state.validators.size() \
            if self.cs.state is not None else 0
        if channel_id == STATE_CHANNEL:
            if tag == "nrs":
                ps.apply_new_round_step(d["h"], d["r"], d["s"], d["lcr"])
            elif tag == "hv":
                ps.set_has_vote(d["h"], d["r"], d["t"], d["i"], n_vals)
            elif tag == "nvb":
                if d["h"] == ps.height and d["r"] == ps.round:
                    ps.proposal_block_parts_header = codec.from_dict(d["psh"])
                    ps.proposal_block_parts = _ba_from_wire(d["bits"])
            elif tag == "maj23":
                self._on_vote_set_maj23(peer, d)
        elif channel_id == DATA_CHANNEL:
            if tag == "prop":
                proposal = codec.from_dict(d["p"])
                ps.proposal = True
                if ps.proposal_block_parts is None:
                    ps.proposal_block_parts_header = \
                        proposal.block_id.part_set_header
                    ps.proposal_block_parts = BitArray(
                        proposal.block_id.part_set_header.total)
                ps.proposal_pol_round = proposal.pol_round
                self.cs.feed_proposal(proposal, peer.id)
            elif tag == "pol":
                if d["h"] == ps.height:
                    ps.proposal_pol_round = d["polr"]
                    ps.proposal_pol = _ba_from_wire(d["pol"])
            elif tag == "part":
                part = _part_from_wire(d["p"])
                if ps.proposal_block_parts is not None:
                    ps.proposal_block_parts.set_index(part.index, True)
                self.cs.feed_block_part(d["h"], d["r"], part, peer.id)
        elif channel_id == VOTE_CHANNEL:
            if tag == "vote":
                vote = codec.from_dict(d["v"])
                ps.set_has_vote(vote.height, vote.round, vote.type,
                                vote.validator_index, n_vals)
                if self.cs.has_exact_vote(vote):
                    # re-gossip of a vote we already hold: the peer
                    # bookkeeping above is all it was worth — don't buy
                    # a WAL write, a queue slot and a dup-check pass
                    peer.gossip.duplicate += 1
                    m = peer.get("m_dup_votes")
                    if m is not None:
                        m.inc()
                    else:
                        _dup_votes_metric().inc()
                    return
                peer.gossip.useful += 1
                m = peer.get("m_useful_votes")
                if m is not None:
                    m.inc()
                self.cs.feed_vote(vote, peer.id)
            elif tag == "commit":
                # whole-commit aggregate catch-up: verification happens
                # in the state machine (feed_commit -> VerifyCommitLight)
                self.cs.feed_commit(codec.from_dict(d["c"]), peer.id)
        elif channel_id == VOTE_SET_BITS_CHANNEL:
            if tag == "vsb":
                bits = _ba_from_wire(d["bits"])
                if bits is not None:
                    ps.apply_vote_set_bits(d["h"], d["r"], d["t"], bits)

    def _on_vote_set_maj23(self, peer, d: dict) -> None:
        """Record the claimed majority and reply with our bits for that
        BlockID (reactor.go Receive StateChannel VoteSetMaj23Message)."""
        cs = self.cs
        h, r, typ = d["h"], d["r"], d["t"]
        bid = codec.from_dict(d["bid"])
        if cs.rs.height != h or cs.rs.votes is None:
            return
        try:
            cs.rs.votes.set_peer_maj23(r, typ, peer.id, bid)
        except Exception:
            return
        vs = (cs.rs.votes.prevotes(r) if typ == PREVOTE_TYPE
              else cs.rs.votes.precommits(r))
        bits = vs.bit_array_by_block_id(bid) if vs is not None else None
        peer.send(VOTE_SET_BITS_CHANNEL, _pack(
            "vsb", h=h, r=r, t=typ, bits=_ba_to_wire(
                bits or BitArray(cs.state.validators.size()))))

    # ------------------------------------------------------- gossip: data

    async def _gossip_data_routine(self, peer) -> None:
        ps: PeerState = peer.get("cons_peer_state")
        try:
            while True:
                rs = self.cs.rs
                sent = False
                if ps.height and ps.height < rs.height:
                    sent = self._send_catchup_part(peer, ps)
                elif ps.height == rs.height:
                    sent = self._send_current_data(peer, ps)
                if not sent:
                    await clock.sleep(self.gossip_sleep)
                else:
                    await clock.sleep(0)
        except asyncio.CancelledError:
            raise
        except Exception:
            pass        # peer is being torn down

    def _send_catchup_part(self, peer, ps: PeerState) -> bool:
        """Feed a lagging peer parts of its next block from our store
        (gossipDataForCatchup, reactor.go:590)."""
        if ps.proposal_block_parts is None:
            # announce the stored block's part-set header so the peer's
            # state mirrors a proposal for its height
            parts = self.cs.block_store.load_block_parts(ps.height)
            if parts is None:
                return False
            ps.proposal_block_parts_header = parts.header()
            ps.proposal_block_parts = BitArray(parts.total)
        parts = self.cs.block_store.load_block_parts(ps.height)
        if parts is None or \
                parts.header() != ps.proposal_block_parts_header:
            return False
        want = parts.bit_array().sub(ps.proposal_block_parts)
        idx, ok = want.pick_random(ps.rng)
        if not ok:
            return False
        part = parts.get_part(idx)
        ps.proposal_block_parts.set_index(idx, True)
        return peer.send(DATA_CHANNEL, _pack(
            "part", h=ps.height, r=ps.round, p=_part_to_wire(part)))

    def _send_current_data(self, peer, ps: PeerState) -> bool:
        rs = self.cs.rs
        if rs.proposal is not None and not ps.proposal:
            ps.proposal = True
            # SetHasProposal (peer_state.go): knowing the part-set header
            # unlocks part gossip to this peer on the NEXT iteration.
            # Without this init, parts only flow once the peer's relay of
            # the proposal loops back to us — a full extra round-trip per
            # hop that starves prevotes of the block at net scale (found
            # by the scenario lab: at 25+ nodes most of the net entered
            # prevote with the proposal but zero parts, nil-prevoting
            # round after round).
            if ps.proposal_block_parts is None:
                ps.proposal_block_parts_header = \
                    rs.proposal.block_id.part_set_header
                ps.proposal_block_parts = BitArray(
                    rs.proposal.block_id.part_set_header.total)
            sent = peer.send(DATA_CHANNEL, _pack(
                "prop", p=codec.to_dict(rs.proposal)))
            if 0 <= rs.proposal.pol_round:
                pol = rs.votes.prevotes(rs.proposal.pol_round)
                if pol is not None:
                    peer.send(DATA_CHANNEL, _pack(
                        "pol", h=rs.height, polr=rs.proposal.pol_round,
                        pol=_ba_to_wire(pol.bit_array())))
            return sent
        if rs.proposal_block_parts is not None and \
                ps.proposal_block_parts is not None and \
                ps.proposal_block_parts_header == \
                rs.proposal_block_parts.header():
            want = rs.proposal_block_parts.bit_array().sub(
                ps.proposal_block_parts)
            idx, ok = want.pick_random(ps.rng)
            if ok:
                part = rs.proposal_block_parts.get_part(idx)
                ps.proposal_block_parts.set_index(idx, True)
                return peer.send(DATA_CHANNEL, _pack(
                    "part", h=rs.height, r=rs.round,
                    p=_part_to_wire(part)))
        return False

    # ------------------------------------------------------ gossip: votes

    async def _gossip_votes_routine(self, peer) -> None:
        ps: PeerState = peer.get("cons_peer_state")
        try:
            while True:
                if not self._send_vote_to_peer(peer, ps):
                    await clock.sleep(self.gossip_sleep)
                else:
                    await clock.sleep(0)
        except asyncio.CancelledError:
            raise
        except Exception:
            pass

    def _send_vote_to_peer(self, peer, ps: PeerState) -> bool:
        """gossipVotesRoutine body (reactor.go:646)."""
        cs = self.cs
        rs = cs.rs
        if ps.height == 0:
            return False
        if ps.height == rs.height:
            # same height: last-commit for NewHeight peers, then POL
            # prevotes, round prevotes, round precommits
            if ps.step == STEP_NEW_HEIGHT and rs.last_commit is not None:
                if self._pick_send_vote(peer, ps, rs.last_commit):
                    return True
            if ps.step <= STEP_PREVOTE and ps.round != -1 and \
                    ps.round <= rs.round:
                if 0 <= ps.proposal_pol_round:
                    pol = rs.votes.prevotes(ps.proposal_pol_round)
                    if pol is not None and \
                            self._pick_send_vote(peer, ps, pol):
                        return True
                pv = rs.votes.prevotes(ps.round)
                if pv is not None and self._pick_send_vote(peer, ps, pv):
                    return True
            if ps.step <= STEP_PRECOMMIT and ps.round != -1 and \
                    ps.round <= rs.round:
                pc = rs.votes.precommits(ps.round)
                if pc is not None and self._pick_send_vote(peer, ps, pc):
                    return True
            if 0 <= ps.proposal_pol_round:
                pol = rs.votes.prevotes(ps.proposal_pol_round)
                if pol is not None and self._pick_send_vote(peer, ps, pol):
                    return True
            return False
        if ps.height + 1 == rs.height and rs.last_commit is not None:
            # peer is one height behind: our last commit has its precommits
            return self._pick_send_vote(peer, ps, rs.last_commit)
        if ps.height < rs.height:
            # catchup: stored commit for the peer's height
            commit = cs.block_store.load_block_commit(ps.height)
            if commit is None:
                seen = cs.block_store.load_seen_commit()
                if seen is not None and seen.height == ps.height:
                    commit = seen
            if commit is None:
                return False
            return self._pick_send_commit_vote(peer, ps, commit)
        return False

    def _pick_send_vote(self, peer, ps: PeerState, vote_set) -> bool:
        """Send one vote the peer lacks (PeerState.PickSendVote)."""
        ours = vote_set.bit_array()
        theirs = ps.vote_bits(vote_set.height, vote_set.round,
                              vote_set.type, ours.size)
        if theirs is None:
            return False
        idx, ok = ours.sub(theirs).pick_random(ps.rng)
        if not ok:
            return False
        vote = vote_set.get_by_index(idx)
        if vote is None:
            return False
        theirs.set_index(idx, True)
        return peer.send(VOTE_CHANNEL, _pack("vote", v=codec.to_dict(vote)))

    def _send_catchup_commit(self, peer, ps: PeerState,
                             commit: Commit) -> bool:
        """Ship a whole aggregated stored commit to a lagging peer: the
        folded lanes cannot be replayed vote-by-vote (their individual
        signatures no longer exist), so the peer verifies the commit as
        one unit instead.  Sent once per height, re-offered at a low
        rng-gated rate so one dropped message cannot strand the peer."""
        if ps.commit_sent_height == commit.height and \
                ps.rng.random() >= 0.02:
            return False
        if peer.send(VOTE_CHANNEL,
                     _pack("commit", c=codec.to_dict(commit))):
            ps.commit_sent_height = commit.height
            return True
        return False

    def _pick_send_commit_vote(self, peer, ps: PeerState,
                               commit: Commit) -> bool:
        if commit.has_aggregate() and \
                self._send_catchup_commit(peer, ps, commit):
            return True
        votes = votes_from_commit(commit)
        present = BitArray.from_indices(
            len(commit.signatures), [v.validator_index for v in votes])
        theirs = ps.vote_bits(commit.height, commit.round, PRECOMMIT_TYPE,
                              len(commit.signatures))
        if theirs is None:
            # peer's round state may not cover this commit round: track ad hoc
            ps.last_commit_round = commit.round
            ps.last_commit = theirs = BitArray(len(commit.signatures))
        idx, ok = present.sub(theirs).pick_random(ps.rng)
        if not ok:
            return False
        vote = next(v for v in votes if v.validator_index == idx)
        theirs.set_index(idx, True)
        return peer.send(VOTE_CHANNEL, _pack("vote", v=codec.to_dict(vote)))

    # ------------------------------------------------------- query maj23

    async def _query_maj23_routine(self, peer) -> None:
        ps: PeerState = peer.get("cons_peer_state")
        try:
            while True:
                await clock.sleep(QUERY_MAJ23_SLEEP
                                    * (0.8 + 0.4 * ps.rng.random()))
                rs = self.cs.rs
                if rs.votes is None or ps.height != rs.height:
                    continue
                for typ, vs in ((PREVOTE_TYPE, rs.votes.prevotes(rs.round)),
                                (PRECOMMIT_TYPE,
                                 rs.votes.precommits(rs.round))):
                    if vs is None:
                        continue
                    maj, has = vs.two_thirds_majority()
                    if has and maj is not None:
                        peer.send(STATE_CHANNEL, _pack(
                            "maj23", h=rs.height, r=rs.round, t=typ,
                            bid=codec.to_dict(maj)))
        except asyncio.CancelledError:
            raise
        except Exception:
            pass


# ---------------------------------------------------------- part wire codec

def _part_to_wire(part: Part) -> dict:
    return {"i": part.index, "b": part.bytes_,
            "pt": part.proof.total, "pi": part.proof.index,
            "pl": part.proof.leaf_hash, "pa": list(part.proof.aunts)}


def _part_from_wire(d: dict) -> Part:
    from ..crypto.merkle import Proof

    return Part(d["i"], d["b"],
                Proof(d["pt"], d["pi"], d["pl"], tuple(d["pa"])))
